"""Node — the root runtime object every host embeds.

Parity: ref:core/src/lib.rs:82-250 `Node::new(data_dir, env)` builds
config manager, libraries, job system, thumbnailer, event bus,
notifications, optional image-labeler and P2P, then performs an
ordered start (lib.rs:163-177: locations → libraries.init → jobs →
p2p) and exposes `shutdown` (lib.rs:240-250). The API layer mounts on
top of this object (api::mount, ref:core/src/api/mod.rs:124).
"""

from __future__ import annotations

import os
import uuid
from typing import Any

from ..jobs.manager import JobManager
from ..object.media.thumbnail.actor import Thumbnailer
from ..parallel import autotune as _autotune
from ..object.orphan_remover import OrphanRemoverActor
from ..tasks.system import TaskSystem
from ..telemetry.events import LoopLagMonitor
from ..utils.events import EventBus
from ..utils.tracing import init_logger, install_loop_excepthook
from .actors import Actors
from .config import BackendFeature, ConfigManager, NodeConfig
from .library import Libraries, Library
from .notifications import Notifications


class Node:
    """Owns every long-lived service; one per process (ref:lib.rs:60-80)."""

    def __init__(
        self,
        data_dir: str | os.PathLike,
        *,
        use_device: bool = True,
        with_logger: bool = False,
        with_labeler: bool = True,
    ):
        self.data_dir = os.fspath(data_dir)
        os.makedirs(self.data_dir, exist_ok=True)
        if with_logger:
            init_logger(self.data_dir)
        if use_device:
            from ..ops import configure_compilation_cache

            configure_compilation_cache()

        self.config = ConfigManager(self.data_dir)
        self.event_bus = EventBus()
        self.notifications = Notifications(self.event_bus)
        self.task_system = TaskSystem()
        self.jobs = JobManager(self.task_system)
        self.libraries = Libraries(self.data_dir, node=self)
        self.actors = Actors()
        from ..location.manager import LocationManager

        self.location_manager = LocationManager(self)
        self.thumbnailer = Thumbnailer(
            os.path.join(self.data_dir, "thumbnails"),
            event_bus=self.event_bus,
            use_device=use_device,
        )
        self.use_device = use_device
        # ref:lib.rs:142 ImageLabeler::new [feature ai] — on by default,
        # disable with with_labeler=False (the reference's feature gate)
        self.image_labeler: Any = None
        if with_labeler:
            from ..models.labeler_actor import ImageLabeler

            self.image_labeler = ImageLabeler(
                os.path.join(self.data_dir, "image_labeler"),
                use_device=use_device,
            )
            # version string tracks the provisioned artifact, mirroring
            # the reference's image_labeler_version (node/config.rs) —
            # "none" means no weights yet, labeling is off
            artifact = self.image_labeler.resolve_artifact()
            version = f"{artifact[0]}:{os.path.basename(artifact[1])}" if artifact else "none"
            if self.config.config.image_labeler_version != version:
                self.config.update(image_labeler_version=version)
        self.p2p: Any = None  # P2PManager, attached by start() when enabled
        self.http: Any = None  # ApiServer handle from start_api()
        # the serve layer (admission gate + read-path caches): absent
        # entirely under SD_SERVE_GATE=0, and every consumer treats a
        # missing runtime as "take the ungated pre-serve path"
        from ..serve import ServeRuntime, enabled as _serve_enabled

        self.serve: Any = ServeRuntime() if _serve_enabled() else None
        from ..api.namespaces import mount

        self.router = mount()  # ref:lib.rs Node::new returns (node, router)
        self.loop_monitor = LoopLagMonitor()
        # persistent telemetry history: sampled allowlisted series into
        # an append-only segment store under the data dir — constructed
        # unconditionally so offline readers (sdx slo, bench_compare)
        # can open the same directory; sampling only starts with the
        # node and only when SD_HISTORY != 0
        from ..telemetry.history import HistoryWriter, history_dir

        self.history = HistoryWriter(history_dir(self.data_dir))
        # the process-wide closed-loop autotuner: started with the node
        # so pipeline policies adapt while jobs run (SD_AUTOTUNE=0 keeps
        # every policy at the static defaults and starts nothing)
        self.autotuner = _autotune.CONTROLLER
        # the process-wide continuous host profiler (telemetry/sampler):
        # refcounted like the autotuner so two in-process nodes share
        # one sampling thread; SD_PROFILE=0 starts nothing (true no-op)
        from ..telemetry import sampler as _sampler

        self.profiler = _sampler.SAMPLER
        self._profiler_started = False
        # the multi-process execution plane (parallel/procpool.py):
        # spawn-started with the node, refcounted like the sampler so
        # two in-process nodes share one worker set. SD_PROCS=0 (the
        # default) starts nothing — the golden single-process path.
        from ..parallel import procpool as _procpool

        self.procpool = _procpool.POOL
        self._procpool_started = False
        # the process-wide resource-growth sampler (telemetry/resources):
        # refcounted like the profiler; SD_RESOURCES=0 starts nothing
        # (true no-op). Inventory providers that need node state
        # (journal/oplog rows, serve caches, history bytes) register at
        # start and unregister at shutdown.
        from ..telemetry import resources as _resources

        self.resources = _resources.SAMPLER
        self._resources_started = False
        self._started = False

    # --- identity ------------------------------------------------------

    @property
    def id(self) -> uuid.UUID:
        return self.config.config.id

    @property
    def identity(self):
        return self.config.config.identity

    def is_feature_enabled(self, feature: BackendFeature) -> bool:
        return feature in self.config.config.features

    def toggle_feature(self, feature: BackendFeature, enabled: bool) -> None:
        """ref:core/src/api/mod.rs:66-81 `toggleFeatureFlag`."""
        feats = self.config.config.features
        if enabled and feature not in feats:
            feats.append(feature)
        if not enabled and feature in feats:
            feats.remove(feature)
        self.config.save()

    # --- lifecycle -----------------------------------------------------

    async def start(self) -> None:
        """Ordered start (ref:lib.rs:163-177; ordering is
        deadlock-sensitive in the reference: locations actor first, then
        libraries init — which cold-resumes jobs — then p2p listeners)."""
        if self._started:
            return
        self._started = True
        # observability: orphaned-task crashes reach the log + error
        # ring, and the loop-lag sampler feeds the flight recorder
        import asyncio

        install_loop_excepthook(asyncio.get_running_loop())
        self.loop_monitor.start()
        self.history.start()
        self.autotuner.start()
        # host profiling: tag THIS thread as the event-loop thread so
        # samples classify as loop vs feeder vs worker, then take a
        # refcounted hold on the process sampler
        self.profiler.register_loop_thread()
        self._profiler_started = self.profiler.start()
        # worker processes up before any job runs, so the first shard's
        # pool batches never pay spawn latency inside a measured pass
        self._procpool_started = self.procpool.start()
        # resource growth surfaces: node-state inventories registered
        # before the sampler's hold so the first tick reads them all
        from ..telemetry import resources as _resources

        for name, fn in _resources.node_providers(self).items():
            self.resources.register_provider(name, fn)
        self._resources_started = self.resources.start()
        # bind the thumbnailer to THIS loop up front: enqueues arrive
        # from worker threads (non-indexed walker) and can only wake the
        # actor thread-safely once it knows its owning loop
        self.thumbnailer._ensure_started()
        for lib in self.libraries.load_all():
            await self._init_library(lib)
        if self.config.config.p2p.enabled:
            from ..p2p.manager import P2PManager

            self.p2p = P2PManager(self)
            await self.p2p.start()

    async def _init_library(self, lib: Library) -> None:
        """Per-library wiring done at load (ref:library/manager/mod.rs:387-535):
        orphan-remover actor started, ingest actor wired when a sync
        transport attaches (p2p/cloud), then cold job resume."""
        lib.node = self
        lib.orphan_remover = OrphanRemoverActor(lib.db)
        lib.orphan_remover.start()
        self.location_manager.ignore_paths.add(self.thumbnailer.data_dir)
        if self.image_labeler is not None:
            self.image_labeler.register_library(lib)
        for loc in lib.db.find("location"):
            await self.location_manager.add(lib, loc)
        await self.jobs.cold_resume(lib)

    async def create_library(self, name: str, description: str = "") -> Library:
        lib = self.libraries.create(
            name,
            description,
            node_pub_id=self.id.bytes,
            node_name=self.config.config.name,
        )
        await self._init_library(lib)
        if self.p2p is not None:
            self.p2p.register_library(lib)
        return lib

    async def enable_cloud_sync(self, lib: Library, api_origin: str | None = None):
        """Start the cloud sender/receiver/ingester trio for a library
        (ref:core/src/cloud/sync/mod.rs:14 declare_actors; the origin
        persists in node preferences like the reference's sd-cloud-api
        env)."""
        from ..cloud.api import CloudClient
        from ..cloud.sync import CloudSync

        prev_origin = self.config.config.preferences.get("cloud_api_origin")
        if api_origin is not None and api_origin != prev_origin:
            self.config.config.preferences["cloud_api_origin"] = api_origin
            self.config.save()
        origin = self.config.config.preferences.get("cloud_api_origin")
        if not origin:
            raise ValueError("no cloud api origin configured")
        existing = getattr(lib, "cloud_sync", None)
        if existing is not None:
            if existing.client.origin == origin.rstrip("/"):
                return existing
            # origin changed: move sync to the new relay
            await existing.shutdown()
            await existing.client.close()
            lib.cloud_sync = None
        client = CloudClient(origin)
        cloud = CloudSync(lib, client)
        try:
            await cloud.start()
        except BaseException:
            await cloud.shutdown()
            await client.close()
            raise
        lib.cloud_sync = cloud
        if BackendFeature.CLOUD_SYNC not in self.config.config.features:
            self.toggle_feature(BackendFeature.CLOUD_SYNC, True)
        return cloud

    async def close_library(self, lib_id: uuid.UUID) -> None:
        """Tear down one loaded library: stop its actors, persist and stop
        its jobs, close the DB, drop it from the registry (the per-library
        half of shutdown(); used by delete/restore)."""
        from ..jobs.manager import shutdown_jobs

        lib = self.libraries.get(lib_id)
        if lib is None:
            return
        cloud = getattr(lib, "cloud_sync", None)
        if cloud is not None:
            await cloud.shutdown()
            await cloud.client.close()
        await shutdown_jobs(self.jobs, lib)
        remover = getattr(lib, "orphan_remover", None)
        if remover is not None:
            await remover.stop()
        ingest = getattr(lib, "ingest", None)
        if ingest is not None:
            await ingest.stop()
        lib.close()
        self.libraries.libraries.pop(lib_id, None)

    async def start_api(self, host: str = "127.0.0.1", port: int = 0) -> int:
        """Serve /rspc + custom-URI over HTTP (ref:apps/server/src/main.rs)."""
        from ..api.server import ApiServer

        self.http = ApiServer(self, self.router)
        return await self.http.start(host, port)

    async def shutdown(self) -> None:
        """ref:lib.rs:240-250: stop jobs (persisting state), thumbnailer
        (persisting queues), actors, p2p, then close libraries."""
        from ..jobs.manager import shutdown_jobs

        if self.http is not None:
            await self.http.shutdown()
            self.http = None

        for lib in list(self.libraries.libraries.values()):
            await shutdown_jobs(self.jobs, lib)
            remover = getattr(lib, "orphan_remover", None)
            if remover is not None:
                await remover.stop()
            cloud = getattr(lib, "cloud_sync", None)
            if cloud is not None:
                await cloud.shutdown()
                await cloud.client.close()
        await self.loop_monitor.stop()
        await self.history.stop()
        await self.autotuner.stop()
        if self._profiler_started:
            self.profiler.stop()
            self._profiler_started = False
        if self._procpool_started:
            self.procpool.stop()
            self._procpool_started = False
        if self._resources_started:
            self.resources.stop()
            self._resources_started = False
        if not self.resources.running():
            # last hold released (or sampling disabled): drop the
            # node-state closures so a dead node can't be read. While a
            # sibling in-process node still holds the sampler, its own
            # registrations (last-wins) stay live instead.
            from ..telemetry import resources as _resources

            for name in _resources.node_providers(self):
                self.resources.unregister_provider(name)
        await self.thumbnailer.shutdown()
        if self.image_labeler is not None:
            await self.image_labeler.shutdown()
        await self.location_manager.shutdown()
        await self.actors.shutdown()
        if self.p2p is not None:
            await self.p2p.shutdown()
        await self.task_system.shutdown()
        for lib in list(self.libraries.libraries.values()):
            lib.close()
        self._started = False
