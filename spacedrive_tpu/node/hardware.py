"""Hardware probing — device model + accelerator inventory.

Parity: ref:core/src/node/hardware.rs — `HardwareModel` detection fed
into node metadata/peer listings — extended with the accelerator
inventory a TPU-native node advertises (device kind, count, memory)
and `crates/fda`'s disk-access check (macOS Full Disk Access prompt,
ref:crates/fda/src/lib.rs:31-40; on non-macOS the check degrades to a
plain read-permission probe).
"""

from __future__ import annotations

import functools
import os
import platform
from typing import Any


@functools.cache
def hardware_model() -> str:
    """Coarse device model string (ref:hardware.rs `HardwareModel`)."""
    system = platform.system()
    if system == "Darwin":
        try:
            import subprocess

            out = subprocess.run(
                ["sysctl", "-n", "hw.model"], capture_output=True, text=True,
                timeout=5,
            )
            return out.stdout.strip() or "Mac"
        except Exception:
            return "Mac"
    if system == "Linux":
        for probe in (
            "/sys/devices/virtual/dmi/id/product_name",
            "/proc/device-tree/model",
        ):
            try:
                with open(probe) as f:
                    name = f.read().strip("\x00\n ")
                if name:
                    return name
            except OSError:
                continue
        return "Linux PC"
    return platform.machine() or "Unknown"


def accelerators() -> list[dict[str, Any]]:
    """The node's JAX-visible accelerator inventory (TPU-native
    extension — advertised in nodeState/peer metadata)."""
    try:
        import jax

        return [
            {
                "id": d.id,
                "kind": d.device_kind,
                "platform": d.platform,
                "process_index": d.process_index,
            }
            for d in jax.devices()
        ]
    except Exception:
        return []


def has_full_disk_access(probe_path: str | None = None) -> bool:
    """ref:crates/fda/src/lib.rs:31-40 — the reference reads a
    TCC-protected dir on macOS to detect Full Disk Access; elsewhere a
    plain readability probe of the requested path stands in."""
    if platform.system() == "Darwin":
        probe = probe_path or os.path.expanduser(
            "~/Library/Application Support/com.apple.TCC"
        )
    else:
        probe = probe_path or os.path.expanduser("~")
    try:
        os.listdir(probe)
        return True
    except PermissionError:
        return False
    except OSError:
        return True  # missing dir ≠ missing permission
