"""Per-library KV-flattened preferences.

Parity: ref:core/src/preferences/{mod.rs,kv.rs} — `LibraryPreferences`
is a nested JSON document flattened into dotted-key rows of the
`preference` table (`PreferenceKVs::from_model`, kv.rs), so partial
updates touch only the affected keys; `read` re-nests the rows into the
document (mod.rs:16-55). Values are stored msgpack-encoded like the
reference's rmpv.
"""

from __future__ import annotations

from typing import Any

import msgpack

from ..db.database import LibraryDb


def _flatten(doc: dict[str, Any], prefix: str = "") -> dict[str, Any]:
    out: dict[str, Any] = {}
    for k, v in doc.items():
        key = f"{prefix}.{k}" if prefix else k
        if isinstance(v, dict) and v and all(isinstance(x, str) for x in v):
            out.update(_flatten(v, key))
        else:
            out[key] = v
    return out


def _nest(flat: dict[str, Any]) -> dict[str, Any]:
    doc: dict[str, Any] = {}
    for key, value in flat.items():
        parts = key.split(".")
        cur = doc
        for p in parts[:-1]:
            cur = cur.setdefault(p, {})
        cur[parts[-1]] = value
    return doc


def write_preferences(db: LibraryDb, doc: dict[str, Any]) -> int:
    """Flatten `doc` and upsert each dotted key (ref:kv.rs `write`)."""
    flat = _flatten(doc)
    for key, value in flat.items():
        # a key can't be both a leaf and a subtree: drop any ancestor
        # leaves and any children this write shadows
        parts = key.split(".")
        for i in range(1, len(parts)):
            db.delete("preference", key=".".join(parts[:i]))
        db.execute("DELETE FROM preference WHERE key LIKE ?", (key + ".%",))
        db.upsert("preference", {"key": key}, value=msgpack.packb(value))
    return len(flat)


def read_preferences(db: LibraryDb) -> dict[str, Any]:
    """Load all rows and re-nest (ref:mod.rs:16-55 `read`)."""
    flat = {
        row["key"]: msgpack.unpackb(row["value"]) if row["value"] is not None else None
        for row in db.query("SELECT key, value FROM preference")
    }
    return _nest(flat)


def clear_preference(db: LibraryDb, key_prefix: str) -> int:
    """Remove a subtree of preferences by dotted-key prefix."""
    return db.execute(
        "DELETE FROM preference WHERE key = ? OR key LIKE ?",
        (key_prefix, key_prefix + ".%"),
    ).rowcount
