"""Search DSL — filter/order/cursor queries over file_path and object.

Parity: ref:core/src/api/search/{mod.rs,file_path.rs,object.rs} —
`search.paths` / `search.objects` take `FilePathFilterArgs` /
`ObjectFilterArgs` (locationId, search string, extension, kinds, tags,
labels, hidden, favorite…), an `ordering` enum (name / size /
dateCreated / dateModified / kind), and cursor pagination (`take` +
opaque cursor = the last row's id) compiled into one SQL query
(file_path.rs:19-266). Results come back normalised (sd-cache).
"""

from __future__ import annotations

from typing import Any

from ..db.database import LibraryDb, blob_u64, escape_like
from .cache import normalise
from .router import RspcError

MAX_TAKE = 100  # ref:api/search/mod.rs take.clamp

# sizes are LE u64 blobs (reference parity); bytewise blob order is not
# numeric order, so order by the byte-reversed (big-endian) hex, whose
# fixed-width lexicographic order IS numeric order
_SIZE_ORDER = (
    "COALESCE("
    + "||".join(
        f"substr(hex(fp.size_in_bytes_bytes),{i},2)" for i in (15, 13, 11, 9, 7, 5, 3, 1)
    )
    + ", '0000000000000000')"
)

_FILE_PATH_ORDER = {
    "name": "fp.name",
    "sizeInBytes": _SIZE_ORDER,
    "dateCreated": "fp.date_created",
    "dateModified": "fp.date_modified",
    "dateIndexed": "fp.date_indexed",
    # ISO-8601 text sorts chronologically; never-accessed rows sort LAST
    # under BOTH directions: '~' (0x7E) is > any digit so it's a max key
    # for ASC, '' is a min key so it lands last under DESC
    "dateAccessed": {"ASC": "COALESCE(o.date_accessed, '~')",
                     "DESC": "COALESCE(o.date_accessed, '')"},
}

_OBJECT_ORDER = {
    # same never-accessed-last sentinels as the file_path ordering —
    # the two search endpoints must agree on dateAccessed semantics
    "dateAccessed": {"ASC": "COALESCE(o.date_accessed, '~')",
                     "DESC": "COALESCE(o.date_accessed, '')"},
    "kind": "o.kind",
}


def _clamp_take(arg: dict[str, Any]) -> int:
    take = int(arg.get("take", 50))
    if take < 1:
        raise RspcError.bad_request("take must be >= 1")
    return min(take, MAX_TAKE)


def search_paths(library: Any, arg: dict[str, Any] | None) -> dict[str, Any]:
    """`search.paths` (ref:api/search/mod.rs:185 + file_path.rs:57-266)."""
    arg = arg or {}
    f = arg.get("filter", {}) or {}
    take = _clamp_take(arg)
    conds: list[str] = []
    params: list[Any] = []

    if (loc := f.get("locationId")) is not None:
        conds.append("fp.location_id = ?")
        params.append(int(loc))
    if (search := f.get("search")) not in (None, ""):
        conds.append("fp.name LIKE ? ESCAPE '\\'")
        params.append(f"%{escape_like(str(search))}%")
    if (ext := f.get("extension")) is not None:
        conds.append("fp.extension = ?")
        params.append(str(ext).lstrip(".").lower())
    if (path := f.get("path")) not in (None, ""):
        conds.append("fp.materialized_path = ?")
        params.append(path)
    if (hidden := f.get("hidden")) is not None:
        conds.append("COALESCE(fp.hidden, 0) = ?")
        params.append(int(bool(hidden)))
    if (kinds := f.get("kinds")):
        conds.append(
            f"o.kind IN ({','.join('?' * len(kinds))})"
        )
        params.extend(int(k) for k in kinds)
    if (tags := f.get("tags")):
        conds.append(
            "fp.object_id IN (SELECT object_id FROM tag_on_object "
            f"WHERE tag_id IN ({','.join('?' * len(tags))}))"
        )
        params.extend(int(t) for t in tags)
    if (labels := f.get("labels")):
        conds.append(
            "fp.object_id IN (SELECT object_id FROM label_on_object "
            f"WHERE label_id IN ({','.join('?' * len(labels))}))"
        )
        params.extend(int(l) for l in labels)
    if (fav := f.get("favorite")) is not None:
        conds.append("COALESCE(o.favorite, 0) = ?")
        params.append(int(bool(fav)))
    if (acc := f.get("accessed")) is not None:
        # recents route: only rows that were ever opened
        conds.append(
            "o.date_accessed IS NOT NULL" if acc else "o.date_accessed IS NULL"
        )
    if (md := f.get("mediaDate")):
        # EXIF capture-time range over media_data.epoch_time
        # (ref:api/search object filters joining media_data)
        if not isinstance(md, dict):
            raise RspcError.bad_request("mediaDate must be {from?, to?}")
        sub = ["md.epoch_time IS NOT NULL"]
        if md.get("from") is not None:
            sub.append("md.epoch_time >= ?")
            params.append(int(md["from"]))
        if md.get("to") is not None:
            sub.append("md.epoch_time <= ?")
            params.append(int(md["to"]))
        conds.append(
            "fp.object_id IN (SELECT md.object_id FROM media_data md "
            f"WHERE {' AND '.join(sub)})"
        )

    order_field, direction = _ordering(arg, _FILE_PATH_ORDER, default="name")
    _apply_cursor(arg.get("cursor"), order_field, direction, "fp.id", conds, params)

    where = ("WHERE " + " AND ".join(conds)) if conds else ""
    rows = library.db.query(
        f"SELECT fp.*, o.kind AS object_kind, o.favorite AS object_favorite, "
        f"o.note AS object_note, o.date_accessed AS object_date_accessed, "
        f"{order_field} AS __order "
        "FROM file_path fp LEFT JOIN object o ON o.id = fp.object_id "
        f"{where} ORDER BY {order_field} {direction}, fp.id ASC LIMIT ?",
        (*params, take + 1),
    )
    has_more = len(rows) > take
    rows = rows[:take]
    cursor_out = [rows[-1].get("__order"), rows[-1]["id"]] if has_more and rows else None
    for r in rows:
        r.pop("__order", None)
        r["size_in_bytes"] = blob_u64(r.pop("size_in_bytes_bytes", None)) or 0
    out = normalise("file_path", rows)
    out["cursor"] = cursor_out
    return out


def search_objects(library: Any, arg: dict[str, Any] | None) -> dict[str, Any]:
    """`search.objects` (ref:api/search/object.rs)."""
    arg = arg or {}
    f = arg.get("filter", {}) or {}
    take = _clamp_take(arg)
    conds: list[str] = []
    params: list[Any] = []

    if (kinds := f.get("kinds")):
        conds.append(f"o.kind IN ({','.join('?' * len(kinds))})")
        params.extend(int(k) for k in kinds)
    if (fav := f.get("favorite")) is not None:
        conds.append("COALESCE(o.favorite, 0) = ?")
        params.append(int(bool(fav)))
    if (hidden := f.get("hidden")) is not None:
        conds.append("COALESCE(o.hidden, 0) = ?")
        params.append(int(bool(hidden)))
    if (tags := f.get("tags")):
        conds.append(
            "o.id IN (SELECT object_id FROM tag_on_object "
            f"WHERE tag_id IN ({','.join('?' * len(tags))}))"
        )
        params.extend(int(t) for t in tags)
    if (search := f.get("search")) not in (None, ""):
        conds.append(
            "o.id IN (SELECT object_id FROM file_path "
            "WHERE name LIKE ? ESCAPE '\\')"
        )
        params.append(f"%{escape_like(str(search))}%")

    order_field, direction = _ordering(arg, _OBJECT_ORDER, default="kind")
    _apply_cursor(arg.get("cursor"), order_field, direction, "o.id", conds, params)

    where = ("WHERE " + " AND ".join(conds)) if conds else ""
    rows = library.db.query(
        f"SELECT o.*, {order_field} AS __order FROM object o {where} "
        f"ORDER BY {order_field} {direction}, o.id ASC LIMIT ?",
        (*params, take + 1),
    )
    has_more = len(rows) > take
    rows = rows[:take]
    cursor_out = [rows[-1].get("__order"), rows[-1]["id"]] if has_more and rows else None
    for r in rows:
        r.pop("__order", None)
    out = normalise("object", rows)
    out["cursor"] = cursor_out
    return out


def search_semantic(library: Any, arg: dict[str, Any] | None) -> dict[str, Any]:
    """`search.semantic` — vector-index cosine top-k over the library's
    embeddings (object/search/index.py). The query string resolves to a
    probe vector: an existing image path embeds through the same trunk
    as the pipeline; anything else matches a stored label name and
    probes with the labeled objects' centroid. No reference counterpart
    — the reference stops at label search; this is the paper's device
    workload sold at query time."""
    import time

    from ..object.search import index as _index
    from ..telemetry import metrics as _tm

    arg = arg or {}
    q = arg.get("query")
    if not q or not isinstance(q, str):
        raise RspcError.bad_request("query must be a non-empty string")
    take = _clamp_take(arg)

    t0 = time.perf_counter()
    probe = _index.probe_for(library, q)
    if probe is None:
        return {"items": [], "nodes": [], "scores": {}, "resolved": False}
    hits = _index.query(library, probe, k=take)
    rows: list[dict[str, Any]] = []
    scores: dict[str, float] = {}
    for object_id, score in hits:
        fp = library.db.query_one(
            "SELECT fp.* FROM file_path fp WHERE fp.object_id = ? "
            "ORDER BY fp.id LIMIT 1",
            (object_id,),
        )
        if fp is None:
            continue
        fp["size_in_bytes"] = blob_u64(fp.pop("size_in_bytes_bytes", None)) or 0
        fp["score"] = float(score)
        rows.append(fp)
        scores[str(fp["id"])] = float(score)
    out = normalise("file_path", rows)
    out["scores"] = scores
    out["resolved"] = True
    _tm.SEARCH_QUERY_SECONDS.observe(time.perf_counter() - t0)
    return out


def _apply_cursor(
    cursor: Any,
    order_field: str,
    direction: str,
    id_col: str,
    conds: list[str],
    params: list[Any],
) -> None:
    """Keyset pagination: the opaque cursor is [last order value, last id];
    resume strictly after that pair in the requested direction."""
    if cursor is None:
        return
    try:
        order_val, last_id = cursor[0], int(cursor[1])
    except (TypeError, ValueError, IndexError):
        raise RspcError.bad_request("malformed cursor")
    if order_val is None:
        # NULL order values sort first in SQLite ASC; resume inside them
        # by id, or past them entirely
        if direction == "ASC":
            conds.append(
                f"(({order_field} IS NULL AND {id_col} > ?) "
                f"OR {order_field} IS NOT NULL)"
            )
            params.append(last_id)
        else:
            conds.append(f"({order_field} IS NULL AND {id_col} > ?)")
            params.append(last_id)
        return
    cmp = ">" if direction == "ASC" else "<"
    null_tail = f" OR {order_field} IS NULL" if direction == "DESC" else ""
    conds.append(
        f"({order_field} {cmp} ? OR ({order_field} = ? AND {id_col} > ?)"
        f"{null_tail})"
    )
    params.extend([order_val, order_val, last_id])


def _ordering(
    arg: dict[str, Any], allowed: dict[str, str], default: str
) -> tuple[str, str]:
    ordering = arg.get("orderBy") or default
    if ordering not in allowed:
        raise RspcError.bad_request(f"unknown orderBy {ordering!r}")
    direction = "DESC" if arg.get("orderDir") == "desc" else "ASC"
    expr = allowed[ordering]
    if isinstance(expr, dict):  # direction-dependent NULL sentinel
        expr = expr[direction]
    return expr, direction
