"""Query-invalidation system.

Parity: ref:core/src/api/utils/invalidate.rs:23-137 — mutations call
`invalidate_query!(library, "key")` which (a) validates at startup that
"key" names a real query in the router (the reference walks its
registry in a `ctor` and panics in debug on unknown keys) and (b)
emits `CoreEvent::InvalidateOperation{library_id, key, arg}` on the
event bus; the frontend's `invalidation.listen` subscription maps these
to react-query refetches.
"""

from __future__ import annotations

import logging
import uuid
from dataclasses import dataclass
from typing import Any

from .router import CoreEventKind, Router

logger = logging.getLogger(__name__)


@dataclass(frozen=True)
class InvalidateOperation:
    library_id: str | None
    key: str
    arg: Any = None

    def to_wire(self) -> dict[str, Any]:
        return {"library_id": self.library_id, "key": self.key, "arg": self.arg}


class InvalidationRegistry:
    """Startup-validated key registry (ref:invalidate.rs:23-90)."""

    def __init__(self, router: Router):
        self._valid = {
            key
            for key, proc in router.procedures.items()
            if proc.kind == "query"
        }

    def validate(self, key: str) -> bool:
        if key not in self._valid:
            logger.warning("invalidate_query: unknown query key %r", key)
            return False
        return True


_registry: InvalidationRegistry | None = None


def install_registry(router: Router) -> None:
    global _registry
    _registry = InvalidationRegistry(router)


def invalidate_query(
    node: Any,
    key: str,
    library: Any = None,
    arg: Any = None,
) -> None:
    """The `invalidate_query!` macro (ref:invalidate.rs:137)."""
    if _registry is not None and not _registry.validate(key):
        return
    op = InvalidateOperation(
        library_id=str(library.id) if library is not None else None,
        key=key,
        arg=arg,
    )
    # the serve layer's read-your-writes hook: the same call that tells
    # the frontend to refetch drops the server-side cached results, so
    # a mutation is never answered by its own pre-image
    from ..serve import runtime_for

    serve = runtime_for(node)
    if serve is not None:
        serve.invalidate_query(
            key, library.id if library is not None else None, source="local"
        )
    node.event_bus.emit((CoreEventKind.INVALIDATE_OPERATION, op))
