// ui.js — behavioral component kit (role parity: ref:packages/ui, the
// reference's React primitives: Dropdown.tsx, DropdownMenu.tsx,
// Dialog.tsx, Toast.tsx, Tooltip.tsx, Tabs.tsx, ContextMenu.tsx).
//
// Dependency-free ES module consumed by the explorer modules; class
// contract + tokens documented in docs/ui.md, styles in ui.css.
// Everything here is accessible by construction: dialogs trap focus
// and restore it on close, menus are keyboard-navigable with ARIA
// roles, toasts announce via role=status, tooltips show on focus as
// well as hover.

import { el } from "/static/js/util.js";
import { t } from "/static/js/i18n.js";

// --- Dialog (ref:packages/ui/src/Dialog.tsx) -------------------------------

const FOCUSABLE =
  'button, [href], input, select, textarea, [tabindex]:not([tabindex="-1"])';

let dialogStack = [];

/** Open a modal dialog. `build(body, close)` fills the body; returns
 *  close(). Focus is trapped inside while open and restored to the
 *  previously focused element on close. Escape closes unless
 *  opts.sticky. */
export function openDialog(title, build, opts = {}) {
  const prev = document.activeElement;
  const back = el("div", "dlg-back open");
  const dlg = el("div", "dlg");
  dlg.setAttribute("role", "dialog");
  dlg.setAttribute("aria-modal", "true");
  if (title) {
    const h = el("h2", "", title);
    dlg.appendChild(h);
  }
  back.appendChild(dlg);

  let closed = false;
  const close = () => {
    if (closed) return;
    closed = true;
    back.remove();
    document.removeEventListener("keydown", onKey, true);
    dialogStack = dialogStack.filter(d => d !== back);
    prev?.focus?.();
    opts.onClose?.();  // fires exactly once on ANY close path
  };

  const onKey = (e) => {
    if (dialogStack[dialogStack.length - 1] !== back) return;
    if (e.key === "Escape" && !opts.sticky) {
      e.stopPropagation();
      close();
    } else if (e.key === "Tab") {
      // focus trap: cycle within the dialog; if focus escaped (e.g.
      // backdrop click on a sticky dialog), pull it back in
      const focusables = [...dlg.querySelectorAll(FOCUSABLE)]
        .filter(n => !n.disabled && n.offsetParent !== null);
      if (!focusables.length) { e.preventDefault(); return; }
      const first = focusables[0], last = focusables[focusables.length - 1];
      const inside = dlg.contains(document.activeElement);
      if (!inside) {
        e.preventDefault(); (e.shiftKey ? last : first).focus();
      } else if (e.shiftKey && document.activeElement === first) {
        e.preventDefault(); last.focus();
      } else if (!e.shiftKey && document.activeElement === last) {
        e.preventDefault(); first.focus();
      }
    }
  };

  back.addEventListener("mousedown", (e) => {
    if (e.target === back && !opts.sticky) close();
  });
  document.addEventListener("keydown", onKey, true);
  build(dlg, close);
  document.body.appendChild(back);
  dialogStack.push(back);
  // initial focus: first focusable in the body, else the dialog itself
  const first = dlg.querySelector(FOCUSABLE);
  (first || dlg).focus?.();
  return close;
}

/** Confirm dialog helper: resolves true (confirmed) / false. */
export function confirmDialog(title, message, opts = {}) {
  return new Promise((resolve) => {
    let result = false;
    openDialog(title, (m, close) => {
      if (message) m.appendChild(el("p", "meta", message));
      const actions = el("div", "modal-actions");
      const cancel = el("button", "", opts.cancelLabel || t("cancel"));
      cancel.onclick = close;
      const go = el("button", opts.danger ? "danger" : "primary",
                    opts.actionLabel || t("ok"));
      go.onclick = () => { result = true; close(); };
      actions.appendChild(cancel);
      actions.appendChild(go);
      m.appendChild(actions);
    }, { onClose: () => resolve(result) });  // Escape/backdrop ⇒ false
  });
}

/** Single-input dialog (Dialog + Input pattern): resolves the entered
 *  string, or null on cancel. */
export function promptDialog(title, opts = {}) {
  return new Promise((resolve) => {
    let result = null;
    openDialog(title, (m, close) => {
      if (opts.message) m.appendChild(el("p", "meta", opts.message));
      const input = el("input");
      input.value = opts.value || "";
      input.placeholder = opts.placeholder || "";
      m.appendChild(input);
      const done = () => { result = input.value; close(); };
      input.addEventListener("keydown", (e) => {
        if (e.key === "Enter") done();
      });
      const actions = el("div", "modal-actions");
      const cancel = el("button", "", t("cancel"));
      cancel.onclick = close;
      const go = el("button", "primary", opts.actionLabel || t("ok"));
      go.onclick = done;
      actions.appendChild(cancel);
      actions.appendChild(go);
      m.appendChild(actions);
      input.focus();
      input.select();
    }, { onClose: () => resolve(result) });  // Escape/backdrop ⇒ null
  });
}

// --- Menu / Dropdown (ref:packages/ui/src/{DropdownMenu,ContextMenu}.tsx) --

let openMenuEl = null;

export function closeMenu() {
  openMenuEl?.remove();
  openMenuEl = null;
}

/** Show a floating menu at (x, y). Items:
 *    {label, onClick, danger?, disabled?} | {separator: true}
 *  Keyboard: arrows/Home/End move, Enter/Space activate, Escape
 *  closes. Click-outside dismisses (wired once in initMenus). */
export function openMenu(x, y, items) {
  closeMenu();
  const menu = el("div", "ctxmenu");
  menu.setAttribute("role", "menu");
  const itemEls = [];
  for (const it of items) {
    if (!it) continue;
    if (it.separator) {
      menu.appendChild(el("div", "ctx-sep"));
      continue;
    }
    const item = el("div",
      "ctx-item" + (it.danger ? " danger" : "") +
      (it.disabled ? " disabled" : ""), it.label);
    item.setAttribute("role", "menuitem");
    item.tabIndex = -1;
    if (!it.disabled) {
      item.onclick = async () => {
        closeMenu();
        try {
          await it.onClick?.();
        } catch (e) {
          toast("✗ " + e.message, { kind: "error" });
        }
      };
      itemEls.push(item);
    }
    menu.appendChild(item);
  }
  menu.addEventListener("keydown", (e) => {
    const idx = itemEls.indexOf(document.activeElement);
    const move = (to) =>
      itemEls[(to + itemEls.length) % itemEls.length]?.focus();
    if (e.key === "ArrowDown") { e.preventDefault(); move(idx + 1); }
    else if (e.key === "ArrowUp") { e.preventDefault(); move(idx - 1); }
    else if (e.key === "Home") { e.preventDefault(); move(0); }
    else if (e.key === "End") { e.preventDefault(); move(-1); }
    else if (e.key === "Enter" || e.key === " ") {
      e.preventDefault(); document.activeElement?.click?.();
    } else if (e.key === "Escape") { e.stopPropagation(); closeMenu(); }
  });
  document.body.appendChild(menu);
  // clamp into the viewport AFTER layout so real size is known
  const r = menu.getBoundingClientRect();
  menu.style.left = Math.min(x, innerWidth - r.width - 6) + "px";
  menu.style.top = Math.min(y, innerHeight - r.height - 6) + "px";
  openMenuEl = menu;
  itemEls[0]?.focus();
  return closeMenu;
}

/** Anchor a dropdown menu to a trigger element: opens below it on
 *  click. `itemsFn()` builds the items lazily per open. */
export function attachDropdown(trigger, itemsFn) {
  trigger.setAttribute("aria-haspopup", "menu");
  trigger.addEventListener("click", (e) => {
    e.stopPropagation();
    if (openMenuEl) { closeMenu(); return; }
    const r = trigger.getBoundingClientRect();
    openMenu(r.left, r.bottom + 4, itemsFn());
  });
}

/** Global dismiss wiring for menus (call once from app boot). */
export function initMenus() {
  document.addEventListener("click", closeMenu);
  document.addEventListener("keydown", (e) => {
    if (e.key === "Escape" && openMenuEl) {
      e.stopPropagation();
      closeMenu();
    }
  }, true);
}

// --- Toast (ref:packages/ui/src/Toast.tsx) ---------------------------------

/** Transient notification. kind: info | ok | error. Errors stay 6s,
 *  the rest 3s (or opts.timeout ms). */
export function toast(message, opts = {}) {
  let holder = document.getElementById("toasts");
  if (!holder) {
    holder = el("div");
    holder.id = "toasts";
    document.body.appendChild(holder);
  }
  const kind = opts.kind || "info";
  const t = el("div", `toast ${kind}`, message);
  t.setAttribute("role", "status");
  holder.appendChild(t);
  const ttl = opts.timeout ?? (kind === "error" ? 6000 : 3000);
  const gone = () => { t.classList.add("out"); setTimeout(() => t.remove(), 300); };
  const timer = setTimeout(gone, ttl);
  t.onclick = () => { clearTimeout(timer); gone(); };
  return t;
}

// --- Tooltip (ref:packages/ui/src/Tooltip.tsx) -----------------------------

let tipEl = null, tipTimer = null;

function showTip(target) {
  const text = target.getAttribute("data-tip");
  if (!text) return;
  hideTip();
  tipEl = el("div", "tooltip", text);
  document.body.appendChild(tipEl);
  const r = target.getBoundingClientRect();
  const tr = tipEl.getBoundingClientRect();
  tipEl.style.left =
    Math.max(4, Math.min(r.left + r.width / 2 - tr.width / 2,
                         innerWidth - tr.width - 4)) + "px";
  tipEl.style.top = (r.top > tr.height + 8
    ? r.top - tr.height - 6 : r.bottom + 6) + "px";
}

function hideTip() {
  clearTimeout(tipTimer);
  tipTimer = null;
  tipEl?.remove();
  tipEl = null;
}

/** Delegated tooltips: any element with `data-tip="…"` gets one on
 *  hover (400 ms delay) or keyboard focus (call once from app boot). */
export function initTooltips() {
  document.addEventListener("mouseover", (e) => {
    const t = e.target.closest?.("[data-tip]");
    if (!t) return;
    clearTimeout(tipTimer);
    tipTimer = setTimeout(() => showTip(t), 400);
  });
  document.addEventListener("mouseout", hideTip);
  document.addEventListener("focusin", (e) => {
    const t = e.target.closest?.("[data-tip]");
    if (t) showTip(t);
  });
  document.addEventListener("focusout", hideTip);
  document.addEventListener("mousedown", hideTip);
}

// --- Tabs (ref:packages/ui/src/Tabs.tsx) -----------------------------------

/** Build an accessible tab strip inside `root`.
 *  defs: [{id, label, render(body)}]. Arrow keys move between tabs;
 *  the active panel re-renders on switch. Returns {select(id)}. */
export function tabs(root, defs, opts = {}) {
  const strip = el("div", "tabs");
  strip.setAttribute("role", "tablist");
  const body = el("div", "tab-body");
  const btns = new Map();
  let generation = 0;

  const select = (id) => {
    for (const [bid, b] of btns) {
      b.classList.toggle("active", bid === id);
      b.setAttribute("aria-selected", bid === id ? "true" : "false");
      b.tabIndex = bid === id ? 0 : -1;
    }
    // async renders fill a detached node and only attach if still the
    // active generation — a slow tab must never leak rows into the
    // tab selected after it
    const gen = ++generation;
    const scratch = el("div");
    Promise.resolve(defs.find(d => d.id === id)?.render(scratch))
      .then(() => {
        if (gen !== generation) return;
        body.innerHTML = "";
        body.append(...scratch.childNodes);
      });
    opts.onSelect?.(id);
  };

  defs.forEach((d, i) => {
    const b = el("button", "tab", d.label);
    b.setAttribute("role", "tab");
    b.onclick = () => select(d.id);
    b.addEventListener("keydown", (e) => {
      const delta = e.key === "ArrowRight" ? 1 : e.key === "ArrowLeft" ? -1 : 0;
      if (!delta) return;
      e.preventDefault();
      const next = defs[(i + delta + defs.length) % defs.length];
      select(next.id);
      btns.get(next.id)?.focus();
    });
    btns.set(d.id, b);
    strip.appendChild(b);
  });

  root.appendChild(strip);
  root.appendChild(body);
  select(opts.initial || defs[0]?.id);
  return { select, body };
}
