// Inspector panel: file details, favorite, note, tag chips + editor,
// labels (role parity: ref:interface/app/$libraryId/Explorer/Inspector).

import client from "/rspc/client.js";
import { $, bus, el, fmtBytes, fullPath, state } from "/static/js/util.js";
import { t } from "/static/js/i18n.js";

/** dt/dd list builder shared by the details and media sections. */
function makeDl() {
  const dl = el("dl");
  const add = (k, v) => { if (v !== undefined && v !== null && v !== "") {
    dl.appendChild(el("dt", "", k)); dl.appendChild(el("dd", "", String(v))); } };
  return { dl, add };
}

/** EXIF/stream facts for the selected object (ref:Inspector MediaData
 *  section over files.getMediaData). */
async function mediaSection(box, n) {
  // `box` is a placeholder appended synchronously by THIS selection's
  // render: if a newer selection supersedes us, the box is already
  // detached and these appends are invisible — no staleness hazard,
  // and the favorite/note/tags render is never serialized behind the
  // media RPC.
  let md = null;
  try {
    md = await client.files.getMediaData(n.object_id, state.lib);
  } catch {
    return;
  }
  if (!md) return;
  const { dl, add } = makeDl();
  const res = md.resolution;
  if (res && res[0]) add(t("media_resolution"), `${res[0]} × ${res[1]}`);
  const cam = md.camera_data || {};
  if (cam.video) {
    if (cam.duration_seconds)
      add(t("media_duration"), `${cam.duration_seconds.toFixed(1)} s`);
    if (cam.fps) add("fps", cam.fps.toFixed(2));
    if (cam.codec) add(t("media_codec"), cam.codec);
  } else {
    add(t("media_taken"), md.media_date);
    const device = [cam.device_make, cam.device_model]
      .filter(Boolean).join(" ");
    if (device) add(t("media_camera"), device);
    if (cam.focal_length) add(t("media_focal"), `${cam.focal_length} mm`);
    if (cam.iso) add("ISO", cam.iso);
    if (cam.aperture) add(t("media_aperture"), `f/${cam.aperture}`);
    if (cam.shutter_speed) add(t("media_shutter"), cam.shutter_speed);
  }
  const loc = md.media_location;
  if (loc && loc.latitude !== undefined)
    add("GPS", `${(+loc.latitude).toFixed(5)}, ${(+loc.longitude).toFixed(5)}`);
  if (md.artist) add(t("media_artist"), md.artist);
  if (!dl.children.length) return;
  const head = el("h4", "", t("media_section"));
  head.style.margin = "12px 0 4px";
  box.appendChild(head);
  box.appendChild(dl);
}

export function updateSelection() {
  const ids = state.selectedIds;
  document.querySelectorAll("#content .card, #content tr[data-fp]")
    .forEach(e => e.classList.toggle("selected", ids.has(+e.dataset.fp)));
}

/** Selection model: plain click = single; ctrl/cmd = toggle; shift =
 *  range from the anchor (ref:interface Explorer multi-select). */
let selGen = 0;  // bumped per select(); stale awaits bail

export async function select(n, ev = null) {
  const gen = ++selGen;
  if (ev && (ev.ctrlKey || ev.metaKey)) {
    if (state.selectedIds.has(n.id) && state.selectedIds.size > 1) {
      state.selectedIds.delete(n.id);
      n = state.nodes.find(x => state.selectedIds.has(x.id)) || n;
    } else {
      state.selectedIds.add(n.id);
    }
  } else if (ev && ev.shiftKey && state.selected) {
    const a = state.nodes.findIndex(x => x.id === state.selected.id);
    const b = state.nodes.findIndex(x => x.id === n.id);
    if (a >= 0 && b >= 0) {
      state.selectedIds = new Set(
        state.nodes.slice(Math.min(a, b), Math.max(a, b) + 1).map(x => x.id)
      );
    } else {
      // stale anchor (nodes were reloaded): degrade to single-select
      // so the inspector never disagrees with the highlight
      state.selectedIds = new Set([n.id]);
    }
  } else {
    state.selectedIds = new Set([n.id]);
  }
  state.selected = n;
  updateSelection();
  const insp = $("inspector");
  insp.classList.add("open");
  insp.innerHTML = "";
  if (state.selectedIds.size > 1) {
    insp.appendChild(el("h3", "", `${state.selectedIds.size} items selected`));
    const chosen = state.nodes.filter(x => state.selectedIds.has(x.id));
    const bytes = chosen.reduce(
      (s, x) => s + (x.is_dir ? 0 : (x.size_in_bytes || 0)), 0);
    insp.appendChild(el("div", "meta", `${fmtBytes(bytes)} total`));
    return;
  }
  insp.appendChild(el("h3", "",
    n.name + (n.extension ? "." + n.extension : "")));
  const { dl, add } = makeDl();
  add("kind", n.is_dir ? "folder" : (n.object_kind ?? ""));
  add("size", n.is_dir ? "" : fmtBytes(n.size_in_bytes));
  add("created", (n.date_created || "").slice(0, 19));
  add("modified", (n.date_modified || "").slice(0, 19));
  add("path", (n.materialized_path || "") + n.name);
  add("cas_id", n.cas_id);
  insp.appendChild(dl);

  if (n.object_id) {
    // media facts fill in asynchronously alongside the controls below
    if ([5, 7].includes(n.object_kind)) {
      const mediaBox = el("div");
      insp.appendChild(mediaBox);
      mediaSection(mediaBox, n);
    }
    // favorite + note (files.setFavorite/setNote take the file_path id)
    const favBtn = el("button", "",
      n.object_favorite ? "★ favorited" : "☆ favorite");
    favBtn.onclick = async () => {
      n.object_favorite = n.object_favorite ? 0 : 1;
      await client.files.setFavorite(
        {id: n.id, favorite: !!n.object_favorite}, state.lib);
      select(n);
    };
    insp.appendChild(favBtn);
    insp.appendChild(el("h4", "", " "));
    const note = el("textarea");
    note.id = "note";
    note.placeholder = "note…";
    note.value = n.object_note || "";
    note.onblur = async () => {
      if (note.value !== (n.object_note || "")) {
        n.object_note = note.value;
        await client.files.setNote(
          {id: n.id, note: note.value}, state.lib);
      }
    };
    insp.appendChild(note);

    // tags (chips + editor)
    const tagHead = el("h4", "", "Tags");
    tagHead.style.margin = "12px 0 4px";
    insp.appendChild(tagHead);
    const chipBox = el("div");
    insp.appendChild(chipBox);
    const myTags = (await client.tags.getForObject(n.object_id, state.lib)).nodes;
    if (gen !== selGen) return;  // superseded while fetching tags
    for (const tg of myTags) {
      const chip = el("span", "chip");
      const dot = el("i", "dot");
      dot.style.background = tg.color || "#5a7bfc";
      chip.appendChild(dot);
      chip.appendChild(document.createTextNode(tg.name || "?"));
      const x = el("span", "x", "×");
      x.onclick = async () => {
        await client.tags.assign(
          {tag_id: tg.id, object_ids: [n.object_id], unassign: true}, state.lib);
        select(n);
      };
      chip.appendChild(x);
      chipBox.appendChild(chip);
    }
    const addBox = el("div", "addtag");
    const sel = el("select");
    sel.appendChild(el("option", "", "+ tag…"));
    for (const tg of state.allTags) {
      if (myTags.some(m => m.id === tg.id)) continue;
      const o = el("option", "", tg.name || "?");
      o.value = tg.id;
      sel.appendChild(o);
    }
    const newOpt = el("option", "", "new tag…");
    newOpt.value = "__new__";
    sel.appendChild(newOpt);
    sel.onchange = async () => {
      if (sel.value === "__new__") {
        const name = prompt("tag name");
        if (!name) { sel.selectedIndex = 0; return; }
        const color = "#" + Math.floor(Math.random()*0xffffff)
          .toString(16).padStart(6, "0");
        const tid = await client.tags.create({name, color}, state.lib);
        await client.tags.assign(
          {tag_id: tid, object_ids: [n.object_id]}, state.lib);
      } else if (sel.value) {
        await client.tags.assign(
          {tag_id: +sel.value, object_ids: [n.object_id]}, state.lib);
      }
      const tags = await client.tags.list(null, state.lib);
      state.allTags = tags.nodes;
      bus.refreshNav?.();
      select(n);
    };
    addBox.appendChild(sel);
    insp.appendChild(addBox);

    // labels (read-only; written by the image labeler)
    const labels =
      (await client.labels.getForObject(n.object_id, state.lib)).nodes;
    if (gen !== selGen) return;  // superseded while fetching labels
    if (labels.length) {
      const lh = el("h4", "", "Labels");
      lh.style.margin = "12px 0 4px";
      insp.appendChild(lh);
      const lb = el("div");
      for (const l of labels)
        lb.appendChild(el("span", "chip", l.name));
      insp.appendChild(lb);
    }

    // spacedrop shortcut
    const dropBtn = el("button", "", "📡 spacedrop this file");
    dropBtn.style.marginTop = "12px";
    dropBtn.onclick = () => bus.openDropPanel([fullPath(n)]);
    insp.appendChild(dropBtn);
  }
}

export function closeInspector() {
  state.selected = null;
  state.selectedIds = new Set();  // a dismissed selection must not
  // stay live for batch context-menu operations
  updateSelection();
  $("inspector").classList.remove("open");
}
