// Network page: who's discovered/connected on the mesh, pair action,
// and the node's WAN path telemetry (punched-direct vs relayed).
// Role parity: ref:interface/app/$libraryId/network.tsx (peer grid)
// plus the reference's p2p debug surface.

import client from "/rspc/client.js";
import { $, el, state } from "/static/js/util.js";
import { toast } from "/static/js/ui.js";
import { t } from "/static/js/i18n.js";

export async function loadNetwork() {
  const c = $("content");
  c.className = "";
  c.innerHTML = "";
  const st = await client.p2p.state();
  if (!st.enabled) {
    c.appendChild(el("div", "meta", t("p2p_disabled")));
    return;
  }
  const head = el("div", "dupgroup");
  head.appendChild(el("b", "", t("this_node")));
  head.appendChild(el("div", "meta", `${t("identity")}: ${st.identity}`));
  head.appendChild(el("div", "meta", `${t("p2p_port")}: ${st.port}`));
  if (st.punch) {
    // path-selection telemetry: how dials actually went out
    head.appendChild(el("div", "meta",
      `${t("wan_paths")}: ${st.punch.direct} ${t("path_direct")} · ` +
      `${st.punch.fallback} ${t("path_relayed")}`));
  }
  c.appendChild(head);

  if (!st.peers.length) {
    c.appendChild(el("div", "meta", t("no_peers")));
    return;
  }
  for (const p of st.peers) {
    const box = el("div", "dupgroup");
    box.dataset.peer = p.identity;
    const title = el("b", "", p.metadata.name || p.identity.slice(0, 16));
    box.appendChild(title);
    const badge = el("span", "badge " + (p.connected ? "ok" : ""),
      p.connected ? t("peer_connected") : t("peer_discovered"));
    badge.style.marginLeft = "8px";
    title.appendChild(badge);
    box.appendChild(el("div", "meta", p.identity));
    if (p.addrs.length)
      box.appendChild(el("div", "meta", p.addrs.join("  ")));
    const os = p.metadata.operating_system || p.metadata.os;
    if (os) box.appendChild(el("div", "meta", os));
    const pair = el("button", "mini", t("pair_with_peer"));
    pair.onclick = async () => {
      try {
        await client.p2p.pairLibrary({identity: p.identity});
        toast(t("pair_requested"), {kind: "ok"});
      } catch (e) {
        toast(`${t("pair_failed")}: ${e.message}`, {kind: "error"});
      }
    };
    box.appendChild(pair);
    c.appendChild(box);
  }
}
