// Shared state + tiny DOM helpers (role parity: packages/client stores).

import { openDialog } from "/static/js/ui.js";

export const KIND_ICON = {0:"📄",1:"📑",2:"📁",3:"📝",4:"📦",5:"🖼️",6:"🎵",
                          7:"🎬",8:"🗜️",9:"⚙️",10:"🔗",11:"🔒",12:"🔑",
                          13:"🔗",14:"🌐"};

export const ORDER_FIELDS =
  ["name", "sizeInBytes", "dateCreated", "dateModified", "dateAccessed"];

// persisted values are validated: a stale/hand-edited key must not
// make every search.paths call 400 with no visible error
function persisted(key, allowed, fallback) {
  const v = localStorage.getItem(key);
  return allowed.includes(v) ? v : fallback;
}

export const state = {
  lib: null, loc: null, tag: null, search: "", cursor: null,
  path: "/",                       // materialized path inside the location
  mode: "browse",                  // browse | search | duplicates
  view: persisted("sd-view", ["grid", "list", "media"], "grid"),
  orderBy: persisted("sd-order", ORDER_FIELDS, "name"),
  orderDir: persisted("sd-orderdir", ["asc", "desc"], "asc"),
  nodes: [], selected: null, selectedIds: new Set(),
  locPaths: {}, locNames: {}, allTags: [],
};

// late-bound cross-module calls (registered by app.js; avoids cycles)
export const bus = {};

export function el(tag, cls, text) {
  const e = document.createElement(tag);
  if (cls) e.className = cls;
  if (text !== undefined) e.textContent = text;
  return e;
}

export const $ = (id) => document.getElementById(id);

export function fmtBytes(n) {
  if (!n && n !== 0) return "";
  const u = ["B","KB","MB","GB","TB"]; let i = 0;
  while (n >= 1024 && i < u.length-1) { n /= 1024; i++; }
  return n.toFixed(n < 10 && i ? 1 : 0) + " " + u[i];
}

export const thumbUrl = (n) =>
  `/spacedrive/thumbnail/${n.ephemeral ? "ephemeral" : state.lib}` +
  `/${n.cas_id.slice(0,3)}/${n.cas_id}.webp`;

/** location-relative path of a row ("/dir/name.ext") */
export const relPath = (n) =>
  (n.materialized_path || "/") + n.name +
  (n.extension ? "." + n.extension : "");

export const fullPath = (n) => (state.locPaths[n.location_id] || "") + relPath(n);

/** Modal helper — thin wrapper over the ui kit's Dialog so every
 *  existing call site gets focus trapping + Escape + focus restore.
 *  (util ⇄ ui is a call-time-only ES-module cycle — both sides touch
 *  the other's exports inside functions, never at eval time.) */
export function modal(title, build) {
  return openDialog(title, build);
}
