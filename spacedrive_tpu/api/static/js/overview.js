// Overview landing page: library stat cards, per-kind breakdown,
// location cards (role parity: ref:interface/app/$libraryId/overview/
// — LibraryStats.tsx, FileKindStats.tsx, LocationCard.tsx).

import client from "/rspc/client.js";
import { $, KIND_ICON, bus, el, fmtBytes, state } from "/static/js/util.js";
import { t } from "/static/js/i18n.js";

function statCard(label, value, tip) {
  const card = el("div", "stat-card");
  if (tip) card.setAttribute("data-tip", tip);
  card.appendChild(el("div", "value", value));
  card.appendChild(el("div", "meta", label));
  return card;
}

export async function loadOverview() {
  const c = $("content");
  c.className = "overview";
  c.innerHTML = "";
  const [stats, kinds, locs] = await Promise.all([
    client.library.statistics(null, state.lib),
    client.library.kindStatistics(null, state.lib),
    client.locations.list(null, state.lib),
  ]);
  if (state.mode !== "overview") return;  // superseded by navigation

  // --- library stats row (ref:overview/LibraryStats.tsx) -------------
  const row = el("div", "stat-row");
  row.appendChild(statCard(t("objects"), String(stats.total_object_count ?? 0)));
  row.appendChild(statCard(t("indexed"), fmtBytes(+stats.total_bytes_used || 0), t("indexed_tip")));
  row.appendChild(statCard(t("capacity"), fmtBytes(+stats.total_bytes_capacity || 0), t("capacity_tip")));
  row.appendChild(statCard(t("free"), fmtBytes(+stats.total_bytes_free || 0)));
  row.appendChild(statCard(t("database"), fmtBytes(+stats.library_db_size || 0), t("database_tip")));
  row.appendChild(statCard(t("previews"), fmtBytes(+stats.preview_media_bytes || 0), t("previews_tip")));
  c.appendChild(row);

  // --- per-kind breakdown (ref:overview/FileKindStats.tsx) -----------
  c.appendChild(el("h4", "ov-head", t("by_kind")));
  const kindRow = el("div", "kind-row");
  for (const k of kinds.statistics) {
    if (!k.count) continue;
    const card = el("div", "kind-card");
    card.appendChild(el("div", "icon", KIND_ICON[k.kind] || "📄"));
    card.appendChild(el("div", "", k.name));
    card.appendChild(el("div", "meta",
      `${k.count}${+k.total_bytes ? " · " + fmtBytes(+k.total_bytes) : ""}`));
    card.onclick = () => {
      Object.assign(state, {mode: "kind", kindFilter: k.kind,
                            kindName: k.name, loc: null, tag: null,
                            cursor: null});
      bus.clearSelection?.();
      bus.loadContent(true);
    };
    kindRow.appendChild(card);
  }
  if (!kindRow.children.length)
    kindRow.appendChild(el("div", "meta", t("nothing_indexed")));
  c.appendChild(kindRow);

  // --- locations (ref:overview/LocationCard.tsx) ---------------------
  c.appendChild(el("h4", "ov-head", t("locations")));
  const locRow = el("div", "kind-row");
  for (const n of locs.nodes) {
    const card = el("div", "kind-card loc");
    card.appendChild(el("div", "icon", "📂"));
    card.appendChild(el("div", "", n.name || n.path));
    card.appendChild(el("div", "meta", n.path));
    card.onclick = () => {
      Object.assign(state, {mode: "browse", loc: n.id, tag: null,
                            path: "/", cursor: null});
      bus.clearSelection?.();
      bus.loadContent(true);
      bus.refreshNav?.();
    };
    locRow.appendChild(card);
  }
  if (!locRow.children.length)
    locRow.appendChild(el("div", "meta", t("no_locations_yet")));
  c.appendChild(locRow);
}
