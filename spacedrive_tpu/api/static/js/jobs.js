// Job manager panel + live progress ticker
// (role parity: ref:interface JobManager + CoreEvent::JobProgress).

import client from "/rspc/client.js";
import { $, el, state } from "/static/js/util.js";

const jobState = new Map(); // id -> live progress event

export function onJobProgress(ev) {
  jobState.set(ev.id, ev);
  $("jobticker").textContent =
    ev.completed_task_count < ev.task_count
      ? `⏳ ${ev.name || "job"} ${ev.completed_task_count}/${ev.task_count}`
      : "";
  if ($("jobs-panel").classList.contains("open")) renderJobs();
}

export async function renderJobs() {
  const reports = await client.jobs.reports(null, state.lib);
  const list = $("jobs-list");
  list.innerHTML = "";
  for (const r of reports) {
    const live = jobState.get(r.id);
    const total = live ? live.task_count : r.task_count;
    const done = live ? live.completed_task_count : r.completed_task_count;
    const running = r.status === "RUNNING" || r.status === "PAUSED";
    const job = el("div", "job " + r.status);
    const row = el("div", "row");
    row.appendChild(el("b", "", r.name));
    row.appendChild(el("span", "status",
      r.status + (total ? ` ${done}/${total}` : "")));
    job.appendChild(row);
    const bar = el("div", "bar");
    const fill = el("i");
    fill.style.width = (total ? Math.round(100 * done / total) :
      (r.status.startsWith("COMPLETED") ? 100 : 0)) + "%";
    bar.appendChild(fill);
    job.appendChild(bar);
    if (r.errors && r.errors.length) {
      const errEl = el("div", "status", r.errors.join("\n"));
      errEl.style.color = "var(--err)";
      errEl.style.whiteSpace = "pre-line";
      job.appendChild(errEl);
    }
    if (running) {
      const act = el("div", "row");
      act.style.marginTop = "6px";
      const pause = el("button", "",
        r.status === "PAUSED" ? "resume" : "pause");
      pause.onclick = async () => {
        await (r.status === "PAUSED" ? client.jobs.resume(r.id)
                                     : client.jobs.pause(r.id));
        renderJobs();
      };
      const cancel = el("button", "danger", "cancel");
      cancel.onclick = async () => {
        await client.jobs.cancel(r.id); renderJobs();
      };
      act.appendChild(pause); act.appendChild(cancel);
      job.appendChild(act);
    }
    list.appendChild(job);
  }
}

export function wireJobsPanel() {
  $("btn-jobs").onclick = () => {
    const p = $("jobs-panel");
    $("drop-panel").classList.remove("open");
    $("settings-panel").classList.remove("open");
    p.classList.toggle("open");
    if (p.classList.contains("open")) renderJobs();
  };
  $("jobs-clear").onclick = async () => {
    await client.jobs.clearAll(null, state.lib); renderJobs();
  };
}
