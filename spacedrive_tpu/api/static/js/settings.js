// Settings panel: node, library, locations, volumes — a tabbed panel
// built on the ui kit (role parity: ref:interface/app/$libraryId/
// settings screens over ref:packages/ui Tabs/Dialog/Toast).

import client from "/rspc/client.js";
import { $, bus, el, fmtBytes, state } from "/static/js/util.js";
import {
  confirmDialog, openDialog, tabs, toast,
} from "/static/js/ui.js";
import { LOCALES, locale, setLocale, t } from "/static/js/i18n.js";

let activeTab = "node";

export function renderSettings() {
  const p = $("settings-panel");
  p.innerHTML = "";
  p.appendChild(el("h2", "", t("settings")));
  tabs(p, [
    {id: "node", label: t("tab_node"), render: renderNodeTab},
    {id: "library", label: t("tab_library"), render: renderLibraryTab},
    {id: "locations", label: t("tab_locations"), render: renderLocationsTab},
    {id: "volumes", label: t("tab_volumes"), render: renderVolumesTab},
    {id: "keys", label: t("tab_keys"), render: renderKeysTab},
    {id: "rules", label: t("tab_rules"), render: renderRulesTab},
  ], {initial: activeTab, onSelect: (id) => { activeTab = id; }});
}

async function renderNodeTab(body) {
  const ns = await client.nodeState();
  body.appendChild(el("h4", "", t("this_node")));
  const nameRow = el("div", "row");
  const nameIn = el("input");
  nameIn.value = ns.name || "";
  const nameBtn = el("button", "mini", t("rename"));
  nameBtn.onclick = async () => {
    await client.nodes.edit({name: nameIn.value});
    toast(t("node_renamed"), {kind: "ok"});
    bus.refreshHeader?.();
  };
  nameRow.appendChild(nameIn);
  nameRow.appendChild(nameBtn);
  body.appendChild(nameRow);

  const bgRow = el("div", "row");
  bgRow.appendChild(el("span", "", t("bg_thumb_pct")));
  const bgIn = el("input");
  bgIn.type = "number";
  bgIn.min = 1; bgIn.max = 100;
  bgIn.style.width = "70px";
  bgIn.value = ns.thumbnailer_background_percentage ?? 50;
  bgIn.onchange = () => client.nodes.updateThumbnailerPreferences(
    {background_processing_percentage: +bgIn.value});
  bgRow.appendChild(bgIn);
  body.appendChild(bgRow);

  body.appendChild(el("h4", "", t("language")));
  const langRow = el("div", "row");
  const sel = el("select");
  for (const [code, label] of Object.entries(LOCALES)) {
    const o = el("option", "", label);
    o.value = code;
    sel.appendChild(o);
  }
  sel.value = locale();
  sel.onchange = () => setLocale(sel.value);
  langRow.appendChild(sel);
  body.appendChild(langRow);

  body.appendChild(el("h4", "", t("features")));
  for (const feat of ["filesOverP2P", "cloudSync"]) {
    const row = el("div", "row");
    row.appendChild(el("span", "", feat));
    const cb = el("input");
    cb.type = "checkbox";
    cb.checked = (ns.features || []).includes(feat);
    cb.onchange = () =>
      client.toggleFeatureFlag({feature: feat, enabled: cb.checked});
    row.appendChild(cb);
    body.appendChild(row);
  }
}

async function renderLibraryTab(body) {
  const libs = await client.library.list();
  const cur = libs.find(l => l.uuid === state.lib);
  if (!cur) return;
  const rn = el("div", "row");
  const libIn = el("input");
  libIn.value = cur.config.name;
  const rb = el("button", "mini", t("rename"));
  rb.onclick = async () => {
    await client.library.edit({id: state.lib, name: libIn.value});
    toast(t("library_renamed"), {kind: "ok"});
    bus.reloadLibraries?.();
  };
  rn.appendChild(libIn);
  rn.appendChild(rb);
  body.appendChild(rn);

  const act = el("div", "row");
  const newBtn = el("button", "mini", t("new_library"));
  newBtn.onclick = () => createLibraryModal();
  const delBtn = el("button", "mini danger", t("delete_library"));
  delBtn.onclick = async () => {
    const ok = await confirmDialog(t("delete_library_title"),
      t("delete_library_body", {name: cur.config.name}),
      {danger: true, actionLabel: t("delete")});
    if (!ok) return;
    await client.library.delete({id: state.lib});
    bus.reloadLibraries?.();
  };
  act.appendChild(newBtn);
  act.appendChild(delBtn);
  body.appendChild(act);

  // Backups (ref:core/src/api/backups.rs + interface settings/node/
  // backups): snapshot now, restore or delete existing snapshots
  body.appendChild(el("h4", "", t("backups_heading")));
  const mk = el("div", "row");
  const bk = el("button", "mini", t("backup_now"));
  const rerender = async () => { body.innerHTML = ""; await renderLibraryTab(body); };
  bk.onclick = async () => {
    try {
      await client.backups.backup(null, state.lib);
      toast(t("backup_done_toast"), {kind: "ok"});
      rerender();
    } catch (e) { toast(e.message, {kind: "error"}); }
  };
  mk.appendChild(bk);
  body.appendChild(mk);
  // only THIS library's snapshots: restore targets the backup's own
  // library_id, so listing others here would roll back a library the
  // user isn't even looking at
  const backups = (await client.backups.getAll())
    .filter(b => b.library_id === state.lib);
  if (!backups.length)
    body.appendChild(el("p", "meta", t("backups_empty")));
  for (const b of backups) {
    const row = el("div", "row");
    row.dataset.backup = b.id;
    row.appendChild(el("span", "", "🗄 " + (b.library_name || b.id)));
    row.appendChild(el("span", "meta", (b.timestamp || "").slice(0, 19)));
    const rs = el("button", "mini", t("backup_restore"));
    rs.onclick = async () => {
      const ok = await confirmDialog(t("backup_restore_title"),
        t("backup_restore_body", {ts: (b.timestamp || "").slice(0, 19)}),
        {danger: true, actionLabel: t("backup_restore")});
      if (!ok) return;
      try {
        await client.backups.restore({path: b.path});
        toast(t("backup_restored_toast"), {kind: "ok"});
        bus.reloadLibraries?.();
      } catch (e) { toast(e.message, {kind: "error"}); }
    };
    row.appendChild(rs);
    const del = el("button", "mini", t("delete"));
    del.onclick = async () => {
      const ok = await confirmDialog(t("backup_delete_title"),
        t("backup_delete_body", {ts: (b.timestamp || "").slice(0, 19)}),
        {danger: true, actionLabel: t("delete")});
      if (!ok) return;
      try {
        await client.backups.delete(b.path);
        rerender();
      } catch (e) { toast(e.message, {kind: "error"}); }
    };
    row.appendChild(del);
    body.appendChild(row);
  }
}

async function renderLocationsTab(body) {
  const locs = await client.locations.list(null, state.lib);
  for (const n of locs.nodes) {
    const row = el("div", "loc-row");
    row.appendChild(el("b", "", n.name || n.path));
    row.appendChild(el("div", "meta", n.path));
    const act = el("div", "actions");
    const rescan = el("button", "mini", t("rescan"));
    rescan.setAttribute("data-tip", t("rescan_tip"));
    rescan.onclick = async () => {
      await client.locations.fullRescan(
        {location_id: n.id, reidentify_objects: false}, state.lib);
      rescan.textContent = t("rescanning");
      toast(t("rescan_started"), {kind: "ok"});
    };
    const del = el("button", "mini danger", t("remove"));
    del.setAttribute("data-tip", t("remove_tip"));
    del.onclick = async () => {
      await client.locations.delete(n.id, state.lib);
      renderSettings();
      bus.refreshNav?.();
    };
    act.appendChild(rescan);
    act.appendChild(del);
    row.appendChild(act);
    body.appendChild(row);
  }
  const addBtn = el("button", "", t("add_location"));
  addBtn.onclick = () => addLocationModal();
  body.appendChild(addBtn);
}

async function renderVolumesTab(body) {
  const vols = await client.volumes.list();
  for (const v of vols) {
    const row = el("div", "row");
    row.appendChild(el("span", "", `${v.name || v.mount_point}`));
    row.appendChild(el("span", "meta",
      `${fmtBytes(v.available_capacity)} free of ${fmtBytes(v.total_capacity)}`));
    body.appendChild(row);
  }
}

// Indexer rules (ref:interface settings/library/rules over
// core/src/api/locations.rs indexer_rules): list system + custom
// rules, create glob-based accept/reject rules, delete custom ones.
async function renderRulesTab(body) {
  const rules = await client.locations.indexerRules.list(null, state.lib);
  const rerender = async () => { body.innerHTML = ""; await renderRulesTab(body); };
  for (const r of rules) {
    const row = el("div", "row");
    row.dataset.rule = String(r.id);
    row.appendChild(el("span", "", "📑 " + r.name));
    row.appendChild(el("span", "meta",
      r.default ? t("rule_system") : t("rule_custom")));
    if (!r.default) {
      const del = el("button", "mini", t("delete"));
      del.onclick = async () => {
        const ok = await confirmDialog(t("rule_delete_title"),
          t("rule_delete_body", {name: r.name}),
          {danger: true, actionLabel: t("delete")});
        if (!ok) return;
        try {
          await client.locations.indexerRules.delete(r.id, state.lib);
          rerender();
        } catch (e) { toast(e.message, {kind: "error"}); }
      };
      row.appendChild(del);
    }
    body.appendChild(row);
  }
  const mk = el("div", "row");
  const name = el("input");
  name.placeholder = t("rule_name_placeholder");
  const globs = el("input");
  globs.placeholder = t("rule_globs_placeholder");
  const kind = el("select");
  for (const [value, key] of [["REJECT_FILES_BY_GLOB", "rule_reject"],
                              ["ACCEPT_FILES_BY_GLOB", "rule_accept"]]) {
    const o = el("option", "", t(key));
    o.value = value;
    kind.appendChild(o);
  }
  const add = el("button", "mini", "+");
  add.onclick = async () => {
    const patterns = globs.value.split(",").map(s => s.trim()).filter(Boolean);
    if (!name.value.trim() || !patterns.length) return;
    try {
      await client.locations.indexerRules.create({
        name: name.value.trim(), kind: kind.value, parameters: patterns,
      }, state.lib);
      toast(t("rule_created_toast"), {kind: "ok"});
      rerender();
    } catch (e) { toast(e.message, {kind: "error"}); }
  };
  mk.appendChild(name);
  mk.appendChild(kind);
  mk.appendChild(globs);
  mk.appendChild(add);
  body.appendChild(mk);
  body.appendChild(el("p", "meta", t("rules_hint")));
}

// Key manager (ref:interface/app/$libraryId/KeyManager/ over
// core/src/api/keys.rs): unlock the per-library vault with the master
// password, then add/mount/unmount/delete stored keys.
async function renderKeysTab(body) {
  const st = await client.keys.state(null, state.lib);
  const rerender = async () => { body.innerHTML = ""; await renderKeysTab(body); };

  if (!st.unlocked) {
    body.appendChild(el("p", "meta", t("keys_locked_body")));
    const row = el("div", "row");
    const pw = el("input");
    pw.type = "password";
    pw.id = "km-password";
    pw.placeholder = t("master_password");
    const go = el("button", "", t("unlock"));
    go.onclick = async () => {
      if (!pw.value) return;
      try {
        const res = await client.keys.unlock(
          {password: pw.value}, state.lib);
        toast(t("keys_unlocked_toast", {n: res.automounted}), {kind: "ok"});
        rerender();
      } catch (e) {
        // wrong password is a 400 — the form must say so, not go dead
        toast(e.message, {kind: "error"});
        pw.select();
      }
    };
    pw.onkeydown = (e) => { if (e.key === "Enter") go.onclick(); };
    row.appendChild(pw);
    row.appendChild(go);
    body.appendChild(row);
    return;
  }

  const failToast = (e) => toast(e.message, {kind: "error"});
  const bar = el("div", "row");
  const addBtn = el("button", "", t("key_add"));
  addBtn.onclick = async () => {
    try {
      await client.keys.add({}, state.lib);
      toast(t("key_added_toast"), {kind: "ok"});
      rerender();
    } catch (e) { failToast(e); }
  };
  const lockBtn = el("button", "", t("keys_lock"));
  lockBtn.onclick = async () => {
    await client.keys.lock(null, state.lib);
    rerender();
  };
  bar.appendChild(addBtn);
  bar.appendChild(lockBtn);
  body.appendChild(bar);

  if (!st.keys.length)
    body.appendChild(el("p", "meta", t("keys_empty")));
  for (const k of st.keys) {
    const row = el("div", "row");
    row.dataset.key = k.uuid;
    row.appendChild(el("span", "", "🔑 " + k.uuid.slice(0, 8)));
    row.appendChild(el("span", "meta",
      k.mounted ? t("key_mounted") : t("key_unmounted")));
    const mnt = el("button", "mini",
      k.mounted ? t("key_unmount") : t("key_mount"));
    mnt.onclick = async () => {
      try {
        await (k.mounted
          ? client.keys.unmount(k.uuid, state.lib)
          : client.keys.mount(k.uuid, state.lib));
        rerender();
      } catch (e) { failToast(e); }
    };
    row.appendChild(mnt);
    const del = el("button", "mini", t("delete"));
    del.onclick = async () => {
      const ok = await confirmDialog(t("key_delete_title"),
        t("key_delete_body"), {danger: true, actionLabel: t("delete")});
      if (!ok) return;
      try {
        await client.keys.delete(k.uuid, state.lib);
        rerender();
      } catch (e) { failToast(e); }
    };
    row.appendChild(del);
    body.appendChild(row);
  }
}

export function addLocationModal() {
  openDialog(t("add_location_title"), (m, close) => {
    m.appendChild(el("p", "meta", t("add_location_body")));
    const path = el("input");
    path.placeholder = t("add_location_path");
    m.appendChild(path);
    const name = el("input");
    name.placeholder = t("add_location_name");
    m.appendChild(name);
    const err = el("div", "meta");
    err.style.color = "var(--err)";
    m.appendChild(err);
    const actions = el("div", "modal-actions");
    const cancel = el("button", "", t("cancel"));
    cancel.onclick = close;
    const go = el("button", "primary", t("add_and_index"));
    go.onclick = async () => {
      try {
        await client.locations.create(
          {path: path.value, name: name.value || null}, state.lib);
        close();
        toast(t("location_added"), {kind: "ok"});
        bus.refreshNav?.();
      } catch (e) {
        err.textContent = e.message;
      }
    };
    actions.appendChild(cancel); actions.appendChild(go);
    m.appendChild(actions);
    path.focus();
  });
}

export function createLibraryModal() {
  openDialog(t("new_library_title"), (m, close) => {
    const name = el("input");
    name.placeholder = t("library_name_placeholder");
    m.appendChild(name);
    const actions = el("div", "modal-actions");
    const cancel = el("button", "", t("cancel"));
    cancel.onclick = close;
    const go = el("button", "primary", t("create"));
    go.onclick = async () => {
      if (!name.value) return;
      await client.library.create({name: name.value});
      close();
      bus.reloadLibraries?.();
    };
    actions.appendChild(cancel); actions.appendChild(go);
    m.appendChild(actions);
    name.focus();
  });
}

export function wireSettingsPanel() {
  $("btn-settings").onclick = () => {
    const p = $("settings-panel");
    $("jobs-panel").classList.remove("open");
    $("drop-panel").classList.remove("open");
    p.classList.toggle("open");
    if (p.classList.contains("open")) renderSettings();
  };
}
