// Settings panel: node, library, locations, volumes — a tabbed panel
// built on the ui kit (role parity: ref:interface/app/$libraryId/
// settings screens over ref:packages/ui Tabs/Dialog/Toast).

import client from "/rspc/client.js";
import { $, bus, el, fmtBytes, state } from "/static/js/util.js";
import {
  confirmDialog, openDialog, tabs, toast,
} from "/static/js/ui.js";

let activeTab = "node";

export function renderSettings() {
  const p = $("settings-panel");
  p.innerHTML = "";
  p.appendChild(el("h2", "", "Settings"));
  tabs(p, [
    {id: "node", label: "Node", render: renderNodeTab},
    {id: "library", label: "Library", render: renderLibraryTab},
    {id: "locations", label: "Locations", render: renderLocationsTab},
    {id: "volumes", label: "Volumes", render: renderVolumesTab},
  ], {initial: activeTab, onSelect: (id) => { activeTab = id; }});
}

async function renderNodeTab(body) {
  const ns = await client.nodeState();
  body.appendChild(el("h4", "", "This node"));
  const nameRow = el("div", "row");
  const nameIn = el("input");
  nameIn.value = ns.name || "";
  const nameBtn = el("button", "mini", "rename");
  nameBtn.onclick = async () => {
    await client.nodes.edit({name: nameIn.value});
    toast("node renamed", {kind: "ok"});
    bus.refreshHeader?.();
  };
  nameRow.appendChild(nameIn);
  nameRow.appendChild(nameBtn);
  body.appendChild(nameRow);

  const bgRow = el("div", "row");
  bgRow.appendChild(el("span", "", "background thumbnailing %"));
  const bgIn = el("input");
  bgIn.type = "number";
  bgIn.min = 1; bgIn.max = 100;
  bgIn.style.width = "70px";
  bgIn.value = ns.thumbnailer_background_percentage ?? 50;
  bgIn.onchange = () => client.nodes.updateThumbnailerPreferences(
    {background_processing_percentage: +bgIn.value});
  bgRow.appendChild(bgIn);
  body.appendChild(bgRow);

  body.appendChild(el("h4", "", "Features"));
  for (const feat of ["filesOverP2P", "cloudSync"]) {
    const row = el("div", "row");
    row.appendChild(el("span", "", feat));
    const cb = el("input");
    cb.type = "checkbox";
    cb.checked = (ns.features || []).includes(feat);
    cb.onchange = () =>
      client.toggleFeatureFlag({feature: feat, enabled: cb.checked});
    row.appendChild(cb);
    body.appendChild(row);
  }
}

async function renderLibraryTab(body) {
  const libs = await client.library.list();
  const cur = libs.find(l => l.uuid === state.lib);
  if (!cur) return;
  const rn = el("div", "row");
  const libIn = el("input");
  libIn.value = cur.config.name;
  const rb = el("button", "mini", "rename");
  rb.onclick = async () => {
    await client.library.edit({id: state.lib, name: libIn.value});
    toast("library renamed", {kind: "ok"});
    bus.reloadLibraries?.();
  };
  rn.appendChild(libIn);
  rn.appendChild(rb);
  body.appendChild(rn);

  const act = el("div", "row");
  const newBtn = el("button", "mini", "+ new library");
  newBtn.onclick = () => createLibraryModal();
  const delBtn = el("button", "mini danger", "delete library");
  delBtn.onclick = async () => {
    const ok = await confirmDialog("Delete library?",
      `“${cur.config.name}” and its index will be removed (files on `
      + "disk are untouched).", {danger: true, actionLabel: "delete"});
    if (!ok) return;
    await client.library.delete({id: state.lib});
    bus.reloadLibraries?.();
  };
  act.appendChild(newBtn);
  act.appendChild(delBtn);
  body.appendChild(act);
}

async function renderLocationsTab(body) {
  const locs = await client.locations.list(null, state.lib);
  for (const n of locs.nodes) {
    const row = el("div", "loc-row");
    row.appendChild(el("b", "", n.name || n.path));
    row.appendChild(el("div", "meta", n.path));
    const act = el("div", "actions");
    const rescan = el("button", "mini", "rescan");
    rescan.setAttribute("data-tip", "re-walk this location and re-identify changes");
    rescan.onclick = async () => {
      await client.locations.fullRescan(
        {location_id: n.id, reidentify_objects: false}, state.lib);
      rescan.textContent = "rescanning…";
      toast("rescan started", {kind: "ok"});
    };
    const del = el("button", "mini danger", "remove");
    del.setAttribute("data-tip", "stop indexing; files on disk are untouched");
    del.onclick = async () => {
      await client.locations.delete(n.id, state.lib);
      renderSettings();
      bus.refreshNav?.();
    };
    act.appendChild(rescan);
    act.appendChild(del);
    row.appendChild(act);
    body.appendChild(row);
  }
  const addBtn = el("button", "", "+ add location");
  addBtn.onclick = () => addLocationModal();
  body.appendChild(addBtn);
}

async function renderVolumesTab(body) {
  const vols = await client.volumes.list();
  for (const v of vols) {
    const row = el("div", "row");
    row.appendChild(el("span", "", `${v.name || v.mount_point}`));
    row.appendChild(el("span", "meta",
      `${fmtBytes(v.available_capacity)} free of ${fmtBytes(v.total_capacity)}`));
    body.appendChild(row);
  }
}

export function addLocationModal() {
  openDialog("Add location", (m, close) => {
    m.appendChild(el("p", "meta",
      "absolute path of a directory to index and watch"));
    const path = el("input");
    path.placeholder = "/path/to/files";
    m.appendChild(path);
    const name = el("input");
    name.placeholder = "display name (optional)";
    m.appendChild(name);
    const err = el("div", "meta");
    err.style.color = "var(--err)";
    m.appendChild(err);
    const actions = el("div", "modal-actions");
    const cancel = el("button", "", "cancel");
    cancel.onclick = close;
    const go = el("button", "primary", "add & index");
    go.onclick = async () => {
      try {
        await client.locations.create(
          {path: path.value, name: name.value || null}, state.lib);
        close();
        toast("location added — indexing", {kind: "ok"});
        bus.refreshNav?.();
      } catch (e) {
        err.textContent = e.message;
      }
    };
    actions.appendChild(cancel); actions.appendChild(go);
    m.appendChild(actions);
    path.focus();
  });
}

export function createLibraryModal() {
  openDialog("New library", (m, close) => {
    const name = el("input");
    name.placeholder = "library name";
    m.appendChild(name);
    const actions = el("div", "modal-actions");
    const cancel = el("button", "", "cancel");
    cancel.onclick = close;
    const go = el("button", "primary", "create");
    go.onclick = async () => {
      if (!name.value) return;
      await client.library.create({name: name.value});
      close();
      bus.reloadLibraries?.();
    };
    actions.appendChild(cancel); actions.appendChild(go);
    m.appendChild(actions);
    name.focus();
  });
}

export function wireSettingsPanel() {
  $("btn-settings").onclick = () => {
    const p = $("settings-panel");
    $("jobs-panel").classList.remove("open");
    $("drop-panel").classList.remove("open");
    p.classList.toggle("open");
    if (p.classList.contains("open")) renderSettings();
  };
}
