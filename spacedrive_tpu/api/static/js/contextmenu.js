// Right-click context menu: rename, delete, copy/cut/paste, validate
// (role parity: ref:interface Explorer ContextMenu over the files.*
// jobs — core/src/object/fs). Menu/dialog/toast primitives come from
// the ui kit (ui.js), matching ref:packages/ui/src/ContextMenu.tsx.

import client from "/rspc/client.js";
import { $, bus, el, fullPath, state } from "/static/js/util.js";
import {
  confirmDialog, initMenus, openMenu, promptDialog, toast,
} from "/static/js/ui.js";
import { t } from "/static/js/i18n.js";

let clipboard = null;  // {op, ids, location_id, lib} — lib-scoped:
// file_path ids are per-library, so a stale clipboard must never
// paste across a library switch

function pasteItem() {
  if (clipboard && clipboard.lib !== state.lib) clipboard = null;
  if (!clipboard || !state.loc || state.mode !== "browse") return null;
  return {
    label: t("menu_paste"),
    onClick: async () => {
      const arg = {
        source_location_id: clipboard.location_id,
        target_location_id: state.loc,
        sources_file_path_ids: clipboard.ids,
        target_relative_path: state.path,
      };
      await (clipboard.op === "cut"
        ? client.files.cutFiles(arg, state.lib)
        : client.files.copyFiles(arg, state.lib));
      if (clipboard.op === "cut") clipboard = null;
    },
  };
}

export function showMenu(x, y, n) {
  const refresh = () => bus.loadContent(true);
  // when the clicked item is part of a multi-selection, batch ops
  // cover the whole selection (same location only — the jobs are
  // per-location like the reference's)
  const multi = state.selectedIds.has(n.id) && state.selectedIds.size > 1;
  // file jobs are per-location; spacedrop is path-based and takes the
  // WHOLE selection regardless of location
  const chosenAll = multi
    ? state.nodes.filter(x => state.selectedIds.has(x.id)) : [n];
  const chosen = chosenAll.filter(x => x.location_id === n.location_id);
  const many = chosen.length > 1;
  const label = (verb) => many ? t("menu_n_items", {verb, n: chosen.length}) : verb;
  const displayName = n.name + (n.extension ? "." + n.extension : "");

  openMenu(x, y, [
    {
      label: t("menu_rename"),
      onClick: async () => {
        const name = await promptDialog(t("rename_title"), {
          value: displayName, actionLabel: t("rename"),
        });
        if (!name) return;
        await client.files.renameFile({id: n.id, new_name: name}, state.lib);
        refresh();
      },
    },
    {
      label: label(t("menu_copy")),
      onClick: () => {
        clipboard = {op: "copy", ids: chosen.map(x => x.id),
                     location_id: n.location_id, lib: state.lib};
        toast(t("copied_items", {n: chosen.length}));
      },
    },
    {
      label: label(t("menu_cut")),
      onClick: () => {
        clipboard = {op: "cut", ids: chosen.map(x => x.id),
                     location_id: n.location_id, lib: state.lib};
        toast(t("cut_items", {n: chosen.length}));
      },
    },
    pasteItem(),
    {separator: true},
    // scoped to the file's folder — a bare location_id would checksum
    // the whole location from a per-file menu item
    n.is_dir ? null : {
      label: t("menu_validate"),
      onClick: () => client.files.validate({
        location_id: n.location_id,
        sub_path: n.materialized_path || "/",
      }, state.lib),
    },
    {
      label: chosenAll.length > 1
        ? `📡 Spacedrop ${chosenAll.length} items` : "📡 Spacedrop",
      onClick: () => bus.openDropPanel(chosenAll.map(fullPath)),
    },
    {separator: true},
    {
      label: label(t("menu_delete")),
      danger: true,
      onClick: async () => {
        const what = many ? t("n_items", {n: chosen.length}) : `“${displayName}”`;
        const ok = await confirmDialog(t("delete_confirm_title"),
          t("delete_confirm_body", {what}),
          {danger: true, actionLabel: t("delete")});
        if (!ok) return;
        await client.files.deleteFiles(
          {location_id: n.location_id,
           file_path_ids: chosen.map(x => x.id)}, state.lib);
      },
    },
  ]);
}

/** Menu for empty space: paste into the current folder. */
export function showBackgroundMenu(x, y) {
  const paste = pasteItem();
  if (paste) openMenu(x, y, [paste]);
}

export function wireContextMenu() {
  initMenus();  // click-outside + capture-phase Escape dismissal
  $("content").addEventListener("contextmenu", (e) => {
    if (e.target.closest(".card, tr[data-fp]")) return;  // item menus
    e.preventDefault();
    showBackgroundMenu(e.clientX, e.clientY);
  });
}
