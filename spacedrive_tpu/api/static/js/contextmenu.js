// Right-click context menu: rename, delete, copy/cut/paste, validate
// (role parity: ref:interface Explorer ContextMenu over the files.*
// jobs — core/src/object/fs).

import client from "/rspc/client.js";
import { $, bus, el, fullPath, modal, state } from "/static/js/util.js";

let clipboard = null;  // {op, ids, location_id, lib} — lib-scoped:
// file_path ids are per-library, so a stale clipboard must never
// paste across a library switch
let menuEl = null;

function closeMenu() {
  menuEl?.remove();
  menuEl = null;
}

function item(label, onclick, danger = false) {
  const it = el("div", "ctx-item" + (danger ? " danger" : ""), label);
  it.onclick = async () => {
    closeMenu();
    try {
      await onclick();
    } catch (e) {
      $("events").textContent = "✗ " + e.message;
    }
  };
  return it;
}

export function showMenu(x, y, n) {
  closeMenu();
  menuEl = el("div", "ctxmenu");
  const refresh = () => bus.loadContent(true);
  // when the clicked item is part of a multi-selection, batch ops
  // cover the whole selection (same location only — the jobs are
  // per-location like the reference's)
  const multi = state.selectedIds.has(n.id) && state.selectedIds.size > 1;
  // file jobs are per-location; spacedrop is path-based and takes the
  // WHOLE selection regardless of location
  const chosenAll = multi
    ? state.nodes.filter(x => state.selectedIds.has(x.id)) : [n];
  const chosen = chosenAll.filter(x => x.location_id === n.location_id);
  const many = chosen.length > 1;
  const label = (verb) => many ? `${verb} ${chosen.length} items` : verb;

  menuEl.appendChild(item("Rename…", async () => {
    const name = prompt(
      "new name", n.name + (n.extension ? "." + n.extension : "")
    );
    if (!name) return;
    await client.files.renameFile({id: n.id, new_name: name}, state.lib);
    refresh();
  }));

  menuEl.appendChild(item(label("Copy"), () => {
    clipboard = {op: "copy", ids: chosen.map(x => x.id),
                 location_id: n.location_id, lib: state.lib};
    $("events").textContent = `copied ${chosen.length} item(s)`;
  }));
  menuEl.appendChild(item(label("Cut"), () => {
    clipboard = {op: "cut", ids: chosen.map(x => x.id),
                 location_id: n.location_id, lib: state.lib};
    $("events").textContent = `cut ${chosen.length} item(s)`;
  }));
  if (clipboard && clipboard.lib !== state.lib) clipboard = null;
  if (clipboard && state.loc && state.mode === "browse") {
    menuEl.appendChild(item("Paste into this folder", async () => {
      const arg = {
        source_location_id: clipboard.location_id,
        target_location_id: state.loc,
        sources_file_path_ids: clipboard.ids,
        target_relative_path: state.path,
      };
      await (clipboard.op === "cut"
        ? client.files.cutFiles(arg, state.lib)
        : client.files.copyFiles(arg, state.lib));
      if (clipboard.op === "cut") clipboard = null;
    }));
  }

  if (!n.is_dir) {
    // scoped to the file's folder — a bare location_id would checksum
    // the whole location from a per-file menu item
    menuEl.appendChild(item("Validate folder checksums", () =>
      client.files.validate({
        location_id: n.location_id,
        sub_path: n.materialized_path || "/",
      }, state.lib)));
  }
  menuEl.appendChild(item(
    chosenAll.length > 1 ? `📡 Spacedrop ${chosenAll.length} items`
                         : "📡 Spacedrop",
    () => bus.openDropPanel(chosenAll.map(fullPath))));

  menuEl.appendChild(item(label("Delete"), () => modal("Delete?", (m, close) => {
    m.appendChild(el("p", "meta",
      (many ? `${chosen.length} items` :
       `“${n.name}${n.extension ? "." + n.extension : ""}”`)
      + " will be moved out of the library and removed from disk."));
    const actions = el("div", "modal-actions");
    const cancel = el("button", "", "cancel");
    cancel.onclick = close;
    const go = el("button", "danger", "delete");
    go.onclick = async () => {
      close();
      try {
        await client.files.deleteFiles(
          {location_id: n.location_id,
           file_path_ids: chosen.map(x => x.id)}, state.lib);
      } catch (e) {
        $("events").textContent = "✗ delete: " + e.message;
      }
    };
    actions.appendChild(cancel);
    actions.appendChild(go);
    m.appendChild(actions);
  }), true));

  menuEl.style.left = Math.min(x, innerWidth - 190) + "px";
  menuEl.style.top = Math.min(y, innerHeight - 240) + "px";
  document.body.appendChild(menuEl);
}

/** Menu for empty space: paste into the current folder. */
export function showBackgroundMenu(x, y) {
  if (clipboard && clipboard.lib !== state.lib) clipboard = null;
  if (!clipboard || !state.loc || state.mode !== "browse") return;
  closeMenu();
  menuEl = el("div", "ctxmenu");
  menuEl.appendChild(item("Paste into this folder", async () => {
    const arg = {
      source_location_id: clipboard.location_id,
      target_location_id: state.loc,
      sources_file_path_ids: clipboard.ids,
      target_relative_path: state.path,
    };
    await (clipboard.op === "cut"
      ? client.files.cutFiles(arg, state.lib)
      : client.files.copyFiles(arg, state.lib));
    if (clipboard.op === "cut") clipboard = null;
  }));
  menuEl.style.left = Math.min(x, innerWidth - 190) + "px";
  menuEl.style.top = Math.min(y, innerHeight - 240) + "px";
  document.body.appendChild(menuEl);
}

export function wireContextMenu() {
  document.addEventListener("click", closeMenu);
  // capture phase: Escape dismisses ONLY the menu when one is open —
  // it must not fall through to the global handler (inspector/panels/
  // pending-spacedrop rejection)
  document.addEventListener("keydown", (e) => {
    if (e.key === "Escape" && menuEl) {
      e.stopPropagation();
      closeMenu();
    }
  }, true);
  $("content").addEventListener("contextmenu", (e) => {
    if (e.target.closest(".card, tr[data-fp]")) return;  // item menus
    e.preventDefault();
    showBackgroundMenu(e.clientX, e.clientY);
  });
}
