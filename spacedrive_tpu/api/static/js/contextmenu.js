// Right-click context menu: rename, delete, copy/cut/paste, validate
// (role parity: ref:interface Explorer ContextMenu over the files.*
// jobs — core/src/object/fs). Menu/dialog/toast primitives come from
// the ui kit (ui.js), matching ref:packages/ui/src/ContextMenu.tsx.

import client from "/rspc/client.js";
import { $, bus, el, fullPath, state } from "/static/js/util.js";
import {
  confirmDialog, initMenus, openDialog, openMenu, promptDialog, toast,
} from "/static/js/ui.js";
import { t } from "/static/js/i18n.js";

let clipboard = null;  // {op, ids, location_id, lib} — lib-scoped:
// file_path ids are per-library, so a stale clipboard must never
// paste across a library switch

function pasteItem() {
  if (clipboard && clipboard.lib !== state.lib) clipboard = null;
  if (!clipboard || !state.loc || state.mode !== "browse") return null;
  return {
    label: t("menu_paste"),
    onClick: async () => {
      const arg = {
        source_location_id: clipboard.location_id,
        target_location_id: state.loc,
        sources_file_path_ids: clipboard.ids,
        target_relative_path: state.path,
      };
      await (clipboard.op === "cut"
        ? client.files.cutFiles(arg, state.lib)
        : client.files.copyFiles(arg, state.lib));
      if (clipboard.op === "cut") clipboard = null;
    },
  };
}

// Tag assignment from the item menu (ref:interface Explorer
// ContextMenu AssignTagMenuItems): checkbox per tag, immediate
// assign/unassign over tags.assign, plus inline new-tag creation.
async function tagsDialog(chosen) {
  const objIds = [...new Set(chosen.map(x => x.object_id).filter(Boolean))];
  if (!objIds.length) { toast(t("tags_need_identify"), {kind: "info"}); return; }
  // per-object tag sets: a multi-selection renders checked only when
  // EVERY object carries the tag, indeterminate when some do —
  // toggling from indeterminate assigns to all (never blind-unassigns
  // from objects whose state the checkbox didn't show)
  const perObject = await Promise.all(objIds.map(async (oid) =>
    new Set((await client.tags.getForObject(oid, state.lib))
      .nodes.map(tg => tg.id))));
  const countFor = (tagId) =>
    perObject.reduce((s, set) => s + (set.has(tagId) ? 1 : 0), 0);
  openDialog(t("assign_tags_title"), (m, close) => {
    const list = el("div");
    const row = (tag) => {
      const lab = el("label", "row");
      const cb = el("input");
      cb.type = "checkbox";
      const cnt = countFor(tag.id);
      cb.checked = cnt === objIds.length && cnt > 0;
      cb.indeterminate = cnt > 0 && cnt < objIds.length;
      cb.onchange = async () => {
        const assign = cb.checked || cb.indeterminate;
        cb.indeterminate = false;
        cb.checked = assign;
        await client.tags.assign({tag_id: tag.id, object_ids: objIds,
                                  unassign: !assign}, state.lib);
        for (const set of perObject)
          assign ? set.add(tag.id) : set.delete(tag.id);
        toast(assign ? t("tag_assigned", {name: tag.name})
                     : t("tag_unassigned", {name: tag.name}),
              {kind: "ok"});
      };
      lab.appendChild(cb);
      lab.appendChild(el("span", "", " 🏷️ " + (tag.name || "?")));
      return lab;
    };
    for (const tag of state.allTags) list.appendChild(row(tag));
    if (!state.allTags.length)
      list.appendChild(el("p", "meta", t("no_tags_yet")));
    m.appendChild(list);
    const mk = el("div", "row");
    const name = el("input");
    name.placeholder = t("new_tag_placeholder");
    const add = el("button", "mini", "+");
    add.onclick = async () => {
      if (!name.value.trim()) return;
      const createdId = await client.tags.create(
        {name: name.value.trim()}, state.lib);
      const created = {id: createdId, name: name.value.trim()};
      await client.tags.assign(
        {tag_id: created.id, object_ids: objIds}, state.lib);
      state.allTags.push(created);
      list.appendChild(row(created));
      const cb = list.lastChild.querySelector("input");
      cb.checked = true;
      name.value = "";
      bus.refreshNav();
    };
    name.onkeydown = (e) => { if (e.key === "Enter") add.onclick(); };
    mk.appendChild(name);
    mk.appendChild(add);
    m.appendChild(mk);
  });
}

// Batch rename (ref:interface Explorer RenameDialog multi form):
// pattern with {n} (counter) and {name} (old stem); extensions are
// preserved; a live preview shows the first few results before apply.
function batchRenameDialog(chosen, refresh) {
  openDialog(t("batch_rename_title", {n: chosen.length}), (m, close) => {
    m.appendChild(el("p", "meta", t("batch_rename_body")));
    const pat = el("input");
    pat.value = "{name}";
    pat.style.width = "100%";
    const preview = el("p", "meta");
    const names = () => chosen.map((x, i) =>
      pat.value.replaceAll("{n}", String(i + 1))
               .replaceAll("{name}", x.name)
      + (x.extension ? "." + x.extension : ""));
    const update = () => {
      preview.textContent =
        names().slice(0, 3).join(" · ") + (chosen.length > 3 ? " …" : "");
    };
    pat.oninput = update;
    update();
    const go = el("button", "", t("rename"));
    go.onclick = async () => {
      const out = names();
      if (new Set(out).size !== out.length) {
        toast(t("batch_rename_collision"), {kind: "error"});
        return;
      }
      // sequential with an honest partial-failure report: a target
      // that already exists (400) must not abort silently mid-batch
      let done = 0;
      let firstErr = null;
      for (let i = 0; i < chosen.length; i++) {
        try {
          await client.files.renameFile(
            {id: chosen[i].id, new_name: out[i]}, state.lib);
          done++;
        } catch (e) {
          firstErr = firstErr || e;
        }
      }
      if (firstErr) {
        toast(t("batch_rename_partial",
                {done, n: chosen.length, error: firstErr.message}),
              {kind: "error"});
      } else {
        toast(t("batch_renamed_toast", {n: chosen.length}), {kind: "ok"});
      }
      close();
      refresh();
    };
    m.appendChild(pat);
    m.appendChild(preview);
    m.appendChild(go);
  });
}

// Context menu for NON-INDEXED rows (ref:core/src/api/ephemeral_files.rs
// over the ephemeral.tsx menu): rename/delete on raw paths — the
// db-backed affordances (tags, copy jobs, validate) don't apply.
export function showEphemeralMenu(x, y, n) {
  const refresh = () => bus.loadContent(true);
  const displayName = n.name + (n.extension ? "." + n.extension : "");
  // delete covers the whole selection (deleteFiles takes a batch);
  // rename is single-item by nature
  const chosen = state.selectedIds.has(n.id) && state.selectedIds.size > 1
    ? state.nodes.filter(x => state.selectedIds.has(x.id)) : [n];
  const many = chosen.length > 1;
  openMenu(x, y, [
    {
      label: t("menu_rename"),
      onClick: async () => {
        const name = await promptDialog(t("rename_title"), {
          value: displayName, actionLabel: t("rename"),
        });
        if (!name) return;
        try {
          await client.ephemeralFiles.renameFile(
            {path: n.path, new_name: name});
          refresh();
        } catch (e) { toast(e.message, {kind: "error"}); }
      },
    },
    {separator: true},
    {
      label: many
        ? t("menu_n_items", {verb: t("menu_delete"), n: chosen.length})
        : t("menu_delete"),
      danger: true,
      onClick: async () => {
        const what = many ? t("n_items", {n: chosen.length})
                          : `“${displayName}”`;
        const ok = await confirmDialog(t("delete_confirm_title"),
          t("eph_delete_body", {what}),
          {danger: true, actionLabel: t("delete")});
        if (!ok) return;
        try {
          const res = await client.ephemeralFiles.deleteFiles(
            {paths: chosen.map(x => x.path)});
          if (res.errors?.length)
            toast(res.errors[0], {kind: "error"});
          refresh();
        } catch (e) { toast(e.message, {kind: "error"}); }
      },
    },
  ]);
}

/** Empty-space menu in ephemeral mode: new folder in the current dir. */
export function showEphemeralBackgroundMenu(x, y) {
  openMenu(x, y, [
    {
      label: t("menu_new_folder"),
      onClick: async () => {
        const name = await promptDialog(t("new_folder_title"), {
          value: t("new_folder_default"), actionLabel: t("create"),
        });
        if (!name) return;
        try {
          await client.ephemeralFiles.createFolder(
            {path: state.ephPath, name});
          bus.loadContent(true);
        } catch (e) { toast(e.message, {kind: "error"}); }
      },
    },
  ]);
}

export function showMenu(x, y, n) {
  const refresh = () => bus.loadContent(true);
  // when the clicked item is part of a multi-selection, batch ops
  // cover the whole selection (same location only — the jobs are
  // per-location like the reference's)
  const multi = state.selectedIds.has(n.id) && state.selectedIds.size > 1;
  // file jobs are per-location; spacedrop is path-based and takes the
  // WHOLE selection regardless of location
  const chosenAll = multi
    ? state.nodes.filter(x => state.selectedIds.has(x.id)) : [n];
  const chosen = chosenAll.filter(x => x.location_id === n.location_id);
  const many = chosen.length > 1;
  const label = (verb) => many ? t("menu_n_items", {verb, n: chosen.length}) : verb;
  const displayName = n.name + (n.extension ? "." + n.extension : "");

  openMenu(x, y, [
    many ? {
      label: t("menu_batch_rename", {n: chosen.length}),
      onClick: () => batchRenameDialog(chosen, refresh),
    } : {
      label: t("menu_rename"),
      onClick: async () => {
        const name = await promptDialog(t("rename_title"), {
          value: displayName, actionLabel: t("rename"),
        });
        if (!name) return;
        await client.files.renameFile({id: n.id, new_name: name}, state.lib);
        refresh();
      },
    },
    {
      label: label(t("menu_tags")),
      onClick: () => tagsDialog(chosen),
    },
    {
      label: label(t("menu_copy")),
      onClick: () => {
        clipboard = {op: "copy", ids: chosen.map(x => x.id),
                     location_id: n.location_id, lib: state.lib};
        toast(t("copied_items", {n: chosen.length}));
      },
    },
    {
      label: label(t("menu_cut")),
      onClick: () => {
        clipboard = {op: "cut", ids: chosen.map(x => x.id),
                     location_id: n.location_id, lib: state.lib};
        toast(t("cut_items", {n: chosen.length}));
      },
    },
    pasteItem(),
    {separator: true},
    // scoped to the file's folder — a bare location_id would checksum
    // the whole location from a per-file menu item
    n.is_dir ? null : {
      label: t("menu_validate"),
      onClick: () => client.files.validate({
        location_id: n.location_id,
        sub_path: n.materialized_path || "/",
      }, state.lib),
    },
    {
      label: chosenAll.length > 1
        ? `📡 Spacedrop ${chosenAll.length} items` : "📡 Spacedrop",
      onClick: () => bus.openDropPanel(chosenAll.map(fullPath)),
    },
    {separator: true},
    {
      label: label(t("menu_delete")),
      danger: true,
      onClick: async () => {
        const what = many ? t("n_items", {n: chosen.length}) : `“${displayName}”`;
        const ok = await confirmDialog(t("delete_confirm_title"),
          t("delete_confirm_body", {what}),
          {danger: true, actionLabel: t("delete")});
        if (!ok) return;
        await client.files.deleteFiles(
          {location_id: n.location_id,
           file_path_ids: chosen.map(x => x.id)}, state.lib);
      },
    },
  ]);
}

/** Menu for empty space: paste into the current folder. */
export function showBackgroundMenu(x, y) {
  const paste = pasteItem();
  if (paste) openMenu(x, y, [paste]);
}

export function wireContextMenu() {
  initMenus();  // click-outside + capture-phase Escape dismissal
  $("content").addEventListener("contextmenu", (e) => {
    if (e.target.closest(".card, tr[data-fp]")) return;  // item menus
    e.preventDefault();
    if (state.mode === "ephemeral")
      showEphemeralBackgroundMenu(e.clientX, e.clientY);
    else showBackgroundMenu(e.clientX, e.clientY);
  });
}
