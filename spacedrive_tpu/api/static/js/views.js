// Content area: grid/list/media views, breadcrumbs + directory
// drill-down, pagination, duplicates groups
// (role parity: ref:interface/app/$libraryId/Explorer views).

import client from "/rspc/client.js";
import { $, KIND_ICON, bus, el, fmtBytes, state, thumbUrl } from "/static/js/util.js";
import { dirTarget, draggable, droppable, guardTarget } from "/static/js/dnd.js";
import { t } from "/static/js/i18n.js";
import { loadOverview } from "/static/js/overview.js";

export function setView(view) {
  state.view = view;
  localStorage.setItem("sd-view", view);
  document.querySelectorAll("#viewsw button").forEach(b =>
    b.classList.toggle("active", b.dataset.view === view));
  loadContent(true);
}

let loadSeq = 0;  // drop stale responses when loads overlap

export async function loadContent(reset) {
  if (state.mode === "duplicates") return loadDuplicates();
  if (state.mode === "ephemeral") return loadEphemeral();
  if (state.mode === "network") {
    ++loadSeq;
    state.nodes = [];
    state.cursor = null;
    renderCrumbs();
    const { loadNetwork } = await import("/static/js/network.js");
    return loadNetwork();
  }
  if (state.mode === "overview") {
    // invalidate any in-flight listing and drop its rows: a stale
    // response must not paint over the landing page, and keyboard
    // selection must not walk invisible nodes
    ++loadSeq;
    state.nodes = [];
    state.cursor = null;
    renderCrumbs();
    return loadOverview();
  }
  if (reset) { state.cursor = null; state.nodes = []; }
  const seq = ++loadSeq;
  const before = state.nodes.length;
  const filter = {};
  const extra = {};
  if (state.mode === "search") {
    if (state.search) filter.search = state.search;
    if (state.loc) filter.locationId = state.loc;
  } else if (state.mode === "favorites") {
    filter.favorite = true;           // ref:favorites.tsx fixed filter
  } else if (state.mode === "recents") {
    filter.accessed = true;           // ref:recents.tsx dateAccessed filter
    extra.orderBy = "dateAccessed";
    extra.orderDir = "desc";
  } else if (state.mode === "kind") {
    filter.kinds = [state.kindFilter];
  } else if (state.mode === "label") {
    filter.labels = [state.labelFilter];  // ref:labels.tsx route
  } else {
    if (state.loc) {
      filter.locationId = state.loc;
      filter.path = state.path;     // non-recursive directory listing
    }
  }
  if (state.tag) filter.tags = [state.tag];
  if (state.view === "media" && state.mode !== "kind") filter.kinds = [5, 7];
  if (!extra.orderBy) {  // recents pins its own dateAccessed ordering
    extra.orderBy = state.orderBy;
    extra.orderDir = state.orderDir;
  }
  const page = await client.search.paths(
    {filter, take: 60, cursor: state.cursor, ...extra}, state.lib);
  if (seq !== loadSeq) return;  // a newer load superseded this one
  state.cursor = page.cursor;
  state.nodes = state.nodes.concat(page.nodes);
  renderCrumbs();
  if (before === 0) render();
  else appendFrom(before);  // keep scroll position on "load more"
}

// ---------- ephemeral (non-indexed) browse ----------
// (ref:interface/app/$libraryId/ephemeral.tsx — browse any path on
// this device without indexing; thumbs are generated on the fly into
// the ephemeral namespace by the backend walker)
async function loadEphemeral() {
  const seq = ++loadSeq;
  state.cursor = null;
  renderCrumbs();
  let page;
  try {
    page = await client.ephemeralFiles.list({ path: state.ephPath });
  } catch (e) {
    if (seq !== loadSeq) return;
    $("content").innerHTML = "";
    $("content").appendChild(el("div", "meta", t("ephemeral_error", {error: e.message})));
    return;
  }
  if (seq !== loadSeq) return;
  state.nodes = page.entries.map((en, i) => ({
    ...en,
    id: "eph:" + en.path,
    object_kind: en.kind,
    date_created: new Date(en.date_created * 1000).toISOString(),
    date_modified: new Date(en.date_modified * 1000).toISOString(),
    materialized_path: null,
    ephemeral: true,
  }));
  render();
}

export function renderCrumbs() {
  const c = $("crumbs");
  c.innerHTML = "";
  const seg = (label, onclick) => {
    const s = el("span", "seg", label);
    s.onclick = onclick;
    c.appendChild(s);
    return s;
  };
  if (state.mode === "ephemeral") {
    // device-absolute crumb trail: every segment is navigable
    const root = state.ephRoot || "/";
    seg("💻 " + (state.ephRootName || root), () => {
      state.ephPath = root; clearSelection(); loadContent(true);
    });
    const rel = state.ephPath.startsWith(root)
      ? state.ephPath.slice(root.length) : state.ephPath;
    let acc = root.endsWith("/") ? root : root + "/";
    for (const p of rel.split("/").filter(Boolean)) {
      c.appendChild(el("span", "sep", "›"));
      acc += p + "/";
      const target = acc.slice(0, -1);
      seg(p, () => { state.ephPath = target; clearSelection();
        loadContent(true); });
    }
    return;
  }
  if (state.mode === "network") {
    c.appendChild(el("span", "", t("network_crumb")));
    return;
  }
  if (state.mode === "search") {
    c.appendChild(el("span", "", t("search_crumb", {query: state.search})));
    const back = el("button", "mini", t("clear"));
    back.style.marginLeft = "8px";
    back.onclick = () => { state.mode = "browse"; state.search = "";
      $("search").value = ""; clearSelection(); loadContent(true); };
    c.appendChild(back);
    return;
  }
  if (state.mode === "duplicates") {
    c.appendChild(el("span", "", t("duplicate_groups")));
    return;
  }
  if (state.mode === "overview") {
    c.appendChild(el("span", "", t("library_overview")));
    return;
  }
  if (state.mode === "favorites") {
    c.appendChild(el("span", "", t("favorites_crumb")));
    return;
  }
  if (state.mode === "recents") {
    c.appendChild(el("span", "", t("recents_crumb")));
    return;
  }
  if (state.mode === "kind") {
    c.appendChild(el("span", "", t("kind_crumb", {kind: state.kindName || state.kindFilter})));
    const back = el("button", "mini", t("back_to_overview"));
    back.style.marginLeft = "8px";
    back.onclick = () => { state.mode = "overview"; clearSelection();
      loadContent(true); };
    c.appendChild(back);
    return;
  }
  if (state.mode === "label") {
    c.appendChild(el("span", "", t("label_crumb", {name: state.labelName || ""})));
    return;
  }
  if (state.tag) {
    c.appendChild(el("span", "", t("tagged_files")));
    return;
  }
  if (!state.loc) {
    c.appendChild(el("span", "", t("select_location")));
    return;
  }
  const crumbDrop = (s, path) =>
    droppable(s, () => guardTarget(state.loc, path));
  crumbDrop(
    seg("📂 " + (state.locNames[state.loc] || "location"), () => {
      state.path = "/"; clearSelection(); loadContent(true);
    }), "/");
  const parts = state.path.split("/").filter(Boolean);
  let acc = "/";
  for (const p of parts) {
    c.appendChild(el("span", "sep", "›"));
    acc += p + "/";
    const target = acc;
    crumbDrop(
      seg(p, () => { state.path = target; clearSelection(); loadContent(true); }),
      target);
  }
}

export function openDir(n) {
  if (n.ephemeral) {
    state.ephPath = n.path;
    clearSelection();
    loadContent(true);
    return;
  }
  state.path = (n.materialized_path || "/") + n.name + "/";
  state.selected = null;
  state.selectedIds = new Set();
  loadContent(true);
}

/** Navigation context changed (folder/search/tag): drop the selection
 *  so stale per-folder ids can't feed batch operations. */
export function clearSelection() {
  state.selected = null;
  state.selectedIds = new Set();
}

export function upDir() {
  if (state.mode === "ephemeral") {
    const root = state.ephRoot || "/";
    if (state.ephPath === root) return;
    const parent = state.ephPath.replace(/\/[^/]+$/, "") || root;
    state.ephPath = parent.length < root.length ? root : parent;
    clearSelection();
    loadContent(true);
    return;
  }
  if (state.mode !== "browse" || !state.loc || state.path === "/") return;
  clearSelection();
  const parts = state.path.split("/").filter(Boolean);
  parts.pop();
  state.path = "/" + parts.map(p => p + "/").join("");
  if (state.path === "") state.path = "/";
  loadContent(true);
}

function render() {
  const c = $("content");
  c.className = state.view;
  c.innerHTML = "";
  appendFrom(0);
}

function appendFrom(start) {
  const c = $("content");
  $("more")?.remove();
  let listBody = c.querySelector("table.listing");
  if (state.view === "list") {
    if (!listBody) {
      listBody = el("table", "listing");
      const head = el("tr");
      for (const h of ["name", "kind", "size", "modified", "path"])
        head.appendChild(el("th", "", t(h)));
      listBody.appendChild(head);
      c.appendChild(listBody);
    }
    renderListRows(listBody, state.nodes.slice(start));
  } else {
    // kind mode already filters server-side; the media-view client
    // filter would blank non-media kinds
    renderCards(c, state.view === "media" && state.mode !== "kind",
                state.nodes.slice(start));
  }
  if (state.cursor) {
    const btn = el("button", "", t("load_more"));
    btn.id = "more";
    btn.onclick = () => loadContent(false);
    c.appendChild(btn);
  }
}

function activate(n) {
  if (n.is_dir) openDir(n);
  else bus.select(n);
}

function renderCards(c, mediaOnly, nodes) {
  for (const n of nodes) {
    if (mediaOnly && ![5,7].includes(n.object_kind)) continue;
    const card = el("div", "card");
    card.dataset.fp = String(n.id);
    if (state.selectedIds.has(n.id))
      card.classList.add("selected");
    const thumb = el("div", "thumb");
    if (n.cas_id && [5,7].includes(n.object_kind)) {
      const img = el("img");
      img.loading = "lazy";
      img.src = thumbUrl(n);
      img.onerror = () => { thumb.textContent = KIND_ICON[n.object_kind] || "📄"; };
      thumb.appendChild(img);
    } else {
      thumb.textContent = n.is_dir ? "📁" : (KIND_ICON[n.object_kind] || "📄");
    }
    card.appendChild(thumb);
    card.appendChild(el("div", "name",
      n.name + (n.extension ? "." + n.extension : "")));
    card.appendChild(el("div", "meta",
      n.is_dir ? t("folder") : fmtBytes(n.size_in_bytes)));
    card.onclick = (e) => bus.select(n, e);
    card.ondblclick = () => activate(n);
    if (!n.ephemeral) {
      // db-backed affordances: tag/favorite menus, move-by-drag
      card.oncontextmenu = (e) => { e.preventDefault();
        if (!state.selectedIds.has(n.id)) bus.select(n);
        bus.showMenu(e.clientX, e.clientY, n); };
      draggable(card, n);
      if (n.is_dir) droppable(card, dirTarget(n));
    } else {
      card.oncontextmenu = (e) => { e.preventDefault();
        if (!state.selectedIds.has(n.id)) bus.select(n);
        bus.showEphemeralMenu(e.clientX, e.clientY, n); };
    }
    c.appendChild(card);
  }
}

function renderListRows(table, nodes) {
  for (const n of nodes) {
    const tr = el("tr");
    tr.dataset.fp = String(n.id);
    if (state.selectedIds.has(n.id))
      tr.classList.add("selected");
    const icon = n.is_dir ? "📁" : (KIND_ICON[n.object_kind] || "📄");
    tr.appendChild(el("td", "",
      `${icon} ${n.name}${n.extension ? "." + n.extension : ""}`));
    tr.appendChild(el("td", "", n.is_dir ? t("folder") : (n.extension || "")));
    tr.appendChild(el("td", "", n.is_dir ? "" : fmtBytes(n.size_in_bytes)));
    tr.appendChild(el("td", "", (n.date_modified || "").slice(0, 16)));
    tr.appendChild(el("td", "", n.materialized_path || ""));
    tr.onclick = (e) => bus.select(n, e);
    tr.ondblclick = () => activate(n);
    if (!n.ephemeral) {
      tr.oncontextmenu = (e) => { e.preventDefault();
        if (!state.selectedIds.has(n.id)) bus.select(n);
        bus.showMenu(e.clientX, e.clientY, n); };
      draggable(tr, n);
      if (n.is_dir) droppable(tr, dirTarget(n));
    } else {
      tr.oncontextmenu = (e) => { e.preventDefault();
        if (!state.selectedIds.has(n.id)) bus.select(n);
        bus.showEphemeralMenu(e.clientX, e.clientY, n); };
    }
    table.appendChild(tr);
  }
}

// ---------- duplicates (config-5 flow surfaced in the UI) ----------
async function loadDuplicates() {
  renderCrumbs();
  const c = $("content");
  c.className = "";
  c.innerHTML = "";
  c.appendChild(el("div", "meta", t("scanning")));
  const groups = await client.search.duplicates({threshold: 8}, state.lib);
  c.innerHTML = "";
  if (!groups.length) {
    const box = el("div", "dupgroup");
    box.appendChild(el("div", "meta", t("no_duplicates")));
    c.appendChild(box);
    return;
  }
  for (const g of groups) {
    const box = el("div", "dupgroup");
    box.appendChild(el("b", "",
      `${g.files.length} files (${g.kind === "exact" ? "identical" : "near-duplicate"})`));
    const files = el("div", "files");
    for (const p of g.files) {
      files.appendChild(el("div", "meta",
        `${p.materialized_path || "/"}${p.name}`
        + `${p.extension ? "." + p.extension : ""} · ${fmtBytes(p.size_in_bytes)}`));
    }
    box.appendChild(files);
    c.appendChild(box);
  }
}

// ---------- keyboard navigation ----------
export function moveSelection(dx, dy) {
  const nodes = state.nodes;
  if (!nodes.length) return;
  let idx = state.selected
    ? nodes.findIndex(n => n.id === state.selected.id) : -1;
  let cols = 1;
  if (state.view !== "list") {
    const c = $("content");
    const card = c.querySelector(".card");
    if (card) cols = Math.max(1, Math.floor(
      c.clientWidth / (card.offsetWidth + 12)));
  }
  const delta = dx + dy * cols;
  idx = idx < 0 ? 0 : Math.max(0, Math.min(nodes.length - 1, idx + delta));
  bus.select(nodes[idx]);
  document.querySelector(`#content [data-fp="${nodes[idx].id}"]`)
    ?.scrollIntoView({block: "nearest"});
}
