// Explorer entry point: boot, library selection, nav, subscriptions,
// keyboard navigation (role parity: ref:interface/app + apps/web entry).

import client, { SdSocket } from "/rspc/client.js";
import { $, bus, el, fmtBytes, state } from "/static/js/util.js";
import { clearSelection, loadContent, moveSelection, openDir, setView, upDir } from "/static/js/views.js";
import { closeInspector, select } from "/static/js/inspector.js";
import { onJobProgress, renderJobs, wireJobsPanel } from "/static/js/jobs.js";
import { openDropPanel, rejectPendingOffer, showDropOffer, wireDropPanel } from "/static/js/spacedrop.js";
import { addLocationModal, wireSettingsPanel } from "/static/js/settings.js";
import { showEphemeralMenu, showMenu, wireContextMenu } from "/static/js/contextmenu.js";
import { showOnboarding } from "/static/js/onboarding.js";
import { attachDropdown, confirmDialog, initTooltips, promptDialog, toast } from "/static/js/ui.js";
import { initI18n, t } from "/static/js/i18n.js";
import { openPreview, previewOpen, wireQuickPreview } from "/static/js/quickpreview.js";
import { droppable, guardTarget } from "/static/js/dnd.js";

const sock = new SdSocket();
let unsubJobs = null;

// late-bound hooks for the other modules
bus.select = select;
bus.openDropPanel = openDropPanel;
bus.loadContent = loadContent;
bus.clearSelection = clearSelection;
bus.reloadLibraries = loadLibraries;
bus.refreshNav = () => state.lib && refreshNav();
bus.refreshHeader = async () => {
  const ns = await client.nodeState();
  $("device").textContent = `${ns.name} · ${ns.device_model}`;
};

// ---------- libraries / nav ----------
export async function loadLibraries() {
  const libs = await client.library.list();
  if (!libs.length) { showOnboarding(); return; }
  $("onboard").classList.remove("open");
  const sel = $("libsel");
  sel.innerHTML = "";
  for (const l of libs) {
    const o = el("option", "", l.config.name);
    o.value = l.uuid; sel.appendChild(o);
  }
  sel.onchange = () => selectLibrary(sel.value);
  const keep = libs.some(l => l.uuid === state.lib) ? state.lib : libs[0].uuid;
  sel.value = keep;
  // a rename/new-library invalidation must NOT reset browsing state
  // when the selected library is unchanged — just refresh the chrome
  if (keep === state.lib) await refreshNav();
  else await selectLibrary(keep);
  bus.refreshHeader();
}

async function selectLibrary(id) {
  // overview is the landing page, like the reference's $libraryId index
  Object.assign(state, { lib:id, loc:null, tag:null, search:"", cursor:null,
                         path:"/", mode:"overview", selected:null,
                         selectedIds:new Set() });
  if (unsubJobs) unsubJobs();
  unsubJobs = sock.subscribe("jobs.progress", onJobProgress, {libraryId:id});
  await refreshNav();
  loadContent(true);
}

function renderRoutes() {
  // overview / favorites / recents (ref:interface/app/$libraryId/
  // {overview,favorites.tsx,recents.tsx} sidebar routes)
  const routes = $("routes");
  routes.innerHTML = "";
  const route = (label, mode) => {
    const item = el("div", "item", label);
    if (state.mode === mode) item.classList.add("active");
    item.onclick = () => { setActive(item);
      Object.assign(state, {mode, loc: null, tag: null, cursor: null});
      clearSelection();
      loadContent(true); };
    routes.appendChild(item);
  };
  route("🏠 " + t("overview"), "overview");
  route("★ " + t("favorites"), "favorites");
  route("🕘 " + t("recents"), "recents");
  route("🌐 " + t("network"), "network");
}

async function refreshNav() {
  renderRoutes();
  const [locs, tags, labels, stats, saved] = await Promise.all([
    client.locations.list(null, state.lib),
    client.tags.list(null, state.lib),
    client.labels.list(null, state.lib),
    client.library.statistics(null, state.lib),
    client.search.saved.list(null, state.lib),
  ]);
  state.locPaths = {};
  state.locNames = {};
  const locDiv = $("locs");
  locDiv.innerHTML = "";
  for (const n of locs.nodes) {
    state.locPaths[n.id] = n.path;
    state.locNames[n.id] = n.name || n.path;
    const item = el("div", "item",
      (n.online === false ? "⚠️ " : "📂 ") + (n.name || n.path));
    if (n.online === false) {
      item.style.opacity = "0.55";
      item.title = t("location_offline_tip");
    }
    item.onclick = () => { setActive(item);
      Object.assign(state, {loc:n.id, tag:null, cursor:null, path:"/",
                            mode:"browse"});
      clearSelection();
      loadContent(true); };
    // sidebar locations are move targets (drop = move to its root)
    droppable(item, () => guardTarget(n.id, "/"));
    locDiv.appendChild(item);
  }
  // "This device" volumes → ephemeral (non-indexed) browse
  // (ref:interface/app/$libraryId/ephemeral.tsx via the sidebar)
  try {
    const vols = await client.volumes.list();
    const volDiv = $("volumes");
    volDiv.innerHTML = "";
    for (const v of vols) {
      const item = el("div", "item", "💻 " + (v.name || v.mount_point));
      item.onclick = () => { setActive(item);
        Object.assign(state, {mode: "ephemeral", ephPath: v.mount_point,
                              ephRoot: v.mount_point,
                              ephRootName: v.name || v.mount_point,
                              loc: null, tag: null, cursor: null});
        clearSelection();
        loadContent(true); };
      volDiv.appendChild(item);
    }
  } catch { /* volumes are best-effort chrome */ }

  state.allTags = tags.nodes;
  const tagDiv = $("tags");
  tagDiv.innerHTML = "";
  for (const n of tags.nodes) {
    const item = el("div", "item", "🏷️ " + (n.name || "?"));
    item.onclick = () => { setActive(item);
      Object.assign(state, {tag:n.id, loc:null, cursor:null, mode:"browse"});
      clearSelection();
      loadContent(true); };
    tagDiv.appendChild(item);
  }
  // AI labels route (ref:interface/app/$libraryId/labels.tsx): the
  // labeler's vocabulary as clickable filters
  const labDiv = $("labels");
  labDiv.innerHTML = "";
  for (const n of labels.nodes) {
    const item = el("div", "item", "🤖 " + (n.name || "?"));
    item.onclick = () => { setActive(item);
      Object.assign(state, {mode: "label", labelFilter: n.id,
                            labelName: n.name, loc: null, tag: null,
                            cursor: null});
      clearSelection();
      loadContent(true); };
    labDiv.appendChild(item);
  }
  if (!labels.nodes.length)
    labDiv.appendChild(el("div", "meta", t("no_labels_yet")));

  const savDiv = $("saved");
  savDiv.innerHTML = "";
  for (const s of saved.nodes) {
    const item = el("div", "item", `🔖 ${s.name || s.search || "?"}`);
    item.onclick = () => { setActive(item);
      Object.assign(state, {mode:"search", search:s.search || "",
                            loc:null, tag:null, cursor:null});
      $("search").value = state.search;
      clearSelection();
      loadContent(true); };
    item.oncontextmenu = async (e) => {
      e.preventDefault();
      const ok = await confirmDialog(t("delete_search_title"),
        t("delete_search_body", {name: s.name || s.search}),
        {danger: true, actionLabel: t("delete")});
      if (ok) {
        await client.search.saved.delete(s.id, state.lib);
        refreshNav();
      }
    };
    savDiv.appendChild(item);
  }

  const tools = $("tools");
  tools.innerHTML = "";
  const dup = el("div", "item", "♊ " + t("duplicates"));
  dup.onclick = () => { setActive(dup);
    Object.assign(state, {mode:"duplicates", loc:null, tag:null});
    clearSelection();
    loadContent(true); };
  tools.appendChild(dup);
  $("stats").textContent =
    `${stats.total_object_count} objects · ${fmtBytes(+stats.total_bytes_used)} indexed`;
}

function setActive(item) {
  document.querySelectorAll("nav .item.active")
    .forEach(e => e.classList.remove("active"));
  if (item) item.classList.add("active");
}

// ---------- header wiring ----------
const SORT_FIELDS = [
  ["name", "sort_name"], ["sizeInBytes", "sort_size"],
  ["dateModified", "sort_modified"], ["dateCreated", "sort_created"],
  ["dateAccessed", "sort_accessed"],
];
attachDropdown($("btn-sort"), () => {
  // these views pin their own ordering (recents = last-opened,
  // ephemeral = dirs-first walker order) or have none — a selectable
  // menu would silently no-op
  if (["recents", "duplicates", "overview", "ephemeral", "network"]
      .includes(state.mode)) {
    return [{label: t("sort_unavailable"), disabled: true}];
  }
  return [
  ...SORT_FIELDS.map(([field, key]) => ({
    label: (state.orderBy === field ? "✓ " : "\u2007 ") + t(key),
    onClick: () => {
      state.orderBy = field;
      localStorage.setItem("sd-order", field);
      clearSelection();
      loadContent(true);
    },
  })),
  {separator: true},
  ...[["asc", "sort_asc"], ["desc", "sort_desc"]].map(([dir, key]) => ({
    label: (state.orderDir === dir ? "✓ " : "\u2007 ") + t(key),
    onClick: () => {
      state.orderDir = dir;
      localStorage.setItem("sd-orderdir", dir);
      clearSelection();
      loadContent(true);
    },
  })),
  ];
});
document.querySelectorAll("#viewsw button").forEach(b =>
  b.onclick = () => setView(b.dataset.view));
$("search").addEventListener("keydown", (e) => {
  if (e.key === "Enter") {
    state.search = e.target.value;
    state.mode = state.search ? "search" : "browse";
    clearSelection();
    loadContent(true);
  }
  if (e.key === "Escape") e.target.blur();
});
$("btn-save-search").onclick = async () => {
  // commit whatever is in the box first — the button must never save
  // a stale query or silently no-op on un-entered text
  const text = $("search").value.trim();
  if (!text) return;
  if (text !== state.search || state.mode !== "search") {
    state.search = text;
    state.mode = "search";
    clearSelection();
    loadContent(true);
  }
  const name = await promptDialog(t("save_search_title"), {
    value: text, message: t("save_search_body"),
    actionLabel: t("save"),
  });
  if (!name) return;
  await client.search.saved.create({name, search: text}, state.lib);
  toast(t("search_saved_toast"), {kind: "ok"});
  refreshNav();
};
$("btn-addloc").onclick = () => addLocationModal();
bus.showMenu = showMenu;
bus.showEphemeralMenu = showEphemeralMenu;
wireJobsPanel();
wireDropPanel();
wireSettingsPanel();
wireContextMenu();
wireQuickPreview();
initTooltips();

// ---------- keyboard navigation ----------
const VIEWS = ["grid", "list", "media"];
document.addEventListener("keydown", (e) => {
  const typing = ["INPUT", "TEXTAREA", "SELECT"]
    .includes(document.activeElement?.tagName);
  if (typing) return;
  switch (e.key) {
    case "/":
      e.preventDefault();
      $("search").focus();
      break;
    case "ArrowRight": e.preventDefault(); moveSelection(1, 0); break;
    case "ArrowLeft": e.preventDefault(); moveSelection(-1, 0); break;
    case "ArrowDown": e.preventDefault(); moveSelection(0, 1); break;
    case "ArrowUp": e.preventDefault(); moveSelection(0, -1); break;
    case "j": moveSelection(1, 0); break;
    case "k": moveSelection(-1, 0); break;
    case "Enter":
      if (state.selected?.is_dir) openDir(state.selected);
      break;
    case " ":
      // space = QuickPreview of the selection (the preview's own
      // capture handler owns the key while open)
      if (state.selected && !previewOpen()) {
        e.preventDefault();
        openPreview(state.selected);
      }
      break;
    case "Backspace": upDir(); break;
    case "v":
      setView(VIEWS[(VIEWS.indexOf(state.view) + 1) % VIEWS.length]);
      break;
    case "Escape":
      // a pending spacedrop offer must be answered, not dismissed
      // (other dialogs handle their own Escape in openDialog)
      if (rejectPendingOffer()) break;
      document.querySelectorAll(".panel.open")
        .forEach(p => p.classList.remove("open"));
      closeInspector();
      break;
  }
});

// ---------- live events ----------
sock.subscribe("p2p.events", (ev) => {
  if (ev.kind === "SpacedropRequest") showDropOffer(ev);
  if (ev.kind === "SpacedropProgress")
    $("events").textContent = `📡 transfer ${ev.percent}%`;
  if (ev.kind && ev.kind.startsWith("Peer") &&
      $("drop-panel").classList.contains("open")) openDropPanel();
});
sock.subscribe("notifications.listen", (ev) => {
  // persisted job-outcome notifications (ref:lib.rs emit_notification)
  const d = ev.data || {};
  const what = d.job || "job";
  const kind = d.kind === "error" ? "error"
             : d.kind === "warning" ? "info" : "ok";
  toast(
    d.message ? `${what}: ${d.message}`
              : `${what} ${t(d.kind === "error" ? "job_failed" : "job_done")}`,
    {kind});
});
sock.subscribe("invalidation.listen", (ev) => {
  $("events").textContent = `↻ ${ev.key}`;
  if (["search.paths", "locations.list", "tags.list"].includes(ev.key))
    loadContent(true);
  if (["locations.list", "tags.list", "labels.list",
       "search.saved.list"].includes(ev.key))
    refreshNav();
  if (ev.key === "library.list") loadLibraries();
  if (ev.key === "jobs.reports" &&
      $("jobs-panel").classList.contains("open")) renderJobs();
});

// ---------- deep links ----------
// `sdx desktop --open-path P` lands here as "#/ephemeral?path=P"
function applyDeepLink() {
  const m = location.hash.match(/^#\/ephemeral\?path=(.+)$/);
  if (!m) return false;
  const path = decodeURIComponent(m[1]);
  Object.assign(state, {mode: "ephemeral", ephPath: path, ephRoot: "/",
                        ephRootName: "/", loc: null, tag: null,
                        cursor: null});
  clearSelection();
  loadContent(true);
  return true;
}
window.addEventListener("hashchange", applyDeepLink);

// ---------- boot ----------
await initI18n();  // catalogs before first render (top-level await)
setView(state.view);
loadLibraries().then(() => { applyDeepLink(); }).catch(e => {
  $("stats").textContent = "error: " + e.message;
});
