// Spacedrop panel: peer list, staged sends, incoming offer modal
// (role parity: ref:core/src/p2p/operations/spacedrop.rs UI flow).

import client from "/rspc/client.js";
import { $, el, fmtBytes } from "/static/js/util.js";
import { openDialog, toast } from "/static/js/ui.js";
import { t } from "/static/js/i18n.js";

let dropQueue = [];  // file paths staged for sending

export async function openDropPanel(paths) {
  if (paths) dropQueue = paths;
  $("jobs-panel").classList.remove("open");
  $("settings-panel").classList.remove("open");
  const p = $("drop-panel");
  p.classList.add("open");
  const st = await client.p2p.state();
  $("drop-self").textContent = st.enabled
    ? `this node: ${st.identity.slice(0, 20)}…` : "p2p disabled";
  $("drop-status").textContent = dropQueue.length
    ? t("drop_ready", {files: dropQueue.map(x => x.split("/").pop()).join(", ")})
    : t("drop_hint");
  const peers = $("peers");
  peers.innerHTML = "";
  for (const peer of st.peers || []) {
    const row = el("div", "peer");
    const label = el("div", "",
      `${peer.metadata?.name || "node"} · ${peer.identity.slice(0, 16)}…` +
      (peer.connected ? " ✓" : ""));
    row.appendChild(label);
    const send = el("button", dropQueue.length ? "primary" : "", t("send"));
    send.disabled = !dropQueue.length;
    send.onclick = async () => {
      try {
        $("drop-status").textContent = t("drop_sending");
        await client.p2p.spacedrop(
          {identity: peer.identity, file_paths: dropQueue});
        $("drop-status").textContent = t("drop_sent");
        toast(t("drop_sent_toast"), {kind: "ok"});
        dropQueue = [];
      } catch (e) {
        $("drop-status").textContent = "✗ " + e.message;
        toast("✗ spacedrop: " + e.message, {kind: "error"});
      }
    };
    row.appendChild(send);
    peers.appendChild(row);
  }
  if (!(st.peers || []).length)
    peers.appendChild(el("div", "meta", t("no_peers")));
}

let pendingOffer = null;  // {id, close} — offer currently dialogued
let offerQueue = [];      // further offers wait their turn — one
// sticky dialog at a time, so Escape always maps to THE visible offer

/** Escape on a pending offer = explicit reject (a dismissed dialog
 *  would strand the sender). Returns true if an offer was handled. */
export function rejectPendingOffer() {
  if (pendingOffer == null) return false;
  const {id, close} = pendingOffer;
  settleOffer(id);
  client.p2p.rejectSpacedrop(id).catch(() => {});
  close();
  return true;
}

/** Clear pending state for offer `id` (and only it) and surface the
 *  next queued offer, if any. */
function settleOffer(id) {
  if (pendingOffer?.id !== id) return;
  pendingOffer = null;
  const next = offerQueue.shift();
  if (next) showDropOffer(next);
}

export function showDropOffer(ev) {
  if (pendingOffer) { offerQueue.push(ev); return; }
  // sticky: the dialog's own Escape/backdrop dismissal is disabled —
  // the global Escape handler routes to rejectPendingOffer instead
  const close = openDialog(t("incoming_spacedrop"), (m, closeDlg) => {
    m.appendChild(el("div", "meta", t("from_peer", {peer: ev.peer.slice(0, 24)})));
    const list = el("div");
    list.style.margin = "8px 0";
    for (const f of ev.files) list.appendChild(el("div", "", "• " + f));
    m.appendChild(list);
    m.appendChild(el("div", "meta", fmtBytes(ev.total_size)));
    const dir = el("input");
    dir.placeholder = t("target_dir_placeholder");
    m.appendChild(dir);
    const actions = el("div", "modal-actions");
    const reject = el("button", "danger", t("reject"));
    reject.onclick = async () => {
      closeDlg();
      settleOffer(ev.id);
      await client.p2p.rejectSpacedrop(ev.id);
    };
    const accept = el("button", "primary", t("accept"));
    accept.onclick = async () => {
      closeDlg();
      settleOffer(ev.id);
      await client.p2p.acceptSpacedrop(
        {id: ev.id, target_dir: dir.value || null});
      toast(t("drop_accepted_toast"), {kind: "ok"});
    };
    actions.appendChild(reject); actions.appendChild(accept);
    m.appendChild(actions);
  }, {sticky: true});
  pendingOffer = {id: ev.id, close};
}

export function wireDropPanel() {
  $("btn-drop").onclick = () => {
    const p = $("drop-panel");
    if (p.classList.contains("open")) p.classList.remove("open");
    else openDropPanel();
  };
}
