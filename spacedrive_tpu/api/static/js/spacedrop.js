// Spacedrop panel: peer list, staged sends, incoming offer modal
// (role parity: ref:core/src/p2p/operations/spacedrop.rs UI flow).

import client from "/rspc/client.js";
import { $, el, fmtBytes } from "/static/js/util.js";

let dropQueue = [];  // file paths staged for sending

export async function openDropPanel(paths) {
  if (paths) dropQueue = paths;
  $("jobs-panel").classList.remove("open");
  $("settings-panel").classList.remove("open");
  const p = $("drop-panel");
  p.classList.add("open");
  const st = await client.p2p.state();
  $("drop-self").textContent = st.enabled
    ? `this node: ${st.identity.slice(0, 20)}…` : "p2p disabled";
  $("drop-status").textContent = dropQueue.length
    ? `ready to send: ${dropQueue.map(x => x.split("/").pop()).join(", ")}`
    : "select a file → “spacedrop this file”, then pick a peer";
  const peers = $("peers");
  peers.innerHTML = "";
  for (const peer of st.peers || []) {
    const row = el("div", "peer");
    const label = el("div", "",
      `${peer.metadata?.name || "node"} · ${peer.identity.slice(0, 16)}…` +
      (peer.connected ? " ✓" : ""));
    row.appendChild(label);
    const send = el("button", dropQueue.length ? "primary" : "", "send");
    send.disabled = !dropQueue.length;
    send.onclick = async () => {
      try {
        $("drop-status").textContent = "sending…";
        await client.p2p.spacedrop(
          {identity: peer.identity, file_paths: dropQueue});
        $("drop-status").textContent = "✓ sent";
        dropQueue = [];
      } catch (e) {
        $("drop-status").textContent = "✗ " + e.message;
      }
    };
    row.appendChild(send);
    peers.appendChild(row);
  }
  if (!(st.peers || []).length)
    peers.appendChild(el("div", "meta", "no peers discovered yet"));
}

let pendingOffer = null;  // offer id awaiting accept/reject

/** Escape on a pending offer = explicit reject (a dismissed modal
 *  would strand the sender). Returns true if an offer was handled. */
export function rejectPendingOffer() {
  if (pendingOffer == null) return false;
  const id = pendingOffer;
  pendingOffer = null;
  client.p2p.rejectSpacedrop(id).catch(() => {});
  $("modal-back").classList.remove("open");
  return true;
}

export function showDropOffer(ev) {
  const back = $("modal-back");
  const modal = $("modal");
  pendingOffer = ev.id;
  modal.innerHTML = "";
  modal.appendChild(el("h2", "", "Incoming Spacedrop"));
  modal.appendChild(el("div", "meta", `from ${ev.peer.slice(0, 24)}…`));
  const list = el("div");
  list.style.margin = "8px 0";
  for (const f of ev.files) list.appendChild(el("div", "", "• " + f));
  modal.appendChild(list);
  modal.appendChild(el("div", "meta", fmtBytes(ev.total_size)));
  const dir = el("input");
  dir.placeholder = "target directory (blank = default)";
  modal.appendChild(dir);
  const actions = el("div", "modal-actions");
  const reject = el("button", "danger", "reject");
  reject.onclick = async () => {
    pendingOffer = null;
    await client.p2p.rejectSpacedrop(ev.id);
    back.classList.remove("open");
  };
  const accept = el("button", "primary", "accept");
  accept.onclick = async () => {
    pendingOffer = null;
    await client.p2p.acceptSpacedrop(
      {id: ev.id, target_dir: dir.value || null});
    back.classList.remove("open");
  };
  actions.appendChild(reject); actions.appendChild(accept);
  modal.appendChild(actions);
  back.classList.add("open");
}

export function wireDropPanel() {
  $("btn-drop").onclick = () => {
    const p = $("drop-panel");
    if (p.classList.contains("open")) p.classList.remove("open");
    else openDropPanel();
  };
}
