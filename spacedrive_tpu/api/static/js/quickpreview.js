// QuickPreview — space-bar full-size preview of the selected item,
// arrows step through the current listing while open (role parity:
// ref:interface/app/$libraryId/Explorer/QuickPreview/index.tsx over
// the range-served original, ref:core/src/custom_uri).

import client from "/rspc/client.js";
import { $, KIND_ICON, bus, el, fmtBytes, relPath, state } from "/static/js/util.js";

export const fileUrl = (n) => {
  if (n.ephemeral) {
    // non-indexed rows serve over the raw-path route (same trust
    // surface as the ephemeralFiles.* procedures)
    return `/spacedrive/local?path=${encodeURIComponent(n.path)}`;
  }
  // per-segment encoding: "#"/"?" in filenames must not become
  // fragment/query separators (encodeURI leaves them bare)
  const path = relPath(n).split("/").map(encodeURIComponent).join("/");
  return `/spacedrive/file/${state.lib}/${n.location_id}${path}`;
};

const TEXT_EXTS = new Set([
  "txt", "md", "json", "py", "js", "ts", "rs", "toml", "yaml", "yml",
  "c", "h", "cpp", "css", "html", "xml", "csv", "log", "sh", "ini",
]);

let current = null; // node being previewed

export const previewOpen = () => !!current;

export function openPreview(n) {
  if (!n || n.is_dir) return;
  current = n;
  render();
  $("preview-back").classList.add("open");
  stampAccess(n);  // no-op for ephemeral rows (no db id to stamp)
}

/** opening a preview counts as opening the file — feeds the recents
 *  route (ref:core/src/api/files.rs:298 updateAccessTime) */
function stampAccess(n) {
  if (n.ephemeral) return;
  n.object_date_accessed = new Date().toISOString();
  client.files.updateAccessTime({ids: [n.id]}, state.lib).catch(() => {});
}

export function closePreview() {
  current = null;
  $("preview-back").classList.remove("open");
  $("preview-body").innerHTML = ""; // stops <video>/<audio> playback
}

/** step to the previous/next non-directory row of the listing */
export function stepPreview(delta) {
  if (!current) return;
  const files = state.nodes.filter((x) => !x.is_dir);
  const idx = files.findIndex((x) => x.id === current.id);
  const next = files[idx + delta];
  if (next) {
    current = next;
    bus.select(next);
    render();
    stampAccess(next);
  }
}

async function render() {
  const n = current;
  const body = $("preview-body");
  body.innerHTML = "";
  $("preview-name").textContent =
    n.name + (n.extension ? "." + n.extension : "") +
    (n.size_in_bytes ? ` · ${fmtBytes(n.size_in_bytes)}` : "");
  const url = fileUrl(n);
  const kind = n.object_kind;
  const ext = (n.extension || "").toLowerCase();  // stored verbatim
  if (kind === 5) {
    const img = el("img");
    img.src = url;
    img.onerror = () => { img.replaceWith(el("div", "meta", "✗ load failed")); };
    body.appendChild(img);
  } else if (kind === 7) {
    const v = el("video");
    v.controls = true;
    v.src = url;
    body.appendChild(v);
  } else if (kind === 6) {
    const a = el("audio");
    a.controls = true;
    a.src = url;
    body.appendChild(a);
  } else if (ext === "pdf") {
    // the browser's own viewer over the range-served original
    const f = el("iframe");
    f.src = url;
    body.appendChild(f);
  } else if ([3, 9].includes(kind) || TEXT_EXTS.has(ext)) {
    const pre = el("pre", "", "loading…");
    body.appendChild(pre);
    try {
      // head only — a 2 GB log must not be pulled into the page
      const resp = await fetch(url, { headers: { Range: "bytes=0-65535" } });
      if (!resp.ok) throw new Error(`HTTP ${resp.status}`);
      const text = await resp.text();
      if (current === n)
        pre.textContent =
          text + (resp.status === 206 && n.size_in_bytes > 65536
            ? "\n… (first 64 KiB)" : "");
    } catch (e) {
      pre.textContent = "✗ " + e.message;
    }
  } else {
    body.appendChild(el("div", "bigicon", KIND_ICON[kind] || "📄"));
    body.appendChild(el("div", "meta", "no preview for this kind"));
  }
}

export function wireQuickPreview() {
  $("preview-back").onclick = (e) => {
    if (e.target.id === "preview-back") closePreview();
  };
  $("preview-close").onclick = closePreview;
  // capture phase: while the preview is open it owns the WHOLE
  // keyboard — any key leaking through would drive the grid underneath
  // (move the selection, open a dir, switch view) and leave `current`
  // pointing at a listing that no longer exists
  document.addEventListener("keydown", (e) => {
    if (!current) return;
    e.stopPropagation();
    if ([" ", "Escape", "ArrowLeft", "ArrowRight"].includes(e.key)) {
      e.preventDefault();
      if (e.key === " " || e.key === "Escape") closePreview();
      else stepPreview(e.key === "ArrowRight" ? 1 : -1);
    }
  }, true);
}
