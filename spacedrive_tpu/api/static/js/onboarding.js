// Onboarding: first-run library creation + first location
// (role parity: ref:interface/app/onboarding).

import client from "/rspc/client.js";
import { $, bus, el } from "/static/js/util.js";
import { t } from "/static/js/i18n.js";

export function showOnboarding() {
  const board = $("onboard");
  board.classList.add("open");
  const box = board.querySelector(".box");
  box.innerHTML = "";
  box.appendChild(el("h1", "", ""));
  box.querySelector("h1").innerHTML = "Welcome to <b>spacedrive-tpu</b>";
  box.appendChild(el("p", "", t("onboard_intro")));
  const name = el("input");
  name.placeholder = t("library_name_placeholder");
  name.value = t("onboard_default_name");
  box.appendChild(name);
  const path = el("input");
  path.placeholder = t("onboard_first_location");
  box.appendChild(path);
  const err = el("div", "meta");
  err.style.color = "var(--err)";
  box.appendChild(err);
  const actions = el("div", "modal-actions");
  const go = el("button", "primary", t("onboard_create"));
  go.onclick = async () => {
    if (!name.value) { err.textContent = t("onboard_name_required"); return; }
    go.disabled = true;
    try {
      const lib = await client.library.create({name: name.value});
      if (path.value) {
        await client.locations.create({path: path.value}, lib.uuid);
      }
      board.classList.remove("open");
      await bus.reloadLibraries?.();
    } catch (e) {
      err.textContent = e.message;
      go.disabled = false;
    }
  };
  actions.appendChild(go);
  box.appendChild(actions);
  name.focus();
}
