// Onboarding: first-run library creation + first location
// (role parity: ref:interface/app/onboarding).

import client from "/rspc/client.js";
import { $, bus, el } from "/static/js/util.js";

export function showOnboarding() {
  const board = $("onboard");
  board.classList.add("open");
  const box = board.querySelector(".box");
  box.innerHTML = "";
  box.appendChild(el("h1", "", ""));
  box.querySelector("h1").innerHTML = "Welcome to <b>spacedrive-tpu</b>";
  box.appendChild(el("p", "",
    "A library is the database that indexes your files. Create one to "
    + "get started — you can add locations (folders to index) next."));
  const name = el("input");
  name.placeholder = "library name";
  name.value = "My Library";
  box.appendChild(name);
  const path = el("input");
  path.placeholder = "first location path (optional, e.g. /home/me/files)";
  box.appendChild(path);
  const err = el("div", "meta");
  err.style.color = "var(--err)";
  box.appendChild(err);
  const actions = el("div", "modal-actions");
  const go = el("button", "primary", "create library");
  go.onclick = async () => {
    if (!name.value) { err.textContent = "name required"; return; }
    go.disabled = true;
    try {
      const lib = await client.library.create({name: name.value});
      if (path.value) {
        await client.locations.create({path: path.value}, lib.uuid);
      }
      board.classList.remove("open");
      await bus.reloadLibraries?.();
    } catch (e) {
      err.textContent = e.message;
      go.disabled = false;
    }
  };
  actions.appendChild(go);
  box.appendChild(actions);
  name.focus();
}
