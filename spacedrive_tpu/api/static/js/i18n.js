// i18n — string catalog + DOM application (role parity:
// ref:interface/locales/* via i18next; here a dependency-free loader).
//
// Catalogs live at /static/i18n/<locale>.json (flat key → string with
// {param} slots). The active locale comes from localStorage("sd-lang")
// or the browser language, falling back to English key-by-key so a
// partially translated catalog never blanks the UI.
//
// Static DOM: elements carry data-i18n="key" (textContent),
// data-i18n-placeholder / data-i18n-tip for attributes; applyDom()
// rewrites them. Dynamic strings: modules import t().

export const LOCALES = {
  en: "English", de: "Deutsch", es: "Español", fr: "Français",
  it: "Italiano", nl: "Nederlands", ru: "Русский", tr: "Türkçe",
  be: "Беларуская", "zh-CN": "中文（简体）", "zh-TW": "中文（繁體）",
};

let catalog = {};
let fallback = {};
let current = "en";

export function locale() {
  return current;
}

function pick() {
  const saved = localStorage.getItem("sd-lang");
  if (saved && LOCALES[saved]) return saved;
  const nav = navigator.language || "en";
  if (LOCALES[nav]) return nav;
  const short = nav.split("-")[0];
  if (LOCALES[short]) return short;
  // base-language match: zh / zh-Hans-CN / zh-SG → first zh-* catalog
  // (Traditional-script tags prefer zh-TW)
  if (short === "zh") {
    return /hant|tw|hk|mo/i.test(nav) ? "zh-TW" : "zh-CN";
  }
  const prefix = Object.keys(LOCALES).find(l => l.startsWith(short + "-"));
  return prefix || "en";
}

async function fetchCatalog(loc) {
  const resp = await fetch(`/static/i18n/${loc}.json`);
  if (!resp.ok) throw new Error(`no catalog for ${loc}`);
  return resp.json();
}

export async function initI18n() {
  current = pick();
  // both fetches are boot-blocking — run them concurrently
  const [en, cat] = await Promise.all([
    fetchCatalog("en").catch(() => ({})),
    current === "en" ? null : fetchCatalog(current).catch(() => null),
  ]);
  fallback = en;
  catalog = cat || en;
  applyDom(document);
  document.documentElement.lang = current;
}

/** Translate `key`, interpolating {name} params; falls back to English,
 *  then to the key itself (visible = greppable, never blank). */
export function t(key, params) {
  let s = catalog[key] ?? fallback[key] ?? key;
  if (params) {
    for (const [k, v] of Object.entries(params)) {
      // function form: "$&"-style patterns in values must stay literal
      s = s.replaceAll(`{${k}}`, () => String(v));
    }
  }
  return s;
}

export function applyDom(root) {
  root.querySelectorAll("[data-i18n]").forEach((el) => {
    el.textContent = t(el.getAttribute("data-i18n"));
  });
  root.querySelectorAll("[data-i18n-placeholder]").forEach((el) => {
    el.placeholder = t(el.getAttribute("data-i18n-placeholder"));
  });
  root.querySelectorAll("[data-i18n-tip]").forEach((el) => {
    el.setAttribute("data-tip", t(el.getAttribute("data-i18n-tip")));
  });
}

/** Persist the choice and reload — every module re-renders from the
 *  new catalog (the reference also reloads routes on language switch). */
export function setLocale(loc) {
  if (!LOCALES[loc]) return;
  localStorage.setItem("sd-lang", loc);
  location.reload();
}
