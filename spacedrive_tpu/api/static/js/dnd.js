// Drag-and-drop file moves: drag the selection onto a folder card/row,
// a breadcrumb segment, or a sidebar location → files.cutFiles (role
// parity: ref:interface/app/$libraryId/Explorer/useExplorerDnd.tsx,
// DragOverlay.tsx, ExplorerDroppable.tsx over core/src/object/fs/cut).

import client from "/rspc/client.js";
import { $, bus, state } from "/static/js/util.js";

let drag = null; // {ids, location_id} — the in-flight drag payload

/** make an item row/card draggable; dragging a selected item drags the
 *  whole (same-location) selection, like the reference's drag overlay */
export function draggable(elem, n) {
  elem.draggable = true;
  elem.addEventListener("dragstart", (e) => {
    const multi = state.selectedIds.has(n.id) && state.selectedIds.size > 1;
    const ids = multi
      ? state.nodes
          .filter((x) => state.selectedIds.has(x.id) &&
                         x.location_id === n.location_id)
          .map((x) => x.id)
      : [n.id];
    drag = { ids, location_id: n.location_id };
    e.dataTransfer.effectAllowed = "move";
    e.dataTransfer.setData("text/plain", String(n.id)); // firefox requires data
  });
  elem.addEventListener("dragend", () => { drag = null; });
}

/** register a drop target; `targetFn` returns {location_id, path} or
 *  null when the current drag must not land here (e.g. a folder onto
 *  itself) */
export function droppable(elem, targetFn) {
  elem.addEventListener("dragover", (e) => {
    if (!drag || !targetFn()) return;
    e.preventDefault();
    e.dataTransfer.dropEffect = "move";
    elem.classList.add("drop-ok");
  });
  elem.addEventListener("dragleave", () => elem.classList.remove("drop-ok"));
  elem.addEventListener("drop", async (e) => {
    e.preventDefault();
    elem.classList.remove("drop-ok");
    const target = drag && targetFn();
    if (!target) return;
    const src = drag;
    drag = null;
    try {
      await client.files.cutFiles({
        source_location_id: src.location_id,
        target_location_id: target.location_id,
        sources_file_path_ids: src.ids,
        target_relative_path: target.path,
      }, state.lib);
      $("events").textContent = `moved ${src.ids.length} item(s)`;
      bus.loadContent(true);
    } catch (err) {
      $("events").textContent = "✗ move: " + err.message;
    }
  });
}

/** drop target for a directory NODE in the listing */
export function dirTarget(n) {
  return () => {
    // a folder can't be dropped into itself or its own selection
    if (!drag || drag.ids.includes(n.id)) return null;
    return {
      location_id: n.location_id,
      path: (n.materialized_path || "/") + n.name + "/",
    };
  };
}