// Drag-and-drop file moves: drag the selection onto a folder card/row,
// a breadcrumb segment, or a sidebar location → files.cutFiles (role
// parity: ref:interface/app/$libraryId/Explorer/useExplorerDnd.tsx,
// DragOverlay.tsx, ExplorerDroppable.tsx over core/src/object/fs/cut).

import client from "/rspc/client.js";
import { bus, state } from "/static/js/util.js";
import { toast } from "/static/js/ui.js";

let drag = null; // {ids, dirPaths, location_id} — the in-flight drag payload

const dirPath = (n) => (n.materialized_path || "/") + n.name + "/";

/** make an item row/card draggable; dragging a selected item drags the
 *  whole (same-location) selection, like the reference's drag overlay */
export function draggable(elem, n) {
  elem.draggable = true;
  elem.addEventListener("dragstart", (e) => {
    const multi = state.selectedIds.has(n.id) && state.selectedIds.size > 1;
    const chosen = multi
      ? state.nodes.filter((x) => state.selectedIds.has(x.id) &&
                                  x.location_id === n.location_id)
      : [n];
    drag = {
      ids: chosen.map((x) => x.id),
      // dragged DIR paths: a dir must never land in its own subtree
      dirPaths: chosen.filter((x) => x.is_dir).map(dirPath),
      location_id: n.location_id,
    };
    e.dataTransfer.effectAllowed = "move";
    e.dataTransfer.setData("text/plain", String(n.id)); // firefox requires data
  });
  elem.addEventListener("dragend", () => { drag = null; });
}

/** register a drop target; `targetFn` returns {location_id, path} or
 *  null when the current drag must not land here (e.g. a folder onto
 *  itself) */
export function droppable(elem, targetFn) {
  elem.addEventListener("dragover", (e) => {
    if (!drag || !targetFn()) return;
    e.preventDefault();
    e.dataTransfer.dropEffect = "move";
    elem.classList.add("drop-ok");
  });
  elem.addEventListener("dragleave", () => elem.classList.remove("drop-ok"));
  elem.addEventListener("drop", async (e) => {
    e.preventDefault();
    elem.classList.remove("drop-ok");
    const target = drag && targetFn();
    if (!target) return;
    const src = drag;
    drag = null;
    try {
      await client.files.cutFiles({
        source_location_id: src.location_id,
        target_location_id: target.location_id,
        sources_file_path_ids: src.ids,
        target_relative_path: target.path,
      }, state.lib);
      toast(`moved ${src.ids.length} item(s)`, {kind: "ok"});
      bus.loadContent(true);
    } catch (err) {
      toast("✗ move: " + err.message, {kind: "error"});
    }
  });
}

/** {location_id, path} if the current drag may land there, else null —
 *  a folder can't be dropped into itself or any of its descendants
 *  (recursive search listings render both in one view) */
export function guardTarget(location_id, path) {
  if (!drag) return null;
  if (drag.location_id === location_id &&
      drag.dirPaths.some((p) => path.startsWith(p))) return null;
  return { location_id, path };
}

/** drop target for a directory NODE in the listing */
export function dirTarget(n) {
  return () => {
    if (!drag || drag.ids.includes(n.id)) return null;
    return guardTarget(n.location_id, dirPath(n));
  };
}