"""Normalized query-cache protocol.

Parity: ref:crates/cache/src/lib.rs:13-40 — `Model` gives each row type
a name + unique id; query results are split into `CacheNode`s (the full
records, keyed `(__type, __id)`) and `Reference`s (pointers embedded in
the result shape), packaged as `NormalisedResults{item(s), nodes}` so
the frontend cache can dedupe records shared across queries.
"""

from __future__ import annotations

from typing import Any, Callable, Iterable

ModelId = Callable[[dict[str, Any]], Any]

# model name -> unique-id extractor (ref `Model::name` + `Model::id`)
_MODELS: dict[str, ModelId] = {}


def register_model(name: str, id_fn: ModelId | None = None) -> None:
    _MODELS[name] = id_fn or (lambda row: row["id"])


for _name in ("location", "file_path", "object", "tag", "label", "volume", "job"):
    register_model(_name)


def _node_id(model: str, row: dict[str, Any]) -> Any:
    if model not in _MODELS:
        register_model(model)
    nid = _MODELS[model](row)
    return nid.hex() if isinstance(nid, bytes) else nid


def reference(model: str, row: dict[str, Any]) -> dict[str, Any]:
    """ref:lib.rs `Reference<T>` wire shape."""
    return {"__type": model, "__id": _node_id(model, row)}


def cache_node(model: str, row: dict[str, Any]) -> dict[str, Any]:
    """ref:lib.rs `CacheNode` wire shape — the record + its key."""
    out = {"__type": model, "__id": _node_id(model, row)}
    for k, v in row.items():
        out[k] = v.hex() if isinstance(v, bytes) else v
    return out


def normalise(model: str, rows: Iterable[dict[str, Any]]) -> dict[str, Any]:
    """`NormalisedResults` for a homogeneous list (ref:lib.rs:31-40)."""
    rows = list(rows)
    return {
        "items": [reference(model, r) for r in rows],
        "nodes": [cache_node(model, r) for r in rows],
    }


def normalise_one(model: str, row: dict[str, Any]) -> dict[str, Any]:
    """`NormalisedResult` for a single record."""
    return {"item": reference(model, row), "nodes": [cache_node(model, row)]}
