"""API layer: typed router, normalized cache, invalidation, HTTP host.

Parity: ref:core/src/api (rspc router + CoreEvent + invalidation),
crates/cache (normalised results), core/src/custom_uri (file and
thumbnail serving), apps/server (Axum host).
"""

from .cache import normalise, normalise_one
from .invalidate import InvalidateOperation, invalidate_query
from .namespaces import mount
from .router import CoreEventKind, Router, RspcError
from .server import ApiServer

__all__ = [
    "ApiServer",
    "CoreEventKind",
    "InvalidateOperation",
    "Router",
    "RspcError",
    "invalidate_query",
    "mount",
    "normalise",
    "normalise_one",
]
