"""Typed procedure router — the rspc analogue.

Parity: ref:core/src/api/mod.rs — `Router<Ctx = Arc<Node>>` built by
`api::mount()` (:124) out of ~20 namespace routers (:197-218), with
library-scoped procedures taking `LibraryArgs<T>{library_id, arg}`
(api/utils/library.rs) resolved to a `Library` before the handler runs,
and the `CoreEvent` stream (:54-58) feeding subscriptions. Procedures
are query/mutation/subscription keyed "namespace.name" exactly like
rspc's merge naming.
"""

from __future__ import annotations

import inspect
import logging
import uuid
from dataclasses import dataclass, field
from enum import Enum
from typing import Any, AsyncIterator, Awaitable, Callable

logger = logging.getLogger(__name__)


class CoreEventKind(str, Enum):
    """ref:core/src/api/mod.rs:54-58 `CoreEvent`."""

    NEW_THUMBNAIL = "NewThumbnail"
    NEW_IDENTIFIED_OBJECTS = "NewIdentifiedObjects"
    JOB_PROGRESS = "JobProgress"
    INVALIDATE_OPERATION = "InvalidateOperation"


class RspcError(Exception):
    """ref:rspc::Error — code + message surfaced to the client."""

    def __init__(self, code: int, message: str):
        super().__init__(message)
        self.code = code
        self.message = message

    @classmethod
    def not_found(cls, what: str) -> "RspcError":
        return cls(404, f"{what} not found")

    @classmethod
    def bad_request(cls, message: str) -> "RspcError":
        return cls(400, message)


@dataclass
class Procedure:
    key: str
    kind: str  # query | mutation | subscription
    fn: Callable[..., Any]
    library_scoped: bool = False
    # admission-gate priority class; None resolves through the
    # serve.policy.NAMESPACE_CLASSES map (sdlint SD015 requires every
    # registration to be covered one way or the other)
    priority: str | None = None


class Router:
    """Procedure registry; namespaces merge by key prefix."""

    def __init__(self) -> None:
        self.procedures: dict[str, Procedure] = {}

    # --- registration (decorators) ---

    def _register(self, key: str, kind: str, library: bool,
                  priority: str | None = None):
        def deco(fn):
            if key in self.procedures:
                raise ValueError(f"duplicate procedure {key}")
            self.procedures[key] = Procedure(key, kind, fn, library,
                                             priority=priority)
            return fn

        return deco

    def query(self, key: str, *, library: bool = False,
              priority: str | None = None):
        return self._register(key, "query", library, priority)

    def mutation(self, key: str, *, library: bool = False,
                 priority: str | None = None):
        return self._register(key, "mutation", library, priority)

    def subscription(self, key: str, *, library: bool = False,
                     priority: str | None = None):
        return self._register(key, "subscription", library, priority)

    def merge(self, other: "Router") -> "Router":
        for key, proc in other.procedures.items():
            if key in self.procedures:
                raise ValueError(f"duplicate procedure {key}")
            self.procedures[key] = proc
        return self

    # --- execution ---

    async def exec(
        self,
        node: Any,
        key: str,
        arg: Any = None,
        library_id: str | uuid.UUID | None = None,
    ) -> Any:
        """Run a query/mutation through the serve layer: admission-gate
        the call under the procedure's priority class, and serve
        allowlisted queries from the read cache (single-flight, tag-
        invalidated, stale-while-revalidate in brownout). Without a
        serve runtime (``SD_SERVE_GATE=0`` or a bare node) this is
        exactly the pre-serve direct path."""
        proc = self.procedures.get(key)
        if proc is None:
            raise RspcError.not_found(f"procedure {key!r}")
        if proc.kind == "subscription":
            raise RspcError.bad_request(f"{key} is a subscription; use subscribe()")
        from ..serve import Shed, class_for_key, runtime_for

        serve = runtime_for(node)
        if serve is None:
            return await self._exec_direct(node, proc, key, arg, library_id)
        klass = class_for_key(key, proc.priority)
        import time as _time

        from ..serve.gate import observe_request_seconds

        t0 = _time.perf_counter()
        try:
            result = await self._exec_gated(
                node, serve, proc, key, arg, library_id, klass
            )
        except Shed as e:
            err = RspcError(429, f"SHED: {e.reason}")
            err.retry_after_s = e.retry_after_s
            raise err from None
        except BaseException:
            # errored-but-answered work counts: a handler that burned
            # 30 s before failing is exactly the latency the
            # interactive_p99 SLO exists to catch (sheds stay excluded
            # — fast 429s would bias the percentile low under overload)
            observe_request_seconds(klass, _time.perf_counter() - t0,
                                    tenant=library_id)
            raise
        # answered rspc calls feed the same per-class request latency
        # series the HTTP middleware does — without this leg the
        # interactive_p99 SLO would only ever see raw-route traffic;
        # library-scoped calls also attribute to the tenant sketch
        observe_request_seconds(klass, _time.perf_counter() - t0,
                                tenant=library_id)
        return result

    async def _exec_gated(
        self, node: Any, serve: Any, proc: Procedure, key: str,
        arg: Any, library_id: Any, klass: str,
    ) -> Any:
        """Admission × cache composition: the gate wraps the cache
        LOADER, not the lookup — a fresh hit costs no SQLite work and
        must not consume (or be shed for) an admission slot, and a
        100-waiter stampede on one key coalesces onto ONE admitted
        load instead of 100 slot requests."""
        from ..serve import CACHEABLE_QUERIES, query_cache_key

        if (
            proc.kind != "query"
            or key not in CACHEABLE_QUERIES
            or not proc.library_scoped
            or library_id is None
        ):
            async with serve.gate.admit(klass, key=key):
                return await self._exec_direct(
                    node, proc, key, arg, library_id
                )

        async def load() -> Any:
            async with serve.gate.admit(klass, key=key):
                # cache loaders run OFF the event loop: an allowlisted
                # query is a pure SQLite read, and a slow/contended disk
                # under it must stall this request's thread, not the
                # loop every other class is served from (it also makes
                # the in-flight budget real — sync handlers never yield,
                # so on-loop they can't overlap enough to be counted)
                return await self._exec_direct(node, proc, key, arg,
                                               library_id, off_loop=True)

        from ..serve import canonical_library_id

        lib_key = canonical_library_id(library_id)
        result = await serve.queries.get(
            query_cache_key(key, library_id, arg),
            load,
            tags=(("lib", lib_key), ("q", key, lib_key)),
            stale_ok=serve.gate.in_brownout(),
            tenant=lib_key,
        )
        return result.value

    async def _exec_direct(
        self, node: Any, proc: Procedure, key: str, arg: Any,
        library_id: Any, off_loop: bool = False,
    ) -> Any:
        args = [node]
        if proc.library_scoped:
            lib = self._resolve_library(node, library_id)
            args.append(lib)
        if _wants_arg(proc.fn, proc.library_scoped):
            args.append(arg)
        try:
            import asyncio

            if off_loop and not inspect.iscoroutinefunction(proc.fn):
                result = await asyncio.to_thread(proc.fn, *args)
            else:
                result = proc.fn(*args)
            if inspect.isawaitable(result):
                result = await result
        except (KeyError, TypeError, ValueError) as e:
            # Handlers index straight into the caller's arg shape (the
            # rspc style); a wrong shape is the CLIENT's error and must
            # answer 400, not crash to a 500 (ref:rspc BadRequest). But
            # ONLY when the raising frame is the handler body itself —
            # the same exception types from deeper in the call tree are
            # server bugs and must keep their 500 + traceback log.
            tb = e.__traceback__
            innermost = None
            while tb is not None:
                innermost = tb.tb_frame.f_code
                tb = tb.tb_next
            if innermost is not proc.fn.__code__:
                raise
            logger.warning("bad argument for %s: %r", key, e)
            raise RspcError.bad_request(
                f"bad argument for {key}: {type(e).__name__}: {e}")
        return result

    def subscribe(
        self,
        node: Any,
        key: str,
        arg: Any = None,
        library_id: str | uuid.UUID | None = None,
    ) -> AsyncIterator[Any]:
        proc = self.procedures.get(key)
        if proc is None or proc.kind != "subscription":
            raise RspcError.not_found(f"subscription {key!r}")
        args = [node]
        if proc.library_scoped:
            args.append(self._resolve_library(node, library_id))
        if _wants_arg(proc.fn, proc.library_scoped):
            args.append(arg)
        return proc.fn(*args)

    @staticmethod
    def _resolve_library(node: Any, library_id: Any):
        if library_id is None:
            raise RspcError.bad_request("library_id required")
        if not isinstance(library_id, uuid.UUID):
            library_id = uuid.UUID(str(library_id))
        lib = node.libraries.get(library_id)
        if lib is None:
            raise RspcError.not_found(f"library {library_id}")
        return lib

    # --- introspection (the generated-TS-types analogue) ---

    def manifest(self) -> dict[str, list[dict[str, Any]]]:
        """Procedure manifest, the stand-in for rspc's exported TS types
        (ref: packages/client/src/core.ts is generated the same way)."""
        out: dict[str, list[dict[str, Any]]] = {
            "queries": [],
            "mutations": [],
            "subscriptions": [],
        }
        plural = {
            "query": "queries",
            "mutation": "mutations",
            "subscription": "subscriptions",
        }
        for proc in sorted(self.procedures.values(), key=lambda p: p.key):
            out[plural[proc.kind]].append(
                {"key": proc.key, "library": proc.library_scoped}
            )
        return out

    def keys(self) -> set[str]:
        return set(self.procedures)


def _wants_arg(fn: Callable[..., Any], library_scoped: bool) -> bool:
    """Handlers are (node[, library][, arg]); arg is passed iff declared."""
    params = [
        p
        for p in inspect.signature(fn).parameters.values()
        if p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD)
    ]
    return len(params) > (2 if library_scoped else 1)
