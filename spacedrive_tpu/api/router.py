"""Typed procedure router — the rspc analogue.

Parity: ref:core/src/api/mod.rs — `Router<Ctx = Arc<Node>>` built by
`api::mount()` (:124) out of ~20 namespace routers (:197-218), with
library-scoped procedures taking `LibraryArgs<T>{library_id, arg}`
(api/utils/library.rs) resolved to a `Library` before the handler runs,
and the `CoreEvent` stream (:54-58) feeding subscriptions. Procedures
are query/mutation/subscription keyed "namespace.name" exactly like
rspc's merge naming.
"""

from __future__ import annotations

import inspect
import logging
import uuid
from dataclasses import dataclass, field
from enum import Enum
from typing import Any, AsyncIterator, Awaitable, Callable

logger = logging.getLogger(__name__)


class CoreEventKind(str, Enum):
    """ref:core/src/api/mod.rs:54-58 `CoreEvent`."""

    NEW_THUMBNAIL = "NewThumbnail"
    NEW_IDENTIFIED_OBJECTS = "NewIdentifiedObjects"
    JOB_PROGRESS = "JobProgress"
    INVALIDATE_OPERATION = "InvalidateOperation"


class RspcError(Exception):
    """ref:rspc::Error — code + message surfaced to the client."""

    def __init__(self, code: int, message: str):
        super().__init__(message)
        self.code = code
        self.message = message

    @classmethod
    def not_found(cls, what: str) -> "RspcError":
        return cls(404, f"{what} not found")

    @classmethod
    def bad_request(cls, message: str) -> "RspcError":
        return cls(400, message)


@dataclass
class Procedure:
    key: str
    kind: str  # query | mutation | subscription
    fn: Callable[..., Any]
    library_scoped: bool = False


class Router:
    """Procedure registry; namespaces merge by key prefix."""

    def __init__(self) -> None:
        self.procedures: dict[str, Procedure] = {}

    # --- registration (decorators) ---

    def _register(self, key: str, kind: str, library: bool):
        def deco(fn):
            if key in self.procedures:
                raise ValueError(f"duplicate procedure {key}")
            self.procedures[key] = Procedure(key, kind, fn, library)
            return fn

        return deco

    def query(self, key: str, *, library: bool = False):
        return self._register(key, "query", library)

    def mutation(self, key: str, *, library: bool = False):
        return self._register(key, "mutation", library)

    def subscription(self, key: str, *, library: bool = False):
        return self._register(key, "subscription", library)

    def merge(self, other: "Router") -> "Router":
        for key, proc in other.procedures.items():
            if key in self.procedures:
                raise ValueError(f"duplicate procedure {key}")
            self.procedures[key] = proc
        return self

    # --- execution ---

    async def exec(
        self,
        node: Any,
        key: str,
        arg: Any = None,
        library_id: str | uuid.UUID | None = None,
    ) -> Any:
        """Run a query/mutation. Library-scoped procedures resolve
        `library_id` first (ref:api/utils/library.rs LibraryArgs)."""
        proc = self.procedures.get(key)
        if proc is None:
            raise RspcError.not_found(f"procedure {key!r}")
        if proc.kind == "subscription":
            raise RspcError.bad_request(f"{key} is a subscription; use subscribe()")
        args = [node]
        if proc.library_scoped:
            lib = self._resolve_library(node, library_id)
            args.append(lib)
        if _wants_arg(proc.fn, proc.library_scoped):
            args.append(arg)
        try:
            result = proc.fn(*args)
            if inspect.isawaitable(result):
                result = await result
        except (KeyError, TypeError, ValueError) as e:
            # Handlers index straight into the caller's arg shape (the
            # rspc style); a wrong shape is the CLIENT's error and must
            # answer 400, not crash to a 500 (ref:rspc BadRequest). But
            # ONLY when the raising frame is the handler body itself —
            # the same exception types from deeper in the call tree are
            # server bugs and must keep their 500 + traceback log.
            tb = e.__traceback__
            innermost = None
            while tb is not None:
                innermost = tb.tb_frame.f_code
                tb = tb.tb_next
            if innermost is not proc.fn.__code__:
                raise
            logger.warning("bad argument for %s: %r", key, e)
            raise RspcError.bad_request(
                f"bad argument for {key}: {type(e).__name__}: {e}")
        return result

    def subscribe(
        self,
        node: Any,
        key: str,
        arg: Any = None,
        library_id: str | uuid.UUID | None = None,
    ) -> AsyncIterator[Any]:
        proc = self.procedures.get(key)
        if proc is None or proc.kind != "subscription":
            raise RspcError.not_found(f"subscription {key!r}")
        args = [node]
        if proc.library_scoped:
            args.append(self._resolve_library(node, library_id))
        if _wants_arg(proc.fn, proc.library_scoped):
            args.append(arg)
        return proc.fn(*args)

    @staticmethod
    def _resolve_library(node: Any, library_id: Any):
        if library_id is None:
            raise RspcError.bad_request("library_id required")
        if not isinstance(library_id, uuid.UUID):
            library_id = uuid.UUID(str(library_id))
        lib = node.libraries.get(library_id)
        if lib is None:
            raise RspcError.not_found(f"library {library_id}")
        return lib

    # --- introspection (the generated-TS-types analogue) ---

    def manifest(self) -> dict[str, list[dict[str, Any]]]:
        """Procedure manifest, the stand-in for rspc's exported TS types
        (ref: packages/client/src/core.ts is generated the same way)."""
        out: dict[str, list[dict[str, Any]]] = {
            "queries": [],
            "mutations": [],
            "subscriptions": [],
        }
        plural = {
            "query": "queries",
            "mutation": "mutations",
            "subscription": "subscriptions",
        }
        for proc in sorted(self.procedures.values(), key=lambda p: p.key):
            out[plural[proc.kind]].append(
                {"key": proc.key, "library": proc.library_scoped}
            )
        return out

    def keys(self) -> set[str]:
        return set(self.procedures)


def _wants_arg(fn: Callable[..., Any], library_scoped: bool) -> bool:
    """Handlers are (node[, library][, arg]); arg is passed iff declared."""
    params = [
        p
        for p in inspect.signature(fn).parameters.values()
        if p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD)
    ]
    return len(params) > (2 if library_scoped else 1)
