"""HTTP host — rspc endpoint + custom-URI file/thumbnail serving.

Parity: two reference pieces in one aiohttp app:
- ref:apps/server/src/main.rs — the Axum host exposing `/rspc` (here:
  `POST /rspc/{key}` with `{library_id?, arg?}` JSON, and
  `GET /rspc/ws` carrying queries/mutations/subscriptions over
  websocket frames like rspc's ws transport);
- ref:core/src/custom_uri/mod.rs:152-190 — `/spacedrive/thumbnail/
  <namespace>/<shard>/<cas_id>.webp` (traversal-guarded) and
  `/spacedrive/file/<library_id>/<location_id>/<path…>` with
  range-aware serving + mime sniffing (serve_file.rs; mod.rs:390).
"""

from __future__ import annotations

import asyncio
import json
import logging
import mimetypes
import os
import uuid
from typing import Any

from aiohttp import WSMsgType, web

from ..files.isolated_path import full_path_from_db_row
from .router import Router, RspcError

logger = logging.getLogger(__name__)

CHUNK = 256 * 1024


def _json_default(o: Any) -> Any:
    if isinstance(o, bytes):
        return o.hex()
    if isinstance(o, uuid.UUID):
        return str(o)
    if hasattr(o, "to_wire"):
        return o.to_wire()
    if hasattr(o, "__dict__"):
        return {k: v for k, v in vars(o).items() if not k.startswith("_")}
    return str(o)


def _dumps(obj: Any) -> str:
    return json.dumps(obj, default=_json_default)


class ApiServer:
    def __init__(self, node: Any, router: Router):
        self.node = node
        self.router = router
        self.app = web.Application()
        self.app.add_routes(
            [
                web.post("/rspc/{key}", self._rspc_http),
                web.get("/rspc/ws", self._rspc_ws),
                web.get("/spacedrive/thumbnail/{ns}/{shard}/{name}", self._thumbnail),
                web.get(
                    "/spacedrive/file/{library_id}/{location_id}/{path:.*}",
                    self._file,
                ),
            ]
        )
        self._runner: web.AppRunner | None = None
        self.port: int | None = None

    # --- lifecycle -----------------------------------------------------

    async def start(self, host: str = "127.0.0.1", port: int = 0) -> int:
        self._runner = web.AppRunner(self.app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, host, port)
        await site.start()
        self.port = site._server.sockets[0].getsockname()[1]  # type: ignore[union-attr]
        return self.port

    async def shutdown(self) -> None:
        if self._runner is not None:
            await self._runner.cleanup()
            self._runner = None

    # --- rspc ----------------------------------------------------------

    async def _rspc_http(self, request: web.Request) -> web.Response:
        key = request.match_info["key"]
        try:
            body = await request.json() if request.can_read_body else {}
        except json.JSONDecodeError:
            return web.json_response({"error": "invalid json"}, status=400)
        try:
            result = await self.router.exec(
                self.node, key, body.get("arg"), body.get("library_id")
            )
            return web.json_response({"result": result}, dumps=_dumps)
        except RspcError as e:
            return web.json_response(
                {"error": e.message, "code": e.code}, status=e.code
            )
        except Exception as e:  # surface like rspc's internal error
            logger.exception("procedure %s failed", key)
            return web.json_response({"error": str(e), "code": 500}, status=500)

    async def _rspc_ws(self, request: web.Request) -> web.WebSocketResponse:
        """rspc ws transport: {id, key, arg?, library_id?, type:
        query|mutation|subscriptionAdd|subscriptionRemove}."""
        ws = web.WebSocketResponse()
        await ws.prepare(request)
        subs: dict[str, asyncio.Task] = {}
        try:
            async for msg in ws:
                if msg.type != WSMsgType.TEXT:
                    continue
                try:
                    req = json.loads(msg.data)
                    mid = req.get("id")
                    kind = req.get("type", "query")
                    if kind in ("query", "mutation"):
                        try:
                            result = await self.router.exec(
                                self.node,
                                req["key"],
                                req.get("arg"),
                                req.get("library_id"),
                            )
                            await ws.send_str(
                                _dumps({"id": mid, "result": result})
                            )
                        except RspcError as e:
                            await ws.send_str(
                                _dumps({"id": mid, "error": e.message, "code": e.code})
                            )
                    elif kind == "subscriptionAdd":
                        try:
                            gen = self.router.subscribe(
                                self.node,
                                req["key"],
                                req.get("arg"),
                                req.get("library_id"),
                            )
                        except RspcError as e:
                            await ws.send_str(
                                _dumps({"id": mid, "error": e.message, "code": e.code})
                            )
                            continue

                        async def pump(gen=gen, mid=mid):
                            async for event in gen:
                                await ws.send_str(
                                    _dumps({"id": mid, "event": event})
                                )

                        prev = subs.pop(mid, None)
                        if prev is not None:
                            prev.cancel()  # duplicate id replaces, not orphans
                        subs[mid] = asyncio.ensure_future(pump())
                    elif kind == "subscriptionRemove":
                        task = subs.pop(mid, None)
                        if task is not None:
                            task.cancel()
                except Exception as e:
                    logger.exception("ws message failed")
                    try:
                        await ws.send_str(_dumps({"error": str(e)}))
                    except Exception:
                        break
        finally:
            for task in subs.values():
                task.cancel()
        return ws

    # --- custom uri ----------------------------------------------------

    async def _thumbnail(self, request: web.Request) -> web.StreamResponse:
        """Traversal-guarded webp serving (ref:custom_uri/mod.rs:152-190)."""
        ns = request.match_info["ns"]
        shard = request.match_info["shard"]
        name = request.match_info["name"]
        if not name.endswith(".webp"):
            raise web.HTTPBadRequest(text="not a webp")
        cas_id = name[: -len(".webp")]
        # the guard: every component must be clean hex/uuid-ish, no traversal
        for part in (ns, shard, cas_id):
            if not part or "/" in part or "\\" in part or ".." in part:
                raise web.HTTPBadRequest(text="bad path")
        store = self.node.thumbnailer.store
        path = os.path.join(store.root, ns, shard, name)
        if os.path.commonpath(
            [os.path.abspath(path), os.path.abspath(store.root)]
        ) != os.path.abspath(store.root):
            raise web.HTTPBadRequest(text="bad path")
        if not os.path.isfile(path):
            raise web.HTTPNotFound()
        return web.FileResponse(
            path, headers={"Content-Type": "image/webp", "Cache-Control": "max-age=86400"}
        )

    async def _file(self, request: web.Request) -> web.StreamResponse:
        """Range-aware file serving out of a location
        (ref:custom_uri/serve_file.rs + mod.rs:390 mime sniff)."""
        try:
            lib_id = uuid.UUID(request.match_info["library_id"])
        except ValueError:
            raise web.HTTPBadRequest(text="bad library id")
        lib = self.node.libraries.get(lib_id)
        if lib is None:
            raise web.HTTPNotFound(text="library")
        loc = lib.db.find_one("location", id=int(request.match_info["location_id"]))
        if loc is None:
            raise web.HTTPNotFound(text="location")
        rel = request.match_info["path"]
        full = os.path.abspath(os.path.join(loc["path"], rel))
        loc_root = os.path.abspath(loc["path"])
        if os.path.commonpath([full, loc_root]) != loc_root:
            raise web.HTTPBadRequest(text="bad path")
        if not os.path.isfile(full):
            raise web.HTTPNotFound()
        ctype = mimetypes.guess_type(full)[0] or _sniff_mime(full)
        # FileResponse implements Range (206/Content-Range/416, incl.
        # suffix ranges) correctly — don't re-implement it
        return web.FileResponse(
            full,
            headers={"Content-Type": ctype, "Accept-Ranges": "bytes"},
        )


def _sniff_mime(path: str) -> str:
    """First-bytes sniff fallback (ref:custom_uri/mod.rs:390 infer)."""
    try:
        with open(path, "rb") as f:
            head = f.read(16)
    except OSError:
        return "application/octet-stream"
    if head.startswith(b"\xff\xd8\xff"):
        return "image/jpeg"
    if head.startswith(b"\x89PNG"):
        return "image/png"
    if head.startswith(b"RIFF") and head[8:12] == b"WEBP":
        return "image/webp"
    if head.startswith(b"GIF8"):
        return "image/gif"
    if head[4:8] == b"ftyp":
        return "video/mp4"
    if head.startswith(b"%PDF"):
        return "application/pdf"
    return "application/octet-stream"
