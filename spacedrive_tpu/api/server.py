"""HTTP host — rspc endpoint + custom-URI file/thumbnail serving.

Parity: two reference pieces in one aiohttp app:
- ref:apps/server/src/main.rs — the Axum host exposing `/rspc` (here:
  `POST /rspc/{key}` with `{library_id?, arg?}` JSON, and
  `GET /rspc/ws` carrying queries/mutations/subscriptions over
  websocket frames like rspc's ws transport);
- ref:core/src/custom_uri/mod.rs:152-190 — `/spacedrive/thumbnail/
  <namespace>/<shard>/<cas_id>.webp` (traversal-guarded) and
  `/spacedrive/file/<library_id>/<location_id>/<path…>` with
  range-aware serving + mime sniffing (serve_file.rs; mod.rs:390).
"""

from __future__ import annotations

import asyncio
import json
import logging
import mimetypes
import os
import re
import time
import uuid
from typing import Any

from aiohttp import WSMsgType, web

from .. import telemetry
from ..files.isolated_path import full_path_from_db_row
from ..serve import BACKGROUND, CONTROL, INTERACTIVE, Shed, runtime_for
from ..serve.gate import observe_request_seconds
from .router import Router, RspcError

logger = logging.getLogger(__name__)

CHUNK = 256 * 1024

#: sentinel class for routes whose admission happens per-procedure
#: inside Router.exec (the rspc transports) — the route-level
#: middleware must not double-admit them
RSPC_DEFERRED = "rspc"

# Host values a browser can only produce for a genuinely-local page.
# Anything else on this localhost-bound server means DNS rebinding: a
# hostile page resolving its own domain to 127.0.0.1 to read
# /spacedrive/local and the ephemeralFiles.* procedures cross-origin.
LOCAL_HOSTNAMES = frozenset({"127.0.0.1", "localhost", "::1"})


def _json_default(o: Any) -> Any:
    if isinstance(o, bytes):
        return o.hex()
    if isinstance(o, uuid.UUID):
        return str(o)
    if hasattr(o, "to_wire"):
        return o.to_wire()
    if hasattr(o, "__dict__"):
        return {k: v for k, v in vars(o).items() if not k.startswith("_")}
    return str(o)


def _dumps(obj: Any) -> str:
    return json.dumps(obj, default=_json_default)


class ApiServer:
    def __init__(self, node: Any, router: Router):
        self.node = node
        self.router = router
        self._allowed_hosts = set(LOCAL_HOSTNAMES)
        self._allow_any_host = False
        self._route_classes: dict[tuple[str, str], str] = {}
        self.app = web.Application(
            middlewares=[self._host_guard, self._admission]
        )
        # every route declares its admission priority class through the
        # _gated seam (sdlint SD015 `ungated-handler` enforces this for
        # new routes); rspc transports defer to per-procedure classes
        self.app.add_routes(
            [
                self._gated(web.get("/", self._index), INTERACTIVE),
                self._gated(web.get("/metrics", self._metrics), CONTROL),
                self._gated(web.get("/trace", self._trace), BACKGROUND),
                self._gated(web.get("/attrib", self._attrib), BACKGROUND),
                self._gated(web.get("/profile", self._profile), BACKGROUND),
                self._gated(web.get("/tenants", self._tenants), BACKGROUND),
                self._gated(web.get("/health", self._health), CONTROL),
                self._gated(web.get("/mesh", self._mesh), INTERACTIVE),
                self._gated(web.get("/search", self._search), INTERACTIVE),
                self._gated(
                    web.get("/static/{path:.*}", self._static), INTERACTIVE
                ),
                self._gated(
                    web.get("/rspc/client.js", self._client_js), INTERACTIVE
                ),
                self._gated(
                    web.get("/rspc/manifest", self._manifest), INTERACTIVE
                ),
                self._gated(
                    web.post("/rspc/{key}", self._rspc_http), RSPC_DEFERRED
                ),
                self._gated(web.get("/rspc/ws", self._rspc_ws), RSPC_DEFERRED),
                self._gated(
                    web.get(
                        "/spacedrive/thumbnail/{ns}/{shard}/{name}",
                        self._thumbnail,
                    ),
                    INTERACTIVE,
                ),
                self._gated(
                    web.get(
                        "/spacedrive/file/{library_id}/{location_id}/{path:.*}",
                        self._file,
                    ),
                    INTERACTIVE,
                ),
                self._gated(
                    web.get("/spacedrive/local", self._local_file), INTERACTIVE
                ),
            ]
        )
        self._runner: web.AppRunner | None = None
        self._client_js_text: str | None = None
        self.port: int | None = None

    # --- lifecycle -----------------------------------------------------

    async def start(self, host: str = "127.0.0.1", port: int = 0) -> int:
        if host in ("", "0.0.0.0", "::"):
            # a DELIBERATE wildcard bind is LAN exposure: clients
            # legitimately arrive under names we cannot enumerate, so
            # the rebinding guard (scoped to the default localhost
            # bind, ADVICE r5) stands down rather than 403 everyone
            self._allow_any_host = True
        else:
            # explicit non-local binds stay reachable by their own name
            self._allowed_hosts.add(host)
        # no access log: formatting a log line per request is measurable
        # loop work at explorer-burst rates, and the telemetry layer
        # already counts every request with labels a logger can't match
        self._runner = web.AppRunner(self.app, access_log=None)
        await self._runner.setup()
        site = web.TCPSite(self._runner, host, port)
        await site.start()
        self.port = site._server.sockets[0].getsockname()[1]  # type: ignore[union-attr]
        return self.port

    async def shutdown(self) -> None:
        if self._runner is not None:
            await self._runner.cleanup()
            self._runner = None

    @web.middleware
    async def _host_guard(self, request: web.Request, handler) -> web.StreamResponse:
        """Reject requests whose Host header names anything but this
        machine — closes the DNS-rebinding read path through
        /spacedrive/local and the ephemeralFiles.* procedures
        (ADVICE r5). An absent Host (HTTP/1.0) is local tooling."""
        host = request.headers.get("Host")
        if host and not self._allow_any_host \
                and _hostname_of(host) not in self._allowed_hosts:
            raise web.HTTPForbidden(text="bad host")
        return await handler(request)

    def _gated(self, route: web.RouteDef, klass: str) -> web.RouteDef:
        """Declare a route's admission priority class (the serve-layer
        seam; sdlint SD015). Returns the route unchanged — the class
        lands in the table the admission middleware resolves against,
        keyed by the CANONICAL path (aiohttp strips the regex from
        ``{name:regex}`` params, so the table must too — otherwise
        pattern routes like ``/static/{path:.*}`` silently run
        ungated)."""
        canonical = re.sub(r"\{([^}:]+):[^}]*\}", r"{\1}", route.path)
        self._route_classes[(route.method, canonical)] = klass
        return route

    @web.middleware
    async def _admission(
        self, request: web.Request, handler
    ) -> web.StreamResponse:
        """Admission-gate every routed request under its declared
        priority class. Shed → 429/``SHED`` + Retry-After, fast. The
        rspc transports pass through — Router.exec admits them under
        the procedure's own class. No serve runtime = the ungated
        pre-serve path, byte-identical."""
        serve = runtime_for(self.node)
        if serve is None:
            return await handler(request)
        resource = getattr(request.match_info.route, "resource", None)
        canonical = resource.canonical if resource is not None else None
        klass = self._route_classes.get((request.method, canonical or ""))
        if klass is None or klass == RSPC_DEFERRED:
            return await handler(request)
        try:
            async with serve.gate.admit(klass, key=canonical or request.path):
                t0 = time.perf_counter()
                try:
                    return await handler(request)
                finally:
                    # admitted request wall time per class — the
                    # interactive series is the interactive_p99 SLO
                    # input (telemetry/slo.py)
                    observe_request_seconds(
                        klass, time.perf_counter() - t0
                    )
        except Shed as e:
            return _shed_response(e)

    async def _metrics(self, _request: web.Request) -> web.Response:
        """Prometheus scrape endpoint over the process registry."""
        return web.Response(
            text=telemetry.render(),
            content_type="text/plain",
            charset="utf-8",
            headers={"X-Prometheus-Format": "0.0.4"},
        )

    async def _trace(self, request: web.Request) -> web.Response:
        """Chrome-trace-event JSON of the completed-span ring — download
        and load straight into Perfetto (ui.perfetto.dev) or
        chrome://tracing. `?trace_id=<hex>` filters to one trace."""
        return web.json_response(
            telemetry.trace_export(request.query.get("trace_id") or None),
            headers={"Content-Disposition": "inline; filename=sd-trace.json"},
        )

    async def _attrib(self, request: web.Request) -> web.Response:
        """Critical-path attribution for one distributed trace (default:
        the last completed pass) — device / host_cpu / link /
        queue_wait / gap bucket split plus the critical-path segments
        (telemetry/attrib.py). `?trace_id=<hex>` picks a trace,
        `?refresh=1` bypasses the per-trace report cache and re-pulls
        peers. Cached through the serve meta cache so dashboard polls
        cost one mesh pull per TTL window."""
        from ..telemetry import attrib as _attrib_mod

        trace_id = request.query.get("trace_id") or None
        refresh = request.query.get("refresh") == "1"

        async def load() -> Any:
            return await _attrib_mod.assemble(
                self.node, trace_id, refresh=refresh
            )

        serve = runtime_for(self.node)
        if serve is None or refresh:
            doc = await load()
        else:
            result = await serve.meta.get(
                ("attrib", trace_id or ""),
                load,
                ttl_s=serve.policy.mesh_ttl_s,
                stale_ok=serve.gate.in_brownout(),
            )
            doc = result.value
        return web.json_response(doc, dumps=_dumps)

    async def _tenants(self, request: web.Request) -> web.Response:
        """Per-tenant accounting snapshot (telemetry/tenants.py): the
        full space-saving sketch read — per-surface totals, resident
        top-K with error bounds and latency buckets, fairness index,
        dominant share. Tenant keys are blake2b hashes; raw library/
        instance UUIDs never appear here. Admission-gated BACKGROUND
        like the other observability reads."""
        from ..telemetry import tenants as _tenants_mod

        return web.json_response(_tenants_mod.snapshot(), dumps=_dumps)

    async def _profile(self, request: web.Request) -> web.Response:
        """The continuous host profiler (telemetry/sampler.py):
        collapsed-stack frame groups, on-CPU vs GIL-wait split, and
        triggered deep-capture windows. `?format=folded` serves
        flamegraph.pl collapsed-stack text (pipe into flamegraph.pl or
        speedscope); `?mesh=1` also pulls every reachable peer's
        profile over the TELEMETRY wire (partial on pull failures,
        never blocking). BACKGROUND class — the mesh leg dials peers,
        so it must never ride the unsheddable control class."""
        from ..telemetry import sampler as _sampler_mod

        if request.query.get("format") == "folded":
            return web.Response(
                text=_sampler_mod.SAMPLER.folded(),
                content_type="text/plain",
                charset="utf-8",
            )
        if request.query.get("mesh") == "1":
            return web.json_response(
                await _sampler_mod.mesh_profile(self.node), dumps=_dumps
            )
        return web.json_response(
            _sampler_mod.SAMPLER.profile(), dumps=_dumps
        )

    async def _health(self, _request: web.Request) -> web.Response:
        """Per-subsystem → per-node health rollup (telemetry.health).
        503 when unhealthy so load balancers / probes can act on the
        status code alone; the JSON body carries the verdicts."""
        from ..telemetry import health as _health_mod

        verdict = _health_mod.evaluate(self.node)
        return web.json_response(
            verdict,
            status=503 if verdict["status"] == _health_mod.UNHEALTHY else 200,
            dumps=_dumps,
        )

    async def _mesh(self, request: web.Request) -> web.Response:
        """Mesh-wide telemetry: this node's snapshot + the federation
        cache's per-peer view (freshness-marked). Pull-through — the
        request refreshes peers whose snapshot aged past the cache's
        refresh interval; `?refresh=0` reads the cache as-is,
        `?force=1` re-pulls everyone. N concurrent dashboard polls
        collapse onto one refresh + one snapshot computation through
        the serve cache's single-flight (federation.mesh_status_cached)."""
        from ..telemetry.federation import mesh_status_cached

        return web.json_response(
            await mesh_status_cached(
                self.node,
                refresh=request.query.get("refresh") != "0",
                force=request.query.get("force") == "1",
            ),
            dumps=_dumps,
        )

    async def _index(self, _request: web.Request) -> web.FileResponse:
        """The explorer web UI (role parity: ref:interface/ + apps/web)."""
        return web.FileResponse(
            os.path.join(os.path.dirname(__file__), "static", "explorer.html"),
            headers={"Content-Type": "text/html; charset=utf-8"},
        )

    async def _static(self, request: web.Request) -> web.StreamResponse:
        """Explorer assets (traversal-guarded; .js/.css/.json only)."""
        root = os.path.abspath(os.path.join(os.path.dirname(__file__), "static"))
        rel = request.match_info["path"]
        full = os.path.abspath(os.path.join(root, rel))
        if os.path.commonpath([full, root]) != root:
            raise web.HTTPBadRequest(text="bad path")
        if not os.path.isfile(full):
            raise web.HTTPNotFound()
        ctype = {
            ".js": "application/javascript",
            ".css": "text/css",
            ".html": "text/html; charset=utf-8",
            ".json": "application/json",  # i18n catalogs
        }.get(os.path.splitext(full)[1])
        if ctype is None:
            raise web.HTTPNotFound()
        return web.FileResponse(full, headers={"Content-Type": ctype})

    async def _client_js(self, _request: web.Request) -> web.Response:
        """The generated JS client (ref:packages/client/src/core.ts is
        the same artifact, generated from the Rust router). The router
        is fixed after mount, so generate once and cache."""
        if self._client_js_text is None:
            from .client_gen import generate_js

            self._client_js_text = generate_js(self.router.manifest())
        return web.Response(
            text=self._client_js_text,
            content_type="application/javascript",
        )

    async def _manifest(self, _request: web.Request) -> web.Response:
        return web.json_response(self.router.manifest())

    async def _search(self, request: web.Request) -> web.Response:
        """`GET /search?library_id=…&q=…[&take=N]` — the semantic-search
        plane's plain-HTTP face (curl/dashboards; rspc clients use the
        `search.semantic` procedure). Rides the exact same router
        procedure and therefore the same serve byte-cache and tag
        invalidation as the POST transport."""
        lib_id = request.query.get("library_id")
        q = request.query.get("q", "")
        if not lib_id or not q:
            return web.json_response(
                {"error": "library_id and q are required"}, status=400
            )
        arg: dict[str, Any] = {"query": q}
        if "take" in request.query:
            try:
                arg["take"] = int(request.query["take"])
            except ValueError:
                return web.json_response(
                    {"error": "take must be an integer"}, status=400
                )
        try:
            serve = runtime_for(self.node)
            if serve is not None:
                from ..serve import canonical_library_id, query_cache_key

                async def load_bytes() -> bytes:
                    result = await self.router.exec(
                        self.node, "search.semantic", arg, lib_id
                    )
                    return _dumps({"result": result}).encode()

                lib_key = canonical_library_id(lib_id)
                res = await serve.queries.get(
                    ("http",) + query_cache_key("search.semantic", lib_id, arg),
                    load_bytes,
                    tags=(("lib", lib_key), ("q", "search.semantic", lib_key)),
                    stale_ok=serve.gate.in_brownout(),
                )
                if res.state != "miss":
                    # see _rspc_http: hit attribution for the byte layer
                    from ..telemetry import tenants as _tenants_mod

                    _tenants_mod.observe("cache_hit", lib_key)
                return web.Response(
                    body=res.value,
                    content_type="application/json",
                    headers={"X-SD-Cache": res.state},
                )
            result = await self.router.exec(
                self.node, "search.semantic", arg, lib_id
            )
            return web.json_response({"result": result}, dumps=_dumps)
        except RspcError as e:
            return web.json_response(
                {"error": e.message, "code": e.code}, status=e.code
            )

    # --- rspc ----------------------------------------------------------

    async def _rspc_http(self, request: web.Request) -> web.Response:
        key = request.match_info["key"]
        try:
            body = await request.json() if request.can_read_body else {}
        except json.JSONDecodeError:
            return web.json_response({"error": "invalid json"}, status=400)
        try:
            serve = runtime_for(self.node)
            lib_id = body.get("library_id")
            if serve is not None and lib_id is not None:
                from ..serve import (
                    CACHEABLE_QUERIES,
                    canonical_library_id,
                    query_cache_key,
                )

                if key in CACHEABLE_QUERIES:
                    # byte-level response cache: a hot explorer query is
                    # served as pre-encoded bytes — under a stampede the
                    # loop pays one dict lookup + send per request
                    # instead of re-serializing 50 rows each time. Rides
                    # the same tags (and therefore the same local+sync
                    # invalidation) as the router's object cache.
                    arg = body.get("arg")

                    async def load_bytes() -> bytes:
                        result = await self.router.exec(
                            self.node, key, arg, lib_id
                        )
                        return _dumps({"result": result}).encode()

                    lib_key = canonical_library_id(lib_id)
                    res = await serve.queries.get(
                        ("http",) + query_cache_key(key, lib_id, arg),
                        load_bytes,
                        tags=(("lib", lib_key), ("q", key, lib_key)),
                        stale_ok=serve.gate.in_brownout(),
                    )
                    if res.state != "miss":
                        # byte-cache hits never reach the router (that's
                        # the point), so the tenant attribution the
                        # object-cache tap would have made happens here;
                        # misses fall through to load_bytes and tap once
                        # inside the router's cache
                        from ..telemetry import tenants as _tenants_mod

                        _tenants_mod.observe("cache_hit", lib_key)
                    return web.Response(
                        body=res.value,
                        content_type="application/json",
                        headers={"X-SD-Cache": res.state},
                    )
            result = await self.router.exec(
                self.node, key, body.get("arg"), body.get("library_id")
            )
            return web.json_response({"result": result}, dumps=_dumps)
        except RspcError as e:
            headers = {}
            retry_after = getattr(e, "retry_after_s", None)
            if e.code == 429 and retry_after is not None:
                # admission-gate shed: tell well-behaved clients when
                # to come back instead of letting them hammer
                headers["Retry-After"] = str(max(1, round(retry_after)))
            return web.json_response(
                {"error": e.message, "code": e.code}, status=e.code,
                headers=headers,
            )
        except Exception as e:  # surface like rspc's internal error
            logger.exception("procedure %s failed", key)
            return web.json_response({"error": str(e), "code": 500}, status=500)

    async def _rspc_ws(self, request: web.Request) -> web.WebSocketResponse:
        """rspc ws transport: {id, key, arg?, library_id?, type:
        query|mutation|subscriptionAdd|subscriptionRemove}."""
        ws = web.WebSocketResponse()
        await ws.prepare(request)
        subs: dict[str, asyncio.Task] = {}
        try:
            async for msg in ws:
                if msg.type != WSMsgType.TEXT:
                    continue
                try:
                    req = json.loads(msg.data)
                    mid = req.get("id")
                    kind = req.get("type", "query")
                    if kind in ("query", "mutation"):
                        try:
                            result = await self.router.exec(
                                self.node,
                                req["key"],
                                req.get("arg"),
                                req.get("library_id"),
                            )
                            await ws.send_str(
                                _dumps({"id": mid, "result": result})
                            )
                        except RspcError as e:
                            await ws.send_str(
                                _dumps({"id": mid, "error": e.message, "code": e.code})
                            )
                    elif kind == "subscriptionAdd":
                        try:
                            gen = self.router.subscribe(
                                self.node,
                                req["key"],
                                req.get("arg"),
                                req.get("library_id"),
                            )
                        except RspcError as e:
                            await ws.send_str(
                                _dumps({"id": mid, "error": e.message, "code": e.code})
                            )
                            continue

                        async def pump(gen=gen, mid=mid):
                            async for event in gen:
                                await ws.send_str(
                                    _dumps({"id": mid, "event": event})
                                )

                        prev = subs.pop(mid, None)
                        if prev is not None:
                            prev.cancel()  # duplicate id replaces, not orphans
                        subs[mid] = asyncio.ensure_future(pump())
                    elif kind == "subscriptionRemove":
                        task = subs.pop(mid, None)
                        if task is not None:
                            task.cancel()
                except Exception as e:
                    logger.exception("ws message failed")
                    try:
                        await ws.send_str(_dumps({"error": str(e)}))
                    except Exception:
                        break
        finally:
            for task in subs.values():
                task.cancel()
        return ws

    # --- custom uri ----------------------------------------------------

    async def _thumbnail(self, request: web.Request) -> web.StreamResponse:
        """Traversal-guarded webp serving (ref:custom_uri/mod.rs:152-190)."""
        ns = request.match_info["ns"]
        shard = request.match_info["shard"]
        name = request.match_info["name"]
        if not name.endswith(".webp"):
            raise web.HTTPBadRequest(text="not a webp")
        cas_id = name[: -len(".webp")]
        # the guard: every component must be clean hex/uuid-ish, no traversal
        for part in (ns, shard, cas_id):
            if not part or "/" in part or "\\" in part or ".." in part:
                raise web.HTTPBadRequest(text="bad path")
        store = self.node.thumbnailer.store
        path = os.path.join(store.root, ns, shard, name)
        if os.path.commonpath(
            [os.path.abspath(path), os.path.abspath(store.root)]
        ) != os.path.abspath(store.root):
            raise web.HTTPBadRequest(text="bad path")
        serve = runtime_for(self.node)
        if serve is not None:
            # byte cache: thumbnails are content-addressed (the webp for
            # a cas_id never changes), so a miss loads once and a hot
            # explorer grid stops touching the disk. Absent files are
            # NOT cached — a freshly generated thumbnail appears on the
            # next request.
            async def load() -> bytes:
                def read() -> bytes:
                    with open(path, "rb") as f:
                        return f.read()

                try:
                    return await asyncio.to_thread(read)
                except OSError:
                    raise web.HTTPNotFound()

            # ns is the owning library's id string, so thumb reads
            # attribute to the tenant whose grid is hot
            result = await serve.thumbs.get(
                (ns, shard, name), load, weigh=len, tenant=ns,
            )
            return web.Response(
                body=result.value,
                headers={
                    "Content-Type": "image/webp",
                    "Cache-Control": "max-age=86400",
                    "X-SD-Cache": result.state,
                },
            )
        if not os.path.isfile(path):
            raise web.HTTPNotFound()
        return web.FileResponse(
            path, headers={"Content-Type": "image/webp", "Cache-Control": "max-age=86400"}
        )

    async def _file(self, request: web.Request) -> web.StreamResponse:
        """Range-aware file serving out of a location
        (ref:custom_uri/serve_file.rs + mod.rs:390 mime sniff)."""
        try:
            lib_id = uuid.UUID(request.match_info["library_id"])
        except ValueError:
            raise web.HTTPBadRequest(text="bad library id")
        lib = self.node.libraries.get(lib_id)
        if lib is None:
            raise web.HTTPNotFound(text="library")
        loc = lib.db.find_one("location", id=int(request.match_info["location_id"]))
        if loc is None:
            raise web.HTTPNotFound(text="location")
        rel = request.match_info["path"]
        full = os.path.abspath(os.path.join(loc["path"], rel))
        loc_root = os.path.abspath(loc["path"])
        if os.path.commonpath([full, loc_root]) != loc_root:
            raise web.HTTPBadRequest(text="bad path")
        if not os.path.isfile(full):
            # the file may live on another node: ServeFrom::Remote
            # (ref:custom_uri/mod.rs:240-268 streams it over P2P)
            remote = await self._serve_remote(request, lib, loc, rel)
            if remote is not None:
                return remote
            raise web.HTTPNotFound()
        ctype = mimetypes.guess_type(full)[0] or _sniff_mime(full)
        # FileResponse implements Range (206/Content-Range/416, incl.
        # suffix ranges) correctly — don't re-implement it
        return web.FileResponse(
            full,
            headers={"Content-Type": ctype, "Accept-Ranges": "bytes"},
        )

    async def _local_file(self, request: web.Request) -> web.StreamResponse:
        """Range-aware serving of a NON-INDEXED local path — the
        ephemeral browse's preview source (the reference's custom URI
        serves ephemeral paths the same way for ephemeral.tsx). Trust
        model: identical to the ephemeralFiles.* procedures on the same
        localhost API (which already list/rename/delete arbitrary local
        paths); this route only adds read."""
        raw = request.query.get("path", "")
        full = os.path.abspath(raw)
        if not raw or not os.path.isabs(raw):
            raise web.HTTPBadRequest(text="absolute path required")
        if not os.path.isfile(full):
            raise web.HTTPNotFound()
        ctype = mimetypes.guess_type(full)[0] or _sniff_mime(full)
        return web.FileResponse(
            full,
            headers={"Content-Type": ctype, "Accept-Ranges": "bytes"},
        )


    async def _serve_remote(
        self, request: web.Request, lib: Any, loc: dict[str, Any], rel: str
    ) -> web.StreamResponse | None:
        """Pull a file owned by another instance over P2P and serve it
        (ref:custom_uri/mod.rs ServeFrom::Remote)."""
        import io

        from ..files.isolated_path import IsolatedFilePathData
        from ..node.config import BackendFeature

        p2p = self.node.p2p
        if p2p is None or not self.node.is_feature_enabled(
            BackendFeature.FILES_OVER_P2P
        ):
            return None
        iso = IsolatedFilePathData.from_relative_str(
            loc["id"], rel.replace(os.sep, "/"), False
        )
        row = lib.db.find_one(
            "file_path",
            location_id=loc["id"],
            materialized_path=iso.materialized_path,
            name=iso.name,
            extension=iso.extension,
        )
        if row is None:
            return None
        # owner instance first when known (instance_id is a local-only
        # cache, ref:schema.prisma:126), then every other library peer
        peers = []
        if loc.get("instance_id") is not None:
            inst = lib.db.find_one("instance", id=loc["instance_id"])
            if inst is not None:
                peer = p2p.peer_for_instance(uuid.UUID(bytes=inst["pub_id"]))
                if peer is not None:
                    peers.append(peer)
        for peer in p2p.peers_for_library(lib.id):
            if peer not in peers:
                peers.append(peer)
        from ..p2p.block import Range as BlockRange
        from ..p2p.operations import FILE_POLICY, request_file

        # honor HTTP Range: fetch only the requested span over P2P
        from ..db.database import blob_u64

        total = blob_u64(row.get("size_in_bytes_bytes")) or 0
        try:
            rng = request.http_range
            start, stop = rng.start, rng.stop
        except ValueError:
            raise web.HTTPRequestRangeNotSatisfiable()
        ranged = start is not None or stop is not None
        if ranged:
            start = start if start is not None else 0
            if start < 0:  # suffix range bytes=-N
                start = max(0, total + start)
            stop = min(stop, total) if stop is not None else total
            if total and start >= total:
                raise web.HTTPRequestRangeNotSatisfiable(
                    headers={"Content-Range": f"bytes */{total}"}
                )
            block_range = BlockRange(start, stop)
        else:
            block_range = BlockRange()

        ctype = mimetypes.guess_type(rel)[0] or "application/octet-stream"
        for peer in peers:
            sink = _StreamSink()
            # single-shot policy: the breaker fast-fails a gone peer so
            # the fallthrough tries the next one without a dial timeout
            fetch = asyncio.ensure_future(
                FILE_POLICY.call(
                    str(peer.identity),
                    lambda peer=peer, sink=sink: request_file(
                        p2p.p2p, peer.identity, lib.id,
                        uuid.UUID(bytes=row["pub_id"]), sink,
                        range=block_range,
                    ),
                )
            )
            try:
                # wait for the first block before committing a response,
                # so a failed peer falls through to the next one
                first = await sink.next_chunk(fetch)
            except Exception as e:
                logger.debug("remote fetch from %s failed: %s", peer.identity, e)
                continue
            if ranged:
                resp = web.StreamResponse(
                    status=206,
                    headers={
                        "Content-Type": ctype,
                        "Content-Range": f"bytes {start}-{stop - 1}/{total}",
                        "Accept-Ranges": "bytes",
                    },
                )
            else:
                resp = web.StreamResponse(
                    headers={"Content-Type": ctype, "Accept-Ranges": "bytes"}
                )
            await resp.prepare(request)
            if first is not None:
                await resp.write(first)
                while (chunk := await sink.next_chunk(fetch)) is not None:
                    await resp.write(chunk)
            await fetch
            await resp.write_eof()
            return resp
        return None


class _StreamSink:
    """File-like sink bridging Transfer.receive's synchronous writes
    into an async chunk stream (blocks arrive on the same loop)."""

    def __init__(self) -> None:
        self._chunks: list[bytes] = []
        self._event = asyncio.Event()

    def write(self, data: bytes) -> None:
        self._chunks.append(data)
        self._event.set()

    async def next_chunk(self, fetch: "asyncio.Future") -> bytes | None:
        """Next block, or None when the transfer completed; re-raises
        the fetch task's error (incl. before the first block)."""
        while not self._chunks:
            if fetch.done():
                fetch.result()  # raises on failure
                return None
            self._event.clear()
            done, _pending = await asyncio.wait(
                [fetch, asyncio.ensure_future(self._event.wait())],
                return_when=asyncio.FIRST_COMPLETED,
            )
            for task in _pending:
                if task is not fetch:
                    task.cancel()
        return self._chunks.pop(0)


def _shed_response(e: Shed) -> web.Response:
    """The fast-fail shed answer: 429, machine-readable ``SHED`` body,
    Retry-After so clients back off instead of retrying hot."""
    return web.json_response(
        {"error": "SHED", "class": e.klass, "reason": e.reason},
        status=429,
        headers={"Retry-After": str(max(1, round(e.retry_after_s)))},
    )


def _hostname_of(host: str) -> str:
    """Hostname from a Host header value: strips :port, unwraps IPv6
    brackets, lowercases, drops a trailing FQDN dot."""
    host = host.strip().lower()
    if host.startswith("["):  # [::1]:port
        return host.partition("]")[0].lstrip("[")
    return host.rsplit(":", 1)[0].rstrip(".") if host else host


def _sniff_mime(path: str) -> str:
    """First-bytes sniff fallback (ref:custom_uri/mod.rs:390 infer)."""
    try:
        with open(path, "rb") as f:
            head = f.read(16)
    except OSError:
        return "application/octet-stream"
    if head.startswith(b"\xff\xd8\xff"):
        return "image/jpeg"
    if head.startswith(b"\x89PNG"):
        return "image/png"
    if head.startswith(b"RIFF") and head[8:12] == b"WEBP":
        return "image/webp"
    if head.startswith(b"GIF8"):
        return "image/gif"
    if head[4:8] == b"ftyp":
        return "video/mp4"
    if head.startswith(b"%PDF"):
        return "application/pdf"
    return "application/octet-stream"
