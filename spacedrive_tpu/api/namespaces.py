"""api::mount() — every procedure namespace.

Parity: ref:core/src/api/mod.rs:197-218 — the namespace list mirrors
the reference router merge order: buildInfo/nodeState root procedures,
then library, locations (incl. indexer rules), files, ephemeralFiles,
jobs, search (+ saved searches), tags, labels, sync, cloud, p2p, nodes,
volumes, preferences, notifications, backups, auth, models,
invalidation. Handlers are (node[, library][, arg]) per router.py;
mutations fire `invalidate_query` exactly where the reference does.
"""

from __future__ import annotations

import asyncio
import os
import uuid
from concurrent.futures import ThreadPoolExecutor
from typing import Any, AsyncIterator

from ..db.database import blob_u64, new_pub_id, now_iso
from ..node.config import BackendFeature
from ..node.preferences import read_preferences, write_preferences
from ..node.statistics import get_statistics, update_statistics
from ..node.volumes import get_volumes, save_volumes
from ..node.notifications import Notifications
from .cache import normalise, normalise_one
from .invalidate import install_registry, invalidate_query
from .router import CoreEventKind, Router, RspcError
from .search import search_objects, search_paths, search_semantic

VERSION = "0.1.0"


def mount() -> Router:
    """Build the full router (ref:api/mod.rs:124 `mount`)."""
    r = Router()
    _root(r)
    _library(r)
    _locations(r)
    _files(r)
    _ephemeral(r)
    _jobs(r)
    _search(r)
    _cloud(r)
    _tags(r)
    _spaces(r)
    _albums(r)
    _labels(r)
    _sync(r)
    _p2p(r)
    _nodes(r)
    _volumes(r)
    _keys(r)
    _preferences(r)
    _notifications(r)
    _backups(r)
    _auth(r)
    _models(r)
    _telemetry(r)
    _invalidation(r)
    install_registry(r)
    return r


# --- root ----------------------------------------------------------------


def _root(r: Router) -> None:
    @r.query("buildInfo")
    def build_info(node):
        return {"version": VERSION, "commit": "tpu-native"}

    @r.query("nodeState")
    def node_state(node):
        from ..node.hardware import accelerators, hardware_model

        cfg = node.config.config
        accels = accelerators()
        return {
            "id": str(cfg.id),
            "name": cfg.name,
            "identity": str(cfg.identity.to_remote_identity()),
            "data_path": node.data_dir,
            "p2p": cfg.p2p.to_dict(),
            "features": [f.value for f in cfg.features],
            "hardware_model": hardware_model(),
            "device_model": accels[0]["kind"] if accels else "cpu",
            "accelerators": accels,
            "image_labeler_version": cfg.image_labeler_version,
            "thumbnailer_background_percentage":
                node.thumbnailer.background_percentage
                if node.thumbnailer else 50,
        }

    @r.mutation("toggleFeatureFlag")
    def toggle_feature(node, arg):
        feature = BackendFeature(arg["feature"])
        node.toggle_feature(feature, bool(arg["enabled"]))
        invalidate_query(node, "nodeState")
        return node.is_feature_enabled(feature)


# --- library -------------------------------------------------------------


def _library(r: Router) -> None:
    @r.query("library.list")
    def list_libraries(node):
        return [
            {
                "uuid": str(lib.id),
                "config": lib.config.to_dict(),
                "instance_id": lib.config.instance_id,
                "instance_public_key": str(lib.instance_uuid),
            }
            for lib in node.libraries.libraries.values()
        ]

    @r.query("library.statistics", library=True)
    def statistics(node, library):
        update_statistics(library.db, node.thumbnailer.data_dir)
        return get_statistics(library.db)

    @r.query("library.kindStatistics", library=True)
    def kind_statistics(node, library):
        """Per-ObjectKind object counts + byte totals for the overview
        page (ref:core/src/api/libraries.rs:132 `kindStatistics`; the
        reference leaves total_bytes at "0" — ours is real)."""
        from ..db.database import blob_u64
        from ..files.kind import ObjectKind

        counts = {
            row["kind"]: row["count"]
            for row in library.db.query(
                "SELECT kind, COUNT(*) AS count FROM object "
                "WHERE kind IS NOT NULL GROUP BY kind"
            )
        }
        # sizes live only as LE u64 blobs (schema parity) — aggregate
        # host-side; one pass over file_path, same cost class as
        # update_statistics
        totals: dict[int, int] = {}
        for row in library.db.query(
            "SELECT o.kind AS kind, fp.size_in_bytes_bytes AS size "
            "FROM file_path fp JOIN object o ON o.id = fp.object_id "
            "WHERE o.kind IS NOT NULL"
        ):
            totals[row["kind"]] = (
                totals.get(row["kind"], 0) + (blob_u64(row["size"]) or 0)
            )

        def kind_name(k: int) -> str:
            try:
                return ObjectKind(k).name
            except ValueError:
                return f"Kind{k}"

        return {
            "statistics": sorted(
                (
                    {"kind": k, "name": kind_name(k), "count": c,
                     "total_bytes": str(totals.get(k, 0))}
                    for k, c in counts.items()
                ),
                key=lambda s: -s["count"],
            )
        }

    @r.mutation("library.create")
    async def create(node, arg):
        lib = await node.create_library(
            arg["name"], arg.get("description", "")
        )
        invalidate_query(node, "library.list")
        return {"uuid": str(lib.id), "config": lib.config.to_dict()}

    @r.mutation("library.edit")
    def edit(node, arg):
        lib = node.libraries.get(uuid.UUID(arg["id"]))
        if lib is None:
            raise RspcError.not_found("library")
        if "name" in arg:
            lib.config.name = arg["name"]
        if "description" in arg:
            lib.config.description = arg["description"]
        node.libraries.save_config(lib)
        invalidate_query(node, "library.list")
        return None

    @r.mutation("library.delete")
    async def delete(node, arg):
        lib_id = uuid.UUID(arg if isinstance(arg, str) else arg["id"])
        await node.close_library(lib_id)  # stop actors/jobs before rm
        node.libraries.delete(lib_id)
        invalidate_query(node, "library.list")
        return None


# --- locations -----------------------------------------------------------


# reachability probes get their own tiny pool (see _with_online): a
# hung mount must never occupy the shared default executor
_PROBE_POOL = ThreadPoolExecutor(max_workers=2,
                                 thread_name_prefix="loc-probe")


def _locations(r: Router) -> None:
    from ..location.indexer.rules import (
        IndexerRule,
        RuleKind,
        RulePerKind,
        load_rules_for_location,
    )
    from ..location.locations import (
        LocationCreateArgs,
        light_scan_location,
        relink_location,
        scan_location,
    )

    async def _with_online(library, rows):
        """`online` for LOCALLY-owned locations = the path is reachable
        (unplugged drive / unmounted share); the reference's sidebar
        dot (ref:core/src/location/mod.rs online set + interface
        Sidebar). Rows owned by other instances keep online=None —
        their connectivity rides p2p.state, and a local isdir on a
        remote path would mislabel every synced location offline.
        Probes run on a DEDICATED 2-thread pool with a short timeout: a
        hung network mount must cost this request one bounded probe,
        never the shared to_thread executor the thumbnailer/identifier
        pipelines live on (a blocked isdir per refresh would exhaust it
        node-wide). Timed-out probes report offline — a mount that
        can't answer a stat in a second isn't browsable anyway."""
        rows = [dict(row) for row in rows]
        local = library.config.instance_id
        loop = asyncio.get_running_loop()

        async def probe(path):
            if not path:
                return False
            try:
                return await asyncio.wait_for(
                    loop.run_in_executor(_PROBE_POOL, os.path.isdir, path),
                    timeout=1.0,
                )
            except asyncio.TimeoutError:
                return False

        checks = [
            probe(row.get("path"))
            for row in rows if row.get("instance_id") == local
        ]
        verdicts = iter(await asyncio.gather(*checks))
        for row in rows:
            row["online"] = (next(verdicts)
                             if row.get("instance_id") == local else None)
        return rows

    @r.query("locations.list", library=True)
    async def list_locations(node, library):
        return normalise(
            "location", await _with_online(library, library.db.find("location"))
        )

    @r.query("locations.get", library=True)
    async def get_location(node, library, arg):
        row = library.db.find_one("location", id=int(arg))
        if row is None:
            raise RspcError.not_found("location")
        [row] = await _with_online(library, [row])
        return normalise_one("location", row)

    @r.mutation("locations.create", library=True)
    async def create(node, library, arg):
        args = LocationCreateArgs(
            path=arg["path"],
            name=arg.get("name"),
            dry_run=bool(arg.get("dry_run", False)),
            indexer_rules_ids=arg.get("indexer_rules_ids", []),
        )
        try:
            loc = args.create(library)
        except (NotADirectoryError, PermissionError, FileNotFoundError) as e:
            # a bad/unreadable path is the caller's error, not a crash
            # (ref:api/locations.rs create error variants)
            raise RspcError.bad_request(f"location path: {e}")
        if loc is None:
            return None
        await scan_location(library, loc, node.jobs)
        await node.location_manager.add(library, loc)
        invalidate_query(node, "locations.list", library)
        return loc["id"]

    @r.mutation("locations.update", library=True)
    def update(node, library, arg):
        fields = {
            k: arg[k] for k in ("name", "hidden", "sync_preview_media") if k in arg
        }
        if fields:
            library.db.update("location", {"id": int(arg["id"])}, **fields)
        if "indexer_rules_ids" in arg:
            library.db.delete("indexer_rule_in_location", location_id=int(arg["id"]))
            for rid in arg["indexer_rules_ids"]:
                library.db.insert(
                    "indexer_rule_in_location",
                    location_id=int(arg["id"]),
                    indexer_rule_id=int(rid),
                )
        invalidate_query(node, "locations.list", library)
        return None

    @r.mutation("locations.delete", library=True)
    async def delete(node, library, arg):
        loc_id = int(arg)
        await node.location_manager.remove(library, loc_id)
        with library.db.transaction() as conn:
            conn.execute(
                "DELETE FROM indexer_rule_in_location WHERE location_id = ?",
                (loc_id,),
            )
            conn.execute("DELETE FROM file_path WHERE location_id = ?", (loc_id,))
            conn.execute("DELETE FROM location WHERE id = ?", (loc_id,))
        invalidate_query(node, "locations.list", library)
        return None

    @r.mutation("locations.fullRescan", library=True)
    async def full_rescan(node, library, arg):
        loc = library.db.find_one("location", id=int(arg["location_id"]))
        if loc is None:
            raise RspcError.not_found("location")
        await scan_location(library, loc, node.jobs)
        return None

    @r.mutation("locations.subPathRescan", library=True)
    async def sub_path_rescan(node, library, arg):
        loc = library.db.find_one("location", id=int(arg["location_id"]))
        if loc is None:
            raise RspcError.not_found("location")
        await light_scan_location(library, loc, arg.get("sub_path", "/"), node.jobs)
        return None

    @r.mutation("locations.relink", library=True)
    def relink(node, library, arg):
        return relink_location(library, arg["path"])

    # indexer rules sub-namespace (ref:api/locations.rs indexer_rules)
    @r.query("locations.indexerRules.list", library=True)
    def rules_list(node, library):
        rows = library.db.find("indexer_rule")
        return [
            {
                "id": row["id"],
                "name": row["name"],
                "default": bool(row["default"]),
                "date_created": row["date_created"],
            }
            for row in rows
        ]

    @r.query("locations.indexerRules.listForLocation", library=True)
    def rules_for_location(node, library, arg):
        return [rule.name for rule in load_rules_for_location(library.db, int(arg))]

    @r.mutation("locations.indexerRules.create", library=True)
    def rules_create(node, library, arg):
        kind = RuleKind[arg["kind"]] if isinstance(arg["kind"], str) else RuleKind(arg["kind"])
        rule = IndexerRule(
            pub_id=new_pub_id(),
            name=arg["name"],
            default=False,
            rules=[RulePerKind(kind=kind, params=list(arg["parameters"]))],
        )
        rid = library.db.insert(
            "indexer_rule",
            pub_id=rule.pub_id,
            name=rule.name,
            rules_per_kind=rule.serialize_rules(),
            date_created=now_iso(),
            date_modified=now_iso(),
            **{"default": 0},
        )
        invalidate_query(node, "locations.indexerRules.list", library)
        return rid

    @r.mutation("locations.indexerRules.delete", library=True)
    def rules_delete(node, library, arg):
        row = library.db.find_one("indexer_rule", id=int(arg))
        if row and row["default"]:
            raise RspcError.bad_request("cannot delete a system rule")
        library.db.delete("indexer_rule_in_location", indexer_rule_id=int(arg))
        library.db.delete("indexer_rule", id=int(arg))
        invalidate_query(node, "locations.indexerRules.list", library)
        return None


# --- files ---------------------------------------------------------------


def _files(r: Router) -> None:
    from ..jobs.manager import JobBuilder
    from ..object.fs.copy import FileCopierJob
    from ..object.fs.cut import FileCutterJob
    from ..object.fs.delete import FileDeleterJob
    from ..object.fs.erase import FileEraserJob
    from ..object.validation.job import ObjectValidatorJob

    @r.query("files.get", library=True)
    def get_file(node, library, arg):
        row = library.db.find_one("file_path", id=int(arg["id"]))
        if row is None:
            raise RspcError.not_found("file_path")
        row["size_in_bytes"] = blob_u64(row.pop("size_in_bytes_bytes", None)) or 0
        obj = (
            library.db.find_one("object", id=row["object_id"])
            if row["object_id"]
            else None
        )
        out = normalise_one("file_path", row)
        out["object"] = obj and {k: v.hex() if isinstance(v, bytes) else v for k, v in obj.items()}
        return out

    @r.mutation("files.setNote", library=True)
    def set_note(node, library, arg):
        _object_update(node, library, int(arg["id"]), note=arg.get("note"))
        return None

    @r.query("files.getMediaData", library=True)
    def get_media_data(node, library, arg):
        """Decoded media_data row for an object id — EXIF capture facts
        for images, stream facts for videos (ref:core/src/api/files.rs:126
        `getMediaData`; blobs are msgpack, decoded here for the
        inspector)."""
        import msgpack

        row = library.db.find_one("media_data", object_id=int(arg))
        if row is None:
            return None

        def mp(blob):
            if blob is None:
                return None
            try:
                return msgpack.unpackb(blob)
            except Exception:
                return None

        return {
            "resolution": mp(row["resolution"]),
            "media_date": mp(row["media_date"]),
            "media_location": mp(row["media_location"]),
            "camera_data": mp(row["camera_data"]),
            "artist": row["artist"],
            "description": row["description"],
            "copyright": row["copyright"],
            "exif_version": row["exif_version"],
            "epoch_time": row["epoch_time"],
        }

    @r.mutation("files.setFavorite", library=True)
    def set_favorite(node, library, arg):
        _object_update(node, library, int(arg["id"]), favorite=int(bool(arg["favorite"])))

    @r.mutation("files.updateAccessTime", library=True)
    def update_access_time(node, library, arg):
        """Stamp object.date_accessed = now for the given file_path ids
        (ref:core/src/api/files.rs:298 `updateAccessTime`; the explorer
        calls it on open/preview and the recents route orders by it).
        One timestamp, one transaction, one invalidation for the whole
        batch; ids without an identified object are skipped — access
        stamping is best-effort, like the reference's find_many."""
        from datetime import datetime, timezone

        now = datetime.now(timezone.utc).isoformat()
        object_ids: list[int] = []
        for fp_id in arg["ids"]:
            row = library.db.find_one("file_path", id=int(fp_id))
            if row and row["object_id"]:
                object_ids.append(row["object_id"])
        if not object_ids:
            return None
        ops = []
        for oid in object_ids:
            if pub := _object_pub(library, oid):
                ops.append(library.sync.shared_update(
                    "object", pub, "date_accessed", now))

        def writes(conn):
            conn.execute(
                "UPDATE object SET date_accessed = ? "
                f"WHERE id IN ({','.join('?' * len(object_ids))})",
                (now, *object_ids),
            )

        library.sync.write_ops(ops, db_writes=writes)
        # search.paths too: the recents/favorites routes render object
        # fields joined onto file_path rows, and the explorer's live
        # refresh only listens for path-level invalidations
        invalidate_query(node, "search.objects", library)
        invalidate_query(node, "search.paths", library)
        return None

    @r.mutation("files.renameFile", library=True)
    def rename(node, library, arg):
        from ..files.isolated_path import full_path_from_db_row, separate_name_and_extension

        row = library.db.find_one("file_path", id=int(arg["id"]))
        if row is None:
            raise RspcError.not_found("file_path")
        loc = library.db.find_one("location", id=row["location_id"])
        old_path = full_path_from_db_row(loc["path"], row)
        new_name = arg["new_name"]
        new_path = os.path.join(os.path.dirname(old_path), new_name)
        if os.path.exists(new_path):
            raise RspcError.bad_request("target name already exists")
        os.rename(old_path, new_path)
        name, ext = separate_name_and_extension(new_name)
        rid = row["pub_id"].hex()
        ops = [
            library.sync.shared_update("file_path", rid, "name", name),
            library.sync.shared_update("file_path", rid, "extension", ext),
        ]
        library.sync.write_ops(
            ops,
            lambda conn: conn.execute(
                "UPDATE file_path SET name = ?, extension = ?, date_modified = ? "
                "WHERE id = ?",
                (name, ext, now_iso(), row["id"]),
            ),
        )
        invalidate_query(node, "search.paths", library)
        return None

    @r.mutation("files.deleteFiles", library=True)
    async def delete_files(node, library, arg):
        await JobBuilder(
            FileDeleterJob(
                {
                    "location_id": int(arg["location_id"]),
                    "file_path_ids": [int(i) for i in arg["file_path_ids"]],
                }
            )
        ).spawn(node.jobs, library)
        return None

    @r.mutation("files.eraseFiles", library=True)
    async def erase_files(node, library, arg):
        await JobBuilder(
            FileEraserJob(
                {
                    "location_id": int(arg["location_id"]),
                    "file_path_ids": [int(i) for i in arg["file_path_ids"]],
                    "passes": int(arg.get("passes", 1)),
                }
            )
        ).spawn(node.jobs, library)
        return None

    @r.mutation("files.copyFiles", library=True)
    async def copy_files(node, library, arg):
        await JobBuilder(FileCopierJob(dict(arg))).spawn(node.jobs, library)
        return None

    @r.mutation("files.cutFiles", library=True)
    async def cut_files(node, library, arg):
        await JobBuilder(FileCutterJob(dict(arg))).spawn(node.jobs, library)
        return None

    @r.mutation("files.validate", library=True)
    async def validate(node, library, arg):
        await JobBuilder(ObjectValidatorJob(dict(arg))).spawn(node.jobs, library)
        return None


def _object_update(node: Any, library: Any, file_path_id: int, **fields: Any) -> None:
    row = library.db.find_one("file_path", id=file_path_id)
    if row is None or not row["object_id"]:
        raise RspcError.not_found("object for file_path")
    pub = _object_pub(library, row["object_id"])
    cols = ", ".join(f"{k} = ?" for k in fields)

    def writes(conn):
        conn.execute(
            f"UPDATE object SET {cols} WHERE id = ?",
            (*fields.values(), row["object_id"]),
        )

    library.sync.write_ops(
        [library.sync.shared_update("object", pub, k, v)
         for k, v in fields.items()] if pub else [],
        db_writes=writes,
    )
    invalidate_query(node, "search.objects", library)
    # favorite/note render on file_path rows (favorites route, grid
    # badges) and the explorer live-refreshes on path invalidations
    invalidate_query(node, "search.paths", library)


# --- ephemeralFiles ------------------------------------------------------


def _ephemeral(r: Router) -> None:
    @r.query("ephemeralFiles.list")
    async def list_dir(node, arg):
        """Non-indexed browse (ref:core/src/location/non_indexed.rs);
        hashing/stat work runs off the event loop."""
        from ..location.non_indexed import walk_dir

        return await asyncio.to_thread(
            walk_dir, node, arg["path"], with_hidden=bool(arg.get("with_hidden", False))
        )

    # mutations on non-indexed paths (ref:core/src/api/ephemeral_files.rs)
    @r.mutation("ephemeralFiles.createFolder")
    def create_folder(node, arg):
        name = arg["name"]
        if os.sep in name or "/" in name:
            raise RspcError.bad_request("folder name must not contain separators")
        path = os.path.join(os.path.abspath(arg["path"]), name)
        try:
            os.mkdir(path)  # exactly one level, races surface as EEXIST
        except OSError as e:
            raise RspcError.bad_request(f"create {path}: {e}")
        return path

    @r.mutation("ephemeralFiles.renameFile")
    def rename_file(node, arg):
        src = os.path.abspath(arg["path"])
        dst = os.path.join(os.path.dirname(src), arg["new_name"])
        # lexists: a dangling symlink is still an entry to rename/protect
        if not os.path.lexists(src):
            raise RspcError.not_found("path")
        if os.path.lexists(dst):
            raise RspcError.bad_request("target name already exists")
        try:
            os.rename(src, dst)
        except OSError as e:
            raise RspcError.bad_request(f"rename: {e}")
        return dst

    @r.mutation("ephemeralFiles.deleteFiles")
    def delete_files(node, arg):
        import shutil

        deleted = 0
        errors: list[str] = []
        for p in arg["paths"]:
            p = os.path.abspath(p)
            try:
                if os.path.islink(p) or os.path.isfile(p):
                    os.remove(p)
                elif os.path.isdir(p):
                    shutil.rmtree(p)
                else:
                    continue
                deleted += 1
            except OSError as e:
                errors.append(f"delete {p}: {e}")  # keep going (job parity)
        return {"deleted": deleted, "errors": errors}


# --- jobs ----------------------------------------------------------------


def _jobs(r: Router) -> None:
    from ..jobs.report import JobReport, JobStatus

    @r.query("jobs.reports", library=True)
    def reports(node, library):
        rows = library.db.query(
            "SELECT * FROM job ORDER BY date_created DESC LIMIT 100"
        )
        out = []
        for row in rows:
            rep = JobReport.from_row(row)
            out.append(
                {
                    "id": str(rep.id),
                    "name": rep.name,
                    "action": rep.action,
                    "status": rep.status.name,
                    "task_count": rep.task_count,
                    "completed_task_count": rep.completed_task_count,
                    "errors": rep.errors_text,
                    "created_at": rep.created_at,
                    "completed_at": rep.completed_at,
                    "parent_id": str(rep.parent_id) if rep.parent_id else None,
                }
            )
        return out

    @r.query("jobs.isActive", library=True)
    def is_active(node, library):
        return bool(node.jobs._active)

    @r.mutation("jobs.pause")
    async def pause(node, arg):
        await node.jobs.pause(uuid.UUID(arg))
        return None

    @r.mutation("jobs.resume")
    async def resume(node, arg):
        await node.jobs.resume(uuid.UUID(arg))
        return None

    @r.mutation("jobs.cancel")
    async def cancel(node, arg):
        await node.jobs.cancel(uuid.UUID(arg))
        return None

    @r.mutation("jobs.clear", library=True)
    def clear(node, library, arg):
        library.db.delete("job", id=uuid.UUID(arg).bytes)
        invalidate_query(node, "jobs.reports", library)
        return None

    @r.mutation("jobs.clearAll", library=True)
    def clear_all(node, library):
        library.db.execute(
            "DELETE FROM job WHERE status NOT IN (?, ?)",
            (int(JobStatus.RUNNING), int(JobStatus.PAUSED)),
        )
        invalidate_query(node, "jobs.reports", library)
        return None

    @r.subscription("jobs.progress", library=True)
    async def progress(node, library) -> AsyncIterator[Any]:
        async for event in _bus_events(node):
            if (
                isinstance(event, tuple)
                and event[0] == CoreEventKind.JOB_PROGRESS
            ):
                ev = event[1]
                # the node bus carries every library's jobs; scope to
                # the subscribed library (LibraryArgs semantics)
                ev_lib = getattr(ev, "library_id", None)
                if ev_lib is None or str(ev_lib) == str(library.id):
                    yield ev


# --- search --------------------------------------------------------------


def _search(r: Router) -> None:
    @r.query("search.paths", library=True)
    def paths(node, library, arg):
        return search_paths(library, arg)

    @r.query("search.objects", library=True)
    def objects(node, library, arg):
        return search_objects(library, arg)

    @r.query("search.semantic", library=True)
    async def semantic(node, library, arg):
        """Vector-index top-k (probe embed + device matmul) — runs off
        the event loop like search.duplicates; the serve layer caches
        the byte result until an embedding write invalidates the
        library tag."""
        return await asyncio.to_thread(search_semantic, library, arg)

    @r.query("search.duplicates", library=True)
    async def duplicates(node, library, arg):
        """Near + exact duplicate groups (device pHash; BASELINE cfg 5).
        Runs off the event loop — the matmuls + grouping take seconds on
        big libraries."""
        from ..object.duplicates import find_duplicates

        return await asyncio.to_thread(
            find_duplicates, library, int((arg or {}).get("threshold", 8))
        )

    @r.mutation("search.detectDuplicates", library=True)
    async def detect_duplicates(node, library, arg):
        from ..jobs.manager import JobBuilder
        from ..object.duplicates import DuplicateDetectorJob

        job_id = await JobBuilder(
            DuplicateDetectorJob(dict(arg or {}))
        ).spawn(node.jobs, library)
        return str(job_id)

    @r.query("search.saved.list", library=True)
    def saved_list(node, library):
        return normalise("saved_search", library.db.find("saved_search"))

    @r.mutation("search.saved.create", library=True)
    def saved_create(node, library, arg):
        sid = library.db.insert(
            "saved_search",
            pub_id=new_pub_id(),
            name=arg.get("name"),
            search=arg.get("search"),
            filters=arg.get("filters"),
            icon=arg.get("icon"),
            description=arg.get("description"),
            date_created=now_iso(),
            date_modified=now_iso(),
        )
        invalidate_query(node, "search.saved.list", library)
        return sid

    @r.mutation("search.saved.delete", library=True)
    def saved_delete(node, library, arg):
        library.db.delete("saved_search", id=int(arg))
        invalidate_query(node, "search.saved.list", library)
        return None


# --- cloud ---------------------------------------------------------------


def _cloud(r: Router) -> None:
    @r.query("cloud.getApiOrigin")
    def get_origin(node):
        return node.config.config.preferences.get("cloud_api_origin")

    @r.mutation("cloud.setApiOrigin")
    def set_origin(node, arg):
        node.config.config.preferences["cloud_api_origin"] = str(arg)
        node.config.save()
        invalidate_query(node, "cloud.getApiOrigin")
        return None

    @r.query("cloud.library.get", library=True)
    async def get_library(node, library):
        from ..cloud.api import CloudApiError, CloudClient

        origin = node.config.config.preferences.get("cloud_api_origin")
        if not origin:
            return None
        from ..utils.resilience import BreakerOpen

        client = CloudClient(origin)
        try:
            return await client.get_library(str(library.id))
        except (CloudApiError, BreakerOpen):
            return None
        finally:
            await client.close()

    @r.mutation("cloud.sync.enable", library=True)
    async def enable(node, library):
        from ..cloud.api import CloudApiError

        from ..utils.resilience import BreakerOpen

        try:
            cloud = await node.enable_cloud_sync(library)
        except ValueError as e:
            raise RspcError.bad_request(str(e))
        except (CloudApiError, BreakerOpen) as e:
            raise RspcError(502, f"cloud relay unreachable: {e}")
        return {"instance": str(library.sync.instance), "enabled": cloud is not None}

    @r.query("cloud.sync.state", library=True)
    def state(node, library):
        cloud = getattr(library, "cloud_sync", None)
        if cloud is None:
            return {"enabled": False}
        return {
            "enabled": True,
            "sent_ops": cloud.sent_ops,
            "received_collections": cloud.received_collections,
            "ingested_ops": cloud.ingested_ops,
        }


# --- tags ----------------------------------------------------------------


def _tag_pub(library, tag_id: int) -> str | None:
    row = library.db.find_one("tag", id=int(tag_id))
    return row["pub_id"].hex() if row else None


def _object_pub(library, object_id: int) -> str | None:
    row = library.db.find_one("object", id=int(object_id))
    return row["pub_id"].hex() if row else None


def _tags(r: Router) -> None:
    @r.query("tags.list", library=True)
    def list_tags(node, library):
        return normalise("tag", library.db.find("tag"))

    @r.query("tags.getForObject", library=True)
    def for_object(node, library, arg):
        rows = library.db.query(
            "SELECT t.* FROM tag t JOIN tag_on_object tobj ON tobj.tag_id = t.id "
            "WHERE tobj.object_id = ?",
            (int(arg),),
        )
        return normalise("tag", rows)

    @r.mutation("tags.create", library=True)
    def create(node, library, arg):
        # every shared-model write rides sync.write_ops so the domain
        # row and its CRDT ops land in ONE transaction and paired
        # devices converge (ref:manager.rs:70-93; sync.mdx)
        pub = new_pub_id()
        now = now_iso()
        values = [("name", arg["name"]), ("color", arg.get("color")),
                  ("date_created", now), ("date_modified", now)]
        box = {}

        def writes(conn):
            box["id"] = conn.execute(
                "INSERT INTO tag (pub_id, name, color, date_created, "
                "date_modified) VALUES (?, ?, ?, ?, ?)",
                (pub, arg["name"], arg.get("color"), now, now),
            ).lastrowid

        library.sync.write_ops(
            library.sync.shared_create(
                "tag", pub.hex(), [(k, v) for k, v in values if v is not None]
            ),
            db_writes=writes,
        )
        invalidate_query(node, "tags.list", library)
        return box["id"]

    @r.mutation("tags.update", library=True)
    def update(node, library, arg):
        fields = {k: arg[k] for k in ("name", "color") if k in arg}
        pub = _tag_pub(library, arg["id"])
        if not fields:
            return None
        cols = ", ".join(f"{k} = ?" for k in fields)

        def writes(conn):
            conn.execute(
                f"UPDATE tag SET {cols} WHERE id = ?",
                (*fields.values(), int(arg["id"])),
            )

        library.sync.write_ops(
            [library.sync.shared_update("tag", pub, k, v)
             for k, v in fields.items()] if pub else [],
            db_writes=writes,
        )
        invalidate_query(node, "tags.list", library)
        return None

    @r.mutation("tags.delete", library=True)
    def delete(node, library, arg):
        tag_id = int(arg)
        pub = _tag_pub(library, tag_id)
        # link removals must sync too, or peers keep dangling
        # tag_on_object rows that resurrect the tag as a ghost via
        # FK placeholder creation on later relation ops
        links = library.db.query(
            "SELECT o.pub_id AS opub FROM tag_on_object t "
            "JOIN object o ON o.id = t.object_id WHERE t.tag_id = ?",
            (tag_id,),
        )
        ops = []
        if pub:
            ops = [
                library.sync.relation_delete(
                    "tag_on_object", {"item": l["opub"].hex(), "group": pub}
                )
                for l in links
            ] + [library.sync.shared_delete("tag", pub)]

        def writes(conn):
            conn.execute("DELETE FROM tag_on_object WHERE tag_id = ?", (tag_id,))
            conn.execute("DELETE FROM tag WHERE id = ?", (tag_id,))

        library.sync.write_ops(ops, db_writes=writes)
        invalidate_query(node, "tags.list", library)
        return None

    @r.mutation("tags.assign", library=True)
    def assign(node, library, arg):
        tag_id = int(arg["tag_id"])
        tag_pub = _tag_pub(library, tag_id)
        oids = [int(o) for o in arg["object_ids"]]
        qmarks = ",".join("?" * len(oids)) or "NULL"
        pub_by_id = {
            row["id"]: row["pub_id"].hex()
            for row in library.db.query(
                f"SELECT id, pub_id FROM object WHERE id IN ({qmarks})", oids
            )
        }
        unassign = bool(arg.get("unassign"))
        now = now_iso()
        ops = []
        for oid in oids:
            obj_pub = pub_by_id.get(oid)
            if tag_pub and obj_pub:
                rid = {"item": obj_pub, "group": tag_pub}
                if unassign:
                    ops.append(library.sync.relation_delete("tag_on_object", rid))
                else:
                    ops.extend(library.sync.relation_create("tag_on_object", rid))

        def writes(conn):
            for oid in oids:
                if unassign:
                    conn.execute(
                        "DELETE FROM tag_on_object WHERE tag_id = ? AND object_id = ?",
                        (tag_id, oid),
                    )
                else:
                    conn.execute(
                        "INSERT INTO tag_on_object (tag_id, object_id, date_created) "
                        "VALUES (?, ?, ?) ON CONFLICT (tag_id, object_id) DO NOTHING",
                        (tag_id, oid, now),
                    )

        library.sync.write_ops(ops, db_writes=writes)
        invalidate_query(node, "tags.getForObject", library)
        return None


# --- spaces / albums (ref:schema.prisma space/album models) --------------


def _collection_ns(r: Router, ns: str, table: str, link_table: str, link_col: str) -> None:
    """spaces and albums share the same CRUD shape."""

    @r.query(f"{ns}.list", library=True, priority="interactive")
    def list_all(node, library):
        return normalise(table, library.db.find(table))

    @r.query(f"{ns}.getObjects", library=True, priority="interactive")
    def get_objects(node, library, arg):
        rows = library.db.query(
            f"SELECT o.* FROM object o JOIN {link_table} l ON l.object_id = o.id "
            f"WHERE l.{link_col} = ?",
            (int(arg),),
        )
        return normalise("object", rows)

    @r.mutation(f"{ns}.create", library=True, priority="interactive")
    def create(node, library, arg):
        cols = dict(
            pub_id=new_pub_id(),
            name=arg["name"],
            date_created=now_iso(),
            date_modified=now_iso(),
        )
        if table == "space":
            cols["description"] = arg.get("description")
        rid = library.db.insert(table, **cols)
        invalidate_query(node, f"{ns}.list", library)
        return rid

    @r.mutation(f"{ns}.delete", library=True, priority="interactive")
    def delete(node, library, arg):
        library.db.delete(link_table, **{link_col: int(arg)})
        library.db.delete(table, id=int(arg))
        invalidate_query(node, f"{ns}.list", library)
        return None

    @r.mutation(f"{ns}.addObjects", library=True, priority="interactive")
    def add_objects(node, library, arg):
        for oid in arg["object_ids"]:
            if arg.get("remove"):
                library.db.delete(
                    link_table, **{link_col: int(arg["id"]), "object_id": int(oid)}
                )
            else:
                extra = (
                    {"date_created": now_iso()}
                    if link_table == "object_in_album"  # space link has no column
                    else {}
                )
                library.db.upsert(
                    link_table,
                    {link_col: int(arg["id"]), "object_id": int(oid)},
                    **extra,
                )
        invalidate_query(node, f"{ns}.getObjects", library)
        return None


def _spaces(r: Router) -> None:
    _collection_ns(r, "spaces", "space", "object_in_space", "space_id")


def _albums(r: Router) -> None:
    _collection_ns(r, "albums", "album", "object_in_album", "album_id")


# --- labels --------------------------------------------------------------


def _labels(r: Router) -> None:
    @r.query("labels.list", library=True)
    def list_labels(node, library):
        return normalise("label", library.db.find("label"))

    @r.query("labels.getForObject", library=True)
    def for_object(node, library, arg):
        rows = library.db.query(
            "SELECT l.* FROM label l JOIN label_on_object lo ON lo.label_id = l.id "
            "WHERE lo.object_id = ?",
            (int(arg),),
        )
        return normalise("label", rows)

    @r.query("labels.getWithObjects", library=True)
    def with_objects(node, library, arg):
        if not arg:
            return {}
        rows = library.db.query(
            "SELECT l.id AS label_id, lo.object_id FROM label l "
            "JOIN label_on_object lo ON lo.label_id = l.id "
            f"WHERE l.id IN ({','.join('?' * len(arg))})",
            [int(i) for i in arg],
        )
        out: dict[int, list[int]] = {}
        for row in rows:
            out.setdefault(row["label_id"], []).append(row["object_id"])
        return out

    @r.mutation("labels.delete", library=True)
    def delete(node, library, arg):
        library.db.delete("label_on_object", label_id=int(arg))
        library.db.delete("label", id=int(arg))
        invalidate_query(node, "labels.list", library)
        return None


# --- sync ----------------------------------------------------------------


def _sync(r: Router) -> None:
    from ..sync.ingest import backfill_operations

    @r.query("sync.enabled", library=True)
    def enabled(node, library):
        return library.sync.emit_messages

    @r.query("sync.messages", library=True)
    def messages(node, library, arg):
        count = int((arg or {}).get("count", 100))
        return [op.to_wire() for op in library.sync.get_ops(count=count)]

    @r.mutation("sync.backfill", library=True)
    def backfill(node, library):
        return backfill_operations(library.sync)

    @r.subscription("sync.newMessage", library=True)
    async def new_message(node, library) -> AsyncIterator[Any]:
        async for event in _bus_events_for(library.event_bus):
            if event == ("SyncMessage", "Created") or event == (
                "SyncMessage",
                "Ingested",
            ):
                yield event[1]


# --- p2p -----------------------------------------------------------------


def _p2p(r: Router) -> None:
    @r.query("p2p.state")
    def state(node):
        if node.p2p is None:
            return {"enabled": False, "peers": []}
        relay_client = node.p2p.relay_client
        return {
            "enabled": True,
            "port": node.p2p.port,
            "identity": str(node.p2p.p2p.remote_identity),
            # path-selection telemetry: punched-direct vs relayed dials
            "punch": (dict(relay_client.punch_stats)
                      if relay_client is not None else None),
            "peers": [
                {
                    "identity": str(p.identity),
                    "metadata": p.metadata,
                    "addrs": sorted(f"{h}:{pt}" for h, pt in p.addrs),
                    "connected": p.is_connected,
                }
                for p in node.p2p.p2p.peers.values()
            ],
        }

    @r.mutation("p2p.spacedrop")
    async def spacedrop(node, arg):
        from ..p2p.identity import RemoteIdentity

        if node.p2p is None:
            raise RspcError.bad_request("p2p disabled")
        drop_id = await node.p2p.spacedrop.send(
            RemoteIdentity.from_str(arg["identity"]), list(arg["file_paths"])
        )
        return str(drop_id)

    def _require_p2p(node):
        if node.p2p is None:
            raise RspcError.bad_request("p2p disabled")
        return node.p2p

    @r.mutation("p2p.acceptSpacedrop")
    def accept(node, arg):
        ok = _require_p2p(node).spacedrop.accept(
            uuid.UUID(arg["id"]), arg.get("target_dir")
        )
        if not ok:
            raise RspcError.not_found("spacedrop request")
        return None

    @r.mutation("p2p.cancelSpacedrop")
    def cancel(node, arg):
        _require_p2p(node).spacedrop.cancel(uuid.UUID(arg))
        return None

    @r.mutation("p2p.rejectSpacedrop")
    def reject(node, arg):
        _require_p2p(node).spacedrop.reject(uuid.UUID(arg))
        return None

    @r.mutation("p2p.pairLibrary")
    async def pair_library(node, arg):
        """Join a peer's library (joiner side of the pairing flow)."""
        from ..p2p.identity import RemoteIdentity

        mgr = _require_p2p(node)
        lib = await mgr.pairing.join(
            mgr.p2p,
            RemoteIdentity.from_str(arg["identity"]),
            uuid.UUID(arg["library_id"]) if arg.get("library_id") else None,
        )
        invalidate_query(node, "library.list")
        return str(lib.id)

    @r.mutation("p2p.acceptPairing")
    def accept_pairing(node, arg):
        if not _require_p2p(node).pairing.accept(uuid.UUID(arg)):
            raise RspcError.not_found("pairing request")
        return None

    @r.mutation("p2p.rejectPairing")
    def reject_pairing(node, arg):
        _require_p2p(node).pairing.reject(uuid.UUID(arg))
        return None

    @r.subscription("p2p.events")
    async def events(node) -> AsyncIterator[Any]:
        """Peer lifecycle (P2P-internal bus) merged with spacedrop
        offers/progress (node event bus — SpacedropManager emits
        there, p2p/manager.py:37); ref:spacedrop.rs:203."""
        if node.p2p is None:
            return
        queue: asyncio.Queue = asyncio.Queue()
        _SENTINEL = object()

        async def pump(gen):
            try:
                async for ev in gen:
                    await queue.put(ev)
            except asyncio.CancelledError:
                raise
            except Exception as exc:  # close the subscription, don't
                await queue.put((_SENTINEL, exc))  # half-starve it
            else:
                await queue.put((_SENTINEL, None))

        pumps = [
            asyncio.create_task(pump(_bus_events_for(node.p2p.p2p.events))),
            asyncio.create_task(pump(_bus_events(node))),
        ]
        try:
            while True:
                event = await queue.get()
                if isinstance(event, tuple) and event and event[0] is _SENTINEL:
                    if event[1] is not None:
                        raise event[1]
                    return  # a source ended cleanly (p2p torn down)
                kind = event[0] if isinstance(event, tuple) and event else None
                if kind in ("PeerDiscovered", "PeerExpired",
                            "PeerConnected", "PeerDisconnected"):
                    yield {"kind": kind, "identity": str(event[1])}
                elif kind == "SpacedropRequest":
                    req = event[1]  # inbound offer → accept/reject dialog
                    yield {
                        "kind": kind,
                        "id": str(req.id),
                        "peer": str(req.peer),
                        "files": list(req.files),
                        "total_size": req.total_size,
                    }
                elif kind == "SpacedropProgress":
                    yield {
                        "kind": kind, "id": str(event[1]),
                        "percent": event[2],
                    }
        finally:
            for t in pumps:
                t.cancel()


# --- nodes / volumes / preferences / notifications -----------------------


def _nodes(r: Router) -> None:
    @r.mutation("nodes.edit")
    def edit(node, arg):
        if arg.get("name"):
            node.config.update(name=arg["name"])
        if "p2p_enabled" in arg:
            node.config.config.p2p.enabled = bool(arg["p2p_enabled"])
            node.config.save()
        invalidate_query(node, "nodeState")
        return None

    @r.mutation("nodes.updateThumbnailerPreferences")
    def thumbnailer_prefs(node, arg):
        node.thumbnailer.set_background_percentage(
            int(arg.get("background_processing_percentage", 50))
        )
        return None


def _volumes(r: Router) -> None:
    @r.query("volumes.list")
    def list_volumes(node):
        return [v.to_dict() for v in get_volumes()]

    @r.mutation("volumes.track", library=True)
    def track(node, library):
        return save_volumes(library.db)


def _key_manager(library):
    """Per-library crypto vault (ref:core/src/api/keys.rs — the
    KeyManager the reference's KeyManager/ UI drives). The keystore
    file lives next to the library database."""
    km = getattr(library, "key_manager", None)
    if km is None:
        from ..crypto.keys import KeyManager

        path = library.db.path
        store = (path[: -len(".db")] if path.endswith(".db") else path) \
            + ".keystore"
        km = KeyManager(store)
        library.key_manager = km
    return km


def _keys(r: Router) -> None:
    from ..crypto.keys import CryptoError

    def guard(fn, *a):
        try:
            return fn(*a)
        except CryptoError as e:
            raise RspcError.bad_request(str(e))

    @r.query("keys.state", library=True)
    def state(node, library):
        km = _key_manager(library)
        mounted = set(km.mounted_uuids())
        return {
            "unlocked": km.unlocked,
            "keys": [
                {"uuid": sk.uuid, "automount": sk.automount,
                 "algorithm": int(sk.algorithm),
                 "mounted": sk.uuid in mounted}
                for sk in km.stored.values()
            ],
        }

    @r.mutation("keys.unlock", library=True)
    def unlock(node, library, arg):
        km = _key_manager(library)
        # snapshot BEFORE clobbering: a wrong-password retry against an
        # already-unlocked vault must restore the working master, not
        # lock the manager and yank every mounted key out from under
        # its consumers (ADVICE r5)
        prev_master = bytes(km._master) if km.unlocked else None
        km.set_master_password(str(arg["password"]).encode())
        if km.stored:
            # VERIFY before committing: decrypting a stored key proves
            # the password. Accepting it unchecked would let a typo'd
            # password "unlock" the vault and encrypt NEW keys under the
            # typo — a keystore needing two different passwords. The
            # probe prefers an unmounted key and never unmounts one that
            # was already mounted (a second unlock must not yank a key
            # out from under its consumers).
            mounted_before = set(km.mounted_uuids())
            probe = next((u for u in km.stored if u not in mounted_before),
                         next(iter(km.stored)))
            try:
                km.mount(probe)
            except CryptoError:
                if prev_master is not None:
                    # mounted keys were never touched (the failed probe
                    # mounts nothing); restoring the master returns the
                    # manager to its exact pre-call state
                    km.set_master_password(prev_master)
                else:
                    km.lock()
                invalidate_query(node, "keys.state", library)
                raise RspcError.bad_request("wrong master password")
            if probe not in mounted_before \
                    and not km.stored[probe].automount:
                km.unmount(probe)
        mounted = guard(km.automount)
        invalidate_query(node, "keys.state", library)
        return {"automounted": mounted}

    @r.mutation("keys.lock", library=True)
    def lock(node, library):
        _key_manager(library).lock()
        invalidate_query(node, "keys.state", library)
        return None

    @r.mutation("keys.add", library=True)
    def add(node, library, arg):
        import secrets as _secrets

        arg = arg or {}
        km = _key_manager(library)
        if arg.get("material"):
            try:
                material = bytes.fromhex(arg["material"])
            except ValueError:
                raise RspcError.bad_request("material must be hex")
        else:
            material = _secrets.token_bytes(32)
        key_uuid = guard(
            lambda: km.add_key(material,
                               automount=bool(arg.get("automount"))))
        invalidate_query(node, "keys.state", library)
        return {"uuid": key_uuid}

    @r.mutation("keys.mount", library=True)
    def mount(node, library, arg):
        guard(_key_manager(library).mount, str(arg))
        invalidate_query(node, "keys.state", library)
        return None

    @r.mutation("keys.unmount", library=True)
    def unmount(node, library, arg):
        guard(_key_manager(library).unmount, str(arg))
        invalidate_query(node, "keys.state", library)
        return None

    @r.mutation("keys.delete", library=True)
    def delete(node, library, arg):
        guard(_key_manager(library).delete_key, str(arg))
        invalidate_query(node, "keys.state", library)
        return None


def _preferences(r: Router) -> None:
    @r.query("preferences.get", library=True)
    def get(node, library):
        return read_preferences(library.db)

    @r.mutation("preferences.update", library=True)
    def update(node, library, arg):
        write_preferences(library.db, arg or {})
        invalidate_query(node, "preferences.get", library)
        return None


def _notifications(r: Router) -> None:
    @r.query("notifications.get")
    def get(node):
        out = [
            {"id": vars(n.id), "data": n.data, "read": n.read}
            for n in node.notifications.list_node()
        ]
        for lib in node.libraries.libraries.values():
            out.extend(
                {"id": vars(n.id), "data": n.data, "read": n.read}
                for n in Notifications.list_library(lib.db, str(lib.id))
            )
        return out

    @r.mutation("notifications.dismiss", library=True)
    def dismiss(node, library, arg):
        Notifications.mark_read(library.db, int(arg))
        return None

    @r.mutation("notifications.dismissAll", library=True)
    def dismiss_all(node, library):
        library.db.execute("UPDATE notification SET read = 1")
        return None

    @r.subscription("notifications.listen")
    async def listen(node) -> AsyncIterator[Any]:
        async for event in _bus_events(node):
            if isinstance(event, tuple) and event and event[0] == "notification":
                n = event[1]
                yield {"id": vars(n.id), "data": n.data}


# --- backups -------------------------------------------------------------


def _backups(r: Router) -> None:
    import json
    import shutil
    import zipfile

    def backups_dir(node) -> str:
        d = os.path.join(node.data_dir, "backups")
        os.makedirs(d, exist_ok=True)
        return d

    @r.query("backups.getAll")
    def get_all(node):
        out = []
        for name in sorted(os.listdir(backups_dir(node))):
            if not name.endswith(".zip"):
                continue
            path = os.path.join(backups_dir(node), name)
            try:
                with zipfile.ZipFile(path) as z:
                    header = json.loads(z.read("header.json"))
            except Exception:
                continue
            header["path"] = path
            out.append(header)
        return out

    @r.mutation("backups.backup", library=True)
    def backup(node, library):
        """Zip the library DB + config with a header
        (ref:core/src/api/backups.rs `start_backup`)."""
        backup_id = str(uuid.uuid4())
        path = os.path.join(backups_dir(node), f"{backup_id}.zip")
        library.db.execute("PRAGMA wal_checkpoint(TRUNCATE)")
        config_path, db_path = node.libraries.paths(library.id)
        with zipfile.ZipFile(path, "w") as z:
            z.writestr(
                "header.json",
                json.dumps(
                    {
                        "id": backup_id,
                        "timestamp": now_iso(),
                        "library_id": str(library.id),
                        "library_name": library.name,
                    }
                ),
            )
            z.write(db_path, "library.db")
            z.write(config_path, "library.sdlibrary")
        return backup_id

    @r.mutation("backups.restore")
    async def restore(node, arg):
        """ref:backups.rs `start_restore` — close, overwrite, reload."""

        def read_header() -> dict:
            with zipfile.ZipFile(arg["path"]) as z:
                return json.loads(z.read("header.json"))

        def overwrite(db_path: str, config_path: str) -> None:
            # bulk DB copy — runs via asyncio.to_thread (sdlint SD001)
            with zipfile.ZipFile(arg["path"]) as z:
                for suffix in ("-wal", "-shm"):
                    if os.path.exists(db_path + suffix):
                        os.remove(db_path + suffix)
                with z.open("library.db") as src, open(db_path, "wb") as dst:
                    shutil.copyfileobj(src, dst)
                with z.open("library.sdlibrary") as src, \
                        open(config_path, "wb") as dst:
                    shutil.copyfileobj(src, dst)

        header = await asyncio.to_thread(read_header)
        lib_id = uuid.UUID(header["library_id"])
        await node.close_library(lib_id)  # full teardown, not just close
        config_path, db_path = node.libraries.paths(lib_id)
        await asyncio.to_thread(overwrite, db_path, config_path)
        lib = node.libraries.load(lib_id)
        await node._init_library(lib)
        invalidate_query(node, "library.list")
        return str(lib_id)

    @r.mutation("backups.delete")
    def delete(node, arg):
        path = arg["path"] if isinstance(arg, dict) else arg
        if os.path.dirname(os.path.abspath(path)) != os.path.abspath(
            backups_dir(node)
        ):
            raise RspcError.bad_request("not a backup path")
        os.remove(path)
        return None


# --- auth / models / invalidation ---------------------------------------


def _auth(r: Router) -> None:
    @r.query("auth.me")
    def me(node):
        # cloud auth is an online service; offline deployments report logged-out
        return None

    @r.mutation("auth.logout")
    def logout(node):
        return None


def _models(r: Router) -> None:
    @r.query("models.imageDetection.list")
    def list_models(node):
        # ref:crates/ai image_labeler/model listing; one built-in JAX model
        return ["labeler-net-v1"]


def _telemetry(r: Router) -> None:
    """The explorer's diagnostics read path — the same registry the
    /metrics scrape endpoint renders, so the frontend and Prometheus
    can never disagree about a number."""
    from .. import telemetry

    @r.query("telemetry.snapshot")
    def snapshot(node):
        return telemetry.snapshot()

    @r.query("telemetry.render")
    def render(node):
        # the Prometheus text, for copy/paste diagnostics in the UI
        return {"text": telemetry.render()}

    @r.query("telemetry.trace_export", priority="background")
    def trace_export(node, arg=None):
        # Chrome-trace JSON (Perfetto-loadable); arg {trace_id?} filters
        trace_id = (arg or {}).get("trace_id") if isinstance(arg, dict) else None
        return telemetry.trace_export(trace_id)

    @r.query("telemetry.events")
    def events(node):
        # the flight recorder's rings, most-recent-last
        return telemetry.events.all_events()

    @r.query("telemetry.debug_bundle", priority="background")
    def debug_bundle(node):
        # the redacted support artifact (see telemetry.bundle)
        return telemetry.debug_bundle(node)

    @r.query("telemetry.tenants", priority="background")
    def tenants(node):
        # the per-tenant heavy-hitter sketches (telemetry.tenants):
        # hashed tenant labels only — explicitly background, an
        # observability read must never contend with control traffic
        return telemetry.tenants.snapshot()

    @r.query("telemetry.health")
    def health(node):
        # per-subsystem → per-node verdicts (telemetry.health)
        from ..telemetry import health as _health

        return _health.evaluate(node)

    @r.query("telemetry.mesh", priority="interactive")
    async def mesh(node, arg=None):
        # mesh-wide view: local snapshot + federated peer snapshots
        # with staleness marking; arg {refresh?: bool, force?: bool}.
        # Single-flighted through the serve cache — N dashboards cost
        # one refresh round per TTL window (same path as GET /mesh).
        # Explicitly INTERACTIVE, not the namespace's control class: a
        # federation refresh dials peers — a control-class (unsheddable)
        # refresh loop would be an ungovernable overload hole, and the
        # identical read over GET /mesh already queues/sheds
        from ..telemetry.federation import mesh_status_cached

        opts = arg if isinstance(arg, dict) else {}
        return await mesh_status_cached(
            node,
            refresh=bool(opts.get("refresh", True)),
            force=bool(opts.get("force")),
        )

    @r.query("telemetry.attrib", priority="background")
    async def attrib(node, arg=None):
        # critical-path attribution for one distributed trace (default:
        # the last completed pass): bucket split + critical-path
        # segments, with executor-side spans pulled from mesh peers.
        # BACKGROUND like trace_export — assembly dials peers, so it
        # must never ride the unsheddable control class
        from ..telemetry import attrib as _attrib

        opts = arg if isinstance(arg, dict) else {}
        return await _attrib.assemble(
            node,
            opts.get("trace_id") or None,
            refresh=bool(opts.get("refresh")),
        )

    @r.query("telemetry.profile", priority="background")
    async def profile(node, arg=None):
        # the continuous host profiler: frame groups, on-CPU vs
        # GIL-wait split, deep-capture windows. arg {mesh?: bool,
        # format?: "folded"}. BACKGROUND like trace_export — the mesh
        # leg dials peers, so it must never ride the control class
        from ..telemetry import sampler as _sampler

        opts = arg if isinstance(arg, dict) else {}
        if opts.get("format") == "folded":
            return {"folded": _sampler.SAMPLER.folded()}
        if opts.get("mesh"):
            return await _sampler.mesh_profile(node)
        return _sampler.SAMPLER.profile()

    @r.query("telemetry.slo")
    def slo(node):
        # SLO burn-rate posture over the node's persistent history
        # (telemetry/slo.py) — the same evaluation the `slo` health
        # subsystem embeds in federation snapshots
        from ..telemetry import slo as _slo

        return _slo.evaluate(getattr(node, "history", None))

    @r.query("telemetry.serve")
    def serve_status(node):
        # admission gate + read-cache state (the overload posture):
        # mode, per-class inflight/shed, cache occupancy
        from ..serve import runtime_for

        serve = runtime_for(node)
        if serve is None:
            return {"enabled": False}
        return {"enabled": True, **serve.snapshot()}


def _invalidation(r: Router) -> None:
    @r.subscription("invalidation.listen")
    async def listen(node) -> AsyncIterator[Any]:
        async for event in _bus_events(node):
            if (
                isinstance(event, tuple)
                and event[0] == CoreEventKind.INVALIDATE_OPERATION
            ):
                yield event[1].to_wire()


# --- helpers -------------------------------------------------------------


async def _bus_events(node: Any) -> AsyncIterator[Any]:
    async for event in _bus_events_for(node.event_bus):
        yield event


async def _bus_events_for(bus: Any) -> AsyncIterator[Any]:
    """Bridge the thread-safe EventBus into an async stream."""
    sub = bus.subscribe()
    try:
        while True:
            for event in sub.poll():
                yield event
            await asyncio.sleep(0.02)
    finally:
        sub.close()
