"""`sdx desktop` — the managed desktop host.

Parity: the reference's desktop app is a Tauri shell
(ref:apps/desktop/src-tauri/src/main.rs) whose jobs are lifecycle, not
UI: run exactly one core per data dir (tauri-plugin-single-instance),
open the frontend in a webview, route `sd://` deep links and file
arguments into the running instance, keep the node alive in the
background, and integrate with the OS launcher. This image has no
webkit2gtk, so the UI half rides the system browser (the explorer web
app IS the interface); everything else is implemented natively here:

- **single instance**: an fcntl lock on `<data_dir>/desktop.lock`.
  A second `sdx desktop` forwards its request (open/focus/quit) to
  the first over the control socket and exits — the lock dies with
  the process, so no stale-pid heuristics.
- **lifecycle**: start the Node + HTTP API, open the explorer in the
  default browser (xdg-open/$BROWSER), run until SIGINT/SIGTERM or a
  control-socket `quit` — closing the browser tab does NOT stop the
  node (tray-style background mode, same as the reference's tray).
- **deep links**: `sdx desktop --open-path /some/dir` targets the
  running instance (or starts one) and opens the explorer on that
  path via the ephemeral-browse route.
- **OS integration**: `sdx desktop --register` writes an XDG
  .desktop entry (file-manager "Open with sdx" + `sdx:` URL scheme)
  under $XDG_DATA_HOME — the `xdg-open`-facing half of Tauri's
  bundler role.

The control plane is a unix socket inside the data dir (filesystem
permissions = same trust boundary as the database itself), one JSON
line per request: {"cmd": "ping"|"open"|"quit", "path": ...?}.
"""

from __future__ import annotations

import asyncio
import errno
import fcntl
import json
import os
import shutil
import signal
import subprocess
import sys
import urllib.parse
from typing import Any, Callable

LOCK_NAME = "desktop.lock"
SOCK_NAME = "desktop.sock"
STATE_NAME = "desktop.json"


class DesktopError(Exception):
    pass


def _explorer_url(port: int, path: str | None = None) -> str:
    url = f"http://127.0.0.1:{port}/"
    if path:
        url += "#/ephemeral?path=" + urllib.parse.quote(path)
    return url


def open_in_browser(url: str) -> bool:
    """Best-effort: $BROWSER, xdg-open, python -m webbrowser."""
    for cmd in filter(None, [os.environ.get("BROWSER"),
                             shutil.which("xdg-open")]):
        try:
            subprocess.Popen(
                [cmd, url], stdout=subprocess.DEVNULL,
                stderr=subprocess.DEVNULL,
                start_new_session=True,
            )
            return True
        except OSError:
            continue
    try:
        import webbrowser

        return webbrowser.open(url)
    except Exception:  # noqa: BLE001 - headless hosts have no browser
        return False


async def control_request(data_dir: str, msg: dict[str, Any],
                          timeout: float = 5.0) -> dict[str, Any]:
    """One JSON request to a running desktop host's control socket."""
    sock = os.path.join(data_dir, SOCK_NAME)
    reader, writer = await asyncio.wait_for(
        asyncio.open_unix_connection(sock), timeout)
    try:
        writer.write(json.dumps(msg).encode() + b"\n")
        await writer.drain()
        line = await asyncio.wait_for(reader.readline(), timeout)
        return json.loads(line)
    finally:
        writer.close()


class DesktopHost:
    """One managed core per data dir + the OS-facing glue."""

    def __init__(self, data_dir: str, *, host: str = "127.0.0.1",
                 port: int = 0, open_browser: bool = True,
                 opener: Callable[[str], bool] = open_in_browser,
                 node_factory: Callable[[], Any] | None = None):
        self.data_dir = os.path.abspath(os.path.expanduser(data_dir))
        self.host = host
        self.port = port
        self.open_browser = open_browser
        self.opener = opener
        self._node_factory = node_factory
        self.node: Any = None
        self.api_port: int | None = None
        self._lock_fd: int | None = None
        self._ctrl_server: asyncio.AbstractServer | None = None
        self._quit = asyncio.Event()
        self.opened_urls: list[str] = []  # observability (and tests)

    # --- single instance ------------------------------------------------

    def try_lock(self) -> bool:
        """True if we are THE instance for this data dir."""
        os.makedirs(self.data_dir, exist_ok=True)
        fd = os.open(os.path.join(self.data_dir, LOCK_NAME),
                     os.O_CREAT | os.O_RDWR, 0o600)
        try:
            fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
        except OSError as e:
            os.close(fd)
            if e.errno in (errno.EAGAIN, errno.EACCES):
                return False
            raise
        os.ftruncate(fd, 0)
        os.write(fd, str(os.getpid()).encode())
        self._lock_fd = fd
        return True

    def _unlock(self) -> None:
        if self._lock_fd is not None:
            try:
                fcntl.flock(self._lock_fd, fcntl.LOCK_UN)
            finally:
                os.close(self._lock_fd)
                self._lock_fd = None

    # --- control socket -------------------------------------------------

    async def _serve_control(self) -> None:
        sock = os.path.join(self.data_dir, SOCK_NAME)
        try:
            os.unlink(sock)
        except FileNotFoundError:
            pass
        self._ctrl_server = await asyncio.start_unix_server(
            self._on_control, sock)
        os.chmod(sock, 0o600)

    async def _on_control(self, reader: asyncio.StreamReader,
                          writer: asyncio.StreamWriter) -> None:
        try:
            line = await asyncio.wait_for(reader.readline(), 5.0)
            msg = json.loads(line or b"{}")
        except Exception:  # noqa: BLE001 - hostile/broken client
            writer.close()
            return
        cmd = msg.get("cmd")
        resp: dict[str, Any] = {"ok": True, "port": self.api_port,
                                "pid": os.getpid()}
        if cmd == "open":
            url = _explorer_url(self.api_port or 0, msg.get("path"))
            self.opened_urls.append(url)
            if self.open_browser:
                self.opener(url)
            resp["url"] = url
        elif cmd == "quit":
            self._quit.set()
        elif cmd != "ping":
            resp = {"ok": False, "error": f"unknown cmd {cmd!r}"}
        try:
            writer.write(json.dumps(resp).encode() + b"\n")
            await writer.drain()
        except (ConnectionError, OSError):
            pass
        writer.close()

    # --- lifecycle -------------------------------------------------------

    def _make_node(self) -> Any:
        if self._node_factory is not None:
            return self._node_factory()
        from .node import Node

        return Node(self.data_dir)

    async def start(self) -> int:
        """Start core + API + control plane; returns the API port.
        A lock already held by THIS host (run_or_forward's probe) is
        kept — releasing and re-acquiring would open a race window for
        a concurrent launch to steal the instance."""
        if self._lock_fd is None and not self.try_lock():
            raise DesktopError("another sdx desktop owns this data dir")
        self.node = self._make_node()
        await self.node.start()
        self.api_port = await self.node.start_api(self.host, self.port)
        await self._serve_control()
        with open(os.path.join(self.data_dir, STATE_NAME), "w") as f:
            json.dump({"pid": os.getpid(), "port": self.api_port}, f)
        return self.api_port

    async def run(self, open_path: str | None = None) -> None:
        """start() + open the UI + serve until quit/signal."""
        await self.start()
        url = _explorer_url(self.api_port or 0, open_path)
        self.opened_urls.append(url)
        if self.open_browser:
            self.opener(url)
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGINT, signal.SIGTERM):
            try:
                loop.add_signal_handler(sig, self._quit.set)
            except (NotImplementedError, RuntimeError):
                pass
        try:
            await self._quit.wait()
        finally:
            await self.shutdown()

    async def shutdown(self) -> None:
        if self._ctrl_server is not None:
            self._ctrl_server.close()
            await self._ctrl_server.wait_closed()
            self._ctrl_server = None
        if self.node is not None:
            await self.node.shutdown()
            self.node = None
        for name in (SOCK_NAME, STATE_NAME):
            try:
                os.unlink(os.path.join(self.data_dir, name))
            except FileNotFoundError:
                pass
        self._unlock()


async def run_or_forward(data_dir: str, *, open_path: str | None = None,
                         quit_running: bool = False,
                         host: str = "127.0.0.1", port: int = 0,
                         open_browser: bool = True,
                         node_factory: Callable[[], Any] | None = None,
                         ) -> int:
    """The `sdx desktop` entry: become the instance, or forward to it.

    Returns a process exit code. Forwarded commands (second instance,
    --quit) return after the running host acknowledges.
    """
    if open_path:
        open_path = parse_open_arg(open_path)
    probe = DesktopHost(data_dir, host=host, port=port,
                        open_browser=open_browser,
                        node_factory=node_factory)
    if quit_running:
        try:
            await control_request(data_dir, {"cmd": "quit"})
            print("sdx desktop: quit sent")
            return 0
        except (OSError, asyncio.TimeoutError):
            print("sdx desktop: no running instance", file=sys.stderr)
            return 1
    if not probe.try_lock():
        # single instance: hand our request to the owner
        try:
            resp = await control_request(
                data_dir, {"cmd": "open", "path": open_path})
        except (OSError, asyncio.TimeoutError) as e:
            print(f"sdx desktop: instance lock held but control socket "
                  f"unreachable: {e}", file=sys.stderr)
            return 1
        print(f"sdx desktop: forwarded to running instance "
              f"(pid {resp.get('pid')}, {resp.get('url')})")
        return 0
    # keep holding the lock into run() — releasing here would let a
    # concurrent launch win the re-acquire and crash this process
    print(f"sdx desktop: starting core for {probe.data_dir}")
    await probe.run(open_path)
    return 0


# --- XDG registration ------------------------------------------------------

DESKTOP_ENTRY = """[Desktop Entry]
Type=Application
Name=Spacedrive TPU
Comment=TPU-native file explorer
Exec={exec_line} desktop --open-path %u
Terminal=false
Categories=System;FileTools;FileManager;
MimeType=inode/directory;x-scheme-handler/sdx;
"""


def parse_open_arg(raw: str) -> str:
    """Normalize what the OS hands the %u field code: a plain path, a
    file:// URI, or an sdx://open/<path> deep link — all become a
    filesystem path for the ephemeral route."""
    if raw.startswith("sdx://"):
        parsed = urllib.parse.urlparse(raw)
        path = urllib.parse.unquote(parsed.path or "")
        if parsed.netloc and parsed.netloc != "open":
            # sdx://<abs-path-first-seg>/... (no recognised verb)
            path = "/" + parsed.netloc + path
        return path or "/"
    if raw.startswith("file://"):
        return urllib.parse.unquote(urllib.parse.urlparse(raw).path) or "/"
    return raw


def register_xdg(exec_line: str | None = None) -> str:
    """Write the XDG application entry (launcher + "Open with" + sdx:
    scheme). Honors $XDG_DATA_HOME; returns the written path."""
    exec_line = exec_line or f"{sys.executable} -m spacedrive_tpu"
    base = os.environ.get("XDG_DATA_HOME",
                          os.path.expanduser("~/.local/share"))
    apps = os.path.join(base, "applications")
    os.makedirs(apps, exist_ok=True)
    path = os.path.join(apps, "sdx.desktop")
    with open(path, "w") as f:
        f.write(DESKTOP_ENTRY.format(exec_line=exec_line))
    # refresh the desktop database so "Open with" menus pick it up
    upd = shutil.which("update-desktop-database")
    if upd:
        subprocess.run([upd, apps], check=False,
                       stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    return path
