"""Ephemeral (non-indexed) directory browsing.

Parity: ref:core/src/location/non_indexed.rs:1-40 — browse any path
with no DB involvement: stream the directory's entries with kind
resolution, per-file metadata, on-the-fly cas_id for regular files, and
queue *ephemeral* thumbnails (stored under `thumbnails/ephemeral/`)
for the thumbnailable ones. Sorted dirs-first like the reference's
grouped response (`NonIndexedPathItem` listing).
"""

from __future__ import annotations

import os
from typing import Any

from ..files.extensions import kind_for_path
from ..files.isolated_path import path_is_hidden
from ..files.kind import ObjectKind
from ..ops.cas import cas_id_cpu


def walk_dir(
    node: Any,
    path: str,
    *,
    with_hidden: bool = False,
    queue_thumbnails: bool = True,
) -> dict[str, Any]:
    """One directory level (the reference streams; we return one page —
    the API layer is free to paginate by slicing)."""
    path = os.path.abspath(path)
    if not os.path.isdir(path):
        raise NotADirectoryError(path)
    entries: list[dict[str, Any]] = []
    thumb_entries: list[tuple[str, str, str]] = []
    with os.scandir(path) as it:
        for entry in it:
            try:
                hidden = path_is_hidden(entry.path)
                if hidden and not with_hidden:
                    continue
                stat = entry.stat(follow_symlinks=False)
                is_dir = entry.is_dir(follow_symlinks=False)
                ext = (
                    ""
                    if is_dir
                    else os.path.splitext(entry.name)[1].lstrip(".").lower()
                )
                kind = (
                    ObjectKind.Folder
                    if is_dir
                    else kind_for_path(entry.path)
                )
                # cas_id only where it's consumed (thumbnail addressing)
                # — hashing every file would make big listings I/O-bound
                cas_id = None
                if (
                    not is_dir
                    and stat.st_size > 0
                    and kind in (ObjectKind.Image, ObjectKind.Video)
                ):
                    try:
                        cas_id = cas_id_cpu(entry.path, stat.st_size)
                    except OSError:
                        pass
                item = {
                    "path": entry.path,
                    "name": entry.name if is_dir else os.path.splitext(entry.name)[0],
                    "extension": ext,
                    "kind": int(kind),
                    "is_dir": is_dir,
                    "size_in_bytes": 0 if is_dir else stat.st_size,
                    "date_created": stat.st_ctime,
                    "date_modified": stat.st_mtime,
                    "hidden": hidden,
                    "cas_id": cas_id,
                    "has_created_thumbnail": False,
                }
                if (
                    cas_id is not None
                    and kind in (ObjectKind.Image, ObjectKind.Video)
                ):
                    if node.thumbnailer.store.exists(None, cas_id):
                        item["has_created_thumbnail"] = True
                    else:
                        thumb_entries.append((cas_id, entry.path, ext))
                entries.append(item)
            except OSError:
                continue
    if queue_thumbnails and thumb_entries:
        node.thumbnailer.new_ephemeral_thumbnails_batch(thumb_entries)
    entries.sort(key=lambda e: (not e["is_dir"], e["name"].lower()))
    return {"entries": entries, "errors": []}
