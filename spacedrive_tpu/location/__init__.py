"""Locations — watched directory trees indexed into the library.

Parity: ref:core/src/location/ (location CRUD, indexer, watcher,
non-indexed browsing).
"""
