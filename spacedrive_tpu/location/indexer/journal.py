"""Persistent per-location index journal — never hash a byte twice.

The journal maps a file_path key `(location_id, materialized_path,
name, extension)` to its last-known stat identity
`(inode, dev, mtime_ns, size)` and the derived results that identity
vouches for: `cas_id`, a thumbnail-stored flag, the media-metadata
digest, the duplicate-detector pHash, and the dirty-range chunk cache
(`ops.cas.ChunkCache`). Consumers — the walker, the file identifier,
the media processor, the duplicate detector — consult it BEFORE reading
any byte: an identity match means the cached result is current, so a
warm pass stats files but only reads/hashes/ships/thumbnails the
changed ones.

Truth discipline (the journal may only ever make a pass FASTER, never
wrong):

- a verdict is `hit` only when every identity field matches exactly
  (`st_mtime_ns`, not the float mtime) AND the entry is not stale;
- journal writes happen strictly AFTER the store/DB commit they vouch
  for (identifier: after the object-link sync write; thumbnails: after
  the rendezvous confirms the webp is in the store) — a crash between
  commit and journal write costs a redundant rehash, never a lie;
- watcher change events mark entries `stale` (targeted invalidation)
  instead of deleting them: a stale entry never vouches, but its chunk
  cache still powers the dirty-range rehash;
- any malformed row/payload (torn write, version drift) reads as
  `bypassed` and is dropped — the pass degrades to a cold rehash.

`SD_INDEX_JOURNAL=0` disables consults AND writes (every lookup counts
as `bypassed`).

Verdict counters: `sd_index_journal_ops_total{result=...}` plus
`sd_index_journal_bytes_saved_total` (see docs/performance.md).
"""

from __future__ import annotations

import collections
import itertools
import logging
import os
import sqlite3
import threading
import time
from dataclasses import dataclass
from typing import Any

from ...db.database import blob_u64, now_iso, u64_blob
from ...ops.cas import ChunkCache
from ...telemetry import metrics as _tm

logger = logging.getLogger(__name__)

#: payload format version; a mismatch reads as a miss and is rewritten
JOURNAL_FORMAT = 1

#: verdict vocabulary (the metric's `result` label)
HIT, MISS, INVALIDATED, BYPASSED = "hit", "miss", "invalidated", "bypassed"


def enabled() -> bool:
    return os.environ.get("SD_INDEX_JOURNAL", "1") != "0"


@dataclass(frozen=True)
class Identity:
    """Exact stat identity — all four fields must match for a hit."""

    inode: int
    dev: int
    mtime_ns: int
    size: int

    @classmethod
    def from_stat(cls, st: os.stat_result) -> "Identity":
        return cls(st.st_ino, st.st_dev, st.st_mtime_ns, st.st_size)

    @classmethod
    def from_metadata(cls, meta: Any) -> "Identity | None":
        """From files.isolated_path.FilePathMetadata (walker plumbing)."""
        if meta is None or not getattr(meta, "mtime_ns", 0):
            return None
        return cls(meta.inode, meta.dev, meta.mtime_ns, meta.size_in_bytes)


def stat_identity(path: str | os.PathLike) -> Identity | None:
    """The sanctioned stat for journal-governed pipelines (sdlint SD012
    flags direct ``os.stat`` in those modules). None when unreadable."""
    try:
        return Identity.from_stat(os.stat(path))
    except OSError:
        return None


# key = (materialized_path, name, extension) within one location
Key = tuple[str, str, str]


def key_of(row_or_iso: Any) -> Key:
    """Key from a file_path DB row (dict) or an IsolatedFilePathData."""
    if isinstance(row_or_iso, dict):
        return (
            row_or_iso["materialized_path"],
            row_or_iso["name"],
            row_or_iso["extension"] or "",
        )
    return (
        row_or_iso.materialized_path,
        row_or_iso.name,
        row_or_iso.extension or "",
    )


@dataclass
class JournalEntry:
    identity: Identity | None
    stale: bool
    cas_id: str | None
    thumb: bool = False
    media_digest: str | None = None
    phash: bytes | None = None
    embed: bool = False
    chunks: ChunkCache | None = None


def entry_of_row(row: dict) -> JournalEntry | None:
    """Strictly validated row → entry decode (None = corrupt/foreign).
    Module-level (not a method) so the procpool worker's
    ``journal.match`` stage runs the EXACT code path consult_many runs
    inline — the verdict parity between pooled and single-process
    consults is by construction, not by reimplementation."""
    payload = _decode_payload(row.get("payload"))
    if payload is None:
        return None
    try:
        ident = None
        if row.get("inode") is not None:
            ident = Identity(
                blob_u64(row["inode"]), blob_u64(row["dev"]),
                blob_u64(row["mtime_ns"]), blob_u64(row["size"]),
            )
        chunks = None
        if payload.get("chunks") is not None:
            chunks = ChunkCache.from_payload(payload["chunks"])
            if chunks is None:
                return None  # torn chunk cache → whole row suspect
        cas = row.get("cas_id")
        media = payload.get("media")
        phash = payload.get("phash")
        if cas is not None and not isinstance(cas, str):
            return None
        if media is not None and not isinstance(media, str):
            return None
        if phash is not None and (
            not isinstance(phash, bytes) or len(phash) != 8
        ):
            return None
        return JournalEntry(
            identity=ident,
            stale=bool(row.get("stale")),
            cas_id=cas,
            thumb=bool(payload.get("thumb")),
            media_digest=media,
            phash=phash,
            embed=bool(payload.get("embed")),
            chunks=chunks,
        )
    except (TypeError, ValueError):
        return None


def _decode_payload(blob: Any) -> dict | None:
    """Strictly validated payload decode; None = corrupt/foreign."""
    if blob is None:
        return {}
    if not isinstance(blob, bytes):
        return None
    try:
        import msgpack

        obj = msgpack.unpackb(blob, raw=False)
    except Exception:  # noqa: BLE001 - torn/corrupt payload
        return None
    if not isinstance(obj, dict) or obj.get("v") != JOURNAL_FORMAT:
        return None
    return obj


#: process-lifetime per-location runtime counters (hits/misses/…,
#: bytes saved), keyed (db path, location_id) — IndexJournal instances
#: are transient per-call wrappers, so the counts live here the way
#: series live in the telemetry registry. Read by location_stats() for
#: the federation snapshot (GET /mesh, sdx mesh-status).
_LOC_RUNTIME: dict[tuple[str, int], dict[str, int]] = {}
_LOC_RUNTIME_LOCK = threading.Lock()
_LOC_FIELDS = ("hits", "misses", "invalidated", "bypassed", "bytes_saved")
#: hard cap on tracked (db, location) counter sets — libraries churned
#: by tests/bench arms would otherwise grow the dict for process
#: lifetime; eviction is oldest-inserted first (dict order)
_LOC_RUNTIME_MAX = 1024
_RT_KEY_SEQ = itertools.count()
#: location_stats() DB-half cache: (monotonic ts, db_half, live ids)
#: per db key — federation refreshes snapshots every ~5 s on the event
#: loop, and the GROUP BY scans one journal row per file
_STATS_CACHE: dict[str, tuple[float, dict, Any]] = {}
_STATS_TTL_S = 5.0


def reset_runtime() -> None:
    """Test/bench isolation (called by telemetry.reset()): drop the
    process-lifetime per-location counters and the stats cache."""
    with _LOC_RUNTIME_LOCK:
        _LOC_RUNTIME.clear()
    _STATS_CACHE.clear()


class IndexJournal:
    """Journal access bound to one library DB. Location scoping rides
    in each call's `location_id` (duplicates span locations)."""

    def __init__(self, db: Any):
        self.db = db

    def _db_key(self) -> str:
        """Runtime-counter namespace for this library DB. Disk DBs key
        by path; in-memory DBs (tests) would all collide on
        ":memory:", so each gets a token minted once per Database
        object (NOT id() — a recycled address must not inherit a dead
        DB's counters)."""
        path = str(getattr(self.db, "path", "?"))
        if path != ":memory:":
            return path
        tok = getattr(self.db, "_journal_rt_key", None)
        if tok is None:
            tok = f":memory:#{next(_RT_KEY_SEQ)}"
            try:
                self.db._journal_rt_key = tok
            except AttributeError:
                pass  # slotted/foreign db: fall back to per-call token
        return tok

    def _loc_count(self, location_id: int | None, field: str,
                   n: int = 1) -> None:
        if location_id is None:
            return
        key = (self._db_key(), int(location_id))
        with _LOC_RUNTIME_LOCK:
            stats = _LOC_RUNTIME.get(key)
            if stats is None:
                while len(_LOC_RUNTIME) >= _LOC_RUNTIME_MAX:
                    _LOC_RUNTIME.pop(next(iter(_LOC_RUNTIME)))
                stats = _LOC_RUNTIME[key] = dict.fromkeys(_LOC_FIELDS, 0)
            stats[field] += n

    # ---- consult -------------------------------------------------------

    def lookup(
        self, location_id: int, key: Key, identity: Identity | None,
        count_invalidated: bool = True, count: bool = True,
    ) -> tuple[str, JournalEntry | None]:
        """(verdict, entry). `hit` entries vouch for their cached
        results; `invalidated` entries are returned too — their chunk
        cache still powers dirty-range rehash. Every call counts on
        `sd_index_journal_ops_total`; a pipeline RE-consulting a file
        the walker already judged this pass (the identifier pulling the
        chunk cache) passes `count_invalidated=False` so one changed
        file counts one invalidation, keeping the hit rate per-file.
        `count=False` suppresses counting entirely — for probe-only
        consults (the watcher's debounce sizing) that are not pipeline
        verdicts and must not drag the /mesh hit rate."""
        if not enabled():
            if count:
                _tm.INDEX_JOURNAL_OPS.inc(result="bypassed")
                self._loc_count(location_id, "bypassed")
            return BYPASSED, None
        mat, name, ext = key
        try:
            row = self.db.query_one(
                "SELECT * FROM index_journal WHERE location_id = ? AND "
                "materialized_path = ? AND name = ? AND extension = ?",
                (location_id, mat, name, ext),
            )
        except sqlite3.Error:
            if count:
                _tm.INDEX_JOURNAL_OPS.inc(result="bypassed")
                self._loc_count(location_id, "bypassed")
            return BYPASSED, None
        if row is None:
            if count:
                _tm.INDEX_JOURNAL_OPS.inc(result="miss")
                self._loc_count(location_id, "misses")
            return MISS, None
        entry = self._entry_of(row)
        if entry is None:
            # corrupt row: drop it so the next pass starts clean
            self._delete_key(location_id, key)
            if count:
                _tm.INDEX_JOURNAL_OPS.inc(result="bypassed")
                self._loc_count(location_id, "bypassed")
            return BYPASSED, None
        if (
            not entry.stale
            and identity is not None
            and entry.identity == identity
        ):
            if count:
                _tm.INDEX_JOURNAL_OPS.inc(result="hit")
                self._loc_count(location_id, "hits")
            return HIT, entry
        if count_invalidated and count:
            _tm.INDEX_JOURNAL_OPS.inc(result="invalidated")
            self._loc_count(location_id, "invalidated")
        return INVALIDATED, entry

    #: keys per batched consult query — 3 bind params per key must stay
    #: under SQLite's default 999-variable limit with headroom
    CONSULT_CHUNK = 300

    def consult_many(
        self,
        location_id: int,
        items: list[tuple[Key, Identity | None]],
        count_invalidated: bool = True,
        count: bool = True,
    ) -> dict[Key, tuple[str, JournalEntry | None]]:
        """Batched :meth:`lookup`: one row-value ``IN`` query per
        ~:data:`CONSULT_CHUNK` keys instead of one SELECT per file —
        the per-entry-SQL floor of mesh shard execution (ROADMAP PR 9
        follow-up). Verdict semantics and counter discipline are
        IDENTICAL to per-key lookup (parity-tested in
        tests/test_serve.py), including the corrupt-row drop."""
        out: dict[Key, tuple[str, JournalEntry | None]] = {}
        if not items:
            return out
        if not enabled():
            for key, _ident in items:
                if count:
                    _tm.INDEX_JOURNAL_OPS.inc(result="bypassed")
                    self._loc_count(location_id, "bypassed")
                out[key] = (BYPASSED, None)
            return out
        rows_by_key: dict[Key, dict] = {}
        try:
            for start in range(0, len(items), self.CONSULT_CHUNK):
                chunk = items[start:start + self.CONSULT_CHUNK]
                placeholders = ",".join("(?,?,?)" for _ in chunk)
                params: list[Any] = [location_id]
                for (mat, name, ext), _ident in chunk:
                    params.extend((mat, name, ext))
                for row in self.db.query(
                    "SELECT * FROM index_journal WHERE location_id = ? "
                    "AND (materialized_path, name, extension) IN "
                    f"(VALUES {placeholders})",
                    params,
                ):
                    rows_by_key[(
                        row["materialized_path"], row["name"],
                        row["extension"],
                    )] = row
        except sqlite3.Error:
            for key, _ident in items:
                if count:
                    _tm.INDEX_JOURNAL_OPS.inc(result="bypassed")
                    self._loc_count(location_id, "bypassed")
                out[key] = (BYPASSED, None)
            return out
        pooled = self._consult_pool(
            location_id, items, rows_by_key, count_invalidated, count,
        )
        if pooled is not None:
            return pooled
        for key, identity in items:
            row = rows_by_key.get(key)
            if row is None:
                if count:
                    _tm.INDEX_JOURNAL_OPS.inc(result="miss")
                    self._loc_count(location_id, "misses")
                out[key] = (MISS, None)
                continue
            entry = self._entry_of(row)
            if entry is None:
                self._delete_key(location_id, key)
                if count:
                    _tm.INDEX_JOURNAL_OPS.inc(result="bypassed")
                    self._loc_count(location_id, "bypassed")
                out[key] = (BYPASSED, None)
                continue
            if (
                not entry.stale
                and identity is not None
                and entry.identity == identity
            ):
                if count:
                    _tm.INDEX_JOURNAL_OPS.inc(result="hit")
                    self._loc_count(location_id, "hits")
                out[key] = (HIT, entry)
                continue
            if count_invalidated and count:
                _tm.INDEX_JOURNAL_OPS.inc(result="invalidated")
                self._loc_count(location_id, "invalidated")
            out[key] = (INVALIDATED, entry)
        return out

    def _entry_of(self, row: dict) -> JournalEntry | None:
        return entry_of_row(row)

    #: smallest consult batch worth a pool round-trip — below this the
    #: msgpack+frame tax exceeds the decode work being escaped
    POOL_MIN_ITEMS = 16

    def _consult_pool(
        self,
        location_id: int,
        items: list[tuple[Key, Identity | None]],
        rows_by_key: dict[Key, dict],
        count_invalidated: bool,
        count: bool,
    ) -> dict[Key, tuple[str, JournalEntry | None]] | None:
        """consult_many's match half on the process pool: the fetched
        rows ship out as plain dicts, the per-row payload decode +
        strict validation + identity compare (the GIL-held middle of a
        warm consult) runs in a worker, and verdict COUNTING stays here
        — one writer per process. Returns None (caller runs the inline
        loop, rows already fetched) when the pool is off, the batch is
        too small, or anything about the round-trip fails. The gate
        counts FETCHED ROWS, not items: a cold pass (no journal rows)
        has no payloads to decode, and shipping a batch of misses
        would be pure IPC tax."""
        if len(rows_by_key) < self.POOL_MIN_ITEMS:
            return None
        from ...parallel import procpool as _procpool

        pool = _procpool.get()
        if pool is None:
            return None
        wire_items: list[list] = []
        wire_rows: list[dict | None] = []
        for key, ident in items:
            wire_items.append([
                list(key),
                [ident.inode, ident.dev, ident.mtime_ns, ident.size]
                if ident is not None else None,
            ])
            wire_rows.append(rows_by_key.get(key))
        try:
            reply = pool.request(
                "journal.match",
                {"items": wire_items, "rows": wire_rows},
                rows=len(items),
            )
            verdicts = reply["verdicts"]
            if len(verdicts) != len(items):
                raise ValueError("verdict count mismatch")
            out: dict[Key, tuple[str, JournalEntry | None]] = {}
            corrupt_keys: list[Key] = []
            tallies: list[str] = []
            for (key, _ident), (verdict, plain, corrupt) in zip(
                items, verdicts,
            ):
                if corrupt:
                    # corrupt row: dropped (below) so the next pass
                    # starts clean — the DB write stays owner-side
                    corrupt_keys.append(key)
                    tallies.append("bypassed")
                    out[key] = (BYPASSED, None)
                    continue
                entry = None
                if plain is not None:
                    chunks = None
                    if plain.get("chunks") is not None:
                        # worker-validated (entry_of_row) — direct
                        # construction skips a second O(chunks) pass
                        p = plain["chunks"]
                        chunks = ChunkCache(
                            p["len"], list(p["dig"]), p.get("cvs"))
                    entry = JournalEntry(
                        identity=Identity(*plain["identity"])
                        if plain.get("identity") is not None else None,
                        stale=bool(plain["stale"]),
                        cas_id=plain.get("cas_id"),
                        thumb=bool(plain.get("thumb")),
                        media_digest=plain.get("media"),
                        phash=plain.get("phash"),
                        embed=bool(plain.get("embed")),
                        chunks=chunks,
                    )
                if verdict == HIT:
                    tallies.append("hits")
                elif verdict == MISS:
                    tallies.append("misses")
                elif verdict == INVALIDATED:
                    tallies.append(
                        "invalidated" if count_invalidated else "")
                else:
                    raise ValueError(f"foreign verdict {verdict!r}")
                out[key] = (verdict, entry)
        except (_procpool.ProcPoolError, KeyError, TypeError, ValueError):
            # anything torn about the round-trip: the inline loop is
            # the fallback and the rows are already in hand. Nothing
            # was counted or deleted yet, so the fallback cannot
            # double-count a verdict.
            return None
        for key in corrupt_keys:
            self._delete_key(location_id, key)
        if count:
            agg = collections.Counter(t for t in tallies if t)
            if agg["hits"]:
                _tm.INDEX_JOURNAL_OPS.inc(agg["hits"], result="hit")
                self._loc_count(location_id, "hits", agg["hits"])
            if agg["misses"]:
                _tm.INDEX_JOURNAL_OPS.inc(agg["misses"], result="miss")
                self._loc_count(location_id, "misses", agg["misses"])
            if agg["invalidated"]:
                _tm.INDEX_JOURNAL_OPS.inc(
                    agg["invalidated"], result="invalidated")
                self._loc_count(
                    location_id, "invalidated", agg["invalidated"])
            if agg["bypassed"]:
                _tm.INDEX_JOURNAL_OPS.inc(agg["bypassed"], result="bypassed")
                self._loc_count(location_id, "bypassed", agg["bypassed"])
        return out

    # ---- record --------------------------------------------------------

    def record_cas(
        self,
        location_id: int,
        key: Key,
        identity: Identity,
        cas_id: str,
        chunks: ChunkCache | None = None,
    ) -> None:
        """Fresh vouch after the identifier's DB commit. Replaces the
        identity and cas; carries forward nothing (content changed ⇒
        thumb/media/phash vouches are void)."""
        if not enabled():
            return
        payload: dict[str, Any] = {"v": JOURNAL_FORMAT}
        if chunks is not None:
            payload["chunks"] = chunks.to_payload()
        self._write(location_id, key, identity, cas_id, payload)

    def record_many(
        self,
        location_id: int,
        records: list[
            tuple[Key, Identity, str, ChunkCache | None, JournalEntry | None]
        ],
    ) -> None:
        """Batch vouch (one transaction — an identifier window writes
        up to 1024×accelerators rows; per-row commits would dominate).
        Each record may carry the PRIOR journal entry: when the
        recomputed cas matches its cas_id the content is unchanged (an
        mtime-only touch), so the thumb/media/phash vouches carry
        forward instead of forcing a re-thumbnail + EXIF re-probe."""
        if not enabled() or not records:
            return
        import msgpack

        stamp = now_iso()
        rows = []
        for (mat, name, ext), ident, cas, chunks, carry in records:
            payload: dict[str, Any] = {"v": JOURNAL_FORMAT}
            if chunks is not None:
                payload["chunks"] = chunks.to_payload()
            if carry is not None and carry.cas_id == cas:
                if carry.thumb:
                    payload["thumb"] = True
                if carry.media_digest is not None:
                    payload["media"] = carry.media_digest
                if carry.phash is not None:
                    payload["phash"] = carry.phash
                if carry.embed:
                    payload["embed"] = True
            rows.append((
                location_id, mat, name, ext,
                u64_blob(ident.inode), u64_blob(ident.dev),
                u64_blob(ident.mtime_ns), u64_blob(ident.size),
                cas, msgpack.packb(payload), stamp,
            ))
        try:
            self.db.executemany(
                "INSERT INTO index_journal (location_id, materialized_path, "
                "name, extension, inode, dev, mtime_ns, size, cas_id, "
                "payload, stale, date_vouched) "
                "VALUES (?,?,?,?,?,?,?,?,?,?,0,?) "
                "ON CONFLICT (location_id, materialized_path, name, extension) "
                "DO UPDATE SET inode=excluded.inode, dev=excluded.dev, "
                "mtime_ns=excluded.mtime_ns, size=excluded.size, "
                "cas_id=excluded.cas_id, payload=excluded.payload, "
                "stale=0, date_vouched=excluded.date_vouched",
                rows,
            )
        except sqlite3.Error:
            logger.exception("index journal batch write failed (non-fatal)")

    def _write(
        self, location_id: int, key: Key, identity: Identity | None,
        cas_id: str | None, payload: dict,
    ) -> None:
        import msgpack

        mat, name, ext = key
        try:
            self.db.execute(
                "INSERT INTO index_journal (location_id, materialized_path, "
                "name, extension, inode, dev, mtime_ns, size, cas_id, "
                "payload, stale, date_vouched) "
                "VALUES (?,?,?,?,?,?,?,?,?,?,0,?) "
                "ON CONFLICT (location_id, materialized_path, name, extension) "
                "DO UPDATE SET inode=excluded.inode, dev=excluded.dev, "
                "mtime_ns=excluded.mtime_ns, size=excluded.size, "
                "cas_id=excluded.cas_id, payload=excluded.payload, "
                "stale=0, date_vouched=excluded.date_vouched",
                (
                    location_id, mat, name, ext,
                    u64_blob(identity.inode) if identity else None,
                    u64_blob(identity.dev) if identity else None,
                    u64_blob(identity.mtime_ns) if identity else None,
                    u64_blob(identity.size) if identity else None,
                    cas_id,
                    msgpack.packb(payload),
                    now_iso(),
                ),
            )
        except sqlite3.Error:
            logger.exception("index journal write failed (non-fatal)")

    def _amend_payload(
        self, location_id: int, key: Key, cas_id: str | None, **updates: Any,
    ) -> None:
        """Merge fields into a FRESH entry's payload. Refuses when the
        row is missing, stale, or vouches a different cas — an amend
        must never resurrect an invalidated vouch."""
        if not enabled():
            return
        import msgpack

        mat, name, ext = key
        try:
            with self.db.transaction() as conn:
                row = conn.execute(
                    "SELECT payload, cas_id, stale FROM index_journal "
                    "WHERE location_id = ? AND materialized_path = ? "
                    "AND name = ? AND extension = ?",
                    (location_id, mat, name, ext),
                ).fetchone()
                if row is None or row["stale"]:
                    return
                if cas_id is not None and row["cas_id"] != cas_id:
                    return
                payload = _decode_payload(row["payload"])
                if payload is None:
                    return
                payload["v"] = JOURNAL_FORMAT
                payload.update(updates)
                conn.execute(
                    "UPDATE index_journal SET payload = ?, date_vouched = ? "
                    "WHERE location_id = ? AND materialized_path = ? "
                    "AND name = ? AND extension = ?",
                    (msgpack.packb(payload), now_iso(), location_id, mat,
                     name, ext),
                )
        except sqlite3.Error:
            logger.exception("index journal amend failed (non-fatal)")

    def vouch_thumb(self, location_id: int, key: Key, cas_id: str) -> None:
        """Mark the thumbnail stored — call ONLY after the webp landed
        in the store (crash between store and this write is safe: the
        next pass re-checks the store and re-vouches)."""
        self._amend_payload(location_id, key, cas_id, thumb=True)

    def vouch_embed(self, location_id: int, key: Key, cas_id: str | None) -> None:
        """Mark the embedding persisted — call ONLY after the
        object_embedding row (and its sync ops) committed; a crash
        between commit and this write just re-embeds once."""
        self._amend_payload(location_id, key, cas_id, embed=True)

    def vouch_media(self, location_id: int, key: Key, cas_id: str | None,
                    digest: str) -> None:
        """Record the media-metadata digest after the media_data upsert.
        An empty digest is a valid vouch: "probed, nothing to extract"
        — it stops warm passes from re-probing EXIF-less files."""
        self._amend_payload(location_id, key, cas_id, media=digest)

    def record_phash(self, location_id: int, key: Key, cas_id: str | None,
                     phash: bytes) -> None:
        self._amend_payload(location_id, key, cas_id, phash=bytes(phash))

    # ---- invalidate ----------------------------------------------------

    def mark_stale(self, location_id: int, key: Key) -> int:
        """Targeted watcher invalidation: the entry stops vouching but
        keeps its chunk cache for the dirty-range rehash."""
        if not enabled():
            return 0
        mat, name, ext = key
        try:
            n = self.db.execute(
                "UPDATE index_journal SET stale = 1 WHERE location_id = ? "
                "AND materialized_path = ? AND name = ? AND extension = ? "
                "AND stale = 0",
                (location_id, mat, name, ext),
            ).rowcount
        except sqlite3.Error:
            return 0
        if n:
            _tm.INDEX_JOURNAL_OPS.inc(n, result="invalidated")
        return n

    def mark_stale_subtree(self, location_id: int, prefix: str) -> int:
        """Invalidate every entry under a materialized-path prefix
        (lost watcher events / RESCAN: unknown depths changed)."""
        if not enabled():
            return 0
        try:
            n = self.db.execute(
                "UPDATE index_journal SET stale = 1 WHERE location_id = ? "
                "AND substr(materialized_path, 1, ?) = ? AND stale = 0",
                (location_id, len(prefix), prefix),
            ).rowcount
        except sqlite3.Error:
            return 0
        if n:
            _tm.INDEX_JOURNAL_OPS.inc(n, result="invalidated")
        return n

    def _delete_key(self, location_id: int, key: Key) -> None:
        mat, name, ext = key
        try:
            self.db.execute(
                "DELETE FROM index_journal WHERE location_id = ? AND "
                "materialized_path = ? AND name = ? AND extension = ?",
                (location_id, mat, name, ext),
            )
        except sqlite3.Error:
            pass

    def delete_path(self, location_id: int, key: Key,
                    subtree_prefix: str | None = None) -> None:
        """Remove journal rows for a deleted path (and, for a removed
        directory, its whole subtree)."""
        if not enabled():
            return
        self._delete_key(location_id, key)
        if subtree_prefix is not None:
            try:
                self.db.execute(
                    "DELETE FROM index_journal WHERE location_id = ? AND "
                    "substr(materialized_path, 1, ?) = ?",
                    (location_id, len(subtree_prefix), subtree_prefix),
                )
            except sqlite3.Error:
                pass

    def rename_path(
        self, location_id: int, old_key: Key, new_key: Key,
        old_prefix: str | None = None, new_prefix: str | None = None,
    ) -> None:
        """A rename moves the key but keeps every vouch: content,
        thumbnail, and media are untouched by a rename. For a directory,
        pass the old/new materialized-path prefixes to move the subtree."""
        if not enabled():
            return
        try:
            # landing on an existing key would violate the PK: clear it
            self._delete_key(location_id, new_key)
            self.db.execute(
                "UPDATE index_journal SET materialized_path = ?, name = ?, "
                "extension = ? WHERE location_id = ? AND "
                "materialized_path = ? AND name = ? AND extension = ?",
                (*new_key, location_id, *old_key),
            )
            if old_prefix is not None and new_prefix is not None:
                rows = self.db.query(
                    "SELECT materialized_path, name, extension FROM "
                    "index_journal WHERE location_id = ? AND "
                    "substr(materialized_path, 1, ?) = ?",
                    (location_id, len(old_prefix), old_prefix),
                )
                for r in rows:
                    moved = new_prefix + r["materialized_path"][len(old_prefix):]
                    self._delete_key(
                        location_id, (moved, r["name"], r["extension"])
                    )
                    self.db.execute(
                        "UPDATE index_journal SET materialized_path = ? "
                        "WHERE location_id = ? AND materialized_path = ? "
                        "AND name = ? AND extension = ?",
                        (moved, location_id, r["materialized_path"],
                         r["name"], r["extension"]),
                    )
        except sqlite3.Error:
            logger.exception("index journal rename failed (non-fatal)")

    def bytes_saved(self, n: int, location_id: int | None = None) -> None:
        if n > 0:
            _tm.INDEX_JOURNAL_BYTES_SAVED.inc(n)
            self._loc_count(location_id, "bytes_saved", n)

    # ---- stats ---------------------------------------------------------

    def location_stats(self) -> dict[int, dict[str, Any]]:
        """Per-location journal effectiveness: persisted entry counts
        (DB truth) joined with this process's runtime verdict counters.
        Rides the federation snapshot's per-library block so hit rates
        and bytes saved show up on ``GET /mesh`` / ``sdx mesh-status``
        without any new wire surface.

        The DB half (a GROUP BY over one row per file, plus the live
        location-id set) is cached for ``_STATS_TTL_S`` per DB:
        federation refreshes every snapshot pull (5 s cadence,
        synchronous on the event loop), and a million-file library
        must not pay a full index_journal scan on each one. Runtime
        counters are merged fresh on every call."""
        db_path = self._db_key()
        now = time.monotonic()
        cached = _STATS_CACHE.get(db_path)
        if cached is not None and now - cached[0] < _STATS_TTL_S:
            db_half, live = cached[1], cached[2]
        else:
            db_half = {}
            try:
                rows = self.db.query(
                    "SELECT location_id, COUNT(*) AS entries, "
                    "COALESCE(SUM(stale), 0) AS stale "
                    "FROM index_journal GROUP BY location_id"
                )
            except sqlite3.Error:
                return {}
            for r in rows:
                db_half[int(r["location_id"])] = {
                    "entries": int(r["entries"]),
                    "stale_entries": int(r["stale"]),
                }
            try:
                live = {int(r["id"]) for r in self.db.query(
                    "SELECT id FROM location")}
            except sqlite3.Error:
                live = None
            while len(_STATS_CACHE) >= _LOC_RUNTIME_MAX:
                _STATS_CACHE.pop(next(iter(_STATS_CACHE)))
            _STATS_CACHE[db_path] = (now, db_half, live)
        out: dict[int, dict[str, Any]] = {
            loc: dict(v) for loc, v in db_half.items()
        }
        with _LOC_RUNTIME_LOCK:
            if live is not None:
                # a deleted location's counters must not haunt GET /mesh
                # until process restart (the DB rows are pruned by
                # prune_orphans; this prunes their runtime shadow)
                for key in [k for k in _LOC_RUNTIME
                            if k[0] == db_path and k[1] not in live]:
                    del _LOC_RUNTIME[key]
            runtime = {
                loc: dict(stats)
                for (path, loc), stats in _LOC_RUNTIME.items()
                if path == db_path
            }
        for loc, stats in runtime.items():
            entry = out.setdefault(
                loc, {"entries": 0, "stale_entries": 0})
            entry.update(stats)
            consults = (stats["hits"] + stats["misses"]
                        + stats["invalidated"])
            entry["hit_rate"] = (
                round(stats["hits"] / consults, 4) if consults else None
            )
        return out


#: orphan-prune delete batch: small enough that one DELETE holds the
#: write lock for milliseconds even against a million-row journal,
#: large enough that a typical prune is one round trip
PRUNE_BATCH = 2048


def prune_orphans(db: Any, batch: int = PRUNE_BATCH) -> int:
    """Drop journal rows whose file_path row vanished — the journal's
    share of the orphan-remover pass (object/orphan_remover.py). Uses
    the DB as the liveness source instead of re-stat'ing paths on disk.

    Deletes in bounded rowid batches: one unbounded DELETE against a
    million-row journal holds SQLite's write lock (and whichever thread
    issued it) for the whole scan. Callers on the event loop should use
    the async wrapper in object/orphan_remover.py, which yields between
    batches."""
    total = 0
    while True:
        n = prune_orphans_step(db, batch)
        total += n
        if n < max(1, batch):
            break
    return total


def prune_orphans_step(db: Any, batch: int = PRUNE_BATCH) -> int:
    """One bounded prune batch; a return < ``batch`` means the journal
    is clean. The orphan-remover actor's async path calls this between
    event-loop yields so a million-row prune can't freeze the loop."""
    batch = max(1, batch)
    try:
        n = db.execute(
            "DELETE FROM index_journal WHERE rowid IN ("
            "SELECT ij.rowid FROM index_journal ij "
            "WHERE NOT EXISTS ("
            "SELECT 1 FROM file_path fp WHERE "
            "fp.location_id = ij.location_id AND "
            "fp.materialized_path = ij.materialized_path AND "
            "fp.name = ij.name AND "
            "fp.extension = ij.extension) LIMIT ?)",
            (batch,),
        ).rowcount
    except sqlite3.Error:
        return 0
    n = max(0, n)
    if n:
        _tm.INDEX_JOURNAL_OPS.inc(n, result="invalidated")
    return n
