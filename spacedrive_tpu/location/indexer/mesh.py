"""Mesh-parallel indexing — one location's identify work, partitioned
across library peers.

The coordinating node walks + saves the location locally (the walk is
metadata-only and cheap — the bytes are the bottleneck), then splits
the resulting orphan file_paths into **journal-keyed shards**: each
entry carries the file-path key ``(materialized_path, name, ext)``
plus the stat identity ``(inode, dev, mtime_ns, size)``, so every
executor — local or remote — consults its OWN index journal before
reading a byte, and a peer that indexed this location before skips its
vouched files exactly like a warm local pass.

Execution is identical on every node (:func:`execute_shard`):

1. journal consult per entry (hit ⇒ reuse the vouched cas, zero I/O);
2. read + batch-hash the rest (device when available, the same
   ``ops.cas`` path the identifier job uses);
3. link objects with **deterministic pub_ids**
   (``object/file_identifier/link.py``) and emit the cas/object sync
   ops — results merge through the existing HLC/LWW path, so a
   twice-executed shard (lease expiry, claim race, peer death after
   sync but before ``complete``) converges instead of corrupting;
4. vouch the journal strictly AFTER the sync write committed, shipping
   ``(identity, cas, chunk-cache)`` back in ``complete`` so the
   coordinator's journal ends bit-identical to a single-node pass.

``distribute_location_index`` is the coordinator entry point: publish
→ announce → self-steal locally through the task system (the
coordinator is just another worker of its own board) → expire and
re-pool dead peers' leases → done when every shard completed. Chips
spanning hosts join through ``parallel.mesh.multihost_init`` (no-op
without a cluster env — the ``jax.distributed`` seam tests/test_multihost.py
exercises).
"""

from __future__ import annotations

import asyncio
import logging
import os
import time
import uuid
from typing import Any

from ...files.isolated_path import full_path_from_db_row
from ...ops import cas
from ...telemetry import metrics as _tm
from ...telemetry import span
from ...telemetry.events import WORK_EVENTS
from ...tasks.task import ExecStatus, Interrupter, Task
from . import journal as _journal

logger = logging.getLogger(__name__)

#: files per shard — small enough that a slow peer's lease stays short,
#: large enough that one claim amortizes a wire round-trip
SHARD_FILES = 128


def shard_files_default() -> int:
    return int(os.environ.get("SD_WORK_SHARD_FILES", str(SHARD_FILES)))


# --- shard building (coordinator) -----------------------------------------


def build_shard_entries(library: Any, location: dict) -> list[dict]:
    """Journal-keyed entries for every orphan file_path of a location:
    identity captured here (one stat per file) so peers can journal-
    match without trusting our verdicts."""
    rows = library.db.query(
        "SELECT * FROM file_path WHERE object_id IS NULL AND cas_id IS NULL "
        "AND is_dir = 0 AND location_id = ? ORDER BY id",
        (location["id"],),
    )
    entries: list[dict] = []
    for row in rows:
        full = full_path_from_db_row(location["path"], row)
        ident = _journal.stat_identity(full)
        from ...db.database import blob_u64

        entries.append({
            "pub_id": row["pub_id"].hex(),
            "mat": row["materialized_path"],
            "name": row["name"],
            "ext": row["extension"] or "",
            "size": blob_u64(row["size_in_bytes_bytes"]) or 0,
            "identity": (
                [ident.inode, ident.dev, ident.mtime_ns, ident.size]
                if ident is not None else None
            ),
        })
    return entries


def make_session(library: Any, location: dict, *,
                 shard_files: int | None = None,
                 lease_max_s: float | None = None) -> Any:
    """Split a location's orphan entries into a published-ready
    WorkSession."""
    from ...p2p.work import LEASE_MAX_S, WorkSession, WorkShard

    entries = build_shard_entries(library, location)
    n = max(1, shard_files or shard_files_default())
    session = WorkSession(
        id=uuid.uuid4().hex,
        library_id=library.id,
        location_pub=location["pub_id"].hex(),
        lease_max_s=lease_max_s if lease_max_s is not None else LEASE_MAX_S,
    )
    for i in range(0, len(entries), n):
        shard_id = f"{session.id[:8]}-{i // n:04d}"
        session.shards[shard_id] = WorkShard(
            id=shard_id, entries=entries[i:i + n]
        )
    return session


# --- shard execution (any node) -------------------------------------------


def _pool_for_backend(backend: str) -> Any:
    """The running process pool when this shard's hash leg is host-side
    (the pool never owns the accelerator — device backends keep the
    owner's batched dispatch), else None."""
    if backend in ("tpu", "device"):
        return None
    if backend == "auto" and cas._device_available():
        return None
    from ...parallel import procpool as _procpool

    return _procpool.get()


def _execute_shard_sync(library: Any, location: dict, entries: list[dict],
                        backend: str) -> list[dict]:
    """Worker-thread half of shard execution: journal consult → read →
    batch hash → link + vouch. Returns wire-shippable per-file results
    ``{pub_id, cas_id, ext, identity, chunks}``.

    With the multi-process plane live (``SD_PROCS`` > 0, CPU hash
    backend), the per-entry stat/read/chunk-digest/hash middle ships to
    pool workers in PipelinePolicy-sized quanta instead of running
    under this thread's GIL; journal consults, the sync-write commit,
    and the vouches stay on the owning process. Every pool failure
    degrades that batch to the identical inline stage function — the
    pool can slow a shard, never wrong it."""
    journal = _journal.IndexJournal(library.db)
    loc_id = location["id"]
    loc_path = location["path"]
    results: list[dict] = []
    messages: list[bytes] = []
    msg_results: list[dict] = []  # result dicts awaiting a cas
    to_record: list[tuple] = []   # journal vouches, written post-commit
    pool = _pool_for_backend(backend)
    # (plain entry, result, key, prior entry) per pool-shipped file
    pool_jobs: list[tuple[dict, dict, tuple, Any]] = []
    pool_bytes = 0  # expected message bytes riding the pool (span size)
    # stat pass first, then ONE batched journal consult for the whole
    # shard — the per-file SELECT was the GIL-bound floor ROADMAP PR 9
    # called out (128-entry shard = 128 round-trips into SQLite)
    stats: list[tuple[dict, "_journal.Identity | None"]] = []
    for e in entries:
        row = {"materialized_path": e["mat"], "name": e["name"],
               "extension": e["ext"], "is_dir": False}
        full = full_path_from_db_row(loc_path, row)
        stats.append((e, _journal.stat_identity(full)))
    consults = journal.consult_many(loc_id, [
        ((e["mat"], e["name"], e["ext"]), ident)
        for e, ident in stats
        if ident is not None and ident.size > 0
    ])
    for e, ident in stats:
        key = (e["mat"], e["name"], e["ext"])
        row = {"materialized_path": e["mat"], "name": e["name"],
               "extension": e["ext"], "is_dir": False}
        full = full_path_from_db_row(loc_path, row)
        result = {
            "pub_id": e["pub_id"], "ext": e["ext"], "cas_id": None,
            "identity": (
                [ident.inode, ident.dev, ident.mtime_ns, ident.size]
                if ident is not None else None
            ),
            "chunks": None,
        }
        results.append(result)
        if ident is None:
            continue  # vanished/unreadable: the next walk removes it
        if ident.size == 0:
            result["cas_id"] = ""
            to_record.append((key, ident, "", None, None))
            continue
        verdict, entry = consults.get(key, (_journal.MISS, None))
        if verdict == _journal.HIT and entry.cas_id:
            result["cas_id"] = entry.cas_id
            result["chunks"] = (
                entry.chunks.to_payload() if entry.chunks is not None
                else None
            )
            journal.bytes_saved(cas.message_len(ident.size),
                                location_id=loc_id)
            continue
        if pool is not None:
            pool_jobs.append((
                {"pub_id": e["pub_id"], "mat": e["mat"],
                 "name": e["name"], "ext": e["ext"]},
                result, key, entry,
            ))
            pool_bytes += cas.message_len(ident.size)
            continue
        try:
            msg = cas.read_message(full, ident.size)
        except OSError as exc:
            logger.debug("mesh shard: unreadable %s: %s", full, exc)
            result["identity"] = None  # no vouch for an unreadable file
            continue
        messages.append(msg)
        msg_results.append(result)
        cache = cas.build_chunk_cache(msg)
        to_record.append((key, ident, result, cache, entry))
        result["chunks"] = cache.to_payload()
    if messages:
        t_hash = time.perf_counter()
        with span("mesh.shard_hash", nbytes=sum(len(m) for m in messages)):
            cas_ids = cas.cas_ids(messages, backend)
        # feed the same stage series the identifier job feeds, so
        # autotune.observed_files_per_s (the lease-sizing throughput
        # self-report) stays honest about mesh-executed files too
        _tm.IDENTIFIER_STAGE_SECONDS.observe(
            time.perf_counter() - t_hash, stage="hash")
        _tm.INDEX_BYTES_HASHED.inc(sum(len(m) for m in messages))
        for result, cas_hex in zip(msg_results, cas_ids):
            result["cas_id"] = cas_hex
    if pool_jobs:
        _pool_hash(pool, loc_path, pool_jobs, to_record, pool_bytes)
    _tm.IDENTIFIER_FILES.inc(len(entries))

    # link + sync write FIRST, then the journal vouch (truth discipline:
    # a crash in between costs a redundant rehash, never a lie)
    from ...object.file_identifier.link import apply_cas_results

    t_db = time.perf_counter()
    apply_cas_results(library, results)
    records = []
    for key, ident, cas_or_result, cache, carry in to_record:
        cas_hex = (
            cas_or_result["cas_id"] if isinstance(cas_or_result, dict)
            else cas_or_result
        )
        if cas_hex is not None:  # "" = vouched-empty sentinel
            records.append((key, ident, cas_hex, cache, carry))
    journal.record_many(loc_id, records)
    _tm.IDENTIFIER_STAGE_SECONDS.observe(
        time.perf_counter() - t_db, stage="db")
    return results


def _pool_hash(pool: Any, loc_path: str, jobs: list[tuple],
               to_record: list[tuple], nbytes: int = 0) -> None:
    """Fan the shard's hash-needing entries across the process pool in
    PipelinePolicy-sized quanta, filling each entry's result dict and
    vouch record from the worker's plain reply. A batch whose pool trip
    fails (worker error past the retry budget, pool mid-shutdown) runs
    the SAME stage function inline — output is identical by
    construction, only the parallelism is lost."""
    from ...parallel import autotune as _autotune
    from ...parallel import procpool as _procpool
    from ...parallel import procworker as _procworker

    quanta = max(1, _autotune.policy("identify").procpool_batch_rows())
    batches = [jobs[i:i + quanta] for i in range(0, len(jobs), quanta)]
    futures = []
    for batch in batches:
        plain_entries = [plain for plain, _res, _key, _carry in batch]
        payload = {"loc_path": loc_path, "entries": plain_entries}
        try:
            futures.append(pool.submit(
                "identify.hash_entries", payload, rows=len(batch)))
        except _procpool.ProcPoolError:
            futures.append(None)  # degrade this batch inline below
    t_hash = time.perf_counter()
    with span("procpool.hash_entries", nbytes=nbytes):
        for batch, fut in zip(batches, futures):
            out = None
            if fut is not None:
                try:
                    out = fut.result(_procpool.REQUEST_TIMEOUT_S)["results"]
                except Exception as exc:  # noqa: BLE001 - degrade inline
                    logger.warning(
                        "procpool hash batch failed (%s); inline fallback",
                        exc)
            if out is None:
                out = _procworker._stage_hash_entries({
                    "loc_path": loc_path,
                    "entries": [p for p, _r, _k, _c in batch],
                })["results"]
            for (_plain, result, key, carry), rec in zip(batch, out):
                ident_raw = rec.get("identity")
                result["identity"] = ident_raw
                result["cas_id"] = rec.get("cas_id")
                result["chunks"] = rec.get("chunks")
                if ident_raw is None or rec.get("cas_id") is None:
                    continue  # unreadable/vanished: no vouch
                # the worker already built + validated this cache
                # (build_chunk_cache output shipped verbatim) — direct
                # construction skips a second O(chunks) validation
                cache = None
                if rec.get("chunks") is not None:
                    p = rec["chunks"]
                    cache = cas.ChunkCache(
                        p["len"], list(p["dig"]), p.get("cvs"))
                to_record.append((
                    key, _journal.Identity(*(int(x) for x in ident_raw)),
                    rec["cas_id"], cache, carry,
                ))
    # the hash leg's WALL, observed once owner-side (the worker stage
    # deliberately does not observe this series: concurrent workers'
    # per-batch times would merge to CPU-seconds and make
    # autotune.observed_files_per_s — the lease-sizing throughput
    # self-report — read a pool-accelerated node as unaccelerated)
    _tm.IDENTIFIER_STAGE_SECONDS.observe(
        time.perf_counter() - t_hash, stage="hash")


async def resolve_location(library: Any, location_pub: str | None) -> dict:
    """Wait for a session's location row to exist on this replica. The
    row syncs like any other; a replica that has not ingested it yet
    nudges its ingest actor and waits briefly — a still-missing
    location raises, the caller skips, and the lease expires back to
    the pool. Shared by every stage executor (identify here, the rest
    in ``stages.py``)."""
    location = None
    loc_pub_bytes = bytes.fromhex(location_pub) if location_pub else None
    for attempt in range(20):
        if loc_pub_bytes is not None:
            location = library.db.find_one("location", pub_id=loc_pub_bytes)
        if location is not None and location.get("path"):
            break
        # the location create op may still be in flight: pull now
        actor = getattr(library, "ingest", None)
        if actor is not None:
            actor.notify()
        await asyncio.sleep(0.05)
    if location is None or not location.get("path"):
        raise RuntimeError(f"location {location_pub} not replicated here yet")
    return location


async def execute_shard(node: Any, library: Any, location_pub: str | None,
                        entries: list[dict], backend: str | None = None) \
        -> list[dict]:
    """Execute one identify shard against this node's replica (the
    stage-generic entry point is ``stages.execute_stage_shard``)."""
    location = await resolve_location(library, location_pub)
    if backend is None:
        backend = "auto" if getattr(node, "use_device", False) else "cpu"
    return await asyncio.to_thread(
        _execute_shard_sync, library, location, entries, backend
    )


class ShardTask(Task):
    """Local shard execution as a task-system unit: the coordinator's
    self-steal loop dispatches these so queue-wait/occupancy telemetry
    and priority preemption cover mesh work like any other work. Stage-
    typed: the task routes to its shard's execution leg."""

    def __init__(self, node: Any, library: Any, location_pub: str,
                 entries: list[dict], backend: str | None = None,
                 stage: str = "identify.hash"):
        super().__init__()
        self.node = node
        self.library = library
        self.location_pub = location_pub
        self.entries = entries
        self.backend = backend
        self.stage = stage
        self.output: list[dict] | None = None

    async def run(self, interrupter: Interrupter) -> ExecStatus:
        if interrupter.check() is not None:
            return ExecStatus.CANCELED
        from .stages import execute_stage_shard

        self.output = await execute_stage_shard(
            self.node, self.library, self.location_pub, self.stage,
            self.entries, self.backend,
        )
        return ExecStatus.DONE


# --- result merge (coordinator, from `complete` bodies) -------------------


def apply_remote_results(node: Any, session: Any, results: list[dict]) -> int:
    """Merge a peer's shipped shard results into this node's replica:
    cas/object rows via the idempotent linker, then journal vouches
    keyed by the identity the executor hashed under — the coordinator's
    journal converges to what a single-node pass would have written,
    without waiting for the peer's sync ops."""
    library = node.libraries.get(session.library_id)
    if library is None:
        return 0
    location = library.db.find_one(
        "location", pub_id=bytes.fromhex(session.location_pub)
    )
    if location is None:
        return 0
    from ...object.file_identifier.link import apply_cas_results

    clean = [r for r in results if isinstance(r, dict)]
    # emit_ops=False: the executing peer already minted the CRDT ops
    # (before its complete) — this is the direct-apply fast path, sync
    # remains the authoritative carrier
    apply_cas_results(library, clean, emit_ops=False)
    journal = _journal.IndexJournal(library.db)
    records = []
    for r in clean:
        ident_raw = r.get("identity")
        cas_hex = r.get("cas_id")
        if ident_raw is None or cas_hex is None:
            continue
        try:
            ident = _journal.Identity(*(int(x) for x in ident_raw))
        except (TypeError, ValueError):
            continue
        chunks = None
        if r.get("chunks") is not None:
            chunks = cas.ChunkCache.from_payload(r["chunks"])
        row = library.db.find_one(
            "file_path", pub_id=bytes.fromhex(str(r["pub_id"]))
        )
        if row is None or row.get("materialized_path") is None:
            continue  # create op not applied yet; peer's vouch suffices
        records.append((_journal.key_of(row), ident, cas_hex, chunks, None))
    journal.record_many(location["id"], records)
    return len(records)


# --- the coordinator loop -------------------------------------------------


async def distribute_location_index(
    node: Any,
    library: Any,
    location_id: int,
    *,
    shard_files: int | None = None,
    lease_max_s: float | None = None,
    backend: str | None = None,
    run_indexer: bool = True,
    deadline_s: float = 600.0,
) -> dict[str, Any]:
    """Walk locally, partition the identify work, and drive it to
    completion across the mesh. Returns pass stats (shards by executor,
    files, seconds). Degrades to a plain local pass when no peers are
    reachable — announce failures and refused claims only mean every
    shard ends up self-stolen."""
    from ...parallel.mesh import multihost_init

    t0 = time.perf_counter()
    location = library.db.find_one("location", id=location_id)
    if location is None or not location.get("path"):
        raise ValueError(f"location {location_id} not found")

    if run_indexer:
        from ...jobs.manager import JobBuilder
        from .job import IndexerJob

        await JobBuilder(IndexerJob({"location_id": location_id})).spawn(
            node.jobs, library
        )
        await node.jobs.wait_idle()

    # chips spanning hosts: join the jax.distributed cluster when the
    # env names one (no-op single-host; tests/test_multihost.py is the
    # seam proving the initialized path hashes correctly)
    multihost_init()

    session = make_session(
        library, location, shard_files=shard_files, lease_max_s=lease_max_s
    )
    return await _drive_session(
        node, library, session, backend=backend, deadline_s=deadline_s,
        t0=t0,
    )


async def distribute_location_stages(
    node: Any,
    library: Any,
    location_id: int,
    stage_ids: list[str],
    *,
    shard_files: int | None = None,
    lease_max_s: float | None = None,
    backend: str | None = None,
    deadline_s: float = 600.0,
) -> dict[str, Any]:
    """Distribute any set of post-identify pipeline stages for one
    location as ONE multi-stage session (stage ids from
    ``parallel/scheduler.py``). The stage drivers' distribute paths
    (thumbnail actor, media processor, duplicates pHash, embed) are
    thin wrappers over this. Degrades exactly like
    ``distribute_location_index``: with no P2P runtime every stage
    shard runs inline here, which IS today's pure-local pass in shard
    clothing."""
    from .stages import make_stage_session

    t0 = time.perf_counter()
    location = library.db.find_one("location", id=location_id)
    if location is None or not location.get("path"):
        raise ValueError(f"location {location_id} not found")
    session = make_stage_session(
        library, location, stage_ids,
        shard_files=shard_files, lease_max_s=lease_max_s,
    )
    return await _drive_session(
        node, library, session, backend=backend, deadline_s=deadline_s,
        t0=t0,
    )


async def _drive_session(
    node: Any, library: Any, session: Any, *,
    backend: str | None, deadline_s: float, t0: float,
) -> dict[str, Any]:
    """Drive a published-ready session to completion: publish →
    announce → self-steal through the task system → retire. Shared by
    the identify pass and the stage-typed distribute paths."""
    from .stages import execute_stage_shard

    manager = getattr(node, "p2p", None)
    plane = getattr(manager, "work", None)
    total_files = sum(len(s.entries) for s in session.shards.values())
    by_stage: dict[str, int] = {}
    for sh in session.shards.values():
        by_stage[sh.stage] = by_stage.get(sh.stage, 0) + 1
    # with the multi-process plane live, the coordinator keeps several
    # shards in flight at once: one shard's owner-side SQL commit
    # overlaps another's worker-side hashing. SD_PROCS=0 keeps today's
    # strictly sequential self-steal (the golden path).
    from ...parallel import procpool as _procpool

    width = _procpool.procs() if _procpool.get() is not None else 1
    if plane is None:
        # no P2P runtime: run every shard inline (still shard-shaped so
        # the journal/link/vouch path is identical)
        if width > 1:
            sem = asyncio.Semaphore(width)

            async def _one_inline(shard: Any) -> None:
                async with sem:
                    await execute_stage_shard(
                        node, library, session.location_pub,
                        shard.stage, shard.entries, backend,
                    )

            await asyncio.gather(*(
                _one_inline(s) for s in session.shards.values()
            ))
        else:
            for shard in session.shards.values():
                await execute_stage_shard(
                    node, library, session.location_pub, shard.stage,
                    shard.entries, backend,
                )
        return {
            "session": session.id, "shards": len(session.shards),
            "files": total_files, "local_shards": len(session.shards),
            "remote_shards": 0, "peers": {}, "stages": by_stage,
            "seconds": round(time.perf_counter() - t0, 3),
        }

    plane.board.publish(session)
    acks = await plane.announce(session)
    WORK_EVENTS.emit("distribute_start", session=session.id,
                     shards=len(session.shards), peers_acked=acks)

    deadline = time.monotonic() + deadline_s
    local_shards = 0
    try:
        while not session.all_done():
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"mesh session {session.id} incomplete after "
                    f"{deadline_s}s ({session.pending()} shards pending)"
                )
            _session, grant, _lease = plane.board.claim(
                session.id, "local", max_shards=width, local=True,
            )
            if not grant:
                # everything is leased out (or done): wait for completes
                # / lease expiries; expire_leases runs inside claim()
                await asyncio.sleep(0.05)
                continue
            # normally one shard (`width` with the process pool live —
            # the execute leg keeps every pool worker fed); an injected
            # claim race can append a duplicate-leased one — execute
            # everything granted so a shard re-leased to "local"
            # (exempt from expiry) can never strand
            handles = [
                (shard, node.task_system.dispatch(ShardTask(
                    node, library, session.location_pub, shard.entries,
                    backend, stage=shard.stage,
                )))
                for shard in grant
            ]
            for shard, handle in handles:
                result = await handle.wait()
                if result.error is not None:
                    raise result.error
                outcome = plane.board.complete(
                    session.id, shard.id, "local", local=True
                )
                if outcome == "completed":
                    local_shards += 1
    finally:
        # success or abandonment: drop the session from the board — the
        # shard entry lists duplicate the location's file metadata, and
        # a nightly coordinator must not accumulate one copy per pass
        # (workers see "done" and stop; late results still ride sync)
        plane.board.retire(session.id)

    by_peer: dict[str, int] = {}
    for shard_id, pid in session.completed_by.items():
        from ...telemetry.peers import peer_label

        label = "local" if pid == "local" else peer_label(pid)
        by_peer[label] = by_peer.get(label, 0) + 1
    stats = {
        "session": session.id,
        "shards": len(session.shards),
        "files": total_files,
        "local_shards": local_shards,
        "remote_shards": len(session.shards) - local_shards,
        "peers": by_peer,
        "stages": by_stage,
        "seconds": round(time.perf_counter() - t0, 3),
    }
    WORK_EVENTS.emit(
        "distribute_done",
        session=stats["session"],
        shards=stats["shards"],
        files=stats["files"],
        remote=stats["remote_shards"],
    )
    return stats
