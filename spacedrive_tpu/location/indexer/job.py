"""IndexerJob — walk a location and persist file_path rows in batches.

Parity: ref:core/src/location/indexer/{indexer_job.rs,mod.rs} —
BATCH_SIZE = 1000 paths per step (:47), save/update steps emitting CRDT
ops (`execute_indexer_save_step`), delete of vanished rows, run
metadata with scan/db timings (:76-88), shallow variant (shallow.rs).

TPU-first note: the indexer is pure host-side metadata work; its output
(orphan file_paths) is what feeds the TPU cas_id batches downstream.
"""

from __future__ import annotations

import logging
import os
import time
from typing import Any

from ...db.database import blob_u64, new_pub_id, now_iso, u64_blob
from ...files.isolated_path import IsolatedFilePathData
from ...jobs import StatefulJob
from ...jobs.job import JobContext, JobError, StepResult
from ...jobs.manager import register_job
from ...telemetry import span
from .journal import IndexJournal, Identity, key_of
from .rules import load_rules_for_location
from .walker import walk, walk_single_dir

logger = logging.getLogger(__name__)

BATCH_SIZE = 1000  # ref:indexer_job.rs:47


class _JournalCheck:
    """Per-walk index-journal consult, counting verdicts for the walk
    span (the counters themselves increment inside IndexJournal)."""

    def __init__(self, journal: IndexJournal, loc_id: int):
        self.journal = journal
        self.loc_id = loc_id
        self.counts: dict[str, int] = {}

    def __call__(self, iso, meta) -> str:
        verdict, _entry = self.journal.lookup(
            self.loc_id, key_of(iso), Identity.from_metadata(meta)
        )
        self.counts[verdict] = self.counts.get(verdict, 0) + 1
        if verdict == "hit" and meta.size_in_bytes:
            # a vouched unchanged file: its whole sampled message will
            # never be read/hashed/shipped this pass
            from ...ops.cas import message_len

            self.journal.bytes_saved(message_len(meta.size_in_bytes),
                                     location_id=self.loc_id)
        return verdict


def _entry_to_step_dict(entry, update: bool = False) -> dict[str, Any]:
    iso = entry.iso_file_path
    meta = entry.metadata
    d = {
        "pub_id": entry.pub_id,
        "materialized_path": iso.materialized_path,
        "name": iso.name,
        "extension": iso.extension,
        "is_dir": iso.is_dir,
        "inode": meta.inode if meta else 0,
        "size": meta.size_in_bytes if meta else 0,
        "created_at": meta.created_at.isoformat(timespec="milliseconds") if meta else None,
        "modified_at": meta.modified_at.isoformat(timespec="milliseconds") if meta else None,
        "hidden": bool(meta.hidden) if meta else False,
        "object_id": entry.object_id,
    }
    if update and not iso.is_dir:
        # a changed row whose identity the journal does NOT vouch for
        # must lose its cas_id/object link so the identifier re-hashes
        # the new content (a journal `hit` here means only metadata —
        # e.g. the hidden flag — changed, so the cas is still current).
        # Without a journal verdict (bypassed/disabled) err on re-hash:
        # a stale cas_id is worse than a redundant one.
        d["clear_cas"] = entry.journal_verdict != "hit"
    return d


@register_job
class IndexerJob(StatefulJob):
    """init: {location_id, sub_path?, shallow?}"""

    NAME = "indexer"
    INVALIDATES = ("search.paths", "locations.list", "library.statistics")

    async def init_job(self, ctx: JobContext) -> None:
        t0 = time.perf_counter()
        library = ctx.library
        location = library.db.find_one("location", id=self.init["location_id"])
        if location is None or not location.get("path"):
            raise JobError(f"location {self.init['location_id']} not found")
        loc_path = location["path"]
        loc_id = location["id"]

        root = loc_path
        if self.init.get("sub_path"):
            root = os.path.join(loc_path, self.init["sub_path"].lstrip("/"))

        self.data["location_id"] = loc_id
        self.data["location_pub_id"] = location["pub_id"].hex()
        self.run_metadata.update(
            total_paths=0, updated_paths=0, removed_paths=0,
            scan_read_time=0.0, db_write_time=0.0, indexing_errors=0,
        )
        if self.init.get("shallow"):
            rules, iso_factory, fetcher, remover, jcheck = self._walk_env(ctx)
            result = walk_single_dir(
                root, rules, iso_factory, fetcher, remover,
                journal_check=jcheck,
            )
            self.steps.extend(self._steps_from_result(result))
        else:
            self.steps.extend(self._run_walk(ctx, root, None))
        self.run_metadata["scan_read_time"] = round(time.perf_counter() - t0, 4)
        ctx.progress(
            message=f"indexed {self.run_metadata['total_paths']} paths",
            phase="indexing",
        )

    def _walk_env(self, ctx: JobContext):
        library = ctx.library
        loc_id = self.data["location_id"]
        location = library.db.find_one("location", id=loc_id)
        loc_path = location["path"]
        rules = load_rules_for_location(library.db, loc_id)

        def iso_factory(p: str, is_dir: bool) -> IsolatedFilePathData:
            return IsolatedFilePathData.new(loc_id, loc_path, p, is_dir)

        def file_paths_fetcher(isos):
            rows = []
            for iso in isos:
                row = library.db.find_one(
                    "file_path",
                    location_id=loc_id,
                    materialized_path=iso.materialized_path,
                    name=iso.name,
                    extension=iso.extension,
                )
                if row is not None:
                    rows.append(row)
            return rows

        def to_remove_fetcher(parent_iso, found_isos):
            found = {(i.materialized_path, i.name, i.extension) for i in found_isos}
            children_mat = parent_iso.materialized_path_for_children() or "/"
            rows = library.db.query(
                "SELECT pub_id, cas_id, object_id, materialized_path, name, extension "
                "FROM file_path WHERE location_id = ? AND materialized_path = ?",
                (loc_id, children_mat),
            )
            return [
                r for r in rows
                if (r["materialized_path"], r["name"], r["extension"]) not in found
            ]

        return (
            rules, iso_factory, file_paths_fetcher, to_remove_fetcher,
            _JournalCheck(IndexJournal(library.db), loc_id),
        )

    def _run_walk(self, ctx: JobContext, root: str, accepted: bool | None) -> list[dict]:
        """One bounded walk; leftover dirs become 'walk' continuation
        steps so arbitrarily large locations index completely."""
        rules, iso_factory, fetcher, remover, jcheck = self._walk_env(ctx)
        with span("walk") as walk_span:
            result = walk(
                root, rules, iso_factory, fetcher, remover,
                update_notifier=lambda p, n: None,
                initial_accepted_by_children=accepted,
                journal_check=jcheck,
            )
            if jcheck.counts:
                # journal verdicts over EVERY walked file (unchanged
                # files included) — the warm-pass hit-rate evidence
                walk_span.annotate(
                    **{f"journal_{k}": v for k, v in jcheck.counts.items()}
                )
        steps = self._steps_from_result(result)
        for leftover in result.to_walk:
            steps.append(
                {
                    "kind": "walk",
                    "path": leftover.path,
                    "accepted": leftover.parent_dir_accepted_by_its_children,
                }
            )
        return steps

    def _steps_from_result(self, result) -> list[dict]:
        steps: list[dict] = []
        for i in range(0, len(result.walked), BATCH_SIZE):
            steps.append(
                {"kind": "save", "entries": [
                    _entry_to_step_dict(e) for e in result.walked[i:i + BATCH_SIZE]
                ]}
            )
        for i in range(0, len(result.to_update), BATCH_SIZE):
            steps.append(
                {"kind": "update", "entries": [
                    _entry_to_step_dict(e, update=True)
                    for e in result.to_update[i:i + BATCH_SIZE]
                ]}
            )
        removals = [r["pub_id"] for r in result.to_remove]
        for i in range(0, len(removals), BATCH_SIZE):
            steps.append({"kind": "remove", "pub_ids": removals[i:i + BATCH_SIZE]})
        md = self.run_metadata
        md["total_paths"] = md.get("total_paths", 0) + len(result.walked)
        md["updated_paths"] = md.get("updated_paths", 0) + len(result.to_update)
        md["removed_paths"] = md.get("removed_paths", 0) + len(removals)
        md["indexing_errors"] = md.get("indexing_errors", 0) + len(result.errors)
        return steps

    async def execute_step(self, ctx: JobContext, step: dict, step_number: int) -> StepResult:
        t0 = time.perf_counter()
        library = ctx.library
        loc_id = self.data["location_id"]
        kind = step["kind"]

        if kind == "walk":
            t_scan = time.perf_counter()
            more = self._run_walk(ctx, step["path"], step.get("accepted"))
            self.run_metadata["scan_read_time"] = round(
                self.run_metadata.get("scan_read_time", 0.0)
                + time.perf_counter() - t_scan, 4
            )
            return StepResult(more_steps=more)
        if kind == "save":
            self._save_batch(library, loc_id, step["entries"], update=False)
        elif kind == "update":
            self._save_batch(library, loc_id, step["entries"], update=True)
        elif kind == "remove":
            ops = []
            for pub_id in step["pub_ids"]:
                ops.extend([library.sync.shared_delete("file_path", pub_id.hex())])

            def deletes(conn):
                for pub_id in step["pub_ids"]:
                    conn.execute("DELETE FROM file_path WHERE pub_id = ?", (pub_id,))

            library.sync.write_ops(ops, deletes)
        self.run_metadata["db_write_time"] = round(
            self.run_metadata.get("db_write_time", 0.0) + time.perf_counter() - t0, 4
        )
        return StepResult()

    def _save_batch(self, library, loc_id: int, entries: list[dict], update: bool) -> None:
        sync = library.sync
        loc_pub = self.data["location_pub_id"]
        ops = []
        for e in entries:
            rid = e["pub_id"].hex()
            if update:
                # only the fields the local UPDATE below mutates sync —
                # identity fields (path/name/location) can't have changed
                fields = [
                    ("hidden", e["hidden"]),
                    ("size_in_bytes_bytes", e["size"]),
                    ("inode", e["inode"]),
                    ("date_modified", e["modified_at"]),
                ]
                if e.get("clear_cas"):
                    # content changed and the journal doesn't vouch for
                    # the old cas: void it (and the object link) so the
                    # identifier's orphan query re-hashes this row
                    fields.extend([("cas_id", None), ("object_id", None)])
                ops.extend(
                    sync.shared_update("file_path", rid, f, v)
                    for f, v in fields
                )
            else:
                ops.extend(
                    sync.shared_create(
                        "file_path", rid,
                        [
                            # FK columns sync as the target's sync id
                            # (sync/apply.py)
                            ("location_id", loc_pub),
                            ("is_dir", e["is_dir"]),
                            ("materialized_path", e["materialized_path"]),
                            ("name", e["name"]),
                            ("extension", e["extension"]),
                            ("hidden", e["hidden"]),
                            ("size_in_bytes_bytes", e["size"]),
                            ("inode", e["inode"]),
                            ("date_created", e["created_at"]),
                            ("date_modified", e["modified_at"]),
                        ],
                    )
                )

        date_indexed = now_iso()

        def writes(conn):
            for e in entries:
                if update:
                    clear = ", cas_id=NULL, object_id=NULL" if e.get("clear_cas") else ""
                    conn.execute(
                        f"UPDATE file_path SET inode=?, size_in_bytes_bytes=?, "
                        f"date_modified=?, hidden=?, date_indexed=?{clear} "
                        f"WHERE pub_id=?",
                        (
                            u64_blob(e["inode"]), u64_blob(e["size"]),
                            e["modified_at"], int(e["hidden"]), date_indexed,
                            e["pub_id"],
                        ),
                    )
                else:
                    conn.execute(
                        "INSERT INTO file_path (pub_id, is_dir, location_id, "
                        "materialized_path, name, extension, hidden, "
                        "size_in_bytes_bytes, inode, date_created, date_modified, "
                        "date_indexed) VALUES (?,?,?,?,?,?,?,?,?,?,?,?) "
                        "ON CONFLICT (location_id, materialized_path, name, extension) "
                        "DO UPDATE SET inode=excluded.inode, "
                        "size_in_bytes_bytes=excluded.size_in_bytes_bytes, "
                        "date_modified=excluded.date_modified, hidden=excluded.hidden",
                        (
                            e["pub_id"], int(e["is_dir"]), loc_id,
                            e["materialized_path"], e["name"], e["extension"],
                            int(e["hidden"]), u64_blob(e["size"]), u64_blob(e["inode"]),
                            e["created_at"], e["modified_at"], date_indexed,
                        ),
                    )

        sync.write_ops(ops, writes)

    async def finalize(self, ctx: JobContext) -> Any:
        from ..locations import update_location_size

        library = ctx.library
        loc_id = self.data.get("location_id")
        if loc_id is not None:
            self._rollup_directory_sizes(library, loc_id)
            update_location_size(library, loc_id)
        ctx.progress(message="indexing complete", phase="done")
        return dict(self.run_metadata)

    @staticmethod
    def _rollup_directory_sizes(library, loc_id: int) -> None:
        """Directory rows get the sum of their subtree's file sizes
        (ref:location/mod.rs reverse_update_directories_sizes).
        One pass over files accumulating into every ancestor prefix —
        O(files × depth) — then a single executemany."""
        totals: dict[str, int] = {}
        for f in library.db.query(
            "SELECT materialized_path, size_in_bytes_bytes FROM file_path "
            "WHERE location_id = ? AND is_dir = 0",
            (loc_id,),
        ):
            size = blob_u64(f["size_in_bytes_bytes"]) or 0
            mat = f["materialized_path"]  # "/a/b/"
            parts = mat.strip("/").split("/") if mat != "/" else []
            prefix = "/"
            for part in parts:
                prefix = f"{prefix}{part}/"
                totals[prefix] = totals.get(prefix, 0) + size
        dirs = library.db.query(
            "SELECT id, materialized_path, name FROM file_path "
            "WHERE location_id = ? AND is_dir = 1",
            (loc_id,),
        )
        library.db.executemany(
            "UPDATE file_path SET size_in_bytes_bytes = ? WHERE id = ?",
            [
                (
                    u64_blob(totals.get(f"{d['materialized_path']}{d['name']}/", 0)),
                    d["id"],
                )
                for d in dirs
            ],
        )
