"""Filesystem walker with injected DB fetchers.

Parity: ref:core/src/location/indexer/walk.rs — breadth-first walk over
a to_walk queue (:119-200), per-entry rule application and the
accept-by-children state machine (:476-586), ancestor backfill (:616-
661), symlink skip, existing-row diffing into to_create/to_update
(:334-430), and per-directory to_remove fetching (:664-680).

The DB is injected as plain callables (exactly the reference's
generics-based design) so the walker unit-tests hermetically.
"""

from __future__ import annotations

import logging
import os
import uuid
from dataclasses import dataclass, field
from typing import Any, Callable, Iterable

from ...files.isolated_path import FilePathMetadata, IsolatedFilePathData
from .rules import IndexerRule, RuleKind

logger = logging.getLogger(__name__)

TO_WALK_QUEUE_INITIAL_CAPACITY = 32
WALKER_PATHS_BUFFER_INITIAL_CAPACITY = 512


@dataclass
class WalkedEntry:
    iso_file_path: IsolatedFilePathData
    metadata: FilePathMetadata | None
    pub_id: bytes = field(default_factory=lambda: uuid.uuid4().bytes)
    object_id: int | None = None  # set for to_update entries
    # index-journal verdict for file entries ("hit"|"miss"|"invalidated"|
    # "bypassed"; None when no journal was consulted) — a non-hit on a
    # to_update entry tells the job to clear cas_id so the identifier
    # re-hashes the changed content
    journal_verdict: str | None = None

    def key(self):
        return self.iso_file_path


@dataclass
class ToWalkEntry:
    path: str
    parent_dir_accepted_by_its_children: bool | None = None
    maybe_parent: str | None = None


@dataclass
class WalkResult:
    walked: list[WalkedEntry]                 # to create
    to_update: list[WalkedEntry]              # changed vs DB
    to_walk: list[ToWalkEntry]                # remaining when limit hit
    to_remove: list[dict[str, Any]]           # DB rows no longer on disk
    errors: list[Exception]
    paths_and_sizes: dict[str, int]           # dir -> accumulated bytes


# fetcher signatures (injected):
#   file_paths_db_fetcher(iso_paths) -> rows with keys
#       {pub_id, object_id, inode, hidden, date_modified, size_in_bytes_bytes,
#        materialized_path, name, extension, is_dir}
#   to_remove_db_fetcher(parent_iso, found_iso_paths) -> rows
#       {pub_id, cas_id, object_id, ...}
#   journal_check(iso, metadata) -> verdict string — the index-journal
#       consult for every walked FILE (location/indexer/journal.py);
#       injected like the DB fetchers so the walker stays hermetic
FilePathsFetcher = Callable[[list[IsolatedFilePathData]], list[dict]]
ToRemoveFetcher = Callable[[IsolatedFilePathData, list[IsolatedFilePathData]], list[dict]]
JournalCheck = Callable[[IsolatedFilePathData, FilePathMetadata], str]


def walk(
    root: str | os.PathLike,
    indexer_rules: list[IndexerRule],
    iso_file_path_factory: Callable[[str, bool], IsolatedFilePathData],
    file_paths_db_fetcher: FilePathsFetcher,
    to_remove_db_fetcher: ToRemoveFetcher,
    update_notifier: Callable[[str, int], None] | None = None,
    limit: int = 100_000,
    initial_accepted_by_children: bool | None = None,
    journal_check: JournalCheck | None = None,
) -> WalkResult:
    """Full recursive walk from `root` (ref:walk.rs:119-200). When the
    limit is hit, the remaining dirs come back in `to_walk` so callers
    can continue in later steps (ref keep_walking, walk.rs:200)."""
    root = os.fspath(root)
    to_walk: list[ToWalkEntry] = [ToWalkEntry(root, initial_accepted_by_children, None)]
    indexed_paths: dict[IsolatedFilePathData, WalkedEntry] = {}
    errors: list[Exception] = []
    paths_and_sizes: dict[str, int] = {}
    to_remove: list[dict] = []

    while to_walk:
        entry = to_walk.pop(0)
        entry_size, removed = _inner_walk_single_dir(
            root, entry, indexer_rules, iso_file_path_factory,
            to_remove_db_fetcher, indexed_paths, to_walk, errors,
            update_notifier,
        )
        to_remove.extend(removed)
        paths_and_sizes[entry.path] = paths_and_sizes.get(entry.path, 0) + entry_size
        if entry.maybe_parent is not None:
            paths_and_sizes[entry.maybe_parent] = (
                paths_and_sizes.get(entry.maybe_parent, 0) + entry_size
            )
        if len(indexed_paths) >= limit:
            break

    walked, to_update = _filter_existing_paths(
        indexed_paths, file_paths_db_fetcher, journal_check
    )
    return WalkResult(walked, to_update, to_walk, to_remove, errors, paths_and_sizes)


def walk_single_dir(
    root: str | os.PathLike,
    indexer_rules: list[IndexerRule],
    iso_file_path_factory: Callable[[str, bool], IsolatedFilePathData],
    file_paths_db_fetcher: FilePathsFetcher,
    to_remove_db_fetcher: ToRemoveFetcher,
    journal_check: JournalCheck | None = None,
) -> WalkResult:
    """Shallow walk (one directory, no recursion) — the light-rescan
    path (ref:walk.rs:265 walk_single_dir, shallow.rs)."""
    root = os.fspath(root)
    indexed_paths: dict[IsolatedFilePathData, WalkedEntry] = {}
    errors: list[Exception] = []
    size, removed = _inner_walk_single_dir(
        root, ToWalkEntry(root), indexer_rules, iso_file_path_factory,
        to_remove_db_fetcher, indexed_paths, None, errors, None,
    )
    walked, to_update = _filter_existing_paths(
        indexed_paths, file_paths_db_fetcher, journal_check
    )
    return WalkResult(walked, to_update, [], removed, errors, {root: size})


def _inner_walk_single_dir(
    root: str,
    entry: ToWalkEntry,
    indexer_rules: list[IndexerRule],
    iso_file_path_factory: Callable[[str, bool], IsolatedFilePathData],
    to_remove_db_fetcher: ToRemoveFetcher,
    indexed_paths: dict[IsolatedFilePathData, WalkedEntry],
    maybe_to_walk: list[ToWalkEntry] | None,
    errors: list[Exception],
    update_notifier: Callable[[str, int], None] | None,
) -> tuple[int, list[dict]]:
    path = entry.path
    try:
        iso_to_walk = iso_file_path_factory(path, True)
    except Exception as e:  # noqa: BLE001
        errors.append(e)
        return 0, []
    try:
        dir_entries = list(os.scandir(path))
    except OSError as e:
        errors.append(e)
        return 0, []

    paths_buffer: dict[IsolatedFilePathData, WalkedEntry] = {}

    for dirent in dir_entries:
        accept_by_children_dir = entry.parent_dir_accepted_by_its_children
        current_path = dirent.path

        if update_notifier is not None:
            update_notifier(current_path, len(indexed_paths) + len(paths_buffer))

        rules_per_kind = IndexerRule.apply_all(indexer_rules, current_path)

        # rejected by any reject-glob (ref:walk.rs:519-527)
        if any(not ok for ok in rules_per_kind.get(RuleKind.REJECT_FILES_BY_GLOB, [])):
            continue

        try:
            st = dirent.stat(follow_symlinks=False)
            if dirent.is_symlink():
                continue  # symlinks hard-ignored (ref:walk.rs:540)
            is_dir = dirent.is_dir(follow_symlinks=False)
        except OSError as e:
            errors.append(e)
            continue

        if is_dir:
            # reject dir + children entirely (ref:walk.rs:546-557)
            if any(
                not ok
                for ok in rules_per_kind.get(
                    RuleKind.REJECT_IF_CHILDREN_DIRECTORIES_ARE_PRESENT, []
                )
            ):
                continue
            accept_results = rules_per_kind.get(
                RuleKind.ACCEPT_IF_CHILDREN_DIRECTORIES_ARE_PRESENT
            )
            if accept_results is not None:
                if any(accept_results):
                    accept_by_children_dir = True
                if accept_by_children_dir is None:
                    accept_by_children_dir = False
            if maybe_to_walk is not None:
                maybe_to_walk.append(
                    ToWalkEntry(current_path, accept_by_children_dir, path)
                )

        # rejected when accept-globs exist and none matched (ref:walk.rs:588-597)
        accepts = rules_per_kind.get(RuleKind.ACCEPT_FILES_BY_GLOB)
        if accepts is not None and all(not a for a in accepts):
            continue

        if accept_by_children_dir is None or accept_by_children_dir:
            try:
                iso = iso_file_path_factory(current_path, is_dir)
                metadata = FilePathMetadata.from_path(current_path, st)
            except Exception as e:  # noqa: BLE001
                errors.append(e)
                continue
            paths_buffer[iso] = WalkedEntry(iso, metadata)

            # ancestor backfill up to (not incl.) root (ref:walk.rs:616-661)
            ancestor = os.path.dirname(current_path)
            while ancestor != root and len(ancestor) > len(root):
                try:
                    aiso = iso_file_path_factory(ancestor, True)
                except Exception as e:  # noqa: BLE001
                    errors.append(e)
                    break
                if aiso in indexed_paths or aiso in paths_buffer:
                    break
                try:
                    ameta = FilePathMetadata.from_path(ancestor)
                except OSError as e:
                    errors.append(e)
                    ancestor = os.path.dirname(ancestor)
                    continue
                paths_buffer[aiso] = WalkedEntry(aiso, ameta)
                ancestor = os.path.dirname(ancestor)

    try:
        to_remove = to_remove_db_fetcher(iso_to_walk, list(paths_buffer.keys()))
    except Exception as e:  # noqa: BLE001
        errors.append(e)
        to_remove = []

    entry_size = sum(
        w.metadata.size_in_bytes for w in paths_buffer.values() if w.metadata
    )
    indexed_paths.update(paths_buffer)
    return entry_size, to_remove


def _filter_existing_paths(
    indexed_paths: dict[IsolatedFilePathData, WalkedEntry],
    file_paths_db_fetcher: FilePathsFetcher,
    journal_check: JournalCheck | None = None,
) -> tuple[list[WalkedEntry], list[WalkedEntry]]:
    """Split into (to_create, to_update) against existing DB rows
    (ref:walk.rs:334-430): an existing row updates when inode, mtime
    (±1 ms) or hidden changed — directory sizes are ignored. Every FILE
    entry additionally gets its index-journal verdict (the per-file
    hit/miss/invalidated stream a warm pass is measured by)."""
    if not indexed_paths:
        return [], []
    if journal_check is not None:
        for iso, entry in indexed_paths.items():
            if not iso.is_dir and entry.metadata is not None:
                try:
                    entry.journal_verdict = journal_check(iso, entry.metadata)
                except Exception:  # noqa: BLE001 - journal must not kill walks
                    logger.exception("journal_check failed")
                    entry.journal_verdict = None
    try:
        rows = file_paths_db_fetcher(list(indexed_paths.keys()))
    except Exception:  # noqa: BLE001 - treat fetch failure as "no rows"
        logger.exception("file_paths_db_fetcher failed; treating all as new")
        rows = []

    from ...db.database import blob_u64

    in_db: dict[IsolatedFilePathData, dict] = {}
    for row in rows:
        iso = IsolatedFilePathData.from_db_row(
            row.get("location_id", 0),
            row["materialized_path"],
            row["name"],
            row["extension"],
            bool(row["is_dir"]),
        )
        in_db[iso] = row

    to_create: list[WalkedEntry] = []
    to_update: list[WalkedEntry] = []
    for iso, entry in indexed_paths.items():
        row = in_db.get(iso)
        if row is None:
            to_create.append(entry)
            continue
        meta = entry.metadata
        if meta is None or row.get("inode") is None:
            continue
        changed = (
            blob_u64(row["inode"]) != meta.inode
            or _mtime_differs(row.get("date_modified"), meta)
            or row.get("hidden") is None
            or bool(row["hidden"]) != meta.hidden
        )
        if changed:
            entry.pub_id = row["pub_id"]
            entry.object_id = row.get("object_id")
            to_update.append(entry)
    return to_create, to_update


def _mtime_differs(stored: str | None, meta: FilePathMetadata) -> bool:
    if stored is None:
        return True
    import datetime as _dt

    try:
        old = _dt.datetime.fromisoformat(stored)
    except ValueError:
        return True
    delta = meta.modified_at - old
    return abs(delta.total_seconds()) > 0.001
