"""Filesystem indexer: rule engine + walker + indexer job.

Parity: ref:core/src/location/indexer/ (rules/mod.rs, walk.rs,
indexer_job.rs, shallow.rs).
"""

from .rules import IndexerRule, RuleKind, RulePerKind, system_rules
from .walker import WalkedEntry, WalkResult, walk, walk_single_dir

__all__ = [
    "IndexerRule",
    "RuleKind",
    "RulePerKind",
    "system_rules",
    "WalkedEntry",
    "WalkResult",
    "walk",
    "walk_single_dir",
]
