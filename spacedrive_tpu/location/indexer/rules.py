"""Indexer rule engine.

Parity: ref:core/src/location/indexer/rules/mod.rs —
four rule kinds (:154-158), per-kind apply semantics (:430-560), and
the seeded system rules (`seed.rs:42-215`: no_os_protected, no_hidden,
no_git, only_images with fixed pub_ids uuid(0..3)).

Globs use globset syntax (``**``, ``*``, ``?``, ``[...]``, ``{a,b}``),
compiled to regexes here.
"""

from __future__ import annotations

import enum
import os
import re
import uuid
from dataclasses import dataclass, field
from typing import Sequence

import msgpack


class RuleKind(enum.IntEnum):
    ACCEPT_FILES_BY_GLOB = 0
    REJECT_FILES_BY_GLOB = 1
    ACCEPT_IF_CHILDREN_DIRECTORIES_ARE_PRESENT = 2
    REJECT_IF_CHILDREN_DIRECTORIES_ARE_PRESENT = 3


def glob_to_regex(glob: str) -> str:
    """globset-syntax glob -> regex string (anchored).

    Semantics follow the globset crate with its DEFAULT settings (the
    reference parses plain `Glob`s, ref:rules/mod.rs:187-195): `*` and
    `?` MAY cross `/` (literal_separator=false), so `*.jpg` matches any
    absolute path ending in .jpg and `**/.*` rejects anything under a
    hidden component; `{a,b}` alternates; `[...]` is a class; `**/`
    also matches the empty prefix.
    """
    return _translate(glob) + r"\Z"


def _translate(glob: str) -> str:
    i, n = 0, len(glob)
    out: list[str] = []
    while i < n:
        c = glob[i]
        if c == "*":
            if glob[i:i + 2] == "**" and glob[i + 2:i + 3] == "/":
                # "**/" -> any (possibly empty) directory prefix
                out.append("(?:.*/)?")
                i += 3
            else:
                out.append(".*")
                i += 2 if glob[i:i + 2] == "**" else 1
        elif c == "?":
            out.append(".")
            i += 1
        elif c == "[":
            j = i + 1
            if j < n and glob[j] in "!^":
                j += 1
            if j < n and glob[j] == "]":
                j += 1
            while j < n and glob[j] != "]":
                j += 1
            if j >= n:
                out.append(re.escape(c))
                i += 1
            else:
                cls = glob[i + 1:j]
                if cls.startswith("!"):
                    cls = "^" + cls[1:]
                out.append(f"[{cls}]")
                i = j + 1
        elif c == "{":
            j = i + 1
            depth = 1
            while j < n and depth:
                if glob[j] == "{":
                    depth += 1
                elif glob[j] == "}":
                    depth -= 1
                j += 1
            if depth:
                out.append(re.escape(c))
                i += 1
            else:
                inner = glob[i + 1:j - 1]
                parts = _split_alternation(inner)
                out.append("(?:" + "|".join(_translate(p) for p in parts) + ")")
                i = j
        else:
            out.append(re.escape(c))
            i += 1
    return "".join(out)


def _split_alternation(inner: str) -> list[str]:
    parts, depth, cur = [], 0, []
    for ch in inner:
        if ch == "{":
            depth += 1
        elif ch == "}":
            depth -= 1
        if ch == "," and depth == 0:
            parts.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    parts.append("".join(cur))
    return parts


class GlobSet:
    """Compiled set of globs; matches if any matches. Like globset, a
    relative pattern matches the *full* path only — so system rules use
    `**/` prefixes to hit any depth."""

    def __init__(self, globs: Sequence[str]):
        self.globs = list(globs)
        self._res = [re.compile(glob_to_regex(g)) for g in globs]

    def is_match(self, path: str) -> bool:
        p = path.replace(os.sep, "/")
        return any(r.match(p) for r in self._res)


@dataclass
class RulePerKind:
    kind: RuleKind
    params: list[str]  # globs or child-dir names
    _glob_set: GlobSet | None = field(default=None, repr=False, compare=False)

    def __post_init__(self) -> None:
        if self.kind in (RuleKind.ACCEPT_FILES_BY_GLOB, RuleKind.REJECT_FILES_BY_GLOB):
            self._glob_set = GlobSet(self.params)

    def apply(self, path: str) -> tuple[RuleKind, bool]:
        """(kind, passed). Semantics per ref:rules/mod.rs:430-560:
        accept-glob passes iff it matches; reject-glob passes iff it
        does NOT match; children rules inspect the dir's entries."""
        if self.kind == RuleKind.ACCEPT_FILES_BY_GLOB:
            return self.kind, self._glob_set.is_match(path)
        if self.kind == RuleKind.REJECT_FILES_BY_GLOB:
            return self.kind, not self._glob_set.is_match(path)
        has_child = _dir_has_children(path, set(self.params))
        if self.kind == RuleKind.ACCEPT_IF_CHILDREN_DIRECTORIES_ARE_PRESENT:
            return self.kind, has_child
        return self.kind, not has_child


def _dir_has_children(path: str, names: set[str]) -> bool:
    try:
        if not os.path.isdir(path):
            return False
        with os.scandir(path) as it:
            for entry in it:
                if entry.name in names and entry.is_dir(follow_symlinks=False):
                    return True
    except OSError:
        return False
    return False


@dataclass
class IndexerRule:
    name: str
    rules: list[RulePerKind]
    default: bool = False
    pub_id: bytes = field(default_factory=lambda: uuid.uuid4().bytes)

    def apply(self, path: str) -> list[tuple[RuleKind, bool]]:
        return [r.apply(path) for r in self.rules]

    @staticmethod
    def apply_all(rules: Sequence["IndexerRule"], path: str) -> dict[RuleKind, list[bool]]:
        out: dict[RuleKind, list[bool]] = {}
        for rule in rules:
            for kind, ok in rule.apply(path):
                out.setdefault(kind, []).append(ok)
        return out

    # --- persistence (rules_per_kind column, msgpack) ---

    def serialize_rules(self) -> bytes:
        return msgpack.packb(
            [{"kind": int(r.kind), "params": r.params} for r in self.rules],
            use_bin_type=True,
        )

    @classmethod
    def deserialize(cls, name: str, raw: bytes, default: bool = False,
                    pub_id: bytes | None = None) -> "IndexerRule":
        rules = [
            RulePerKind(RuleKind(o["kind"]), o["params"])
            for o in msgpack.unpackb(raw, raw=False)
        ]
        return cls(name, rules, default, pub_id or uuid.uuid4().bytes)


# --- seeded system rules (ref:rules/seed.rs; fixed pub_ids, never reorder) ---

def no_os_protected() -> IndexerRule:
    return IndexerRule(
        "No OS protected",
        [
            RulePerKind(
                RuleKind.REJECT_FILES_BY_GLOB,
                [
                    "**/.spacedrive",
                    # linux (gitignore Global/Linux + FHS special dirs)
                    "**/*~",
                    "**/.fuse_hidden*",
                    "**/.directory",
                    "**/.Trash-*",
                    "**/.nfs*",
                    "/{dev,sys,proc}",
                    "/{run,var,boot}",
                    "**/lost+found",
                ],
            )
        ],
        default=True,
        pub_id=uuid.UUID(int=0).bytes,
    )


def no_hidden() -> IndexerRule:
    return IndexerRule(
        "No Hidden",
        [RulePerKind(RuleKind.REJECT_FILES_BY_GLOB, ["**/.*"])],
        default=False,
        pub_id=uuid.UUID(int=1).bytes,
    )


def no_git() -> IndexerRule:
    return IndexerRule(
        "No Git",
        [
            RulePerKind(
                RuleKind.REJECT_FILES_BY_GLOB,
                ["**/{.git,.gitignore,.gitattributes,.gitkeep,.gitconfig,.gitmodules}"],
            )
        ],
        default=False,
        pub_id=uuid.UUID(int=2).bytes,
    )


def only_images() -> IndexerRule:
    return IndexerRule(
        "Only Images",
        [
            RulePerKind(
                RuleKind.ACCEPT_FILES_BY_GLOB,
                ["*.{avif,bmp,gif,ico,jpeg,jpg,png,svg,tif,tiff,webp}"],
            )
        ],
        default=False,
        pub_id=uuid.UUID(int=3).bytes,
    )


def system_rules() -> list[IndexerRule]:
    """DO NOT REORDER (pub_ids are positional, ref:seed.rs:42)."""
    return [no_os_protected(), no_hidden(), no_git(), only_images()]


def seed_rules(db) -> None:
    """Upsert system rules into a library DB (ref:seed.rs:40-72)."""
    from ...db.database import now_iso

    for rule in system_rules():
        existing = db.find_one("indexer_rule", pub_id=rule.pub_id)
        blob = rule.serialize_rules()
        if existing:
            db.update(
                "indexer_rule", {"pub_id": rule.pub_id},
                name=rule.name, rules_per_kind=blob,
                **{"default": int(rule.default)},
            )
        else:
            db.insert(
                "indexer_rule", pub_id=rule.pub_id, name=rule.name,
                rules_per_kind=blob, date_created=now_iso(),
                date_modified=now_iso(), **{"default": int(rule.default)},
            )


def load_rules_for_location(db, location_id: int) -> list[IndexerRule]:
    rows = db.query(
        "SELECT ir.* FROM indexer_rule ir "
        "JOIN indexer_rule_in_location iril ON iril.indexer_rule_id = ir.id "
        "WHERE iril.location_id = ?",
        (location_id,),
    )
    return [
        IndexerRule.deserialize(
            r["name"] or "", r["rules_per_kind"], bool(r["default"]), r["pub_id"]
        )
        for r in rows
    ]
