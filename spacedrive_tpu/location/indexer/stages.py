"""Stage-typed shard execution — the per-stage legs of the unified
execution continuum (``parallel/scheduler.py``).

``location/indexer/mesh.py`` proved the shape for identify: journal-
keyed entries, executor-side journal consult, procpool CPU leg,
idempotent results shipping back in ``complete``. This module
generalizes it to the remaining pipeline stages — thumbnails, media
extraction, duplicates pHash, semantic embeddings — so a WORK shard of
ANY stage executes identically on every node:

1. **journal first**: every executor consults its OWN index journal
   before touching a byte (a warm peer's vouched thumb/phash/embed is
   served from its local store/DB — warm-peer hits count);
2. **procpool middle**: the stage's CPU-bound leg (webp encode, gray
   decode, embed decode) ships to the executor's local process pool in
   PipelinePolicy-sized quanta, inline-degrading on any pool failure —
   the pool can slow a shard, never wrong it (PR 15 contract);
3. **idempotent results**: per-file results ship back in ``complete``
   and merge through :func:`apply_stage_results` — deterministic
   content (same webp encoder, same derived embed params, same DCT
   pHash) means a re-stolen or double-leased shard of any stage
   converges bit-identical to a single-node pass;
4. **vouch last**: journal vouches are written strictly AFTER the
   durable commit (store write, media_data upsert, phash UPDATE,
   object_embedding transaction) — truth discipline, same as identify.

Rows that only exist locally (``media_data``, ``object.phash``,
``object_embedding``'s table row) converge because results ship; the
embed stage ADDITIONALLY mints the same CRDT ops a local pass would
(``sync.shared_create``), so vectors replicate to non-participant
peers exactly like PR 16's local pass.
"""

from __future__ import annotations

import asyncio
import logging
import time
import uuid
from typing import Any, Callable

from ...files.isolated_path import full_path_from_db_row
from ...parallel import scheduler as _scheduler
from ...telemetry import span
from . import journal as _journal

logger = logging.getLogger(__name__)


# --- shard building (coordinator) -----------------------------------------


_THUMBABLE: tuple[str, ...] | None = None
_MEDIA_EXTS: tuple[str, ...] | None = None
_IMAGE_EXTS: tuple[str, ...] | None = None


def _ext_sets() -> tuple[tuple[str, ...], tuple[str, ...], tuple[str, ...]]:
    global _THUMBABLE, _MEDIA_EXTS, _IMAGE_EXTS
    if _THUMBABLE is None:
        from ...object.media.job import (
            MEDIA_DATA_EXTENSIONS,
            THUMBNAILABLE_EXTENSIONS,
        )
        from ...object.media.thumbnail.process import IMAGE_EXTENSIONS

        _THUMBABLE = tuple(THUMBNAILABLE_EXTENSIONS)
        _MEDIA_EXTS = tuple(MEDIA_DATA_EXTENSIONS)
        _IMAGE_EXTS = tuple(IMAGE_EXTENSIONS)
    return _THUMBABLE, _MEDIA_EXTS, _IMAGE_EXTS


def build_stage_entries(library: Any, location: dict,
                        stage_id: str) -> list[dict]:
    """Journal-keyed entries for one stage of a location — the same
    work-list the stage's local job would build (identified rows with
    the stage's input available), each entry carrying everything an
    executor needs without waiting on row sync: the file-path key, the
    cas, and the deterministic object pub."""
    if stage_id == _scheduler.STAGE_IDENTIFY:
        from .mesh import build_shard_entries

        return build_shard_entries(library, location)
    thumbable, media_exts, image_exts = _ext_sets()
    exts = {
        _scheduler.STAGE_THUMB: thumbable,
        _scheduler.STAGE_MEDIA: media_exts,
        _scheduler.STAGE_PHASH: image_exts,
        _scheduler.STAGE_EMBED: image_exts,
    }[stage_id]
    extra = ""
    if stage_id == _scheduler.STAGE_PHASH:
        # mirror DuplicateDetectorJob's work-list: only objects still
        # missing a pHash (vouched reuse happens executor-side)
        extra = " AND o.phash IS NULL"
    qmarks = ",".join("?" for _ in exts)
    rows = library.db.query(
        f"SELECT fp.pub_id, fp.materialized_path, fp.name, fp.extension, "
        f"fp.cas_id, o.pub_id AS obj_pub "
        f"FROM file_path fp JOIN object o ON fp.object_id = o.id "
        f"WHERE fp.location_id = ? AND fp.is_dir = 0 "
        f"AND fp.cas_id IS NOT NULL AND fp.extension IN ({qmarks})"
        f"{extra} ORDER BY fp.id",
        (location["id"], *exts),
    )
    return [
        {
            "pub_id": r["pub_id"].hex(),
            "mat": r["materialized_path"],
            "name": r["name"],
            "ext": r["extension"] or "",
            "cas_id": r["cas_id"],
            "obj_pub": r["obj_pub"].hex(),
        }
        for r in rows
    ]


def make_stage_session(library: Any, location: dict, stage_ids: list[str], *,
                       shard_files: int | None = None,
                       lease_max_s: float | None = None) -> Any:
    """ONE multi-stage WorkSession covering every requested stage of a
    location: shards carry their stage id, and a single announce fans
    the whole pass out (peers steal whichever stage they are fastest
    at — the board's per-stage rate preference does the matching)."""
    from ...p2p.work import LEASE_MAX_S, WorkSession, WorkShard
    from .mesh import shard_files_default

    n = max(1, shard_files or shard_files_default())
    session = WorkSession(
        id=uuid.uuid4().hex,
        library_id=library.id,
        location_pub=location["pub_id"].hex(),
        lease_max_s=lease_max_s if lease_max_s is not None else LEASE_MAX_S,
    )
    for stage_id in stage_ids:
        spec = _scheduler.spec(stage_id)  # loud on a typo'd stage
        if stage_id == _scheduler.STAGE_EMBED:
            from ...models import embedder as _embedder

            if not _embedder.enabled():
                continue  # SD_EMBED=0: the stage simply publishes nothing
        entries = build_stage_entries(library, location, stage_id)
        for i in range(0, len(entries), n):
            shard_id = f"{session.id[:8]}-{spec.id}-{i // n:04d}"
            session.shards[shard_id] = WorkShard(
                id=shard_id, entries=entries[i:i + n], stage=stage_id,
            )
    return session


# --- per-stage execution (any node) ----------------------------------------


async def execute_stage_shard(
    node: Any, library: Any, location_pub: str | None, stage_id: str,
    entries: list[dict], backend: str | None = None,
) -> list[dict]:
    """Execute one stage-typed shard against this node's replica —
    the dispatch seam both the mesh worker and the coordinator's
    self-steal ride. Observes the per-stage throughput EWMA the
    control loop sizes leases from."""
    from .mesh import execute_shard, resolve_location

    t0 = time.monotonic()
    if stage_id == _scheduler.STAGE_IDENTIFY:
        results = await execute_shard(
            node, library, location_pub, entries, backend)
    else:
        fn = _SYNC_EXECUTORS[stage_id]
        location = await resolve_location(library, location_pub)
        results = await asyncio.to_thread(fn, node, library, location,
                                          entries)
    _scheduler.RATES.observe(stage_id, len(entries),
                             time.monotonic() - t0)
    return results


def _consult(journal: Any, loc_id: int, loc_path: str,
             entry: dict) -> tuple[str, Any, str]:
    """One executor-side journal consult for a stage entry. Returns
    ``(verdict, journal_entry, full_path)`` — callers check the
    stage's own vouch field AND that the vouch is for this exact cas
    (count_invalidated=False: the walker already judged changed files
    this pass)."""
    row = {"materialized_path": entry["mat"], "name": entry["name"],
           "extension": entry["ext"], "is_dir": False}
    full = full_path_from_db_row(loc_path, row)
    verdict, jentry = journal.lookup(
        loc_id, (entry["mat"], entry["name"], entry["ext"]),
        _journal.stat_identity(full), count_invalidated=False,
    )
    return verdict, jentry, full


def _object_by_pub(library: Any, obj_pub_hex: str) -> dict | None:
    try:
        return library.db.find_one(
            "object", pub_id=bytes.fromhex(str(obj_pub_hex)))
    except ValueError:
        return None


# --- thumb ------------------------------------------------------------------


def _store_of(node: Any) -> Any:
    return getattr(getattr(node, "thumbnailer", None), "store", None)


def _read_webp(store: Any, lib_id: str, cas_id: str) -> bytes | None:
    path = store.path_for(lib_id, cas_id)
    try:
        with open(path, "rb") as f:
            return f.read()
    except OSError:
        return None


def _execute_thumb_sync(node: Any, library: Any, location: dict,
                        entries: list[dict]) -> list[dict]:
    """The thumbnail stage leg: journal/store consult → webp generate
    (procpool ``thumb.cpu``, inline fallback) → store write → vouch →
    ship the webp bytes so the coordinator's store converges
    bit-identical without re-decoding anything."""
    journal = _journal.IndexJournal(library.db)
    loc_id, loc_path = location["id"], location["path"]
    lib_id = str(library.id)
    store = _store_of(node)
    results: list[dict] = []
    pending: list[tuple[dict, dict, tuple, str]] = []  # entry, result, key, path
    for e in entries:
        verdict, jentry, full = _consult(journal, loc_id, loc_path, e)
        key = (e["mat"], e["name"], e["ext"])
        result = {"pub_id": e["pub_id"], "mat": e["mat"], "name": e["name"],
                  "ext": e["ext"], "cas_id": e["cas_id"], "webp": None,
                  "error": None}
        results.append(result)
        if (verdict == _journal.HIT and jentry is not None and jentry.thumb
                and jentry.cas_id == e["cas_id"] and store is not None):
            webp = _read_webp(store, lib_id, e["cas_id"])
            if webp is not None:
                # warm-peer hit: vouched AND verifiably in the store —
                # serve the stored bytes, zero decode work
                result["webp"] = webp
                continue
        pending.append((e, result, key, full))
    if pending:
        pool = _scheduler.pool_for(_scheduler.STAGE_THUMB)
        futures: list[Any] = []
        if pool is not None:
            from ...parallel import procpool as _procpool

            for _e, _r, _k, full in pending:
                ext = _e["ext"]
                try:
                    futures.append(pool.submit(
                        "thumb.cpu", {"path": full, "ext": ext}, rows=1))
                except _procpool.ProcPoolError:
                    futures.append(None)
        else:
            futures = [None] * len(pending)
        from ...object.media.thumbnail.process import (
            ThumbError,
            generate_one_cpu,
        )

        with span("continuum.thumb", nbytes=0):
            for (e, result, key, full), fut in zip(pending, futures):
                webp = err = None
                if fut is not None:
                    try:
                        from ...parallel import procpool as _procpool

                        out = fut.result(_procpool.REQUEST_TIMEOUT_S)
                        webp, err = out.get("webp"), out.get("error")
                    except Exception as exc:  # noqa: BLE001 - degrade inline
                        logger.debug("thumb pool leg failed (%s); inline",
                                     exc)
                        fut = None
                if fut is None and err is None and webp is None:
                    try:
                        webp = generate_one_cpu(full, e["ext"])
                    except (ThumbError, OSError) as exc:
                        err = f"{type(exc).__name__}: {exc}"
                if webp is None:
                    result["error"] = err or "undecodable"
                    continue
                if store is not None:
                    store.write(lib_id, e["cas_id"], webp)
                    # vouch strictly AFTER the webp landed in the store
                    journal.vouch_thumb(loc_id, key, e["cas_id"])
                result["webp"] = webp
    return results


def _apply_thumb(node: Any, library: Any, location: dict,
                 results: list[dict]) -> int:
    """Coordinator merge: land the shipped webp bytes in OUR store and
    vouch — idempotent (same deterministic bytes every execution), so
    duplicate completions re-write identical content."""
    journal = _journal.IndexJournal(library.db)
    loc_id = location["id"]
    lib_id = str(library.id)
    store = _store_of(node)
    applied = 0
    for r in results:
        webp, cas_id = r.get("webp"), r.get("cas_id")
        if not isinstance(webp, (bytes, bytearray)) or not cas_id \
                or store is None:
            continue
        store.write(lib_id, str(cas_id), bytes(webp))
        journal.vouch_thumb(
            loc_id, (r.get("mat", ""), r.get("name", ""), r.get("ext", "")),
            str(cas_id),
        )
        applied += 1
    return applied


# --- media.extract ----------------------------------------------------------


def _commit_media(library: Any, journal: Any, loc_id: int, key: tuple,
                  cas_id: str, obj_pub: str, cols: dict | None) -> None:
    """Land one extracted media row locally + vouch. The digest is
    computed NODE-LOCALLY (cols + this replica's object_id) so each
    node's journal carries exactly what its own local pass would have
    written. ``cols=None`` = probed-nothing-extractable: still a vouch
    (empty digest), so warm passes stop re-probing."""
    from ...object.media.job import _media_digest

    if cols is None:
        journal.vouch_media(loc_id, key, cas_id, "")
        return
    obj = _object_by_pub(library, obj_pub)
    if obj is None:
        return  # object row not replicated yet: the peer's vouch stands
    library.db.upsert("media_data", {"object_id": obj["id"]}, **cols)
    journal.vouch_media(
        loc_id, key, cas_id,
        _media_digest({**cols, "object_id": obj["id"]}),
    )


def _execute_media_sync(node: Any, library: Any, location: dict,
                        entries: list[dict]) -> list[dict]:
    """The media-extraction leg: journal consult → EXIF/video probe →
    local media_data upsert + vouch → ship the extracted columns (the
    row is a local-only table, so results are the ONLY carrier)."""
    from ...object.media.job import MEDIA_DATA_EXTENSIONS  # noqa: F401
    from ...object.media.media_data import ImageMetadata, VideoMetadata
    from ...object.media.thumbnail.process import VIDEO_EXTENSIONS

    journal = _journal.IndexJournal(library.db)
    loc_id, loc_path = location["id"], location["path"]
    results: list[dict] = []
    for e in entries:
        verdict, jentry, full = _consult(journal, loc_id, loc_path, e)
        key = (e["mat"], e["name"], e["ext"])
        result = {"pub_id": e["pub_id"], "mat": e["mat"], "name": e["name"],
                  "ext": e["ext"], "cas_id": e["cas_id"],
                  "obj_pub": e["obj_pub"], "cols": None, "probed": False}
        results.append(result)
        if (verdict == _journal.HIT and jentry is not None
                and jentry.media_digest is not None
                and jentry.cas_id == e["cas_id"]):
            # warm hit: serve the already-extracted row from OUR db
            obj = _object_by_pub(library, e["obj_pub"])
            row = (
                library.db.find_one("media_data", object_id=obj["id"])
                if obj is not None else None
            )
            if row is not None:
                result["cols"] = {
                    k: row[k] for k in row.keys()
                    if k not in ("id", "object_id")
                }
                result["probed"] = True
                continue
            if jentry.media_digest == "":
                result["probed"] = True
                continue  # vouched "nothing extractable": nothing to ship
        ext = (e["ext"] or "").lower()
        meta = (
            VideoMetadata.from_path(full) if ext in VIDEO_EXTENSIONS
            else ImageMetadata.from_path(full)
        )
        result["probed"] = True
        if meta is None:
            _commit_media(library, journal, loc_id, key, e["cas_id"],
                          e["obj_pub"], None)
            continue
        cols = {k: v for k, v in meta.to_row(0).items() if k != "object_id"}
        result["cols"] = cols
        _commit_media(library, journal, loc_id, key, e["cas_id"],
                      e["obj_pub"], cols)
    return results


def _apply_media(node: Any, library: Any, location: dict,
                 results: list[dict]) -> int:
    journal = _journal.IndexJournal(library.db)
    loc_id = location["id"]
    applied = 0
    for r in results:
        if not r.get("probed"):
            continue
        key = (r.get("mat", ""), r.get("name", ""), r.get("ext", ""))
        cols = r.get("cols")
        _commit_media(library, journal, loc_id, key, str(r.get("cas_id")),
                      str(r.get("obj_pub", "")),
                      dict(cols) if isinstance(cols, dict) else None)
        applied += 1
    return applied


# --- phash ------------------------------------------------------------------


def _inline_gray(full: str | None, thumb_path: str | None) -> Any:
    """Inline fallback: the EXACT decode the pool stage runs
    (procworker._stage_phash_gray is pure), so pooled and inline grays
    are bit-identical."""
    import numpy as np

    from ...ops import phash_jax
    from ...parallel.procworker import _stage_phash_gray

    blob = _stage_phash_gray(
        {"path": full, "thumb_path": thumb_path})["gray"]
    if blob is None:
        return None
    return np.frombuffer(blob, np.float32).reshape(
        phash_jax.DCT_SIZE, phash_jax.DCT_SIZE).copy()


def _commit_phash(library: Any, journal: Any, loc_id: int, key: tuple,
                  cas_id: str, obj_pub: str, ph: bytes) -> None:
    obj = _object_by_pub(library, obj_pub)
    if obj is None:
        # no object row on this replica (op ingest still in flight):
        # don't vouch what wasn't committed — the stage recomputes on
        # a replica that can land it
        return
    library.db.execute(
        "UPDATE object SET phash = ? WHERE id = ?", (ph, obj["id"]))
    # vouch ordered after the phash row committed (SD017 dominance)
    journal.record_phash(loc_id, key, cas_id, ph)


def _execute_phash_sync(node: Any, library: Any, location: dict,
                        entries: list[dict]) -> list[dict]:
    """The duplicates-pHash leg: journal-vouched reuse → gray decode
    (procpool ``phash.gray``, inline fallback) → ONE device DCT batch →
    local object.phash update + vouch → ship the 8-byte hashes."""
    import numpy as np

    from ...ops import phash_jax

    journal = _journal.IndexJournal(library.db)
    loc_id, loc_path = location["id"], location["path"]
    lib_id = str(library.id)
    store = _store_of(node)
    results: list[dict] = []
    to_hash: list[tuple[dict, dict, tuple, Any]] = []
    pool = _scheduler.pool_for(_scheduler.STAGE_PHASH)
    futures: list[Any] = []
    pend: list[tuple[dict, dict, tuple, str, str | None]] = []
    for e in entries:
        verdict, jentry, full = _consult(journal, loc_id, loc_path, e)
        key = (e["mat"], e["name"], e["ext"])
        result = {"pub_id": e["pub_id"], "mat": e["mat"], "name": e["name"],
                  "ext": e["ext"], "cas_id": e["cas_id"],
                  "obj_pub": e["obj_pub"], "phash": None}
        results.append(result)
        if (verdict == _journal.HIT and jentry is not None
                and jentry.phash is not None
                and jentry.cas_id == e["cas_id"]):
            result["phash"] = jentry.phash
            _commit_phash(library, journal, loc_id, key, e["cas_id"],
                          e["obj_pub"], jentry.phash)
            continue
        thumb_path = (
            store.path_for(lib_id, e["cas_id"]) if store is not None else None
        )
        pend.append((e, result, key, full, thumb_path))
    if pool is not None:
        from ...parallel import procpool as _procpool

        for _e, _r, _k, full, thumb_path in pend:
            try:
                futures.append(pool.submit(
                    "phash.gray", {"path": full, "thumb_path": thumb_path},
                    rows=1))
            except _procpool.ProcPoolError:
                futures.append(None)
    else:
        futures = [None] * len(pend)
    for (e, result, key, full, thumb_path), fut in zip(pend, futures):
        gray = None
        if fut is not None:
            try:
                from ...parallel import procpool as _procpool

                blob = fut.result(_procpool.REQUEST_TIMEOUT_S)["gray"]
                if blob is not None:
                    gray = np.frombuffer(blob, np.float32).reshape(
                        phash_jax.DCT_SIZE, phash_jax.DCT_SIZE).copy()
            except Exception:  # noqa: BLE001 - degrade inline
                gray = _inline_gray(full, thumb_path)
        else:
            gray = _inline_gray(full, thumb_path)
        if gray is not None:
            to_hash.append((e, result, key, gray))
    if to_hash:
        with span("continuum.phash", nbytes=0):
            hashes = phash_jax.phash_batch(
                np.stack([g for _e, _r, _k, g in to_hash]))
        for (e, result, key, _g), h in zip(to_hash, hashes):
            ph = h.tobytes()
            result["phash"] = ph
            _commit_phash(library, journal, loc_id, key, e["cas_id"],
                          e["obj_pub"], ph)
    return results


def _apply_phash(node: Any, library: Any, location: dict,
                 results: list[dict]) -> int:
    journal = _journal.IndexJournal(library.db)
    loc_id = location["id"]
    applied = 0
    for r in results:
        ph = r.get("phash")
        if not isinstance(ph, (bytes, bytearray)):
            continue
        _commit_phash(
            library, journal, loc_id,
            (r.get("mat", ""), r.get("name", ""), r.get("ext", "")),
            str(r.get("cas_id")), str(r.get("obj_pub", "")), bytes(ph),
        )
        applied += 1
    return applied


# --- embed ------------------------------------------------------------------


def _commit_embed(library: Any, journal: Any, loc_id: int, key: tuple,
                  cas_id: str, obj_pub: str, blob: bytes, *,
                  emit_ops: bool) -> bool:
    """Land one embedding vector locally. The EXECUTING node mints the
    CRDT ops (emit_ops=True) exactly like the local embed stage; the
    complete-receiving coordinator applies directly (emit_ops=False) —
    the executor's ops still arrive through sync and LWW-apply over
    identical bytes (mesh.apply_remote_results precedent)."""
    from ...db.database import now_iso
    from ...models import embedder as _embedder

    obj = _object_by_pub(library, obj_pub)
    if obj is None:
        return False
    stamp = now_iso()

    def db_write(conn) -> None:
        conn.execute(
            "INSERT INTO object_embedding (object_id, vector, dim, "
            "model, date_calculated) VALUES (?,?,?,?,?) "
            "ON CONFLICT (object_id) DO UPDATE SET "
            "vector=excluded.vector, dim=excluded.dim, "
            "model=excluded.model, "
            "date_calculated=excluded.date_calculated",
            (obj["id"], blob, _embedder.EMBED_DIM, _embedder.MODEL_NAME,
             stamp),
        )

    if emit_ops:
        sync = library.sync
        ops = sync.shared_create(
            "object_embedding", obj["pub_id"].hex(),
            [
                ("vector", blob),
                ("dim", _embedder.EMBED_DIM),
                ("model", _embedder.MODEL_NAME),
                ("date_calculated", stamp),
            ],
        )
        sync.write_ops(ops, db_write)
    else:
        with library.db.transaction() as conn:
            db_write(conn)
    # vouch strictly AFTER the durable commit
    journal.vouch_embed(loc_id, key, cas_id)
    return True


def _execute_embed_sync(node: Any, library: Any, location: dict,
                        entries: list[dict]) -> list[dict]:
    """The semantic-embedding leg: journal-vouched reuse → decode
    (procpool ``embed.decode``, inline fallback — same decode_image
    body) → ONE padded device forward → object_embedding rows + CRDT
    ops in one transaction → vouch → ship the vector blobs (derived
    model params are seed-deterministic, so every executor's forward is
    bit-identical)."""
    import numpy as np

    from ...models import embedder as _embedder
    from ...ops import embed_jax

    journal = _journal.IndexJournal(library.db)
    loc_id, loc_path = location["id"], location["path"]
    results: list[dict] = []
    pend: list[tuple[dict, dict, tuple, str]] = []
    for e in entries:
        verdict, jentry, full = _consult(journal, loc_id, loc_path, e)
        key = (e["mat"], e["name"], e["ext"])
        result = {"pub_id": e["pub_id"], "mat": e["mat"], "name": e["name"],
                  "ext": e["ext"], "cas_id": e["cas_id"],
                  "obj_pub": e["obj_pub"], "vector": None}
        results.append(result)
        if (verdict == _journal.HIT and jentry is not None and jentry.embed
                and jentry.cas_id == e["cas_id"]):
            obj = _object_by_pub(library, e["obj_pub"])
            row = (
                library.db.find_one("object_embedding", object_id=obj["id"])
                if obj is not None else None
            )
            if row is not None and row.get("vector"):
                result["vector"] = row["vector"]  # warm hit: serve stored
                continue
        pend.append((e, result, key, full))
    if not pend:
        return results
    # decode leg: pooled in one quantum-shaped batch, inline fallback
    paths = [full for _e, _r, _k, full in pend]
    planes: list[Any] = []
    pool = _scheduler.pool_for(_scheduler.STAGE_EMBED)
    if pool is not None and len(paths) > 1:
        try:
            from ...parallel import procpool as _procpool

            reply = pool.request(
                "embed.decode", {"paths": list(paths)}, rows=len(paths))
            raw_planes = reply["planes"]
            if len(raw_planes) != len(paths):
                raise ValueError("plane count mismatch")
            shape = (_embedder.IMAGE_SIZE, _embedder.IMAGE_SIZE, 3)
            for raw in raw_planes:
                if raw is None:
                    planes.append(None)
                    continue
                arr = np.frombuffer(raw, np.float32)
                if arr.size != int(np.prod(shape)):
                    raise ValueError("plane size mismatch")
                planes.append(arr.reshape(shape))
        except Exception:  # noqa: BLE001 - degrade inline
            planes = []
    if not planes:
        planes = [_embedder.decode_image(p) for p in paths]
    batch: list[tuple[dict, dict, tuple]] = []
    imgs: list[Any] = []
    for (e, result, key, _full), img in zip(pend, planes):
        if img is None:
            continue
        batch.append((e, result, key))
        imgs.append(img)
    if not imgs:
        return results
    with span("continuum.embed", nbytes=0):
        vectors = embed_jax.embed_batch(np.stack(imgs))
    for (e, result, key), vec in zip(batch, vectors):
        blob = _embedder.vector_to_blob(vec)
        # ship regardless of the local commit: the executor's replica
        # may not have ingested the object row yet — the coordinator's
        # apply leg owns durability, the local commit + ops are the
        # executor-replica bonus
        result["vector"] = blob
        _commit_embed(library, journal, loc_id, key, e["cas_id"],
                      e["obj_pub"], blob, emit_ops=True)
    from ...object.search import index as _search_index

    _search_index.refresh(library)
    return results


def _apply_embed(node: Any, library: Any, location: dict,
                 results: list[dict]) -> int:
    journal = _journal.IndexJournal(library.db)
    loc_id = location["id"]
    applied = 0
    for r in results:
        blob = r.get("vector")
        if not isinstance(blob, (bytes, bytearray)):
            continue
        if _commit_embed(
            library, journal, loc_id,
            (r.get("mat", ""), r.get("name", ""), r.get("ext", "")),
            str(r.get("cas_id")), str(r.get("obj_pub", "")), bytes(blob),
            emit_ops=False,
        ):
            applied += 1
    if applied:
        from ...object.search import index as _search_index

        _search_index.refresh(library)
    return applied


_SYNC_EXECUTORS: dict[str, Callable] = {
    _scheduler.STAGE_THUMB: _execute_thumb_sync,
    _scheduler.STAGE_MEDIA: _execute_media_sync,
    _scheduler.STAGE_PHASH: _execute_phash_sync,
    _scheduler.STAGE_EMBED: _execute_embed_sync,
}


# --- result merge (coordinator, from `complete` bodies) --------------------


def apply_stage_results(node: Any, session: Any, stage_id: str,
                        results: list[dict]) -> int:
    """Merge a peer's shipped stage-shard results into this node's
    replica — the stage-typed generalization of
    ``mesh.apply_remote_results`` (which still handles identify)."""
    if stage_id == _scheduler.STAGE_IDENTIFY:
        from .mesh import apply_remote_results

        return apply_remote_results(node, session, results)
    library = node.libraries.get(session.library_id)
    if library is None:
        return 0
    location = library.db.find_one(
        "location", pub_id=bytes.fromhex(session.location_pub))
    if location is None:
        return 0
    clean = [r for r in results if isinstance(r, dict)]
    apply_fn = {
        _scheduler.STAGE_THUMB: _apply_thumb,
        _scheduler.STAGE_MEDIA: _apply_media,
        _scheduler.STAGE_PHASH: _apply_phash,
        _scheduler.STAGE_EMBED: _apply_embed,
    }.get(stage_id)
    if apply_fn is None:
        return 0
    return apply_fn(node, library, location, clean)
