"""Location CRUD + scan orchestration.

Parity: ref:core/src/location/mod.rs — LocationCreateArgs::create
(:1-200 region), `scan_location` spawning the
Indexer → FileIdentifier → MediaProcessor chain (:443-475),
`light_scan_location` (:517), and `.spacedrive` metadata markers
(location/metadata.rs).
"""

from __future__ import annotations

import json
import logging
import os
import uuid
from dataclasses import dataclass
from typing import Any

from ..db.database import new_pub_id, now_iso, u64_blob
from ..jobs import JobBuilder, JobManager
from ..node.library import Library

logger = logging.getLogger(__name__)

SPACEDRIVE_LOCATION_METADATA_FILE = ".spacedrive"


@dataclass
class LocationCreateArgs:
    path: str
    name: str | None = None
    dry_run: bool = False
    indexer_rules_ids: list[int] | None = None

    def create(self, library: Library) -> dict[str, Any] | None:
        path = os.path.abspath(self.path)
        if not os.path.isdir(path):
            raise NotADirectoryError(path)
        existing = library.db.find_one("location", path=path)
        if existing is not None:
            raise FileExistsError(f"location already exists for {path}")
        if self.dry_run:
            return None

        pub_id = new_pub_id()
        name = self.name or os.path.basename(path.rstrip(os.sep)) or path
        date_created = now_iso()
        loc_id = library.db.insert(
            "location",
            pub_id=pub_id,
            name=name,
            path=path,
            date_created=date_created,
            instance_id=library.config.instance_id,
        )
        # default rules attach (ref:location/mod.rs create flow)
        rule_ids = self.indexer_rules_ids
        if rule_ids is None:
            rule_ids = [
                r["id"] for r in library.db.query(
                    'SELECT id FROM indexer_rule WHERE "default" = 1'
                )
            ]
        for rid in rule_ids:
            library.db.insert(
                "indexer_rule_in_location", location_id=loc_id, indexer_rule_id=rid
            )
        # sync ops for the shared location row
        library.sync.write_ops(
            library.sync.shared_create(
                "location",
                pub_id.hex(),
                [("name", name), ("path", path), ("date_created", date_created)],
            )
        )
        # marker file (ref:location/metadata.rs)
        try:
            metadata_path = os.path.join(path, SPACEDRIVE_LOCATION_METADATA_FILE)
            with open(metadata_path, "w", encoding="utf-8") as f:
                json.dump({"location_pub_id": pub_id.hex(), "library_id": str(library.id)}, f)
        except OSError:
            logger.warning("could not write .spacedrive marker in %s", path)
        return library.db.find_one("location", id=loc_id)


async def _spawn_scan_chain(
    library: Library,
    location: dict[str, Any],
    job_manager: JobManager,
    *,
    sub_path: str | None = None,
    shallow: bool = False,
    backend: str = "auto",
    notify: bool = True,
) -> uuid.UUID:
    """The one Indexer → FileIdentifier → MediaProcessor chain every
    scan variant spawns (ref:location/mod.rs:443-475 JobBuilder chain).
    `notify=False` (watcher-triggered rescans) suppresses the chain's
    outcome notification — those fire per filesystem flush."""
    from ..object.file_identifier.job import FileIdentifierJob
    from ..object.media.job import MediaProcessorJob
    from .indexer.job import IndexerJob

    init: dict[str, Any] = {"location_id": location["id"]}
    if sub_path is not None:
        init["sub_path"] = sub_path
    indexer_init = {**init, "shallow": True} if shallow else dict(init)
    jobs = [
        IndexerJob(indexer_init),
        FileIdentifierJob({**init, "backend": backend}),
        MediaProcessorJob({**init, "backend": backend}),
    ]
    for j in jobs:
        j.notify_outcome = notify
    builder = JobBuilder(jobs[0]).queue_next(jobs[1]).queue_next(jobs[2])
    return await builder.spawn(job_manager, library)


async def scan_location(
    library: Library,
    location: dict[str, Any],
    job_manager: JobManager,
    *,
    backend: str = "auto",
) -> uuid.UUID:
    """Full scan job chain (ref:location/mod.rs:443-475)."""
    return await _spawn_scan_chain(library, location, job_manager, backend=backend)


async def deep_rescan_sub_path(
    library: Library,
    location: dict[str, Any],
    sub_path: str,
    job_manager: JobManager,
    *,
    backend: str = "auto",
) -> uuid.UUID:
    """Full (recursive) rescan of one subtree — what a directory moved
    into the location needs (a shallow scan of its parent would index
    only the dir row, not its pre-existing contents)."""
    return await _spawn_scan_chain(
        library, location, job_manager, sub_path=sub_path, backend=backend,
        notify=False,  # watcher-driven; see _spawn_scan_chain
    )


async def light_scan_location(
    library: Library,
    location: dict[str, Any],
    sub_path: str,
    job_manager: JobManager,
) -> uuid.UUID:
    """Shallow re-scan of one directory (ref:location/mod.rs:517)."""
    return await _spawn_scan_chain(
        library, location, job_manager, sub_path=sub_path, shallow=True,
        notify=False,  # watcher-driven; see _spawn_scan_chain
    )


def relink_location(library: Library, path: str) -> dict[str, Any] | None:
    """Re-attach a moved location by its `.spacedrive` marker."""
    marker = os.path.join(path, SPACEDRIVE_LOCATION_METADATA_FILE)
    try:
        with open(marker, "r", encoding="utf-8") as f:
            meta = json.load(f)
    except (OSError, json.JSONDecodeError):
        return None
    pub_id = bytes.fromhex(meta["location_pub_id"])
    row = library.db.find_one("location", pub_id=pub_id)
    if row is None:
        return None
    library.db.update("location", {"id": row["id"]}, path=os.path.abspath(path))
    return library.db.find_one("location", id=row["id"])


def update_location_size(library: Library, location_id: int) -> int:
    """Roll directory sizes up into the location row
    (ref:location/mod.rs reverse_update_directories_sizes)."""
    from ..db.database import blob_u64

    total = sum(
        blob_u64(r["size_in_bytes_bytes"]) or 0
        for r in library.db.query(
            "SELECT size_in_bytes_bytes FROM file_path "
            "WHERE location_id = ? AND is_dir = 0",
            (location_id,),
        )
    )
    library.db.update(
        "location", {"id": location_id},
        size_in_bytes=u64_blob(total),
    )
    return total
