"""Normalized watcher events.

Parity: ref:core/src/location/manager/watcher/mod.rs — the per-OS
watchers (linux/macos/windows.rs) normalize raw notify events into the
same small vocabulary the event handler consumes: create/modify for
files and dirs, rename with both endpoints resolved (the reference's
rename tracker pairs partial events), and remove. `is_dir` reflects the
event target where knowable.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass


class EventKind(enum.Enum):
    CREATE = "create"
    MODIFY = "modify"
    RENAME = "rename"
    REMOVE = "remove"
    RESCAN = "rescan"  # events were lost (queue overflow) — reconcile


@dataclass(frozen=True)
class WatchEvent:
    kind: EventKind
    path: str  # absolute; for RENAME this is the NEW path
    old_path: str | None = None  # RENAME only
    is_dir: bool = False
