"""Linux inotify backend (ctypes, no external deps).

Parity: ref:core/src/location/manager/watcher/linux.rs — the reference
rides `notify`'s inotify backend and adds rename-cookie pairing and
event normalization on top; this backend speaks inotify directly:
recursive watch registration (new subdirectories are watched as they
appear), MOVED_FROM/MOVED_TO pairing by cookie with a grace window
(unpaired halves degrade to REMOVE/CREATE like the reference's rename
tracker timeout), and CLOSE_WRITE standing in for the final modify.
"""

from __future__ import annotations

import asyncio
import ctypes
import ctypes.util
import errno
import logging
import os
import struct
from typing import Awaitable, Callable

from ...utils.tasks import supervise
from .events import EventKind, WatchEvent

logger = logging.getLogger(__name__)

IN_ACCESS = 0x0001
IN_MODIFY = 0x0002
IN_ATTRIB = 0x0004
IN_CLOSE_WRITE = 0x0008
IN_MOVED_FROM = 0x0040
IN_MOVED_TO = 0x0080
IN_CREATE = 0x0100
IN_DELETE = 0x0200
IN_DELETE_SELF = 0x0400
IN_MOVE_SELF = 0x0800
IN_ISDIR = 0x40000000
IN_Q_OVERFLOW = 0x4000
IN_IGNORED = 0x8000

_MASK = (
    IN_CLOSE_WRITE
    | IN_ATTRIB
    | IN_MOVED_FROM
    | IN_MOVED_TO
    | IN_CREATE
    | IN_DELETE
    | IN_DELETE_SELF
)

RENAME_GRACE = 0.1  # unpaired MOVED_FROM/TO settle window (ref rename tracker)

_libc = ctypes.CDLL(ctypes.util.find_library("c") or "libc.so.6", use_errno=True)


class InotifyWatcher:
    """One instance per watched root (a location)."""

    def __init__(
        self,
        root: str,
        emit: Callable[[WatchEvent], Awaitable[None] | None],
    ):
        self.root = os.path.abspath(root)
        self.emit = emit
        self._fd: int | None = None
        self._wd_paths: dict[int, str] = {}
        self._path_wds: dict[str, int] = {}
        self._pending_from: dict[int, tuple[str, bool, asyncio.TimerHandle]] = {}
        self._loop: asyncio.AbstractEventLoop | None = None
        # async emit-handler tasks: retained so a failing handler surfaces
        # through its done-callback instead of as a GC-time unraisable
        # warning (sdlint SD003)
        self._emit_tasks: set[asyncio.Task] = set()

    # --- lifecycle -----------------------------------------------------

    def start(self) -> None:
        self._loop = asyncio.get_running_loop()
        fd = _libc.inotify_init1(os.O_NONBLOCK)
        if fd < 0:
            raise OSError(ctypes.get_errno(), "inotify_init1 failed")
        self._fd = fd
        self._watch_tree(self.root)
        self._loop.add_reader(fd, self._on_readable)

    async def start_async(self) -> None:
        """start() with the tree walk (one add_watch syscall per dir —
        seconds on huge locations) off the event loop."""
        self._loop = asyncio.get_running_loop()
        fd = _libc.inotify_init1(os.O_NONBLOCK)
        if fd < 0:
            raise OSError(ctypes.get_errno(), "inotify_init1 failed")
        self._fd = fd
        await asyncio.to_thread(self._watch_tree, self.root)
        self._loop.add_reader(fd, self._on_readable)

    def stop(self) -> None:
        if self._fd is None:
            return
        if self._loop is not None:
            self._loop.remove_reader(self._fd)
        for _wd, (old, is_dir, handle) in list(self._pending_from.items()):
            handle.cancel()
        self._pending_from.clear()
        os.close(self._fd)
        self._fd = None
        self._wd_paths.clear()
        self._path_wds.clear()

    # --- watch registration --------------------------------------------

    def _watch_tree(self, path: str) -> None:
        self._add_watch(path)
        for dirpath, dirnames, _files in os.walk(path):
            for d in dirnames:
                self._add_watch(os.path.join(dirpath, d))

    def _add_watch(self, path: str) -> None:
        assert self._fd is not None
        wd = _libc.inotify_add_watch(self._fd, os.fsencode(path), _MASK)
        if wd < 0:
            err = ctypes.get_errno()
            if err in (errno.ENOENT, errno.EACCES):
                return
            raise OSError(err, f"inotify_add_watch({path}) failed")
        self._wd_paths[wd] = path
        self._path_wds[path] = wd

    def _rm_watch_under(self, path: str) -> None:
        for p, wd in list(self._path_wds.items()):
            if p == path or p.startswith(path + os.sep):
                self._wd_paths.pop(wd, None)
                self._path_wds.pop(p, None)

    # --- event pump ----------------------------------------------------

    def _on_readable(self) -> None:
        assert self._fd is not None
        try:
            buf = os.read(self._fd, 1 << 16)
        except BlockingIOError:
            return
        except OSError:
            return
        offset = 0
        while offset + 16 <= len(buf):
            wd, mask, cookie, length = struct.unpack_from("iIII", buf, offset)
            name = buf[offset + 16 : offset + 16 + length].split(b"\0", 1)[0].decode(
                errors="surrogateescape"
            )
            offset += 16 + length
            self._handle(wd, mask, cookie, name)

    def _handle(self, wd: int, mask: int, cookie: int, name: str) -> None:
        if mask & IN_Q_OVERFLOW:
            # kernel queue overflow: events lost at unknown depths
            self._emit(WatchEvent(EventKind.RESCAN, self.root, is_dir=True))
            return
        if mask & IN_IGNORED:
            path = self._wd_paths.pop(wd, None)
            if path is not None:
                self._path_wds.pop(path, None)
            return
        base = self._wd_paths.get(wd)
        if base is None:
            return
        path = os.path.join(base, name) if name else base
        is_dir = bool(mask & IN_ISDIR)

        if mask & IN_MOVED_FROM:
            assert self._loop is not None
            handle = self._loop.call_later(
                RENAME_GRACE, self._expire_move_from, cookie
            )
            self._pending_from[cookie] = (path, is_dir, handle)
            return
        if mask & IN_MOVED_TO:
            pending = self._pending_from.pop(cookie, None)
            if pending is not None:
                old, was_dir, handle = pending
                handle.cancel()
                if was_dir:
                    self._rewrite_watches(old, path)
                self._emit(
                    WatchEvent(EventKind.RENAME, path, old_path=old, is_dir=was_dir)
                )
            else:
                # moved in from outside the tree = create
                if is_dir:
                    self._watch_tree(path)
                self._emit(WatchEvent(EventKind.CREATE, path, is_dir=is_dir))
            return
        if mask & IN_CREATE:
            if is_dir:
                self._watch_tree(path)  # watch before children appear
                self._emit(WatchEvent(EventKind.CREATE, path, is_dir=True))
            # file creates are reported at CLOSE_WRITE (content settled)
            return
        if mask & (IN_CLOSE_WRITE | IN_ATTRIB):
            kind = EventKind.MODIFY
            # CLOSE_WRITE on a brand-new file: we suppressed its CREATE
            self._emit(WatchEvent(kind, path, is_dir=is_dir))
            return
        if mask & (IN_DELETE | IN_DELETE_SELF):
            if mask & IN_DELETE_SELF and path == self.root:
                self._emit(WatchEvent(EventKind.REMOVE, path, is_dir=True))
                return
            if is_dir:
                self._rm_watch_under(path)
            self._emit(WatchEvent(EventKind.REMOVE, path, is_dir=is_dir))

    def _expire_move_from(self, cookie: int) -> None:
        """MOVED_FROM with no matching MOVED_TO: moved out of tree = remove."""
        pending = self._pending_from.pop(cookie, None)
        if pending is None:
            return
        old, is_dir, _handle = pending
        if is_dir:
            self._rm_watch_under(old)
        self._emit(WatchEvent(EventKind.REMOVE, old, is_dir=is_dir))

    def _rewrite_watches(self, old: str, new: str) -> None:
        for p, wd in list(self._path_wds.items()):
            if p == old or p.startswith(old + os.sep):
                np = new + p[len(old) :]
                self._path_wds.pop(p)
                self._path_wds[np] = wd
                self._wd_paths[wd] = np

    def _emit(self, event: WatchEvent) -> None:
        result = self.emit(event)
        if asyncio.iscoroutine(result):
            assert self._loop is not None
            supervise(self._loop.create_task(result), self._emit_tasks,
                      logger, "watcher emit handler")


def available() -> bool:
    return hasattr(_libc, "inotify_init1") and os.name == "posix"
