"""Filesystem watching: normalized events + per-platform backends.

Parity: ref:core/src/location/manager/watcher/ — `notify`-based
watchers with per-OS normalization; here an inotify ctypes backend on
Linux and a portable polling backend elsewhere, both emitting the same
`WatchEvent` vocabulary.
"""

from __future__ import annotations

import platform
from typing import Awaitable, Callable

from .events import EventKind, WatchEvent
from .inotify import InotifyWatcher, available as inotify_available
from .polling import PollingWatcher


def new_watcher(
    root: str,
    emit: Callable[[WatchEvent], "Awaitable[None] | None"],
    *,
    force_polling: bool = False,
    poll_interval: float = 1.0,
):
    """RecommendedWatcher equivalent (ref:watcher/mod.rs:14)."""
    if not force_polling and platform.system() == "Linux" and inotify_available():
        return InotifyWatcher(root, emit)
    return PollingWatcher(root, emit, interval=poll_interval)


__all__ = [
    "EventKind",
    "InotifyWatcher",
    "PollingWatcher",
    "WatchEvent",
    "new_watcher",
]
