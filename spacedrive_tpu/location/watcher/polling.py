"""Polling fallback backend — mtime-snapshot diffing.

Parity role: the reference's notify crate falls back to poll-watching
where native watchers are unavailable (and macOS FSEvents/windows
ReadDirectoryChangesW normalizations live in their own modules,
ref:core/src/location/manager/watcher/{macos,windows}.rs). This backend
is the portable equivalent: it snapshots the tree every `interval`
seconds and diffs (path → (mtime, size, is_dir)); renames are detected
by matching (inode, size) pairs of removed/added entries, like the
reference's inode-based rename resolution (watcher/utils.rs inode
helpers).
"""

from __future__ import annotations

import asyncio
import os
import stat as stat_mod
from typing import Awaitable, Callable

from .events import EventKind, WatchEvent

Snapshot = dict[str, tuple[float, int, bool, int]]  # mtime, size, is_dir, inode


def take_snapshot(root: str) -> Snapshot:
    snap: Snapshot = {}
    for dirpath, dirnames, filenames in os.walk(root):
        for name in dirnames + filenames:
            p = os.path.join(dirpath, name)
            try:
                st = os.stat(p, follow_symlinks=False)
            except OSError:
                continue
            snap[p] = (
                st.st_mtime,
                st.st_size,
                stat_mod.S_ISDIR(st.st_mode),
                st.st_ino,
            )
    return snap


def diff_snapshots(old: Snapshot, new: Snapshot) -> list[WatchEvent]:
    events: list[WatchEvent] = []
    removed = {p: meta for p, meta in old.items() if p not in new}
    added = {p: meta for p, meta in new.items() if p not in old}
    # rename pairing by inode (ref:watcher/utils.rs inode helpers);
    # the kernel reuses freed inodes, so demand the full identity
    # (inode, is_dir, size, mtime) to survive delete+create in one tick
    by_identity = {meta: p for p, meta in removed.items()}
    for p, meta in list(added.items()):
        src = by_identity.get(meta)
        if src is not None:
            events.append(
                WatchEvent(EventKind.RENAME, p, old_path=src, is_dir=meta[2])
            )
            removed.pop(src)
            added.pop(p)
            by_identity.pop(meta)
    for p, meta in removed.items():
        events.append(WatchEvent(EventKind.REMOVE, p, is_dir=meta[2]))
    for p, meta in added.items():
        events.append(WatchEvent(EventKind.CREATE, p, is_dir=meta[2]))
    for p, meta in new.items():
        old_meta = old.get(p)
        if old_meta is not None and (meta[0], meta[1]) != (old_meta[0], old_meta[1]):
            events.append(WatchEvent(EventKind.MODIFY, p, is_dir=meta[2]))
    return events


class PollingWatcher:
    def __init__(
        self,
        root: str,
        emit: Callable[[WatchEvent], Awaitable[None] | None],
        interval: float = 1.0,
    ):
        self.root = os.path.abspath(root)
        self.emit = emit
        self.interval = interval
        self._task: asyncio.Task | None = None
        self._snap: Snapshot = {}

    def start(self) -> None:
        self._snap = take_snapshot(self.root)
        self._task = asyncio.get_running_loop().create_task(self._run())

    async def start_async(self) -> None:
        self._snap = await asyncio.to_thread(take_snapshot, self.root)
        self._task = asyncio.get_running_loop().create_task(self._run())

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None

    async def _run(self) -> None:
        while True:
            await asyncio.sleep(self.interval)
            new = await asyncio.to_thread(take_snapshot, self.root)
            for event in diff_snapshots(self._snap, new):
                result = self.emit(event)
                if asyncio.iscoroutine(result):
                    await result
            self._snap = new
