"""macOS/Windows watcher event normalizers.

Parity: ref:core/src/location/manager/watcher/{macos,windows}.rs — the
reference's per-OS watchers are mostly *normalization state machines*
that turn each platform's quirky raw streams into the shared event
vocabulary (`events.WatchEvent`), and those machines are portable even
though the native sources (FSEvents, ReadDirectoryChangesW) only exist
on their hosts. This module implements both machines host-independently:
on a mac/windows host a thin adapter feeds them raw events; everywhere
else the polling backend remains the fallback (COMPONENTS.md scope
note), and the tests drive the machines with simulated streams.

macOS quirks handled (ref:macos.rs:1-10,94-97,122-126,168,221-223):
- FSEvents reports renames as bare `RenameMode::Any` per PATH with no
  pairing cookie. The old-path half targets a path that no longer
  exists; the new-path half targets one that does. Halves pair within
  a 100 ms window; an unpaired old half is a move OUT of the location
  (→ REMOVE), an unpaired new half is a move IN (→ CREATE).
- Finder emits a doubled folder-create; the second is deduped against
  the latest created folder (a unique-constraint hit otherwise).
- Data/metadata modifies coalesce per path behind a quiet window; a
  file updated so often it never goes quiet ("reincident") is flushed
  at a longer cap so a long download still shows progress.

Windows quirks handled (ref:windows.rs:1-8,94-95,106-116,171,192,293):
- A move inside the watched tree arrives as REMOVE(old) then
  CREATE(new). Removes are therefore held for a grace window and
  paired by file identity (inode stand-in) with a later create →
  RENAME; only an unpaired remove really deletes.
- `RenameMode::From`/`RenameMode::To` halves pair in either arrival
  order; unpaired halves degrade to REMOVE/CREATE like macOS.
- A create for a file still exclusively locked by its writer is
  retried via the modify path later (the raw adapter reports it
  locked; the machine re-queues rather than emitting a broken create).

Both machines take an injectable clock and existence/identity probes so
the tests are deterministic; `tick(now)` drives expiry exactly like the
reference's 100 ms handler tick loop (mod.rs).
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Callable

from .events import EventKind, WatchEvent

RENAME_WINDOW = 0.1      # ref:macos.rs:168 (100 ms rename pairing)
MODIFY_QUIET = 0.1       # per-path coalescing quiet window
REINCIDENT_CAP = 10.0    # ref: "bigger timeout" for hot files
REMOVE_GRACE = 0.1       # ref:windows.rs remove→create pairing wait


@dataclass
class _Pending:
    path: str
    is_dir: bool
    at: float
    ident: int | None = None  # windows: file identity (inode stand-in)


def _pop_fresh(buf: dict[str, _Pending], now: float,
               path: str | None = None,
               ident: int | None = None) -> _Pending | None:
    """Pop the best-matching buffered half still inside the pairing
    window. Concurrent renames can have several halves buffered at
    once; first-inserted-wins would mispair them, so candidates rank:
    identity match (when both sides have one) > same basename (a MOVE
    keeps its name) > same parent dir (a rename stays put) > FIFO."""
    fresh = [(k, p) for k, p in buf.items() if now - p.at <= RENAME_WINDOW]
    if not fresh:
        return None

    def rank(item):
        _k, p = item
        if ident is not None and p.ident is not None:
            if p.ident == ident:
                return 0
            return 4  # identity known on both sides and DIFFERENT
        if path is not None:
            if os.path.basename(p.path) == os.path.basename(path):
                return 1
            if os.path.dirname(p.path) == os.path.dirname(path):
                return 2
        return 3

    key, p = min(fresh, key=rank)
    if ident is not None and p.ident is not None and p.ident != ident:
        return None  # every candidate has a contradicting identity
    del buf[key]
    return p


class _ModifyCoalescer:
    """Shared modify buffering: repeated modifies reset a quiet timer;
    a path that never goes quiet flushes at REINCIDENT_CAP anyway."""

    def __init__(self) -> None:
        self._last: dict[str, float] = {}
        self._first: dict[str, float] = {}
        self._dirs: set[str] = set()

    def touch(self, path: str, is_dir: bool, now: float) -> None:
        self._last[path] = now
        self._first.setdefault(path, now)
        if is_dir:
            self._dirs.add(path)

    def drop(self, path: str) -> None:
        self._last.pop(path, None)
        self._first.pop(path, None)
        self._dirs.discard(path)

    def due(self, now: float) -> list[WatchEvent]:
        out = []
        for path, last in list(self._last.items()):
            if now - last >= MODIFY_QUIET \
                    or now - self._first[path] >= REINCIDENT_CAP:
                out.append(WatchEvent(EventKind.MODIFY, path,
                                      is_dir=path in self._dirs))
                self.drop(path)
        return out


class MacOsNormalizer:
    """FSEvents-shaped raw stream → normalized WatchEvents.

    Raw kinds: "create_file", "create_dir", "modify_data",
    "modify_meta", "rename_any", "remove_file", "remove_dir".
    """

    def __init__(self, exists: Callable[[str], bool],
                 is_dir: Callable[[str], bool] = lambda p: False,
                 ident: Callable[[str], int | None] = lambda p: None,
                 ident_of_missing: Callable[[str], int | None]
                 = lambda p: None):
        # `ident` stats an existing path (inode); `ident_of_missing`
        # resolves a VANISHED path from the location index (the
        # reference pairs by the indexed inode, macos.rs) — both
        # optional: without them pairing falls back to basename/parent
        # heuristics, with them concurrent renames cannot mispair
        self._exists = exists
        self._is_dir = is_dir
        self._ident = ident
        self._ident_missing = ident_of_missing
        self._old_half: dict[str, _Pending] = {}   # vanished paths
        self._new_half: dict[str, _Pending] = {}   # appeared paths
        self._last_created_dir: tuple[str, float] | None = None
        self._mods = _ModifyCoalescer()

    def on_raw(self, kind: str, path: str, now: float,
               is_dir: bool = False) -> list[WatchEvent]:
        out: list[WatchEvent] = []
        if kind == "create_dir":
            # Finder's doubled folder-create (ref:macos.rs:94-97)
            last = self._last_created_dir
            if last and last[0] == path and now - last[1] <= RENAME_WINDOW:
                return out
            self._last_created_dir = (path, now)
            out.append(WatchEvent(EventKind.CREATE, path, is_dir=True))
        elif kind == "create_file":
            out.append(WatchEvent(EventKind.CREATE, path, is_dir=False))
        elif kind in ("modify_data", "modify_meta"):
            self._mods.touch(path, is_dir, now)
        elif kind == "rename_any":
            if self._exists(path):
                # new half: pair with the best buffered old half
                my_ident = self._ident(path)
                old = _pop_fresh(self._old_half, now, path=path,
                                 ident=my_ident)
                if old is not None:
                    out.append(WatchEvent(EventKind.RENAME, path,
                                          old_path=old.path,
                                          is_dir=self._is_dir(path)))
                else:
                    self._new_half[path] = _Pending(
                        path, self._is_dir(path), now, my_ident)
            else:
                my_ident = self._ident_missing(path)
                new = _pop_fresh(self._new_half, now, path=path,
                                 ident=my_ident)
                if new is not None:
                    out.append(WatchEvent(EventKind.RENAME, new.path,
                                          old_path=path,
                                          is_dir=new.is_dir))
                else:
                    self._old_half[path] = _Pending(path, is_dir, now,
                                                    my_ident)
                self._mods.drop(path)
        elif kind in ("remove_file", "remove_dir"):
            self._mods.drop(path)
            out.append(WatchEvent(EventKind.REMOVE, path,
                                  is_dir=kind == "remove_dir"))
        return out

    def tick(self, now: float) -> list[WatchEvent]:
        """Expire unpaired halves + flush quiet modifies
        (ref:macos.rs:168: >100 ms old halves become removals)."""
        out: list[WatchEvent] = []
        for path, p in list(self._old_half.items()):
            if now - p.at > RENAME_WINDOW:
                del self._old_half[path]
                # moved OUT of the location (ref:macos.rs:7-8)
                out.append(WatchEvent(EventKind.REMOVE, path,
                                      is_dir=p.is_dir))
        for path, p in list(self._new_half.items()):
            if now - p.at > RENAME_WINDOW:
                del self._new_half[path]
                # moved IN from elsewhere (ref:macos.rs:9-10)
                out.append(WatchEvent(EventKind.CREATE, path,
                                      is_dir=p.is_dir))
        out.extend(self._mods.due(now))
        return out


class WindowsNormalizer:
    """ReadDirectoryChangesW-shaped raw stream → normalized events.

    Raw kinds: "create", "modify", "remove", "rename_from", "rename_to".
    `ident` is the file-identity probe result (nFileIndex / inode
    stand-in) where the adapter could stat the path.
    """

    def __init__(self, locked: Callable[[str], bool] = lambda p: False,
                 is_dir: Callable[[str], bool] = lambda p: False,
                 exists: Callable[[str], bool] = lambda p: True):
        # `exists` re-stats a path when its deferred locked-create
        # finally unblocks: a locked file DELETED before release must
        # not yield a spurious CREATE after its REMOVE
        self._locked = locked
        self._is_dir = is_dir
        self._exists = exists
        self._pending_removes: dict[str, _Pending] = {}
        self._from_half: dict[str, _Pending] = {}
        self._to_half: dict[str, _Pending] = {}
        self._locked_creates: dict[str, _Pending] = {}
        self._mods = _ModifyCoalescer()

    def on_raw(self, kind: str, path: str, now: float,
               is_dir: bool = False,
               ident: int | None = None) -> list[WatchEvent]:
        out: list[WatchEvent] = []
        if kind == "create":
            if self._locked(path):
                # writer still holds the handle: defer and RE-PROBE the
                # lock at every tick — emitting before release would be
                # the broken event this exists to prevent
                # (ref:windows.rs:94-95)
                self._locked_creates[path] = _Pending(path, is_dir, now,
                                                      ident)
                return out
            # a recent REMOVE with the same identity = a move
            # (ref:windows.rs:106-116)
            if ident is not None:
                for old, p in list(self._pending_removes.items()):
                    if p.ident == ident and now - p.at <= REMOVE_GRACE:
                        del self._pending_removes[old]
                        out.append(WatchEvent(EventKind.RENAME, path,
                                              old_path=old, is_dir=is_dir))
                        return out
            out.append(WatchEvent(EventKind.CREATE, path, is_dir=is_dir))
        elif kind == "modify":
            self._mods.touch(path, is_dir, now)
        elif kind == "remove":
            self._mods.drop(path)
            # a deferred locked create for a now-removed path is dead:
            # the writer deleted the file before ever releasing it
            self._locked_creates.pop(path, None)
            self._pending_removes[path] = _Pending(path, is_dir, now, ident)
        elif kind == "rename_from":
            self._locked_creates.pop(path, None)
            to = _pop_fresh(self._to_half, now, path=path, ident=ident)
            if to is not None:
                out.append(WatchEvent(EventKind.RENAME, to.path,
                                      old_path=path, is_dir=to.is_dir))
            else:
                self._from_half[path] = _Pending(path, is_dir, now, ident)
            self._mods.drop(path)
        elif kind == "rename_to":
            frm = _pop_fresh(self._from_half, now, path=path, ident=ident)
            if frm is not None:
                out.append(WatchEvent(EventKind.RENAME, path,
                                      old_path=frm.path, is_dir=is_dir))
            else:
                self._to_half[path] = _Pending(path, is_dir, now, ident)
        return out

    def tick(self, now: float) -> list[WatchEvent]:
        out: list[WatchEvent] = []
        for path, p in list(self._locked_creates.items()):
            if not self._locked(path):
                del self._locked_creates[path]
                # re-stat before emitting: "no longer locked" may mean
                # "no longer exists" (deleted while held), and a CREATE
                # for a vanished path would contradict its REMOVE
                if self._exists(path):
                    out.append(WatchEvent(EventKind.CREATE, path,
                                          is_dir=p.is_dir))
        for path, p in list(self._pending_removes.items()):
            if now - p.at > REMOVE_GRACE:
                del self._pending_removes[path]
                out.append(WatchEvent(EventKind.REMOVE, path,
                                      is_dir=p.is_dir))
        for path, p in list(self._from_half.items()):
            if now - p.at > RENAME_WINDOW:
                del self._from_half[path]
                out.append(WatchEvent(EventKind.REMOVE, path,
                                      is_dir=p.is_dir))
        for path, p in list(self._to_half.items()):
            if now - p.at > RENAME_WINDOW:
                del self._to_half[path]
                out.append(WatchEvent(EventKind.CREATE, path,
                                      is_dir=p.is_dir))
        out.extend(self._mods.due(now))
        return out
