"""Location manager — per-location watchers + event application.

Parity: ref:core/src/location/manager/mod.rs:36-60 — an actor that
(un)registers locations for watching, can pause/resume a location's
watcher (used by fs-ops jobs to ignore their own writes), holds an
ignore-path set, and applies normalized watcher events to the library
DB (watcher/utils.rs, 1,072 LoC):

- RENAME → rewrite the file_path row (and the whole subtree's
  materialized_paths for directories) — precise, no rescan;
- REMOVE → delete the row/subtree;
- CREATE/MODIFY → debounced shallow rescan of the affected parent dirs
  (`light_scan_location`), which batches the new/changed files into the
  indexer → identifier (TPU cas_id) → media pipeline. The reference
  applies per-file inline updates; routing through the shallow-scan job
  chain instead keeps device work batched (§SURVEY.md 2.4).
"""

from __future__ import annotations

import asyncio
import logging
import os
from dataclasses import dataclass, field
from typing import Any

from ..db.database import now_iso
from ..files.isolated_path import IsolatedFilePathData
from ..telemetry.events import WATCHER_EVENTS
from ..utils.tasks import supervise
from .indexer.journal import IndexJournal, key_of, stat_identity
from .locations import deep_rescan_sub_path, light_scan_location
from .watcher import EventKind, WatchEvent, new_watcher

logger = logging.getLogger(__name__)

DEBOUNCE = 0.2  # event settle window before shallow rescans fire
# Journal-verdict-driven debounce sizing (PR 7 follow-up): a burst
# whose events the index journal still vouches for — rename storms
# (vouches MOVE, zero re-work) and touch storms (stat identity
# unchanged) — needs consolidation, not per-event rescans, so the
# settle window widens with the vouched count, up to DEBOUNCE_MAX. A
# burst of real content changes keeps the snappy base window.
DEBOUNCE_MAX = 2.0
DEBOUNCE_WIDEN_MIN = 4  # vouched events before the window starts widening


@dataclass
class _Watched:
    library: Any
    location: dict[str, Any]
    watcher: Any
    paused: int = 0  # pause() nesting depth
    dirty_dirs: set[str] = field(default_factory=set)  # shallow rescan targets
    deep_dirs: set[str] = field(default_factory=set)  # recursive rescan targets
    flush_handle: Any = None
    # current-burst accounting (reset at each flush)
    burst_total: int = 0
    burst_vouched: int = 0
    last_event: float = 0.0     # monotonic time of the last counted event
    last_debounce: float = 0.0  # last window emitted on the ring


class LocationManager:
    """One per node (ref:manager/mod.rs `LocationManagerActor`)."""

    def __init__(self, node: Any):
        self.node = node
        self._watched: dict[tuple[str, int], _Watched] = {}
        self.ignore_paths: set[str] = set()
        self.events_applied = 0
        # debounce sizing (instance attrs so tests can compress time)
        self.debounce = DEBOUNCE
        self.debounce_max = DEBOUNCE_MAX
        # in-flight debounced rescans: retained so they can't be
        # GC-cancelled mid-flush and shutdown can drain them (sdlint SD003)
        self._flush_tasks: set[asyncio.Task] = set()
        self._shutting_down = False

    # --- registration (ref:manager/mod.rs:36-60) -----------------------

    async def add(self, library: Any, location: dict[str, Any],
                  *, force_polling: bool = False, poll_interval: float = 1.0) -> None:
        key = (str(library.id), location["id"])
        if key in self._watched or not os.path.isdir(location["path"]):
            return
        entry = _Watched(library=library, location=location, watcher=None)

        def emit(event: WatchEvent, entry=entry):
            return self._on_event(entry, event)

        entry.watcher = new_watcher(
            location["path"], emit,
            force_polling=force_polling, poll_interval=poll_interval,
        )
        await entry.watcher.start_async()  # tree walk off the event loop
        self._watched[key] = entry

    async def remove(self, library: Any, location_id: int) -> None:
        entry = self._watched.pop((str(library.id), location_id), None)
        if entry is not None:
            entry.watcher.stop()
            if entry.flush_handle is not None:
                entry.flush_handle.cancel()

    def pause(self, library: Any, location_id: int) -> None:
        """Temporarily ignore events (fs-ops jobs writing into the
        location; ref:manager/mod.rs stop_watcher/reinit_watcher)."""
        entry = self._watched.get((str(library.id), location_id))
        if entry is not None:
            entry.paused += 1

    def resume(self, library: Any, location_id: int) -> None:
        entry = self._watched.get((str(library.id), location_id))
        if entry is not None and entry.paused > 0:
            entry.paused -= 1

    def is_watched(self, library: Any, location_id: int) -> bool:
        return (str(library.id), location_id) in self._watched

    async def shutdown(self) -> None:
        self._shutting_down = True
        for entry in self._watched.values():
            entry.watcher.stop()
            if entry.flush_handle is not None:
                entry.flush_handle.cancel()
        self._watched.clear()
        if self._flush_tasks:
            # let in-flight rescans settle rather than strand them
            # half-applied; _flush_done drains the set as they finish
            await asyncio.gather(*list(self._flush_tasks),
                                 return_exceptions=True)

    # --- event application (ref:watcher/utils.rs) ----------------------

    def _rel(self, entry: _Watched, path: str) -> str | None:
        root = os.path.abspath(entry.location["path"])
        ap = os.path.abspath(path)
        if ap == root:
            return ""
        if not ap.startswith(root + os.sep):
            return None
        return ap[len(root) + 1 :]

    def _ignored(self, path: str) -> bool:
        ap = os.path.abspath(path)
        return any(
            ap == ig or ap.startswith(ig + os.sep) for ig in self.ignore_paths
        )

    async def _on_event(self, entry: _Watched, event: WatchEvent) -> None:
        if entry.paused > 0 or self._ignored(event.path):
            return
        rel = self._rel(entry, event.path)
        if rel is None:
            return
        rel = rel.replace(os.sep, "/")
        self.events_applied += 1
        db = entry.library.db
        loc_id = entry.location["id"]
        journal = IndexJournal(db)
        kind = event.kind
        try:
            if kind == EventKind.RENAME:
                old_rel = self._rel(entry, event.old_path or "")
                if old_rel is not None:
                    old_rel = old_rel.replace(os.sep, "/")
                    # a rename moves the journal vouches wholesale — if
                    # the old entry was vouching, this event needs NO
                    # rescan, so it counts toward the vouched burst and
                    # pushes any PENDING rescan out (widened window)
                    # instead of letting it fire mid-storm
                    old_iso = IsolatedFilePathData.from_relative_str(
                        loc_id, old_rel, event.is_dir
                    )
                    _, jentry = journal.lookup(
                        loc_id, key_of(old_iso), None, count=False,
                    )
                    self._count_burst(
                        entry,
                        vouched=jentry is not None and not jentry.stale,
                    )
                    self._apply_rename(db, loc_id, old_rel, rel, event.is_dir)
                    if entry.flush_handle is not None:
                        self._schedule_flush(entry)
                    return
                kind = EventKind.CREATE  # renamed in from outside = create
            if kind == EventKind.REMOVE:
                self._apply_remove(db, loc_id, rel, event.is_dir)
                return
            vouched = False
            if kind == EventKind.RESCAN:
                # events were lost at unknown depths — full rescan, and
                # the journal stops vouching for the whole subtree (the
                # losses may hide sub-mtime-granularity modifications)
                sub = "/" + rel.strip("/")
                journal.mark_stale_subtree(
                    loc_id, sub if sub.endswith("/") else sub + "/"
                )
                entry.deep_dirs.add(sub)
            elif kind == EventKind.MODIFY and rel == "" and event.is_dir:
                return  # attrib touch on the location root: nothing to do
            elif kind == EventKind.CREATE and event.is_dir:
                # a dir moved/created with pre-existing contents emits no
                # per-child events: recursively scan the dir itself
                entry.deep_dirs.add("/" + rel.strip("/"))
            else:
                # CREATE/MODIFY file: a TARGETED journal invalidation —
                # the entry stops vouching (its chunk cache stays for
                # the dirty-range rehash) — then a shallow rescan of the
                # parent batches the changed file into the
                # indexer→identifier pipeline; unchanged siblings stay
                # journal-vouched through that rescan
                iso = IsolatedFilePathData.from_relative_str(
                    loc_id, rel, False
                )
                jkey = key_of(iso)
                if kind == EventKind.MODIFY:
                    # burst sizing: a MODIFY whose journal entry still
                    # has the dirty-range fast path (entry present,
                    # size unchanged — a touch/attrib storm, or an
                    # in-place mutation the chunk cache re-vouches in
                    # ~ms) counts as vouched: the rescan it needs is
                    # near-free, so coalescing beats firing per event
                    _, jentry = journal.lookup(
                        loc_id, jkey, None, count=False
                    )
                    st = stat_identity(event.path)
                    vouched = (
                        jentry is not None
                        and jentry.identity is not None
                        and st is not None
                        and st.size == jentry.identity.size
                    )
                journal.mark_stale(loc_id, jkey)
                parent = os.path.dirname(rel)
                entry.dirty_dirs.add("/" + parent.replace(os.sep, "/").strip("/"))
            self._count_burst(entry, vouched=vouched)
            self._schedule_flush(entry)
        except Exception:
            logger.exception("watcher event application failed: %s", event)

    def _apply_rename(
        self, db: Any, loc_id: int, old_rel: str, new_rel: str, is_dir: bool
    ) -> None:
        old_iso = IsolatedFilePathData.from_relative_str(loc_id, old_rel, is_dir)
        # a rename changes no content: the journal entry MOVES with the
        # file, keeping its cas/thumb/media vouches — no re-hash, no
        # re-thumbnail (the cheapest possible "targeted re-index")
        _new_iso = IsolatedFilePathData.from_relative_str(loc_id, new_rel, is_dir)
        IndexJournal(db).rename_path(
            loc_id, key_of(old_iso), key_of(_new_iso),
            *(
                (
                    f"{old_iso.materialized_path}{old_iso.name}/",
                    f"{_new_iso.materialized_path}{_new_iso.name}/",
                )
                if is_dir else (None, None)
            ),
        )
        row = db.find_one(
            "file_path",
            location_id=loc_id,
            materialized_path=old_iso.materialized_path,
            name=old_iso.name,
            extension=old_iso.extension,
            is_dir=int(is_dir),
        )
        new_iso = IsolatedFilePathData.from_relative_str(loc_id, new_rel, is_dir)
        if row is None:
            return  # never indexed; the next rescan picks it up
        db.update(
            "file_path",
            {"id": row["id"]},
            materialized_path=new_iso.materialized_path,
            name=new_iso.name,
            extension=new_iso.extension,
            date_modified=now_iso(),
        )
        if is_dir:
            # rewrite the subtree's materialized paths (ref:utils.rs rename)
            old_prefix = f"{old_iso.materialized_path}{old_iso.name}/"
            new_prefix = f"{new_iso.materialized_path}{new_iso.name}/"
            rows = db.query(
                "SELECT id, materialized_path FROM file_path "
                "WHERE location_id = ? AND substr(materialized_path, 1, ?) = ?",
                (loc_id, len(old_prefix), old_prefix),
            )
            for child in rows:
                db.update(
                    "file_path",
                    {"id": child["id"]},
                    materialized_path=new_prefix
                    + child["materialized_path"][len(old_prefix):],
                )

    def _apply_remove(self, db: Any, loc_id: int, rel: str, is_dir: bool) -> None:
        # the event's is_dir can be unknowable post-deletion: try file then dir
        journal = IndexJournal(db)
        for as_dir in ([is_dir] if is_dir else [False, True]):
            iso = IsolatedFilePathData.from_relative_str(loc_id, rel, as_dir)
            journal.delete_path(
                loc_id, key_of(iso),
                f"{iso.materialized_path}{iso.name}/" if as_dir else None,
            )
            row = db.find_one(
                "file_path",
                location_id=loc_id,
                materialized_path=iso.materialized_path,
                name=iso.name,
                extension=iso.extension,
                is_dir=int(as_dir),
            )
            if row is None:
                continue
            if as_dir:
                prefix = f"{iso.materialized_path}{iso.name}/"
                db.execute(
                    "DELETE FROM file_path WHERE location_id = ? "
                    "AND substr(materialized_path, 1, ?) = ?",
                    (loc_id, len(prefix), prefix),
                )
            db.delete("file_path", id=row["id"])
            return

    # --- debounced shallow rescan --------------------------------------

    def _count_burst(self, entry: _Watched, vouched: bool) -> None:
        """Accumulate the current burst's journal verdicts (reset at
        each flush): `vouched` events are ones the index journal still
        has a free/near-free path for — rename storms (vouches MOVE)
        and touch storms (size-stable entries the dirty-range rehash
        re-vouches in ~ms)."""
        import time

        now = time.monotonic()
        if (
            entry.flush_handle is None
            and now - entry.last_event > self.debounce_max
        ):
            # a rename-only storm schedules no flush, so its counters
            # never reset through _flush — a later lone event must not
            # inherit the stale widened window
            entry.burst_total = 0
            entry.burst_vouched = 0
        entry.last_event = now
        entry.burst_total += 1
        if vouched:
            entry.burst_vouched += 1

    def _debounce_window(self, entry: _Watched) -> float:
        """Journal-verdict-driven settle window: a burst DOMINATED by
        vouched events widens linearly with the vouched count (each
        extra event is more evidence the storm is churn, not content),
        capped at `debounce_max`; real content-change bursts keep the
        snappy base window."""
        if (
            entry.burst_vouched < DEBOUNCE_WIDEN_MIN
            or entry.burst_vouched * 2 < entry.burst_total
        ):
            return self.debounce
        widen = entry.burst_vouched / DEBOUNCE_WIDEN_MIN
        return min(self.debounce_max, self.debounce * widen)

    def _schedule_flush(self, entry: _Watched) -> None:
        if entry.flush_handle is not None:
            entry.flush_handle.cancel()
        loop = asyncio.get_running_loop()
        window = self._debounce_window(entry)
        entry.last_debounce = window
        entry.flush_handle = loop.call_later(
            window, self._spawn_flush, loop, entry
        )

    def _spawn_flush(self, loop: asyncio.AbstractEventLoop,
                     entry: _Watched) -> None:
        if self._shutting_down:
            # the debounce timer may fire in the same tick shutdown()
            # runs: its cancel() no-ops on a fired handle and the drain
            # below would miss a flush spawned after its gather snapshot
            return
        supervise(loop.create_task(self._flush(entry)), self._flush_tasks,
                  logger, "debounced rescan")

    async def _flush(self, entry: _Watched) -> None:
        dirs, entry.dirty_dirs = entry.dirty_dirs, set()
        deep, entry.deep_dirs = entry.deep_dirs, set()
        entry.flush_handle = None
        total, entry.burst_total = entry.burst_total, 0
        vouched, entry.burst_vouched = entry.burst_vouched, 0
        # flight-recorder record of the burst: when an index storm hits,
        # "what watcher activity preceded it" is the first question —
        # and the vouched/total split says whether the debounce sizing
        # read the storm right
        WATCHER_EVENTS.emit(
            "burst_flush",
            location=entry.location.get("id"),
            shallow_dirs=len(dirs), deep_dirs=len(deep),
            events=total, vouched=vouched,
            debounce_s=round(entry.last_debounce, 3),
        )
        # a deep scan of an ancestor covers shallow/deep scans below it
        def covered(sub: str, by: str) -> bool:
            return by == "/" or sub == by or sub.startswith(by.rstrip("/") + "/")

        deep = {
            d for d in deep if not any(covered(d, other) for other in deep if other != d)
        }
        dirs = {d for d in dirs if not any(covered(d, dd) for dd in deep)}
        for sub in sorted(deep):
            try:
                await deep_rescan_sub_path(
                    entry.library, entry.location, sub or "/", self.node.jobs
                )
            except Exception:
                logger.exception("deep rescan of %r failed", sub)
        for sub in sorted(dirs):
            try:
                await light_scan_location(
                    entry.library, entry.location, sub or "/", self.node.jobs
                )
            except Exception:
                logger.exception("shallow rescan of %r failed", sub)
