"""Mobile core bridge — the embedded host the mobile apps link against.

Parity: ref:apps/mobile/modules/sd-core/core/src/lib.rs — the reference
compiles the core INTO the app and exposes exactly two functions to the
JS side: `handle_core_msg(query, data_dir, callback)` (lazy-inits the
node on first use, executes one JSON-RPC request or a batch, answers
through a callback) and `spawn_core_event_listener(callback)` (the
subscription event channel). The platform shims (JNI on Android, ObjC
on iOS — `sd-core/{android,ios}/crate`) are thin marshalling wrappers
around those two calls.

This module is the same surface, TPU-native: a dedicated background
event loop owns ONE Node (the RUNTIME/NODE statics), both entry points
are callable from ANY foreign thread (the platform shims call in from
JS/JNI threads), and callbacks fire off-loop exactly like the
reference's. Message format is JSON-RPC shaped like rspc's:

    request:  {"id": .., "method": "<procedure key>",
               "params": {"arg": .., "library_id": ..}}    (or a list)
    response: {"jsonrpc": "2.0", "id": ..,
               "result": {"type": "response", "data": ..}}
            | {"id": .., "result": {"type": "error",
               "data": {"code": .., "message": ..}}}

Subscriptions: a request whose method is a subscription procedure
upgrades — the immediate response is `{"type": "started"}` and every
yielded value arrives on the event listener as
`{"id": .., "result": {"type": "event", "data": ..}}` until a
`{"method": "subscriptionStop", "params": {"id": ..}}` request or
core shutdown (the reference's SUBSCRIPTIONS map + oneshot cancel).

Embedding note: on-device the platform shim hosts CPython (libpython +
this package) and binds these two functions over the same string/
callback ABI the reference's JNI/ObjC shims use; everything below the
bridge line is identical to the desktop/server hosts — same Router,
same Node, same library data dir.
"""

from __future__ import annotations

import asyncio
import json
import threading
from typing import Any, Callable

# the server host's serializer (bytes→hex, UUID→str, to_wire/__dict__
# fallbacks): router payloads are NOT all JSON-native, and a plain
# json.dumps here would kill subscriptions the ws transport serves fine
from .api.server import _dumps

_lock = threading.Lock()
_loop: asyncio.AbstractEventLoop | None = None
_thread: threading.Thread | None = None
_node: Any = None
_init_lock: asyncio.Lock | None = None
_event_cb: Callable[[str], None] | None = None
_subscriptions: dict[Any, asyncio.Task] = {}


def _runtime() -> asyncio.AbstractEventLoop:
    """The RUNTIME static: one background loop thread, lazily started."""
    global _loop, _thread
    with _lock:
        if _loop is not None and _thread is not None and _thread.is_alive():
            return _loop
        loop = asyncio.new_event_loop()

        def run() -> None:
            asyncio.set_event_loop(loop)
            loop.run_forever()

        t = threading.Thread(target=run, name="sdx-mobile-core", daemon=True)
        t.start()
        _loop, _thread = loop, t
        return loop


async def _ensure_node(data_dir: str):
    """The NODE static: lazy-init on the first message (ref:lib.rs:72-87).
    The lock serializes concurrent FIRST messages — without it two
    early calls would both start Nodes on the same data dir and leak
    one of them."""
    global _node, _init_lock
    if _init_lock is None:
        _init_lock = asyncio.Lock()
    async with _init_lock:
        if _node is not None:
            return _node
        from .node import Node

        node = Node(data_dir)
        await node.start()
        _node = node
        return node


def _error_response(req_id: Any, code: int, message: str) -> dict[str, Any]:
    return {"jsonrpc": "2.0", "id": req_id,
            "result": {"type": "error",
                       "data": {"code": code, "message": message}}}


async def _run_one(node, request: dict[str, Any]) -> dict[str, Any] | None:
    from .api.router import RspcError

    req_id = request.get("id")
    method = str(request.get("method", ""))
    params = request.get("params") or {}
    arg = params.get("arg")
    library_id = params.get("library_id")

    if method == "subscriptionStop":
        task = _subscriptions.pop(params.get("id"), None)
        if task is not None:
            task.cancel()
        return {"jsonrpc": "2.0", "id": req_id,
                "result": {"type": "response", "data": None}}

    proc = node.router.procedures.get(method)
    if proc is None:
        return _error_response(req_id, 404, f"procedure {method!r}")
    if proc.kind == "subscription":
        if _event_cb is None:
            return _error_response(
                req_id, 400,
                "no event listener: call spawn_core_event_listener first")
        if req_id in _subscriptions:
            return _error_response(req_id, 400, f"id {req_id!r} in use")
        try:
            # resolution errors (unknown proc shape, bad library_id)
            # raise HERE, before "started" is promised — the ws
            # transport answers these on the request too
            agen = node.router.subscribe(node, method, arg, library_id)
        except RspcError as e:
            return _error_response(req_id, e.code, e.message)

        async def pump() -> None:
            try:
                async for item in agen:
                    cb = _event_cb
                    if cb is None:
                        break
                    cb(_dumps({
                        "jsonrpc": "2.0", "id": req_id,
                        "result": {"type": "event", "data": item},
                    }))
            except asyncio.CancelledError:
                raise
            except Exception as e:  # noqa: BLE001 - surfaced to the app
                cb = _event_cb
                if cb is not None:
                    cb(_dumps(_error_response(req_id, 500, str(e))))
            finally:
                _subscriptions.pop(req_id, None)

        _subscriptions[req_id] = asyncio.get_running_loop().create_task(pump())
        return {"jsonrpc": "2.0", "id": req_id,
                "result": {"type": "started"}}

    try:
        data = await node.router.exec(node, method, arg, library_id)
        return {"jsonrpc": "2.0", "id": req_id,
                "result": {"type": "response", "data": data}}
    except RspcError as e:
        return _error_response(req_id, e.code, e.message)
    except Exception as e:  # noqa: BLE001 - the app gets a clean error
        return _error_response(req_id, 500, f"{type(e).__name__}: {e}")


def handle_core_msg(query: str, data_dir: str,
                    callback: Callable[[str], None]) -> None:
    """Entry point #1 (ref:lib.rs:65): execute one request or a batch.
    Callable from any thread; `callback` receives the JSON response
    array (always an array, like the reference's join_all collect)."""
    loop = _runtime()

    async def work() -> None:
        try:
            await _work_inner()
        except Exception as e:  # noqa: BLE001 - the callback MUST fire:
            # a swallowed exception here leaves the app-side promise
            # waiting forever
            try:
                callback(_dumps([_error_response(None, 500,
                                                 f"bridge: {e}")]))
            except Exception:  # noqa: BLE001 - nothing left to tell
                pass

    async def _work_inner() -> None:
        try:
            parsed = json.loads(query)
        except ValueError:
            # decode failures echo the query back as the error, exactly
            # like the reference (ref:lib.rs:95-99 — which also decodes
            # BEFORE touching the NODE static: garbage input must not
            # pay full core startup)
            callback(_dumps([_error_response(None, 400, query)]))
            return
        try:
            node = await _ensure_node(data_dir)
        except Exception as e:  # noqa: BLE001 - init failure → app dialog
            callback(_dumps([_error_response(None, 500,
                                             f"core init: {e}")]))
            return
        reqs = parsed if isinstance(parsed, list) else [parsed]

        async def one(req):
            if not isinstance(req, dict):
                return _error_response(None, 400, "bad request")
            if not isinstance(req.get("id"), (str, int, float, type(None))):
                return _error_response(None, 400,
                                       "id must be a string, number or null")
            return await _run_one(node, req)

        # concurrent like the reference's join_all; gather preserves
        # response order
        responses = await asyncio.gather(*(one(r) for r in reqs))
        callback(_dumps([r for r in responses if r is not None]))

    asyncio.run_coroutine_threadsafe(work(), loop)


def spawn_core_event_listener(callback: Callable[[str], None]) -> None:
    """Entry point #2 (ref:lib.rs:123): register the subscription event
    channel. Last registration wins (hot-reload of the JS side)."""
    global _event_cb
    _event_cb = callback


def shutdown_core(timeout: float = 15.0) -> None:
    """Tear the embedded core down (app background/exit): cancel
    subscriptions, node shutdown, stop the runtime loop. Best-effort
    against in-flight messages: the init lock is awaited so a Node
    whose start() is mid-flight is captured and shut down, not leaked;
    a teardown that overruns `timeout` still stops the loop."""
    global _node, _loop, _thread, _event_cb, _init_lock
    with _lock:
        loop, thread = _loop, _thread
        _loop = _thread = None
        _event_cb = None
    if loop is None or thread is None or not thread.is_alive():
        _node = None
        _init_lock = None
        return

    async def stop() -> None:
        global _node
        for task in list(_subscriptions.values()):
            task.cancel()
        if _subscriptions:
            await asyncio.gather(*_subscriptions.values(),
                                 return_exceptions=True)
        _subscriptions.clear()
        # wait out any in-flight _ensure_node so ITS node is the one we
        # shut down (reading the global, not a pre-teardown snapshot)
        if _init_lock is not None:
            async with _init_lock:
                node, _node = _node, None
        else:
            node, _node = _node, None
        if node is not None:
            await node.shutdown()

    fut = asyncio.run_coroutine_threadsafe(stop(), loop)
    try:
        fut.result(timeout)
    except Exception:  # noqa: BLE001 - teardown is best-effort; the
        pass           # loop still stops below either way
    finally:
        loop.call_soon_threadsafe(loop.stop)
        thread.join(timeout)
        if not thread.is_alive():
            # the selector + self-pipe fds leak per background/
            # foreground cycle otherwise
            loop.close()
        _subscriptions.clear()
        _node = None
        _init_lock = None
