"""sdx — the command-line host.

Parity: two reference hosts in one binary — the headless server
(ref:apps/server/src/main.rs: node + HTTP API) and the crypto
inspector CLI (ref:apps/cli/src/main.rs: prints encrypted-file header
details). Plus the survey's build-plan surface (SURVEY §7 step 4):
`sdx index <path> --backend=tpu|cpu` and `sdx bench`.

Run as `python -m spacedrive_tpu <command>`.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import sys
import time
from typing import Any

DEFAULT_DATA_DIR = os.path.expanduser("~/.spacedrive_tpu")


def _make_node(args: argparse.Namespace, **kwargs: Any):
    from .node import Node

    node = Node(
        args.data_dir,
        use_device=(getattr(args, "backend", "tpu") != "cpu"),
        **kwargs,
    )
    if getattr(args, "no_p2p", False):
        node.config.config.p2p.enabled = False
    return node


async def _get_or_create_library(node, name: str):
    for lib in node.libraries.libraries.values():
        if lib.name == name:
            return lib
    return await node.create_library(name)


# --- commands -------------------------------------------------------------


async def cmd_index(args: argparse.Namespace) -> int:
    from .location.locations import LocationCreateArgs, scan_location
    from .node.statistics import update_statistics

    node = _make_node(args)
    await node.start()
    try:
        lib = await _get_or_create_library(node, args.library)
        existing = lib.db.find_one("location", path=os.path.abspath(args.path))
        t0 = time.perf_counter()
        if existing is None:
            loc = LocationCreateArgs(path=args.path).create(lib)
        else:
            loc = existing
        await scan_location(lib, loc, node.jobs, backend=args.backend)
        await node.jobs.wait_idle()
        await node.thumbnailer.wait_library_batch(str(lib.id))
        elapsed = time.perf_counter() - t0
        stats = update_statistics(lib.db, node.thumbnailer.data_dir)
        files = lib.db.count("file_path", "is_dir = 0")
        print(
            json.dumps(
                {
                    "library": lib.name,
                    "location_id": loc["id"],
                    "files": files,
                    "objects": stats["total_object_count"],
                    "bytes": int(stats["total_bytes_used"]),
                    "thumbnails": node.thumbnailer.generated,
                    "labeled": node.image_labeler.labeled
                    if node.image_labeler
                    else 0,
                    "backend": args.backend,
                    "seconds": round(elapsed, 2),
                }
            )
        )
        return 0
    finally:
        await node.shutdown()


async def cmd_serve(args: argparse.Namespace) -> int:
    node = _make_node(args)
    await node.start()
    port = await node.start_api(host=args.host, port=args.port)
    print(f"sdx serving on http://{args.host}:{port}  (rspc: /rspc/<key>)")
    if node.p2p is not None:
        print(f"p2p on port {node.p2p.port}, identity {node.p2p.p2p.remote_identity}")
        if args.auto_accept_pairing:
            node.p2p.pairing.auto_accept = True
            print("pairing: auto-accept enabled")
    elif args.auto_accept_pairing:
        print("warning: --auto-accept-pairing ignored (p2p disabled)",
              file=sys.stderr)
    if args.cloud:
        # persist the origin even with zero libraries yet — libraries
        # created later enable against it via cloud.sync.enable
        node.config.config.preferences["cloud_api_origin"] = args.cloud
        node.config.save()
        enabled = 0
        for lib in list(node.libraries.libraries.values()):
            try:
                await node.enable_cloud_sync(lib)
                enabled += 1
            except Exception as e:
                print(f"cloud sync for {lib.name!r} failed: {e}", file=sys.stderr)
        print(f"cloud sync: {args.cloud} ({enabled} libraries enabled)")
    try:
        while True:
            await asyncio.sleep(3600)
    except (KeyboardInterrupt, asyncio.CancelledError):
        pass
    finally:
        await node.shutdown()
    return 0


async def cmd_relay(args: argparse.Namespace) -> int:
    """Run the standalone self-hosted relay: WAN sync collections over
    HTTP + the P2P rendezvous (authenticated listen/dial splicing) —
    the deployable form of what the reference's closed cloud provides."""
    from .cloud.relay import CloudRelay
    from .p2p.relay import RelayLimits

    relay = CloudRelay(p2p_limits=RelayLimits(
        max_pipes_per_target=args.max_pipes_per_target,
        max_pipes_total=args.max_pipes,
        pipe_rate_bytes_per_s=args.pipe_rate,
    ))
    port = await relay.start(host=args.host, port=args.port,
                             p2p_port=args.p2p_port)
    print(f"relay: sync API on http://{args.host}:{port}/api  "
          f"(point nodes' --cloud at http://{args.host}:{port})")
    print(f"relay: p2p rendezvous on {args.host}:{relay.p2p_port}  "
          f"(point nodes' p2p.relay at {args.host}:{relay.p2p_port})")
    try:
        while True:
            await asyncio.sleep(args.stats_interval or 3600)
            if args.stats_interval:
                s = relay.p2p_relay.stats.snapshot()
                print(f"relay stats: {json.dumps(s)}", flush=True)
    except (KeyboardInterrupt, asyncio.CancelledError):
        pass
    finally:
        await relay.shutdown()
    return 0


async def cmd_status(args: argparse.Namespace) -> int:
    node = _make_node(args, with_labeler=False)
    await node.start()
    try:
        out = await node.router.exec(node, "nodeState")
        out["libraries"] = []
        for lib in node.libraries.libraries.values():
            reports = await node.router.exec(
                node, "jobs.reports", library_id=str(lib.id)
            )
            out["libraries"].append(
                {
                    "id": str(lib.id),
                    "name": lib.name,
                    "file_paths": lib.db.count("file_path"),
                    "objects": lib.db.count("object"),
                    "recent_jobs": reports[:5],
                }
            )
        print(json.dumps(out, indent=2))
        return 0
    finally:
        await node.shutdown()


async def cmd_browse(args: argparse.Namespace) -> int:
    from .location.non_indexed import walk_dir

    node = _make_node(args, with_labeler=False)
    try:
        listing = walk_dir(node, args.path, with_hidden=args.hidden,
                           queue_thumbnails=False)
        for e in listing["entries"]:
            kind = "dir " if e["is_dir"] else "file"
            print(f"{kind}  {e['size_in_bytes']:>12}  {e['name']}"
                  + (f".{e['extension']}" if e["extension"] else ""))
        return 0
    finally:
        await node.shutdown()


async def cmd_duplicates(args: argparse.Namespace) -> int:
    from .jobs.manager import JobBuilder
    from .object.duplicates import DuplicateDetectorJob, find_duplicates

    node = _make_node(args, with_labeler=False)
    await node.start()
    try:
        lib = await _get_or_create_library(node, args.library)
        await JobBuilder(
            DuplicateDetectorJob({"threshold": args.threshold})
        ).spawn(node.jobs, lib)
        await node.jobs.wait_idle()
        groups = find_duplicates(lib, threshold=args.threshold)
        print(json.dumps(groups, indent=2))
        return 0
    finally:
        await node.shutdown()


async def cmd_search(args: argparse.Namespace) -> int:
    """Search an indexed library: plain name match by default,
    `--semantic` scores the query against the vector index (the query
    is an image path to embed, or a label name whose objects' centroid
    becomes the probe)."""
    from .api.search import search_paths, search_semantic

    node = _make_node(args, with_labeler=False)
    await node.start()
    try:
        lib = await _get_or_create_library(node, args.library)
        if args.semantic:
            out = search_semantic(
                lib, {"query": args.query, "take": args.take}
            )
            if not out.get("resolved"):
                print(
                    "query resolved to no probe vector (not an image "
                    "path or a stored label name)",
                    file=sys.stderr,
                )
                return 1
        else:
            out = search_paths(
                lib,
                {"filter": {"search": args.query}, "take": args.take},
            )
        print(json.dumps(out, indent=2, default=str))
        return 0
    finally:
        await node.shutdown()


import contextlib


@contextlib.asynccontextmanager
async def _mesh_node(args: argparse.Namespace):
    """Started node with p2p up and discovery settled, or SystemExit(1)."""
    node = _make_node(args, with_labeler=False)
    await node.start()
    try:
        if node.p2p is None:
            print("p2p is disabled in the node config", file=sys.stderr)
            raise SystemExit(1)
        await asyncio.sleep(args.wait)  # let discovery settle
        yield node
    finally:
        await node.shutdown()


async def cmd_peers(args: argparse.Namespace) -> int:
    """Discover mesh peers for a few seconds and list them."""
    async with _mesh_node(args) as node:
        peers = node.p2p.p2p.discovered_peers()
        for p in peers:
            print(
                json.dumps(
                    {
                        "identity": str(p.identity),
                        "name": p.metadata.get("name"),
                        "os": p.metadata.get("operating_system"),
                        "libraries": [
                            x for x in p.metadata.get("libraries", "").split(",") if x
                        ],
                        "addrs": sorted(f"{h}:{pt}" for h, pt in p.addrs),
                    }
                )
            )
        if not peers:
            print("no peers discovered", file=sys.stderr)
        return 0


async def cmd_pair(args: argparse.Namespace) -> int:
    """Join a peer's library over the mesh (consent happens on the peer)."""
    import uuid

    from .p2p.identity import RemoteIdentity

    async with _mesh_node(args) as node:
        try:
            lib = await node.p2p.pairing.join(
                node.p2p.p2p,
                RemoteIdentity.from_str(args.identity),
                uuid.UUID(args.library) if args.library else None,
            )
        except PermissionError as e:
            print(f"rejected: {e}", file=sys.stderr)
            return 1
        except asyncio.TimeoutError:
            print("peer did not respond (offline, or consent timed out)",
                  file=sys.stderr)
            return 1
        except FileExistsError as e:
            print(str(e), file=sys.stderr)
            return 1
        except (ValueError, ConnectionError) as e:
            print(f"pairing failed: {e}", file=sys.stderr)
            return 1
        print(json.dumps({"library": str(lib.id), "name": lib.name}))
        # give the first sync pull a moment before tearing down
        await asyncio.sleep(2)
        return 0


async def cmd_spacedrop(args: argparse.Namespace) -> int:
    """Send files to a peer (they accept/reject on their end)."""
    from .p2p.identity import RemoteIdentity

    async with _mesh_node(args) as node:
        try:
            drop_id = await node.p2p.spacedrop.send(
                RemoteIdentity.from_str(args.identity), list(args.files)
            )
        except PermissionError as e:
            print(f"rejected: {e}", file=sys.stderr)
            return 1
        except asyncio.TimeoutError:
            print("peer did not respond", file=sys.stderr)
            return 1
        except (ValueError, ConnectionError, OSError) as e:
            print(f"spacedrop failed: {e}", file=sys.stderr)
            return 1
        print(json.dumps({"drop_id": str(drop_id), "sent": len(args.files)}))
        return 0


def _http_get(url: str, timeout: float = 30.0) -> str:
    import urllib.request

    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.read().decode()


async def cmd_mesh_status(args: argparse.Namespace) -> int:
    """Mesh-wide observability: every known peer's latest telemetry
    snapshot with staleness marking, plus this node's own health.
    With --url, reads a running node's GET /mesh; otherwise boots an
    ephemeral mesh node, discovers peers, and pulls directly."""
    if args.url:
        import urllib.error

        url = args.url.rstrip("/") + "/mesh"
        if args.no_refresh:
            url += "?refresh=0"
        try:
            doc = await asyncio.to_thread(_http_get, url)
        except (urllib.error.URLError, OSError) as e:
            print(f"mesh-status: cannot reach {url}: {e}", file=sys.stderr)
            print("is a node running? start one with `sdx serve`",
                  file=sys.stderr)
            return 1
        _write_or_print(json.dumps(json.loads(doc), indent=2), args.out)
        return 0

    from .telemetry.federation import mesh_status

    async with _mesh_node(args) as node:
        await node.p2p.refresh_federation(force=True)
        status = mesh_status(node)
        _write_or_print(json.dumps(status, indent=2, default=str), args.out)
        peers = status["mesh"]["peers"]
        if not peers:
            print("no peers in the federation cache (none discovered?)",
                  file=sys.stderr)
        return 0


async def cmd_serve_status(args: argparse.Namespace) -> int:
    """Serve-layer posture: admission-gate mode, per-class
    inflight/queued/shed counts, and read-cache occupancy. With --url,
    reads a running node's rspc telemetry.serve; otherwise boots an
    ephemeral node and reports its (idle) gate state."""
    if args.url:
        import urllib.error
        import urllib.request

        url = args.url.rstrip("/") + "/rspc/telemetry.serve"
        req = urllib.request.Request(
            url, data=b"{}", headers={"Content-Type": "application/json"},
            method="POST",
        )

        def post() -> str:
            with urllib.request.urlopen(req, timeout=10) as resp:
                return resp.read().decode()

        try:
            doc = await asyncio.to_thread(post)
        except (urllib.error.URLError, OSError) as e:
            print(f"serve-status: cannot reach {url}: {e}", file=sys.stderr)
            print("is a node running? start one with `sdx serve`",
                  file=sys.stderr)
            return 1
        _write_or_print(
            json.dumps(json.loads(doc).get("result"), indent=2), args.out
        )
        return 0

    from .node import Node
    from .serve import runtime_for

    node = Node(args.data_dir, use_device=False, with_labeler=False)
    try:
        serve = runtime_for(node)
        doc = (
            {"enabled": False} if serve is None
            else {"enabled": True, **serve.snapshot()}
        )
        _write_or_print(json.dumps(doc, indent=2, default=str), args.out)
        return 0
    finally:
        await node.shutdown()


async def cmd_tenants(args: argparse.Namespace) -> int:
    """Per-tenant accounting: the space-saving heavy-hitter sketches
    (telemetry/tenants.py) — per-surface totals, resident top-K with
    error bounds, fairness index, dominant share. Tenant keys are
    hashed labels, never raw UUIDs. With --url, reads a running
    node's GET /tenants; with --peer, shows the named mesh peer's
    federated tenant digest; otherwise boots an ephemeral mesh node
    and shows the mesh-wide digests."""
    if args.url:
        import urllib.error

        url = args.url.rstrip("/") + "/tenants"
        try:
            doc = await asyncio.to_thread(_http_get, url)
        except (urllib.error.URLError, OSError) as e:
            print(f"tenants: cannot reach {url}: {e}", file=sys.stderr)
            print("is a node running? start one with `sdx serve`",
                  file=sys.stderr)
            return 1
        _write_or_print(json.dumps(json.loads(doc), indent=2), args.out)
        return 0

    from .telemetry.federation import mesh_status

    async with _mesh_node(args) as node:
        await node.p2p.refresh_federation(force=True)
        mesh = mesh_status(node)["mesh"]
        from .telemetry import tenants as _tenants_mod

        peers = {
            pid: {
                "peer_label": p.get("peer_label"),
                "stale": p.get("stale"),
                "tenants": (p.get("snapshot") or {}).get("tenants"),
            }
            for pid, p in mesh.get("peers", {}).items()
        }
        if args.peer:
            want = args.peer
            match = {
                pid: p for pid, p in peers.items()
                if want in (pid, p.get("peer_label"))
                or pid.startswith(want)
            }
            if not match:
                print(f"tenants: no mesh peer matches {want!r} "
                      f"(known: {sorted(peers)})", file=sys.stderr)
                return 1
            doc: dict = {"peers": match}
        else:
            doc = {"local": _tenants_mod.snapshot(), "peers": peers}
        _write_or_print(json.dumps(doc, indent=2, default=str), args.out)
        return 0


def cmd_crypto(args: argparse.Namespace) -> int:
    from .crypto import FileHeader, decrypt_file, encrypt_file

    if args.crypto_cmd == "inspect":
        # ref:apps/cli/src/main.rs — print header details
        with open(args.file, "rb") as f:
            header, raw = FileHeader.from_reader(f)
        print(
            json.dumps(
                {
                    "version": header.version,
                    "algorithm": header.algorithm.name,
                    "keyslots": [
                        {
                            "hashing": ks.hashing_algorithm.kind,
                            "params": int(ks.hashing_algorithm.params),
                        }
                        for ks in header.keyslots
                    ],
                    "has_metadata": header.metadata is not None,
                    "has_preview_media": header.preview_media is not None,
                    "header_bytes": len(raw),
                },
                indent=2,
            )
        )
    elif args.crypto_cmd == "encrypt":
        import getpass

        pw = args.password or getpass.getpass("password: ")
        encrypt_file(args.file, args.file + ".sdenc", pw.encode())
        print(f"wrote {args.file}.sdenc")
    elif args.crypto_cmd == "decrypt":
        import getpass

        pw = args.password or getpass.getpass("password: ")
        out = (
            args.file[: -len(".sdenc")]
            if args.file.endswith(".sdenc")
            else args.file + ".decrypted"
        )
        meta = decrypt_file(args.file, out, pw.encode())
        print(f"wrote {out}" + (f"  metadata: {meta}" if meta else ""))
    return 0


def cmd_labeler(args: argparse.Namespace) -> int:
    """Provision/inspect the image-labeler model artifact.

    The reference downloads pretrained YOLOv8 before labeling can run
    (ref:crates/ai/src/image_labeler/model/yolov8.rs:45-88); offline
    deployments instead train a checkpoint here (`sdx labeler train`)
    or drop any `.onnx` classifier at <data-dir>/image_labeler/model.onnx.
    """
    labeler_dir = os.path.join(args.data_dir, "image_labeler")
    if args.labeler_cmd == "provision":
        from .models import provision

        try:
            classes = None
            if args.classes:
                with open(args.classes) as f:
                    classes = [ln.strip() for ln in f if ln.strip()]
            if args.bundled:
                if args.src or args.url or args.sha256 or args.classes:
                    raise ValueError(
                        "--bundled installs the pinned in-package artifact; "
                        "it cannot combine with --from/--url/--sha256/--classes"
                    )
                info = provision.install_bundled(labeler_dir)
            elif args.src:
                info = provision.import_artifact(
                    args.src, labeler_dir, classes=classes,
                    sha256=args.sha256,
                )
            else:
                url = args.url or provision.DEFAULT_MODEL_URL
                print(f"downloading {url}…", file=sys.stderr, flush=True)
                info = provision.fetch(url, labeler_dir, classes=classes,
                                       sha256=args.sha256)
        except Exception as e:  # noqa: BLE001 - CLI contract: JSON + rc 1
            print(json.dumps({"error": f"{type(e).__name__}: {e}"}))
            return 1
        print(json.dumps(info, indent=2))
        return 0
    if args.labeler_cmd == "status":
        from .models.labeler_actor import ImageLabeler

        actor = ImageLabeler(labeler_dir)
        artifact = actor.resolve_artifact()
        info = {"artifact": None, "enabled": False}
        if artifact is not None:
            info = {"artifact": {"kind": artifact[0], "path": artifact[1]},
                    "enabled": True}
            if artifact[0] == "checkpoint":
                from .models import checkpoint

                _params, meta = checkpoint.load(artifact[1])
                info["classes"] = len(meta["classes"])
                info["image_size"] = meta["image_size"]
                info["metrics"] = meta.get("metrics", {})
        print(json.dumps(info, indent=2))
        return 0
    if args.labeler_cmd == "train":
        from .models.train import TrainConfig, train_folder

        cfg = TrainConfig(
            image_size=args.image_size, batch_size=args.batch_size,
            steps=args.steps, learning_rate=args.lr,
            use_device=args.backend != "cpu",
        )
        out = args.out or os.path.join(labeler_dir, "weights.npz")
        metrics = train_folder(
            args.dataset, out, cfg,
            progress=lambda step, loss: print(
                f"step {step}/{cfg.steps}  loss {loss:.4f}", flush=True
            ),
        )
        print(json.dumps({"checkpoint": out, "metrics": metrics}, indent=2))
        return 0
    if args.labeler_cmd == "train-demo":
        import numpy as np

        from .models import checkpoint as ckpt_mod
        from .models.train import (
            TrainConfig, array_batches, digits_demo_dataset, train,
        )

        cfg = TrainConfig(
            image_size=32, widths=(8, 16, 32, 32, 32), depths=(1, 1, 1, 1),
            batch_size=64, steps=args.steps,
            use_device=args.backend != "cpu",
        )
        (tr_x, tr_y), (ev_x, ev_y), classes = digits_demo_dataset(cfg.image_size)
        params, _model, metrics = train(
            array_batches(tr_x, tr_y, cfg.batch_size), classes, cfg,
            eval_set=(ev_x, ev_y),
            progress=lambda step, loss: print(
                f"step {step}/{cfg.steps}  loss {loss:.4f}", flush=True
            ),
        )
        out = args.out or os.path.join(labeler_dir, "weights.npz")
        ckpt_mod.save(out, params, classes=classes, image_size=cfg.image_size,
                      widths=cfg.widths, depths=cfg.depths,
                      extra={"metrics": metrics, "trained_on": "sklearn-digits"})
        print(json.dumps({"checkpoint": out, "metrics": metrics}, indent=2))
        return 0
    return 2


def cmd_bench(_args: argparse.Namespace) -> int:
    import runpy

    bench = os.path.join(os.path.dirname(os.path.dirname(__file__)), "bench.py")
    runpy.run_path(bench, run_name="__main__")
    return 0


def _write_or_print(doc: str, out: str | None) -> None:
    if out:
        with open(out, "w") as f:
            f.write(doc + "\n")
        print(f"wrote {out} ({len(doc)} bytes)", file=sys.stderr)
    else:
        print(doc)


def cmd_trace_export(args: argparse.Namespace) -> int:
    """Fetch a running node's Chrome-trace JSON (GET /trace) — load the
    output in Perfetto (ui.perfetto.dev) or chrome://tracing."""
    import urllib.error
    import urllib.request

    url = args.url.rstrip("/") + "/trace"
    if args.trace_id:
        url += f"?trace_id={args.trace_id}"
    try:
        with urllib.request.urlopen(url, timeout=10) as resp:
            doc = resp.read().decode()
    except (urllib.error.URLError, OSError) as e:
        print(f"trace-export: cannot reach {url}: {e}", file=sys.stderr)
        print("is a node running? start one with `sdx serve`", file=sys.stderr)
        return 1
    # refuse to write a non-trace artifact (a proxy error page, a
    # different server on that port) — with a message, not a traceback
    try:
        parsed = json.loads(doc)
        events = parsed["traceEvents"]
    except (ValueError, TypeError, KeyError):
        print(f"trace-export: {url} did not return Chrome-trace JSON "
              f"(is that really an sdx node?)", file=sys.stderr)
        return 1
    print(f"trace-export: {len(events)} events", file=sys.stderr)
    _write_or_print(json.dumps(parsed, indent=2), args.out)
    return 0


def cmd_attrib(args: argparse.Namespace) -> int:
    """Critical-path attribution report from a running node: where the
    last pass's (or --trace-id's) wall-clock went — device / host_cpu /
    link / queue_wait / unattributed-gap — with executor-side spans
    pulled from mesh peers (docs/observability.md "Attribution,
    history, and SLOs")."""
    import urllib.error
    import urllib.parse
    import urllib.request

    url = args.url.rstrip("/") + "/attrib"
    query = {}
    if args.trace_id:
        query["trace_id"] = args.trace_id
    if args.refresh:
        query["refresh"] = "1"
    if query:
        url += "?" + urllib.parse.urlencode(query)
    try:
        with urllib.request.urlopen(url, timeout=30) as resp:
            doc = json.loads(resp.read().decode())
    except (urllib.error.URLError, OSError, ValueError) as e:
        print(f"attrib: cannot reach {url}: {e}", file=sys.stderr)
        print("is a node running? start one with `sdx serve`",
              file=sys.stderr)
        return 1
    if doc.get("error"):
        print(f"attrib: {doc['error']}", file=sys.stderr)
        return 1
    _write_or_print(json.dumps(doc, indent=2), args.out)
    buckets = doc.get("buckets") or {}
    if buckets:
        wall = doc.get("wall_seconds") or 0.0
        split = "  ".join(
            f"{k}={v:.2f}s" for k, v in sorted(
                buckets.items(), key=lambda kv: kv[1], reverse=True)
        )
        print(f"attrib: {wall:.2f}s critical path — {split}",
              file=sys.stderr)
    return 0


async def cmd_profile_peer(args: argparse.Namespace) -> int:
    """Pull a MESH PEER's host profile over the TELEMETRY wire
    (profile_pull — the same library-members-only trust bar as
    trace_pull; frame names are module:function only, so nothing
    needing redaction rides the wire)."""
    from .p2p.identity import RemoteIdentity
    from .p2p.manager import SYNC_POLICY
    from .p2p.operations import request_profile
    from .utils.resilience import BreakerOpen

    async with _mesh_node(args) as node:
        try:
            doc = await SYNC_POLICY.call(
                args.peer,
                lambda: request_profile(
                    node.p2p.p2p, RemoteIdentity.from_str(args.peer)
                ),
            )
        except PermissionError as e:
            print(f"profile: peer refused: {e}", file=sys.stderr)
            return 1
        except (BreakerOpen, ValueError, ConnectionError, OSError,
                EOFError, asyncio.TimeoutError) as e:
            print(f"profile: cannot reach peer: {e}", file=sys.stderr)
            return 1
        if args.folded:
            _write_or_print(str(doc.get("folded", "")).rstrip("\n"),
                            args.out)
        else:
            _write_or_print(json.dumps(doc.get("profile"), indent=2),
                            args.out)
        return 0


def cmd_profile(args: argparse.Namespace) -> int:
    """Host-profile read path: the continuous sampler's collapsed-stack
    view from a running node (--url, default), or pulled from a mesh
    peer (--peer). --folded emits flamegraph.pl collapsed-stack text —
    pipe it into flamegraph.pl / speedscope."""
    if args.peer:
        return asyncio.run(cmd_profile_peer(args))
    import urllib.error

    url = args.url.rstrip("/") + "/profile"
    if args.folded:
        url += "?format=folded"
    elif args.mesh:
        url += "?mesh=1"
    try:
        doc = _http_get(url)
    except (urllib.error.URLError, OSError) as e:
        print(f"profile: cannot reach {url}: {e}", file=sys.stderr)
        print("is a node running? start one with `sdx serve`",
              file=sys.stderr)
        return 1
    if args.folded:
        _write_or_print(doc.rstrip("\n"), args.out)
        return 0
    try:
        parsed = json.loads(doc)
    except ValueError:
        print(f"profile: {url} did not return JSON "
              f"(is that really an sdx node?)", file=sys.stderr)
        return 1
    _write_or_print(json.dumps(parsed, indent=2), args.out)
    local = parsed.get("local") if args.mesh else parsed
    if isinstance(local, dict) and local.get("enabled"):
        groups = local.get("frame_groups") or []
        split = "  ".join(
            f"{g['group']}={g['share']:.0%}" for g in groups[:5]
        )
        print(f"profile: {local.get('samples', 0)} samples over "
              f"{local.get('duration_s', 0)}s — {split}", file=sys.stderr)
    return 0


def cmd_slo(args: argparse.Namespace) -> int:
    """SLO burn-rate posture. With --url, the live evaluation from a
    running node (rspc telemetry.slo); otherwise evaluated offline over
    the data dir's persistent telemetry history — which survives
    restarts, so this reads a continuous series across node
    generations."""
    if args.url:
        import urllib.error
        import urllib.request

        url = args.url.rstrip("/") + "/rspc/telemetry.slo"
        req = urllib.request.Request(
            url, data=b"{}", headers={"Content-Type": "application/json"},
            method="POST",
        )
        try:
            with urllib.request.urlopen(req, timeout=10) as resp:
                payload = json.loads(resp.read().decode())
        except (urllib.error.URLError, OSError, ValueError) as e:
            print(f"slo: cannot reach {url}: {e}", file=sys.stderr)
            print("is a node running? start one with `sdx serve`",
                  file=sys.stderr)
            return 1
        doc = payload.get("result")
    else:
        from .telemetry import slo as _slo
        from .telemetry.history import history_dir

        doc = _slo.evaluate(directory=history_dir(args.data_dir))
    _write_or_print(json.dumps(doc, indent=2), args.out)
    if isinstance(doc, dict):
        for s in doc.get("slos") or []:
            print(f"slo: {s['name']}: {s['status']}"
                  + (f"  (current {s['current']:g})"
                     if isinstance(s.get("current"), (int, float)) else ""),
                  file=sys.stderr)
    return 0


async def cmd_debug_bundle_peer(args: argparse.Namespace) -> int:
    """Pull a REMOTE node's debug bundle across the mesh. The bundle is
    built — and fully redacted — by the OWNING node before anything
    touches the wire (telemetry.bundle runs there); this side only
    receives the already-clean artifact. The peer must have the
    remoteRspc feature enabled."""
    from .p2p.identity import RemoteIdentity
    from .p2p.rspc import RSPC_POLICY, RemoteRspcError, remote_exec

    async with _mesh_node(args) as node:
        try:
            bundle = await RSPC_POLICY.call(
                args.peer,
                lambda: remote_exec(
                    node.p2p.p2p,
                    RemoteIdentity.from_str(args.peer),
                    "telemetry.debug_bundle",
                ),
            )
        except RemoteRspcError as e:
            print(f"debug-bundle: peer refused: {e} (code {e.code})",
                  file=sys.stderr)
            if e.code == 403:
                print("the peer must enable the remoteRspc feature "
                      "(toggleFeature remoteRspc)", file=sys.stderr)
            return 1
        except (ValueError, ConnectionError, OSError, EOFError,
                asyncio.TimeoutError) as e:
            print(f"debug-bundle: cannot reach peer: {e}", file=sys.stderr)
            return 1
        _write_or_print(json.dumps(bundle, indent=2), args.out)
        return 0


def cmd_debug_bundle(args: argparse.Namespace) -> int:
    """The redacted debug bundle: from a running node (--url) with live
    metrics/rings, from a mesh peer (--peer, redacted on the owning
    node), or offline straight off the data dir."""
    from .telemetry.bundle import render_bundle

    if args.peer:
        return asyncio.run(cmd_debug_bundle_peer(args))
    if args.url:
        import urllib.error
        import urllib.request

        url = args.url.rstrip("/") + "/rspc/telemetry.debug_bundle"
        req = urllib.request.Request(
            url, data=b"{}", headers={"Content-Type": "application/json"},
            method="POST",
        )
        try:
            with urllib.request.urlopen(req, timeout=30) as resp:
                payload = json.loads(resp.read().decode())
        except (urllib.error.URLError, OSError) as e:
            print(f"debug-bundle: cannot reach {url}: {e}", file=sys.stderr)
            return 1
        doc = json.dumps(payload.get("result"), indent=2)
    else:
        doc = render_bundle(data_dir=args.data_dir)
    _write_or_print(doc, args.out)
    return 0


# --- argument parsing -----------------------------------------------------


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="sdx", description=__doc__)
    p.add_argument("--data-dir", default=DEFAULT_DATA_DIR)
    p.add_argument(
        "--faults", metavar="PLAN", default=None,
        help="arm the fault-injection plane for this invocation "
             "(chaos testing): \"point:mode[:k=v,...][;...]\" — see "
             "docs/robustness.md; SD_FAULTS/SD_FAULT_SEED are the env "
             "equivalents",
    )
    p.add_argument("--fault-seed", type=int, default=0,
                   help="deterministic seed for --faults probabilities")
    sub = p.add_subparsers(dest="cmd", required=True)

    ix = sub.add_parser("index", help="index a directory into a library")
    ix.add_argument("path")
    ix.add_argument("--backend", choices=["tpu", "cpu", "auto"], default="auto")
    ix.add_argument("--library", default="default")
    ix.add_argument("--no-p2p", action="store_true")

    sv = sub.add_parser("serve", help="run the node + HTTP API")
    sv.add_argument("--host", default="127.0.0.1")
    sv.add_argument("--port", type=int, default=8080)
    sv.add_argument("--backend", choices=["tpu", "cpu"], default="tpu")
    sv.add_argument("--auto-accept-pairing", action="store_true",
                    help="headless nodes: accept library joins without a prompt")
    sv.add_argument("--cloud", metavar="ORIGIN",
                    help="enable cloud sync for all libraries against this relay")

    st = sub.add_parser("status", help="node + library status")
    st.add_argument("--no-p2p", action="store_true", default=True)

    lic = sub.add_parser(
        "licenses",
        help="dependency + license inventory (the deps-generator role)",
    )
    lic.add_argument("--out", help="write JSON here instead of stdout")

    br = sub.add_parser("browse", help="ephemeral (non-indexed) listing")
    br.add_argument("path")
    br.add_argument("--hidden", action="store_true")

    du = sub.add_parser("duplicates", help="find duplicate images")
    du.add_argument("--library", default="default")
    du.add_argument("--threshold", type=int, default=8)
    du.add_argument("--no-p2p", action="store_true", default=True)

    se = sub.add_parser("search", help="search an indexed library")
    se.add_argument("query", help="name substring; with --semantic, an "
                    "image path or stored label name")
    se.add_argument("--library", default="default")
    se.add_argument("--semantic", action="store_true",
                    help="vector-index cosine top-k instead of name match")
    se.add_argument("--take", type=int, default=10)
    se.add_argument("--no-p2p", action="store_true", default=True)

    pe = sub.add_parser("peers", help="discover and list mesh peers")
    pe.add_argument("--wait", type=float, default=3.0)

    pa = sub.add_parser("pair", help="join a peer's library")
    pa.add_argument("identity", help="the peer's identity string (sdx peers)")
    pa.add_argument("--library", help="library uuid (default: peer's first)")
    pa.add_argument("--wait", type=float, default=3.0)

    sd = sub.add_parser("spacedrop", help="send files to a peer")
    sd.add_argument("identity")
    sd.add_argument("files", nargs="+")
    sd.add_argument("--wait", type=float, default=3.0)

    cr = sub.add_parser("crypto", help="encrypted-file tools")
    crs = cr.add_subparsers(dest="crypto_cmd", required=True)
    for name in ("inspect", "encrypt", "decrypt"):
        c = crs.add_parser(name)
        c.add_argument("file")
        if name != "inspect":
            c.add_argument("--password")

    lb = sub.add_parser("labeler", help="image-labeler model artifacts")
    lbs = lb.add_subparsers(dest="labeler_cmd", required=True)
    lbs.add_parser("status", help="show the provisioned model artifact")
    lp = lbs.add_parser(
        "provision",
        help="install a pretrained model: download (default) or import a local file",
    )
    lp.add_argument(
        "--from", dest="src",
        help="local .onnx classifier or .npz checkpoint to import "
             "(default: download --url)",
    )
    lp.add_argument(
        "--bundled", action="store_true",
        help="install the in-package offline artifact (trained digits "
             "classifier, sha256-pinned) — works air-gapped",
    )
    lp.add_argument(
        "--url", default=None,
        help="ONNX download URL (default: the official YOLOv8n release asset)",
    )
    lp.add_argument(
        "--sha256", default=None,
        help="pin the download's sha256; mismatch aborts before install",
    )
    lp.add_argument(
        "--classes",
        help="text file of class names, one per line (stored as classes.json)",
    )
    lt = lbs.add_parser("train", help="train a checkpoint on a folder-per-class dataset")
    lt.add_argument("dataset", help="root dir: <root>/<class_name>/*.jpg")
    lt.add_argument("--out", help="checkpoint path (default: <data-dir>/image_labeler/weights.npz)")
    lt.add_argument("--image-size", type=int, default=96)
    lt.add_argument("--batch-size", type=int, default=32)
    lt.add_argument("--steps", type=int, default=600)
    lt.add_argument("--lr", type=float, default=1e-3)
    lt.add_argument("--backend", choices=["tpu", "cpu"], default="tpu")
    ld = lbs.add_parser("train-demo", help="self-contained demo: train on bundled digit scans")
    ld.add_argument("--out")
    ld.add_argument("--steps", type=int, default=300)
    ld.add_argument("--backend", choices=["tpu", "cpu"], default="tpu")

    rl = sub.add_parser(
        "relay", help="run the standalone sync relay + P2P rendezvous"
    )
    rl.add_argument("--host", default="0.0.0.0")
    rl.add_argument("--port", type=int, default=8490)
    rl.add_argument("--p2p-port", type=int, default=8491)
    rl.add_argument("--max-pipes-per-target", type=int, default=8,
                    help="concurrent relayed pipes per listening identity")
    rl.add_argument("--max-pipes", type=int, default=256,
                    help="concurrent relayed pipes across the relay")
    rl.add_argument("--pipe-rate", type=int, default=None, metavar="BYTES_PER_S",
                    help="per-direction byte-rate cap per pipe (default unlimited)")
    rl.add_argument("--stats-interval", type=float, default=60.0,
                    help="seconds between stats log lines (0 disables)")

    sub.add_parser("bench", help="run the headline benchmark")

    te = sub.add_parser(
        "trace-export",
        help="export a running node's span ring as Perfetto-loadable "
             "Chrome-trace JSON",
    )
    te.add_argument("--url", default="http://127.0.0.1:8080",
                    help="the node's HTTP API origin (sdx serve)")
    te.add_argument("--trace-id", default=None,
                    help="filter to one trace id (hex)")
    te.add_argument("--out", help="write JSON here instead of stdout")

    db = sub.add_parser(
        "debug-bundle",
        help="redacted diagnostic bundle: config (secrets stripped), "
             "metrics, spans, flight-recorder rings, versions/env",
    )
    db.add_argument("--url", default=None,
                    help="pull the bundle from a running node instead of "
                         "building offline from --data-dir")
    db.add_argument("--peer", default=None, metavar="IDENTITY",
                    help="pull a MESH PEER's bundle (redacted on the owning "
                         "node before it rides the wire; the peer must have "
                         "remoteRspc enabled)")
    db.add_argument("--wait", type=float, default=3.0,
                    help="discovery settle time before dialing --peer")
    db.add_argument("--out", help="write JSON here instead of stdout")

    at = sub.add_parser(
        "attrib",
        help="critical-path attribution: where the last pass's "
             "wall-clock went (device / host_cpu / link / queue_wait / "
             "unattributed-gap), mesh-wide",
    )
    at.add_argument("trace_id", nargs="?", default=None,
                    help="trace id (hex; default: the last completed pass)")
    at.add_argument("--url", default="http://127.0.0.1:8080",
                    help="the node's HTTP API origin (sdx serve)")
    at.add_argument("--refresh", action="store_true",
                    help="bypass the report cache and re-pull mesh peers")
    at.add_argument("--out", help="write JSON here instead of stdout")

    pf = sub.add_parser(
        "profile",
        help="continuous host profile: collapsed-stack frame groups, "
             "on-CPU vs GIL-wait split, triggered deep captures "
             "(flamegraph.pl text with --folded)",
    )
    pf.add_argument("--url", default="http://127.0.0.1:8080",
                    help="the node's HTTP API origin (sdx serve)")
    pf.add_argument("--peer", default=None, metavar="IDENTITY",
                    help="pull a MESH PEER's profile over the TELEMETRY "
                         "wire (library members only, like trace_pull)")
    pf_fmt = pf.add_mutually_exclusive_group()
    pf_fmt.add_argument("--folded", action="store_true",
                        help="emit flamegraph.pl collapsed-stack text "
                             "instead of the JSON document")
    pf_fmt.add_argument("--mesh", action="store_true",
                        help="with --url: include every reachable peer's "
                             "profile (partial on pull failures)")
    pf.add_argument("--wait", type=float, default=3.0,
                    help="discovery settle time before dialing --peer")
    pf.add_argument("--out", help="write output here instead of stdout")

    so = sub.add_parser(
        "slo",
        help="SLO burn-rate posture: per-objective status over the "
             "persistent telemetry history (multi-window burn rates)",
    )
    so.add_argument("--url", default=None,
                    help="read a running node's rspc telemetry.slo "
                         "instead of evaluating the data dir's history "
                         "offline")
    so.add_argument("--out", help="write JSON here instead of stdout")

    ms = sub.add_parser(
        "mesh-status",
        help="mesh-wide observability: every peer's latest telemetry "
             "snapshot (freshness-marked) + this node's health",
    )
    ms.add_argument("--url", default=None,
                    help="read a running node's GET /mesh instead of booting "
                         "an ephemeral mesh node")
    ms.add_argument("--no-refresh", action="store_true",
                    help="with --url: serve the cached mesh view without "
                         "re-pulling peers")
    ms.add_argument("--wait", type=float, default=3.0,
                    help="discovery settle time (ephemeral-node mode)")
    ms.add_argument("--out", help="write JSON here instead of stdout")

    ss = sub.add_parser(
        "serve-status",
        help="serve-layer posture: admission-gate mode, per-class "
             "inflight/shed counts, read-cache occupancy",
    )
    ss.add_argument("--url", default=None,
                    help="read a running node's rspc telemetry.serve "
                         "instead of booting an ephemeral node")
    ss.add_argument("--out", help="write JSON here instead of stdout")

    tn = sub.add_parser(
        "tenants",
        help="per-tenant accounting: heavy-hitter sketches per surface "
             "(serve/relay/p2p/sync), fairness index, dominant share — "
             "hashed tenant labels, never raw UUIDs",
    )
    tn.add_argument("--url", default=None,
                    help="read a running node's GET /tenants instead of "
                         "booting an ephemeral mesh node")
    tn.add_argument("--peer", default=None, metavar="LABEL",
                    help="show one mesh peer's federated tenant digest "
                         "(peer_label or instance-id prefix)")
    tn.add_argument("--wait", type=float, default=3.0,
                    help="discovery settle time (ephemeral-node mode)")
    tn.add_argument("--out", help="write JSON here instead of stdout")

    dk = sub.add_parser(
        "desktop",
        help="managed desktop host: single instance, browser UI, "
             "deep links, background node (ref:apps/desktop/src-tauri)",
    )
    dk.add_argument("--host", default="127.0.0.1")
    dk.add_argument("--port", type=int, default=0)
    dk.add_argument("--open-path", default=None, metavar="PATH",
                    help="open the explorer on PATH (deep link; targets "
                         "the running instance if one exists)")
    dk.add_argument("--no-open", action="store_true",
                    help="don't launch a browser (headless/CI)")
    dk.add_argument("--quit", action="store_true",
                    help="stop the running instance for this data dir")
    dk.add_argument("--register", action="store_true",
                    help="write the XDG launcher/'Open with' entry and exit")
    return p


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    from .utils import faults as _faults

    if getattr(args, "faults", None):
        _faults.install(
            _faults.FaultPlan.parse(args.faults, seed=args.fault_seed)
        )
    else:
        _faults.install_from_env()
    if args.cmd == "index":
        return asyncio.run(cmd_index(args))
    if args.cmd == "serve":
        return asyncio.run(cmd_serve(args))
    if args.cmd == "relay":
        return asyncio.run(cmd_relay(args))
    if args.cmd == "status":
        return asyncio.run(cmd_status(args))
    if args.cmd == "browse":
        return asyncio.run(cmd_browse(args))
    if args.cmd == "duplicates":
        return asyncio.run(cmd_duplicates(args))
    if args.cmd == "search":
        return asyncio.run(cmd_search(args))
    if args.cmd == "peers":
        return asyncio.run(cmd_peers(args))
    if args.cmd == "pair":
        return asyncio.run(cmd_pair(args))
    if args.cmd == "spacedrop":
        return asyncio.run(cmd_spacedrop(args))
    if args.cmd == "crypto":
        return cmd_crypto(args)
    if args.cmd == "labeler":
        return cmd_labeler(args)
    if args.cmd == "bench":
        return cmd_bench(args)
    if args.cmd == "trace-export":
        return cmd_trace_export(args)
    if args.cmd == "attrib":
        return cmd_attrib(args)
    if args.cmd == "profile":
        return cmd_profile(args)
    if args.cmd == "slo":
        return cmd_slo(args)
    if args.cmd == "debug-bundle":
        return cmd_debug_bundle(args)
    if args.cmd == "mesh-status":
        return asyncio.run(cmd_mesh_status(args))
    if args.cmd == "serve-status":
        return asyncio.run(cmd_serve_status(args))
    if args.cmd == "tenants":
        return asyncio.run(cmd_tenants(args))
    if args.cmd == "desktop":
        from . import desktop

        if args.register:
            path = desktop.register_xdg()
            print(f"registered {path}")
            return 0
        return asyncio.run(desktop.run_or_forward(
            args.data_dir, open_path=args.open_path,
            quit_running=args.quit, host=args.host, port=args.port,
            open_browser=not args.no_open,
        ))
    if args.cmd == "licenses":
        from .utils.deps import collect

        doc = json.dumps(collect(), indent=2)
        if args.out:
            with open(args.out, "w") as f:
                f.write(doc + "\n")
        else:
            print(doc)
        return 0
    return 2


if __name__ == "__main__":
    sys.exit(main())
