"""Read-path cache — bounded LRU + single-flight + brownout SWR.

Three behaviors, one structure:

- **bounded LRU**: entries are evicted oldest-used first when the
  entry-count or weight budget (thumbnail bytes) is exceeded — a
  traffic burst can grow the cache to its budget and no further;
- **single-flight**: concurrent loads of one key coalesce onto one
  loader call — a stampede of 100 explorer tabs on one hot directory
  issues ONE SQLite query, everyone awaits the same future;
- **stale-while-revalidate brownout**: when the admission gate reports
  brownout, an expired entry is served anyway (stamped ``stale``) while
  a single-flight refresh runs behind it — under overload a slightly
  old listing beats a shed.

Invalidation is tag-based: every entry carries tags like
``("lib", <library-uuid>)`` and ``("q", <query-key>, <library-uuid>)``;
local mutations (``api.invalidate.invalidate_query``) and sync-applied
ingest batches (``sync.ingest`` → ``p2p.manager`` wiring) drop the
affected tags. Counted on ``sd_serve_cache_*``.

Asyncio-confined: get/invalidate run on the node's event loop (the only
place the serve surface executes); no internal locking.
"""

from __future__ import annotations

import asyncio
import logging
import time
from collections import OrderedDict
from typing import Any, Awaitable, Callable, NamedTuple

from ..telemetry import metrics as _tm
from ..telemetry import tenants as _tenants
from ..utils.tasks import supervise

logger = logging.getLogger(__name__)

#: cache read outcomes (the ``result`` label on sd_serve_cache_ops_total)
HIT, MISS, STALE, COALESCED, BYPASS = (
    "hit", "miss", "stale", "coalesced", "bypass",
)

Key = tuple
Tag = tuple


class CacheResult(NamedTuple):
    value: Any
    state: str  # hit | miss | stale | coalesced | bypass
    age_s: float


class _Entry:
    __slots__ = ("value", "stored_at", "ttl_s", "tags", "weight")

    def __init__(self, value: Any, ttl_s: float, tags: tuple[Tag, ...],
                 weight: int):
        self.value = value
        self.stored_at = time.monotonic()
        self.ttl_s = ttl_s
        self.tags = tags
        self.weight = weight


class ReadCache:
    """One bounded cache region (queries, thumbnail bytes, meta views)."""

    def __init__(
        self,
        name: str,
        *,
        max_entries: int = 1024,
        max_weight: int | None = None,
        default_ttl_s: float = 5.0,
        stale_max_s: float = 120.0,
    ):
        self.name = name
        self.max_entries = max_entries
        self.max_weight = max_weight
        self.default_ttl_s = default_ttl_s
        self.stale_max_s = stale_max_s
        self._entries: "OrderedDict[Key, _Entry]" = OrderedDict()
        self._tags: dict[Tag, set[Key]] = {}
        self._inflight: dict[Key, "asyncio.Future[Any]"] = {}
        self._refreshes: set[asyncio.Task] = set()
        self._weight = 0
        # invalidation epoch: a load that STARTED before an invalidation
        # must not store its (pre-mutation) result after it — the
        # awaiting callers still get the value, but the next read loads
        # fresh (read-your-writes survives the load/invalidate race)
        self._epoch = 0

    # --- read -----------------------------------------------------------

    async def get(
        self,
        key: Key,
        loader: Callable[[], Awaitable[Any]],
        *,
        ttl_s: float | None = None,
        tags: tuple[Tag, ...] = (),
        stale_ok: bool = False,
        weigh: Callable[[Any], int] | None = None,
        tenant: Any = None,
    ) -> CacheResult:
        """Cached value for ``key``, loading (single-flight) on miss.

        ``ttl_s=0`` stores nothing: pure request coalescing — N
        concurrent callers cost one loader run, and the next caller
        after completion loads fresh (the /mesh refresh shape).
        ``stale_ok`` (brownout) serves an expired entry while a
        background single-flight refresh replaces it. ``tenant`` (the
        owning library id, when the caller has one) feeds the
        per-tenant cache hit/miss sketches — hashed on entry, never
        stored here.
        """
        ttl = self.default_ttl_s if ttl_s is None else ttl_s
        entry = self._entries.get(key)
        now = time.monotonic()
        if entry is not None:
            age = now - entry.stored_at
            if age < entry.ttl_s:
                self._entries.move_to_end(key)
                _tm.SERVE_CACHE_OPS.inc(
                    cache="query" if self.name == "query"
                    else "thumb" if self.name == "thumb" else "meta",
                    result="hit")
                _tenants.observe("cache_hit", tenant)
                return CacheResult(entry.value, HIT, age)
            if stale_ok and age - entry.ttl_s < self.stale_max_s:
                # brownout: answer stale NOW, refresh behind the response
                self._refresh_in_background(key, loader, ttl, tags, weigh)
                _tm.SERVE_CACHE_OPS.inc(
                    cache="query" if self.name == "query"
                    else "thumb" if self.name == "thumb" else "meta",
                    result="stale")
                _tenants.observe("cache_hit", tenant)
                return CacheResult(entry.value, STALE, age)
            self._evict_key(key)
        fut = self._inflight.get(key)
        if fut is not None:
            _tm.SERVE_CACHE_OPS.inc(
                    cache="query" if self.name == "query"
                    else "thumb" if self.name == "thumb" else "meta",
                    result="coalesced")
            _tenants.observe("cache_hit", tenant)
            value = await asyncio.shield(fut)
            return CacheResult(value, COALESCED, 0.0)
        value = await self._load(key, loader, ttl, tags, weigh)
        _tm.SERVE_CACHE_OPS.inc(
                    cache="query" if self.name == "query"
                    else "thumb" if self.name == "thumb" else "meta",
                    result="miss")
        _tenants.observe("cache_miss", tenant)
        return CacheResult(value, MISS, 0.0)

    def get_sync(
        self,
        key: Key,
        loader: Callable[[], Any],
        *,
        ttl_s: float | None = None,
        tags: tuple[Tag, ...] = (),
    ) -> Any:
        """Synchronous TTL read-through for sync callers (the federation
        responder's local_snapshot). No single-flight — the loop cannot
        interleave a sync loader — but repeated polls inside the TTL
        window still cost one computation."""
        ttl = self.default_ttl_s if ttl_s is None else ttl_s
        entry = self._entries.get(key)
        if entry is not None:
            if time.monotonic() - entry.stored_at < entry.ttl_s:
                self._entries.move_to_end(key)
                _tm.SERVE_CACHE_OPS.inc(
                    cache="query" if self.name == "query"
                    else "thumb" if self.name == "thumb" else "meta",
                    result="hit")
                return entry.value
            self._evict_key(key)
        value = loader()
        if ttl > 0:
            self._store(key, value, ttl, tags, weight=1)
        _tm.SERVE_CACHE_OPS.inc(
                    cache="query" if self.name == "query"
                    else "thumb" if self.name == "thumb" else "meta",
                    result="miss")
        return value

    async def _load(
        self, key: Key, loader, ttl: float, tags, weigh,
    ) -> Any:
        fut: "asyncio.Future[Any]" = asyncio.get_running_loop().create_future()
        self._inflight[key] = fut
        epoch = self._epoch
        try:
            value = await loader()
        except BaseException as e:
            if not fut.done():
                fut.set_exception(e)
                # awaiting coalesced callers re-raise; nothing retained
                fut.exception()
            raise
        else:
            if not fut.done():
                fut.set_result(value)
            if ttl > 0 and epoch == self._epoch:
                # an invalidation fired mid-load ⇒ this value may be a
                # pre-mutation read: hand it to the waiters, store nothing
                weight = weigh(value) if weigh is not None else 1
                self._store(key, value, ttl, tags, weight)
            return value
        finally:
            self._inflight.pop(key, None)

    def _refresh_in_background(self, key, loader, ttl, tags, weigh) -> None:
        if key in self._inflight:
            return  # a refresh is already running; everyone rides it

        async def refresh() -> None:
            try:
                await self._load(key, loader, ttl, tags, weigh)
            except Exception as e:  # noqa: BLE001 - the stale answer already went out
                # expected under sustained brownout (the refresh load can
                # itself be shed); the NEXT stale read retries
                logger.debug("stale-refresh of %r failed: %r", key, e)

        task = asyncio.ensure_future(refresh())
        supervise(task, self._refreshes, logger,
                  f"serve-cache refresh ({self.name})")

    # --- write / evict --------------------------------------------------

    def _store(self, key: Key, value: Any, ttl: float,
               tags: tuple[Tag, ...], weight: int) -> None:
        self._evict_key(key)
        self._entries[key] = _Entry(value, ttl, tuple(tags), weight)
        self._weight += weight
        for tag in tags:
            self._tags.setdefault(tag, set()).add(key)
        while len(self._entries) > self.max_entries or (
            self.max_weight is not None and self._weight > self.max_weight
            and len(self._entries) > 1
        ):
            old_key, _ = next(iter(self._entries.items()))
            self._evict_key(old_key)
        _tm.SERVE_CACHE_ENTRIES.set(
            len(self._entries),
            cache="query" if self.name == "query"
            else "thumb" if self.name == "thumb" else "meta")

    def _evict_key(self, key: Key) -> None:
        entry = self._entries.pop(key, None)
        if entry is None:
            return
        self._weight -= entry.weight
        for tag in entry.tags:
            keys = self._tags.get(tag)
            if keys is not None:
                keys.discard(key)
                if not keys:
                    del self._tags[tag]
        _tm.SERVE_CACHE_ENTRIES.set(
            len(self._entries),
            cache="query" if self.name == "query"
            else "thumb" if self.name == "thumb" else "meta")

    def invalidate_tag(self, tag: Tag, source: str = "local") -> int:
        """Drop every entry carrying ``tag``; returns the count. Bumps
        the epoch even when nothing is stored yet — an IN-FLIGHT load
        for the tag is exactly as stale as a stored entry."""
        self._epoch += 1
        keys = self._tags.get(tag)
        if not keys:
            return 0
        n = 0
        for key in list(keys):
            self._evict_key(key)
            n += 1
        if n:
            _tm.SERVE_CACHE_INVALIDATIONS.inc(
                n, source="sync" if source == "sync" else "local")
        return n

    def invalidate_key(self, key: Key, source: str = "local") -> None:
        self._epoch += 1
        if key in self._entries:
            self._evict_key(key)
            _tm.SERVE_CACHE_INVALIDATIONS.inc(
                source="sync" if source == "sync" else "local")

    def clear(self) -> None:
        self._epoch += 1
        self._entries.clear()
        self._tags.clear()
        self._weight = 0
        _tm.SERVE_CACHE_ENTRIES.set(
            0,
            cache="query" if self.name == "query"
            else "thumb" if self.name == "thumb" else "meta")

    # --- introspection --------------------------------------------------

    def snapshot(self) -> dict[str, Any]:
        return {
            "entries": len(self._entries),
            "weight": self._weight,
            "max_entries": self.max_entries,
            "max_weight": self.max_weight,
            "inflight_loads": len(self._inflight),
        }

    def __len__(self) -> int:
        return len(self._entries)
