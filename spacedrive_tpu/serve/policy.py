"""ServePolicy — the serve layer's single tuning seam.

Every knob the admission gate, the read-path cache, and write-combined
sync ingest consume lives here, the same way ``parallel.autotune``'s
``PipelinePolicy`` owns the pipeline sizing constants: one policy
object, read live at each decision point, so the PR 8 controller can
later close the loop on serving capacity (shrink interactive budgets
under loop lag, widen them when the node idles) without touching a
consumer.

Priority classes (ordered, highest first — the overload contract from
docs/robustness.md "Serving under overload"):

- ``control`` — health probes, metrics scrapes, diagnostics. Never
  queued, never shed: a load balancer must always learn the truth.
- ``sync`` — replication and P2P serving legs (SYNC/SYNC_REQUEST /
  TELEMETRY / WORK responders, federation). Never shed: a node that
  stops replicating under read pressure diverges exactly when its
  peers most need to offload it.
- ``interactive`` — explorer reads: rspc queries/mutations, thumbnail
  fetches, file serving, search. Queued with a deadline, then shed.
- ``background`` — trace exports, debug bundles, backups, model
  listings. First to shed; in brownout they shed immediately.

``SD_SERVE_GATE=0`` disables the whole serve layer (gate AND caches):
every request takes exactly the pre-serve code path, golden-tested in
tests/test_serve.py.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field

#: the priority-class vocabulary (also the metric label values)
CONTROL = "control"
SYNC = "sync"
INTERACTIVE = "interactive"
BACKGROUND = "background"

CLASSES = (CONTROL, SYNC, INTERACTIVE, BACKGROUND)


def enabled() -> bool:
    """The serve layer's master switch (``SD_SERVE_GATE=0`` = off)."""
    return os.environ.get("SD_SERVE_GATE", "1") != "0"


@dataclass
class ClassBudget:
    """One priority class's admission budget.

    ``sheddable=False`` classes (control, sync) are always admitted
    immediately — their budgets exist for observability (the inflight
    gauge), not enforcement. Sheddable classes run up to
    ``max_inflight`` concurrently, park up to ``max_queue`` waiters for
    at most ``queue_deadline_s`` each, and fast-fail everything else.
    """

    max_inflight: int
    max_queue: int = 0
    queue_deadline_s: float = 0.0
    sheddable: bool = True


@dataclass
class ServePolicy:
    """All serve-layer knobs; defaults sized for one node on a small
    host (the budgets bound *concurrency*, not rate — SQLite serializes
    internally, so a handful of in-flight reads already saturates it)."""

    # Interactive sizing rationale: per-library SQLite serializes
    # writes and the GIL serializes the Python row work, so in-flight
    # beyond the host's core count buys zero throughput — concurrent
    # heavy reads only convoy behind each other, multiplying every
    # admitted request's service time. The budget follows the cores
    # (floor 2 so one slow read can never starve the class, cap 8);
    # the queue is deliberately SHORT in time terms (max_queue ×
    # per-read service) because every queued entry adds its full
    # service time to the admitted p99 — the bench bar is "admitted
    # p99 ≤ 5× unloaded p99", not "accept everything".
    budgets: dict[str, ClassBudget] = field(default_factory=lambda: {
        CONTROL: ClassBudget(max_inflight=64, sheddable=False),
        SYNC: ClassBudget(max_inflight=32, sheddable=False),
        INTERACTIVE: ClassBudget(
            max_inflight=max(2, min(8, os.cpu_count() or 4)),
            max_queue=8, queue_deadline_s=0.1,
        ),
        BACKGROUND: ClassBudget(
            max_inflight=2, max_queue=4, queue_deadline_s=0.25,
        ),
    })

    #: advisory deadline installed (utils.resilience.deadline_scope)
    #: around each admitted sheddable request, so downstream awaits are
    #: clipped instead of holding a slot forever
    request_deadline_s: float = 30.0

    #: Retry-After seconds advertised on shed responses
    retry_after_s: float = 1.0

    # --- brownout (degraded serving) -----------------------------------
    #: event-loop lag that flips the gate into brownout (matches the
    #: health model's LOOP_LAG_DEGRADED)
    brownout_loop_lag_s: float = 0.2
    #: brownout persists this long past the last shed / lag spike
    #: (hysteresis: the mode must not flap per request; in brownout a
    #: full sheddable budget fast-fails instead of queueing)
    brownout_hold_s: float = 5.0

    # --- read-path cache ------------------------------------------------
    #: explorer-query cache entries (each one normalised result page)
    query_cache_entries: int = 2048
    #: freshness TTL for cached query results; invalidation (local
    #: mutations + sync-applied batches) is the primary correctness
    #: mechanism — the TTL only bounds staleness against writes that
    #: bypass the invalidation plane entirely
    query_ttl_s: float = 5.0
    #: how far past TTL a stale entry may be served in brownout
    stale_serve_max_s: float = 120.0
    #: thumbnail byte-cache budget (content-addressed entries — a webp
    #: for a cas_id never changes, so eviction is the only invalidation)
    thumb_cache_bytes: int = 32 * 1024 * 1024
    #: /mesh view + local-snapshot micro-TTLs: N concurrent dashboards
    #: cost one computation per window (single-flight collapses the rest)
    mesh_ttl_s: float = 2.0
    snapshot_ttl_s: float = 1.0

    # --- write-combined sync ingest --------------------------------------
    #: remote ops coalesced into one SQLite transaction (also the ingest
    #: actor's yield quantum, replacing the old fixed 64)
    sync_txn_ops: int = 64


#: the process default; tests swap it via `serve.gate.AdmissionGate(policy=…)`
#: or by mutating fields (dataclass, live-read at each decision point)
POLICY = ServePolicy()


def policy() -> ServePolicy:
    return POLICY


# --- the rspc priority map (sdlint SD015's coverage source) ---------------
#
# Every rspc namespace (the key prefix before the first ".", or the full
# key for root procedures) must appear here, or the registration site
# must pass an explicit ``priority=`` — sdlint SD015 `ungated-handler`
# enforces that NEW procedures cannot silently bypass the gate seam.
NAMESPACE_CLASSES: dict[str, str] = {
    # root procedures
    "buildInfo": "control",
    "nodeState": "control",
    "toggleFeatureFlag": "interactive",
    # interactive explorer surface
    "library": "interactive",
    "locations": "interactive",
    "files": "interactive",
    "ephemeralFiles": "interactive",
    "jobs": "interactive",
    "search": "interactive",
    "tags": "interactive",
    "spaces": "interactive",
    "albums": "interactive",
    "labels": "interactive",
    "volumes": "interactive",
    "keys": "interactive",
    "preferences": "interactive",
    "notifications": "interactive",
    "nodes": "interactive",
    "invalidation": "interactive",
    # replication / mesh planes
    "sync": "sync",
    "p2p": "sync",
    "cloud": "sync",
    # diagnostics (the health/metrics read path). Only the CHEAP
    # answers ride control: the heavyweight members (mesh federation
    # refresh, trace export, debug bundle) carry explicit priority=
    # overrides at their registration — control is unsheddable, so
    # anything expensive under it is an overload hole
    "telemetry": "control",
    # heavyweight maintenance
    "backups": "background",
    "auth": "background",
    "models": "background",
}


def class_for_key(key: str, explicit: str | None = None) -> str:
    """Priority class for an rspc procedure key: the registration's
    explicit class wins, else the namespace map, else interactive."""
    if explicit is not None:
        return explicit
    ns = key.split(".", 1)[0] if "." in key else key
    return NAMESPACE_CLASSES.get(ns, INTERACTIVE)


#: query keys the read-path cache may serve (library-scoped reads whose
#: results are invalidated by the mutation plane AND sync-applied ops).
#: Deliberately an allowlist: a query must be read-only, normalised,
#: and a pure function of DB state to be cacheable — everything else
#: always hits SQLite. (`locations.list` is NOT here: it stamps live
#: per-row path reachability (`online`), which no DB mutation — and
#: therefore no invalidation — tracks; caching it freezes the sidebar
#: dot for a TTL after a volume unmounts.)
CACHEABLE_QUERIES = frozenset({
    "search.paths",
    "search.objects",
    "search.semantic",
    "tags.list",
    "labels.list",
    "library.statistics",
    "library.kindStatistics",
})
