"""The serve layer — overload-safe read path for one node.

Three cooperating parts (ROADMAP open item 5; docs/robustness.md
"Serving under overload"):

- :mod:`spacedrive_tpu.serve.gate` — per-priority-class admission with
  queue-then-shed and brownout detection;
- :mod:`spacedrive_tpu.serve.cache` — bounded LRU + single-flight +
  stale-while-revalidate for explorer queries, thumbnail bytes, and the
  /mesh//snapshot meta views;
- write-combined sync ingest (:mod:`spacedrive_tpu.sync.ingest`) reads
  its transaction quantum from :mod:`spacedrive_tpu.serve.policy`.

:class:`ServeRuntime` bundles the per-node state; ``Node`` constructs
one when ``SD_SERVE_GATE`` is not ``0`` and exposes it as
``node.serve`` — every consumer treats a missing/None runtime as "the
ungated pre-serve path".
"""

from __future__ import annotations

import uuid
from typing import Any

from .cache import ReadCache
from .gate import AdmissionGate, Shed
from .policy import (
    BACKGROUND,
    CACHEABLE_QUERIES,
    CLASSES,
    CONTROL,
    INTERACTIVE,
    SYNC,
    ServePolicy,
    class_for_key,
    enabled,
    policy,
)

__all__ = [
    "AdmissionGate", "ReadCache", "ServeRuntime", "Shed",
    "CONTROL", "SYNC", "INTERACTIVE", "BACKGROUND", "CLASSES",
    "CACHEABLE_QUERIES", "ServePolicy", "canonical_library_id",
    "class_for_key", "enabled", "policy", "runtime_for",
]


class ServeRuntime:
    """One node's serve-layer state: the admission gate plus the three
    cache regions (explorer queries, thumbnail bytes, meta views)."""

    def __init__(self, policy_obj: ServePolicy | None = None):
        self._policy = policy_obj
        pol = policy_obj if policy_obj is not None else policy()
        self.gate = AdmissionGate(policy_obj)
        self.queries = ReadCache(
            "query",
            max_entries=pol.query_cache_entries,
            default_ttl_s=pol.query_ttl_s,
            stale_max_s=pol.stale_serve_max_s,
        )
        self.thumbs = ReadCache(
            "thumb",
            max_entries=65536,
            max_weight=pol.thumb_cache_bytes,
            # content-addressed: a cas_id's webp never changes, so the
            # TTL is effectively "until evicted"
            default_ttl_s=86400.0,
            stale_max_s=86400.0,
        )
        self.meta = ReadCache(
            "meta", max_entries=64,
            default_ttl_s=pol.mesh_ttl_s,
            stale_max_s=pol.stale_serve_max_s,
        )

    @property
    def policy(self) -> ServePolicy:
        return self._policy if self._policy is not None else policy()

    # --- invalidation entry points --------------------------------------

    def invalidate_library(self, library_id: Any, source: str = "local") -> int:
        """Every cached read for one library is void — fired by
        sync-applied ingest batches (coarse: remote ops don't say which
        queries they dirty) and by local mutations' invalidate_query."""
        return self.queries.invalidate_tag(
            ("lib", canonical_library_id(library_id)), source=source
        )

    def invalidate_query(self, key: str, library_id: Any = None,
                         source: str = "local") -> int:
        """Local mutation invalidation. The mutation plane names exact
        query keys, but a handler that dirtied ``search.paths`` almost
        always dirtied ``locations.list`` too — read-your-writes beats
        cache retention, so the whole library tag drops. A NODE-scoped
        mutation (library create/delete, config) clears the query cache
        outright: entries carry only library tags, node mutations are
        rare, and a tag nothing ever carries would be a silent no-op."""
        if library_id is not None:
            return self.invalidate_library(library_id, source=source)
        n = len(self.queries)
        self.queries.clear()
        return n

    def snapshot(self) -> dict[str, Any]:
        return {
            "gate": self.gate.snapshot(),
            "caches": {
                "query": self.queries.snapshot(),
                "thumb": self.thumbs.snapshot(),
                "meta": self.meta.snapshot(),
            },
        }


def runtime_for(node: Any) -> ServeRuntime | None:
    """The node's serve runtime, or None when absent/disabled — every
    call site treats None as 'take the ungated pre-serve path'."""
    if not enabled():
        return None
    return getattr(node, "serve", None)


def canonical_library_id(library_id: Any) -> str:
    """One spelling per library for cache keys AND invalidation tags.
    ``_resolve_library`` accepts any ``uuid.UUID()``-parsable form
    (uppercase, undashed, urn:), but invalidation fires with the
    canonical ``str(library.id)`` — without normalizing here, a
    non-canonical client spelling would mint cache entries that
    read-your-writes invalidation can never drop."""
    try:
        return str(uuid.UUID(str(library_id)))
    except (ValueError, AttributeError, TypeError):
        return str(library_id)


def query_cache_key(key: str, library_id: Any, arg: Any) -> tuple:
    """Deterministic cache key for one rspc query execution."""
    import json

    return (
        key,
        canonical_library_id(library_id),
        json.dumps(arg, sort_keys=True, default=str) if arg is not None else "",
    )
