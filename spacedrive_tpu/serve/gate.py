"""Admission gate — per-priority-class budgets with queue-then-shed.

The overload failure mode this closes: every read (explorer listing,
thumbnail fetch, search, /mesh poll) used to go straight at per-library
SQLite on the shared event loop, so a traffic burst or a slow disk
queued unbounded work, the loop-lag monitor went red, and the node
stopped answering *everything* — including the health probe that would
have told a balancer to route around it, and the sync legs that keep
replicas converging.

The gate puts a budget in front of each priority class
(:mod:`spacedrive_tpu.serve.policy`): control and sync always admit
(counted, never blocked); interactive and background requests run up to
their in-flight budget, park in a bounded FIFO with a deadline when the
budget is full, and **shed fast-fail** (:class:`Shed` → HTTP 429 +
``Retry-After``) beyond that. Every shed lands on the ``serve`` flight
ring with the active trace id and bumps ``sd_gate_requests_total``.

Brownout: when the event-loop-lag gauge (the existing health signal)
crosses the degraded threshold, or sheds/queue-saturation happened
within the hold window, :meth:`AdmissionGate.in_brownout` reports True
— background requests shed immediately, queue deadlines shrink, and the
read cache serves stale entries instead of shedding
(:mod:`spacedrive_tpu.serve.cache`). Gate state rides
``telemetry.health`` → federation snapshots → ``GET /mesh``.

``SD_SERVE_GATE=0``: :meth:`admit` yields immediately with zero
bookkeeping — the ungated path, golden-tested identical to pre-serve
behavior.
"""

from __future__ import annotations

import asyncio
import collections
import contextlib
import time
from typing import Any, AsyncIterator

from ..telemetry import metrics as _tm
from ..telemetry import tenants as _tenants
from ..telemetry.events import SERVE_EVENTS
from ..telemetry.snapshot import gauge_value
from . import policy as _policy
from .policy import BACKGROUND, CLASSES, CONTROL, INTERACTIVE, SYNC, ServePolicy

NORMAL = "normal"
BROWNOUT = "brownout"


def observe_request_seconds(klass: str, seconds: float,
                            tenant: Any = None) -> None:
    """Admitted-request wall time per priority class — the ONE record
    site both serve surfaces share (the HTTP admission middleware and
    the rspc Router.exec leg), so the `interactive_p99` SLO input
    covers rspc traffic, not just raw HTTP routes. The conditional maps
    onto the class-constant vocabulary (an unknown string — which the
    gate itself degrades to background — records as background too).
    ``tenant`` (the request's library id, when the surface knows one)
    rides the same call into the per-tenant serve sketch
    (telemetry/tenants.py) so request latency and volume attribute to
    the library that caused them."""
    _tm.SERVE_REQUEST_SECONDS.observe(
        seconds,
        klass="control" if klass == CONTROL
        else "sync" if klass == SYNC
        else "interactive" if klass == INTERACTIVE
        else "background",
    )
    _tenants.observe("serve", tenant, seconds=seconds)


class Shed(Exception):
    """Admission refused — answer 429/``SHED`` with Retry-After and move
    on; the caller must NOT fall back to doing the work anyway."""

    def __init__(self, klass: str, retry_after_s: float, reason: str):
        super().__init__(f"shed {klass} request: {reason}")
        self.klass = klass
        self.retry_after_s = retry_after_s
        self.reason = reason


class _Waiter:
    __slots__ = ("future", "enqueued_at")

    def __init__(self, future: "asyncio.Future[None]") -> None:
        self.future = future
        self.enqueued_at = time.monotonic()


class AdmissionGate:
    """Per-class admission control over one node's serve surface."""

    def __init__(self, policy: ServePolicy | None = None):
        self._policy = policy
        self.inflight: dict[str, int] = {c: 0 for c in CLASSES}
        self._queues: dict[str, collections.deque[_Waiter]] = {
            c: collections.deque() for c in CLASSES
        }
        self.admitted: dict[str, int] = {c: 0 for c in CLASSES}
        self.shed: dict[str, int] = {c: 0 for c in CLASSES}
        self._mode = NORMAL
        self._brownout_until = 0.0

    @property
    def policy(self) -> ServePolicy:
        return self._policy if self._policy is not None else _policy.policy()

    # --- mode -----------------------------------------------------------

    def in_brownout(self) -> bool:
        return self._refresh_mode() == BROWNOUT

    def _refresh_mode(self) -> str:
        pol = self.policy
        now = time.monotonic()
        lag = gauge_value("sd_event_loop_lag_seconds")
        saturated = False
        for klass, budget in pol.budgets.items():
            if not budget.sheddable:
                continue
            if (
                self.inflight.get(klass, 0) >= budget.max_inflight
                and len(self._queues[klass]) >= max(1, budget.max_queue // 2)
            ):
                saturated = True
                break
        if lag >= pol.brownout_loop_lag_s or saturated:
            self._brownout_until = now + pol.brownout_hold_s
        mode = BROWNOUT if now < self._brownout_until else NORMAL
        if mode != self._mode:
            self._mode = mode
            _tm.GATE_MODE.set(1.0 if mode == BROWNOUT else 0.0)
            SERVE_EVENTS.emit(
                "mode", mode=mode, loop_lag_s=round(lag, 4),
                saturated=saturated,
            )
            if mode == BROWNOUT:
                # brownout ENTRY opens a host-profiler deep capture:
                # the overload incident's flight record gains the
                # frames that were burning the loop (hysteresis in the
                # sampler keeps a flapping gate to one window)
                from ..telemetry import sampler as _sampler

                _sampler.trigger("brownout")
        return mode

    def _note_shed(self) -> None:
        """A shed is itself overload evidence: extend the brownout hold
        so the cache keeps serving stale instead of thrashing."""
        self._brownout_until = time.monotonic() + self.policy.brownout_hold_s

    # --- admission ------------------------------------------------------

    @contextlib.asynccontextmanager
    async def admit(self, klass: str, key: str = "") -> AsyncIterator[None]:
        """Hold one slot of ``klass``'s budget for the block. Raises
        :class:`Shed` instead of entering when the class is saturated
        past its queue. No-op when the serve layer is disabled."""
        if not _policy.enabled():
            yield
            return
        from ..utils.resilience import deadline_scope

        pol = self.policy
        budget = pol.budgets.get(klass)
        if budget is None or klass not in self.inflight:
            # a mistyped priority= (class_for_key returns it verbatim)
            # degrades to background gating — never a KeyError 500
            klass = BACKGROUND
            budget = pol.budgets[BACKGROUND]
        mode = self._refresh_mode()
        queue_wait_s = None
        if budget.sheddable and self.inflight[klass] >= budget.max_inflight:
            queue_wait_s = await self._queue_for_slot(klass, budget, mode, key)
        else:
            self.inflight[klass] += 1
        # from here the slot is HELD (counted here or reserved for us by
        # the releasing request's _grant_next) — every statement that can
        # raise, the admission bookkeeping included, lives inside the
        # try so the finally always gives the slot back; a metric-
        # registry error between acquire and try used to permanently
        # shrink the class budget (sdlint SD016)
        try:
            self.admitted[klass] += 1
            # bounded-IfExp labels: the class vocabulary is fixed
            # (CLASSES), spelled out so sdlint SD007 can prove it at
            # the call site
            _tm.GATE_REQUESTS.inc(
                klass="control" if klass == "control"
                else "sync" if klass == "sync"
                else "background" if klass == "background"
                else "interactive",
                outcome="admitted")
            _tm.GATE_INFLIGHT.set(
                self.inflight[klass],
                klass="control" if klass == "control"
                else "sync" if klass == "sync"
                else "background" if klass == "background"
                else "interactive")
            if queue_wait_s is not None:
                # observed HERE, with the slot protected by the finally
                # — inside _queue_for_slot a failing observe would leak
                # the just-granted slot
                _tm.GATE_QUEUE_SECONDS.observe(
                    queue_wait_s,
                    klass="control" if klass == "control"
                    else "sync" if klass == "sync"
                    else "background" if klass == "background"
                    else "interactive",
                )
            if budget.sheddable and pol.request_deadline_s:
                with deadline_scope(pol.request_deadline_s):
                    yield
            else:
                yield
        finally:
            self.inflight[klass] -= 1
            self._grant_next(klass, budget)
            _tm.GATE_INFLIGHT.set(
                self.inflight[klass],
                klass="control" if klass == "control"
                else "sync" if klass == "sync"
                else "background" if klass == "background"
                else "interactive")

    async def _queue_for_slot(
        self, klass: str, budget: Any, mode: str, key: str
    ) -> float:
        """Park until a slot frees or the queue deadline passes. On
        success the releasing request has already transferred its slot
        (inflight stays reserved for us); returns the queue wait in
        seconds — recorded by the CALLER inside its try/finally, so a
        failing metric write cannot leak the granted slot."""
        queue = self._queues[klass]
        deadline = budget.queue_deadline_s
        if mode == BROWNOUT:
            # saturated (the event-loop-lag / in-flight signals said so):
            # stop queueing and fast-fail — parking more work behind a
            # full budget only converts future sheds into slow sheds,
            # and the admitted stream must keep its latency bound
            self._shed(klass, key, "brownout fast-fail")
        if len(queue) >= budget.max_queue or deadline <= 0:
            self._shed(klass, key, "queue full")
        waiter = _Waiter(asyncio.get_running_loop().create_future())
        queue.append(waiter)
        try:
            # the queued-outcome metric rides INSIDE the try: from the
            # append on, an exception anywhere here must unregister the
            # waiter (or pass a granted slot on) — an orphan waiter
            # would absorb the next _grant_next and shrink the budget
            _tm.GATE_REQUESTS.inc(
                klass="control" if klass == "control"
                else "sync" if klass == "sync"
                else "background" if klass == "background"
                else "interactive",
                outcome="queued")
            await asyncio.wait_for(
                asyncio.shield(waiter.future), timeout=deadline
            )
        except asyncio.CancelledError:
            # the REQUEST died while parked (client disconnect, task
            # teardown): the slot must not die with it
            if waiter.future.done() and not waiter.future.cancelled():
                # granted in the same tick we were cancelled — the
                # releasing request already reserved inflight for us;
                # hand the slot straight to the next waiter
                self.inflight[klass] -= 1
                self._grant_next(klass, budget)
            else:
                waiter.future.cancel()
                with contextlib.suppress(ValueError):
                    queue.remove(waiter)
            raise
        except asyncio.TimeoutError:
            if waiter.future.done():
                # the slot was granted in the same tick the timer fired:
                # it is ours — proceed admitted
                pass
            else:
                waiter.future.cancel()
                with contextlib.suppress(ValueError):
                    queue.remove(waiter)
                self._shed(
                    klass, key,
                    f"queue deadline {deadline:.2f}s exceeded",
                    queue_wait_s=time.monotonic() - waiter.enqueued_at,
                )
        except BaseException:
            # anything else (a raising metric registry, a broken loop):
            # same discipline as cancellation — never leave an orphan
            # waiter behind for _grant_next to hand a slot to
            if waiter.future.done() and not waiter.future.cancelled():
                self.inflight[klass] -= 1
                self._grant_next(klass, budget)
            else:
                waiter.future.cancel()
                with contextlib.suppress(ValueError):
                    queue.remove(waiter)
            raise
        return time.monotonic() - waiter.enqueued_at

    def _grant_next(self, klass: str, budget: Any) -> None:
        """Slot handoff on release: wake the oldest live waiter and
        reserve the slot for it (so a burst can never overshoot the
        budget between release and wakeup)."""
        queue = self._queues[klass]
        while queue and self.inflight[klass] < budget.max_inflight:
            waiter = queue.popleft()
            if waiter.future.done():
                continue  # timed out / cancelled while queued
            self.inflight[klass] += 1
            waiter.future.set_result(None)
            break

    def _shed(self, klass: str, key: str, reason: str,
              queue_wait_s: float = 0.0) -> None:
        self.shed[klass] += 1
        self._note_shed()
        _tm.GATE_REQUESTS.inc(
            klass="control" if klass == "control"
            else "sync" if klass == "sync"
            else "background" if klass == "background"
            else "interactive",
            outcome="shed")
        SERVE_EVENTS.emit(
            "shed",
            klass=klass,
            key=key,
            reason=reason,
            queue_wait_s=round(queue_wait_s, 4),
        )
        raise Shed(klass, self.policy.retry_after_s, reason)

    # --- introspection --------------------------------------------------

    def snapshot(self) -> dict[str, Any]:
        """Gate state for health / ``GET /mesh`` / ``sdx serve-status``."""
        pol = self.policy
        classes = {}
        for klass in CLASSES:
            budget = pol.budgets.get(klass)
            classes[klass] = {
                "inflight": self.inflight[klass],
                "queued": len(self._queues[klass]),
                "admitted_total": self.admitted[klass],
                "shed_total": self.shed[klass],
                "max_inflight": budget.max_inflight if budget else None,
                "sheddable": budget.sheddable if budget else True,
            }
        return {
            "enabled": _policy.enabled(),
            "mode": self._refresh_mode() if _policy.enabled() else NORMAL,
            "classes": classes,
        }
