"""Execute ONNX graphs with JAX — the TPU-native ONNX Runtime stand-in.

The reference hands its YOLOv8 `.onnx` to the `ort` C++ runtime with
per-platform execution providers (ref:crates/ai/src/lib.rs:22-77).
Here the execution provider IS XLA: `OnnxModel.__call__` is a pure
function of its inputs, so `jax.jit` compiles the whole graph into one
TPU program (MXU convs, fused elementwise). Static shapes only — the
vision models this serves (YOLO heads, CNN classifiers) are static.

Supported op set: what YOLO-family detectors and common CNN/MLP
classifiers use. Unsupported ops raise with the op name so gaps are
explicit, never silent.
"""

from __future__ import annotations

import functools
import math
from typing import Any, Callable

import numpy as np

from . import onnx_proto as proto


def _jax():
    import jax

    return jax


def _np_static(x: Any, what: str) -> np.ndarray:
    """Concretize a value that must be static (shape/index operands)."""
    try:
        return np.asarray(x)
    except Exception as exc:  # jax tracer: data-dependent shape
        raise ValueError(
            f"ONNX graph uses a data-dependent {what}; static shapes only"
        ) from exc


class _Env(dict):
    def fetch(self, names: list[str]) -> list[Any]:
        return [None if n == "" else self[n] for n in names]


def _is_host(v: Any) -> bool:
    return v is None or isinstance(v, (np.ndarray, np.generic, int, float, bool))


# Ops whose implementations call into jax.lax/jax.nn directly; everything
# else is written against the jnp/numpy-compatible API surface and runs
# in PLAIN NUMPY when all its inputs are host values. That keeps shape
# subgraphs (Shape→Gather→Concat→Reshape…) concrete under jax.jit —
# inside a trace, jnp ops stage even on constants, which would turn a
# Reshape target into a tracer.
_DEVICE_ONLY = frozenset({
    "Conv", "ConvTranspose", "MaxPool", "AveragePool", "GlobalAveragePool",
    "GlobalMaxPool", "Resize", "Upsample", "Softmax", "Erf", "MatMul",
    "Gemm",
})


_OPS: dict[str, Callable] = {}


def op(name: str):
    def deco(fn):
        _OPS[name] = fn
        return fn
    return deco


def _attr_value(a: dict[str, Any]) -> Any:
    t = a.get("type", 0)
    if t == 1:
        return a["f"]
    if t == 2:
        return a["i"]
    if t == 3:
        return a["s"].decode()
    if t == 4:
        return proto.tensor_to_array(a["t"])
    if t == 6:
        return list(a.get("floats", []))
    if t == 7:
        return list(a.get("ints", []))
    if t == 8:
        return [s.decode() for s in a.get("strings", [])]
    raise ValueError(f"unsupported attribute type {t} ({a.get('name')})")


def _attrs(node: dict[str, Any]) -> dict[str, Any]:
    return {a["name"]: _attr_value(a) for a in node.get("attribute", [])}


# --- elementwise / activation ---------------------------------------------

def _ew(fn):
    return lambda jnp, attrs, *xs: fn(jnp, *xs)


op("Add")(_ew(lambda jnp, a, b: a + b))
op("Sub")(_ew(lambda jnp, a, b: a - b))
op("Mul")(_ew(lambda jnp, a, b: a * b))
op("Div")(_ew(lambda jnp, a, b: a / b))
op("Pow")(_ew(lambda jnp, a, b: a ** b))
op("Sqrt")(_ew(lambda jnp, a: jnp.sqrt(a)))
op("Exp")(_ew(lambda jnp, a: jnp.exp(a)))
op("Log")(_ew(lambda jnp, a: jnp.log(a)))
op("Neg")(_ew(lambda jnp, a: -a))
op("Abs")(_ew(lambda jnp, a: jnp.abs(a)))
op("Relu")(_ew(lambda jnp, a: jnp.maximum(a, 0)))
op("Sigmoid")(_ew(lambda jnp, a: 1.0 / (1.0 + jnp.exp(-a))))
op("Tanh")(_ew(lambda jnp, a: jnp.tanh(a)))
op("Erf")(_ew(lambda jnp, a: _jax().scipy.special.erf(a)))
op("Identity")(_ew(lambda jnp, a: a))
op("Floor")(_ew(lambda jnp, a: jnp.floor(a)))
op("Ceil")(_ew(lambda jnp, a: jnp.ceil(a)))
op("Min")(_ew(lambda jnp, *xs: functools.reduce(jnp.minimum, xs)))
op("Max")(_ew(lambda jnp, *xs: functools.reduce(jnp.maximum, xs)))


@op("LeakyRelu")
def _leaky_relu(jnp, attrs, x):
    alpha = attrs.get("alpha", 0.01)
    return jnp.where(x >= 0, x, alpha * x)


@op("HardSigmoid")
def _hard_sigmoid(jnp, attrs, x):
    alpha = attrs.get("alpha", 0.2)
    beta = attrs.get("beta", 0.5)
    return jnp.clip(alpha * x + beta, 0.0, 1.0)


@op("HardSwish")
def _hard_swish(jnp, attrs, x):
    return x * jnp.clip(x / 6.0 + 0.5, 0.0, 1.0)


@op("Clip")
def _clip(jnp, attrs, x, lo=None, hi=None):
    lo = attrs.get("min", lo)
    hi = attrs.get("max", hi)
    if lo is not None:
        x = jnp.maximum(x, lo)
    if hi is not None:
        x = jnp.minimum(x, hi)
    return x


@op("Softmax")
def _softmax(jnp, attrs, x):
    import jax

    return jax.nn.softmax(x, axis=attrs.get("axis", -1))


# --- tensor shuffling ------------------------------------------------------

@op("Concat")
def _concat(jnp, attrs, *xs):
    return jnp.concatenate(xs, axis=attrs["axis"])


@op("Reshape")
def _reshape(jnp, attrs, x, shape=None):
    target = [int(v) for v in _np_static(shape, "Reshape target").tolist()]
    # ONNX: 0 copies the input dim (unless allowzero), -1 infers
    out = [x.shape[i] if v == 0 and not attrs.get("allowzero") else v
           for i, v in enumerate(target)]
    return jnp.reshape(x, out)


@op("Flatten")
def _flatten(jnp, attrs, x):
    axis = attrs.get("axis", 1)
    lead = int(np.prod(x.shape[:axis], dtype=np.int64)) if axis else 1
    return jnp.reshape(x, (lead, -1))


@op("Transpose")
def _transpose(jnp, attrs, x):
    perm = attrs.get("perm") or list(range(x.ndim))[::-1]
    return jnp.transpose(x, perm)


@op("Unsqueeze")
def _unsqueeze(jnp, attrs, x, axes=None):
    ax = attrs.get("axes")
    if ax is None:
        ax = _np_static(axes, "Unsqueeze axes").tolist()
    out = x
    for a in sorted(int(v) for v in ax):
        out = jnp.expand_dims(out, a)
    return out


@op("Squeeze")
def _squeeze(jnp, attrs, x, axes=None):
    ax = attrs.get("axes")
    if ax is None and axes is not None:
        ax = _np_static(axes, "Squeeze axes").tolist()
    return jnp.squeeze(x, axis=tuple(int(v) for v in ax) if ax else None)


@op("Shape")
def _shape(jnp, attrs, x):
    return np.asarray(x.shape, np.int64)  # static under jit by design


@op("Gather")
def _gather(jnp, attrs, x, idx):
    axis = attrs.get("axis", 0)
    if isinstance(x, np.ndarray):
        return np.take(x, _np_static(idx, "Gather indices"), axis=axis)
    return jnp.take(x, jnp.asarray(idx), axis=axis)


@op("Slice")
def _slice(jnp, attrs, x, starts=None, ends=None, axes=None, steps=None):
    if starts is None:  # opset < 10: attributes
        starts = attrs["starts"]
        ends = attrs["ends"]
        axes = attrs.get("axes")
        steps = None
    starts = _np_static(starts, "Slice starts").tolist()
    ends = _np_static(ends, "Slice ends").tolist()
    axes = (_np_static(axes, "Slice axes").tolist()
            if axes is not None else list(range(len(starts))))
    steps = (_np_static(steps, "Slice steps").tolist()
             if steps is not None else [1] * len(starts))
    idx = [slice(None)] * x.ndim
    for st, en, ax, sp in zip(starts, ends, axes, steps):
        ax = int(ax) % x.ndim
        idx[ax] = slice(int(st), int(en), int(sp))
    return x[tuple(idx)]


@op("Split")
def _split(jnp, attrs, x, split=None):
    axis = attrs.get("axis", 0)
    sizes = attrs.get("split")
    if sizes is None and split is not None:
        sizes = _np_static(split, "Split sizes").tolist()
    if sizes is None:
        n = attrs["num_outputs"]
        base = x.shape[axis] // n
        rem = x.shape[axis] - base * n
        sizes = [base + (1 if i < rem else 0) for i in range(n)]
    bounds = np.cumsum(sizes)[:-1].tolist()
    return tuple(jnp.split(x, bounds, axis=axis))


@op("Cast")
def _cast(jnp, attrs, x):
    return x.astype(proto._DTYPES[attrs["to"]])


@op("Constant")
def _constant(jnp, attrs):
    if "value" in attrs:
        return attrs["value"]
    for k in ("value_float", "value_int"):
        if k in attrs:
            return np.asarray(attrs[k])
    if "value_floats" in attrs:
        return np.asarray(attrs["value_floats"], np.float32)
    if "value_ints" in attrs:
        return np.asarray(attrs["value_ints"], np.int64)
    raise ValueError("Constant node without value")


@op("ConstantOfShape")
def _constant_of_shape(jnp, attrs, shape):
    dims = _np_static(shape, "ConstantOfShape dims").tolist()
    fill = attrs.get("value")
    if fill is None:
        return np.zeros(dims, np.float32)
    return np.full(dims, fill.reshape(-1)[0], fill.dtype)


@op("Range")
def _range(jnp, attrs, start, limit, delta):
    return np.arange(
        _np_static(start, "Range").item(),
        _np_static(limit, "Range").item(),
        _np_static(delta, "Range").item(),
    )


@op("Expand")
def _expand(jnp, attrs, x, shape):
    dims = [int(v) for v in _np_static(shape, "Expand shape").tolist()]
    # ONNX Expand broadcasts; dim of 1 in target keeps input dim
    out_shape = list(np.broadcast_shapes(tuple(x.shape), tuple(dims)))
    return jnp.broadcast_to(x, out_shape)


@op("Tile")
def _tile(jnp, attrs, x, reps):
    return jnp.tile(x, [int(v) for v in _np_static(reps, "Tile reps").tolist()])


@op("Pad")
def _pad(jnp, attrs, x, pads=None, value=None):
    mode = attrs.get("mode", "constant")
    p = attrs.get("pads")
    if p is None:
        p = _np_static(pads, "Pad pads").tolist()
    n = x.ndim
    pairs = [(int(p[i]), int(p[i + n])) for i in range(n)]
    cval = 0.0
    if value is not None:
        cval = float(_np_static(value, "Pad value").reshape(-1)[0])
    if mode == "constant":
        return jnp.pad(x, pairs, constant_values=cval)
    return jnp.pad(x, pairs, mode={"reflect": "reflect", "edge": "edge"}[mode])


# --- reductions ------------------------------------------------------------

def _reduce(jnp_fn_name):
    def fn(jnp, attrs, x, axes_in=None):
        axes = attrs.get("axes")
        if axes is None and axes_in is not None:
            axes = _np_static(axes_in, "Reduce axes").tolist()
        axes = tuple(int(a) for a in axes) if axes else None
        keep = bool(attrs.get("keepdims", 1))
        return getattr(jnp, jnp_fn_name)(x, axis=axes, keepdims=keep)
    return fn


op("ReduceMean")(_reduce("mean"))
op("ReduceSum")(_reduce("sum"))
op("ReduceMax")(_reduce("max"))
op("ReduceMin")(_reduce("min"))


@op("ArgMax")
def _argmax(jnp, attrs, x):
    axis = attrs.get("axis", 0)
    out = jnp.argmax(x, axis=axis)
    if attrs.get("keepdims", 1):
        out = jnp.expand_dims(out, axis)
    return out


# --- linear algebra --------------------------------------------------------

@op("MatMul")
def _matmul(jnp, attrs, a, b):
    return jnp.matmul(a, b, precision=_jax().lax.Precision.HIGHEST)


@op("Gemm")
def _gemm(jnp, attrs, a, b, c=None):
    alpha = attrs.get("alpha", 1.0)
    beta = attrs.get("beta", 1.0)
    if attrs.get("transA"):
        a = a.T
    if attrs.get("transB"):
        b = b.T
    out = alpha * jnp.matmul(a, b, precision=_jax().lax.Precision.HIGHEST)
    if c is not None:
        out = out + beta * c
    return out


# --- convolution / pooling -------------------------------------------------

def _conv_pads(attrs, x_shape, k_shape, strides, dilations):
    """Resolve ONNX pads/auto_pad to lax ((lo, hi), ...) per spatial dim."""
    spatial = len(k_shape)
    auto = attrs.get("auto_pad", "NOTSET")
    if auto in ("NOTSET", ""):
        p = attrs.get("pads", [0] * (2 * spatial))
        return [(int(p[i]), int(p[i + spatial])) for i in range(spatial)]
    if auto == "VALID":
        return [(0, 0)] * spatial
    pairs = []
    for i in range(spatial):
        in_dim = x_shape[2 + i]
        eff_k = (k_shape[i] - 1) * dilations[i] + 1
        out_dim = math.ceil(in_dim / strides[i])
        total = max(0, (out_dim - 1) * strides[i] + eff_k - in_dim)
        lo = total // 2
        hi = total - lo
        if auto == "SAME_UPPER":
            pairs.append((lo, hi))
        else:  # SAME_LOWER
            pairs.append((hi, lo))
    return pairs


@op("Conv")
def _conv(jnp, attrs, x, w, b=None):
    import jax

    spatial = w.ndim - 2
    strides = attrs.get("strides", [1] * spatial)
    dilations = attrs.get("dilations", [1] * spatial)
    groups = attrs.get("group", 1)
    pads = _conv_pads(attrs, x.shape, w.shape[2:], strides, dilations)
    dn = jax.lax.conv_dimension_numbers(
        x.shape, w.shape,
        ("NCHW", "OIHW", "NCHW") if spatial == 2 else ("NCW", "OIW", "NCW"),
    )
    out = jax.lax.conv_general_dilated(
        x, w, window_strides=strides, padding=pads,
        rhs_dilation=dilations, dimension_numbers=dn,
        feature_group_count=groups, precision=jax.lax.Precision.HIGHEST,
    )
    if b is not None:
        out = out + b.reshape((1, -1) + (1,) * spatial)
    return out


@op("ConvTranspose")
def _conv_transpose(jnp, attrs, x, w, b=None):
    import jax

    spatial = w.ndim - 2
    strides = attrs.get("strides", [1] * spatial)
    # Attributes this lowering does not model — refuse rather than compute
    # a silently wrong result (module policy: unsupported gaps raise).
    if attrs.get("group", 1) != 1:
        raise NotImplementedError("ConvTranspose: group != 1")
    if any(int(d) != 1 for d in attrs.get("dilations", [1] * spatial)):
        raise NotImplementedError("ConvTranspose: dilations != 1")
    if any(int(p) != 0 for p in attrs.get("output_padding", [0] * spatial)):
        raise NotImplementedError("ConvTranspose: output_padding")
    if "output_shape" in attrs:
        raise NotImplementedError("ConvTranspose: output_shape")
    if attrs.get("auto_pad", "NOTSET") not in (
        "NOTSET", b"NOTSET", "VALID", b"VALID",  # VALID ≡ NOTSET w/ zero pads
    ):
        raise NotImplementedError("ConvTranspose: auto_pad SAME_*")
    pads = attrs.get("pads", [0] * (2 * spatial))
    pairs = [(int(pads[i]), int(pads[i + spatial])) for i in range(spatial)]
    # ONNX ConvTranspose weight is (C_in, C_out/groups, kH, kW)
    out = jax.lax.conv_transpose(
        x, jnp.transpose(w, (1, 0) + tuple(range(2, w.ndim))),
        strides=strides, precision=jax.lax.Precision.HIGHEST,
        padding=[(k - 1 - lo, k - 1 - hi)
                 for (lo, hi), k in zip(pairs, w.shape[2:])],
        dimension_numbers=("NCHW", "OIHW", "NCHW") if spatial == 2 else None,
        transpose_kernel=True,
    )
    if b is not None:
        out = out + b.reshape((1, -1) + (1,) * spatial)
    return out


def _pool(jnp, attrs, x, reducer, init, is_avg=False):
    import jax

    kernel = attrs["kernel_shape"]
    spatial = len(kernel)
    strides = attrs.get("strides", [1] * spatial)
    dilations = attrs.get("dilations", [1] * spatial)
    pads = _conv_pads(attrs, x.shape, kernel, strides, dilations)
    if attrs.get("ceil_mode"):
        # grow the high pad so the last partial window is included
        new_pads = []
        for i in range(spatial):
            in_dim = x.shape[2 + i] + pads[i][0] + pads[i][1]
            eff_k = (kernel[i] - 1) * dilations[i] + 1
            rem = (in_dim - eff_k) % strides[i]
            extra = (strides[i] - rem) % strides[i] if rem else 0
            new_pads.append((pads[i][0], pads[i][1] + extra))
        pads = new_pads
    window = (1, 1) + tuple(kernel)
    win_strides = (1, 1) + tuple(strides)
    win_dil = (1, 1) + tuple(dilations)
    full_pads = [(0, 0), (0, 0)] + pads
    out = jax.lax.reduce_window(
        x, init, reducer, window, win_strides, full_pads,
        window_dilation=win_dil,
    )
    if is_avg:
        if attrs.get("count_include_pad"):
            out = out / float(np.prod(kernel))
        else:
            ones = jnp.ones_like(x)
            counts = jax.lax.reduce_window(
                ones, 0.0, jax.lax.add, window, win_strides, full_pads,
                window_dilation=win_dil,
            )
            out = out / counts
    return out


@op("MaxPool")
def _max_pool(jnp, attrs, x):
    import jax

    return _pool(jnp, attrs, x, jax.lax.max, -jnp.inf)


@op("AveragePool")
def _avg_pool(jnp, attrs, x):
    import jax

    return _pool(jnp, attrs, x, jax.lax.add, 0.0, is_avg=True)


@op("GlobalAveragePool")
def _global_avg_pool(jnp, attrs, x):
    return jnp.mean(x, axis=tuple(range(2, x.ndim)), keepdims=True)


@op("GlobalMaxPool")
def _global_max_pool(jnp, attrs, x):
    return jnp.max(x, axis=tuple(range(2, x.ndim)), keepdims=True)


@op("BatchNormalization")
def _batch_norm(jnp, attrs, x, scale, bias, mean, var):
    eps = attrs.get("epsilon", 1e-5)
    shape = (1, -1) + (1,) * (x.ndim - 2)
    inv = 1.0 / jnp.sqrt(var + eps)
    return (x - mean.reshape(shape)) * (scale * inv).reshape(shape) + \
        bias.reshape(shape)


@op("InstanceNormalization")
def _instance_norm(jnp, attrs, x, scale, bias):
    eps = attrs.get("epsilon", 1e-5)
    axes = tuple(range(2, x.ndim))
    mean = jnp.mean(x, axis=axes, keepdims=True)
    var = jnp.var(x, axis=axes, keepdims=True)
    shape = (1, -1) + (1,) * (x.ndim - 2)
    return (x - mean) / jnp.sqrt(var + eps) * scale.reshape(shape) + \
        bias.reshape(shape)


@op("LayerNormalization")
def _layer_norm(jnp, attrs, x, scale, bias=None):
    eps = attrs.get("epsilon", 1e-5)
    axis = attrs.get("axis", -1)
    axes = tuple(range(axis % x.ndim, x.ndim))
    mean = jnp.mean(x, axis=axes, keepdims=True)
    var = jnp.var(x, axis=axes, keepdims=True)
    out = (x - mean) / jnp.sqrt(var + eps) * scale
    return out + bias if bias is not None else out


@op("Resize")
def _resize(jnp, attrs, x, roi=None, scales=None, sizes=None):
    import jax

    mode = attrs.get("mode", "nearest")
    if sizes is not None:
        out_spatial = [int(v) for v in
                       _np_static(sizes, "Resize sizes").tolist()][2:]
    else:
        sc = _np_static(scales, "Resize scales").tolist()
        out_spatial = [int(round(x.shape[2 + i] * sc[2 + i]))
                       for i in range(x.ndim - 2)]
    out_shape = tuple(x.shape[:2]) + tuple(out_spatial)
    method = {"nearest": "nearest", "linear": "bilinear",
              "cubic": "bicubic"}[mode]
    return jax.image.resize(x, out_shape, method=method)


@op("Upsample")
def _upsample(jnp, attrs, x, scales=None):
    sc = attrs.get("scales") or _np_static(scales, "Upsample scales").tolist()
    fake_attrs = {"mode": attrs.get("mode", "nearest")}
    return _resize(jnp, fake_attrs, x, None, np.asarray(sc, np.float32), None)


@op("Where")
def _where(jnp, attrs, cond, a, b):
    return jnp.where(cond, a, b)


@op("Equal")
def _equal(jnp, attrs, a, b):
    return a == b


@op("Greater")
def _greater(jnp, attrs, a, b):
    return a > b


@op("Less")
def _less(jnp, attrs, a, b):
    return a < b


@op("Dropout")
def _dropout(jnp, attrs, x, *rest):
    return x  # inference mode


# --- the model object ------------------------------------------------------


class OnnxModel:
    """A decoded ONNX graph, executable as a pure JAX function.

    `inputs`/`outputs` are the graph's I/O names (initializers
    excluded); `__call__` takes arrays in input order and returns the
    list of outputs. Wrap in `jax.jit` for compiled execution.
    """

    def __init__(self, model: dict[str, Any]):
        self.model = model
        graph = model["graph"]
        self.graph = graph
        self.initializers = {
            t["name"]: proto.tensor_to_array(t)
            for t in graph.get("initializer", [])
        }
        self.inputs = [
            vi["name"] for vi in graph.get("input", [])
            if vi["name"] not in self.initializers
        ]
        self.outputs = [vi["name"] for vi in graph.get("output", [])]
        self.nodes = graph.get("node", [])
        unsupported = sorted({
            n["op_type"] for n in self.nodes if n["op_type"] not in _OPS
        })
        if unsupported:
            raise NotImplementedError(
                f"unsupported ONNX ops: {', '.join(unsupported)}"
            )

    def input_shapes(self) -> dict[str, tuple[int, ...]]:
        shapes = {}
        for vi in self.graph.get("input", []):
            if vi["name"] in self.initializers:
                continue
            dims = vi.get("type", {}).get("tensor_type", {}) \
                .get("shape", {}).get("dim", [])
            shapes[vi["name"]] = tuple(
                int(d.get("dim_value", -1)) if "dim_value" in d else -1
                for d in dims
            )
        return shapes

    def __call__(self, *args: Any) -> list[Any]:
        import jax.numpy as jnp

        if len(args) != len(self.inputs):
            raise ValueError(
                f"expected {len(self.inputs)} inputs {self.inputs}, "
                f"got {len(args)}"
            )
        env = _Env(self.initializers)
        env.update(zip(self.inputs, args))
        for node in self.nodes:
            op_type = node["op_type"]
            fn = _OPS[op_type]
            ins = env.fetch(node["input"])
            host = op_type not in _DEVICE_ONLY and all(_is_host(i) for i in ins)
            outs = fn(np if host else jnp, _attrs(node), *ins)
            out_names = node["output"]
            if not isinstance(outs, tuple):
                outs = (outs,)
            for name, val in zip(out_names, outs):
                if name:
                    env[name] = val
        return [env[n] for n in self.outputs]


def load(path_or_bytes: str | bytes) -> OnnxModel:
    """Load an `.onnx` file (or raw bytes) into an executable OnnxModel."""
    if isinstance(path_or_bytes, bytes):
        data = path_or_bytes
    else:
        with open(path_or_bytes, "rb") as f:
            data = f.read()
    return OnnxModel(proto.decode_model(data))
