"""Labeler training — produce a real checkpoint for the labeler actor.

The reference ships inference-only (it downloads pretrained YOLOv8,
ref:crates/ai/src/image_labeler/model/yolov8.rs:37-41); in an offline
deployment that download never happens and labeling stays off. This
module is the TPU-native framework's way to make the capability real
without a download: train (or fine-tune) LabelerNet on a labeled image
folder and save a checkpoint the actor loads.

Dataset layout: `root/<class_name>/*.jpg|png|…` — one folder per class
(multi-label rows can repeat an image under several folders; dedup by
cas would be overkill here). `sdx labeler train <root>` wires this up.

The training step itself is `labeler.train_step`, jit/pjit-able over a
device mesh (dp batch sharding + fsdp/tp param sharding, see
`labeler.param_shardings`).
"""

from __future__ import annotations

import logging
import os
from dataclasses import dataclass
from typing import Any, Callable, Iterator, Sequence

import numpy as np

from . import checkpoint
from . import labeler as labeler_model

logger = logging.getLogger(__name__)

IMAGE_EXTS = (".jpg", ".jpeg", ".png", ".webp", ".bmp", ".gif", ".tif", ".tiff")


@dataclass
class TrainConfig:
    image_size: int = 96
    widths: tuple[int, ...] = (16, 32, 64, 128, 128)
    depths: tuple[int, ...] = (1, 1, 1, 1)
    batch_size: int = 32
    steps: int = 600
    learning_rate: float = 1e-3
    seed: int = 0
    eval_fraction: float = 0.1
    use_device: bool = True


def scan_folder_dataset(root: str | os.PathLike) -> tuple[list[tuple[str, int]], list[str]]:
    """folder-per-class layout → ([(path, class_idx)], class_names)."""
    root = os.fspath(root)
    classes = sorted(
        d for d in os.listdir(root)
        if os.path.isdir(os.path.join(root, d)) and not d.startswith(".")
    )
    if not classes:
        raise ValueError(f"{root}: no class folders found")
    samples: list[tuple[str, int]] = []
    for idx, name in enumerate(classes):
        cdir = os.path.join(root, name)
        for fn in sorted(os.listdir(cdir)):
            if fn.lower().endswith(IMAGE_EXTS):
                samples.append((os.path.join(cdir, fn), idx))
    if not samples:
        raise ValueError(f"{root}: class folders contain no images")
    return samples, classes


def _decode(path: str, image_size: int) -> np.ndarray | None:
    from PIL import Image

    try:
        with Image.open(path) as img:
            img = img.convert("RGB").resize((image_size, image_size))
            return np.asarray(img, np.float32) / 255.0
    except Exception:
        logger.warning("train: failed to decode %s", path)
        return None


def _folder_batches(
    samples: list[tuple[str, int]], n_classes: int, cfg: TrainConfig,
    rng: np.random.Generator,
) -> Iterator[tuple[np.ndarray, np.ndarray]]:
    """Infinite shuffled FIXED-SHAPE batch stream from disk.

    Every yielded batch has exactly `bs` rows (failed decodes are
    backfilled by repeating rows) so the jitted train step compiles
    once — ragged batches would recompile per distinct shape, which on
    a tunneled TPU costs more than the step itself. Decoded images are
    cached as uint8 under a ~512 MB budget; beyond that, re-decode.
    """
    bs = min(cfg.batch_size, len(samples))
    cache: dict[str, np.ndarray | None] = {}
    cache_cap = max(1, (512 << 20) // (cfg.image_size * cfg.image_size * 3))

    def fetch(path: str) -> np.ndarray | None:
        if path in cache:
            hit = cache[path]
            return None if hit is None else hit.astype(np.float32) / 255.0
        arr = _decode(path, cfg.image_size)
        if len(cache) < cache_cap:
            cache[path] = None if arr is None else (
                (arr * 255.0).astype(np.uint8)
            )
        return arr

    while True:
        order = rng.permutation(len(samples))
        for off in range(0, max(1, len(order) - bs + 1), bs):
            idxs = order[off:off + bs]
            images, labels = [], []
            for i in idxs:
                path, cls = samples[i]
                arr = fetch(path)
                if arr is None:
                    continue
                images.append(arr)
                row = np.zeros(n_classes, np.float32)
                row[cls] = 1.0
                labels.append(row)
            if not images:
                continue
            while len(images) < bs:  # backfill to a fixed shape
                j = len(images) % len(labels)
                images.append(images[j])
                labels.append(labels[j])
            yield np.stack(images), np.stack(labels)


def train(
    batches: Iterator[tuple[np.ndarray, np.ndarray]],
    classes: Sequence[str],
    cfg: TrainConfig,
    *,
    eval_set: tuple[np.ndarray, np.ndarray] | None = None,
    progress: Callable[[int, float], None] | None = None,
) -> tuple[Any, labeler_model.LabelerNet, dict[str, float]]:
    """Run `cfg.steps` optimizer steps; returns (params, model, metrics)."""
    import jax

    model = labeler_model.LabelerNet(
        num_classes=len(classes), widths=cfg.widths, depths=cfg.depths
    )
    device = None
    if not cfg.use_device:
        device = jax.devices("cpu")[0]
    with jax.default_device(device) if device else _nullcontext():
        params, opt_state, tx = labeler_model.create_train_state(
            jax.random.key(cfg.seed), image_size=cfg.image_size,
            learning_rate=cfg.learning_rate, model=model,
        )
        step_fn = jax.jit(
            lambda p, o, x, y: labeler_model.train_step(model, tx, p, o, x, y)
        )
        loss = float("nan")
        for step in range(cfg.steps):
            images, labels = next(batches)
            params, opt_state, loss = step_fn(params, opt_state, images, labels)
            if progress and (step % 20 == 0 or step == cfg.steps - 1):
                progress(step, float(loss))
        metrics: dict[str, float] = {"final_loss": float(loss)}
        if eval_set is not None:
            images, labels = eval_set
            probs = np.asarray(
                jax.nn.sigmoid(model.apply({"params": params}, images))
            )
            top1 = (probs.argmax(1) == labels.argmax(1)).mean()
            metrics["eval_top1"] = float(top1)
    return params, model, metrics


class _nullcontext:
    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False


def train_folder(
    root: str | os.PathLike, out_path: str | os.PathLike,
    cfg: TrainConfig | None = None,
    progress: Callable[[int, float], None] | None = None,
) -> dict[str, float]:
    """Train on a folder-per-class dataset and save the checkpoint."""
    cfg = cfg or TrainConfig()
    samples, classes = scan_folder_dataset(root)
    rng = np.random.default_rng(cfg.seed)
    order = rng.permutation(len(samples))
    n_eval = max(1, int(len(samples) * cfg.eval_fraction))
    eval_samples = [samples[i] for i in order[:n_eval]]
    train_samples = [samples[i] for i in order[n_eval:]]
    if not train_samples:
        raise ValueError("dataset too small to split")
    eval_imgs, eval_rows = [], []
    for path, cls in eval_samples:
        arr = _decode(path, cfg.image_size)
        if arr is None:
            continue
        eval_imgs.append(arr)
        row = np.zeros(len(classes), np.float32)
        row[cls] = 1.0
        eval_rows.append(row)
    eval_set = (
        (np.stack(eval_imgs), np.stack(eval_rows)) if eval_imgs else None
    )
    batches = _folder_batches(train_samples, len(classes), cfg, rng)
    params, _model, metrics = train(
        batches, classes, cfg, eval_set=eval_set, progress=progress
    )
    checkpoint.save(
        out_path, params, classes=list(classes), image_size=cfg.image_size,
        widths=cfg.widths, depths=cfg.depths,
        extra={"metrics": metrics, "trained_on": os.fspath(root)},
    )
    return metrics


def digits_demo_dataset(image_size: int = 32) -> tuple[
    tuple[np.ndarray, np.ndarray], tuple[np.ndarray, np.ndarray], list[str]
]:
    """Bundled real dataset (sklearn digits, 1,797 8×8 scans) for the
    self-contained train demo + tests: returns (train, eval, classes)."""
    from sklearn.datasets import load_digits

    digits = load_digits()
    imgs = digits.images.astype(np.float32) / 16.0  # [N, 8, 8] in [0,1]
    n = imgs.shape[0]
    # upscale 8→image_size (nearest) and tile to 3 channels
    reps = image_size // 8
    big = np.repeat(np.repeat(imgs, reps, axis=1), reps, axis=2)
    rgb = np.repeat(big[..., None], 3, axis=-1)
    labels = np.zeros((n, 10), np.float32)
    labels[np.arange(n), digits.target] = 1.0
    rng = np.random.default_rng(0)
    order = rng.permutation(n)
    split = int(n * 0.9)
    tr, ev = order[:split], order[split:]
    classes = [f"digit {d}" for d in range(10)]
    return (rgb[tr], labels[tr]), (rgb[ev], labels[ev]), classes


# --- procedural scene corpus ------------------------------------------------
#
# The only REAL image set available without egress is sklearn's digit
# scans, and "digit 7" is a useless label for a photo library (VERDICT
# r4 weak #2). These generators render the coarse visual statistics of
# the content kinds a file manager actually meets — page-like documents,
# flat-chrome screenshots, sparse strokes, low-frequency natural fields,
# axes-and-series charts, dark scenes — so the bundled offline model
# can say something TRUE about real files. They are also the test
# oracle: the golden test renders held-out samples with a different
# seed and demands the bundled artifact classify them.

SCENE_CLASSES = [
    "document scan", "screenshot", "line art", "photo", "chart",
    "dark photo",
]


def _pool2(img: np.ndarray) -> np.ndarray:
    """2×2 average pool (renders at 2× then downsamples: cheap AA)."""
    return (img[0::2, 0::2] + img[1::2, 0::2]
            + img[0::2, 1::2] + img[1::2, 1::2]) / 4.0


def render_scene(kind: str, rng: np.random.Generator,
                 image_size: int = 32) -> np.ndarray:
    """One [S, S, 3] float32 image in [0, 1] of the given scene kind."""
    s = image_size * 2
    img = np.zeros((s, s, 3), np.float32)
    if kind == "document scan":
        img[:] = 0.82 + rng.uniform(0.0, 0.15)
        img += rng.normal(0, 0.02, img.shape).astype(np.float32)
        margin = int(s * rng.uniform(0.08, 0.18))
        line_h = max(1, int(s * rng.uniform(0.03, 0.06)))
        y = margin
        while y < s - margin:
            x = margin
            while x < s - margin:
                w = int(rng.integers(2, max(3, s // 5)))
                if rng.random() < 0.85:  # word; else inter-word gap
                    img[y:y + line_h, x:min(x + w, s - margin)] *= \
                        rng.uniform(0.15, 0.45)
                x += w + int(rng.integers(1, 4))
            y += line_h + int(rng.integers(line_h, 2 * line_h + 1))
    elif kind == "screenshot":
        img[:] = rng.uniform(0.08, 0.95, 3)
        bar_h = int(s * rng.uniform(0.06, 0.14))
        img[:bar_h] = rng.uniform(0, 1, 3)
        if rng.random() < 0.7:  # sidebar
            img[bar_h:, : int(s * rng.uniform(0.12, 0.3))] = \
                rng.uniform(0, 1, 3)
        for _ in range(int(rng.integers(3, 9))):  # flat panels/buttons
            x0 = int(rng.integers(0, s - 8))
            y0 = int(rng.integers(0, s - 8))
            w = int(rng.integers(6, s // 2))
            h = int(rng.integers(4, s // 3))
            img[y0:y0 + h, x0:x0 + w] = rng.uniform(0, 1, 3)
    elif kind == "line art":
        img[:] = rng.uniform(0.92, 1.0)
        for _ in range(int(rng.integers(2, 6))):
            x = rng.uniform(0, s - 1)
            y = rng.uniform(0, s - 1)
            vx, vy = rng.normal(0, 2.5, 2)
            for _ in range(60):
                vx = vx * 0.9 + rng.normal(0, 1.0)
                vy = vy * 0.9 + rng.normal(0, 1.0)
                x = float(np.clip(x + vx, 0, s - 2))
                y = float(np.clip(y + vy, 0, s - 2))
                img[int(y):int(y) + 2, int(x):int(x) + 2] = 0.05
    elif kind == "photo":
        coarse = rng.uniform(0, 1, (4, 4, 3)).astype(np.float32)
        img = np.kron(coarse, np.ones((s // 4, s // 4, 1), np.float32))
        grad = np.linspace(rng.uniform(-0.3, 0.3), rng.uniform(-0.3, 0.3),
                           s, dtype=np.float32)[:, None, None]
        img = img + grad + rng.normal(0, 0.05, img.shape).astype(np.float32)
        for _ in range(3):  # soften edges toward natural statistics
            img = (np.roll(img, 1, 0) + np.roll(img, -1, 0)
                   + np.roll(img, 1, 1) + np.roll(img, -1, 1) + img) / 5.0
        img = np.clip(img, 0, 1)
    elif kind == "chart":
        img[:] = rng.uniform(0.95, 1.0)
        ax = int(s * 0.12)
        img[s - ax - 1: s - ax, ax:, :] = 0.25       # x axis
        img[: s - ax, ax: ax + 1, :] = 0.25          # y axis
        color = rng.uniform(0, 0.8, 3)
        n_bars = int(rng.integers(4, 9))
        bw = (s - 2 * ax) // n_bars
        if rng.random() < 0.5:  # bar chart
            for i in range(n_bars):
                h = int(rng.uniform(0.1, 0.8) * (s - 2 * ax))
                x0 = ax + 2 + i * bw
                img[s - ax - 1 - h: s - ax - 1, x0: x0 + max(1, bw - 2)] = color
        else:  # polyline series
            ys = (s - ax - 1
                  - rng.uniform(0.05, 0.8, n_bars + 1) * (s - 2 * ax))
            for i in range(n_bars):
                x0, x1 = ax + i * bw, ax + (i + 1) * bw
                y0, y1 = ys[i], ys[i + 1]
                for t in np.linspace(0, 1, 2 * bw):
                    xx = int(x0 + t * (x1 - x0))
                    yy = int(y0 + t * (y1 - y0))
                    img[max(yy - 1, 0): yy + 1, xx: xx + 1] = color
        for gy in range(ax, s - ax, max(4, (s - 2 * ax) // 5)):  # gridlines
            img[gy: gy + 1, ax:, :] = np.minimum(img[gy: gy + 1, ax:, :], 0.85)
    elif kind == "dark photo":
        coarse = rng.uniform(0, 0.18, (4, 4, 3)).astype(np.float32)
        img = np.kron(coarse, np.ones((s // 4, s // 4, 1), np.float32))
        for _ in range(int(rng.integers(2, 7))):  # bright sources
            cx = int(rng.integers(2, s - 2))
            cy = int(rng.integers(2, s - 2))
            r = int(rng.integers(1, max(2, s // 12)))
            img[max(cy - r, 0): cy + r, max(cx - r, 0): cx + r] = \
                rng.uniform(0.7, 1.0, 3)
        for _ in range(2):
            img = (np.roll(img, 1, 0) + np.roll(img, -1, 0)
                   + np.roll(img, 1, 1) + np.roll(img, -1, 1) + img) / 5.0
        img = np.clip(img + rng.normal(0, 0.02, img.shape), 0, 1)
    else:
        raise ValueError(f"unknown scene kind {kind!r}")
    return np.clip(_pool2(img), 0, 1).astype(np.float32)


def scene_dataset(image_size: int = 32, per_class: int = 400,
                  seed: int = 1) -> tuple[np.ndarray, np.ndarray]:
    """[N, S, S, 3] images + one-hot-over-SCENE_CLASSES labels."""
    rng = np.random.default_rng(seed)
    xs, ys = [], []
    for ci, kind in enumerate(SCENE_CLASSES):
        for _ in range(per_class):
            xs.append(render_scene(kind, rng, image_size))
            row = np.zeros((len(SCENE_CLASSES),), np.float32)
            row[ci] = 1.0
            ys.append(row)
    return np.stack(xs), np.stack(ys)


def bundled_dataset(image_size: int = 32, per_scene: int = 400,
                    seed: int = 1) -> tuple[
    tuple[np.ndarray, np.ndarray], tuple[np.ndarray, np.ndarray], list[str]
]:
    """Digits + procedural scenes in ONE label space: the bundled
    offline model keeps the real-scan digit head and gains scene/kind
    classes a photo library actually benefits from."""
    (dtr_x, dtr_y), (dev_x, dev_y), digit_classes = \
        digits_demo_dataset(image_size)
    sx, sy = scene_dataset(image_size, per_scene, seed)
    classes = digit_classes + SCENE_CLASSES
    n_d, n_s = len(digit_classes), len(SCENE_CLASSES)

    def widen(y, off, total):
        out = np.zeros((y.shape[0], total), np.float32)
        out[:, off:off + y.shape[1]] = y
        return out

    rng = np.random.default_rng(seed + 1)
    order = rng.permutation(sx.shape[0])
    split = int(sx.shape[0] * 0.9)
    tr_x = np.concatenate([dtr_x, sx[order[:split]]])
    tr_y = np.concatenate([widen(dtr_y, 0, n_d + n_s),
                           widen(sy[order[:split]], n_d, n_d + n_s)])
    ev_x = np.concatenate([dev_x, sx[order[split:]]])
    ev_y = np.concatenate([widen(dev_y, 0, n_d + n_s),
                           widen(sy[order[split:]], n_d, n_d + n_s)])
    return (tr_x, tr_y), (ev_x, ev_y), classes


def array_batches(
    images: np.ndarray, labels: np.ndarray, batch_size: int, seed: int = 0
) -> Iterator[tuple[np.ndarray, np.ndarray]]:
    rng = np.random.default_rng(seed)
    n = images.shape[0]
    if n == 0:
        raise ValueError("empty dataset")
    batch_size = min(batch_size, n)
    while True:
        order = rng.permutation(n)
        for off in range(0, n - batch_size + 1, batch_size):
            idx = order[off:off + batch_size]
            yield images[idx], labels[idx]
