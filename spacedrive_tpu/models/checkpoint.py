"""Labeler checkpoint artifacts — save/load trained LabelerNet weights.

The reference gates labeling on a provisioned model artifact: it
downloads a versioned YOLOv8 `.onnx` into the node data dir before the
labeler can run (ref:crates/ai/src/image_labeler/model/yolov8.rs:45-88,
ref:core/src/node/config.rs `image_labeler_version`). This module is
the same contract for the TPU-native model: a single `.npz` file
holding flattened params plus a JSON header recording the architecture
(widths/depths/image_size) and the class vocabulary, so inference can
reconstruct the exact network. Inference NEVER runs from randomly
initialized weights — no artifact, no labels.
"""

from __future__ import annotations

import json
import os
from typing import Any

import numpy as np

_META_KEY = "__meta__"


def _flatten(tree: Any, prefix: str = "") -> dict[str, np.ndarray]:
    out: dict[str, np.ndarray] = {}
    if isinstance(tree, dict):
        for k, v in tree.items():
            out.update(_flatten(v, f"{prefix}{k}/"))
    else:
        out[prefix.rstrip("/")] = np.asarray(tree)
    return out


def _unflatten(flat: dict[str, np.ndarray]) -> dict[str, Any]:
    tree: dict[str, Any] = {}
    for path, arr in flat.items():
        parts = path.split("/")
        node = tree
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = arr
    return tree


def save(path: str | os.PathLike, params: Any, *, classes: list[str],
         image_size: int, widths: list[int] | tuple[int, ...],
         depths: list[int] | tuple[int, ...],
         extra: dict[str, Any] | None = None) -> None:
    """Write params + architecture metadata as one .npz artifact."""
    path = os.fspath(path)
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    flat = _flatten(params)
    meta = {
        "format": "spacedrive-labeler-v1",
        "classes": list(classes),
        "image_size": int(image_size),
        "widths": [int(w) for w in widths],
        "depths": [int(d) for d in depths],
        **(extra or {}),
    }
    flat[_META_KEY] = np.frombuffer(
        json.dumps(meta).encode("utf-8"), np.uint8
    ).copy()
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        np.savez(f, **flat)
    os.replace(tmp, path)


def load(path: str | os.PathLike) -> tuple[dict[str, Any], dict[str, Any]]:
    """Read a checkpoint → (params pytree, meta dict)."""
    with np.load(os.fspath(path)) as z:
        flat = {k: z[k] for k in z.files}
    raw = flat.pop(_META_KEY, None)
    if raw is None:
        raise ValueError(f"{path}: not a labeler checkpoint (missing meta)")
    meta = json.loads(bytes(raw.tobytes()).decode("utf-8"))
    if meta.get("format") != "spacedrive-labeler-v1":
        raise ValueError(f"{path}: unknown checkpoint format {meta.get('format')}")
    return _unflatten(flat), meta
