"""Image labeler — the framework's flagship TPU model.

Role parity with the reference's `sd-ai` image labeler, which runs a
YOLOv8 ONNX model over library images and writes `label` /
`label_on_object` rows (ref:crates/ai/src/image_labeler/actor.rs:67-73,
model download ref:crates/ai/src/image_labeler/model/yolov8.rs:45-88).
The reference treats detection boxes only as a label source — every
class whose confidence clears a threshold becomes a text label — so the
TPU-native model is a multi-label classifier over the same 80-class
vocabulary, built conv-first for the MXU:

- NHWC convs with channel counts in multiples of 128 at the deep stages
  (MXU tile alignment), bfloat16 activations, float32 params.
- No data-dependent control flow; the whole forward is one XLA program.
- Mesh-shardable: batch over `dp`, channels over `tp`, params optionally
  over `fsdp`. `shardings()` returns PartitionSpec pytrees for pjit.
"""

from __future__ import annotations

import functools
from typing import Any, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp
import optax
from jax.sharding import PartitionSpec as P

# The 80-class COCO vocabulary YOLOv8 ships with — the reference maps
# detections to these names as searchable labels.
LABEL_CLASSES = (
    "person", "bicycle", "car", "motorcycle", "airplane", "bus", "train",
    "truck", "boat", "traffic light", "fire hydrant", "stop sign",
    "parking meter", "bench", "bird", "cat", "dog", "horse", "sheep",
    "cow", "elephant", "bear", "zebra", "giraffe", "backpack", "umbrella",
    "handbag", "tie", "suitcase", "frisbee", "skis", "snowboard",
    "sports ball", "kite", "baseball bat", "baseball glove", "skateboard",
    "surfboard", "tennis racket", "bottle", "wine glass", "cup", "fork",
    "knife", "spoon", "bowl", "banana", "apple", "sandwich", "orange",
    "broccoli", "carrot", "hot dog", "pizza", "donut", "cake", "chair",
    "couch", "potted plant", "bed", "dining table", "toilet", "tv",
    "laptop", "mouse", "remote", "keyboard", "cell phone", "microwave",
    "oven", "toaster", "sink", "refrigerator", "book", "clock", "vase",
    "scissors", "teddy bear", "hair drier", "toothbrush",
)

NUM_CLASSES = len(LABEL_CLASSES)
DEFAULT_IMAGE_SIZE = 224


class ConvBlock(nn.Module):
    """Conv → GroupNorm → SiLU, bfloat16 compute."""

    features: int
    strides: int = 1

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        x = nn.Conv(
            self.features, (3, 3), strides=(self.strides, self.strides),
            padding="SAME", use_bias=False, dtype=jnp.bfloat16,
        )(x)
        x = nn.GroupNorm(num_groups=min(32, self.features // 4), dtype=jnp.bfloat16)(x)
        return nn.silu(x)


class Bottleneck(nn.Module):
    """Residual pair of 3×3 convs (the YOLO-family bottleneck shape)."""

    features: int

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        y = ConvBlock(self.features)(x)
        y = ConvBlock(self.features)(y)
        return x + y


class LabelerNet(nn.Module):
    """Multi-label image classifier over the 80-class label vocabulary.

    Stage widths keep deep channels at 128/256 so matmuls land on full
    MXU tiles; a 224×224×3 input runs stem stride 2 then 4 stages of
    stride-2 downsampling to a 7×7×256 map.
    """

    num_classes: int = NUM_CLASSES
    widths: Sequence[int] = (32, 64, 128, 256, 256)
    depths: Sequence[int] = (1, 2, 2, 1)

    @nn.compact
    def __call__(self, images: jax.Array) -> jax.Array:
        """images: float[B, H, W, 3] in [0, 1] → logits float32[B, C]."""
        x = images.astype(jnp.bfloat16)
        x = ConvBlock(self.widths[0], strides=2)(x)
        for width, depth in zip(self.widths[1:], self.depths):
            x = ConvBlock(width, strides=2)(x)
            for _ in range(depth):
                x = Bottleneck(width)(x)
        x = jnp.mean(x, axis=(1, 2))  # global average pool
        x = nn.Dense(512, dtype=jnp.bfloat16)(x)
        x = nn.silu(x)
        logits = nn.Dense(self.num_classes, dtype=jnp.bfloat16)(x)
        return logits.astype(jnp.float32)


def param_shardings(params: Any, mesh_axes: tuple[str, ...] = ("fsdp", "tp")) -> Any:
    """PartitionSpec pytree: last (output-channel) dim over `tp`, the
    penultimate over `fsdp`; small tensors replicated."""
    fsdp, tp = mesh_axes

    def spec(p: jax.Array) -> P:
        if p.ndim >= 2 and p.shape[-1] % 2 == 0:
            if p.ndim >= 2 and p.shape[-2] % 2 == 0 and p.shape[-2] >= 8:
                return P(*([None] * (p.ndim - 2)), fsdp, tp)
            return P(*([None] * (p.ndim - 1)), tp)
        return P()

    return jax.tree.map(spec, params)


def init_params(rng: jax.Array, image_size: int = DEFAULT_IMAGE_SIZE, model: LabelerNet | None = None) -> Any:
    model = model or LabelerNet()
    dummy = jnp.zeros((1, image_size, image_size, 3), jnp.float32)
    return model.init(rng, dummy)["params"]


def create_train_state(rng: jax.Array, image_size: int = DEFAULT_IMAGE_SIZE,
                       learning_rate: float = 1e-3, model: LabelerNet | None = None):
    """(params, opt_state, tx) for the labeler fine-tuning loop."""
    model = model or LabelerNet()
    params = init_params(rng, image_size, model)
    tx = optax.adamw(learning_rate)
    return params, tx.init(params), tx


def loss_fn(model: LabelerNet, params: Any, images: jax.Array, labels: jax.Array) -> jax.Array:
    """Multi-label sigmoid BCE (labels: float[B, C] in {0,1})."""
    logits = model.apply({"params": params}, images)
    return optax.sigmoid_binary_cross_entropy(logits, labels).mean()


def train_step(model: LabelerNet, tx: optax.GradientTransformation, params: Any,
               opt_state: Any, images: jax.Array, labels: jax.Array):
    """One SGD step; pure function of its inputs, jit/pjit it at the call
    site with whatever mesh shardings the host chose."""
    loss, grads = jax.value_and_grad(functools.partial(loss_fn, model))(params, images, labels)
    updates, opt_state = tx.update(grads, opt_state, params)
    params = optax.apply_updates(params, updates)
    return params, opt_state, loss


def infer_step(model: LabelerNet, params: Any, images: jax.Array,
               threshold: float = 0.35) -> tuple[jax.Array, jax.Array]:
    """(probs float32[B, C], mask bool[B, C]) — mask selects emitted
    labels, mirroring the reference's confidence cut before writing
    `label` rows."""
    probs = jax.nn.sigmoid(model.apply({"params": params}, images))
    return probs, probs >= threshold
