"""Minimal ONNX protobuf codec — no `onnx` dependency.

The reference runs its image labeler from a downloaded YOLOv8 `.onnx`
file through ONNX Runtime (ref:crates/ai/src/image_labeler/model/
yolov8.rs:37-88, ref:crates/ai/Cargo.toml:45-68). This module gives the
TPU-native framework the same artifact compatibility: it decodes the
ONNX protobuf wire format (the public, frozen `onnx.proto` schema —
field numbers below are copied from that spec) into plain dicts that
`onnx_runtime.py` executes with JAX. An encoder is included so tests
can construct genuine ONNX bytes and so models can be exported.

Only the message subset a vision model needs is implemented: Model,
Graph, Node, Attribute, Tensor, ValueInfo and friends.
"""

from __future__ import annotations

import struct
from typing import Any

import numpy as np

# --- protobuf wire primitives ---------------------------------------------

_WIRE_VARINT = 0
_WIRE_FIXED64 = 1
_WIRE_LEN = 2
_WIRE_FIXED32 = 5


def _read_varint(buf: bytes, pos: int) -> tuple[int, int]:
    result = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7
        if shift > 70:
            raise ValueError("varint too long")


def _write_varint(out: bytearray, value: int) -> None:
    if value < 0:
        value &= (1 << 64) - 1  # two's-complement 64-bit, per proto spec
    while True:
        b = value & 0x7F
        value >>= 7
        if value:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def _signed64(value: int) -> int:
    return value - (1 << 64) if value >= (1 << 63) else value


# --- schema-driven decode/encode ------------------------------------------
#
# A schema is {field_no: (name, kind)} where kind is one of
#   "int"    varint int64
#   "float"  fixed32 float
#   "bytes"  length-delimited bytes
#   "str"    length-delimited utf-8
#   "ints"   repeated varint (packed or not)
#   "floats" repeated fixed32 (packed or not)
#   "bytes*" repeated bytes
#   "str*"   repeated string
#   ("msg", schema)   embedded message
#   ("msg*", schema)  repeated embedded message
# Schemas may be mutated after definition to close recursive loops
# (Attribute ↔ Graph).

Schema = dict[int, tuple[str, Any]]


def decode_message(buf: bytes, schema: Schema) -> dict[str, Any]:
    msg: dict[str, Any] = {}
    pos = 0
    end = len(buf)
    while pos < end:
        key, pos = _read_varint(buf, pos)
        field_no, wire = key >> 3, key & 7
        spec = schema.get(field_no)
        # read the raw payload first so unknown fields skip cleanly
        if wire == _WIRE_VARINT:
            raw, pos = _read_varint(buf, pos)
        elif wire == _WIRE_FIXED64:
            raw = buf[pos:pos + 8]
            pos += 8
        elif wire == _WIRE_LEN:
            n, pos = _read_varint(buf, pos)
            raw = buf[pos:pos + n]
            pos += n
        elif wire == _WIRE_FIXED32:
            raw = buf[pos:pos + 4]
            pos += 4
        else:
            raise ValueError(f"unsupported wire type {wire}")
        if spec is None:
            continue
        name, kind = spec
        if kind == "int":
            msg[name] = _signed64(raw) if isinstance(raw, int) else raw
        elif kind == "float":
            msg[name] = struct.unpack("<f", raw)[0]
        elif kind == "bytes":
            msg[name] = bytes(raw)
        elif kind == "str":
            msg[name] = raw.decode("utf-8")
        elif kind == "ints":
            lst = msg.setdefault(name, [])
            if wire == _WIRE_VARINT:
                lst.append(_signed64(raw))
            else:  # packed
                p = 0
                while p < len(raw):
                    v, p = _read_varint(raw, p)
                    lst.append(_signed64(v))
        elif kind == "floats":
            lst = msg.setdefault(name, [])
            if wire == _WIRE_FIXED32:
                lst.append(struct.unpack("<f", raw)[0])
            else:  # packed
                lst.extend(struct.unpack(f"<{len(raw) // 4}f", raw))
        elif kind == "bytes*":
            msg.setdefault(name, []).append(bytes(raw))
        elif kind == "str*":
            msg.setdefault(name, []).append(raw.decode("utf-8"))
        elif isinstance(kind, tuple) and kind[0] == "msg":
            msg[name] = decode_message(raw, kind[1])
        elif isinstance(kind, tuple) and kind[0] == "msg*":
            msg.setdefault(name, []).append(decode_message(raw, kind[1]))
        else:
            raise ValueError(f"bad schema kind {kind!r}")
    return msg


def encode_message(msg: dict[str, Any], schema: Schema) -> bytes:
    out = bytearray()
    by_name = {spec[0]: (no, spec[1]) for no, spec in schema.items()}
    for name, value in msg.items():
        if value is None:
            continue
        field_no, kind = by_name[name]
        if kind == "int":
            _write_varint(out, field_no << 3 | _WIRE_VARINT)
            _write_varint(out, int(value))
        elif kind == "float":
            _write_varint(out, field_no << 3 | _WIRE_FIXED32)
            out += struct.pack("<f", float(value))
        elif kind in ("bytes", "str"):
            data = value.encode("utf-8") if isinstance(value, str) else bytes(value)
            _write_varint(out, field_no << 3 | _WIRE_LEN)
            _write_varint(out, len(data))
            out += data
        elif kind == "ints":
            for v in value:  # unpacked: simplest, always valid
                _write_varint(out, field_no << 3 | _WIRE_VARINT)
                _write_varint(out, int(v))
        elif kind == "floats":
            for v in value:
                _write_varint(out, field_no << 3 | _WIRE_FIXED32)
                out += struct.pack("<f", float(v))
        elif kind in ("bytes*", "str*"):
            for v in value:
                data = v.encode("utf-8") if isinstance(v, str) else bytes(v)
                _write_varint(out, field_no << 3 | _WIRE_LEN)
                _write_varint(out, len(data))
                out += data
        elif isinstance(kind, tuple) and kind[0] == "msg":
            data = encode_message(value, kind[1])
            _write_varint(out, field_no << 3 | _WIRE_LEN)
            _write_varint(out, len(data))
            out += data
        elif isinstance(kind, tuple) and kind[0] == "msg*":
            for v in value:
                data = encode_message(v, kind[1])
                _write_varint(out, field_no << 3 | _WIRE_LEN)
                _write_varint(out, len(data))
                out += data
        else:
            raise ValueError(f"bad schema kind {kind!r}")
    return bytes(out)


# --- ONNX message schemas (field numbers from the public onnx.proto) ------

TENSOR_SCHEMA: Schema = {
    1: ("dims", "ints"),
    2: ("data_type", "int"),
    4: ("float_data", "floats"),
    5: ("int32_data", "ints"),
    6: ("string_data", "bytes*"),
    7: ("int64_data", "ints"),
    8: ("name", "str"),
    9: ("raw_data", "bytes"),
}

_DIM_SCHEMA: Schema = {
    1: ("dim_value", "int"),
    2: ("dim_param", "str"),
}

_SHAPE_SCHEMA: Schema = {
    1: ("dim", ("msg*", _DIM_SCHEMA)),
}

_TENSOR_TYPE_SCHEMA: Schema = {
    1: ("elem_type", "int"),
    2: ("shape", ("msg", _SHAPE_SCHEMA)),
}

_TYPE_SCHEMA: Schema = {
    1: ("tensor_type", ("msg", _TENSOR_TYPE_SCHEMA)),
}

VALUE_INFO_SCHEMA: Schema = {
    1: ("name", "str"),
    2: ("type", ("msg", _TYPE_SCHEMA)),
}

# Attribute and Graph are mutually recursive; close the loop below.
ATTRIBUTE_SCHEMA: Schema = {
    1: ("name", "str"),
    2: ("f", "float"),
    3: ("i", "int"),
    4: ("s", "bytes"),
    5: ("t", ("msg", TENSOR_SCHEMA)),
    7: ("floats", "floats"),
    8: ("ints", "ints"),
    9: ("strings", "bytes*"),
    10: ("tensors", ("msg*", TENSOR_SCHEMA)),
    20: ("type", "int"),
}

NODE_SCHEMA: Schema = {
    1: ("input", "str*"),
    2: ("output", "str*"),
    3: ("name", "str"),
    4: ("op_type", "str"),
    5: ("attribute", ("msg*", ATTRIBUTE_SCHEMA)),
    7: ("domain", "str"),
}

GRAPH_SCHEMA: Schema = {
    1: ("node", ("msg*", NODE_SCHEMA)),
    2: ("name", "str"),
    5: ("initializer", ("msg*", TENSOR_SCHEMA)),
    11: ("input", ("msg*", VALUE_INFO_SCHEMA)),
    12: ("output", ("msg*", VALUE_INFO_SCHEMA)),
    13: ("value_info", ("msg*", VALUE_INFO_SCHEMA)),
}

ATTRIBUTE_SCHEMA[6] = ("g", ("msg", GRAPH_SCHEMA))
ATTRIBUTE_SCHEMA[11] = ("graphs", ("msg*", GRAPH_SCHEMA))

_OPSET_SCHEMA: Schema = {
    1: ("domain", "str"),
    2: ("version", "int"),
}

MODEL_SCHEMA: Schema = {
    1: ("ir_version", "int"),
    2: ("producer_name", "str"),
    3: ("producer_version", "str"),
    5: ("model_version", "int"),
    7: ("graph", ("msg", GRAPH_SCHEMA)),
    8: ("opset_import", ("msg*", _OPSET_SCHEMA)),
}

# TensorProto.DataType values (public onnx.proto enum)
_DTYPES: dict[int, np.dtype] = {
    1: np.dtype(np.float32),
    2: np.dtype(np.uint8),
    3: np.dtype(np.int8),
    4: np.dtype(np.uint16),
    5: np.dtype(np.int16),
    6: np.dtype(np.int32),
    7: np.dtype(np.int64),
    9: np.dtype(np.bool_),
    10: np.dtype(np.float16),
    11: np.dtype(np.float64),
    12: np.dtype(np.uint32),
    13: np.dtype(np.uint64),
}
_DTYPE_CODES = {v: k for k, v in _DTYPES.items()}


def tensor_to_array(tensor: dict[str, Any]) -> np.ndarray:
    """TensorProto dict → numpy array."""
    code = tensor.get("data_type", 1)
    if code == 16:  # BFLOAT16: raw 16-bit payloads; upcast to float32
        raw = np.frombuffer(tensor.get("raw_data", b""), "<u2")
        out = (raw.astype(np.uint32) << 16).view(np.float32)
        return out.reshape(tensor.get("dims", []))
    dtype = _DTYPES.get(code)
    if dtype is None:
        raise ValueError(f"unsupported tensor data_type {code}")
    dims = tensor.get("dims", [])
    if "raw_data" in tensor and tensor["raw_data"] != b"":
        arr = np.frombuffer(tensor["raw_data"], dtype.newbyteorder("<"))
    elif code == 1 and "float_data" in tensor:
        arr = np.asarray(tensor["float_data"], np.float32)
    elif code == 7 and "int64_data" in tensor:
        arr = np.asarray(tensor["int64_data"], np.int64)
    elif code in (2, 3, 4, 5, 6, 9, 10) and "int32_data" in tensor:
        arr = np.asarray(tensor["int32_data"], np.int32).astype(dtype)
    else:
        arr = np.zeros(0, dtype)
    return arr.reshape(dims).astype(dtype, copy=False)


def array_to_tensor(name: str, arr: np.ndarray) -> dict[str, Any]:
    """numpy array → TensorProto dict (raw_data encoding)."""
    arr = np.asarray(arr)  # NOT ascontiguousarray: it promotes 0-d to (1,)
    code = _DTYPE_CODES.get(arr.dtype)
    if code is None:
        raise ValueError(f"unsupported numpy dtype {arr.dtype}")
    return {
        "name": name,
        "dims": list(arr.shape),
        "data_type": code,
        "raw_data": arr.astype(arr.dtype.newbyteorder("<")).tobytes(),
    }


# --- builder API (tests + export) -----------------------------------------


def make_attribute(name: str, value: Any) -> dict[str, Any]:
    if isinstance(value, bool):
        return {"name": name, "type": 2, "i": int(value)}
    if isinstance(value, int):
        return {"name": name, "type": 2, "i": value}
    if isinstance(value, float):
        return {"name": name, "type": 1, "f": value}
    if isinstance(value, str):
        return {"name": name, "type": 3, "s": value.encode()}
    if isinstance(value, bytes):
        return {"name": name, "type": 3, "s": value}
    if isinstance(value, np.ndarray):
        return {"name": name, "type": 4, "t": array_to_tensor(name, value)}
    if isinstance(value, (list, tuple)):
        if all(isinstance(v, int) for v in value):
            return {"name": name, "type": 7, "ints": list(value)}
        if all(isinstance(v, (int, float)) for v in value):
            return {"name": name, "type": 6, "floats": [float(v) for v in value]}
        if all(isinstance(v, str) for v in value):
            return {"name": name, "type": 8, "strings": [v.encode() for v in value]}
    raise ValueError(f"cannot infer attribute type for {name}={value!r}")


def make_node(op_type: str, inputs: list[str], outputs: list[str],
              name: str = "", **attrs: Any) -> dict[str, Any]:
    return {
        "op_type": op_type,
        "input": list(inputs),
        "output": list(outputs),
        "name": name or f"{op_type}_{outputs[0]}",
        "attribute": [make_attribute(k, v) for k, v in attrs.items()],
    }


def make_value_info(name: str, shape: tuple[int, ...],
                    elem_type: int = 1) -> dict[str, Any]:
    return {
        "name": name,
        "type": {"tensor_type": {
            "elem_type": elem_type,
            "shape": {"dim": [{"dim_value": int(d)} for d in shape]},
        }},
    }


def make_model(nodes: list[dict], inputs: list[dict], outputs: list[dict],
               initializers: dict[str, np.ndarray] | None = None,
               opset: int = 17, name: str = "graph") -> dict[str, Any]:
    return {
        "ir_version": 8,
        "producer_name": "spacedrive_tpu",
        "opset_import": [{"domain": "", "version": opset}],
        "graph": {
            "name": name,
            "node": nodes,
            "input": inputs,
            "output": outputs,
            "initializer": [
                array_to_tensor(k, v) for k, v in (initializers or {}).items()
            ],
        },
    }


def encode_model(model: dict[str, Any]) -> bytes:
    return encode_message(model, MODEL_SCHEMA)


def decode_model(buf: bytes) -> dict[str, Any]:
    return decode_message(buf, MODEL_SCHEMA)
