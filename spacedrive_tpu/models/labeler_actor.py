"""ImageLabeler actor — batched, resumable labeling over library images.

Parity: ref:crates/ai/src/image_labeler/actor.rs — a node-global actor
fed `new_batch(library, entries)` (actor.rs:202), decoding images on
CPU, running the model in batches, and writing `label` +
`label_on_object` rows per object (actor.rs:67-73, 291); pending
batches persist to `to_resume_batches.bin` across restarts
(actor.rs:73-99). The model itself is the JAX LabelerNet
(models/labeler.py) instead of YOLOv8-ONNX: images resize to 224² on
device via the thumbnail resize path's PIL decode, batch as
[B, 224, 224, 3] float32, and every class whose sigmoid clears the
threshold becomes a text label (model/yolov8.rs maps detections to
class-name labels the same way).
"""

from __future__ import annotations

import asyncio
import collections
import itertools
import json
import logging
import os
import secrets
import uuid
from dataclasses import dataclass, field
from typing import Any

import msgpack
import numpy as np

from ..db.database import new_pub_id, now_iso
from . import labeler as labeler_model

logger = logging.getLogger(__name__)

RESUME_FILE = "to_resume_batches.bin"  # ref:actor.rs:92
DEFAULT_BATCH_SIZE = 16
PENDING_LABELS_THRESHOLD = 0.35


@dataclass
class Batch:
    library_id: str
    entries: list[dict[str, Any]]  # {file_path_id, object_id, path}
    id: int = 0


class ImageLabeler:
    """`Node.image_labeler` (ref:crates/ai `ImageLabeler`)."""

    def __init__(
        self,
        data_dir: str | os.PathLike,
        *,
        use_device: bool = True,
        batch_size: int = DEFAULT_BATCH_SIZE,
        threshold: float = PENDING_LABELS_THRESHOLD,
        image_size: int = labeler_model.DEFAULT_IMAGE_SIZE,
    ):
        self.data_dir = os.fspath(data_dir)
        os.makedirs(self.data_dir, exist_ok=True)
        self.use_device = use_device
        self.batch_size = batch_size
        self.threshold = threshold
        self.image_size = image_size
        self._queue: collections.deque[Batch] = collections.deque()
        self._work: asyncio.Event | None = None  # set when queue non-empty
        self._batch_ids = itertools.count((secrets.randbits(40) << 20) | 1)
        self._batch_pending: dict[int, int] = {}
        self._libraries: dict[str, Any] = {}
        self._cond: asyncio.Condition | None = None
        self._worker: asyncio.Task | None = None
        self._stopped = False
        self.labeled = 0
        self.errors = 0
        self.skipped = 0  # entries completed with labeling disabled
        self.classes: list[str] = list(labeler_model.LABEL_CLASSES)
        self._params = None
        self._model = None
        self._infer = None
        self._disabled = False
        self._inflight: Batch | None = None
        # crash recovery (ref:actor.rs:73-99): batches persisted at
        # shutdown re-queue, keyed to libraries that re-register; the
        # file stays on disk (re-persisted, never just deleted) so a
        # crash before completion still resumes next boot
        self._resume_raw: list[dict[str, Any]] = []
        path = os.path.join(self.data_dir, RESUME_FILE)
        if os.path.exists(path):
            try:
                with open(path, "rb") as f:
                    self._resume_raw = msgpack.unpackb(f.read(), raw=False)
            except Exception:
                logger.exception("failed to load %s", RESUME_FILE)
                os.remove(path)

    # --- model ----------------------------------------------------------
    #
    # The reference only labels once a model artifact is provisioned
    # (it downloads versioned YOLOv8 .onnx before the actor can run,
    # ref:crates/ai/src/image_labeler/model/yolov8.rs:45-88). Same
    # contract here: weights.npz (trained LabelerNet checkpoint,
    # models/checkpoint.py) or model.onnx (any ONNX classifier/YOLO
    # head, models/onnx_runtime.py) in the actor data dir. Without an
    # artifact the actor completes batches WITHOUT writing rows —
    # random-weight inference would write noise labels.

    def resolve_artifact(self) -> tuple[str, str] | None:
        """(kind, path) of the provisioned model artifact, or None."""
        onnx_path = os.environ.get("SD_LABELER_ONNX") or os.path.join(
            self.data_dir, "model.onnx"
        )
        if os.path.exists(onnx_path):
            return ("onnx", onnx_path)
        ckpt_path = os.environ.get("SD_LABELER_CKPT") or os.path.join(
            self.data_dir, "weights.npz"
        )
        if os.path.exists(ckpt_path):
            return ("checkpoint", ckpt_path)
        return None

    def _ensure_model(self) -> bool:
        """Load the provisioned artifact; False = labeling disabled.

        Re-resolves on every call so an artifact provisioned while the
        node is running (e.g. `sdx labeler train` against a live
        `sdx serve` data dir) enables labeling without a restart.
        """
        if self._infer is not None:
            return True
        artifact = self.resolve_artifact()
        if artifact is None:
            if not self._disabled:  # warn once per disabled episode
                logger.warning(
                    "image labeler disabled: no model artifact (weights.npz "
                    "checkpoint or model.onnx) in %s — batches will complete "
                    "without writing labels", self.data_dir,
                )
            self._disabled = True
            return False
        self._disabled = False
        kind, path = artifact
        if kind == "onnx":
            self._load_onnx(path)
        else:
            self._load_checkpoint(path)
        logger.info(
            "image labeler: loaded %s artifact %s (%d classes, %d px)",
            kind, path, len(self.classes), self.image_size,
        )
        return True

    def _load_checkpoint(self, path: str) -> None:
        import jax

        from . import checkpoint

        params, meta = checkpoint.load(path)
        self.classes = list(meta["classes"])
        self.image_size = int(meta["image_size"])
        self._model = labeler_model.LabelerNet(
            num_classes=len(self.classes),
            widths=tuple(meta["widths"]),
            depths=tuple(meta["depths"]),
        )
        device = jax.devices()[0] if self.use_device else jax.devices("cpu")[0]
        self._params = jax.device_put(params, device)
        model = self._model

        @jax.jit
        def infer(params, images):
            return jax.nn.sigmoid(model.apply({"params": params}, images))

        params_ref = self._params
        self._infer = lambda images: infer(params_ref, images)

    def _load_onnx(self, path: str) -> None:
        import jax
        import jax.numpy as jnp

        from . import onnx_runtime

        model = onnx_runtime.load(path)
        shapes = model.input_shapes()
        in_shape = shapes.get(model.inputs[0]) if model.inputs else None
        if in_shape and len(in_shape) == 4:
            if in_shape[2] and in_shape[2] > 0:
                self.image_size = int(in_shape[2])
            if in_shape[0] and in_shape[0] > 0:
                self.batch_size = int(in_shape[0])
        self.classes = list(labeler_model.LABEL_CLASSES)

        def run(images):
            """float[B, H, W, 3] in [0,1] → probs float[B, C]."""
            x = jnp.transpose(images, (0, 3, 1, 2))  # ONNX vision = NCHW
            out = model(x)[0]
            if out.ndim == 3:
                # YOLO-family head. Channel dim is far smaller than the
                # anchor dim (e.g. 84 vs 8400); detect the layout from
                # static shapes rather than assuming one export style.
                d1, d2 = int(out.shape[1]), int(out.shape[2])
                if d1 < d2:
                    # v8 export [B, 4+C, anchors]: class scores are
                    # post-sigmoid; a label's confidence is its best
                    # anchor (the reference keeps any class clearing
                    # the threshold, actor.rs:291)
                    return jnp.max(out[:, 4:, :], axis=-1)
                # v5-style export [B, anchors, 5+C]: obj conf at 4,
                # class probs from 5; score = obj * cls, best anchor
                obj = out[:, :, 4:5]
                return jnp.max(obj * out[:, :, 5:], axis=1)
            return jax.nn.sigmoid(out)  # rank-2 classifier logits

        jitted = jax.jit(run)
        self._infer = jitted
        # YOLO class count may differ from the default vocabulary
        probe = np.zeros(
            (self.batch_size, self.image_size, self.image_size, 3), np.float32
        )
        n_classes = int(jax.eval_shape(run, probe).shape[1])
        if n_classes != len(self.classes):
            self.classes = [f"class {i}" for i in range(n_classes)]
        # provisioned class names (models/provision.py) override the
        # positional defaults when the cardinality matches
        names_path = os.path.join(self.data_dir, "classes.json")
        if os.path.exists(names_path):
            try:
                with open(names_path) as f:
                    names = json.load(f)
                if isinstance(names, list) and len(names) == n_classes:
                    self.classes = [str(c) for c in names]
                else:
                    logger.warning(
                        "classes.json has %s names but the model has %d "
                        "classes; ignoring", len(names), n_classes,
                    )
            except Exception:  # noqa: BLE001 - names are advisory
                logger.exception("unreadable classes.json; ignoring")

    # --- API (ref:actor.rs new_batch / resume) --------------------------

    def register_library(self, library: Any) -> None:
        """Libraries announce themselves so resumed batches can bind."""
        self._libraries[str(library.id)] = library
        for raw in [r for r in self._resume_raw if r["library_id"] == str(library.id)]:
            self._resume_raw.remove(raw)
            self.new_batch(library, raw["entries"])

    def new_batch(self, library: Any, entries: list[dict[str, Any]]) -> int:
        entries = [e for e in entries if e.get("object_id") is not None]
        if not entries:
            return 0
        self._libraries[str(library.id)] = library
        batch = Batch(library_id=str(library.id), entries=entries)
        batch.id = next(self._batch_ids)
        self._queue.append(batch)
        self._batch_pending[batch.id] = len(entries)
        self._persist()
        self._ensure_started()
        if self._work is not None:
            self._work.set()
        return batch.id

    async def wait_batch(self, batch_id: int) -> None:
        if batch_id == 0:
            return
        self._ensure_started()
        assert self._cond is not None
        async with self._cond:
            await self._cond.wait_for(
                lambda: self._batch_pending.get(batch_id, 0) == 0
            )

    # --- lifecycle ------------------------------------------------------

    def _ensure_started(self) -> None:
        if self._stopped:
            return
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            return
        if self._cond is None:
            self._cond = asyncio.Condition()
        if self._work is None:
            self._work = asyncio.Event()
        if self._queue:
            self._work.set()
        if self._worker is None or self._worker.done():
            self._worker = loop.create_task(self._run(), name="image-labeler")

    async def shutdown(self) -> None:
        self._stopped = True
        if self._worker is not None:
            self._worker.cancel()
            try:
                await self._worker
            except (asyncio.CancelledError, Exception):
                pass
        self._persist()

    def _persist(self) -> None:
        path = os.path.join(self.data_dir, RESUME_FILE)
        batches = list(self._queue)
        if self._inflight is not None:
            batches.insert(0, self._inflight)
        pending = [
            {"library_id": b.library_id, "entries": b.entries}
            for b in batches
        ] + self._resume_raw
        if not pending:
            if os.path.exists(path):
                os.remove(path)
            return
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(msgpack.packb(pending, use_bin_type=True))
        os.replace(tmp, path)

    # --- worker ---------------------------------------------------------

    async def _run(self) -> None:
        assert self._work is not None
        while not self._stopped:
            if not self._queue:
                self._work.clear()
                await self._work.wait()
                continue
            batch = self._queue.popleft()
            self._inflight = batch  # stays in the resume file until done
            try:
                await self._process(batch)
            except asyncio.CancelledError:
                # shutdown mid-batch: keep it in the resume file
                # (_inflight still set) for the next boot
                self._persist()
                raise
            except Exception:
                logger.exception("labeler batch %d failed", batch.id)
                self.errors += len(batch.entries)
            self._inflight = None
            self._persist()
            assert self._cond is not None
            async with self._cond:
                self._batch_pending.pop(batch.id, None)
                self._cond.notify_all()

    async def _process(self, batch: Batch) -> None:
        library = self._libraries.get(batch.library_id)
        if library is None:
            logger.warning("labeler: unknown library %s", batch.library_id)
            return
        if not await asyncio.to_thread(self._ensure_model):
            # no provisioned model artifact: complete the batch without
            # writing rows (never infer from random weights)
            self.skipped += len(batch.entries)
            self._batch_pending[batch.id] = 0
            return
        wrote = False
        for off in range(0, len(batch.entries), self.batch_size):
            chunk = batch.entries[off : off + self.batch_size]
            decoded = await asyncio.to_thread(self._decode_chunk, chunk)
            ok = [(e, arr) for e, arr in zip(chunk, decoded) if arr is not None]
            self.errors += len(chunk) - len(ok)
            if not ok:
                continue
            images = np.stack([arr for _e, arr in ok])
            probs = await asyncio.to_thread(self._infer_chunk, images)
            await asyncio.to_thread(
                self._write_labels, library, [e for e, _ in ok], probs
            )
            wrote = True
            self._batch_pending[batch.id] = max(
                0, self._batch_pending.get(batch.id, 0) - len(chunk)
            )
        # fresh labels must reach live explorers (the sidebar Labels
        # route listens on labels.list invalidations)
        node = getattr(library, "node", None)
        if wrote and node is not None:
            from ..api.invalidate import invalidate_query

            invalidate_query(node, "labels.list", library)

    def _decode_chunk(self, chunk: list[dict[str, Any]]) -> list[np.ndarray | None]:
        # same dispatch as the thumbnailer (HEIF rides libheif, not PIL)
        from PIL import Image

        from ..object.media.images import format_image

        out: list[np.ndarray | None] = []
        for entry in chunk:
            try:
                rgba = format_image(entry["path"])
                img = Image.fromarray(rgba).convert("RGB").resize(
                    (self.image_size, self.image_size)
                )
                out.append(np.asarray(img, np.float32) / 255.0)
            except Exception:
                out.append(None)
        return out

    def _infer_chunk(self, images: np.ndarray) -> np.ndarray:
        import jax

        n = images.shape[0]
        if n < self.batch_size:
            # pad the ragged tail so every chunk hits ONE compiled program
            pad = np.zeros(
                (self.batch_size - n, *images.shape[1:]), images.dtype
            )
            images = np.concatenate([images, pad])
        if not self.use_device:
            with jax.default_device(jax.devices("cpu")[0]):
                probs = self._infer(images)
        else:
            probs = self._infer(images)
        return np.asarray(probs)[:n]

    def _write_labels(
        self, library: Any, entries: list[dict[str, Any]], probs: np.ndarray
    ) -> None:
        """label + label_on_object rows (ref:actor.rs:67-73,291)."""
        db = library.db
        for entry, row_probs in zip(entries, probs):
            names = [
                self.classes[i]
                for i in np.nonzero(row_probs >= self.threshold)[0]
            ]
            for name in names:
                label = db.find_one("label", name=name)
                label_id = (
                    label["id"]
                    if label is not None
                    else db.insert(
                        "label",
                        name=name,
                        date_created=now_iso(),
                        date_modified=now_iso(),
                    )
                )
                db.upsert(
                    "label_on_object",
                    {"label_id": label_id, "object_id": entry["object_id"]},
                )
            self.labeled += 1
