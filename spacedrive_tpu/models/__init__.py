"""TPU model zoo for the framework's ML subsystems.

The reference embeds one model family — a YOLOv8 ONNX image labeler run
through ONNX Runtime C++ (ref:crates/ai/src/lib.rs:22-77). Here the
labeler is a native flax model compiled by XLA, shardable over a device
mesh (dp/fsdp/tp), with the same role: emit text labels for images in a
library so they become searchable.
"""

from .labeler import LabelerNet, LABEL_CLASSES, create_train_state, train_step, infer_step

__all__ = [
    "LabelerNet",
    "LABEL_CLASSES",
    "create_train_state",
    "train_step",
    "infer_step",
]
