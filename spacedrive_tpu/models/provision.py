"""Labeler model provisioning — the reference's model-download flow.

The reference can't label until it fetches a versioned YOLOv8 `.onnx`
from its CDN (ref:crates/ai/src/image_labeler/model/yolov8.rs:45-88).
Parity here, generalized for offline deployments:

- `fetch(url)` downloads an ONNX classifier into the labeler dir (the
  reference's path; needs egress).
- `import_artifact(path)` installs a local `.onnx` (any classifier or
  YOLO-family head the JAX ONNX runtime executes) or a `weights.npz`
  LabelerNet checkpoint.

Every install is VALIDATED before it lands: the model is loaded and a
zero-image smoke inference runs through the actual inference path, so a
broken file can never silently disable labeling at index time. Class
names ride along in `classes.json` next to the model (consumed by
`labeler_actor._load_onnx`); YOLO-style 80-class models default to the
COCO vocabulary.
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
import tempfile
import urllib.request

# The reference pins its model by version name (yolov8.rs:45-60); the
# official ultralytics release asset is the natural default source.
DEFAULT_MODEL_URL = (
    "https://github.com/ultralytics/assets/releases/download/v8.1.0/yolov8n.onnx"
)


class ProvisionError(Exception):
    pass


def _validate_onnx(path: str) -> dict:
    """Load + smoke-infer through the real actor path; returns info.
    `path` must be named model.onnx — the probe actor resolves it from
    its directory exactly like a provisioned node would."""
    from .labeler_actor import ImageLabeler

    actor = ImageLabeler(os.path.dirname(path), use_device=False)
    if not actor._ensure_model():
        raise ProvisionError("model failed to load")
    import numpy as np

    probs = actor._infer_chunk(
        np.zeros((1, actor.image_size, actor.image_size, 3), np.float32)
    )
    if probs.ndim != 2 or probs.shape[1] != len(actor.classes):
        raise ProvisionError(
            f"smoke inference returned {probs.shape}, expected "
            f"[B, {len(actor.classes)}]"
        )
    return {
        "classes": len(actor.classes),
        "class_names": list(actor.classes),
        "image_size": actor.image_size,
        "batch_size": actor.batch_size,
    }


def _validate_checkpoint(path: str) -> dict:
    from . import checkpoint

    _params, meta = checkpoint.load(path)
    return {
        "classes": len(meta["classes"]),
        "image_size": meta["image_size"],
    }


def sha256_file(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for block in iter(lambda: f.read(1 << 20), b""):
            h.update(block)
    return h.hexdigest()


def _check_digest(path: str, sha256: str) -> None:
    actual = sha256_file(path)
    if actual.lower() != sha256.lower():
        raise ProvisionError(
            f"sha256 mismatch: artifact is {actual}, "
            f"pinned {sha256.lower()} — refusing to install"
        )


def import_artifact(
    src: str, labeler_dir: str, classes: list[str] | None = None,
    sha256: str | None = None,
) -> dict:
    """Validate `src` (.onnx or .npz) and install it as THE labeler
    artifact. Returns an info dict (kind, path, classes, …). A `sha256`
    pin is checked before any validation or install."""
    if sha256 is not None:
        _check_digest(src, sha256)
    os.makedirs(labeler_dir, exist_ok=True)
    if src.endswith(".npz"):
        if classes:
            raise ProvisionError(
                "--classes applies to ONNX imports; a checkpoint embeds "
                "its own class names"
            )
        info = _validate_checkpoint(src)
        dest = os.path.join(labeler_dir, "weights.npz")
        if os.path.abspath(src) != os.path.abspath(dest):
            shutil.copyfile(src, dest)
        # resolve_artifact prefers model.onnx — a stale one would
        # silently shadow the checkpoint just provisioned
        for stale in ("model.onnx", "classes.json"):
            p = os.path.join(labeler_dir, stale)
            if os.path.exists(p):
                os.unlink(p)
        return {"kind": "checkpoint", "path": dest, **info}

    # ONNX: validate from a scratch dir so a bad file never lands
    with tempfile.TemporaryDirectory(prefix="sd-provision-") as tmp:
        cand = os.path.join(tmp, "model.onnx")
        shutil.copyfile(src, cand)
        if classes:
            with open(os.path.join(tmp, "classes.json"), "w") as f:
                json.dump(classes, f)
        info = _validate_onnx(cand)
        if classes and len(classes) != info["classes"]:
            raise ProvisionError(
                f"model has {info['classes']} classes but --classes "
                f"names {len(classes)}"
            )
        dest = os.path.join(labeler_dir, "model.onnx")
        shutil.move(cand, dest)
        cls_dest = os.path.join(labeler_dir, "classes.json")
        if classes:
            with open(cls_dest, "w") as f:
                json.dump(classes, f)
        elif os.path.exists(cls_dest):
            os.unlink(cls_dest)  # stale names from a previous model
    return {"kind": "onnx", "path": dest, **info}


def install_bundled(labeler_dir: str) -> dict:
    """Install the in-package offline artifact (`models/bundled/`) —
    a trained digits LabelerNet — verified against its MANIFEST.json
    sha256 pin. Zero egress: this is the air-gapped answer to the
    reference's CDN download (yolov8.rs:45-88)."""
    from .make_bundled import ARTIFACT, MANIFEST

    if not (os.path.exists(ARTIFACT) and os.path.exists(MANIFEST)):
        raise ProvisionError(
            "bundled artifact missing from the package; rebuild with "
            "`python -m spacedrive_tpu.models.make_bundled`"
        )
    with open(MANIFEST) as f:
        manifest = json.load(f)
    info = import_artifact(ARTIFACT, labeler_dir, sha256=manifest["sha256"])
    info["bundled"] = {
        "sha256": manifest["sha256"],
        "metrics": manifest.get("metrics", {}),
        "classes": manifest.get("classes", []),
    }
    return info


def fetch(url: str, labeler_dir: str, classes: list[str] | None = None,
          timeout: float = 120.0, sha256: str | None = None) -> dict:
    """Download an ONNX model (the reference's provisioning path) and
    install it via `import_artifact`.

    `sha256` pins the artifact's digest: the download is rejected before
    validation if it doesn't match, mirroring the reference's
    version-pinned CDN flow (yolov8.rs pins by versioned path). Smoke
    inference alone proves the file WORKS, not that it is the file you
    meant to install — pin digests for any unauthenticated mirror."""
    os.makedirs(labeler_dir, exist_ok=True)
    tmp = tempfile.NamedTemporaryFile(suffix=".onnx", delete=False)
    try:
        try:
            with urllib.request.urlopen(url, timeout=timeout) as resp:
                shutil.copyfileobj(resp, tmp)
            tmp.close()
        except Exception as e:  # noqa: BLE001 - network envs vary
            raise ProvisionError(
                f"download failed ({e}); offline deployments can provision "
                "with `sdx labeler provision --from <model.onnx>` or train a "
                "checkpoint with `sdx labeler train`"
            ) from e
        return import_artifact(tmp.name, labeler_dir, classes=classes,
                               sha256=sha256)
    finally:
        tmp.close()
        os.unlink(tmp.name)
