"""Build the bundled offline labeler artifact.

The reference cannot label anything until it downloads YOLOv8 from a
CDN (ref:crates/ai/src/image_labeler/model/yolov8.rs:45-88); an
air-gapped install therefore never labels. This framework ships a
small trained checkpoint IN the package (`models/bundled/`) so
`sdx labeler provision --bundled` works with zero egress.

The artifact is a LabelerNet trained on two corpora that need no
network: sklearn's bundled digit scans (1,797 real 8×8 images — the
digit head) and a procedurally rendered scene/kind corpus
(`train.SCENE_CLASSES`: document scan, screenshot, line art, photo,
chart, dark photo — the statistics a file manager's content actually
has). A modest model with an honest scope — but on a real photo
library it now says "photo"/"screenshot"/"document scan" instead of
"digit 7". Same artifact contract (`weights.npz`) as any user-trained
or downloaded model.

Run `python -m spacedrive_tpu.models.make_bundled` to rebuild; it
retrains with a fixed seed, overwrites the artifact, and rewrites
`MANIFEST.json` (sha256 pin + metrics + provenance). Provisioning
verifies the pin before install.
"""

from __future__ import annotations

import json
import os

from .provision import sha256_file

BUNDLED_DIR = os.path.join(os.path.dirname(__file__), "bundled")
ARTIFACT = os.path.join(BUNDLED_DIR, "labeler_offline.npz")
MANIFEST = os.path.join(BUNDLED_DIR, "MANIFEST.json")


def build(steps: int = 1200, use_device: bool = False) -> dict:
    from . import checkpoint
    from .train import TrainConfig, array_batches, bundled_dataset, train

    cfg = TrainConfig(
        image_size=32, widths=(8, 16, 32, 32, 32), depths=(1, 1, 1, 1),
        batch_size=64, steps=steps, use_device=use_device, seed=0,
    )
    (tr_x, tr_y), (ev_x, ev_y), classes = bundled_dataset(cfg.image_size)
    params, _model, metrics = train(
        array_batches(tr_x, tr_y, cfg.batch_size, seed=cfg.seed),
        classes, cfg, eval_set=(ev_x, ev_y),
        progress=lambda step, loss: print(f"step {step}  loss {loss:.4f}"),
    )
    os.makedirs(BUNDLED_DIR, exist_ok=True)
    checkpoint.save(
        ARTIFACT, params, classes=classes, image_size=cfg.image_size,
        widths=cfg.widths, depths=cfg.depths,
        extra={"metrics": metrics,
               "trained_on": "sklearn digits (1,797 8x8 scans) + "
                             "procedural scene corpus (train.py)"},
    )
    manifest = {
        "artifact": os.path.basename(ARTIFACT),
        "sha256": sha256_file(ARTIFACT),
        "bytes": os.path.getsize(ARTIFACT),
        "classes": classes,
        "image_size": cfg.image_size,
        "steps": steps,
        "metrics": metrics,
        "rebuild": "python -m spacedrive_tpu.models.make_bundled",
    }
    with open(MANIFEST, "w") as f:
        json.dump(manifest, f, indent=2)
    return manifest


if __name__ == "__main__":
    print(json.dumps(build(), indent=2))
