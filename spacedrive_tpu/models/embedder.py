"""Deterministic JAX-native image embedder — the semantic-search trunk.

The reference's third device workload is an ONNX image model driven by
an actor (ref:crates/ai/src/image_labeler/actor.rs); its output here is
not labels but a fixed-width f32 vector per image, persisted in
`object_embedding` and replicated through the CRDT plane. Quality is
explicitly not the bar (PAPER.md reproduces the *engine*, not the
model) — determinism, shape discipline, and throughput are:

- **determinism**: weights derive from a fixed seed via a pinned
  bit-generator, so every node materializes the *same* projection and
  a replicated vector equals the locally computed one bit-for-bit.
  A provisioned checkpoint (`embedder.npz`, same artifact format as
  the labeler's) overrides the derived weights when present.
- **shape discipline**: one input shape (IMAGE_SIZE² RGB f32), one
  output shape (EMBED_DIM f32) — the dispatch layer (ops/embed_jax)
  never sees a ragged tensor.
- **the math body lives here** so the jitted single-device, shard_map
  and host programs in ops/embed_jax all close over the identical
  forward function (PR 4's tri-path parity discipline).
"""

from __future__ import annotations

import os
from typing import Any

import numpy as np

from . import checkpoint

#: fixed model vocabulary — wire format (vector width in the DB and on
#: the sync plane), not a load knob
EMBED_DIM = 128
IMAGE_SIZE = 32
PATCH = 4  # mean-pool patch edge → (IMAGE_SIZE/PATCH)² · 3 features
HIDDEN = 128
MODEL_NAME = "patchpool-v1"

ENV_VAR = "SD_EMBED"

ARTIFACT_NAME = "embedder.npz"


def enabled() -> bool:
    """SD_EMBED=0 turns the whole subsystem into a true no-op: no
    pipeline stage, no DB writes, no sync ops, no index."""
    return os.environ.get(ENV_VAR, "1") != "0"


def _derived_params() -> dict[str, np.ndarray]:
    """Seed-derived projection weights. PCG64 with a fixed seed is a
    pinned stream (numpy guarantees stream stability per bit
    generator), so every process on every node derives byte-identical
    weights — the property the replicated index leans on."""
    rng = np.random.Generator(np.random.PCG64(0))
    feat = (IMAGE_SIZE // PATCH) ** 2 * 3
    return {
        "w1": rng.standard_normal((feat, HIDDEN)).astype(np.float32)
        * np.float32(1.0 / np.sqrt(feat)),
        "b1": np.zeros((HIDDEN,), np.float32),
        "w2": rng.standard_normal((HIDDEN, EMBED_DIM)).astype(np.float32)
        * np.float32(1.0 / np.sqrt(HIDDEN)),
        "b2": np.zeros((EMBED_DIM,), np.float32),
    }


_params: dict[str, np.ndarray] | None = None


def params(models_dir: str | os.PathLike | None = None) -> dict[str, np.ndarray]:
    """The embedder weights: a provisioned `embedder.npz` checkpoint if
    one is installed, else the seed-derived projection. Cached for the
    process lifetime (first resolution wins, like the labeler's
    artifact)."""
    global _params
    if _params is not None:
        return _params
    if models_dir is not None:
        path = os.path.join(os.fspath(models_dir), ARTIFACT_NAME)
        if os.path.exists(path):
            try:
                tree, meta = checkpoint.load(path)
                if meta.get("kind") == "embedder" and all(
                    k in tree for k in ("w1", "b1", "w2", "b2")
                ):
                    _params = {
                        k: np.asarray(tree[k], np.float32)
                        for k in ("w1", "b1", "w2", "b2")
                    }
                    return _params
            except (OSError, ValueError):
                pass  # corrupt artifact → derived weights still work
    _params = _derived_params()
    return _params


def reset_params_cache() -> None:
    global _params
    _params = None


def save_artifact(models_dir: str | os.PathLike,
                  tree: dict[str, np.ndarray] | None = None) -> str:
    """Install an embedder checkpoint using the labeler artifact format
    (classes empty — this trunk emits vectors, not a vocabulary)."""
    path = os.path.join(os.fspath(models_dir), ARTIFACT_NAME)
    checkpoint.save(
        path,
        tree if tree is not None else _derived_params(),
        classes=[],
        image_size=IMAGE_SIZE,
        widths=(HIDDEN, EMBED_DIM),
        depths=(1, 1),
        extra={"kind": "embedder", "model": MODEL_NAME},
    )
    return path


def forward(p: dict[str, Any], images):
    """The per-batch forward body — [B, S, S, 3] f32 in [0,1] →
    [B, EMBED_DIM] f32. jnp-only; ops/embed_jax closes over this exact
    function for the jitted, sharded, and host programs so the three
    paths are bit-identical by construction. Patch mean-pool (a fixed
    8×8 grid) then a 2-layer tanh projection: per-row math only, no
    cross-batch reductions, so dp-sharding the batch dim cannot change
    a single bit."""
    import jax.numpy as jnp

    x = images.astype(jnp.float32)
    b = x.shape[0]
    g = IMAGE_SIZE // PATCH
    x = x.reshape(b, g, PATCH, g, PATCH, 3).mean(axis=(2, 4))
    x = x.reshape(b, g * g * 3)
    h = jnp.tanh(x @ p["w1"] + p["b1"])
    return (h @ p["w2"] + p["b2"]).astype(jnp.float32)


def decode_image(path: str, image_size: int = IMAGE_SIZE) -> np.ndarray | None:
    """Decode one image to the embedder's input plane — the same
    dispatch as the labeler (HEIF rides libheif, not PIL). Module-level
    so the procpool `embed.decode` stage and the inline fallback run
    the EXACT same code path; None = undecodable."""
    from PIL import Image

    from ..object.media.images import format_image

    try:
        rgba = format_image(path)
        img = Image.fromarray(rgba).convert("RGB").resize(
            (image_size, image_size)
        )
        return np.asarray(img, np.float32) / 255.0
    except Exception:  # noqa: BLE001 - undecodable → caller skips
        return None


def vector_to_blob(vec: np.ndarray) -> bytes:
    """f32 LE wire/DB encoding of one embedding vector."""
    return np.asarray(vec, dtype="<f4").tobytes()


def blob_to_vector(blob: bytes, dim: int = EMBED_DIM) -> np.ndarray | None:
    """Strictly validated blob → vector decode (None = corrupt/foreign
    width — a poisoned sync op must never wedge index maintenance)."""
    if not isinstance(blob, (bytes, bytearray, memoryview)):
        return None
    if len(blob) != dim * 4:
        return None
    arr = np.frombuffer(bytes(blob), dtype="<f4")
    if arr.shape != (dim,) or not np.all(np.isfinite(arr)):
        return None
    return arr.astype(np.float32)
