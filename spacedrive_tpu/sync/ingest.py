"""Sync ingest actor — applying remote op streams.

Parity: ref:core/crates/sync/src/ingest.rs — a per-library actor with
the state machine WaitingForNotification → RetrievingMessages →
Ingesting (:49-93); `receive_crdt_operation` merges the remote HLC
timestamp, rejects old ops per (model, record, field) LWW, applies the
op and stores it in one transaction (:120-166); `is_operation_old`
(:169-192) consults the stored op log. Backfill parity:
ref:core/crates/sync/src/backfill.rs (generate ops for rows that
predate sync).

The transport is injected: `request_ops(timestamps, count)` is any
async callable — loopback queues in tests, the P2P sync exchange or the
cloud relay in production (ref:core/src/p2p/sync/mod.rs:22-70).
"""

from __future__ import annotations

import asyncio
import enum
import logging
import time
import uuid
from typing import Any, Awaitable, Callable, Iterable

from ..telemetry import metrics as _tm
from ..telemetry import span as _span
from ..telemetry import tenants as _tenants
from ..telemetry import trace as _trace
from ..telemetry.events import SYNC_EVENTS
from ..telemetry.peers import peer_label
from .apply import apply_op
from .crdt import CRDTOperation, DELETE
from .hlc import ClockDriftError, NTP64
from .manager import SyncManager, _record_id_blob

logger = logging.getLogger(__name__)

OPS_PER_REQUEST = 1000  # ref:core/src/cloud/sync/ingest.rs:21

# request_ops(timestamps, count) -> (ops, has_more)
RequestOps = Callable[
    [list[tuple[uuid.UUID, NTP64]], int],
    Awaitable[tuple[list[CRDTOperation], bool]],
]


# Global LWW order: (HLC timestamp, instance pub_id). is_operation_old and
# the delete re-apply query MUST use the same predicate or equal-timestamp
# delete/update races diverge by arrival order. Bind params:
# (timestamp, timestamp, pub_id).
_LWW_NEWER_SQL = (
    "(co.timestamp > ? OR (co.timestamp = ? AND i.pub_id > ?))"
)


class State(enum.Enum):
    WAITING_FOR_NOTIFICATION = "waiting"
    RETRIEVING_MESSAGES = "retrieving"
    INGESTING = "ingesting"


def is_operation_old(sync: SyncManager, op: CRDTOperation) -> bool:
    """True if a stored op for the same (model, record) supersedes
    `op` — same-field update or any delete that is strictly newer in
    the global LWW order (HLC timestamp, instance pub_id), the same
    order the delete re-apply path and the property-test oracle use
    (ref:ingest.rs:169-192). An exact echo (same timestamp, same
    instance) is not selected and re-applies idempotently."""
    rows = sync.db.query(
        "SELECT co.kind FROM crdt_operation co "
        "JOIN instance i ON i.id = co.instance_id "
        "WHERE co.model = ? AND co.record_id = ? AND " + _LWW_NEWER_SQL,
        (op.model, _record_id_blob(op.record_id), int(op.timestamp),
         int(op.timestamp), op.instance.bytes),
    )
    mine = op.kind()
    for row in rows:
        if row["kind"] == DELETE or row["kind"] == mine:
            return True
    return False


# per-op ingest outcomes (the write-combined path finalizes them only
# after the shared transaction committed)
_APPLIED, _TOMBSTONE, _STALE, _GUARD = "applied", "tombstone", "stale", "guard"


def _guard_op(sync: SyncManager, op: CRDTOperation,
              skew: float) -> str | None:
    """Delta-guard + fault-plane check, NO DB access — returns the
    rejection reason when the op is refused, else None (proceed). A
    guard trip rejects *that op* — counted and flight-recorded by
    :func:`_finalize_guard` — instead of poisoning the whole batch, and
    the watermark deliberately does NOT advance past it."""
    from ..utils import faults as _faults

    if _faults.hit("sync.ingest") is not None:
        # "poison": this op reads as a clock-skew-burst casualty — it is
        # rejected exactly like a real delta-guard trip so the peer's
        # later legitimate ops are re-pulled and convergence survives
        return "injected poisoned op"
    try:
        sync.clock.update(op.timestamp)
    except ClockDriftError as e:
        return str(e)[:200]
    return None


def _receive_into(sync: SyncManager, op: CRDTOperation, conn) -> str:
    """LWW-check + apply + store on the CALLER's transaction — the
    write-combined core. No watermark/metric side effects here: a
    rolled-back transaction must not leave the in-memory view claiming
    ops it never stored (:func:`_finalize_committed` runs post-commit)."""
    if is_operation_old(sync, op):
        return _STALE
    iid = _ensure_instance_conn(sync, op.instance, conn)
    apply_op(conn, op)
    if op.data.kind == DELETE:
        # Determinism under delete/update races: the row must be
        # a pure function of the op SET, not arrival order. A
        # delete may arrive after updates that are HLC-newer
        # than it (which is_operation_old can't reject — kinds
        # differ); re-applying the stored newer ops rebuilds
        # exactly the state the other arrival order produces.
        # (The reference resurrects-by-upsert and genuinely
        # diverges here; found by tests/test_sync_properties.)
        # "Newer" means the full LWW order (timestamp, instance
        # pub_id) — a same-timestamp op from a higher instance id
        # also supersedes this delete.
        newer = conn.execute(
            "SELECT co.data FROM crdt_operation co "
            "JOIN instance i ON i.id = co.instance_id "
            "WHERE co.model = ? AND co.record_id = ? "
            "AND " + _LWW_NEWER_SQL +
            " ORDER BY co.timestamp ASC, i.pub_id ASC",
            (op.model, _record_id_blob(op.record_id),
             int(op.timestamp), int(op.timestamp),
             op.instance.bytes),
        ).fetchall()
        for row in newer:
            raw = row["data"] if isinstance(row, dict) else row[0]
            apply_op(conn, CRDTOperation.unpack(raw))
    conn.execute(
        "INSERT OR REPLACE INTO crdt_operation "
        "(id, timestamp, model, record_id, kind, data, instance_id) "
        "VALUES (?, ?, ?, ?, ?, ?, ?)",
        (
            op.id.bytes,
            int(op.timestamp),
            op.model,
            _record_id_blob(op.record_id),
            op.kind(),
            op.pack(),
            iid,
        ),
    )
    return _TOMBSTONE if op.data.kind == DELETE else _APPLIED


def _finalize_guard(op: CRDTOperation, skew: float,
                    guard_error: str | None) -> None:
    """Bookkeeping for a guard-rejected op: counted and flight-recorded,
    and the watermark deliberately NOT advanced past it. Split from
    :func:`_finalize_committed` because this path carries no commit to
    vouch for — keeping them one function made every caller look like
    it could vouch without a commit (sdlint SD017), and the guard
    branch genuinely never may."""
    _tm.HLC_DELTA_GUARD.inc()
    SYNC_EVENTS.emit(
        "delta_guard",
        peer=peer_label(op.instance),
        skew_seconds=round(skew, 3),
        error=guard_error or "delta guard",
    )


def _finalize_committed(sync: SyncManager, op: CRDTOperation,
                        outcome: str) -> None:
    """Post-commit bookkeeping for one stored-or-stale op: outcome
    counters and the watermark (which advances even for rejected-old
    ops — they're *seen*). Callers MUST order this strictly after the
    transaction that stored the op committed — sdlint SD017 checks the
    dominance."""
    peer = peer_label(op.instance)
    _tm.SYNC_OPS.inc(
        result="tombstone" if outcome == _TOMBSTONE
        else "applied" if outcome == _APPLIED else "stale"
    )
    # tenant accounting keyed by origin instance (SyncManager carries
    # no library id) — the one choke point both the per-op and
    # write-combined batch paths funnel through
    _tenants.observe("ingest", op.instance)
    current = sync.timestamps.get(op.instance, NTP64(0))
    if op.timestamp > current:
        sync.timestamps[op.instance] = op.timestamp
        if op.instance != sync.instance:
            _tm.SYNC_WATERMARK.set(op.timestamp.as_unix(), peer=peer)


def receive_crdt_operation(sync: SyncManager, op: CRDTOperation) -> bool:
    """Merge clock, LWW-check, apply + store atomically; returns True if
    the op was applied (ref:ingest.rs:120-166). One op = one
    transaction — the write-combined batch path is
    :func:`ingest_batch`."""
    peer = peer_label(op.instance)
    # observed skew: remote op's HLC time vs our wall clock (positive =
    # remote ahead); sampled per op, cheap (one gauge set)
    skew = op.timestamp.as_unix() - time.time()
    _tm.HLC_CLOCK_SKEW.set(skew, peer=peer)
    guard_error = _guard_op(sync, op, skew)
    if guard_error is not None:
        _finalize_guard(op, skew, guard_error)
        return False
    with sync.db.transaction() as conn:
        outcome = _receive_into(sync, op, conn)
    _finalize_committed(sync, op, outcome)
    return outcome in (_APPLIED, _TOMBSTONE)


def ingest_txn_quantum() -> int:
    """Ops coalesced per SQLite transaction by the ingest actor. 1 (the
    historical op-per-transaction behavior) when write combining is off
    (``SD_SYNC_WRITE_COMBINE=0``) or the serve layer is disabled
    (``SD_SERVE_GATE=0`` reproduces pre-serve behavior exactly); else
    the serve policy's ``sync_txn_ops`` seam (PR 8 controller-tunable)."""
    import os

    from ..serve import enabled as _serve_enabled
    from ..serve import policy as _serve_policy

    if not _serve_enabled() or os.environ.get(
        "SD_SYNC_WRITE_COMBINE", "1"
    ) == "0":
        return 1
    return max(1, int(_serve_policy().sync_txn_ops))


def ingest_batch(
    sync: SyncManager, ops: list[CRDTOperation], txn_ops: int | None = None,
) -> list[bool]:
    """Write-combined ingest: apply+store ``ops`` in chunks of
    ``txn_ops`` per SQLite transaction instead of one transaction per
    op, so replication keeps converging while interactive reads hammer
    the same file. Per-op outcomes (applied/True, rejected/False) come
    back in order; watermarks/metrics are finalized strictly AFTER each
    chunk's commit, so a rolled-back chunk never advances the in-memory
    view past ops that were not stored.

    Failure isolation: a chunk whose shared transaction raises is
    rolled back and retried op-per-transaction (the pre-combining
    path), so one malformed op costs its own rejection, never its
    neighbors'. ``sd_sync_txn_combined_total`` counts the per-op
    transactions avoided."""
    quantum = ingest_txn_quantum() if txn_ops is None else max(1, txn_ops)
    results: list[bool] = []
    for start in range(0, len(ops), quantum):
        chunk = ops[start:start + quantum]
        if quantum == 1 or len(chunk) == 1:
            for op in chunk:
                results.append(receive_crdt_operation(sync, op))
            continue
        metas: list[tuple[CRDTOperation, str, float, str | None]] = []
        try:
            with sync.db.transaction() as conn:
                for op in chunk:
                    peer = peer_label(op.instance)
                    skew = op.timestamp.as_unix() - time.time()
                    _tm.HLC_CLOCK_SKEW.set(skew, peer=peer)
                    guard_error = _guard_op(sync, op, skew)
                    if guard_error is not None:
                        outcome = _GUARD
                    else:
                        outcome = _receive_into(sync, op, conn)
                    metas.append((op, outcome, skew, guard_error))
        except Exception:
            logger.exception(
                "write-combined ingest chunk failed; retrying per-op"
            )
            for op in chunk:
                try:
                    results.append(receive_crdt_operation(sync, op))
                except Exception:
                    logger.exception("op %s rejected after chunk rollback",
                                     op.id)
                    results.append(False)
            continue
        for op, outcome, skew, guard_error in metas:
            if outcome == _GUARD:
                _finalize_guard(op, skew, guard_error)
            else:
                _finalize_committed(sync, op, outcome)
            results.append(outcome in (_APPLIED, _TOMBSTONE))
        _tm.SYNC_TXN_COMBINED.inc(len(chunk) - 1)
    return results


def _ensure_instance_conn(sync: SyncManager, instance: uuid.UUID,
                          conn) -> int:
    """Resolve (or placeholder-create) the op's originating instance row
    on the CALLER's open transaction — opening a nested implicit
    transaction from inside a write-combined chunk would commit the
    outer one mid-flight. The pairing flow fills in identity/metadata
    for placeholder rows later."""
    row = conn.execute(
        "SELECT id FROM instance WHERE pub_id = ?", (instance.bytes,)
    ).fetchone()
    if row is not None:
        return row["id"] if isinstance(row, dict) else row[0]
    from ..db.database import now_iso

    now = now_iso()
    cur = conn.execute(
        "INSERT INTO instance (pub_id, identity, node_id, node_name, "
        "node_platform, last_seen, date_created) VALUES (?,?,?,?,?,?,?)",
        (instance.bytes, b"", b"", "", 0, now, now),
    )
    return cur.lastrowid


class IngestActor:
    """One per library; drives the pull side of sync."""

    def __init__(
        self,
        sync: SyncManager,
        request_ops: RequestOps,
        ops_per_request: int = OPS_PER_REQUEST,
        poll_interval: float | None = 30.0,
        on_applied: Callable[[], Any] | None = None,
    ):
        self.sync = sync
        self.request_ops = request_ops
        self.ops_per_request = ops_per_request
        # fired after any batch that APPLIED at least one op — the serve
        # layer's sync-side cache invalidation hook (p2p.manager wires
        # it to drop the library's cached reads)
        self.on_applied = on_applied
        # anti-entropy: tick even without a notification so a lost alert
        # (peer discovered late, dropped datagram) only delays, never
        # strands, convergence; None disables (tests with loopback queues)
        self.poll_interval = poll_interval
        self.state = State.WAITING_FOR_NOTIFICATION
        self.applied = 0
        self.rejected = 0
        # last op outcome, for accept/reject transition events (True so
        # a batch that opens with a reject records the transition)
        self._last_op_accepted = True
        self._notify = asyncio.Event()
        self._task: asyncio.Task | None = None
        self._stopped = False
        self._idle = asyncio.Event()
        self._idle.set()
        # trace of the most recent notifier (a p2p SYNC header): the
        # pull it triggers reports into the initiating node's trace
        self._notify_trace: "_trace.TraceContext | None" = None

    # --- actor API (ref:ingest.rs Event::Notification) ---
    def notify(self, trace_ctx: "_trace.TraceContext | None" = None) -> None:
        if trace_ctx is not None:
            self._notify_trace = trace_ctx
        self._notify.set()
        self._ensure_started()

    def _ensure_started(self) -> None:
        if self._stopped:
            return
        if self._task is None or self._task.done():
            self._task = asyncio.get_running_loop().create_task(
                self._run(), name="sync-ingest"
            )

    async def stop(self) -> None:
        self._stopped = True
        self._notify.set()
        if self._task is not None:
            try:
                await asyncio.wait_for(self._task, timeout=10)
            except (asyncio.TimeoutError, asyncio.CancelledError):
                self._task.cancel()

    async def wait_idle(self) -> None:
        """Settle: no notification pending and the tick loop is parked."""
        self._ensure_started()
        while not self._idle.is_set() or self._notify.is_set():
            await self._idle.wait()
            if self._notify.is_set():
                # notification not yet picked up by the loop; yield
                await asyncio.sleep(0.01)

    # --- state machine (ref:ingest.rs:49-93) ---
    async def _run(self) -> None:
        waited = 0.0
        while not self._stopped:
            self.state = State.WAITING_FOR_NOTIFICATION
            try:
                await asyncio.wait_for(self._notify.wait(), timeout=1.0)
            except asyncio.TimeoutError:
                waited += 1.0
                if self.poll_interval is None or waited < self.poll_interval:
                    continue
                # anti-entropy tick: pull despite no notification
            if self._stopped:
                break
            waited = 0.0
            self._notify.clear()
            self._idle.clear()
            tick_trace, self._notify_trace = self._notify_trace, None
            try:
                with _trace.use(tick_trace):
                    await self._tick()
            except Exception:
                logger.exception("sync ingest tick failed")
            finally:
                self._idle.set()

    async def _tick(self) -> None:
        while not self._stopped:
            self.state = State.RETRIEVING_MESSAGES
            timestamps = list(self.sync.timestamps.items())
            with _span("sync.request"):
                ops, has_more = await self.request_ops(
                    timestamps, self.ops_per_request
                )
            self.state = State.INGESTING
            if ops:
                _tm.SYNC_INGEST_BACKLOG.set(len(ops))
                batch_applied = batch_rejected = 0
                quantum = ingest_txn_quantum()
                with _span("sync.ingest"):
                    # write-combined: `quantum` ops share one SQLite
                    # transaction (ingest_batch), and the loop yields
                    # between windows — a 1000-op batch is seconds of
                    # synchronous SQLite work that must not freeze the
                    # event loop the API, the work-stealing plane, and
                    # the loop-lag monitor all share
                    window = max(64, quantum)
                    for start in range(0, len(ops), window):
                        if start:
                            await asyncio.sleep(0)
                        chunk = ops[start:start + window]
                        outcomes = ingest_batch(
                            self.sync, chunk, txn_ops=quantum
                        )
                        for i, (op, ok) in enumerate(
                            zip(chunk, outcomes), start=start
                        ):
                            if ok:
                                self.applied += 1
                                batch_applied += 1
                            else:
                                self.rejected += 1
                                batch_rejected += 1
                            # flight-record accept↔reject TRANSITIONS
                            # (not per-op emits): the ring captures when
                            # a stream of applies turns into rejects and
                            # vice versa
                            if ok != self._last_op_accepted:
                                self._last_op_accepted = ok
                                if ok:
                                    SYNC_EVENTS.emit(
                                        "accept_resume",
                                        peer=peer_label(op.instance),
                                        batch_index=i,
                                    )
                                else:
                                    SYNC_EVENTS.emit(
                                        "reject_start",
                                        peer=peer_label(op.instance),
                                        batch_index=i,
                                    )
                _tm.SYNC_INGEST_BACKLOG.set(0)
                SYNC_EVENTS.emit(
                    "ingest_batch",
                    applied=batch_applied,
                    rejected=batch_rejected,
                    has_more=bool(has_more),
                )
                self.sync.observe_replication_lag()
                if batch_applied and self.on_applied is not None:
                    try:
                        self.on_applied()
                    except Exception:  # noqa: BLE001 - invalidation is best-effort
                        logger.exception("ingest on_applied hook failed")
            if ops and self.sync.event_bus is not None:
                self.sync.event_bus.emit(("SyncMessage", "Ingested"))
            if not has_more:
                break


# --- backfill (ref:core/crates/sync/src/backfill.rs) ---------------------

#: rows examined (and ops flushed) per backfill batch — the whole pass
#: is bounded-memory at any table size: one batch of rows, one covered
#: membership probe, one write_ops flush, repeat
BACKFILL_BATCH = 1024


def backfill_operations(sync: SyncManager) -> int:
    """Emit create+update ops for every syncable row that has no op log
    yet (a library that predates sync, or was seeded directly). Returns
    the number of ops written.

    Bounded-memory by construction: rows stream through a rowid cursor
    in :data:`BACKFILL_BATCH` chunks, coverage is probed per chunk with
    an ``IN (...)`` membership query (never a full DISTINCT set — a
    million-row op log must not materialize in Python), and ops flush
    per chunk. Callers run this off the event loop (``to_thread``); the
    cursor shape keeps each SQLite lock hold short either way."""
    from ..db.sync_registry import SYNC_MODELS, SyncKind

    written = 0
    for model in SYNC_MODELS.values():
        if model.kind is SyncKind.LOCAL:
            continue
        last_rowid = -1
        while True:
            rows = sync.db.query(
                f"SELECT rowid AS _backfill_rid, * FROM {model.name} "
                "WHERE rowid > ? ORDER BY rowid LIMIT ?",
                (last_rowid, BACKFILL_BATCH),
            )
            if not rows:
                break
            last_rowid = rows[-1]["_backfill_rid"]
            pending: list[tuple[Any, Any, dict]] = []
            for row in rows:
                row = {k: v for k, v in row.items()
                       if k != "_backfill_rid"}
                record_id = _row_sync_id(sync, model, row)
                if record_id is None:
                    continue
                pending.append((_record_id_blob(record_id), record_id,
                                row))
            if not pending:
                continue
            # membership probe scoped to THIS chunk's ids — the no-op
            # case (backfill on every pairing accept) stays O(rows
            # scanned), with nothing accumulated across chunks
            qmarks = ",".join("?" for _ in pending)
            covered = {
                r["record_id"]
                for r in sync.db.query(
                    "SELECT record_id FROM crdt_operation "
                    f"WHERE model = ? AND record_id IN ({qmarks})",
                    (model.name, *[blob for blob, _, _ in pending]),
                )
            }
            ops: list[CRDTOperation] = []
            for blob, record_id, row in pending:
                if blob in covered:
                    continue
                values = _row_sync_values(sync, model, row)
                if model.kind is SyncKind.SHARED:
                    ops.extend(
                        sync.shared_create(model.name, record_id, values))
                else:
                    ops.extend(
                        sync.relation_create(model.name, record_id,
                                             values))
            if ops:
                sync.write_ops(ops)
                written += len(ops)
            if len(rows) < BACKFILL_BATCH:
                break
    return written


def _row_sync_id(sync: SyncManager, model, row) -> Any:
    from ..db.sync_registry import SyncKind

    if model.kind is SyncKind.RELATION:
        item = _fk_sync_id(sync, model.item, row[model.item.column])
        group = _fk_sync_id(sync, model.group, row[model.group.column])
        if item is None or group is None:
            return None
        return {"item": item, "group": group}
    if model.id_ref is not None:
        return _fk_sync_id(sync, model.id_ref, row[model.id_ref.column])
    v = row[model.id_field]
    if v is None:
        return None
    return v.hex() if isinstance(v, (bytes, bytearray)) else v


def _fk_sync_id(sync: SyncManager, fr, local_id) -> Any:
    if local_id is None:
        return None
    target = sync.db.find_one(fr.table, id=local_id)
    if target is None:
        return None
    v = target[fr.target_id_field]
    return v.hex() if isinstance(v, (bytes, bytearray)) else v


def _row_sync_values(sync: SyncManager, model, row) -> list[tuple[str, Any]]:
    """Synced (field, wire-value) pairs for a backfilled row."""
    from ..db.database import blob_u64
    from .apply import _U64_COLUMNS

    skip = {"id", model.id_field, *(model.local_fields or ())}
    if model.kind.name == "RELATION":
        skip |= {model.item.column, model.group.column}
    fk_cols = {fr.column: fr for fr in model.foreign_refs}
    if model.id_ref is not None:
        skip.add(model.id_ref.column)
    values = []
    for col, v in row.items():
        if col in skip or v is None:
            continue
        if col in fk_cols:
            v = _fk_sync_id(sync, fk_cols[col], v)
            if v is None:
                continue
        elif col in _U64_COLUMNS.get(model.name, ()):
            v = blob_u64(v)
        elif isinstance(v, (bytes, bytearray)):
            v = bytes(v).hex() if col == "pub_id" else bytes(v)
        values.append((col, v))
    return values
