"""Hybrid logical clock with NTP64 timestamps.

The reference uses the `uhlc` crate (ref:core/crates/sync/src/
manager.rs:49 `HLCBuilder::new().with_id(instance).build()`); its
timestamps are NTP64: a u64 fixed-point count of seconds since the Unix
epoch, 32 integer bits . 32 fraction bits (~233 ps resolution). The HLC
guarantees strictly monotonic timestamps per instance and merges remote
timestamps on ingest so causality is never inverted.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from uuid import UUID

MASK64 = (1 << 64) - 1


class NTP64(int):
    """u64 NTP-format timestamp (seconds * 2^32)."""

    def __new__(cls, value: int = 0) -> "NTP64":
        return super().__new__(cls, value & MASK64)

    @classmethod
    def from_unix(cls, seconds: float) -> "NTP64":
        return cls(int(seconds * (1 << 32)))

    def as_unix(self) -> float:
        return self / (1 << 32)

    def __str__(self) -> str:
        return f"{self.as_unix():.9f}"


@dataclass(frozen=True, order=True)
class Timestamp:
    """(time, id) pair — total order: time first, instance id tiebreak
    (uhlc's Timestamp shape)."""

    time: NTP64
    id: UUID


class HybridLogicalClock:
    """Monotonic HLC for one instance.

    `new_timestamp` returns max(wall_clock, last + 1); `update` folds a
    remote timestamp in so subsequent local events order after it.
    A remote timestamp more than `max_drift_seconds` ahead of the wall
    clock is rejected (uhlc's delta guard, default 100 ms there; we are
    more lenient because file-manager peers have worse clocks).
    """

    def __init__(self, instance_id: UUID, max_drift_seconds: float = 60.0):
        self.instance_id = instance_id
        self.max_drift = NTP64.from_unix(max_drift_seconds)
        self._last = NTP64(0)
        self._lock = threading.Lock()

    def now(self) -> NTP64:
        return NTP64.from_unix(time.time())

    def new_timestamp(self) -> Timestamp:
        with self._lock:
            phys = self.now()
            self._last = phys if phys > self._last else NTP64(self._last + 1)
            return Timestamp(self._last, self.instance_id)

    def peek_last(self) -> NTP64:
        with self._lock:
            return self._last

    def update(self, remote_time: NTP64) -> None:
        """Merge a remote op's timestamp (ingest path,
        ref:core/crates/sync/src/ingest.rs:120-131). Raises ClockDriftError
        when the remote clock is unacceptably far in the future."""
        phys = self.now()
        if remote_time > phys + self.max_drift:
            raise ClockDriftError(
                f"remote timestamp {NTP64(remote_time)} is "
                f"{NTP64(remote_time).as_unix() - phys.as_unix():.1f}s ahead"
            )
        with self._lock:
            if remote_time > self._last:
                self._last = NTP64(remote_time)


class ClockDriftError(Exception):
    pass
