"""Sync manager — the write/read sides of library replication.

Parity: ref:core/crates/sync/src/manager.rs — `write_ops` persists
domain rows and their crdt_operation rows in ONE transaction (:70-93);
`get_ops` pages ops after per-instance watermarks (:115-172); the
manager owns the library's HLC and instance identity and emits
SyncMessage events for the P2P layer.
"""

from __future__ import annotations

import logging
import time
import uuid
from typing import Any, Callable, Iterable

from ..db.database import LibraryDb
from ..telemetry import metrics as _tm
from ..telemetry.peers import peer_label
from ..utils.events import EventBus
from .crdt import CRDTOperation
from .factory import OperationFactory
from .hlc import HybridLogicalClock, NTP64

logger = logging.getLogger(__name__)


class SyncManager(OperationFactory):
    """One per library. Also the OperationFactory for local writes."""

    def __init__(
        self,
        db: LibraryDb,
        instance: uuid.UUID,
        event_bus: EventBus | None = None,
        emit_messages: bool = True,
    ):
        super().__init__(HybridLogicalClock(instance), instance)
        self.db = db
        self.event_bus = event_bus or EventBus()
        self.emit_messages = emit_messages
        # per-instance ingest watermarks (ref:manager.rs:29 `timestamps`)
        self.timestamps: dict[uuid.UUID, NTP64] = {}
        self._load_timestamps()

    # --- startup ---

    def _load_timestamps(self) -> None:
        rows = self.db.query(
            "SELECT i.pub_id, MAX(c.timestamp) AS ts FROM crdt_operation c "
            "JOIN instance i ON i.id = c.instance_id GROUP BY c.instance_id"
        )
        for row in rows:
            self.timestamps[uuid.UUID(bytes=row["pub_id"])] = NTP64(row["ts"])

    # --- replication observability ---

    def replication_watermarks(self) -> dict[str, float]:
        """Per-remote-instance latest applied HLC timestamp (unix
        seconds), keyed by the capped ``peer_label`` short-hash — the
        raw pub_id never reaches a metric label or a wire snapshot."""
        return {
            peer_label(inst): ts.as_unix()
            for inst, ts in self.timestamps.items()
            if inst != self.instance
        }

    def observe_replication_lag(self) -> dict[str, float]:
        """Refresh ``sd_sync_lag_seconds{peer}`` /
        ``sd_sync_watermark_seconds{peer}`` from the in-memory
        watermarks and return the lag map. Lag is wall-clock now minus
        the latest *applied* HLC timestamp from that peer: ~0 right
        after a converged sync round, growing while this replica falls
        (or the peer goes) behind. Called after ingest batches and by
        the health/federation read paths so the gauges stay honest even
        when no ops are flowing."""
        now = time.time()
        lags: dict[str, float] = {}
        for inst, ts in self.timestamps.items():
            if inst == self.instance:
                continue
            label = peer_label(inst)
            watermark = ts.as_unix()
            lag = max(0.0, now - watermark)
            lags[label] = lag
            _tm.SYNC_LAG.set(lag, peer=label)
            _tm.SYNC_WATERMARK.set(watermark, peer=label)
        return lags

    def _instance_db_id(self, instance: uuid.UUID) -> int:
        row = self.db.find_one("instance", pub_id=instance.bytes)
        if row is None:
            raise ValueError(f"unknown instance {instance}")
        return row["id"]

    # --- write side (ref:manager.rs:70-93) ---

    def write_ops(
        self,
        ops: list[CRDTOperation],
        db_writes: Callable[[Any], None] | None = None,
    ) -> None:
        """Atomically apply `db_writes(conn)` (domain rows) and persist
        `ops`; then notify subscribers (SyncMessage::Created)."""
        if not ops and db_writes is None:
            return
        instance_ids: dict[uuid.UUID, int] = {}
        with self.db.transaction() as conn:
            if db_writes is not None:
                db_writes(conn)
            for op in ops:
                iid = instance_ids.get(op.instance)
                if iid is None:
                    iid = self._instance_db_id(op.instance)
                    instance_ids[op.instance] = iid
                conn.execute(
                    "INSERT OR REPLACE INTO crdt_operation "
                    "(id, timestamp, model, record_id, kind, data, instance_id) "
                    "VALUES (?, ?, ?, ?, ?, ?, ?)",
                    (
                        op.id.bytes,
                        int(op.timestamp),
                        op.model,
                        _record_id_blob(op.record_id),
                        op.kind(),
                        op.pack(),
                        iid,
                    ),
                )
        if ops and self.emit_messages:
            self.event_bus.emit(("SyncMessage", "Created"))

    # --- read side (ref:manager.rs:115-172) ---

    def get_ops(
        self,
        count: int = 1000,
        clocks: Iterable[tuple[uuid.UUID, NTP64]] = (),
    ) -> list[CRDTOperation]:
        """Ops strictly after each instance's watermark, oldest first.
        `clocks` are the requesting peer's per-instance watermarks;
        instances not listed start from 0. Filtering and paging happen
        in SQL so cost is O(page), not O(op-log)."""
        clock_map = {inst: int(ts) for inst, ts in clocks}
        conds, params = [], []
        for row in self.db.query("SELECT id, pub_id FROM instance"):
            watermark = clock_map.get(uuid.UUID(bytes=row["pub_id"]), -1)
            conds.append("(c.instance_id = ? AND c.timestamp > ?)")
            params.extend([row["id"], watermark])
        if not conds:
            return []
        rows = self.db.query(
            "SELECT c.data FROM crdt_operation c "
            f"WHERE {' OR '.join(conds)} "
            "ORDER BY c.timestamp ASC LIMIT ?",
            (*params, count),
        )
        return [CRDTOperation.unpack(r["data"]) for r in rows]

    def get_cloud_ops(self, count: int = 1000) -> list[tuple[bytes, CRDTOperation]]:
        """Pending rows from the cloud receive cache
        (ref:core/src/cloud/sync/ingest.rs)."""
        rows = self.db.query(
            "SELECT id, data FROM cloud_crdt_operation ORDER BY timestamp ASC LIMIT ?",
            (count,),
        )
        return [(r["id"], CRDTOperation.unpack(r["data"])) for r in rows]


def _record_id_blob(record_id: Any) -> bytes:
    import msgpack

    return msgpack.packb(record_id, use_bin_type=True)
