"""Sync layer — HLC-ordered last-write-wins CRDT replication.

Parity targets: the reference's `sd-sync` vocabulary crate
(ref:crates/sync/src/{crdt.rs,factory.rs,compressed.rs}) and the
`sd-core-sync` manager/ingest (ref:core/crates/sync/src/) — see
spacedrive_tpu/sync/manager.py and ingest.py.
"""

from .hlc import NTP64, HybridLogicalClock, Timestamp
from .crdt import (
    CRDTOperation,
    CRDTOperationData,
    CompressedCRDTOperation,
    CompressedCRDTOperations,
)
from .factory import OperationFactory

__all__ = [
    "NTP64",
    "HybridLogicalClock",
    "Timestamp",
    "CRDTOperation",
    "CRDTOperationData",
    "CompressedCRDTOperation",
    "CompressedCRDTOperations",
    "OperationFactory",
]
