"""CRDT operation vocabulary.

Parity: ref:crates/sync/src/crdt.rs:25-61 (CRDTOperation / Create,
Update{field,value}, Delete; kind strings "c" / "u:<field>" / "d") and
ref:crates/sync/src/compressed.rs (nested grouping for wire batches).

Values are JSON-compatible Python values; whole operations serialize
with msgpack for the wire and the `crdt_operation` table's `data` BLOB.
"""

from __future__ import annotations

import uuid
from dataclasses import dataclass, field
from typing import Any, Iterable

import msgpack

from .hlc import NTP64

CREATE = "c"
UPDATE = "u"
DELETE = "d"


@dataclass(frozen=True)
class CRDTOperationData:
    kind: str                       # CREATE | UPDATE | DELETE
    field_name: str | None = None   # UPDATE only
    value: Any = None               # UPDATE only

    @classmethod
    def create(cls) -> "CRDTOperationData":
        return cls(CREATE)

    @classmethod
    def update(cls, field_name: str, value: Any) -> "CRDTOperationData":
        return cls(UPDATE, field_name, value)

    @classmethod
    def delete(cls) -> "CRDTOperationData":
        return cls(DELETE)

    def as_kind_string(self) -> str:
        """'c' / 'u:<field>' / 'd' — the `kind` column of
        crdt_operation rows (ref:crates/sync/src/crdt.rs:15-22)."""
        if self.kind == UPDATE:
            return f"u:{self.field_name}"
        return self.kind

    def to_wire(self) -> dict[str, Any]:
        if self.kind == UPDATE:
            return {"u": {"field": self.field_name, "value": self.value}}
        return {self.kind: None}

    @classmethod
    def from_wire(cls, obj: dict[str, Any]) -> "CRDTOperationData":
        if "u" in obj:
            return cls.update(obj["u"]["field"], obj["u"]["value"])
        if "c" in obj:
            return cls.create()
        if "d" in obj:
            return cls.delete()
        raise ValueError(f"bad CRDTOperationData wire form: {obj!r}")


@dataclass(frozen=True)
class CRDTOperation:
    instance: uuid.UUID       # originating instance pub_id
    timestamp: NTP64          # HLC time
    id: uuid.UUID             # unique op id
    model: str                # table name (sync registry key)
    record_id: Any            # JSON sync id (e.g. hex pub_id or composite)
    data: CRDTOperationData

    def kind(self) -> str:
        return self.data.as_kind_string()

    def to_wire(self) -> dict[str, Any]:
        return {
            "instance": self.instance.bytes,
            "timestamp": int(self.timestamp),
            "id": self.id.bytes,
            "model": self.model,
            "record_id": self.record_id,
            "data": self.data.to_wire(),
        }

    @classmethod
    def from_wire(cls, obj: dict[str, Any]) -> "CRDTOperation":
        return cls(
            instance=uuid.UUID(bytes=obj["instance"]),
            timestamp=NTP64(obj["timestamp"]),
            id=uuid.UUID(bytes=obj["id"]),
            model=obj["model"],
            record_id=obj["record_id"],
            data=CRDTOperationData.from_wire(obj["data"]),
        )

    def pack(self) -> bytes:
        return msgpack.packb(self.to_wire(), use_bin_type=True)

    @classmethod
    def unpack(cls, raw: bytes) -> "CRDTOperation":
        return cls.from_wire(msgpack.unpackb(raw, raw=False, strict_map_key=False))


@dataclass(frozen=True)
class CompressedCRDTOperation:
    timestamp: NTP64
    id: uuid.UUID
    data: CRDTOperationData

    @classmethod
    def from_op(cls, op: CRDTOperation) -> "CompressedCRDTOperation":
        return cls(op.timestamp, op.id, op.data)

    def to_wire(self) -> dict[str, Any]:
        return {
            "timestamp": int(self.timestamp),
            "id": self.id.bytes,
            "data": self.data.to_wire(),
        }

    @classmethod
    def from_wire(cls, obj: dict[str, Any]) -> "CompressedCRDTOperation":
        return cls(
            NTP64(obj["timestamp"]),
            uuid.UUID(bytes=obj["id"]),
            CRDTOperationData.from_wire(obj["data"]),
        )


@dataclass
class CompressedCRDTOperations:
    """Adjacent-run grouping instance → model → record for wire batches
    (ref:crates/sync/src/compressed.rs): shared prefixes are sent once.
    """

    groups: list[tuple[uuid.UUID, list[tuple[str, list[tuple[Any, list[CompressedCRDTOperation]]]]]]] = field(
        default_factory=list
    )

    @classmethod
    def compress(cls, ops: Iterable[CRDTOperation]) -> "CompressedCRDTOperations":
        out = cls()
        for op in ops:
            if not out.groups or out.groups[-1][0] != op.instance:
                out.groups.append((op.instance, []))
            models = out.groups[-1][1]
            if not models or models[-1][0] != op.model:
                models.append((op.model, []))
            records = models[-1][1]
            if not records or records[-1][0] != op.record_id:
                records.append((op.record_id, []))
            records[-1][1].append(CompressedCRDTOperation.from_op(op))
        return out

    def expand(self) -> list[CRDTOperation]:
        ops = []
        for instance, models in self.groups:
            for model, records in models:
                for record_id, compressed in records:
                    for c in compressed:
                        ops.append(CRDTOperation(instance, c.timestamp, c.id, model, record_id, c.data))
        return ops

    def __len__(self) -> int:
        return sum(
            len(compressed)
            for _, models in self.groups
            for _, records in models
            for _, compressed in records
        )

    def pack(self) -> bytes:
        wire = [
            [
                inst.bytes,
                [
                    [model, [[rid, [c.to_wire() for c in comp]] for rid, comp in records]]
                    for model, records in models
                ],
            ]
            for inst, models in self.groups
        ]
        return msgpack.packb(wire, use_bin_type=True)

    @classmethod
    def unpack(cls, raw: bytes) -> "CompressedCRDTOperations":
        wire = msgpack.unpackb(raw, raw=False, strict_map_key=False)
        out = cls()
        for inst_b, models in wire:
            out.groups.append(
                (
                    uuid.UUID(bytes=inst_b),
                    [
                        (
                            model,
                            [
                                (rid, [CompressedCRDTOperation.from_wire(c) for c in comp])
                                for rid, comp in records
                            ],
                        )
                        for model, records in models
                    ],
                )
            )
        return out
