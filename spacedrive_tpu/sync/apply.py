"""Applying remote CRDT operations to the library DB.

Parity: the generated `ModelSyncData::from_op` appliers
(ref:crates/sync-generator/src/lib.rs:22-36 — model sync types map ops
to typed upserts) as used by the ingest actor
(ref:core/crates/sync/src/ingest.rs:146-166 `apply_op`).

Wire conventions (set by this framework's OperationFactory call sites):
- SHARED models identify records by their sync id — `pub_id` as a hex
  string (or `name`/`key` for label/preference).
- Foreign-key columns sync as the *target's* sync id and are resolved
  to local integer ids here; unknown targets get a placeholder row so
  ops can apply in any order (the later Create fills the fields in).
- RELATION models identify records by {"item": …, "group": …} of the
  two sides' sync ids (ref:crates/sync/src/factory.rs:71-105).
- u64 columns (file_path.size_in_bytes_bytes / inode) sync as ints and
  are stored as 8-byte LE blobs (schema convention, db/schema.py:5-8).
"""

from __future__ import annotations

import logging
import sqlite3
from typing import Any

from ..db.database import u64_blob
from ..db.sync_registry import SYNC_MODELS, ForeignRef, SyncKind, SyncModel
from .crdt import CREATE, DELETE, UPDATE, CRDTOperation

logger = logging.getLogger(__name__)

# columns stored as 8-byte LE blobs but synced as ints
_U64_COLUMNS = {
    "file_path": {"size_in_bytes_bytes", "inode"},
    "location": {"size_in_bytes"},
}


class ApplyError(Exception):
    pass


def _sync_id_to_key(model: SyncModel, record_id: Any) -> Any:
    """Wire sync id → DB value for the identity column."""
    if model.id_field == "pub_id":
        return bytes.fromhex(record_id)
    return record_id


def _resolve_fk(conn: sqlite3.Connection, fr: ForeignRef, sync_id: Any) -> int | None:
    """Target sync id → local integer id, creating a placeholder row for
    targets whose Create op hasn't arrived yet."""
    if sync_id is None:
        return None
    key = (
        bytes.fromhex(sync_id) if fr.target_id_field == "pub_id" else sync_id
    )
    row = conn.execute(
        f"SELECT id FROM {fr.table} WHERE {fr.target_id_field} = ?", (key,)
    ).fetchone()
    if row is not None:
        return row["id"]
    cur = conn.execute(
        f"INSERT INTO {fr.table} ({fr.target_id_field}) VALUES (?)", (key,)
    )
    return cur.lastrowid


def _db_value(
    conn: sqlite3.Connection, model: SyncModel, col: str, value: Any
) -> tuple[str, Any]:
    """(column, value) as stored locally for one synced field."""
    for fr in model.foreign_refs:
        if fr.column == col:
            return col, _resolve_fk(conn, fr, value)
    if value is not None and col in _U64_COLUMNS.get(model.name, ()):
        return col, u64_blob(int(value))
    return col, value


def _shared_row_id(
    conn: sqlite3.Connection, model: SyncModel, record_id: Any, create: bool
) -> int | None:
    """Local row id for a SHARED record, optionally creating it."""
    if model.id_ref is not None:
        # identity lives through an FK (media_data → object.pub_id)
        fk = _resolve_fk(conn, model.id_ref, record_id)
        row = conn.execute(
            f"SELECT id FROM {model.name} WHERE {model.id_ref.column} = ?", (fk,)
        ).fetchone()
        if row is not None:
            return row["id"]
        if not create:
            return None
        return conn.execute(
            f"INSERT INTO {model.name} ({model.id_ref.column}) VALUES (?)", (fk,)
        ).lastrowid
    key = _sync_id_to_key(model, record_id)
    row = conn.execute(
        f"SELECT id FROM {model.name} WHERE {model.id_field} = ?", (key,)
    ).fetchone()
    if row is not None:
        return row["id"]
    if not create:
        return None
    return conn.execute(
        f"INSERT INTO {model.name} ({model.id_field}) VALUES (?)", (key,)
    ).lastrowid


def _relation_keys(
    conn: sqlite3.Connection, model: SyncModel, record_id: Any
) -> tuple[int | None, int | None]:
    assert model.item is not None and model.group is not None
    if not isinstance(record_id, dict):
        raise ApplyError(f"relation record_id must be a dict: {record_id!r}")
    return (
        _resolve_fk(conn, model.item, record_id.get("item")),
        _resolve_fk(conn, model.group, record_id.get("group")),
    )


def apply_op(conn: sqlite3.Connection, op: CRDTOperation) -> None:
    """Apply one remote op inside the caller's transaction."""
    model = SYNC_MODELS.get(op.model)
    if model is None or model.kind is SyncKind.LOCAL:
        raise ApplyError(f"model does not sync: {op.model}")

    if model.kind is SyncKind.SHARED:
        if op.data.kind == CREATE:
            _shared_row_id(conn, model, op.record_id, create=True)
        elif op.data.kind == UPDATE:
            rid = _shared_row_id(conn, model, op.record_id, create=True)
            col, val = _db_value(conn, model, op.data.field_name, op.data.value)
            if col in model.local_fields:
                return  # @local fields never apply from remote
            conn.execute(
                f"UPDATE {model.name} SET {col} = ? WHERE id = ?", (val, rid)
            )
        elif op.data.kind == DELETE:
            rid = _shared_row_id(conn, model, op.record_id, create=False)
            if rid is not None:
                conn.execute(f"DELETE FROM {model.name} WHERE id = ?", (rid,))
        return

    # RELATION (tag_on_object / label_on_object)
    item_id, group_id = _relation_keys(conn, model, op.record_id)
    item_col = model.item.column
    group_col = model.group.column
    if op.data.kind == CREATE:
        conn.execute(
            f"INSERT OR IGNORE INTO {model.name} ({item_col}, {group_col}) "
            "VALUES (?, ?)",
            (item_id, group_id),
        )
    elif op.data.kind == UPDATE:
        conn.execute(
            f"INSERT OR IGNORE INTO {model.name} ({item_col}, {group_col}) "
            "VALUES (?, ?)",
            (item_id, group_id),
        )
        conn.execute(
            f"UPDATE {model.name} SET {op.data.field_name} = ? "
            f"WHERE {item_col} = ? AND {group_col} = ?",
            (op.data.value, item_id, group_id),
        )
    elif op.data.kind == DELETE:
        conn.execute(
            f"DELETE FROM {model.name} WHERE {item_col} = ? AND {group_col} = ?",
            (item_id, group_id),
        )
