"""OperationFactory — building CRDT ops for local writes.

Parity: ref:crates/sync/src/factory.rs. A create emits one Create op
plus one Update op per non-null field (so late-joining peers converge
field-wise under LWW); updates are per-field; deletes are singular.
"""

from __future__ import annotations

import uuid
from typing import Any, Iterable

from .crdt import CRDTOperation, CRDTOperationData
from .hlc import HybridLogicalClock


class OperationFactory:
    """Mixin/impl over a clock + instance id. The sync manager subclasses
    this; unit tests use it standalone."""

    def __init__(self, clock: HybridLogicalClock, instance: uuid.UUID):
        self.clock = clock
        self.instance = instance

    def new_op(self, model: str, record_id: Any, data: CRDTOperationData) -> CRDTOperation:
        return CRDTOperation(
            instance=self.instance,
            timestamp=self.clock.new_timestamp().time,
            id=uuid.uuid4(),
            model=model,
            record_id=record_id,
            data=data,
        )

    def shared_create(
        self, model: str, record_id: Any, values: Iterable[tuple[str, Any]] = ()
    ) -> list[CRDTOperation]:
        return [self.new_op(model, record_id, CRDTOperationData.create())] + [
            self.new_op(model, record_id, CRDTOperationData.update(f, v))
            for f, v in values
        ]

    def shared_update(self, model: str, record_id: Any, field: str, value: Any) -> CRDTOperation:
        return self.new_op(model, record_id, CRDTOperationData.update(field, value))

    def shared_delete(self, model: str, record_id: Any) -> CRDTOperation:
        return self.new_op(model, record_id, CRDTOperationData.delete())

    # Relations share the same op shapes; the record id is the
    # {item, group} composite (ref:crates/sync/src/factory.rs:71-105).
    relation_create = shared_create
    relation_update = shared_update
    relation_delete = shared_delete
