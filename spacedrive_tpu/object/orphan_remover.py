"""OrphanRemoverActor — deletes objects with no remaining file_paths.

Parity: ref:core/src/object/orphan_remover.rs — invokable actor with a
periodic tick (1 min interval, 10 s debounce, orphan_remover.rs:12-49),
clean-up loop removing ≤512 orphaned objects (and their tag links) per
round until none remain (orphan_remover.rs:57-96).
"""

from __future__ import annotations

import asyncio
import logging
import time

logger = logging.getLogger(__name__)

TICK_INTERVAL = 60.0  # ref:orphan_remover.rs ONE_MINUTE
DEBOUNCE = 10.0  # ref:orphan_remover.rs TEN_SECONDS
BATCH = 512  # ref:orphan_remover.rs:63


def process_clean_up(db) -> int:
    """One full clean-up pass; returns objects removed. Also prunes
    index-journal rows whose file_path vanished: liveness comes from
    the journal/DB join (location/indexer/journal.prune_orphans), never
    from re-stat'ing paths on disk — a vanished row must not keep a
    stale vouch alive."""
    from ..location.indexer.journal import prune_orphans

    removed = 0
    while True:
        rows = db.query(
            "SELECT o.id FROM object o WHERE NOT EXISTS "
            "(SELECT 1 FROM file_path fp WHERE fp.object_id = o.id) LIMIT ?",
            (BATCH,),
        )
        if not rows:
            pruned = prune_orphans(db)
            if pruned:
                logger.debug("pruned %d orphaned journal rows", pruned)
            return removed
        ids = [r["id"] for r in rows]
        qmarks = ",".join("?" for _ in ids)
        with db.transaction() as conn:
            conn.execute(f"DELETE FROM tag_on_object WHERE object_id IN ({qmarks})", ids)
            conn.execute(f"DELETE FROM label_on_object WHERE object_id IN ({qmarks})", ids)
            conn.execute(f"DELETE FROM object WHERE id IN ({qmarks})", ids)
        removed += len(ids)
        logger.debug("removed %d orphaned objects", len(ids))


async def process_clean_up_async(db) -> int:
    """The actor's clean-up pass: same work as :func:`process_clean_up`
    but yielding to the event loop between every delete batch — the PR 9
    ingest lesson applied to maintenance: a million-row clean-up is
    thousands of short lock holds with scheduling points between them,
    never one loop-freezing scan."""
    from ..location.indexer.journal import (
        PRUNE_BATCH,
        prune_orphans_step,
    )

    removed = 0
    while True:
        rows = db.query(
            "SELECT o.id FROM object o WHERE NOT EXISTS "
            "(SELECT 1 FROM file_path fp WHERE fp.object_id = o.id) LIMIT ?",
            (BATCH,),
        )
        if not rows:
            break
        ids = [r["id"] for r in rows]
        qmarks = ",".join("?" for _ in ids)
        with db.transaction() as conn:
            conn.execute(f"DELETE FROM tag_on_object WHERE object_id IN ({qmarks})", ids)
            conn.execute(f"DELETE FROM label_on_object WHERE object_id IN ({qmarks})", ids)
            conn.execute(f"DELETE FROM object WHERE id IN ({qmarks})", ids)
        removed += len(ids)
        logger.debug("removed %d orphaned objects", len(ids))
        await asyncio.sleep(0)
    pruned = 0
    while True:
        n = prune_orphans_step(db, PRUNE_BATCH)
        pruned += n
        if n < PRUNE_BATCH:
            break
        await asyncio.sleep(0)
    if pruned:
        logger.debug("pruned %d orphaned journal rows", pruned)
    return removed


class OrphanRemoverActor:
    def __init__(self, db, tick_interval: float = TICK_INTERVAL, debounce: float = DEBOUNCE):
        self.db = db
        self.tick_interval = tick_interval
        self.debounce = debounce
        self._wake = asyncio.Event()
        self._task: asyncio.Task | None = None
        self._stopped = False
        self._last_checked = 0.0

    def start(self) -> None:
        if self._task is None:
            self._stopped = False
            self._task = asyncio.get_running_loop().create_task(self._run())

    async def stop(self) -> None:
        # cooperative flag first (sdlint SD011: the tick loop must have
        # a stop condition of its own), cancel as the fast path
        self._stopped = True
        self._wake.set()
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None

    async def invoke(self) -> None:
        self._wake.set()

    async def _run(self) -> None:
        while not self._stopped:
            try:
                await asyncio.wait_for(self._wake.wait(), timeout=self.tick_interval)
            except asyncio.TimeoutError:
                pass
            if self._stopped:
                return
            self._wake.clear()
            if time.monotonic() - self._last_checked > self.debounce:
                try:
                    await process_clean_up_async(self.db)
                except Exception:  # noqa: BLE001 - actor must survive
                    logger.exception("orphan clean-up failed")
                self._last_checked = time.monotonic()
