"""FileDeleterJob — remove file_paths from disk (and the library DB).

Parity: ref:core/src/object/fs/delete.rs — directories via
`remove_dir_all`, files via `remove_file` (delete.rs:79-83). The
reference leaves DB cleanup to the watcher; here the rows (and their
CRDT delete ops) are removed in the same job so the library stays
consistent even with watching disabled.
"""

from __future__ import annotations

import asyncio
import os
import shutil

from ...db.database import escape_like
from ...jobs import StatefulJob
from ...jobs.job import JobContext, StepResult
from ...jobs.manager import register_job
from . import get_location_path, get_many_files_datas


def _delete_path(step: dict) -> None:
    if os.path.islink(step["full_path"]):
        os.remove(step["full_path"])  # never follow links
    elif step["is_dir"]:
        shutil.rmtree(step["full_path"])
    else:
        os.remove(step["full_path"])


@register_job
class FileDeleterJob(StatefulJob):
    """init: {location_id, file_path_ids}"""

    NAME = "file_deleter"
    INVALIDATES = ("search.paths",)

    async def init_job(self, ctx: JobContext) -> None:
        db = ctx.library.db
        loc_path = get_location_path(db, self.init["location_id"])
        for fd in get_many_files_datas(db, loc_path, self.init["file_path_ids"]):
            self.steps.append(
                {
                    "full_path": fd.full_path,
                    "file_path_id": fd.row["id"],
                    "pub_id": fd.row["pub_id"],
                    "is_dir": bool(fd.row.get("is_dir")),
                }
            )
        ctx.progress(task_count=len(self.steps), phase="deleting")

    async def execute_step(self, ctx: JobContext, step: dict, step_number: int) -> StepResult:
        errors = []
        try:
            # rmtree of a deep tree can run for seconds — keep it off
            # the event loop so other jobs/streams keep making progress
            await asyncio.to_thread(_delete_path, step)
        except FileNotFoundError:
            pass  # already gone — the DB row still needs removal
        except OSError as e:
            return StepResult(errors=[f"delete {step['full_path']}: {e}"])

        self._remove_rows(ctx.library, step)
        return StepResult(errors=errors)

    def _remove_rows(self, library, step: dict) -> None:
        db, sync = library.db, library.sync
        rows = [db.find_one("file_path", id=step["file_path_id"])]
        if step["is_dir"] and rows[0] is not None:
            mat = (rows[0]["materialized_path"] or "/") + rows[0]["name"] + "/"
            rows += db.query(
                "SELECT * FROM file_path WHERE location_id = ? AND "
                "(materialized_path = ? OR materialized_path LIKE ? ESCAPE '\\')",
                (rows[0]["location_id"], mat, escape_like(mat) + "%"),
            )
        rows = [r for r in rows if r is not None]
        if not rows:
            return
        ops = [sync.shared_delete("file_path", r["pub_id"].hex()) for r in rows]
        ids = [r["id"] for r in rows]

        def writes(conn):
            qmarks = ",".join("?" for _ in ids)
            conn.execute(f"DELETE FROM file_path WHERE id IN ({qmarks})", ids)

        sync.write_ops(ops, writes)

    async def finalize(self, ctx: JobContext):
        ctx.progress(message="delete complete", phase="done")
        return dict(self.run_metadata)
