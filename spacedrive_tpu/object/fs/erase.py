"""FileEraserJob — secure-overwrite then delete.

Parity: ref:core/src/object/fs/erase.rs — directories expand to one
step per child and are collected for removal at finalize
(erase.rs:104-141); files are overwritten `passes` times with random
data in BLOCK_LEN blocks, truncated, flushed, then removed
(erase.rs:143-177; the overwrite loop itself is
ref:crates/crypto/src/fs/erase.rs:18-42). Erased rows leave the DB in
the same transaction as their CRDT delete ops.
"""

from __future__ import annotations

import os

from ...db.database import escape_like
from ...files.isolated_path import full_path_from_db_row
from ...jobs import StatefulJob
from ...jobs.job import JobContext, StepResult
from ...jobs.manager import register_job
from . import get_location_path, get_many_files_datas

BLOCK_LEN = 1 << 20  # ref:crates/crypto/src/primitives.rs BLOCK_LEN


def erase_file(path: str, passes: int) -> None:
    """Overwrite with random data block-wise, pass by pass, then
    truncate (ref:crates/crypto/src/fs/erase.rs:18-42)."""
    with open(path, "r+b") as f:
        size = os.fstat(f.fileno()).st_size
        block_count, additional = divmod(size, BLOCK_LEN)
        for _ in range(max(1, passes)):
            f.seek(0)
            for _ in range(block_count):
                f.write(os.urandom(BLOCK_LEN))
            if additional:
                f.write(os.urandom(additional))
            f.flush()
            os.fsync(f.fileno())
        f.truncate(0)


@register_job
class FileEraserJob(StatefulJob):
    """init: {location_id, file_path_ids, passes}"""

    NAME = "file_eraser"

    async def init_job(self, ctx: JobContext) -> None:
        db = ctx.library.db
        loc_path = get_location_path(db, self.init["location_id"])
        for fd in get_many_files_datas(db, loc_path, self.init["file_path_ids"]):
            self.steps.append(
                {
                    "full_path": fd.full_path,
                    "file_path_id": fd.row["id"],
                    "is_dir": bool(fd.row.get("is_dir")),
                }
            )
        self.run_metadata["directories_to_remove"] = []
        ctx.progress(task_count=len(self.steps), phase="erasing")

    async def execute_step(self, ctx: JobContext, step: dict, step_number: int) -> StepResult:
        full_path = step["full_path"]
        if os.path.islink(full_path):
            # never follow links: unlink only, the target is out of scope
            try:
                os.remove(full_path)
            except OSError as e:
                return StepResult(errors=[f"unlink {full_path}: {e}"])
            return StepResult()

        if step["is_dir"]:
            more = []
            try:
                children = sorted(os.listdir(full_path))
            except OSError as e:
                return StepResult(errors=[f"read_dir {full_path}: {e}"])
            for child in children:
                child_path = os.path.join(full_path, child)
                more.append(
                    {
                        "full_path": child_path,
                        "file_path_id": None,
                        "is_dir": os.path.isdir(child_path) and not os.path.islink(child_path),
                    }
                )
            dirs = self.run_metadata["directories_to_remove"] + [full_path]
            return StepResult(more_steps=more, metadata={"directories_to_remove": dirs})

        try:
            # the overwrite passes fire MODIFY storms; don't let the
            # watcher rescan a file that's being scrambled
            from . import watcher_pause

            with watcher_pause(ctx, self.init["location_id"]):
                erase_file(full_path, self.init.get("passes", 1))
                os.remove(full_path)
        except FileNotFoundError:
            pass
        except OSError as e:
            return StepResult(errors=[f"erase {full_path}: {e}"])
        return StepResult()

    async def finalize(self, ctx: JobContext):
        # deepest-first so children go before parents (ref:erase.rs:181-196)
        db, sync = ctx.library.db, ctx.library.sync
        for d in sorted(self.run_metadata["directories_to_remove"], key=len, reverse=True):
            try:
                os.rmdir(d)
            except OSError as e:
                self.errors.append(f"rmdir {d}: {e}")
        loc_path = get_location_path(db, self.init["location_id"])
        candidates = []
        for fp_id in self.init["file_path_ids"]:
            row = db.find_one("file_path", id=fp_id)
            if row is None:
                continue
            candidates.append(row)
            if row.get("is_dir"):
                mat = (row["materialized_path"] or "/") + row["name"] + "/"
                candidates += db.query(
                    "SELECT * FROM file_path WHERE location_id = ? AND "
                    "(materialized_path = ? OR materialized_path LIKE ? ESCAPE '\\')",
                    (row["location_id"], mat, escape_like(mat) + "%"),
                )
        # only rows whose on-disk path is actually gone — a failed erase
        # must keep its library record
        rows = [
            r for r in candidates
            if not os.path.lexists(full_path_from_db_row(loc_path, r))
        ]
        if rows:
            ops = [sync.shared_delete("file_path", r["pub_id"].hex()) for r in rows]
            ids = [r["id"] for r in rows]

            def writes(conn):
                qmarks = ",".join("?" for _ in ids)
                conn.execute(f"DELETE FROM file_path WHERE id IN ({qmarks})", ids)

            sync.write_ops(ops, writes)
        ctx.progress(message="erase complete", phase="done")
        return dict(self.run_metadata)
