"""FileCopierJob — recursive copy with duplicate renaming.

Parity: ref:core/src/object/fs/copy.rs — init resolves source FileDatas
and target paths, renaming when source == target
(copy.rs:60-106); execute_step: directories create the target dir and
push one more step per child (copy.rs:118-160), files copy with
"(N)" renaming when the target already exists (copy.rs:162-200).
"""

from __future__ import annotations

import logging
import os
import shutil

from ...jobs import StatefulJob
from ...jobs.job import JobContext, JobError, StepResult
from ...jobs.manager import register_job
from . import (
    construct_target_filename,
    fetch_source_and_target_location_paths,
    find_available_filename_for_duplicate,
    get_many_files_datas,
)

logger = logging.getLogger(__name__)


@register_job
class FileCopierJob(StatefulJob):
    """init: {source_location_id, target_location_id,
    sources_file_path_ids, target_relative_path}"""

    NAME = "file_copier"
    INVALIDATES = ("search.paths",)

    async def init_job(self, ctx: JobContext) -> None:
        db = ctx.library.db
        init = self.init
        src_loc_path, tgt_loc_path = fetch_source_and_target_location_paths(
            db, init["source_location_id"], init["target_location_id"]
        )
        target_dir = os.path.normpath(
            os.path.join(tgt_loc_path, init.get("target_relative_path", "").lstrip("/"))
        )
        for fd in get_many_files_datas(db, src_loc_path, init["sources_file_path_ids"]):
            target = os.path.join(target_dir, construct_target_filename(fd))
            if os.path.abspath(fd.full_path) == os.path.abspath(target):
                target = find_available_filename_for_duplicate(target)
            self.steps.append(
                {
                    "source_path": fd.full_path,
                    "target_path": target,
                    "is_dir": bool(fd.row.get("is_dir")),
                }
            )
        self.data["sources_location_path"] = src_loc_path
        # copy targets must never become copy sources (directory copied
        # into its own subtree would otherwise recurse forever)
        self.data["target_roots"] = [
            os.path.abspath(s["target_path"]) for s in self.steps if s["is_dir"]
        ]
        ctx.progress(task_count=len(self.steps), phase="copying")

    async def execute_step(self, ctx: JobContext, step: dict, step_number: int) -> StepResult:
        source, target = step["source_path"], step["target_path"]
        if step["is_dir"]:
            # snapshot children BEFORE creating the target: copying a
            # directory into itself must not descend into the copy
            try:
                children = sorted(os.listdir(source))
            except OSError as e:
                raise JobError(f"read_dir {source}: {e}") from e
            os.makedirs(target, exist_ok=True)
            skip = {os.path.abspath(target), *self.data.get("target_roots", [])}
            more = []
            for child in children:
                child_path = os.path.join(source, child)
                child_abs = os.path.abspath(child_path)
                if any(child_abs == t or child_abs.startswith(t + os.sep) for t in skip):
                    continue
                more.append(
                    {
                        "source_path": child_path,
                        "target_path": os.path.join(target, child),
                        "is_dir": os.path.isdir(child_path),
                    }
                )
            return StepResult(more_steps=more)

        if os.path.exists(target):
            target = find_available_filename_for_duplicate(target)
        try:
            os.makedirs(os.path.dirname(target), exist_ok=True)
            shutil.copy2(source, target)
        except OSError as e:
            raise JobError(f"copy {source} -> {target}: {e}") from e
        return StepResult()

    async def finalize(self, ctx: JobContext):
        ctx.progress(message="copy complete", phase="done")
        return dict(self.run_metadata)
