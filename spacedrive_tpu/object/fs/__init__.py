"""File-operation jobs — copy / cut / delete / erase.

Parity: ref:core/src/object/fs/mod.rs — `FileData` (row + resolved full
path, mod.rs:44-47), `get_many_files_datas` (mod.rs:49-83),
`construct_target_filename` extension handling (mod.rs:132-152),
`" (N)"` duplicate-suffix renaming (DUPLICATE_PATTERN mod.rs:32-34,
`append_digit_to_filename`/`find_available_filename_for_duplicate`
mod.rs:154-200), `fetch_source_and_target_location_paths`
(mod.rs:107-130).
"""

from __future__ import annotations

import contextlib
import os
import re
from dataclasses import dataclass

from ...files.isolated_path import full_path_from_db_row

DUPLICATE_PATTERN = re.compile(r" \(\d+\)")


class FileSystemJobsError(Exception):
    pass


@dataclass
class FileData:
    """A file_path DB row plus its absolute on-disk path."""

    row: dict
    full_path: str


@contextlib.contextmanager
def watcher_pause(ctx, location_id: int):
    """Suppress the location watcher while a job scribbles in its own
    location (ref:location/manager/mod.rs stop_watcher/reinit_watcher —
    the reference's fs jobs ignore their own write events the same way)."""
    node = getattr(ctx.library, "node", None)
    mgr = getattr(node, "location_manager", None) if node is not None else None
    if mgr is not None:
        mgr.pause(ctx.library, location_id)
    try:
        yield
    finally:
        if mgr is not None:
            mgr.resume(ctx.library, location_id)


def get_location_path(db, location_id: int) -> str:
    loc = db.find_one("location", id=location_id)
    if loc is None or not loc.get("path"):
        raise FileSystemJobsError(f"location {location_id} not found")
    return loc["path"]


def get_many_files_datas(db, location_path: str, file_path_ids: list[int]) -> list[FileData]:
    out = []
    for fp_id in file_path_ids:
        row = db.find_one("file_path", id=fp_id)
        if row is None:
            raise FileSystemJobsError(f"file_path {fp_id} not found")
        out.append(FileData(row, full_path_from_db_row(location_path, row)))
    return out


def fetch_source_and_target_location_paths(
    db, source_location_id: int, target_location_id: int
) -> tuple[str, str]:
    return get_location_path(db, source_location_id), get_location_path(db, target_location_id)


def construct_target_filename(file_data: FileData) -> str:
    """Directory or extension-less file → bare name; file → name.ext
    (ref:mod.rs:132-152)."""
    row = file_data.row
    if row.get("is_dir") or not row.get("extension"):
        return row["name"]
    return f"{row['name']}.{row['extension']}"


def append_digit_to_filename(file_name: str, ext: str | None, current_int: int) -> str:
    """'photo (2)' handling: strips an existing ' (N)' suffix before
    appending the new counter (ref:mod.rs:154-172)."""
    matches = list(DUPLICATE_PATTERN.finditer(file_name))
    base = file_name[: matches[-1].start()] if matches else file_name
    if ext:
        return f"{base} ({current_int}).{ext}"
    return f"{base} ({current_int})"


def find_available_filename_for_duplicate(target_path: str) -> str:
    """First 'name (N).ext' that does not exist yet
    (ref:mod.rs:174-200)."""
    directory = os.path.dirname(target_path)
    filename = os.path.basename(target_path)
    stem, dot, ext = filename.rpartition(".")
    if not dot or not stem:
        stem, ext = filename, ""
    for i in range(1, 2**32):
        candidate = os.path.join(directory, append_digit_to_filename(stem, ext or None, i))
        if not os.path.exists(candidate):
            return candidate
    raise FileSystemJobsError(f"no available filename for duplicate of {target_path}")
