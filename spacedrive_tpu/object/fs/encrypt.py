"""FileEncryptor/FileDecryptor jobs — sd-crypto over library files.

Parity: ref:core/src/object/fs/{encrypt.rs,decrypt.rs} (reference
pre-rewrite file-crypto jobs) on top of crates/crypto: encrypt writes
`<name>.sdenc` next to the source with a keyslotted header (optional
embedded metadata = the file_path row essentials, optional preview
media = the existing thumbnail, matching the reference's header
extras); decrypt reverses by password. The location watcher's pause
window keeps the jobs' own writes from echoing back as events.
"""

from __future__ import annotations

import asyncio
import os

from ...crypto.header import decrypt_file, encrypt_file
from ...crypto.hashing import HashingAlgorithm
from ...crypto.stream import Algorithm
from ...jobs import StatefulJob
from ...jobs.job import JobContext, StepResult
from ...jobs.manager import register_job
from . import get_location_path, get_many_files_datas, watcher_pause

ENCRYPTED_EXT = "sdenc"


def _read_preview(path: str) -> bytes | None:
    """Blocking thumbnail read — runs via asyncio.to_thread."""
    try:
        with open(path, "rb") as f:
            return f.read()
    except OSError:
        return None


@register_job
class FileEncryptorJob(StatefulJob):
    """init: {location_id, file_path_ids, password, algorithm?,
    with_metadata?, with_preview_media?, erase_original?}"""

    NAME = "file_encryptor"

    async def init_job(self, ctx: JobContext) -> None:
        db = ctx.library.db
        loc_path = get_location_path(db, self.init["location_id"])
        for fd in get_many_files_datas(db, loc_path, self.init["file_path_ids"]):
            if fd.row.get("is_dir"):
                continue  # ref:encrypt.rs skips directories
            self.steps.append(
                {
                    "full_path": fd.full_path,
                    "cas_id": fd.row.get("cas_id"),
                    "name": fd.row.get("name"),
                    "extension": fd.row.get("extension"),
                }
            )
        ctx.progress(task_count=len(self.steps), phase="encrypting")

    async def execute_step(self, ctx: JobContext, step: dict, step_number: int) -> StepResult:
        src = step["full_path"]
        dst = f"{src}.{ENCRYPTED_EXT}"
        metadata = None
        if self.init.get("with_metadata", True):
            metadata = {
                "name": step["name"],
                "extension": step["extension"],
                "cas_id": step["cas_id"],
            }
        preview = None
        if self.init.get("with_preview_media") and step["cas_id"]:
            node = getattr(ctx.library, "node", None)
            if node is not None:
                thumb = node.thumbnailer.store.path_for(
                    str(ctx.library.id), step["cas_id"]
                )
                preview = await asyncio.to_thread(_read_preview, thumb)
        with watcher_pause(ctx, self.init["location_id"]):
            encrypt_file(
                src,
                dst,
                self.init["password"].encode(),
                algorithm=Algorithm(self.init.get("algorithm", 0)),
                hashing=HashingAlgorithm(
                    self.init.get("hashing", HashingAlgorithm.ARGON2ID)
                ),
                metadata=metadata,
                preview_media=preview,
                _test_overrides=tuple(self.init["_test_overrides"])
                if self.init.get("_test_overrides")
                else None,
            )
            if self.init.get("erase_original"):
                from .erase import erase_file

                erase_file(src, passes=1)
                os.remove(src)
        ctx.progress(completed_task_count=step_number + 1)
        return StepResult()

    async def finalize(self, ctx: JobContext):
        return {"encrypted": len(self.steps)}


@register_job
class FileDecryptorJob(StatefulJob):
    """init: {location_id, file_path_ids, password, erase_original?}"""

    NAME = "file_decryptor"

    async def init_job(self, ctx: JobContext) -> None:
        db = ctx.library.db
        loc_path = get_location_path(db, self.init["location_id"])
        for fd in get_many_files_datas(db, loc_path, self.init["file_path_ids"]):
            if fd.row.get("is_dir"):
                continue
            self.steps.append({"full_path": fd.full_path})
        ctx.progress(task_count=len(self.steps), phase="decrypting")

    async def execute_step(self, ctx: JobContext, step: dict, step_number: int) -> StepResult:
        src = step["full_path"]
        if src.endswith(f".{ENCRYPTED_EXT}"):
            dst = src[: -(len(ENCRYPTED_EXT) + 1)]
        else:
            dst = src + ".decrypted"
        with watcher_pause(ctx, self.init["location_id"]):
            decrypt_file(
                src,
                dst,
                self.init["password"].encode(),
                _test_overrides=tuple(self.init["_test_overrides"])
                if self.init.get("_test_overrides")
                else None,
            )
            if self.init.get("erase_original"):
                os.remove(src)
        ctx.progress(completed_task_count=step_number + 1)
        return StepResult()

    async def finalize(self, ctx: JobContext):
        return {"decrypted": len(self.steps)}
