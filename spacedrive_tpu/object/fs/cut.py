"""FileCutterJob — move file_paths into a target directory.

Parity: ref:core/src/object/fs/cut.rs — same-path is a no-op
(cut.rs:93-96), an existing target is skipped with a non-critical
"WouldOverwrite" error (cut.rs:98-110), otherwise a rename
(cut.rs:111-122; cross-device falls back to copy+remove, which
`fs::rename` cannot do — shutil.move covers the EXDEV case).
"""

from __future__ import annotations

import os
import shutil

from ...jobs import StatefulJob
from ...jobs.job import JobContext, JobError, StepResult
from ...jobs.manager import register_job
from . import (
    construct_target_filename,
    fetch_source_and_target_location_paths,
    get_many_files_datas,
)


@register_job
class FileCutterJob(StatefulJob):
    """init: {source_location_id, target_location_id,
    sources_file_path_ids, target_relative_path}"""

    NAME = "file_cutter"
    INVALIDATES = ("search.paths",)

    async def init_job(self, ctx: JobContext) -> None:
        db = ctx.library.db
        init = self.init
        src_loc_path, tgt_loc_path = fetch_source_and_target_location_paths(
            db, init["source_location_id"], init["target_location_id"]
        )
        target_dir = os.path.normpath(
            os.path.join(tgt_loc_path, init.get("target_relative_path", "").lstrip("/"))
        )
        for fd in get_many_files_datas(db, src_loc_path, init["sources_file_path_ids"]):
            self.steps.append(
                {
                    "source_path": fd.full_path,
                    "target_path": os.path.join(target_dir, construct_target_filename(fd)),
                }
            )
        self.data["target_directory"] = target_dir
        ctx.progress(task_count=len(self.steps), phase="moving")

    async def execute_step(self, ctx: JobContext, step: dict, step_number: int) -> StepResult:
        source, target = step["source_path"], step["target_path"]
        if os.path.abspath(source) == os.path.abspath(target):
            return StepResult()
        if os.path.lexists(target):
            return StepResult(errors=[f"would overwrite: {target}"])
        try:
            os.makedirs(os.path.dirname(target), exist_ok=True)
            shutil.move(source, target)
        except OSError as e:
            raise JobError(f"move {source} -> {target}: {e}") from e
        return StepResult()

    async def finalize(self, ctx: JobContext):
        ctx.progress(message="move complete", phase="done")
        return dict(self.run_metadata)
