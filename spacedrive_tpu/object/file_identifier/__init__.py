"""file_identifier — links orphan file_paths to content-addressed
Objects. Parity: ref:core/src/object/file_identifier/."""

from .job import FileIdentifierJob, CHUNK_SIZE

__all__ = ["FileIdentifierJob", "CHUNK_SIZE"]
