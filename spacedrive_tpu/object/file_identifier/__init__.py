"""file_identifier — links orphan file_paths to content-addressed
Objects. Parity: ref:core/src/object/file_identifier/."""

# the reference's 100-file CPU parity chunk now lives with the other
# pipeline sizing in the autotuner's policy module
from ...parallel.autotune import IDENTIFY_CPU_WINDOW as CHUNK_SIZE
from .job import FileIdentifierJob

__all__ = ["FileIdentifierJob", "CHUNK_SIZE"]
