"""Object linking shared by the identifier job and the mesh shard plane.

Two call shapes exist over one invariant (same content ⇒ same object):

- :func:`kind_for_row` — extension → ObjectKind resolution (moved out
  of ``job.py`` so shard execution resolves kinds identically);
- :func:`object_pub_for` — **deterministic** object pub_id derived
  from ``(library_id, cas_id)``. The single-node identifier can mint
  random pub_ids because its own DB query is the dedupe point; a mesh
  pass has no such point — two peers executing a re-stolen shard
  concurrently would each mint a fresh object for the same cas. A
  uuid5 over the library+cas makes both executions emit byte-identical
  ``shared_create("object", …)`` ops, so the HLC/LWW merge converges
  to ONE object row no matter how many times a shard ran;
- :func:`apply_cas_results` — idempotent upsert of shard results
  (cas_id + object link per file_path) through the sync factory:
  rows already carrying the cas are skipped without emitting ops, so
  duplicate completions cost nothing and never bump HLC clocks.
"""

from __future__ import annotations

import uuid
from typing import Any

from ...db.database import now_iso
from ...files.extensions import from_str as ext_from_str
from ...files.kind import ObjectKind

#: uuid5 namespace for deterministic object pub_ids (mesh shard plane)
OBJECT_NS = uuid.UUID("5d0b5e1f-c45e-4a8a-9b7e-8f3a2d6c0001")


def kind_for_row(row: dict) -> ObjectKind:
    """Extension → ObjectKind (full magic-sniff happens in the media
    pipeline where bytes are read)."""
    if row.get("is_dir"):
        return ObjectKind.Folder
    ext = row.get("extension") or ""
    if not ext:
        return ObjectKind.Unknown
    poss = ext_from_str(ext)
    if poss is None:
        return ObjectKind.Unknown
    if poss.known is not None:
        return poss.known.kind
    # conflicting extension: prefer the first conflict's kind
    return poss.conflicts[0].kind


def object_pub_for(library_id: Any, cas_id: str) -> bytes:
    """Deterministic object pub_id for ``(library, cas_id)`` — every
    executor of the same content mints the same object identity."""
    return uuid.uuid5(OBJECT_NS, f"{library_id}:{cas_id}").bytes


#: pub_ids per IN query — one 16-byte blob bind each; stays well under
#: SQLite's default 999-variable limit
_LINK_CHUNK = 400

#: smallest result batch worth a pool round-trip for the prep leg
_PREP_POOL_MIN = 32


def _prep_results(lib_id: Any, results: list[dict]) \
        -> list[tuple[dict, bytes, str, bytes]]:
    """``(result, fp_pub, cas, deterministic obj_pub)`` per linkable
    result — apply_cas_results' pure prep. Ships to the process pool
    (stage ``link.prep``) when it is live and the batch is big enough;
    the inline loop is both the small-batch path and the fallback, so
    pooled and single-process prep are identical by construction."""
    if len(results) >= _PREP_POOL_MIN:
        from ...parallel import procpool as _procpool

        pool = _procpool.get()
        if pool is not None:
            # None pub_id stays None: the worker's fromhex(str(None))
            # rejects it exactly like the inline KeyError skip
            wire = [
                {"pub_id": res.get("pub_id"),
                 "cas_id": res.get("cas_id"),
                 "ext": res.get("ext")}
                for res in results
            ]
            try:
                reply = pool.request(
                    "link.prep",
                    {"library_id": str(lib_id), "results": wire},
                    rows=len(results),
                )
                return [
                    (results[i], bytes(fp_pub), cas, bytes(obj_pub))
                    for i, fp_pub, cas, obj_pub in reply["usable"]
                ]
            except (_procpool.ProcPoolError, KeyError, TypeError,
                    ValueError, IndexError):
                pass  # fall through to the inline prep
    usable: list[tuple[dict, bytes, str, bytes]] = []
    for res in results:
        cas = res.get("cas_id")
        if not cas or not isinstance(cas, str):
            continue  # empty/unreadable files carry no cas to link
        try:
            fp_pub = bytes.fromhex(str(res["pub_id"]))
        except (KeyError, ValueError):
            continue
        usable.append((res, fp_pub, cas, object_pub_for(lib_id, cas)))
    return usable


def _rows_by_pub(
    db: Any, table: str, columns: str, pubs: list[bytes], batched: bool,
) -> dict[bytes, dict]:
    """``{pub_id: row}`` for the pubs that exist. ``batched`` fetches
    with chunked ``IN`` queries (one per ~400 pubs); the per-file path
    issues one ``find_one`` per pub — kept as the parity oracle
    (tests/test_serve.py proves both modes produce identical links)."""
    out: dict[bytes, dict] = {}
    if not batched:
        for pub in pubs:
            row = db.find_one(table, pub_id=pub)
            if row is not None:
                out[bytes(row["pub_id"])] = row
        return out
    for start in range(0, len(pubs), _LINK_CHUNK):
        chunk = pubs[start:start + _LINK_CHUNK]
        placeholders = ",".join("?" for _ in chunk)
        for row in db.query(
            f"SELECT {columns} FROM {table} "
            f"WHERE pub_id IN ({placeholders})",
            chunk,
        ):
            out[bytes(row["pub_id"])] = row
    return out


def apply_cas_results(
    library: Any, results: list[dict], *, emit_ops: bool = True,
    batched: bool = True,
) -> tuple[int, int]:
    """Apply shard results (``{"pub_id": hex, "cas_id": str, "ext":
    str}`` per file) to this replica: create deterministic objects,
    link file_paths, and (for the EXECUTING node) emit the sync ops
    that carry both to the mesh.

    ``emit_ops=False`` is the complete-receiver's mode: the executor
    already minted the authoritative CRDT ops (they are written before
    the ``complete`` is ever sent), so the coordinator applies the same
    values directly — re-emitting them would double the mesh's op
    volume and make every other replica ingest the work twice. The
    executor's ops still arrive through sync and LWW-apply over the
    identical values, so the op log stays the source of truth.

    Idempotent by construction — (a) rows already carrying the cas and
    an object link are skipped entirely, (b) object/file_path rows are
    upserted (placeholder-friendly, like ``sync/apply.py``), so results
    may land before the file_path create ops have synced here, and a
    twice-applied batch emits ops only the first time.

    Returns ``(created_objects, linked_paths)``.
    """
    sync = library.sync
    lib_id = getattr(library, "id", None)
    ops: list = []
    date_created = now_iso()
    to_link: list[tuple[bytes, str, bytes]] = []  # (fp pub, cas, obj pub)
    new_objects: dict[bytes, int] = {}  # obj pub -> kind
    created = linked = 0
    # normalize first, then ONE batched fetch per table (a 128-file
    # shard used to cost 256 point SELECTs here — the other half of the
    # per-entry-SQL floor batched alongside journal.consult_many).
    # With the process pool live the normalize/uuid5 prep ships out
    # (shared-nothing: result subsets in, plain tuples back); the row
    # fetches and the sync-write commit below stay on this process.
    usable = _prep_results(lib_id, results)
    fp_rows = _rows_by_pub(
        library.db, "file_path", "pub_id, cas_id, object_id",
        [fp for _res, fp, _cas, _obj in usable], batched,
    )
    obj_rows = _rows_by_pub(
        library.db, "object", "pub_id",
        sorted({obj for _res, _fp, _cas, obj in usable}), batched,
    )
    for res, fp_pub, cas, obj_pub in usable:
        row = fp_rows.get(fp_pub)
        if row is not None and row.get("cas_id") == cas \
                and row.get("object_id") is not None:
            continue  # already converged (duplicate completion)
        obj_row = obj_rows.get(obj_pub)
        if obj_row is None and obj_pub not in new_objects:
            kind = kind_for_row(
                {"extension": res.get("ext"), "is_dir": False}
            )
            new_objects[obj_pub] = int(kind)
            if emit_ops:
                ops.extend(sync.shared_create(
                    "object", obj_pub.hex(),
                    [("kind", int(kind)), ("date_created", date_created)],
                ))
            created += 1
        rid = fp_pub.hex()
        if emit_ops:
            ops.append(sync.shared_update("file_path", rid, "cas_id", cas))
            ops.append(
                sync.shared_update("file_path", rid, "object_id",
                                   obj_pub.hex())
            )
        to_link.append((fp_pub, cas, obj_pub))
        linked += 1

    if not to_link:
        return 0, 0

    def writes(conn):
        for obj_pub, kind in new_objects.items():
            conn.execute(
                "INSERT OR IGNORE INTO object (pub_id, kind, date_created) "
                "VALUES (?,?,?)",
                (obj_pub, kind, date_created),
            )
        obj_ids: dict[bytes, int | None] = {}
        if batched:
            needed = sorted({obj_pub for _fp, _cas, obj_pub in to_link})
            for start in range(0, len(needed), _LINK_CHUNK):
                chunk = needed[start:start + _LINK_CHUNK]
                placeholders = ",".join("?" for _ in chunk)
                for r in conn.execute(
                    "SELECT id, pub_id FROM object "
                    f"WHERE pub_id IN ({placeholders})",
                    chunk,
                ).fetchall():
                    obj_ids[bytes(r["pub_id"])] = r["id"]
        for fp_pub, cas, obj_pub in to_link:
            obj_id = obj_ids.get(obj_pub)
            if obj_id is None and obj_pub not in obj_ids:
                r = conn.execute(
                    "SELECT id FROM object WHERE pub_id = ?", (obj_pub,)
                ).fetchone()
                obj_id = obj_ids[obj_pub] = r["id"] if r is not None else None
            # placeholder-friendly: the file_path create op may not
            # have synced to this replica yet (sync/apply.py fills the
            # fields in when it arrives)
            conn.execute(
                "INSERT OR IGNORE INTO file_path (pub_id) VALUES (?)",
                (fp_pub,),
            )
            conn.execute(
                "UPDATE file_path SET cas_id = ?, object_id = ? "
                "WHERE pub_id = ?",
                (cas, obj_id, fp_pub),
            )

    sync.write_ops(ops, writes)
    return created, linked
