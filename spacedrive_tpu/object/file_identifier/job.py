"""FileIdentifierJob — cas_id hashing + object linking, TPU-batched.

Parity: ref:core/src/object/file_identifier/ — orphan query with cursor
pagination (file_identifier_job.rs:56-165), CHUNK_SIZE = 100 files per
step (mod.rs:33-34), FileMetadata::new = fs metadata + kind resolve +
cas_id (mod.rs:57-96), then cas_id sync updates + object
dedupe/create/connect (mod.rs:98-350).

TPU-first: where the reference hashes ≤100 files concurrently on CPU
cores (join_all), each step here assembles the sampled messages on the
host and hashes the whole chunk as ONE device batch (Pallas/XLA BLAKE3)
— the batch dim replaces task-level concurrency. The chunk size is
raised accordingly (devices want bigger batches), configurable via
init["chunk_size"].
"""

from __future__ import annotations

import logging
import os
import time
from typing import Any

from ...db.database import blob_u64, escape_like, new_pub_id, now_iso
from ...files.isolated_path import full_path_from_db_row as _row_full_path
from ...files.isolated_path import materialized_prefix
from .link import kind_for_row as _kind_for_row
from ...jobs import StatefulJob
from ...jobs.job import JobContext, JobError, StepResult
from ...jobs.manager import register_job
from ...location.indexer import journal as _journal
from ...ops import cas
from ...parallel import autotune as _autotune
from ...telemetry import metrics as _tm
from ...telemetry import span
from ...telemetry import profiler as _profiler

logger = logging.getLogger(__name__)

# Window/depth sizing lives in the per-workload "identify"
# PipelinePolicy (parallel/autotune.py): the static base is
# IDENTIFY_DEVICE_WINDOW rows per accelerator (a v5e-8 window is 8192
# rows dp-sharded so every chip hashes a warm 1024-row shard from ONE
# dispatch) with feeder.pipeline_depth windows in flight; the
# closed-loop controller widens/narrows both from observed feeder
# wait, link probes, and occupancy. CPU backends keep the reference's
# 100-row parity chunk (autotune.IDENTIFY_CPU_WINDOW, ref:mod.rs:34).


def orphan_where_clause(sub_path_mat: str | None = None) -> str:
    """Orphan = no object, not identified yet, real file
    (ref:file_identifier_job.rs orphan_path_filters)."""
    base = (
        "object_id IS NULL AND cas_id IS NULL AND is_dir = 0 "
        "AND location_id = ?"
    )
    if sub_path_mat is not None:
        base += " AND materialized_path LIKE ? ESCAPE '\\'"
    return base


@register_job
class FileIdentifierJob(StatefulJob):
    """init: {location_id, sub_path?, backend?, chunk_size?}"""

    NAME = "file_identifier"
    INVALIDATES = ("search.paths", "search.objects")
    IS_BATCHED = True
    _pipeline = None  # runtime-only window pipeline (never serialized)
    _profiling = False  # holds one jax-profiler refcount while running

    async def init_job(self, ctx: JobContext) -> None:
        library = ctx.library
        loc_id = self.init["location_id"]
        location = library.db.find_one("location", id=loc_id)
        if location is None:
            raise JobError(f"location {loc_id} not found")

        backend = self.init.get("backend", "auto")
        if backend in ("tpu", "device", "auto"):
            from ...parallel.mesh import accelerator_count

            # the STATIC base sizes the step estimate; live windows are
            # re-read from the policy per fetch (an autotuned window may
            # grow — fewer windows than steps, the extras no-op — or
            # shrink — execute_step drains via more_steps)
            default_chunk = (
                _autotune.IDENTIFY_DEVICE_WINDOW * accelerator_count()
            )
        else:
            default_chunk = _autotune.IDENTIFY_CPU_WINDOW
        chunk = self.init.get("chunk_size") or default_chunk

        params: list[Any] = [loc_id]
        where = orphan_where_clause(self.init.get("sub_path") and self.init["sub_path"])
        if self.init.get("sub_path"):
            params.append(escape_like(materialized_prefix(self.init['sub_path'])) + "%")
        total = library.db.count("file_path", where, tuple(params))

        self.data.update(
            location_id=loc_id,
            location_path=location["path"],
            backend=backend,
            chunk_size=chunk,
            cursor=0,
        )
        n_steps = (total + chunk - 1) // chunk
        for _ in range(n_steps):
            self.steps.append({"kind": "identify"})
        self.run_metadata.update(
            total_orphan_paths=total, created_objects=0, linked_objects=0,
            hash_time=0.0, db_time=0.0,
            journal_hits=0, journal_dirty_rehash=0,
        )
        ctx.progress(
            task_count=n_steps,
            message=f"identifying {total} orphan paths", phase="identifying",
        )

    def _fetch_window(self, library, cursor: int):
        """Read+dispatch stage: one cursor window of rows, their sampled
        bytes, and — on the device path — the hash batch already
        dispatched (async) so back-to-back windows pipeline transfers.
        Runs on a worker thread; disk I/O never blocks the loop.

        The index journal is consulted per row BEFORE any byte is read:
        a `hit` reuses the vouched cas_id with zero I/O; an invalidated
        entry with a chunk cache and an unchanged message length takes
        the host dirty-range rehash (only dirty chunks pay BLAKE3, zero
        bytes shipped to the device); everything else rides the device
        batch as before."""
        d = self.data
        params: list[Any] = [d["location_id"]]
        where = orphan_where_clause(self.init.get("sub_path"))
        if self.init.get("sub_path"):
            params.append(escape_like(materialized_prefix(self.init['sub_path'])) + "%")
        limit = self._window_limit()
        # cursor pagination by id (ref:file_identifier_job.rs:126-165)
        rows = library.db.query(
            f"SELECT * FROM file_path WHERE {where} AND id > ? ORDER BY id LIMIT ?",
            tuple(params) + (cursor, limit),
        )
        loc_path = d["location_path"]
        loc_id = d["location_id"]
        journal = _journal.IndexJournal(library.db)
        metas: list[dict | None] = []
        messages: list[bytes] = []
        msg_rows: list[dict] = []
        resolved: dict[int, str] = {}  # row id -> cas from journal/dirty-range
        # row id -> (key, identity, cas, chunk cache, prior entry) to
        # vouch after commit; the prior entry lets an unchanged-content
        # re-record (mtime-only touch) keep its thumb/media/phash vouches
        to_record: dict[int, tuple] = {}
        jstats = {"hit": 0, "dirty": 0, "dirty_chunks": 0}
        for row in rows:
            full = _row_full_path(loc_path, row)
            size = blob_u64(row["size_in_bytes_bytes"]) or 0
            key = _journal.key_of(row)
            if size == 0:
                metas.append({"row": row, "cas_id": None})
                # journal the empty file (cas sentinel "") so warm-pass
                # walks get a `hit` instead of an eternal miss
                ident = _journal.stat_identity(full)
                if ident is not None:
                    to_record[row["id"]] = (key, ident, "", None, None)
                continue
            ident = _journal.stat_identity(full)
            entry = None
            if ident is not None:
                # the walker already counted this file's verdict this
                # pass — don't double-count the invalidation here
                verdict, entry = journal.lookup(
                    loc_id, key, ident, count_invalidated=False
                )
                if verdict == _journal.HIT and entry.cas_id:
                    # vouched: skip the read, the hash, and the transfer
                    resolved[row["id"]] = entry.cas_id
                    journal.bytes_saved(cas.message_len(size),
                                        location_id=loc_id)
                    jstats["hit"] += 1
                    metas.append({"row": row, "cas_id": "journal"})
                    continue
            try:
                msg = cas.read_message(full, size)
            except OSError as e:
                metas.append(None)
                logger.debug("identifier: unreadable %s: %s", full, e)
                continue
            if (
                ident is not None
                and entry is not None
                and entry.chunks is not None
                and entry.chunks.msg_len == len(msg)
                and len(msg) > cas.CHUNK_LEN
            ):
                try:
                    cas_id, cache, n_dirty, hashed = cas.dirty_range_rehash(
                        msg, entry.chunks
                    )
                except ValueError:
                    cache = None
                else:
                    resolved[row["id"]] = cas_id
                    to_record[row["id"]] = (key, ident, cas_id, cache, entry)
                    journal.bytes_saved(len(msg) - hashed,
                                        location_id=loc_id)
                    _tm.INDEX_BYTES_HASHED.inc(hashed)
                    jstats["dirty"] += 1
                    jstats["dirty_chunks"] += n_dirty
                    metas.append({"row": row, "cas_id": "journal"})
                    continue
            messages.append(msg)
            msg_rows.append(row)
            metas.append({"row": row, "cas_id": "pending"})
            if ident is not None:
                # cas filled in post-hash; digest-only chunk cache so the
                # FIRST in-place modification can already diff chunks
                to_record[row["id"]] = (key, ident, None,
                                        cas.build_chunk_cache(msg), entry)
        backend = d["backend"]
        use_device = backend in ("tpu", "device") or (
            backend == "auto" and cas._device_available()
        )
        if use_device and messages:
            try:
                fin = cas.cas_ids_begin(messages)  # async dispatch NOW
            except Exception:
                fin = None

            def finisher(fin=fin, messages=messages, backend=backend):
                # JAX dispatch is async — device failures usually surface
                # at materialization, so the fallback wraps the FINISH
                # (explicit "tpu" stays strict; "auto" degrades to host)
                if fin is not None:
                    try:
                        return fin()
                    except Exception:
                        if backend != "auto":
                            raise
                        logger.warning("device hashing failed; host fallback")
                elif backend != "auto":
                    raise RuntimeError("device dispatch failed")
                return cas.cas_ids(messages, "cpu")

        else:
            finisher = lambda: cas.cas_ids(messages, backend)
        return (rows, metas, messages, msg_rows, finisher, resolved,
                to_record, jstats, limit)

    def _window_limit(self) -> int:
        """Rows for the next cursor window. An explicit init
        ``chunk_size`` pins it; device backends read the LIVE
        "identify" PipelinePolicy (the autotuner's seam — each fetch
        sees the current window sizing); CPU backends keep the
        reference parity chunk recorded at init."""
        d = self.data
        if self.init.get("chunk_size"):
            return d["chunk_size"]
        if d["backend"] in ("tpu", "device", "auto"):
            from ...parallel.mesh import accelerator_count

            return _autotune.policy("identify").identify_window_rows(
                accelerator_count()
            )
        return d["chunk_size"]

    async def execute_step(self, ctx: JobContext, step: dict, step_number: int) -> StepResult:
        import asyncio

        from ...parallel import WindowPipeline
        from ...parallel.mesh import accelerator_count

        library = ctx.library
        d = self.data
        if not self._profiling:
            # optional device profile around the pipeline driver
            # (SD_JAX_PROFILE=<logdir>; no-op on CPU-only CI). Armed
            # lazily like the pipeline below, so a pause (whose cleanup
            # released the profiler hold) re-arms on resume instead of
            # truncating the capture at the first preemption.
            self._profiling = _profiler.profile_start("identify")
        if self._pipeline is None:
            # The producer chains cursor windows back-to-back: window
            # N+1's disk reads and device dispatch start as soon as N's
            # reads finish, so up to feeder-depth transfers are in
            # flight while this step's hashes complete and its DB writes
            # run (SURVEY §7 hard part #2). Fetches are side-effect-free,
            # so a pause/resume simply re-reads in-flight windows. The
            # depth is a LIVE policy read (autotuner seam): each parked
            # window re-checks the current bound.
            def fetch(cursor):
                window = self._fetch_window(library, cursor)
                rows = window[0]
                if not rows:
                    return None
                return rows[-1]["id"], window

            self._pipeline = WindowPipeline(
                fetch, d["cursor"],
                depth=lambda: _autotune.policy("identify").feeder_depth(
                    accelerator_count()
                ),
                # window[2] = the sampled messages riding the H2D link
                measure=lambda w: sum(len(m) for m in w[2]),
            )

        t0 = time.perf_counter()
        window = await asyncio.to_thread(self._pipeline.take)
        take_time = time.perf_counter() - t0
        if window is None:
            return StepResult()
        (rows, metas, messages, msg_rows, finisher, resolved, to_record,
         jstats, limit) = window
        d["cursor"] = rows[-1]["id"]

        _tm.IDENTIFIER_BATCH_FILL.observe(len(rows) / limit)
        msg_bytes = sum(len(m) for m in messages)
        async with span("identify.hash", nbytes=msg_bytes) as hash_span:
            cas_ids = await asyncio.to_thread(finisher)
            if jstats["hit"] or jstats["dirty"]:
                # journal verdict on the trace: how much of this window
                # the journal spared the device
                hash_span.annotate(
                    journal_hits=jstats["hit"],
                    journal_dirty_rehash=jstats["dirty"],
                    journal_dirty_chunks=jstats["dirty_chunks"],
                )
        _tm.INDEX_BYTES_HASHED.inc(msg_bytes)
        # run_metadata keeps its historical take+finish meaning; the
        # STAGE metric must cover only the finisher, or feeder wait
        # (its own series) would masquerade as device-hash time
        hash_time = time.perf_counter() - t0
        _tm.IDENTIFIER_STAGE_SECONDS.observe(hash_span.duration,
                                             stage="hash")

        by_row_id = {r["id"]: c for r, c in zip(msg_rows, cas_ids)}
        by_row_id.update(resolved)

        t1 = time.perf_counter()
        async with span("identify.db"):
            created, linked = self._link_objects(library, rows, by_row_id)
            # journal vouches ONLY after the cas/object sync write
            # committed: a crash in between costs a redundant rehash on
            # resume, never a journal entry ahead of the DB
            records = []
            for row_id, (key, ident, cas_hex, cache, carry) in to_record.items():
                if cas_hex is None:
                    cas_hex = by_row_id.get(row_id)
                if cas_hex is not None:  # "" = vouched-empty sentinel
                    records.append((key, ident, cas_hex, cache, carry))
            _journal.IndexJournal(library.db).record_many(
                d["location_id"], records
            )
        db_time = time.perf_counter() - t1
        _tm.IDENTIFIER_STAGE_SECONDS.observe(db_time, stage="db")
        _tm.IDENTIFIER_FILES.inc(len(rows))
        # the per-batch device vs host split the TPU capacity model
        # needs: finisher = device materialization; window wait + DB
        # linking = host
        _tm.PIPELINE_DEVICE_SECONDS.observe(hash_span.duration,
                                            pipeline="identify")
        _tm.PIPELINE_HOST_SECONDS.observe(take_time + db_time,
                                          pipeline="identify")

        errors = [f"unreadable file_path {r['id']}" for m, r in zip(metas, rows) if m is None]
        # the step count was estimated from the STATIC window at init;
        # if the autotuner shrank windows mid-job there are more windows
        # than steps — on the last step, keep draining until the cursor
        # is exhausted (an extra step against a dry pipeline no-ops)
        more_steps = [] if self.steps else [{"kind": "identify"}]
        return StepResult(
            errors=errors,
            more_steps=more_steps,
            metadata={
                "created_objects": self.run_metadata["created_objects"] + created,
                "linked_objects": self.run_metadata["linked_objects"] + linked,
                "hash_time": round(self.run_metadata["hash_time"] + hash_time, 4),
                "db_time": round(self.run_metadata["db_time"] + db_time, 4),
                "journal_hits": (
                    self.run_metadata.get("journal_hits", 0) + jstats["hit"]
                ),
                "journal_dirty_rehash": (
                    self.run_metadata.get("journal_dirty_rehash", 0)
                    + jstats["dirty"]
                ),
            },
        )

    def _link_objects(
        self, library, rows: list[dict], cas_by_row_id: dict[int, str]
    ) -> tuple[int, int]:
        """cas_id updates + object dedupe/create/connect in one sync
        write (ref:mod.rs:157-347)."""
        sync = library.sync
        ops = []
        created = linked = 0

        # existing objects for these cas_ids
        distinct = sorted({c for c in cas_by_row_id.values()})
        existing: dict[str, tuple[int, bytes]] = {}
        if distinct:
            qmarks = ",".join("?" for _ in distinct)
            for row in library.db.query(
                f"SELECT fp.cas_id, fp.object_id, o.pub_id AS object_pub FROM file_path fp "
                f"JOIN object o ON o.id = fp.object_id "
                f"WHERE fp.cas_id IN ({qmarks}) AND fp.object_id IS NOT NULL",
                tuple(distinct),
            ):
                existing.setdefault(row["cas_id"], (row["object_id"], row["object_pub"]))

        new_objects: dict[str, tuple[bytes, dict]] = {}  # cas -> (obj pub_id, row)
        updates: list[tuple[dict, str, int | None, bytes | None]] = []
        for row in rows:
            cas_id = cas_by_row_id.get(row["id"])
            if cas_id is None:
                continue
            if cas_id in existing:
                obj_id, obj_pub = existing[cas_id]
                updates.append((row, cas_id, obj_id, obj_pub))
                linked += 1
            elif cas_id in new_objects:
                updates.append((row, cas_id, None, new_objects[cas_id][0]))
                linked += 1
            else:
                obj_pub = new_pub_id()
                new_objects[cas_id] = (obj_pub, row)
                updates.append((row, cas_id, None, obj_pub))
                created += 1

        date_created = now_iso()
        obj_rows: dict[bytes, int] = {}

        def writes(conn):
            # create missing objects
            for cas_id, (obj_pub, src_row) in new_objects.items():
                kind = _kind_for_row(src_row)
                cur = conn.execute(
                    "INSERT INTO object (pub_id, kind, date_created) VALUES (?,?,?)",
                    (obj_pub, int(kind), date_created),
                )
                obj_rows[obj_pub] = cur.lastrowid
            # connect + cas updates
            for row, cas_id, obj_id, obj_pub in updates:
                if obj_id is None and obj_pub is not None:
                    obj_id = obj_rows.get(obj_pub)
                conn.execute(
                    "UPDATE file_path SET cas_id = ?, object_id = ? WHERE id = ?",
                    (cas_id, obj_id, row["id"]),
                )

        for cas_id, (obj_pub, src_row) in new_objects.items():
            kind = _kind_for_row(src_row)
            ops.extend(
                sync.shared_create(
                    "object", obj_pub.hex(),
                    [("kind", int(kind)), ("date_created", date_created)],
                )
            )
        for row, cas_id, _obj_id, obj_pub in updates:
            rid = row["pub_id"].hex()
            ops.append(sync.shared_update("file_path", rid, "cas_id", cas_id))
            if obj_pub is not None:
                ops.append(
                    sync.shared_update("file_path", rid, "object_id", obj_pub.hex())
                )

        sync.write_ops(ops, writes)
        return created, linked

    def cleanup(self) -> None:
        """Every exit path (done/pause/cancel/fail) stops the window
        pipeline and keeps its stats."""
        if self._profiling:
            self._profiling = False
            _profiler.profile_stop()
        if self._pipeline is not None:
            stats = self._pipeline.stats
            self.run_metadata["prefetch_hits"] = stats.prefetch_hits
            self.run_metadata["prefetch_misses"] = stats.prefetch_misses
            self._pipeline.close()
            self._pipeline = None

    async def finalize(self, ctx: JobContext) -> Any:
        self.cleanup()
        ctx.progress(message="identification complete", phase="done")
        return dict(self.run_metadata)


