"""Duplicate detection — pHash job + grouping query.

BASELINE.json config 5. The job walks image objects that lack an
`object.phash`, decodes the *originals* (JPEG draft mode decodes at
1/8 DCT scale, so this is cheap and avoids the distance inflation of
re-hashing webp-q30 thumbnails; the thumbnail is only the fallback),
batches 32×32 grayscale planes, and runs the device pHash
(ops/phash_jax.py). `find_duplicates` then groups objects by Hamming
distance via blockwise MXU matmuls. Exact-duplicate grouping by cas_id
(the reference's only dedup, ref:core/src/object/file_identifier
object reuse by cas_id) falls out of the same query.
"""

from __future__ import annotations

import logging
import os
from typing import Any

import numpy as np

from ..files.kind import ObjectKind
from ..jobs import StatefulJob
from ..jobs.job import JobContext, StepResult
from ..jobs.manager import register_job
from ..location.indexer import journal as _journal
from ..ops import phash_jax

logger = logging.getLogger(__name__)

CHUNK = 64


@register_job
class DuplicateDetectorJob(StatefulJob):
    """init: {location_id?, threshold?} — hashes image objects missing
    a phash; finalize records the duplicate groups found."""

    NAME = "duplicate_detector"
    IS_BATCHED = True

    async def init_job(self, ctx: JobContext) -> None:
        db = ctx.library.db
        conds = ["o.kind = ?", "o.phash IS NULL", "fp.cas_id IS NOT NULL"]
        params: list[Any] = [int(ObjectKind.Image)]
        if self.init.get("location_id"):
            conds.append("fp.location_id = ?")
            params.append(int(self.init["location_id"]))
        rows = db.query(
            "SELECT o.id AS object_id, fp.cas_id, fp.location_id, "
            "fp.materialized_path, fp.name, fp.extension, fp.is_dir, "
            "fp.size_in_bytes_bytes "
            "FROM object o JOIN file_path fp ON fp.object_id = o.id "
            f"WHERE {' AND '.join(conds)} GROUP BY o.id",
            params,
        )
        for off in range(0, len(rows), CHUNK):
            self.steps.append({"rows": rows[off : off + CHUNK]})
        self.run_metadata.update(hashed=0, skipped=0)
        ctx.progress(
            task_count=len(self.steps),
            message=f"hashing {len(rows)} images",
            phase="phash",
        )

    def _location(self, ctx: JobContext, location_id: int) -> dict | None:
        locs = self.data.setdefault("_loc_cache", {})
        loc = locs.get(location_id)
        if loc is None:
            loc = ctx.library.db.find_one("location", id=location_id)
            locs[location_id] = loc
        return loc

    def _decode_gray(self, ctx: JobContext, row: dict) -> np.ndarray | None:
        """Original-first decode: JPEG draft mode pulls a 1/8-scale DCT
        decode, so cost stays low while avoiding the distance inflation
        of re-hashing webp-q30 (possibly upscaled) thumbnails; the
        thumbnail is the fallback when the original is gone/undecodable."""
        from PIL import Image

        loc = self._location(ctx, row["location_id"])
        if loc is not None:
            from ..files.isolated_path import full_path_from_db_row

            path = full_path_from_db_row(loc["path"], row)
            try:
                with Image.open(path) as img:
                    if img.format == "JPEG":
                        img.draft("RGB", (phash_jax.DCT_SIZE, phash_jax.DCT_SIZE))
                    return phash_jax.to_gray32(np.asarray(img.convert("RGBA")))
            except Exception:
                pass
        node = getattr(ctx.library, "node", None)
        if node is not None:
            thumb = node.thumbnailer.store.path_for(
                str(ctx.library.id), row["cas_id"]
            )
            if os.path.exists(thumb):
                try:
                    with Image.open(thumb) as img:
                        return phash_jax.to_gray32(
                            np.asarray(img.convert("RGBA"))
                        )
                except Exception:
                    pass
        return None

    def _pool_decode(self, ctx: JobContext, pool: Any,
                     pending: list[tuple[int, dict]],
                     grays: list) -> None:
        """Ship the undecoded rows' gray-plane decode (original-first
        JPEG draft, thumbnail fallback — the CPU-bound leg of a pHash
        step) to the process pool; the device phash_batch and the DB
        update stay on the owning process. Any pool failure degrades
        that row to the inline decoder — identical output either way
        (the worker runs the same PIL → to_gray32 path)."""
        import numpy as np

        from ..files.isolated_path import full_path_from_db_row
        from ..parallel import procpool as _procpool

        futs = []
        for _idx, r in pending:
            loc = self._location(ctx, r["location_id"])
            path = (
                full_path_from_db_row(loc["path"], r)
                if loc is not None else None
            )
            node = getattr(ctx.library, "node", None)
            thumb = (
                node.thumbnailer.store.path_for(
                    str(ctx.library.id), r["cas_id"])
                if node is not None else None
            )
            try:
                futs.append(pool.submit(
                    "phash.gray", {"path": path, "thumb_path": thumb},
                    rows=1,
                ))
            except _procpool.ProcPoolError:
                futs.append(None)
        for (idx, r), fut in zip(pending, futs):
            gray = None
            if fut is not None:
                try:
                    blob = fut.result(
                        _procpool.REQUEST_TIMEOUT_S)["gray"]
                    if blob is not None:
                        gray = np.frombuffer(blob, np.float32).reshape(
                            phash_jax.DCT_SIZE, phash_jax.DCT_SIZE
                        ).copy()
                except Exception:  # noqa: BLE001 - degrade inline
                    gray = self._decode_gray(ctx, r)
            else:
                gray = self._decode_gray(ctx, r)
            grays[idx] = gray

    async def execute_step(self, ctx: JobContext, step: dict, step_number: int) -> StepResult:
        import asyncio

        from ..db.database import blob_u64

        rows = step["rows"]
        journal = _journal.IndexJournal(ctx.library.db)

        def consult(r: dict):
            """Journal-vouched pHash: skip the original's full decode
            when a fresh entry for this exact cas already carries one."""
            from ..files.isolated_path import full_path_from_db_row

            loc = self._location(ctx, r["location_id"])
            if loc is None:
                return None
            # count_invalidated=False: the walker already counted this
            # pass's invalidations — keep the hit rate per-file
            verdict, entry = journal.lookup(
                r["location_id"], _journal.key_of(r),
                _journal.stat_identity(full_path_from_db_row(loc["path"], r)),
                count_invalidated=False,
            )
            if (
                verdict == _journal.HIT and entry is not None
                and entry.phash is not None and entry.cas_id == r["cas_id"]
            ):
                journal.bytes_saved(blob_u64(r["size_in_bytes_bytes"]) or 0,
                                    location_id=r["location_id"])
                return entry.phash
            return None

        def decode_all():
            from ..parallel import procpool as _procpool

            pool = _procpool.get()
            cached, grays = [], []
            pending: list[tuple[int, dict]] = []  # undecoded (idx, row)
            for r in rows:
                ph = consult(r)
                cached.append(ph)
                if ph is not None or pool is None:
                    grays.append(
                        None if ph is not None else self._decode_gray(ctx, r)
                    )
                else:
                    grays.append(None)
                    pending.append((len(grays) - 1, r))
            if pending and pool is not None:
                self._pool_decode(ctx, pool, pending, grays)
            return cached, grays

        cached, grays = await asyncio.to_thread(decode_all)
        ok = [
            (r, g) for r, g, c in zip(rows, grays, cached)
            if g is not None and c is None
        ]
        reused = [(r, c) for r, c in zip(rows, cached) if c is not None]
        skipped = len(rows) - len(ok) - len(reused)
        updates: list[tuple[bytes, int]] = [
            (ph, row["object_id"]) for row, ph in reused
        ]
        hashed_pairs: list[tuple[dict, bytes]] = []
        if ok:
            batch = np.stack([g for _r, g in ok])
            hashes = await asyncio.to_thread(phash_jax.phash_batch, batch)
            for (row, _g), h in zip(ok, hashes):
                updates.append((h.tobytes(), row["object_id"]))
                hashed_pairs.append((row, h.tobytes()))
        if updates:
            ctx.library.db.executemany(
                "UPDATE object SET phash = ? WHERE id = ?", updates
            )
            # journal writes ordered after the phash rows committed —
            # inside the `if updates:` guard so the commit provably
            # dominates the vouch (hashed_pairs ⊆ updates, so this
            # moves no work; sdlint SD017 checks the dominance)
            for row, ph in hashed_pairs:
                journal.record_phash(
                    row["location_id"], _journal.key_of(row),
                    row["cas_id"], ph
                )
        self.run_metadata["hashed"] += len(ok)
        self.run_metadata["reused"] = self.run_metadata.get("reused", 0) + len(reused)
        self.run_metadata["skipped"] += skipped
        ctx.progress(completed_task_count=step_number + 1)
        return StepResult()

    async def finalize(self, ctx: JobContext) -> Any:
        import asyncio

        self.data.pop("_loc_cache", None)  # not serializable state
        groups = await asyncio.to_thread(
            find_duplicates, ctx.library, int(self.init.get("threshold", 8))
        )
        self.run_metadata["duplicate_groups"] = len(groups)
        return {
            "hashed": self.run_metadata["hashed"],
            "duplicate_groups": len(groups),
        }


def find_duplicates(library: Any, threshold: int = 8) -> list[dict[str, Any]]:
    """Near-duplicate groups over all hashed objects + exact cas_id
    groups. Returns [{object_ids, kind: 'near'|'exact'}]."""
    rows = library.db.query(
        "SELECT id, phash FROM object WHERE phash IS NOT NULL"
    )
    near = phash_jax.duplicate_groups(
        [(r["id"], r["phash"]) for r in rows], threshold=threshold
    )
    out = [{"object_ids": g, "kind": "near"} for g in near]
    exact = library.db.query(
        "SELECT cas_id, GROUP_CONCAT(DISTINCT object_id) AS ids FROM file_path "
        "WHERE cas_id IS NOT NULL AND object_id IS NOT NULL "
        "GROUP BY cas_id HAVING COUNT(DISTINCT object_id) > 1"
    )
    for r in exact:
        out.append(
            {
                "object_ids": [int(i) for i in r["ids"].split(",")],
                "kind": "exact",
            }
        )
    # enrich with the file_path rows so clients can render the groups
    from ..db.database import blob_u64

    all_ids = sorted({oid for g in out for oid in g["object_ids"]})
    by_object: dict[int, list[dict[str, Any]]] = {}
    for off in range(0, len(all_ids), 900):  # SQLite bind-variable limit
        chunk = all_ids[off:off + 900]
        qmarks = ",".join("?" * len(chunk))
        for row in library.db.query(
            f"SELECT object_id, name, extension, materialized_path, cas_id, "
            f"size_in_bytes_bytes FROM file_path WHERE object_id IN ({qmarks})",
            tuple(chunk),
        ):
            by_object.setdefault(row["object_id"], []).append({
                "name": row["name"],
                "extension": row["extension"],
                "materialized_path": row["materialized_path"],
                "cas_id": row["cas_id"],
                "size_in_bytes": blob_u64(row["size_in_bytes_bytes"]) or 0,
            })
    for g in out:
        g["files"] = [f for oid in g["object_ids"] for f in by_object.get(oid, [])]
    return out


async def distribute_phash(
    node: Any, library: Any, location_id: int, **kwargs: Any,
) -> dict[str, Any]:
    """Distribute one location's duplicates-pHash pass as stage-typed
    WORK shards (parallel/scheduler.py STAGE_PHASH): executors reuse
    journal-vouched hashes, gray-decode through their own procpool, DCT
    in one device batch, and ship the 8-byte hashes back — the
    local-only ``object.phash`` column converges via the shipped
    results. With no P2P runtime this IS a local pass in shard
    clothing."""
    from ..location.indexer.mesh import distribute_location_stages
    from ..parallel import scheduler as _scheduler

    return await distribute_location_stages(
        node, library, location_id, [_scheduler.STAGE_PHASH], **kwargs
    )
