"""Thumbnail generation pipeline: CPU decode → TPU batch resize → webp.

Parity: ref:core/src/object/media/thumbnail/process.rs:394-473
(`generate_image_thumbnail` / `generate_video_thumbnail`) and
ref:crates/ffmpeg/src/movie_decoder.rs (video: preferred stream, seek
~10%, decode one frame, rotation-aware scale).

The TPU-first difference from the reference: decode stays on host
threads, but *all* resampling runs as batched `scale_and_translate`
device calls (spacedrive_tpu/ops/thumbnail_jax.py) — one compiled
program per size bucket instead of a per-image CPU resize pool.
"""

from __future__ import annotations

import io
import logging
import math
import os
from dataclasses import dataclass

import numpy as np

from ....ops import thumbnail_jax as tj

logger = logging.getLogger(__name__)

WEBP_QUALITY = 30  # ref:process.rs:440
from ..images import MAXIMUM_FILE_SIZE as MAX_FILE_SIZE  # ref:consts.rs:9

MAX_DIM = 4096  # ref:crates/images/src/consts.rs:33


def shrink_to_max_dim(arr: "np.ndarray") -> "np.ndarray":
    """Stride-downsample oversized decodes to fit the largest bucket
    (the reference rejects >4096² outright; we degrade instead)."""
    h, w = arr.shape[:2]
    if max(h, w) > MAX_DIM:
        step = math.ceil(max(h, w) / MAX_DIM)
        arr = np.ascontiguousarray(arr[::step, ::step])
    return arr

# Decodable subsets of the taxonomy (the taxonomy stays the single
# source of truth, ref:crates/file-ext; the reference fans out to the
# `image` crate / libheif / resvg / pdfium by extension,
# ref:crates/images/src/handler.rs:18-60 — HEIF/PDF need their own
# decoders and are gated out here until a native frontend lands).
from ....files.extensions import all_extensions as _all_extensions

_PIL_DECODABLE = {
    "jpg", "jpeg", "png", "gif", "bmp", "tiff", "tif", "webp", "ico",
    "apng",
}
_CV2_DECODABLE = {
    "mp4", "mov", "avi", "mkv", "webm", "m4v", "mpg", "mpeg", "mpe",
    "wmv", "flv", "3gp", "ogv", "mts", "m2ts", "m2v", "ts", "vob", "qt",
}
from ..images import HEIF_EXTENSIONS, format_image, heif_available
from ..svg import svg_available

IMAGE_EXTENSIONS = tuple(
    e for e in _all_extensions("Image") if e in _PIL_DECODABLE
) + (tuple(e for e in _all_extensions("Image") if e in HEIF_EXTENSIONS)
     if heif_available() else ())
# The native libav frontend (preferred, probed lazily at first decode
# so imports never trigger a compile) handles the full video taxonomy;
# exotic containers degrade to a per-file error on cv2-only hosts.
VIDEO_EXTENSIONS = tuple(_all_extensions("Video"))
# Document/vector formats (ref:crates/images/src/handler.rs:18-60 fans
# out to resvg + pdfium; here: librsvg via ctypes + the bundled PDF
# reader in ../pdf.py). The extension sets live in ..images — the
# single dispatch — gated here by renderer availability.
from ..images import PDF_EXTENSIONS as _PDF_EXTS
from ..images import SVG_EXTENSIONS as _SVG_EXTS

SVG_EXTENSIONS = tuple(sorted(_SVG_EXTS)) if svg_available() else ()
PDF_EXTENSIONS = tuple(sorted(_PDF_EXTS))
DOC_EXTENSIONS = PDF_EXTENSIONS + SVG_EXTENSIONS
VIDEO_SEEK_FRACTION = 0.1  # ref:movie_decoder.rs seeks ~10% in


class ThumbError(Exception):
    pass


@dataclass
class Decoded:
    """One decoded frame ready for the device batch."""
    array: np.ndarray  # HxWx4 uint8 RGBA
    target: tuple[int, int]  # (th, tw) scaled dims
    orientation: int = 1
    is_video: bool = False  # film-strip overlay on finish


def can_generate(extension: str | None) -> bool:
    e = (extension or "").lower()
    return e in IMAGE_EXTENSIONS or e in VIDEO_EXTENSIONS or \
        e in DOC_EXTENSIONS


def is_video(extension: str | None) -> bool:
    return (extension or "").lower() in VIDEO_EXTENSIONS


def decode_image(path: str) -> Decoded:
    """Decode a still image to RGBA, reading EXIF orientation.

    Uses JPEG draft-mode DCT scaling so huge photos decode near the
    target size instead of full-res (the decode-side analogue of the
    reference's resize-after-full-decode; output parity is held by the
    device resample, which always produces `scale_dimensions` dims).
    """
    from PIL import Image

    if os.path.getsize(path) > MAX_FILE_SIZE:
        raise ThumbError(f"file over {MAX_FILE_SIZE} bytes: {path}")
    with Image.open(path) as img:
        w0, h0 = img.size
        tw, th = tj.scale_dimensions(w0, h0)
        orientation = 1
        try:
            orientation = int(img.getexif().get(0x0112, 1) or 1)
        except Exception:
            pass
        if img.format == "JPEG":
            img.draft("RGB", (tw, th))  # smallest DCT scale ≥ target
        img = img.convert("RGBA")
        arr = np.asarray(img)
    arr = shrink_to_max_dim(arr)
    h, w = arr.shape[:2]
    if min(h, w) < 1:
        raise ThumbError(f"empty image: {path}")
    return Decoded(array=arr, target=(th, tw), orientation=orientation)


def needs_cpu_fallback(d: Decoded) -> bool:
    """Targets beyond the device output canvas (aspect > 4:1) resize on
    host instead of the batched device path."""
    th, tw = d.target
    return th > tj.OUT_CANVAS or tw > tj.OUT_CANVAS or max(
        d.array.shape[:2]
    ) > tj.BUCKETS[-1]


def decode_video_frame(path: str) -> Decoded:
    """Grab one frame ~10% into the video through the native FFmpeg
    frontend (native/movie_decoder.c — preferred stream with
    embedded-cover preference, ~10% seek, display-matrix rotation;
    ref:movie_decoder.rs:32-629, cover check :352), with cv2 as the
    fallback when libav isn't present. Target dims bound the max
    dimension to 256 (ref:process.rs:470)."""
    from ....native import video_available, video_frame

    if video_available():
        try:
            arr, rotation, is_cover = video_frame(
                path, seek_fraction=VIDEO_SEEK_FRACTION
            )
        except ValueError as exc:
            raise ThumbError(str(exc))
        if rotation % 360 and rotation % 90 == 0:
            # display matrix says rotate clockwise by `rotation`; only
            # right-angle rotations are meaningful for a raster thumb
            arr = np.ascontiguousarray(
                np.rot90(arr, k=(-rotation // 90) % 4)
            )
        arr = shrink_to_max_dim(arr)
        h, w = arr.shape[:2]
        tw, th = tj.video_dimensions(w, h)
        # embedded cover art is album art, not footage: no film strip
        return Decoded(array=arr, target=(th, tw), is_video=not is_cover)
    try:
        import cv2
    except Exception as e:  # pragma: no cover
        raise ThumbError(f"video decode unavailable: {e}")
    cap = cv2.VideoCapture(path)
    try:
        if not cap.isOpened():
            raise ThumbError(f"cannot open video: {path}")
        frames = cap.get(cv2.CAP_PROP_FRAME_COUNT) or 0
        if frames > 0:
            cap.set(cv2.CAP_PROP_POS_FRAMES, int(frames * VIDEO_SEEK_FRACTION))
        ok, frame = cap.read()
        if not ok:
            # fall back to the first frame (seek can fail near EOF)
            cap.set(cv2.CAP_PROP_POS_FRAMES, 0)
            ok, frame = cap.read()
        if not ok or frame is None:
            raise ThumbError(f"no decodable frame: {path}")
    finally:
        cap.release()
    rgb = shrink_to_max_dim(frame[:, :, ::-1])  # BGR → RGB
    h, w = rgb.shape[:2]
    arr = np.dstack([rgb, np.full((h, w, 1), 255, np.uint8)])
    tw, th = tj.video_dimensions(w, h)
    return Decoded(array=np.ascontiguousarray(arr), target=(th, tw), is_video=True)


def decode_heif_image(path: str, extension: str) -> Decoded:
    """HEIC/HEIF/AVIF through the libheif dispatch (ref:crates/images
    HEIF handler); orientation is baked in by libheif's transforms."""
    arr = shrink_to_max_dim(format_image(path, extension))
    h, w = arr.shape[:2]
    tw, th = tj.scale_dimensions(w, h)
    return Decoded(array=arr, target=(th, tw))


def decode_document(path: str, extension: str) -> Decoded:
    """SVG (ref:svg.rs:14-21, render cap 512²) and PDF first page
    (ref:pdf.rs:82-83) through the format_image dispatch; every
    failure becomes ThumbError so one bad document never aborts the
    surrounding batch."""
    try:
        arr = format_image(path, extension)
    except Exception as exc:
        raise ThumbError(f"document decode failed ({path}): {exc}")
    arr = shrink_to_max_dim(arr)
    h, w = arr.shape[:2]
    tw, th = tj.scale_dimensions(w, h)
    return Decoded(array=arr, target=(th, tw))


def decode(path: str, extension: str | None) -> Decoded:
    ext = (extension or "").lower()
    if is_video(extension):
        return decode_video_frame(path)
    if ext in HEIF_EXTENSIONS:
        return decode_heif_image(path, extension)
    if ext in SVG_EXTENSIONS or ext in PDF_EXTENSIONS:
        return decode_document(path, ext)
    return decode_image(path)


def encode_webp(arr: np.ndarray, quality: int = WEBP_QUALITY) -> bytes:
    """RGBA uint8 → webp bytes at the reference's quality 30
    (ref:process.rs:431-440)."""
    from PIL import Image

    buf = io.BytesIO()
    Image.fromarray(arr, "RGBA").save(buf, "WEBP", quality=quality)
    return buf.getvalue()


def apply_film_strip(arr: np.ndarray) -> np.ndarray:
    """Sprocket-hole side strips marking video thumbs
    (ref:crates/ffmpeg/src/film_strip.rs draws the same overlay)."""
    arr = arr.copy()
    h, w = arr.shape[:2]
    strip = max(4, min(w // 10, 20))
    hole_h = max(2, strip // 2)
    hole_w = max(2, strip // 2)
    pitch = hole_h * 3
    for x0, x1 in ((0, strip), (w - strip, w)):
        arr[:, x0:x1, :3] = (arr[:, x0:x1, :3] * 0.2).astype(np.uint8)
        cx0 = x0 + (strip - hole_w) // 2
        for y in range((pitch - hole_h) // 2, h - hole_h, pitch):
            arr[y : y + hole_h, cx0 : cx0 + hole_w, :3] = 235
    return arr


def finish(decoded: Decoded, resized: np.ndarray) -> bytes:
    """Orientation-correct the device output, overlay, and encode."""
    arr = tj.apply_orientation(resized, decoded.orientation)
    if decoded.is_video:
        arr = apply_film_strip(arr)
    return encode_webp(np.ascontiguousarray(arr))


def resize_decoded(batch: list[Decoded]) -> list[np.ndarray]:
    """One (or few, per bucket) device calls for a whole decoded batch."""
    return tj.resize_batch([d.array for d in batch], [d.target for d in batch])


def resize_cpu(d: Decoded) -> bytes:
    """Pure-CPU fallback path (extreme aspect ratios / no device): PIL
    resize with the same Triangle filter + quality."""
    from PIL import Image

    th, tw = d.target
    img = Image.fromarray(d.array, "RGBA").resize((tw, th), Image.BILINEAR)
    arr = tj.apply_orientation(np.asarray(img), d.orientation)
    if d.is_video:
        arr = apply_film_strip(arr)
    return encode_webp(np.ascontiguousarray(arr))


def generate_one_cpu(path: str, extension: str | None) -> bytes:
    return resize_cpu(decode(path, extension))
