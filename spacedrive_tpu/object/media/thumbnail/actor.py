"""The node-wide thumbnailer actor.

Parity: ref:core/src/object/media/thumbnail/{actor.rs,worker.rs,
process.rs} — a node-global actor outside the job system; jobs dispatch
batches and only await counts. Foreground batches are a priority LIFO
stack, background a FIFO queue (state.rs:23-32); background work is
throttled to `background_processing_percentage`% of cores
(process.rs:105-128); each thumb gets a 30s timeout (process.rs:172);
queues persist across crashes (state.rs); `NewThumbnail` events flow to
the node event bus (ref:core/src/api/mod.rs:54).

TPU shape: a batch is processed as [decode on host threads] →
[ONE device resize call per size bucket] → [webp encode on host
threads]; "pause/preempt" maps to batch-boundary draining, the leftover
pattern the reference uses for its queues.
"""

from __future__ import annotations

import asyncio
import collections
import itertools
import logging
import os
import secrets
from typing import Any, Sequence

from ....parallel import autotune as _autotune
from ....parallel import procpool as _procpool
from ....telemetry import metrics as _tm
from ....telemetry import span
from ....telemetry import trace as _trace
from ....utils import faults as _faults
from .process import (
    Decoded,
    ThumbError,
    can_generate,
    decode,
    finish,
    generate_one_cpu,
    needs_cpu_fallback,
    resize_cpu,
    resize_decoded,
)
from .state import Batch, load_state, save_state
from .store import ThumbnailStore, get_shard_hex

logger = logging.getLogger(__name__)

GENERATION_TIMEOUT_S = 30  # ref:process.rs:172
# images per device dispatch per accelerator: autotune.THUMB_DEVICE_BATCH
# via the "thumbnail" PipelinePolicy (read live in _device_chunk)


ThumbKey = tuple[str, str, str]  # (namespace, shard, cas_id)


class Thumbnailer:
    """`Node.thumbnailer` — see module docstring for the contract."""

    def __init__(
        self,
        data_dir: str | os.PathLike,
        event_bus: Any = None,
        background_processing_percentage: int = 50,  # ref:actor.rs:98
        use_device: bool = True,
    ):
        self.data_dir = os.fspath(data_dir)
        os.makedirs(self.data_dir, exist_ok=True)
        self.store = ThumbnailStore(self.data_dir)
        self.event_bus = event_bus
        self.use_device = use_device
        cores = os.cpu_count() or 1
        self._fg_parallelism = cores
        self.background_percentage = max(
            0, min(100, background_processing_percentage)
        )
        self._bg_parallelism = max(1, cores * self.background_percentage // 100)
        self._fg: collections.deque[Batch] = collections.deque()  # LIFO
        self._bg: collections.deque[Batch] = collections.deque()  # FIFO
        self._current: Batch | None = None  # in-flight (for persistence)
        # random base so a batch id persisted in a resumed job's state
        # can't collide with a fresh id from this process
        self._batch_ids = itertools.count((secrets.randbits(40) << 20) | 1)
        self._batch_pending: collections.Counter[int] = collections.Counter()
        self._pending: collections.Counter[str] = collections.Counter()
        self._cond: asyncio.Condition | None = None
        self._wake: asyncio.Event | None = None
        self._chunk_rows: int | None = None  # explicit override (tests);
        # None → read the live "thumbnail" PipelinePolicy per batch
        self._accel: int | None = None  # cached accelerator count
        self._worker: asyncio.Task | None = None
        self._stopped = False
        self.generated = 0
        self.skipped = 0
        self.errors = 0
        # Crash recovery: previously queued batches resume as background,
        # and are re-persisted at once so a second crash before the first
        # batch completes still loses nothing (the load deleted the file).
        # Entries whose thumbnail already landed in the store are dropped
        # here: a crash between chunk store and journal write leaves the
        # stored prefix inside the persisted batch, and re-decoding /
        # re-resizing it would redo device work the store already holds.
        for b in load_state(self.data_dir):
            kept = [
                e for e in b.entries
                if not self.store.exists(b.library_id, e[0])
            ]
            already = len(b.entries) - len(kept)
            if already:
                self.skipped += already
                _tm.THUMB_FILES.inc(already, result="skipped")
            if not kept:
                continue
            b.entries = kept
            b.background = True
            b.id = next(self._batch_ids)
            self._bg.append(b)
            self._pending[self._ns(b.library_id)] += len(b.entries)
            self._batch_pending[b.id] = len(b.entries)
        self._save()

    # ---- lifecycle -----------------------------------------------------
    def _ns(self, library_id: str | None) -> str:
        return self.store.namespace(library_id)

    def _save(self) -> None:
        batches = list(self._fg) + list(self._bg)
        if self._current is not None and self._current.entries:
            batches.insert(0, self._current)
        save_state(self.data_dir, batches)

    def _ensure_started(self) -> None:
        """Lazily bind to the running loop (actor model: one worker)."""
        if self._stopped:
            return
        if self._worker is None or self._worker.done():
            self._cond = self._cond or asyncio.Condition()
            self._wake = self._wake or asyncio.Event()
            self._loop = asyncio.get_running_loop()
            self._worker = self._loop.create_task(
                self._run(), name="thumbnailer"
            )
            if self._fg or self._bg:
                self._wake.set()

    def _kick(self) -> None:
        """Start/wake the worker. Raises RuntimeError off-loop — the
        caller then schedules `_kick_on_loop` via call_soon_threadsafe
        (asyncio.Event.set is NOT thread-safe, and enqueues arrive from
        to_thread workers — e.g. the non-indexed walker queueing
        on-the-fly thumbnails)."""
        self._ensure_started()
        assert self._wake is not None
        self._wake.set()

    def _kick_on_loop(self) -> None:
        try:
            self._kick()
        except RuntimeError:
            pass  # loop shutting down

    async def shutdown(self) -> None:
        """Persist unprocessed batches (including the in-flight
        remainder) and stop (ref:state.rs:47-75)."""
        self._stopped = True
        if self._wake is not None:
            self._wake.set()
        if self._worker is not None:
            try:
                await asyncio.wait_for(asyncio.shield(self._worker), timeout=60)
            except (asyncio.TimeoutError, asyncio.CancelledError):
                self._worker.cancel()
                try:
                    await self._worker
                except (asyncio.CancelledError, Exception):
                    pass
        self._save()
        # unblock rendezvous waiters: with the actor stopped their work
        # will never drain, and hanging a job forever is worse
        if self._cond is not None:
            async with self._cond:
                self._cond.notify_all()

    # ---- dispatch API (ref:actor.rs new_*_thumbnails_batch) ------------
    def set_background_percentage(self, pct: int) -> None:
        """Re-derive background parallelism from a percentage of cores
        (ref:actor.rs:98 `background_processing_percentage` update)."""
        cores = os.cpu_count() or 1
        self.background_percentage = max(0, min(100, pct))
        self._bg_parallelism = max(1, cores * self.background_percentage // 100)

    def new_indexed_thumbnails_batch(
        self,
        library_id: str,
        entries: Sequence[tuple[str, str] | tuple[str, str, str]],
        background: bool = False,
    ) -> int:
        """entries: (cas_id, path[, extension]); returns a batch id for
        `wait_batch`, or 0 if nothing was queued."""
        return self._enqueue(library_id, entries, background)

    def new_ephemeral_thumbnails_batch(
        self, entries: Sequence[tuple[str, str] | tuple[str, str, str]]
    ) -> int:
        return self._enqueue(None, entries, background=False)

    def _enqueue(self, library_id, entries, background) -> int:
        library_id = str(library_id) if library_id is not None else None
        norm: list[tuple[str, str, str]] = []
        for e in entries:
            cas_id, path = e[0], e[1]
            ext = (
                e[2]
                if len(e) > 2
                else os.path.splitext(path)[1].lstrip(".").lower()
            )
            if not cas_id or not can_generate(ext):
                continue
            if self.store.exists(library_id, cas_id):
                self.skipped += 1
                _tm.THUMB_FILES.inc(result="skipped")
                continue
            norm.append((cas_id, path, ext))
        if not norm:
            return 0
        batch = Batch(library_id=library_id, entries=norm, background=background)
        batch.id = next(self._batch_ids)
        # the actor worker is a separate task: the batch carries the
        # enqueueing trace (media job, watcher, ephemeral walk) across
        batch.trace = _trace.wire_current()
        if background:
            self._bg.append(batch)
        else:
            self._fg.appendleft(batch)  # LIFO priority stack
        self._pending[self._ns(library_id)] += len(norm)
        self._batch_pending[batch.id] = len(norm)
        self._save()
        # which thread are we on? asyncio.Event.set is only safe on the
        # owning loop — and once the worker is pre-started (Node.start),
        # _kick would NOT raise off-loop, so the check must be explicit
        try:
            running = asyncio.get_running_loop()
        except RuntimeError:
            running = None
        owner = getattr(self, "_loop", None)
        if running is not None and (owner is None or running is owner):
            self._kick()
        elif owner is not None and owner.is_running():
            # off-loop caller (a to_thread worker) or a foreign loop:
            # hand the kick to the owning loop
            owner.call_soon_threadsafe(self._kick_on_loop)
        # with no loop bound yet, the batch is persisted and processed
        # on first await/start()
        return batch.id

    def delete_thumbnails(self, library_id: str | None, cas_ids: list[str]) -> int:
        return self.store.remove(library_id, cas_ids)

    # ---- rendezvous (ref:job.rs WaitThumbnails) ------------------------
    async def wait_batch(self, batch_id: int) -> None:
        """Wait for one dispatched batch (ids are per-process; an
        unknown/finished id — e.g. after an actor restart — is done)."""
        if batch_id <= 0:
            return
        self._ensure_started()
        assert self._cond is not None
        async with self._cond:
            await self._cond.wait_for(
                lambda: self._stopped or self._batch_pending[batch_id] == 0
            )

    async def wait_library_batch(self, library_id: str | None) -> None:
        """Wait for a whole namespace to drain (coarser than
        `wait_batch`; unrelated background work counts too)."""
        self._ensure_started()
        ns = self._ns(library_id)
        assert self._cond is not None
        async with self._cond:
            await self._cond.wait_for(
                lambda: self._stopped or self._pending[ns] == 0
            )

    def pending_count(self, library_id: str | None) -> int:
        return self._pending[self._ns(library_id)]

    # ---- worker --------------------------------------------------------
    async def _run(self) -> None:
        assert self._wake is not None and self._cond is not None
        while not self._stopped:
            if not self._fg and not self._bg:
                self._wake.clear()
                try:
                    await asyncio.wait_for(self._wake.wait(), timeout=1.0)
                except asyncio.TimeoutError:
                    continue
            if self._stopped:
                break
            if self._fg:
                batch = self._fg.popleft()
            elif self._bg:
                batch = self._bg.popleft()
            else:
                continue
            self._current = batch
            try:
                await self._process_batch(batch)
            except asyncio.CancelledError:
                # shutdown cancelled us mid-batch: requeue the remainder
                # so shutdown's _save persists it (waiters unblock via
                # the _stopped clause in their predicates)
                self._current = None
                if batch.entries:
                    self._fg.appendleft(batch)
                raise
            except Exception:
                logger.exception("thumbnail batch failed")
                self.errors += len(batch.entries)
                await self._account(batch, len(batch.entries))
                batch.entries = []
            self._current = None
            if batch.entries:
                # drained early because _stopped flipped mid-batch
                self._fg.appendleft(batch)
            self._save()

    async def _account(self, batch: Batch, n: int) -> None:
        assert self._cond is not None
        async with self._cond:
            ns = self._ns(batch.library_id)
            self._pending[ns] -= n
            if self._pending[ns] <= 0:
                # drop zeroed keys: a Counter with zero values is still
                # truthy, which turns `while thumbnailer._pending` polls
                # into infinite loops
                del self._pending[ns]
            self._batch_pending[batch.id] -= n
            if self._batch_pending[batch.id] <= 0:
                del self._batch_pending[batch.id]
            self._cond.notify_all()

    async def _process_batch(self, batch: Batch) -> None:
        with _trace.use(_trace.TraceContext.from_wire(batch.trace)):
            pool = self._pool()
            if pool is not None:
                await self._process_batch_pool(batch, pool)
            else:
                await self._process_batch_traced(batch)

    def _pool(self) -> Any:
        """The running process pool, but ONLY for the software path:
        device actors keep the batched device resize (the pool never
        owns the accelerator) and their rare extreme-aspect stragglers
        stay inline. ``SD_PROCS=0`` always lands here as None — the
        golden single-process pipeline below."""
        if self.use_device:
            return None
        return _procpool.get()

    async def _process_batch_pool(self, batch: Batch, pool: Any) -> None:
        """Software-path batches ride the multi-process plane: decode →
        CPU resize → orientation/overlay → webp encode run in pool
        workers (``thumb.cpu`` = ``process.generate_one_cpu``, the
        exact inline host path, so the stored webp bytes are
        bit-identical either way). Store, events, and accounting stay
        on this process; entries are consumed strictly in order, the
        same crash-resume contract as the inline pipeline. Jobs ship
        per image — decode dominates the IPC tax by orders of
        magnitude, and variable image sizes would skew any multi-image
        quantum — with in-flight bounded by the worker count."""
        entries = list(batch.entries)
        done = 0
        chunk_rows = self._device_chunk()
        # keep workers fed (2× pool width) but honor the background
        # throttle: a background batch may not saturate the pool any
        # more than it may saturate the host thread budget
        width = _procpool.procs() * 2
        if batch.background:
            width = min(width, max(1, self._bg_parallelism))
        sem = asyncio.Semaphore(max(1, width))

        async def _one(entry: tuple[str, str, str]) -> bytes | None:
            _cas_id, path, ext = entry
            async with sem:
                try:
                    reply = await asyncio.wait_for(
                        pool.run("thumb.cpu", {"path": path, "ext": ext}),
                        timeout=GENERATION_TIMEOUT_S,
                    )
                    webp = reply.get("webp")
                    if webp is None:
                        # typed image failure from the worker — a
                        # retry would decode the same bad bytes again
                        logger.debug("thumb failed %s: %s", path,
                                     reply.get("error"))
                    return webp
                except (_procpool.ProcPoolError, asyncio.TimeoutError):
                    # pool-side INFRASTRUCTURE failure is not evidence
                    # the image is bad: one inline retry before erroring
                    try:
                        return await asyncio.wait_for(
                            asyncio.to_thread(generate_one_cpu, path, ext),
                            timeout=GENERATION_TIMEOUT_S,
                        )
                    except (ThumbError, asyncio.TimeoutError, OSError) as e:
                        logger.debug("thumb failed %s: %s", path, e)
                        return None

        pos = 0
        while pos < len(entries) and not self._stopped:
            chunk = entries[pos:pos + chunk_rows]
            pos += len(chunk)
            _tm.THUMB_BATCH_FILL.observe(len(chunk) / chunk_rows)
            # workers account the per-image stage time (shipped back in
            # their telemetry deltas); this span is the owner-side wall
            # the attribution engine files under host_cpu
            async with span("procpool.thumb_cpu") as pool_span:
                webps = await asyncio.gather(*(_one(e) for e in chunk))
            _tm.PIPELINE_HOST_SECONDS.observe(
                pool_span.duration, pipeline="thumbnail")
            for (cas_id, _path, _ext), webp in zip(chunk, webps):
                if webp is None:
                    self.errors += 1
                    _tm.THUMB_FILES.inc(result="error")
                else:
                    self._store_one(batch.library_id, cas_id, webp)
            if _faults.hit("thumbnail.persist") is not None:
                # same crash window as the inline pipeline: chunk
                # stored, journal/accounting not yet — resume must
                # skip exactly the stored prefix
                raise _faults.InjectedCrash(
                    "injected crash between chunk store and journal write"
                )
            done += len(chunk)
            batch.entries = entries[done:]
            await self._account(batch, len(chunk))

    def _device_chunk(self) -> int:
        """Images per device dispatch: the live "thumbnail"
        PipelinePolicy scaled by the accelerator count (a dp-sharded
        resize splits the chunk over every chip, so each still sees the
        per-device batch). CPU-only hosts keep the parity base (virtual
        devices share cores — bigger host chunks would only add
        latency). Read per batch, so an autotuner adjustment lands on
        the next batch; an explicit ``_chunk_rows`` (tests, chaos
        harness) always wins."""
        if self._chunk_rows is not None:
            return self._chunk_rows
        if self._accel is None:
            n = 1
            if self.use_device:
                try:
                    from ....parallel.mesh import accelerator_count

                    n = accelerator_count()
                except Exception:  # noqa: BLE001 - no usable jax
                    n = 1
            self._accel = n
        return _autotune.policy("thumbnail").thumb_chunk_rows(self._accel)

    async def _process_batch_traced(self, batch: Batch) -> None:
        """Stage-overlapped chunk loop.

        The per-chunk stages — host decode → device resize → host webp
        encode + store — are independent across chunks, so they run as
        a 3-deep software pipeline: while chunk N rides the device,
        chunk N+1 is decoding on the thread pool and chunk N−1 is
        encoding/storing. Encode tasks are chained (at most one
        outstanding, awaited before the next starts), so entries are
        consumed strictly in order — the persisted resume state only
        ever drops a prefix whose thumbnails are already on disk.
        """
        parallelism = (
            self._bg_parallelism if batch.background else self._fg_parallelism
        )
        sem = asyncio.Semaphore(parallelism)
        chunk_rows = self._device_chunk()

        async def _decode(entry: tuple[str, str, str]) -> Decoded | None:
            cas_id, path, ext = entry
            async with sem:
                try:
                    return await asyncio.wait_for(
                        asyncio.to_thread(decode, path, ext),
                        timeout=GENERATION_TIMEOUT_S,
                    )
                except (ThumbError, asyncio.TimeoutError, OSError) as e:
                    logger.debug("thumb decode failed %s: %s", path, e)
                    return None

        async def _decode_chunk(chunk):
            async with span("thumbnail.decode") as decode_span:
                decoded = await asyncio.gather(*(_decode(e) for e in chunk))
            _tm.THUMB_STAGE_SECONDS.observe(
                decode_span.duration, stage="decode")
            _tm.PIPELINE_HOST_SECONDS.observe(
                decode_span.duration, pipeline="thumbnail")
            return decoded

        entries = list(batch.entries)
        done = 0  # entries fully stored+accounted (a prefix of `entries`)

        async def _encode_chunk(chunk, decoded, device_idx, ds, resized):
            """Final stage for one chunk: webp-encode device outputs,
            run host-path stragglers, store, account, and release the
            chunk from the batch's persisted remainder."""
            nonlocal done
            for d in decoded:
                if d is None:
                    self.errors += 1
                    _tm.THUMB_FILES.inc(result="error")
            # host-path stragglers (extreme aspect / no device),
            # concurrent now that they ride their own pipeline stage
            fallback = [
                i for i, d in enumerate(decoded)
                if d is not None and i not in device_idx
            ]
            if device_idx and resized is None:
                # the device stage failed past the degradation ladder:
                # degrade the chunk to the CPU reference resize instead
                # of erroring it — slower pixels beat missing thumbnails
                from ....telemetry.events import RESILIENCE_EVENTS

                RESILIENCE_EVENTS.emit(
                    "thumbnail_cpu_fallback", entries=len(device_idx),
                )
                fallback = fallback + list(device_idx)
                device_idx = []
                ds = []

            async def _one_fallback(i):
                async with sem:  # same host-thread budget as decode
                    try:
                        webp = await asyncio.wait_for(
                            asyncio.to_thread(resize_cpu, decoded[i]),
                            timeout=GENERATION_TIMEOUT_S,
                        )
                        self._store_one(batch.library_id, chunk[i][0], webp)
                    except Exception:
                        self.errors += 1
                        _tm.THUMB_FILES.inc(result="error")

            async def _one_finish(d, r):
                async with sem:
                    return await asyncio.to_thread(finish, d, r)

            async with span("thumbnail.encode") as encode_span:
                await asyncio.gather(*(_one_fallback(i) for i in fallback))
                # device_idx is non-empty only when the device stage
                # produced output — a wholesale failure was rerouted to
                # the CPU fallback above
                if device_idx:
                    try:
                        webps = await asyncio.gather(
                            *(
                                _one_finish(d, r)
                                for d, r in zip(ds, resized)
                            )
                        )
                        for i, webp in zip(device_idx, webps):
                            self._store_one(
                                batch.library_id, chunk[i][0], webp)
                    except Exception:
                        logger.exception("thumbnail encode chunk failed")
                        self.errors += len(device_idx)
                        _tm.THUMB_FILES.inc(
                            len(device_idx), result="error")
            _tm.THUMB_STAGE_SECONDS.observe(
                encode_span.duration, stage="encode")
            _tm.PIPELINE_HOST_SECONDS.observe(
                encode_span.duration, pipeline="thumbnail")
            if _faults.hit("thumbnail.persist") is not None:
                # simulated process death in the window between "chunk
                # stored" and "journal dropped it": InjectedCrash is a
                # BaseException, so no recovery path below can absorb it
                # — only a fresh actor (standing in for a fresh process)
                # resumes, and the resume filter must skip this chunk
                raise _faults.InjectedCrash(
                    "injected crash between chunk store and journal write"
                )
            done += len(chunk)
            # only now may the resume state drop this chunk
            batch.entries = entries[done:]
            await self._account(batch, len(chunk))

        pos = 0  # decode cursor
        decode_task: asyncio.Task | None = None
        encode_task: asyncio.Task | None = None
        try:
            while pos < len(entries) and not self._stopped:
                chunk = entries[pos:pos + chunk_rows]
                if decode_task is None:
                    decode_task = asyncio.ensure_future(_decode_chunk(chunk))
                decoded = await decode_task
                decode_task = None
                pos += len(chunk)
                if pos < len(entries) and not self._stopped:
                    # chunk N+1 decodes while chunk N rides the device
                    decode_task = asyncio.ensure_future(
                        _decode_chunk(entries[pos:pos + chunk_rows])
                    )
                _tm.THUMB_BATCH_FILL.observe(len(chunk) / chunk_rows)
                device_idx = [
                    i for i, d in enumerate(decoded)
                    if d is not None and self.use_device
                    and not needs_cpu_fallback(d)
                ]
                ds = [decoded[i] for i in device_idx]
                resized = None
                if ds:
                    try:
                        async with span(
                            "thumbnail.device",
                            nbytes=sum(d.array.nbytes for d in ds),
                        ) as device_span:
                            resized = await asyncio.to_thread(
                                resize_decoded, ds)
                        _tm.THUMB_STAGE_SECONDS.observe(
                            device_span.duration, stage="device")
                        _tm.PIPELINE_DEVICE_SECONDS.observe(
                            device_span.duration, pipeline="thumbnail")
                    except Exception:
                        logger.exception("device resize batch failed")
                        resized = None
                if encode_task is not None:
                    await encode_task  # chunk N−1 finishes storing first
                encode_task = asyncio.ensure_future(
                    _encode_chunk(
                        chunk, decoded, device_idx, ds, resized)
                )
            if encode_task is not None:
                await encode_task
                encode_task = None
        finally:
            # cancel the read-ahead and retrieve it so no orphan warns;
            # the trailing encode (started work) must complete so its
            # thumbnails are stored before the remainder persists
            if decode_task is not None:
                decode_task.cancel()
                try:
                    await decode_task
                except (asyncio.CancelledError, Exception):  # noqa: BLE001
                    pass
            while encode_task is not None and not encode_task.done():
                # started encode work MUST finish before the remainder
                # persists (its chunk's entries are dropped by done+=),
                # so keep re-awaiting across repeated cancellations —
                # the shield keeps each cancel from reaching the encode
                try:
                    await asyncio.shield(encode_task)
                except asyncio.CancelledError:
                    continue
                except Exception:  # noqa: BLE001 - logged in the task
                    break

    def _store_one(self, library_id: str | None, cas_id: str, webp: bytes) -> None:
        self.store.write(library_id, cas_id, webp)
        self.generated += 1
        _tm.THUMB_FILES.inc(result="generated")
        if self.event_bus is not None:
            self.event_bus.emit(
                {
                    "type": "NewThumbnail",
                    "thumb_key": (
                        self._ns(library_id),
                        get_shard_hex(cas_id),
                        cas_id,
                    ),
                }
            )


async def distribute_thumbnails(
    node: Any, library: Any, location_id: int, **kwargs: Any,
) -> dict[str, Any]:
    """Distribute one location's thumbnail pass across library peers as
    stage-typed WORK shards (parallel/scheduler.py STAGE_THUMB): every
    executor consults its own journal + store first, encodes through
    its own procpool, and ships the webp bytes back so the
    coordinator's store converges bit-identical. With no P2P runtime
    this IS a local pass in shard clothing."""
    from ....location.indexer.mesh import distribute_location_stages
    from ....parallel import scheduler as _scheduler

    return await distribute_location_stages(
        node, library, location_id, [_scheduler.STAGE_THUMB], **kwargs
    )
