"""Node-level thumbnailer: TPU batch-resize pipeline behind an actor.

Parity: ref:core/src/object/media/thumbnail/ — the node-wide actor
outside the job system (actor.rs), priority LIFO foreground vs FIFO
background queues + bounded background parallelism (process.rs:105-128),
30s per-thumb timeout (process.rs:172), crash-resumable pending state
(state.rs), sharded webp storage (shard.rs), versioned directory
(directory.rs), and orphan cleanup (clean_up.rs).
"""

from .actor import Thumbnailer, ThumbKey
from .store import ThumbnailStore, get_shard_hex

__all__ = ["Thumbnailer", "ThumbKey", "ThumbnailStore", "get_shard_hex"]

TARGET_PX = 262144  # ref:thumbnail/mod.rs:45
WEBP_QUALITY = 30  # ref:thumbnail/mod.rs:49
VIDEO_THUMB_SIZE = 256  # ref:thumbnail/process.rs:470
GENERATION_TIMEOUT_S = 30  # ref:thumbnail/process.rs:172
EPHEMERAL_DIR = "ephemeral"  # ref:thumbnail/mod.rs (EPHEMERAL_DIR)
