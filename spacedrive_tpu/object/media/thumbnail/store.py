"""Sharded on-disk thumbnail storage with versioned directory layout.

Parity: ref:core/src/object/media/thumbnail/{shard.rs,directory.rs,
clean_up.rs} — thumbs live at
`<data>/thumbnails/<library_id | ephemeral>/<cas_id[0..3]>/<cas_id>.webp`
(actor.rs:53-62, shard.rs:10), the directory carries a version file
migrated by the same version-manager pattern as configs (directory.rs),
and cleanup removes shards/files whose cas_ids no longer exist in the
library DB (clean_up.rs).
"""

from __future__ import annotations

import logging
import os
import shutil

from ....utils.version_manager import VersionManager

logger = logging.getLogger(__name__)

THUMBNAIL_DIR_VERSION = 1
_VERSION_FILE = "version.txt"
EPHEMERAL_DIR = "ephemeral"

_dir_vm = VersionManager(THUMBNAIL_DIR_VERSION)


def get_shard_hex(cas_id: str) -> str:
    """First 3 hex chars → up to 4096 shard dirs (ref:shard.rs:10)."""
    return cas_id[:3]


class ThumbnailStore:
    """The `thumbnails/` tree under a node's data dir."""

    def __init__(self, data_dir: str | os.PathLike):
        self.root = os.path.join(os.fspath(data_dir), "thumbnails")
        os.makedirs(self.root, exist_ok=True)
        self._migrate_directory()

    def _migrate_directory(self) -> None:
        """Versioned layout migration (ref:directory.rs)."""
        vfile = os.path.join(self.root, _VERSION_FILE)
        try:
            with open(vfile) as f:
                version = int(f.read().strip() or 0)
        except (OSError, ValueError):
            version = 0
        if version != THUMBNAIL_DIR_VERSION:
            # v0 → v1: flat files move into shard dirs
            for name in os.listdir(self.root):
                if name.endswith(".webp") and os.path.isfile(
                    os.path.join(self.root, name)
                ):
                    cas = name[: -len(".webp")]
                    dst = os.path.join(self.root, EPHEMERAL_DIR, get_shard_hex(cas))
                    os.makedirs(dst, exist_ok=True)
                    os.replace(
                        os.path.join(self.root, name), os.path.join(dst, name)
                    )
            with open(vfile, "w") as f:
                f.write(str(THUMBNAIL_DIR_VERSION))

    def namespace(self, library_id) -> str:
        """Namespace dir: stringified library id (UUIDs welcome) or the
        ephemeral dir (ref:actor.rs:53-62)."""
        return str(library_id) if library_id is not None else EPHEMERAL_DIR

    def path_for(self, library_id: str | None, cas_id: str) -> str:
        return os.path.join(
            self.root, self.namespace(library_id), get_shard_hex(cas_id),
            f"{cas_id}.webp",
        )

    def exists(self, library_id: str | None, cas_id: str) -> bool:
        return os.path.exists(self.path_for(library_id, cas_id))

    def write(self, library_id: str | None, cas_id: str, webp: bytes) -> str:
        path = self.path_for(library_id, cas_id)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(webp)
        os.replace(tmp, path)  # atomic publish
        return path

    def remove(self, library_id: str | None, cas_ids: list[str]) -> int:
        """Delete thumbs by cas_id (ref:actor.rs delete channel)."""
        n = 0
        for cas in cas_ids:
            try:
                os.remove(self.path_for(library_id, cas))
                n += 1
            except OSError:
                pass
        return n

    def remove_library(self, library_id: str) -> None:
        shutil.rmtree(os.path.join(self.root, library_id), ignore_errors=True)

    def cleanup(self, library_id: str, live_cas_ids: set[str]) -> int:
        """Remove thumbs whose cas_id is no longer referenced
        (ref:clean_up.rs process_clean_up)."""
        base = os.path.join(self.root, library_id)
        removed = 0
        if not os.path.isdir(base):
            return 0
        for shard in os.listdir(base):
            sdir = os.path.join(base, shard)
            if not os.path.isdir(sdir):
                continue
            for name in os.listdir(sdir):
                if name.endswith(".webp") and name[: -len(".webp")] not in live_cas_ids:
                    try:
                        os.remove(os.path.join(sdir, name))
                        removed += 1
                    except OSError:
                        pass
            try:
                os.rmdir(sdir)  # only succeeds when empty
            except OSError:
                pass
        return removed
