"""Crash-resumable pending-thumbnail state.

Parity: ref:core/src/object/media/thumbnail/state.rs:23-115 — the actor
persists its queued batches to `thumbs_to_process.bin` on shutdown (and
whenever the queue changes), reloads them at startup, and deletes the
file after a successful load.
"""

from __future__ import annotations

import logging
import os
from dataclasses import dataclass, field

import msgpack

logger = logging.getLogger(__name__)

STATE_FILE = "thumbs_to_process.bin"


@dataclass
class Batch:
    """One dispatched thumbnail batch."""

    library_id: str | None  # None = ephemeral namespace
    entries: list[tuple[str, str, str]]  # (cas_id, path, extension)
    background: bool = False
    id: int = 0  # process-local rendezvous handle; not persisted
    # originating trace context (wire dict) — persisted, so a batch
    # resumed after a crash still reports into the trace that queued it
    trace: dict | None = None

    def to_wire(self) -> dict:
        return {
            "library_id": self.library_id,
            "entries": [list(e) for e in self.entries],
            "background": self.background,
            "trace": self.trace,
        }

    @classmethod
    def from_wire(cls, d: dict) -> "Batch":
        return cls(
            library_id=d.get("library_id"),
            entries=[tuple(e) for e in d.get("entries", [])],
            background=bool(d.get("background", False)),
            trace=d.get("trace") if isinstance(d.get("trace"), dict) else None,
        )


def save_state(data_dir: str | os.PathLike, batches: list[Batch]) -> None:
    path = os.path.join(os.fspath(data_dir), STATE_FILE)
    if not batches:
        try:
            os.remove(path)
        except OSError:
            pass
        return
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(msgpack.packb([b.to_wire() for b in batches]))
    os.replace(tmp, path)


def load_state(data_dir: str | os.PathLike) -> list[Batch]:
    """Load and DELETE the state file (ref:state.rs — removed after
    load so a crash mid-processing re-persists only the remainder)."""
    path = os.path.join(os.fspath(data_dir), STATE_FILE)
    try:
        with open(path, "rb") as f:
            raw = msgpack.unpackb(f.read())
        os.remove(path)
    except OSError:
        return []
    except Exception:
        logger.warning("corrupt %s; discarding", STATE_FILE)
        try:
            os.remove(path)
        except OSError:
            pass
        return []
    return [Batch.from_wire(d) for d in raw]
