"""EXIF / media metadata extraction.

Parity: ref:crates/media-metadata/src/image/mod.rs:27-47
(ImageMetadata{resolution, date_taken, location, camera_data, artist,
description, copyright, exif_version}) and orientation handling
(image/orientation.rs) — extracted with PIL instead of kamadak-exif.
"""

from __future__ import annotations

import datetime as _dt
import logging
import os
from dataclasses import asdict, dataclass, field
from typing import Any

import msgpack

logger = logging.getLogger(__name__)

# EXIF orientation values 1-8 (the TPU resize pipeline turns these into
# transpose/flip ops on the batch, ref:crates/media-metadata/src/image/
# orientation.rs)
ORIENTATION_NORMAL = 1


@dataclass
class MediaLocation:
    latitude: float
    longitude: float
    altitude: float | None = None
    direction: float | None = None

    def plus_code(self) -> str:
        """Open Location Code of this position (parity with the
        reference's pluscodes module, ref:crates/media-metadata/src/
        image/geographic/pluscodes.rs)."""
        return encode_plus_code(self.latitude, self.longitude)


@dataclass
class CameraData:
    device_make: str | None = None
    device_model: str | None = None
    focal_length: float | None = None
    shutter_speed: str | None = None
    iso: int | None = None
    aperture: float | None = None
    flash: bool | None = None
    lens_make: str | None = None
    lens_model: str | None = None
    orientation: int = ORIENTATION_NORMAL


@dataclass
class ImageMetadata:
    resolution: tuple[int, int] = (0, 0)
    date_taken: str | None = None
    epoch_time: int | None = None
    location: MediaLocation | None = None
    camera_data: CameraData = field(default_factory=CameraData)
    artist: str | None = None
    description: str | None = None
    copyright: str | None = None
    exif_version: str | None = None

    @classmethod
    def from_path(cls, path: str | os.PathLike) -> "ImageMetadata | None":
        try:
            from PIL import ExifTags, Image

            with Image.open(path) as im:
                meta = cls(resolution=(im.width, im.height))
                exif = im.getexif()
                if not exif:
                    return meta
                tags = {ExifTags.TAGS.get(k, k): v for k, v in exif.items()}
                ifd = {}
                try:
                    raw_ifd = exif.get_ifd(ExifTags.IFD.Exif)
                    ifd = {ExifTags.TAGS.get(k, k): v for k, v in raw_ifd.items()}
                except Exception:  # noqa: BLE001
                    pass

                dt = ifd.get("DateTimeOriginal") or tags.get("DateTime")
                if isinstance(dt, str):
                    meta.date_taken = dt
                    try:
                        parsed = _dt.datetime.strptime(dt, "%Y:%m:%d %H:%M:%S")
                        meta.epoch_time = int(parsed.timestamp())
                    except ValueError:
                        pass
                meta.artist = _s(tags.get("Artist"))
                meta.description = _s(tags.get("ImageDescription"))
                meta.copyright = _s(tags.get("Copyright"))
                ev = ifd.get("ExifVersion")
                if isinstance(ev, bytes):
                    meta.exif_version = ev.decode("ascii", "ignore")
                cam = meta.camera_data
                cam.device_make = _s(tags.get("Make"))
                cam.device_model = _s(tags.get("Model"))
                cam.orientation = int(tags.get("Orientation") or ORIENTATION_NORMAL)
                cam.lens_make = _s(ifd.get("LensMake"))
                cam.lens_model = _s(ifd.get("LensModel"))
                fl = ifd.get("FocalLength")
                cam.focal_length = float(fl) if fl is not None else None
                ap = ifd.get("FNumber")
                cam.aperture = float(ap) if ap is not None else None
                iso = ifd.get("ISOSpeedRatings")
                cam.iso = int(iso) if isinstance(iso, (int, float)) else None
                fl_ = ifd.get("Flash")
                cam.flash = bool(int(fl_) & 1) if isinstance(fl_, (int, float)) else None

                meta.location = _gps(exif)
                return meta
        except Exception as e:  # noqa: BLE001 - any decode failure = no metadata
            logger.debug("exif extraction failed for %s: %s", path, e)
            return None

    # --- persistence into media_data (ref:schema.prisma:281-310) ---

    def to_row(self, object_id: int) -> dict[str, Any]:
        return {
            "resolution": msgpack.packb(list(self.resolution)),
            "media_date": msgpack.packb(self.date_taken),
            "media_location": (
                msgpack.packb(asdict(self.location)) if self.location else None
            ),
            "camera_data": msgpack.packb(asdict(self.camera_data)),
            "artist": self.artist,
            "description": self.description,
            "copyright": self.copyright,
            "exif_version": self.exif_version,
            "epoch_time": self.epoch_time,
            "object_id": object_id,
        }


@dataclass
class VideoMetadata:
    """ref:crates/media-metadata/src/video.rs (the reference ships a
    stub; this extracts real stream facts via the cv2/ffmpeg decoder)."""

    resolution: tuple[int, int] = (0, 0)
    duration_seconds: float | None = None
    fps: float | None = None
    frame_count: int | None = None
    codec: str | None = None

    @classmethod
    def from_path(cls, path: str | os.PathLike) -> "VideoMetadata | None":
        # native FFmpeg probe first (real codec names + container
        # duration, ref:crates/ffmpeg); cv2 as fallback
        try:
            from ...native import video_meta

            meta = video_meta(os.fspath(path))
        except Exception:
            meta = None
        if meta is not None and meta["width"] and meta["height"]:
            return cls(
                resolution=(meta["width"], meta["height"]),
                duration_seconds=meta["duration_seconds"] or None,
                fps=meta["fps"] or None,
                frame_count=meta["frame_count"] or None,
                codec=meta["codec"] or None,
            )
        try:
            import cv2
        except Exception:
            return None
        cap = cv2.VideoCapture(os.fspath(path))
        try:
            if not cap.isOpened():
                return None
            w = int(cap.get(cv2.CAP_PROP_FRAME_WIDTH) or 0)
            h = int(cap.get(cv2.CAP_PROP_FRAME_HEIGHT) or 0)
            fps = float(cap.get(cv2.CAP_PROP_FPS) or 0) or None
            frames = int(cap.get(cv2.CAP_PROP_FRAME_COUNT) or 0) or None
            fourcc = int(cap.get(cv2.CAP_PROP_FOURCC) or 0)
            codec = (
                "".join(chr((fourcc >> (8 * i)) & 0xFF) for i in range(4)).strip()
                or None
                if fourcc
                else None
            )
            duration = (frames / fps) if frames and fps else None
            if not (w and h):
                return None
            return cls(
                resolution=(w, h),
                duration_seconds=duration,
                fps=fps,
                frame_count=frames,
                codec=codec,
            )
        finally:
            cap.release()

    def to_row(self, object_id: int) -> dict[str, Any]:
        """media_data row (resolution blob shared with images; the
        video facts ride the camera_data blob slot as a typed dict)."""
        return {
            "resolution": msgpack.packb(list(self.resolution)),
            "camera_data": msgpack.packb(
                {
                    "video": True,
                    "duration_seconds": self.duration_seconds,
                    "fps": self.fps,
                    "frame_count": self.frame_count,
                    "codec": self.codec,
                }
            ),
            "object_id": object_id,
        }


def _s(v: Any) -> str | None:
    return str(v).strip("\x00 ").strip() if v is not None else None


def _gps(exif) -> MediaLocation | None:
    try:
        from PIL import ExifTags

        gps_raw = exif.get_ifd(ExifTags.IFD.GPSInfo)
        if not gps_raw:
            return None
        gps = {ExifTags.GPSTAGS.get(k, k): v for k, v in gps_raw.items()}
        lat = _dms(gps.get("GPSLatitude"), gps.get("GPSLatitudeRef", "N"))
        lon = _dms(gps.get("GPSLongitude"), gps.get("GPSLongitudeRef", "E"))
        if lat is None or lon is None:
            return None
        alt = gps.get("GPSAltitude")
        return MediaLocation(
            latitude=lat, longitude=lon,
            altitude=float(alt) if alt is not None else None,
        )
    except Exception:  # noqa: BLE001
        return None


def _dms(value, ref: str) -> float | None:
    if not value or len(value) != 3:
        return None
    deg = float(value[0]) + float(value[1]) / 60 + float(value[2]) / 3600
    if ref in ("S", "W"):
        deg = -deg
    return deg


# --- Open Location Code (plus codes), parity with
# ref:crates/media-metadata/src/image/geographic/pluscodes.rs ---

_OLC_ALPHABET = "23456789CFGHJMPQRVWX"


def encode_plus_code(lat: float, lon: float, code_length: int = 10) -> str:
    lat = min(90.0, max(-90.0, lat)) + 90.0
    lon = ((lon + 180.0) % 360.0)
    code = ""
    lat_res, lon_res = 400.0, 400.0
    for i in range(code_length // 2):
        lat_res /= 20.0
        lon_res /= 20.0
        code += _OLC_ALPHABET[min(19, int(lat / lat_res))]
        lat -= int(lat / lat_res) * lat_res
        code += _OLC_ALPHABET[min(19, int(lon / lon_res))]
        lon -= int(lon / lon_res) * lon_res
        if i == 3:
            code += "+"
    if "+" not in code:
        code += "+"
    return code
