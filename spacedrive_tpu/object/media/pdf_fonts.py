"""Embedded PDF font programs → cairo glyphs, via freetype (ctypes).

The reference gets embedded-font text for free from PDFium
(ref:crates/images/src/pdf.rs:82-83); our from-scratch rasterizer
previously substituted cairo toy faces, which mangles any PDF whose
fonts are subset-embedded (most real documents). This module loads the
embedded program (FontFile = Type1, FontFile2 = TrueType, FontFile3 =
CFF/Type1C — freetype parses all three) straight from memory and
renders through `cairo_show_glyphs` with REAL glyph indices, so
subset custom encodings draw the right outlines.

Char-code → glyph-index resolution, in order:
- simple fonts: code → unicode via the base encoding (latin-1 is the
  shared ASCII core of Standard/WinAnsi) patched by /Differences
  (glyph names resolved through a full-ASCII name table), then the
  face cmap; symbol-font fallback probes 0xF000+code (the MS symbol
  convention freetype exposes);
- Type0/CIDFontType2 (Identity-H): 2-byte codes are CIDs mapped
  through /CIDToGIDMap (Identity or the stream form).

Advances prefer the PDF's own /Widths//W arrays (authoritative for
subsets) and fall back to cairo's glyph extents. Every failure path
degrades to the toy-font rendering, never to an exception.
"""

from __future__ import annotations

import ctypes
import ctypes.util
import itertools
import logging
from typing import Any

logger = logging.getLogger(__name__)

FT_LOAD_DEFAULT = 0


class CairoGlyph(ctypes.Structure):
    _fields_ = [("index", ctypes.c_ulong),
                ("x", ctypes.c_double), ("y", ctypes.c_double)]


_ft_lib: list[Any] = []  # [handle, FT_Library] or [None]


def _ft():
    if _ft_lib:
        return _ft_lib[0]
    try:
        ft = ctypes.CDLL(ctypes.util.find_library("freetype")
                         or "libfreetype.so.6")
        ft.FT_Init_FreeType.argtypes = [ctypes.POINTER(ctypes.c_void_p)]
        ft.FT_Init_FreeType.restype = ctypes.c_int
        ft.FT_New_Memory_Face.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_long, ctypes.c_long,
            ctypes.POINTER(ctypes.c_void_p)]
        ft.FT_New_Memory_Face.restype = ctypes.c_int
        ft.FT_Get_Char_Index.argtypes = [ctypes.c_void_p, ctypes.c_ulong]
        ft.FT_Get_Char_Index.restype = ctypes.c_uint
        ft.FT_Done_Face.argtypes = [ctypes.c_void_p]
        ft.FT_Done_Face.restype = ctypes.c_int
        lib = ctypes.c_void_p()
        if ft.FT_Init_FreeType(ctypes.byref(lib)) != 0:
            raise OSError("FT_Init_FreeType failed")
        _ft_lib.extend([ft, lib])
    except OSError as exc:
        logger.info("freetype unavailable for embedded PDF fonts: %s", exc)
        _ft_lib.append(None)
    return _ft_lib[0]


_cairo_ft_bound: list[bool] = []


def _cairo_ft():
    """The cairo handle with the FT + glyph entry points bound (they
    live in libcairo itself; bound lazily once)."""
    from .pdf_raster import _TextExtents, _cairo

    c = _cairo()
    if c is None:
        return None
    if not _cairo_ft_bound:
        V, I = ctypes.c_void_p, ctypes.c_int
        c.cairo_ft_font_face_create_for_ft_face.restype = V
        c.cairo_ft_font_face_create_for_ft_face.argtypes = [V, I]
        c.cairo_font_face_destroy.restype = None
        c.cairo_font_face_destroy.argtypes = [V]
        c.cairo_set_font_face.restype = None
        c.cairo_set_font_face.argtypes = [V, V]
        c.cairo_show_glyphs.restype = None
        c.cairo_show_glyphs.argtypes = [V, ctypes.POINTER(CairoGlyph), I]
        c.cairo_glyph_extents.restype = None
        c.cairo_glyph_extents.argtypes = [
            V, ctypes.POINTER(CairoGlyph), I, ctypes.POINTER(_TextExtents)]
        c.cairo_font_face_set_user_data.restype = I
        c.cairo_font_face_set_user_data.argtypes = [V, V, V, V]
        _cairo_ft_bound.append(True)
    return c


# --- FT face lifetime --------------------------------------------------------
#
# cairo's scaled-font holdover cache may keep the font face (and through it
# the FT_Face) alive past cairo_font_face_destroy; cairo's contract for
# cairo_ft_font_face_create_for_ft_face requires the FT_Face to outlive every
# cairo reference. So the FT_Face (and the memory buffer it parses lazily) is
# freed from a cairo user-data destroy hook — invoked only when the LAST
# cairo reference drops — never directly.

class _CairoUserDataKey(ctypes.Structure):
    _fields_ = [("unused", ctypes.c_int)]


_FT_KEY = _CairoUserDataKey()
_DESTROY_T = ctypes.CFUNCTYPE(None, ctypes.c_void_p)
_live_ft_faces: dict[int, tuple] = {}  # token -> (buf, FT_Face)
# PDF decodes run concurrently on worker threads: next() on a count is
# GIL-atomic, so parallel loads can never share a token (a shared token
# would let one face's destroy hook free the OTHER face)
_token_counter = itertools.count(1)


@_DESTROY_T
def _ft_face_destroy_hook(data):
    buf, face = _live_ft_faces.pop(int(data or 0), (None, None))
    ft = _ft_lib[0] if _ft_lib else None
    if ft is not None and face:
        ft.FT_Done_Face(face)


def _bind_ft_lifetime(c, cairo_face, face, buf) -> None:
    """Tie (buf, face) to the cairo face's last-reference drop. On the
    (OOM-only) registration failure the pair stays in the registry
    forever — a bounded leak, never a dangling FT_Face."""
    token = next(_token_counter)
    _live_ft_faces[token] = (buf, face)
    status = c.cairo_font_face_set_user_data(
        cairo_face, ctypes.byref(_FT_KEY), ctypes.c_void_p(token),
        _ft_face_destroy_hook)
    if status != 0:  # CAIRO_STATUS_NO_MEMORY: hook not registered
        logger.warning("cairo_font_face_set_user_data failed (%d); "
                       "leaking FT face rather than risking a UAF", status)


# --- glyph names (full ASCII coverage; AGL's latin core) -------------------

_NAME_TO_UNICODE = {
    "space": 0x20, "exclam": 0x21, "quotedbl": 0x22, "numbersign": 0x23,
    "dollar": 0x24, "percent": 0x25, "ampersand": 0x26, "quotesingle": 0x27,
    "parenleft": 0x28, "parenright": 0x29, "asterisk": 0x2A, "plus": 0x2B,
    "comma": 0x2C, "hyphen": 0x2D, "period": 0x2E, "slash": 0x2F,
    "zero": 0x30, "one": 0x31, "two": 0x32, "three": 0x33, "four": 0x34,
    "five": 0x35, "six": 0x36, "seven": 0x37, "eight": 0x38, "nine": 0x39,
    "colon": 0x3A, "semicolon": 0x3B, "less": 0x3C, "equal": 0x3D,
    "greater": 0x3E, "question": 0x3F, "at": 0x40,
    "bracketleft": 0x5B, "backslash": 0x5C, "bracketright": 0x5D,
    "asciicircum": 0x5E, "underscore": 0x5F, "grave": 0x60,
    "braceleft": 0x7B, "bar": 0x7C, "braceright": 0x7D, "asciitilde": 0x7E,
}


def _glyph_name_to_unicode(name: str) -> int | None:
    if len(name) == 1:
        return ord(name)
    if name in _NAME_TO_UNICODE:
        return _NAME_TO_UNICODE[name]
    if name.startswith("uni") and len(name) == 7:
        try:
            return int(name[3:], 16)
        except ValueError:
            return None
    return None


class EmbeddedFont:
    """A loaded embedded font: freetype face + cairo font face + the
    char-code mapping and width table needed to lay out a show op."""

    def __init__(self, cairo_face: Any, code_to_gid, two_byte: bool,
                 widths: dict[int, float], default_width: float):
        self.cairo_face = cairo_face
        self._code_to_gid = code_to_gid  # callable code → gid
        self.two_byte = two_byte
        self.widths = widths             # code → advance /1000 units
        self.default_width = default_width
        self._released = False

    def release(self) -> None:
        """Drop OUR reference to the cairo face. The FT_Face and its
        backing buffer are freed by the user-data destroy hook when
        cairo drops its LAST reference — which may be later than this
        call if the scaled-font holdover cache still holds the face."""
        if self._released:
            return
        self._released = True
        c = _cairo_ft()
        if c is not None and self.cairo_face:
            c.cairo_font_face_destroy(self.cairo_face)
        self.cairo_face = None

    def codes(self, raw: bytes):
        if self.two_byte:
            return [(raw[i] << 8) | raw[i + 1]
                    for i in range(0, len(raw) - 1, 2)]
        return list(raw)

    def gid(self, code: int) -> int:
        return self._code_to_gid(code)

    def width(self, code: int) -> float:
        """Advance in text-space /1000 units, or the font default."""
        return self.widths.get(code, self.default_width)


def _load_face(data: bytes):
    ft = _ft()
    if ft is None:
        return None, None
    face = ctypes.c_void_p()
    buf = ctypes.create_string_buffer(data, len(data))
    if ft.FT_New_Memory_Face(_ft_lib[1], buf, len(data), 0,
                             ctypes.byref(face)) != 0:
        return None, None
    return face, buf


def _font_program(doc: Any, descriptor: dict) -> bytes | None:
    from .pdf import Stream, _apply_filters

    for key in ("FontFile2", "FontFile3", "FontFile"):
        obj = doc.resolve(descriptor.get(key))
        if isinstance(obj, Stream):
            try:
                data = _apply_filters(doc, obj.dict, obj.raw)
                if isinstance(data, bytes) and data:
                    return data
            except Exception:
                continue
    return None


def _simple_encoding_map(doc: Any, fdict: dict) -> dict[int, int]:
    """code → unicode for a simple font: latin-1 core patched by any
    /Encoding /Differences."""
    mapping = {code: code for code in range(32, 256)}
    enc = doc.resolve(fdict.get("Encoding"))
    if isinstance(enc, dict):
        diffs = doc.resolve(enc.get("Differences"))
        if isinstance(diffs, list):
            code = 0
            for item in diffs:
                item = doc.resolve(item)
                if isinstance(item, (int, float)):
                    code = int(item)
                else:
                    uni = _glyph_name_to_unicode(str(item))
                    if uni is not None:
                        mapping[code] = uni
                    code += 1
    return mapping


def _simple_widths(doc: Any, fdict: dict) -> tuple[dict[int, float], float]:
    widths: dict[int, float] = {}
    try:
        first = int(doc.resolve(fdict.get("FirstChar", 0)))
        arr = doc.resolve(fdict.get("Widths"))
        if isinstance(arr, list):
            for i, w in enumerate(arr):
                w = doc.resolve(w)
                if isinstance(w, (int, float)):
                    widths[first + i] = float(w)
    except Exception:
        pass
    return widths, 500.0


def _cid_widths(doc: Any, d0: dict) -> tuple[dict[int, float], float]:
    """CIDFont /W array: [c [w1 w2 …] | c1 c2 w]*; /DW default."""
    widths: dict[int, float] = {}
    default = 1000.0
    try:
        dw = doc.resolve(d0.get("DW"))
        if isinstance(dw, (int, float)):
            default = float(dw)
        arr = doc.resolve(d0.get("W"))
        if isinstance(arr, list):
            i = 0
            while i < len(arr):
                c1 = doc.resolve(arr[i])
                nxt = doc.resolve(arr[i + 1]) if i + 1 < len(arr) else None
                if isinstance(nxt, list):
                    for j, w in enumerate(nxt):
                        w = doc.resolve(w)
                        if isinstance(w, (int, float)):
                            widths[int(c1) + j] = float(w)
                    i += 2
                elif i + 2 < len(arr):
                    w = doc.resolve(arr[i + 2])
                    # 2-byte codes cap CIDs at 0xFFFF; clamp so a
                    # hostile /W [0 4294967295 w] can't spin/OOM
                    lo = max(0, int(c1))
                    hi = min(int(nxt), 0xFFFF)
                    for code in range(lo, hi + 1):
                        widths[code] = float(w)
                    i += 3
                else:
                    break
    except Exception:
        pass
    return widths, default


def load_embedded_font(doc: Any, fdict: dict) -> EmbeddedFont | None:
    """Build an EmbeddedFont from a resolved PDF font dict, or None
    when there is no usable embedded program (caller keeps toy faces)."""
    c = _cairo_ft()
    ft = _ft()
    if c is None or ft is None:
        return None
    try:
        subtype = str(doc.resolve(fdict.get("Subtype", "")))
        if subtype == "Type0":
            desc = doc.resolve(fdict.get("DescendantFonts"))
            if not isinstance(desc, list) or not desc:
                return None
            d0 = doc.resolve(desc[0])
            if not isinstance(d0, dict):
                return None
            descriptor = doc.resolve(d0.get("FontDescriptor"))
            if not isinstance(descriptor, dict):
                return None
            data = _font_program(doc, descriptor)
            if data is None:
                return None
            face, buf = _load_face(data)
            if face is None:
                return None
            cid2gid = doc.resolve(d0.get("CIDToGIDMap", "Identity"))
            gid_table: bytes | None = None
            from .pdf import Stream, _apply_filters

            if isinstance(cid2gid, Stream):
                try:
                    table = _apply_filters(doc, cid2gid.dict, cid2gid.raw)
                    gid_table = table if isinstance(table, bytes) else None
                except Exception:
                    gid_table = None

            def code_to_gid(code: int, _t=gid_table) -> int:
                if _t is not None:
                    off = code * 2
                    if off + 1 < len(_t):
                        return (_t[off] << 8) | _t[off + 1]
                    return 0
                return code  # Identity: CID == GID

            widths, default = _cid_widths(doc, d0)
            cairo_face = c.cairo_ft_font_face_create_for_ft_face(
                face, FT_LOAD_DEFAULT)
            _bind_ft_lifetime(c, cairo_face, face, buf)
            return EmbeddedFont(cairo_face, code_to_gid, True, widths,
                                default)

        descriptor = doc.resolve(fdict.get("FontDescriptor"))
        if not isinstance(descriptor, dict):
            return None
        data = _font_program(doc, descriptor)
        if data is None:
            return None
        face, buf = _load_face(data)
        if face is None:
            return None
        enc_map = _simple_encoding_map(doc, fdict)
        gid_cache: dict[int, int] = {}

        def code_to_gid(code: int) -> int:
            gid = gid_cache.get(code)
            if gid is None:
                uni = enc_map.get(code, code)
                gid = ft.FT_Get_Char_Index(face, uni)
                if gid == 0:
                    # MS symbol-font convention (freetype maps the
                    # (3,0) cmap into 0xF000..0xF0FF)
                    gid = ft.FT_Get_Char_Index(face, 0xF000 + code)
                gid_cache[code] = gid
            return gid

        widths, default = _simple_widths(doc, fdict)
        cairo_face = c.cairo_ft_font_face_create_for_ft_face(
            face, FT_LOAD_DEFAULT)
        _bind_ft_lifetime(c, cairo_face, face, buf)
        return EmbeddedFont(cairo_face, code_to_gid, False, widths,
                            default)
    except Exception as exc:  # noqa: BLE001 - hostile input; toy fallback
        logger.debug("embedded font load failed: %s", exc)
        return None
