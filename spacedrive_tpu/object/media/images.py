"""Image decode/convert dispatch by extension.

Parity: ref:crates/images/src/handler.rs:18-60 — `format_image` routes
by extension to Generic (the `image` crate → here PIL), HEIF
(libheif-rs/libheif-sys → here a ctypes binding over the system
libheif, the same C library), SVG (resvg) and PDF (pdfium) handlers;
max-size guards ref:crates/images/src/consts.rs:9,33,39. SVG/PDF
raise `UnsupportedImage` when no rasterizer is present in the image —
the dispatch stays, the handler is gated (the reference gates the same
way via cargo features).
"""

from __future__ import annotations

import ctypes
import ctypes.util
import os
from typing import Optional

import numpy as np

MAXIMUM_FILE_SIZE = 192 * 1024 * 1024  # ref:consts.rs:9
SVG_RENDER_SIZE = 512  # ref:consts.rs:33 (SVG render cap 512²)
PDF_RENDER_WIDTH = 1024  # ref:consts.rs:39

HEIF_EXTENSIONS = {"heif", "heifs", "heic", "heics", "avif", "avci", "avcs"}
SVG_EXTENSIONS = {"svg", "svgz"}
PDF_EXTENSIONS = {"pdf"}


class ImageHandlerError(Exception):
    pass


class UnsupportedImage(ImageHandlerError):
    pass


# --- libheif ctypes binding (ref:crates/images HEIF handler) -------------


class _HeifError(ctypes.Structure):
    _fields_ = [
        ("code", ctypes.c_int),
        ("subcode", ctypes.c_int),
        ("message", ctypes.c_char_p),
    ]


_HEIF_COLORSPACE_RGB = 1
_HEIF_CHROMA_INTERLEAVED_RGBA = 11
_HEIF_CHANNEL_INTERLEAVED = 10

_heif: ctypes.CDLL | None = None


def _load_heif() -> ctypes.CDLL | None:
    global _heif
    if _heif is not None:
        return _heif
    name = ctypes.util.find_library("heif") or "libheif.so.1"
    try:
        lib = ctypes.CDLL(name)
    except OSError:
        return None
    lib.heif_context_alloc.restype = ctypes.c_void_p
    lib.heif_context_read_from_file.restype = _HeifError
    lib.heif_context_read_from_file.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_void_p,
    ]
    lib.heif_context_get_primary_image_handle.restype = _HeifError
    lib.heif_context_get_primary_image_handle.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_void_p),
    ]
    lib.heif_decode_image.restype = _HeifError
    lib.heif_decode_image.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_void_p),
        ctypes.c_int, ctypes.c_int, ctypes.c_void_p,
    ]
    lib.heif_image_handle_get_width.restype = ctypes.c_int
    lib.heif_image_handle_get_width.argtypes = [ctypes.c_void_p]
    lib.heif_image_handle_get_height.restype = ctypes.c_int
    lib.heif_image_handle_get_height.argtypes = [ctypes.c_void_p]
    lib.heif_image_get_plane_readonly.restype = ctypes.POINTER(ctypes.c_uint8)
    lib.heif_image_get_plane_readonly.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.POINTER(ctypes.c_int),
    ]
    lib.heif_image_release.argtypes = [ctypes.c_void_p]
    lib.heif_image_handle_release.argtypes = [ctypes.c_void_p]
    lib.heif_context_free.argtypes = [ctypes.c_void_p]
    _heif = lib
    return lib


def heif_available() -> bool:
    return _load_heif() is not None


def decode_heif(path: str) -> np.ndarray:
    """HEIC/HEIF/AVIF → RGBA uint8 via the system libheif (the same C
    library the reference links, ref:crates/images/Cargo.toml:13,32)."""
    lib = _load_heif()
    if lib is None:
        raise UnsupportedImage("libheif not available")

    def check(err: _HeifError, stage: str) -> None:
        if err.code != 0:
            msg = err.message.decode() if err.message else "?"
            raise ImageHandlerError(f"libheif {stage}: {msg} (code {err.code})")

    ctx = lib.heif_context_alloc()
    if not ctx:
        raise ImageHandlerError("heif_context_alloc failed")
    handle = ctypes.c_void_p()
    img = ctypes.c_void_p()
    try:
        check(
            lib.heif_context_read_from_file(ctx, os.fsencode(path), None), "read"
        )
        check(
            lib.heif_context_get_primary_image_handle(
                ctx, ctypes.byref(handle)
            ),
            "primary handle",
        )
        check(
            lib.heif_decode_image(
                handle,
                ctypes.byref(img),
                _HEIF_COLORSPACE_RGB,
                _HEIF_CHROMA_INTERLEAVED_RGBA,
                None,
            ),
            "decode",
        )
        width = lib.heif_image_handle_get_width(handle)
        height = lib.heif_image_handle_get_height(handle)
        stride = ctypes.c_int()
        plane = lib.heif_image_get_plane_readonly(
            img, _HEIF_CHANNEL_INTERLEAVED, ctypes.byref(stride)
        )
        if not plane:
            raise ImageHandlerError("heif: no interleaved plane")
        buf = np.ctypeslib.as_array(plane, shape=(height, stride.value))
        return buf[:, : width * 4].reshape(height, width, 4).copy()
    finally:
        if img:
            lib.heif_image_release(img)
        if handle:
            lib.heif_image_handle_release(handle)
        lib.heif_context_free(ctx)


# --- generic + dispatch ---------------------------------------------------


def decode_generic(path: str) -> np.ndarray:
    from PIL import Image

    with Image.open(path) as im:
        return np.asarray(im.convert("RGBA"))


def decode_svg(path: str) -> np.ndarray:
    """SVG/SVGZ via librsvg (ref:handler.rs SVG → resvg). Gzip payloads
    are expanded under the same size cap as the on-disk file."""
    from . import svg as svg_mod

    if not svg_mod.svg_available():
        raise UnsupportedImage(
            "no SVG rasterizer (librsvg unavailable; reference: resvg)"
        )
    with open(path, "rb") as f:
        data = f.read(MAXIMUM_FILE_SIZE + 1)
    if len(data) > MAXIMUM_FILE_SIZE:
        raise ImageHandlerError(f"file over {MAXIMUM_FILE_SIZE} bytes")
    if data[:2] == b"\x1f\x8b":  # svgz
        import gzip
        import io as _io

        try:
            with gzip.GzipFile(fileobj=_io.BytesIO(data)) as gz:
                data = gz.read(MAXIMUM_FILE_SIZE + 1)
        except Exception as exc:
            raise ImageHandlerError(f"svgz decompress failed: {exc}") from exc
        if len(data) > MAXIMUM_FILE_SIZE:
            raise ImageHandlerError("svgz expands past the size cap")
    try:
        return svg_mod.render_svg(data)
    except ImageHandlerError:
        raise
    except Exception as exc:
        raise ImageHandlerError(f"svg render failed: {exc}") from exc


def decode_pdf(path: str) -> np.ndarray:
    """PDF first page (ref:handler.rs PDF → pdfium) via ../pdf.py."""
    from . import pdf as pdf_mod

    try:
        return pdf_mod.render_pdf(path)
    except ImageHandlerError:
        raise
    except Exception as exc:
        raise ImageHandlerError(f"pdf render failed: {exc}") from exc


def format_image(path: str, extension: str | None = None) -> np.ndarray:
    """Decode any supported still image/document to RGBA uint8
    (ref:handler.rs:18-60 `format_image` — the single dispatch)."""
    if os.path.getsize(path) > MAXIMUM_FILE_SIZE:
        raise ImageHandlerError(f"file over {MAXIMUM_FILE_SIZE} bytes")
    ext = (extension or os.path.splitext(path)[1].lstrip(".")).lower()
    if ext in HEIF_EXTENSIONS:
        return decode_heif(path)
    if ext in SVG_EXTENSIONS:
        return decode_svg(path)
    if ext in PDF_EXTENSIONS:
        return decode_pdf(path)
    return decode_generic(path)
