"""SVG rasterization via ctypes over librsvg + cairo.

Role parity with the reference's resvg handler
(ref:crates/images/src/svg.rs:14-21: render capped at 512², then into
the normal thumbnail pipeline). Same shape here: librsvg (the system C
library GNOME ships) renders the document into a cairo ARGB32 surface
capped at `MAX_RENDER_DIM`², which is returned as an RGBA numpy array
for the batched device resize.
"""

from __future__ import annotations

import ctypes
import ctypes.util
import logging
from functools import lru_cache

import numpy as np

logger = logging.getLogger(__name__)

MAX_RENDER_DIM = 512  # ref:crates/images/src/consts.rs:33 (SVG cap)

_CAIRO_FORMAT_ARGB32 = 0


class _RsvgRectangle(ctypes.Structure):
    _fields_ = [
        ("x", ctypes.c_double),
        ("y", ctypes.c_double),
        ("width", ctypes.c_double),
        ("height", ctypes.c_double),
    ]


class _RsvgDimensionData(ctypes.Structure):
    _fields_ = [
        ("width", ctypes.c_int),
        ("height", ctypes.c_int),
        ("em", ctypes.c_double),
        ("ex", ctypes.c_double),
    ]


@lru_cache(maxsize=1)
def _libs():
    """(rsvg, cairo, gobject) ctypes handles, or None if unavailable."""
    try:
        rsvg = ctypes.CDLL(
            ctypes.util.find_library("rsvg-2") or "librsvg-2.so.2"
        )
        cairo = ctypes.CDLL(
            ctypes.util.find_library("cairo") or "libcairo.so.2"
        )
        gobject = ctypes.CDLL(
            ctypes.util.find_library("gobject-2.0") or "libgobject-2.0.so.0"
        )
        return _bind(rsvg, cairo, gobject)
    except (OSError, AttributeError) as exc:
        # AttributeError = librsvg too old for render_document (< 2.46)
        logger.info("librsvg/cairo unavailable: %s", exc)
        return None


def _bind(rsvg, cairo, gobject):
    rsvg.rsvg_handle_new_from_data.restype = ctypes.c_void_p
    rsvg.rsvg_handle_new_from_data.argtypes = [
        ctypes.c_char_p, ctypes.c_size_t, ctypes.c_void_p,
    ]
    rsvg.rsvg_handle_get_dimensions.restype = None
    rsvg.rsvg_handle_get_dimensions.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(_RsvgDimensionData),
    ]
    try:
        rsvg.rsvg_handle_get_intrinsic_size_in_pixels.restype = ctypes.c_int
        rsvg.rsvg_handle_get_intrinsic_size_in_pixels.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_double),
            ctypes.POINTER(ctypes.c_double),
        ]
    except AttributeError:
        pass
    rsvg.rsvg_handle_render_document.restype = ctypes.c_int
    rsvg.rsvg_handle_render_document.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p,
        ctypes.POINTER(_RsvgRectangle), ctypes.c_void_p,
    ]

    cairo.cairo_image_surface_create.restype = ctypes.c_void_p
    cairo.cairo_image_surface_create.argtypes = [ctypes.c_int] * 3
    cairo.cairo_create.restype = ctypes.c_void_p
    cairo.cairo_create.argtypes = [ctypes.c_void_p]
    cairo.cairo_image_surface_get_data.restype = ctypes.POINTER(ctypes.c_ubyte)
    cairo.cairo_image_surface_get_data.argtypes = [ctypes.c_void_p]
    cairo.cairo_image_surface_get_stride.restype = ctypes.c_int
    cairo.cairo_image_surface_get_stride.argtypes = [ctypes.c_void_p]
    cairo.cairo_surface_flush.argtypes = [ctypes.c_void_p]
    cairo.cairo_destroy.argtypes = [ctypes.c_void_p]
    cairo.cairo_surface_destroy.argtypes = [ctypes.c_void_p]
    cairo.cairo_status.restype = ctypes.c_int
    cairo.cairo_status.argtypes = [ctypes.c_void_p]

    gobject.g_object_unref.argtypes = [ctypes.c_void_p]
    return rsvg, cairo, gobject


def svg_available() -> bool:
    return _libs() is not None


def _intrinsic_size(rsvg, handle) -> tuple[float, float]:
    if hasattr(rsvg, "rsvg_handle_get_intrinsic_size_in_pixels"):
        w = ctypes.c_double()
        h = ctypes.c_double()
        if rsvg.rsvg_handle_get_intrinsic_size_in_pixels(
            handle, ctypes.byref(w), ctypes.byref(h)
        ) and w.value > 0 and h.value > 0:
            return w.value, h.value
    dims = _RsvgDimensionData()
    rsvg.rsvg_handle_get_dimensions(handle, ctypes.byref(dims))
    if dims.width > 0 and dims.height > 0:
        return float(dims.width), float(dims.height)
    return float(MAX_RENDER_DIM), float(MAX_RENDER_DIM)


def render_svg(path_or_bytes: str | bytes,
               max_dim: int = MAX_RENDER_DIM) -> np.ndarray:
    """Render an SVG document → RGBA uint8 [H, W, 4], longest side
    scaled to `max_dim` (aspect preserved)."""
    libs = _libs()
    if libs is None:
        raise RuntimeError("librsvg/cairo not available")
    rsvg, cairo, gobject = libs
    if isinstance(path_or_bytes, bytes):
        data = path_or_bytes
    else:
        with open(path_or_bytes, "rb") as f:
            data = f.read()
    handle = rsvg.rsvg_handle_new_from_data(data, len(data), None)
    if not handle:
        raise ValueError("invalid SVG document")
    surface = cr = None
    try:
        iw, ih = _intrinsic_size(rsvg, handle)
        scale = max_dim / max(iw, ih)
        w = max(1, int(round(iw * scale)))
        h = max(1, int(round(ih * scale)))
        surface = cairo.cairo_image_surface_create(_CAIRO_FORMAT_ARGB32, w, h)
        cr = cairo.cairo_create(surface)
        if cairo.cairo_status(cr) != 0:
            raise RuntimeError("cairo context creation failed")
        viewport = _RsvgRectangle(0.0, 0.0, float(w), float(h))
        ok = rsvg.rsvg_handle_render_document(
            handle, cr, ctypes.byref(viewport), None
        )
        if not ok:
            raise ValueError("SVG render failed")
        cairo.cairo_surface_flush(surface)
        stride = cairo.cairo_image_surface_get_stride(surface)
        buf = cairo.cairo_image_surface_get_data(surface)
        raw = np.ctypeslib.as_array(buf, shape=(h, stride))
        px = raw[:, : w * 4].reshape(h, w, 4).copy()
    finally:
        if cr:
            cairo.cairo_destroy(cr)
        if surface:
            cairo.cairo_surface_destroy(surface)
        gobject.g_object_unref(handle)
    # cairo ARGB32 is premultiplied, native-endian (BGRA on LE)
    b, g, r, a = px[..., 0], px[..., 1], px[..., 2], px[..., 3]
    rgba = np.stack([r, g, b, a], axis=-1).astype(np.uint16)
    alpha = np.maximum(rgba[..., 3:4], 1)
    rgba[..., :3] = np.minimum(255, rgba[..., :3] * 255 // alpha)
    out = rgba.astype(np.uint8)
    out[..., 3] = px[..., 3]
    return out
