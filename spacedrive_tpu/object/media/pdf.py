"""PDF first-page thumbnails — a bounded, dependency-free PDF reader.

Role parity with the reference's PDFium handler
(ref:crates/images/src/pdf.rs:82-83: render page 1 into a bitmap).
This host has no PDFium/poppler C API, so the frontend is a real (if
bounded) PDF reader implemented here:

strategy 1: the page's embedded `/Thumb` image (PDF's own thumbnail);
strategy 2: the largest image XObject on page 1 (covers scanned
            documents, slides, photo PDFs — the cases where a page
            render is dominated by one raster anyway);
strategy 3: typeset the page's extracted text onto a white canvas with
            the true MediaBox aspect (degraded but honest for
            text-only documents: real content, default font).

Supported plumbing: classic + stream xrefs (PNG predictors), object
streams, Flate/DCT/ASCIIHex/ASCII85/RunLength filters, page-tree
inheritance. Encrypted files raise `PdfUnsupported`.
"""

from __future__ import annotations

import io
import logging
import re
import zlib
from dataclasses import dataclass
from typing import Any

import numpy as np

logger = logging.getLogger(__name__)

MAX_RENDER_DIM = 512  # match the SVG cap; thumbnails are ≤512² anyway
MAX_INFLATE = 64 * 1024 * 1024  # hard cap per decoded stream (deflate-bomb guard)


class PdfError(Exception):
    pass


class PdfUnsupported(PdfError):
    pass


class Name(str):
    """A PDF name object (distinct from string literals)."""


@dataclass(frozen=True)
class Ref:
    num: int
    gen: int


_WHITESPACE = b"\x00\t\n\x0c\r "
_DELIMS = b"()<>[]{}/%"


class _Lexer:
    def __init__(self, data: bytes, pos: int = 0):
        self.data = data
        self.pos = pos

    def skip_ws(self) -> None:
        d = self.data
        n = len(d)
        while self.pos < n:
            c = d[self.pos]
            if c in _WHITESPACE:
                self.pos += 1
            elif c == 0x25:  # % comment
                while self.pos < n and d[self.pos] not in b"\r\n":
                    self.pos += 1
            else:
                return

    def peek(self) -> int:
        return self.data[self.pos] if self.pos < len(self.data) else -1

    def token(self) -> bytes:
        """Read a bare token (keyword/number)."""
        self.skip_ws()
        start = self.pos
        d = self.data
        n = len(d)
        while self.pos < n and d[self.pos] not in _WHITESPACE and \
                d[self.pos] not in _DELIMS:
            self.pos += 1
        return d[start:self.pos]

    # --- object parsing ---------------------------------------------------

    def parse(self) -> Any:
        self.skip_ws()
        c = self.peek()
        if c == -1:
            raise PdfError("unexpected EOF")
        d = self.data
        if c == 0x2F:  # /Name
            self.pos += 1
            return Name(self._name_chars())
        if c == 0x28:  # (string)
            return self._literal_string()
        if c == 0x3C:  # < or <<
            if d[self.pos:self.pos + 2] == b"<<":
                return self._dict_or_stream()
            return self._hex_string()
        if c == 0x5B:  # [
            self.pos += 1
            arr = []
            while True:
                self.skip_ws()
                if self.peek() == 0x5D:
                    self.pos += 1
                    return arr
                arr.append(self.parse())
        if c == 0x5D:
            raise PdfError("unbalanced ]")
        tok = self.token()
        if tok in (b"true", b"false"):
            return tok == b"true"
        if tok == b"null":
            return None
        # number, possibly an "n g R" reference
        try:
            if b"." in tok:
                return float(tok)
            value = int(tok)
        except ValueError:
            raise PdfError(f"bad token {tok!r} at {self.pos}")
        save = self.pos
        self.skip_ws()
        tok2 = self.token()
        if tok2.isdigit():
            self.skip_ws()
            if self.token() == b"R":
                return Ref(value, int(tok2))
        self.pos = save
        return value

    def _name_chars(self) -> str:
        out = bytearray()
        d = self.data
        n = len(d)
        while self.pos < n:
            c = d[self.pos]
            if c in _WHITESPACE or c in _DELIMS:
                break
            if c == 0x23 and self.pos + 2 < n:  # #xx escape
                try:
                    out.append(int(d[self.pos + 1:self.pos + 3], 16))
                    self.pos += 3
                    continue
                except ValueError:
                    pass
            out.append(c)
            self.pos += 1
        return out.decode("latin-1")

    def _literal_string(self) -> bytes:
        d = self.data
        self.pos += 1  # (
        depth = 1
        out = bytearray()
        n = len(d)
        while self.pos < n:
            c = d[self.pos]
            self.pos += 1
            if c == 0x5C:  # backslash
                if self.pos >= n:
                    break
                e = d[self.pos]
                self.pos += 1
                mapping = {0x6E: 10, 0x72: 13, 0x74: 9, 0x62: 8, 0x66: 12,
                           0x28: 40, 0x29: 41, 0x5C: 92}
                if e in mapping:
                    out.append(mapping[e])
                elif 0x30 <= e <= 0x37:  # octal
                    oct_digits = chr(e)
                    for _ in range(2):
                        if self.pos < n and 0x30 <= d[self.pos] <= 0x37:
                            oct_digits += chr(d[self.pos])
                            self.pos += 1
                    out.append(int(oct_digits, 8) & 0xFF)
                elif e in b"\r\n":
                    if e == 0x0D and self.pos < n and d[self.pos] == 0x0A:
                        self.pos += 1
                else:
                    out.append(e)
            elif c == 0x28:
                depth += 1
                out.append(c)
            elif c == 0x29:
                depth -= 1
                if depth == 0:
                    return bytes(out)
                out.append(c)
            else:
                out.append(c)
        raise PdfError("unterminated string")

    def _hex_string(self) -> bytes:
        self.pos += 1  # <
        d = self.data
        end = d.index(b">", self.pos)
        hx = re.sub(rb"\s", b"", d[self.pos:end])
        self.pos = end + 1
        if len(hx) % 2:
            hx += b"0"
        return bytes.fromhex(hx.decode("ascii"))

    def _dict_or_stream(self) -> Any:
        d = self.data
        self.pos += 2  # <<
        obj: dict[str, Any] = {}
        while True:
            self.skip_ws()
            if d[self.pos:self.pos + 2] == b">>":
                self.pos += 2
                break
            key = self.parse()
            if not isinstance(key, Name):
                raise PdfError(f"dict key not a name: {key!r}")
            obj[str(key)] = self.parse()
        save = self.pos
        self.skip_ws()
        if d[self.pos:self.pos + 6] == b"stream":
            self.pos += 6
            if d[self.pos:self.pos + 2] == b"\r\n":
                self.pos += 2
            elif d[self.pos:self.pos + 1] in (b"\n", b"\r"):
                self.pos += 1
            return _RawStream(obj, self.pos)
        self.pos = save
        return obj


@dataclass
class _RawStream:
    """Stream dict + offset of its data (length resolved lazily)."""
    dict: dict[str, Any]
    data_offset: int


@dataclass
class Stream:
    dict: dict[str, Any]
    raw: bytes  # undecoded (filters still applied)


# --- filters ---------------------------------------------------------------


def _inflate_bounded(data: bytes, cap: int = MAX_INFLATE) -> bytes:
    """zlib inflate with a hard output bound (untrusted-input bomb guard).

    Raises zlib.error for truncated/corrupt streams exactly like
    zlib.decompress did, so callers' fallback paths still trigger."""
    d = zlib.decompressobj()
    out = d.decompress(data, cap)
    if d.unconsumed_tail or (not d.eof and d.decompress(b"", 1)):
        raise PdfUnsupported(f"inflated stream exceeds {cap} byte cap")
    if not d.eof:
        raise zlib.error("incomplete or truncated deflate stream")
    return out


def _png_predictor(data: bytes, colors: int, bpc: int, columns: int) -> bytes:
    bpp = max(1, (colors * bpc) // 8)
    row_len = (columns * colors * bpc + 7) // 8
    n_rows = len(data) // (1 + row_len)
    if n_rows == 0:
        return b""
    # Rows are [filter_type, row_len bytes]; reshape and split.
    arr = np.frombuffer(data[: n_rows * (1 + row_len)], dtype=np.uint8)
    arr = arr.reshape(n_rows, 1 + row_len)
    ftypes = arr[:, 0]
    rows = arr[:, 1:].copy()
    # Sub and Up rows are vectorized (per-lane cumsum within the row /
    # elementwise add of the previous row); Average and Paeth have a
    # sequential left-dependency and stay scalar, but only those rows
    # pay the Python loop.
    prev = np.zeros(row_len, dtype=np.uint8)
    for r in range(n_rows):
        ft = ftypes[r]
        row = rows[r]
        if ft == 0:
            pass
        elif ft == 1:  # Sub: per-lane cumsum along the row (mod 256)
            for lane in range(bpp):
                acc = np.cumsum(row[lane::bpp], dtype=np.uint64)
                row[lane::bpp] = (acc & 0xFF).astype(np.uint8)
        elif ft == 2:  # Up: elementwise add of previous row
            np.add(row, prev, out=row, casting="unsafe")
        elif ft == 3:  # Average (left term is sequential; scalar per row)
            rl = row.tolist()
            pv = prev.tolist()
            for i in range(row_len):
                left = rl[i - bpp] if i >= bpp else 0
                rl[i] = (rl[i] + (left + pv[i]) // 2) & 0xFF
            row[:] = rl
        elif ft == 4:  # Paeth (sequential; scalar per row)
            rl = row.tolist()
            pv = prev.tolist()
            for i in range(row_len):
                a = rl[i - bpp] if i >= bpp else 0
                b = pv[i]
                c = pv[i - bpp] if i >= bpp else 0
                p = a + b - c
                pa, pb, pc = abs(p - a), abs(p - b), abs(p - c)
                pr = a if (pa <= pb and pa <= pc) else (b if pb <= pc else c)
                rl[i] = (rl[i] + pr) & 0xFF
            row[:] = rl
        prev = row
    return rows.tobytes()


def _apply_filters(doc: "PdfDocument", sdict: dict, raw: bytes,
                   stop_before_dct: bool = False) -> bytes | tuple[bytes, str]:
    """Run the filter chain. With stop_before_dct, returns
    (bytes, 'dct'|'jpx') when an image codec filter is reached."""
    filters = doc.resolve(sdict.get("Filter", []))
    if isinstance(filters, (Name, str)):
        filters = [filters]
    parms = doc.resolve(sdict.get("DecodeParms", sdict.get("DP", [])))
    if isinstance(parms, dict) or parms is None:
        parms = [parms]
    data = raw
    for i, f in enumerate(filters):
        f = str(f)
        p = doc.resolve(parms[i]) if i < len(parms) else None
        p = p or {}
        if f in ("FlateDecode", "Fl"):
            data = _inflate_bounded(data)
            pred = doc.resolve(p.get("Predictor", 1)) or 1
            if pred >= 10:
                data = _png_predictor(
                    data,
                    doc.resolve(p.get("Colors", 1)) or 1,
                    doc.resolve(p.get("BitsPerComponent", 8)) or 8,
                    doc.resolve(p.get("Columns", 1)) or 1,
                )
            elif pred != 1:
                raise PdfUnsupported(f"TIFF predictor {pred}")
        elif f in ("ASCIIHexDecode", "AHx"):
            hx = re.sub(rb"[\s>]", b"", data)
            if len(hx) % 2:
                hx += b"0"
            data = bytes.fromhex(hx.decode("ascii"))
        elif f in ("ASCII85Decode", "A85"):
            txt = data.replace(b"<~", b"")
            end = txt.find(b"~>")
            if end != -1:
                txt = txt[:end]
            import base64

            data = base64.a85decode(re.sub(rb"\s", b"", txt))
        elif f in ("RunLengthDecode", "RL"):
            out = bytearray()
            j = 0
            while j < len(data):
                n = data[j]
                j += 1
                if n == 128:
                    break
                if n < 128:
                    out += data[j:j + n + 1]
                    j += n + 1
                else:
                    out += bytes([data[j]]) * (257 - n)
                    j += 1
            data = bytes(out)
        elif f in ("DCTDecode", "DCT", "JPXDecode"):
            if stop_before_dct:
                return data, ("jpx" if f == "JPXDecode" else "dct")
            raise PdfUnsupported(f"filter {f} outside image context")
        elif f == "Crypt":
            raise PdfUnsupported("Crypt filter")
        else:
            raise PdfUnsupported(f"filter {f}")
    if stop_before_dct:
        return data, "raw"
    return data


# --- document --------------------------------------------------------------


class PdfDocument:
    def __init__(self, data: bytes):
        self.data = data
        self.objects: dict[int, Any] = {}  # cache
        self.offsets: dict[int, int] = {}
        self.in_stream: dict[int, tuple[int, int]] = {}  # num → (objstm, idx)
        self.trailer: dict[str, Any] = {}
        self._load_xref()
        if "Encrypt" in self.trailer:
            raise PdfUnsupported("encrypted PDF")

    # --- xref machinery ---------------------------------------------------

    def _load_xref(self) -> None:
        tail = self.data[-2048:]
        m = None
        for m in re.finditer(rb"startxref\s+(\d+)", tail):
            pass
        if m is None:
            self._brute_force_scan()
            return
        offset = int(m.group(1))
        seen = set()
        try:
            while offset and offset not in seen:
                seen.add(offset)
                offset = self._load_xref_section(offset)
        except (PdfError, ValueError, IndexError, zlib.error) as exc:
            logger.debug("xref parse failed (%s); brute-force scan", exc)
            self._brute_force_scan()

    def _load_xref_section(self, offset: int) -> int | None:
        lex = _Lexer(self.data, offset)
        lex.skip_ws()
        if self.data[lex.pos:lex.pos + 4] == b"xref":
            lex.pos += 4
            while True:
                lex.skip_ws()
                if self.data[lex.pos:lex.pos + 7] == b"trailer":
                    lex.pos += 7
                    trailer = lex.parse()
                    break
                start = int(lex.token())
                count = int(lex.token())
                for i in range(count):
                    off = int(lex.token())
                    int(lex.token())  # generation
                    kind = lex.token()
                    num = start + i
                    if kind == b"n" and num not in self.offsets and \
                            num not in self.in_stream:
                        self.offsets[num] = off
            for k, v in trailer.items():
                self.trailer.setdefault(k, v)
            xref_stm = trailer.get("XRefStm")
            if isinstance(xref_stm, int):
                self._load_xref_section(xref_stm)
            prev = trailer.get("Prev")
            return int(prev) if prev is not None else None
        # xref stream: "n g obj <<...>> stream"
        num = int(lex.token())
        int(lex.token())
        if lex.token() != b"obj":
            raise PdfError("bad xref stream header")
        raw = lex.parse()
        if not isinstance(raw, _RawStream):
            raise PdfError("xref object is not a stream")
        stream = self._materialize_stream(raw)
        self.objects[num] = stream
        sdict = stream.dict
        data = _apply_filters(self, sdict, stream.raw)
        w = [int(self.resolve(x)) for x in self.resolve(sdict["W"])]
        size = int(self.resolve(sdict["Size"]))
        index = self.resolve(sdict.get("Index", [0, size]))
        row_len = sum(w)
        pos = 0

        def field(row: bytes, k: int) -> int:
            s = sum(w[:k])
            chunk = row[s:s + w[k]]
            return int.from_bytes(chunk, "big") if chunk else (
                1 if k == 0 else 0
            )

        for j in range(0, len(index), 2):
            start, count = int(index[j]), int(index[j + 1])
            for i in range(count):
                if pos + row_len > len(data):
                    break
                row = data[pos:pos + row_len]
                pos += row_len
                objnum = start + i
                ftype = field(row, 0) if w[0] else 1
                if objnum in self.offsets or objnum in self.in_stream:
                    continue
                if ftype == 1:
                    self.offsets[objnum] = field(row, 1)
                elif ftype == 2:
                    self.in_stream[objnum] = (field(row, 1), field(row, 2))
        for k, v in sdict.items():
            if k in ("Size", "Root", "Info", "ID", "Encrypt"):
                self.trailer.setdefault(k, v)
        prev = sdict.get("Prev")
        return int(prev) if prev is not None else None

    def _brute_force_scan(self) -> None:
        """Recovery path: regex every `N G obj` in the file."""
        for m in re.finditer(rb"(?m)^\s*(\d+)\s+(\d+)\s+obj\b", self.data):
            self.offsets[int(m.group(1))] = m.start()
        if "Root" not in self.trailer:
            m = re.search(rb"/Root\s+(\d+)\s+(\d+)\s+R", self.data)
            if m:
                self.trailer["Root"] = Ref(int(m.group(1)), int(m.group(2)))

    # --- objects ----------------------------------------------------------

    def _materialize_stream(self, raw: _RawStream) -> Stream:
        length = self.resolve(raw.dict.get("Length"))
        if not isinstance(length, int):
            end = self.data.find(b"endstream", raw.data_offset)
            if end == -1:
                raise PdfError("unterminated stream")
            length = end - raw.data_offset
        data = self.data[raw.data_offset:raw.data_offset + length]
        return Stream(raw.dict, data)

    def get_object(self, num: int) -> Any:
        if num in self.objects:
            return self.objects[num]
        value: Any = None
        if num in self.offsets:
            lex = _Lexer(self.data, self.offsets[num])
            lex.skip_ws()
            got = int(lex.token())
            int(lex.token())
            kw = lex.token()
            if kw != b"obj" or got != num:
                value = None
            else:
                value = lex.parse()
                if isinstance(value, _RawStream):
                    value = self._materialize_stream(value)
        elif num in self.in_stream:
            stm_num, idx = self.in_stream[num]
            value = self._objstm_object(stm_num, idx)
        self.objects[num] = value
        return value

    def _objstm_object(self, stm_num: int, idx: int) -> Any:
        stm = self.get_object(stm_num)
        if not isinstance(stm, Stream):
            raise PdfError("object stream missing")
        data = _apply_filters(self, stm.dict, stm.raw)
        n = int(self.resolve(stm.dict["N"]))
        first = int(self.resolve(stm.dict["First"]))
        head = _Lexer(data, 0)
        pairs = []
        for _ in range(n):
            pairs.append((int(head.token()), int(head.token())))
        if idx >= len(pairs):
            raise PdfError("objstm index out of range")
        _objnum, rel = pairs[idx]
        lex = _Lexer(data, first + rel)
        return lex.parse()

    def resolve(self, obj: Any, depth: int = 0) -> Any:
        while isinstance(obj, Ref) and depth < 32:
            obj = self.get_object(obj.num)
            depth += 1
        return obj

    # --- pages ------------------------------------------------------------

    def first_page(self) -> dict[str, Any]:
        root = self.resolve(self.trailer.get("Root"))
        if not isinstance(root, dict):
            raise PdfError("no document catalog")
        node = self.resolve(root.get("Pages"))
        inherited: dict[str, Any] = {}
        depth = 0
        while isinstance(node, dict) and depth < 64:
            depth += 1
            for key in ("Resources", "MediaBox", "Rotate"):
                if key in node:
                    inherited[key] = node[key]
            if str(node.get("Type", "")) == "Page" or "Contents" in node and \
                    "Kids" not in node:
                page = dict(inherited)
                page.update(node)
                return page
            kids = self.resolve(node.get("Kids"))
            if not kids:
                break
            node = self.resolve(kids[0])
        raise PdfError("no page found")


# --- image extraction ------------------------------------------------------


def _decode_image_xobject(doc: PdfDocument, stream: Stream) -> np.ndarray | None:
    """Image XObject → RGB uint8 array, or None if unsupported."""
    d = stream.dict
    try:
        data, codec = _apply_filters(doc, d, stream.raw, stop_before_dct=True)
    except PdfUnsupported:
        return None
    except Exception:
        return None
    if codec == "jpx":
        return None  # JPEG2000: PIL support is build-dependent; skip
    if codec == "dct":
        from PIL import Image

        try:
            img = Image.open(io.BytesIO(data))
            return np.asarray(img.convert("RGB"))
        except Exception:
            return None
    width = doc.resolve(d.get("Width"))
    height = doc.resolve(d.get("Height"))
    bpc = doc.resolve(d.get("BitsPerComponent", 8))
    cs = doc.resolve(d.get("ColorSpace"))
    if not isinstance(width, int) or not isinstance(height, int):
        return None
    palette = None
    ncomp = None
    if isinstance(cs, list) and cs and str(cs[0]) == "Indexed":
        base = doc.resolve(cs[1])
        lookup = doc.resolve(cs[3])
        if isinstance(lookup, Stream):
            lookup = _apply_filters(doc, lookup.dict, lookup.raw)
        base_n = 3 if "RGB" in str(base) else (1 if "Gray" in str(base) else 3)
        if isinstance(lookup, bytes):
            palette = np.frombuffer(lookup, np.uint8)
            palette = palette[: (len(palette) // base_n) * base_n].reshape(
                -1, base_n
            )
            ncomp = 1
    if ncomp is None:
        name = str(cs if not isinstance(cs, list) else cs[0])
        if "RGB" in name:
            ncomp = 3
        elif "Gray" in name or "G" == name:
            ncomp = 1
        elif "CMYK" in name:
            ncomp = 4
        elif isinstance(cs, list) and str(cs[0]) == "ICCBased":
            icc = doc.resolve(cs[1])
            n = doc.resolve(icc.dict.get("N", 3)) if isinstance(icc, Stream) else 3
            ncomp = int(n)
        else:
            ncomp = 3
    if bpc == 1:
        bits = np.unpackbits(
            np.frombuffer(data, np.uint8).reshape(height, -1), axis=1
        )[:, : width * ncomp]
        arr = (bits * 255).astype(np.uint8).reshape(height, width, ncomp)
    elif bpc == 8:
        need = width * height * ncomp
        if len(data) < need:
            return None
        arr = np.frombuffer(data[:need], np.uint8).reshape(
            height, width, ncomp
        )
    else:
        return None
    if palette is not None:
        arr = palette[np.minimum(arr[..., 0], len(palette) - 1)]
        if arr.shape[-1] == 1:
            arr = np.repeat(arr, 3, axis=-1)
    if arr.shape[-1] == 1:
        arr = np.repeat(arr, 3, axis=-1)
    elif arr.shape[-1] == 4:  # CMYK → RGB
        c, m, y, k = [arr[..., i].astype(np.int32) for i in range(4)]
        r = 255 - np.minimum(255, c + k)
        gg = 255 - np.minimum(255, m + k)
        b = 255 - np.minimum(255, y + k)
        arr = np.stack([r, gg, b], axis=-1).astype(np.uint8)
    return arr[..., :3]


def _largest_page_image(doc: PdfDocument, page: dict) -> np.ndarray | None:
    res = doc.resolve(page.get("Resources")) or {}
    xobjects = doc.resolve(res.get("XObject")) or {}
    candidates: list[tuple[int, Stream]] = []
    for _name, ref in list(xobjects.items())[:32]:
        obj = doc.resolve(ref)
        if not isinstance(obj, Stream):
            continue
        if str(doc.resolve(obj.dict.get("Subtype", ""))) != "Image":
            continue
        w = doc.resolve(obj.dict.get("Width", 0)) or 0
        h = doc.resolve(obj.dict.get("Height", 0)) or 0
        if w >= 8 and h >= 8:
            candidates.append((w * h, obj))
    # largest declared size first; the first decodable one wins, so a
    # page of many tiles decodes one image, not all of them
    candidates.sort(key=lambda t: -t[0])
    for _px, obj in candidates:
        arr = _decode_image_xobject(doc, obj)
        if arr is not None:
            return arr
    return None


# --- text fallback ---------------------------------------------------------

_TEXT_SHOW = {b"Tj", b"'", b'"'}


def _extract_text(doc: PdfDocument, page: dict, limit: int = 2000) -> list[str]:
    contents = doc.resolve(page.get("Contents"))
    if isinstance(contents, Stream):
        contents = [contents]
    elif isinstance(contents, list):
        contents = [doc.resolve(c) for c in contents]
    else:
        return []
    data = b"\n".join(
        _apply_filters(doc, c.dict, c.raw)
        for c in contents if isinstance(c, Stream)
    )
    lines: list[str] = []
    current: list[str] = []
    lex = _Lexer(data, 0)
    stack: list[Any] = []
    total = 0
    while lex.pos < len(data) and total < limit:
        lex.skip_ws()
        c = lex.peek()
        if c == -1:
            break
        try:
            if c in (0x2F, 0x28, 0x3C, 0x5B) or \
                    chr(c).isdigit() or c in (0x2B, 0x2D, 0x2E):
                stack.append(lex.parse())
                continue
            op = lex.token()
        except PdfError:
            break
        if not op:
            lex.pos += 1
            continue
        if op in _TEXT_SHOW and stack:
            s = stack[-1]
            if isinstance(s, bytes):
                txt = _printable(s)
                if txt:
                    current.append(txt)
                    total += len(txt)
        elif op == b"TJ" and stack and isinstance(stack[-1], list):
            parts = [
                _printable(x) for x in stack[-1] if isinstance(x, bytes)
            ]
            txt = "".join(parts)
            if txt:
                current.append(txt)
                total += len(txt)
        elif op in (b"Td", b"TD", b"T*", b"TL", b"Tm", b"ET"):
            if current:
                lines.append("".join(current).strip())
                current = []
        stack = []
    if current:
        lines.append("".join(current).strip())
    return [ln for ln in lines if ln]


def _printable(raw: bytes) -> str:
    """Simple-font bytes ≈ latin-1; drop strings that are mostly
    unprintable (CID-keyed fonts we can't map)."""
    txt = raw.decode("latin-1", errors="replace")
    printable = sum(1 for ch in txt if ch.isprintable() or ch.isspace())
    if len(txt) == 0 or printable / len(txt) < 0.7:
        return ""
    return "".join(ch if ch.isprintable() or ch == " " else " " for ch in txt)


def _render_text_page(lines: list[str], media_box: list[float],
                      max_dim: int = MAX_RENDER_DIM) -> np.ndarray:
    from PIL import Image, ImageDraw, ImageFont

    try:
        bw = abs(float(media_box[2]) - float(media_box[0])) or 612.0
        bh = abs(float(media_box[3]) - float(media_box[1])) or 792.0
    except Exception:
        bw, bh = 612.0, 792.0
    scale = max_dim / max(bw, bh)
    w = max(64, int(bw * scale))
    h = max(64, int(bh * scale))
    img = Image.new("RGB", (w, h), (255, 255, 255))
    draw = ImageDraw.Draw(img)
    margin = w // 12
    font_px = max(8, h // 42)
    try:
        font = ImageFont.load_default(size=font_px)
    except TypeError:  # older PIL: fixed-size bitmap font
        font = ImageFont.load_default()
    y = margin
    max_chars = max(16, (w - 2 * margin) // max(4, font_px // 2))
    for line in lines:
        while line and y < h - margin:
            draw.text((margin, y), line[:max_chars], fill=(40, 40, 40),
                      font=font)
            line = line[max_chars:]
            y += int(font_px * 1.45)
        if y >= h - margin:
            break
    return np.asarray(img)


# --- public API ------------------------------------------------------------


def render_pdf(path_or_bytes: str | bytes,
               max_dim: int = MAX_RENDER_DIM) -> np.ndarray:
    """First-page thumbnail → RGBA uint8 [H, W, 4].

    Raises PdfError/PdfUnsupported when nothing can be produced.
    """
    if isinstance(path_or_bytes, bytes):
        data = path_or_bytes
    else:
        with open(path_or_bytes, "rb") as f:
            data = f.read()
    if not data.startswith(b"%PDF"):
        raise PdfError("not a PDF")
    doc = PdfDocument(data)
    page = doc.first_page()

    # 1. the page's own /Thumb image (PDF's bundled thumbnail)
    thumb = doc.resolve(page.get("Thumb"))
    arr = None
    if isinstance(thumb, Stream):
        arr = _decode_image_xobject(doc, thumb)
    # 2. real page render: content-stream rasterizer over cairo
    # (pdf_raster.py — paths, text, transforms, placed images; the
    # PDFium-role renderer, ref:crates/images/src/pdf.rs:82-83)
    if arr is None:
        from .pdf_raster import rasterize_page

        try:
            arr = rasterize_page(doc, page, max_dim)
        except Exception:
            logger.debug("pdf raster failed; falling back", exc_info=True)
            arr = None
    # 3. largest image on the page (cairo unavailable / nothing painted)
    if arr is None:
        arr = _largest_page_image(doc, page)
    # 4. typeset extracted text
    if arr is None:
        lines = _extract_text(doc, page)
        if not lines:
            raise PdfUnsupported("no renderable content on page 1")
        arr = _render_text_page(
            lines, doc.resolve(page.get("MediaBox")) or [0, 0, 612, 792],
            max_dim,
        )
    rotate = doc.resolve(page.get("Rotate", 0)) or 0
    if rotate % 360:
        arr = np.rot90(arr, k=(-int(rotate) // 90) % 4)
    h, w = arr.shape[:2]
    if max(h, w) > max_dim:  # bound the decode for the batch pipeline
        step = -(-max(h, w) // max_dim)
        arr = np.ascontiguousarray(arr[::step, ::step])
        h, w = arr.shape[:2]
    rgba = np.dstack([arr, np.full((h, w, 1), 255, np.uint8)])
    return rgba


def pdf_available() -> bool:
    return True  # pure python + PIL; always present
