"""PDF page rasterizer — a minimal content-stream interpreter on cairo.

The role PDFium plays in the reference (ref:crates/images/src/pdf.rs:
82-83 renders page 1 into a bitmap). This module interprets the page's
content stream directly: path construction + fill/stroke/clip, colors
(gray/RGB/CMYK + numeric sc/scn), affine transforms (q/Q/cm), text via
cairo's toy font API with the PDF text matrix, image/form XObjects
placed through the CTM (the unit-square mapping), drawn onto a cairo
ARGB32 surface through ctypes (the binding style of svg.py).

Text renders with the EMBEDDED font program when the PDF carries one
(FontFile/FontFile2/FontFile3 via freetype + cairo_show_glyphs —
pdf_fonts.py; the common case for real documents, which subset-embed
their faces), falling back to cairo toy faces otherwise.

Deliberate scope (thumbnails, not print fidelity): no shading/pattern
color spaces (skipped), no blend modes or soft masks. Unsupported
constructs degrade to "skip that operator", never to an exception —
the caller falls back to the image/text strategies.
"""

from __future__ import annotations

import ctypes
import ctypes.util
import logging
import math
from typing import Any

import numpy as np

logger = logging.getLogger(__name__)

_FORMAT_ARGB32 = 0
_FONT_SLANT_NORMAL, _FONT_SLANT_ITALIC = 0, 1
_FONT_WEIGHT_NORMAL, _FONT_WEIGHT_BOLD = 0, 1
_FILL_RULE_WINDING, _FILL_RULE_EVEN_ODD = 0, 1

_MAX_OPS = 200_000          # content-stream operator budget
_MAX_FORM_DEPTH = 6         # nested Form XObject recursion cap


class _CairoMatrix(ctypes.Structure):
    _fields_ = [(n, ctypes.c_double) for n in ("xx", "yx", "xy", "yy", "x0", "y0")]


class _TextExtents(ctypes.Structure):
    _fields_ = [(n, ctypes.c_double) for n in
                ("x_bearing", "y_bearing", "width", "height",
                 "x_advance", "y_advance")]


_cairo_lib: list[Any] = []  # memoized [handle] or [None]


def _cairo():
    if _cairo_lib:
        return _cairo_lib[0]
    try:
        c = ctypes.CDLL(ctypes.util.find_library("cairo") or "libcairo.so.2")
        V, D, I = ctypes.c_void_p, ctypes.c_double, ctypes.c_int
        c.cairo_image_surface_create.restype = V
        c.cairo_image_surface_create.argtypes = [I, I, I]
        c.cairo_image_surface_create_for_data.restype = V
        c.cairo_image_surface_create_for_data.argtypes = [
            ctypes.c_char_p, I, I, I, I]
        c.cairo_create.restype = V
        c.cairo_create.argtypes = [V]
        c.cairo_status.restype = I
        c.cairo_status.argtypes = [V]
        c.cairo_image_surface_get_data.restype = ctypes.POINTER(ctypes.c_ubyte)
        c.cairo_image_surface_get_data.argtypes = [V]
        c.cairo_image_surface_get_stride.restype = I
        c.cairo_image_surface_get_stride.argtypes = [V]
        for fn, args in {
            "cairo_destroy": [V], "cairo_surface_destroy": [V],
            "cairo_surface_flush": [V],
            "cairo_save": [V], "cairo_restore": [V],
            "cairo_new_path": [V], "cairo_close_path": [V],
            "cairo_move_to": [V, D, D], "cairo_line_to": [V, D, D],
            "cairo_curve_to": [V, D, D, D, D, D, D],
            "cairo_set_source_rgb": [V, D, D, D],
            "cairo_set_line_width": [V, D],
            "cairo_fill": [V], "cairo_fill_preserve": [V],
            "cairo_stroke": [V], "cairo_stroke_preserve": [V],
            "cairo_clip": [V], "cairo_paint": [V],
            "cairo_set_fill_rule": [V, I],
            "cairo_set_matrix": [V, ctypes.POINTER(_CairoMatrix)],
            "cairo_identity_matrix": [V],
            "cairo_set_source_surface": [V, V, D, D],
            "cairo_select_font_face": [V, ctypes.c_char_p, I, I],
            "cairo_set_font_size": [V, D],
            "cairo_show_text": [V, ctypes.c_char_p],
            "cairo_text_extents": [V, ctypes.c_char_p,
                                   ctypes.POINTER(_TextExtents)],
        }.items():
            getattr(c, fn).argtypes = args
            getattr(c, fn).restype = None
        _cairo_lib.append(c)
    except OSError as exc:
        logger.info("cairo unavailable for PDF raster: %s", exc)
        _cairo_lib.append(None)
    return _cairo_lib[0]


def raster_available() -> bool:
    return _cairo() is not None


# --- affine helpers (PDF matrices are [a b c d e f]) -----------------------


def _mat_mul(m, n):
    a, b, c, d, e, f = m
    a2, b2, c2, d2, e2, f2 = n
    return (
        a * a2 + b * c2, a * b2 + b * d2,
        c * a2 + d * c2, c * b2 + d * d2,
        e * a2 + f * c2 + e2, e * b2 + f * d2 + f2,
    )


def _mat_apply(m, x, y):
    a, b, c, d, e, f = m
    return a * x + c * y + e, b * x + d * y + f


def _mat_scale(m) -> float:
    """Geometric-mean scale factor (for line widths / font sizes)."""
    a, b, c, d, _e, _f = m
    det = abs(a * d - b * c)
    return math.sqrt(det) if det > 1e-12 else 1e-6


class _GState:
    __slots__ = ("ctm", "fill", "stroke", "line_width")

    def __init__(self, ctm, fill=(0.0, 0.0, 0.0), stroke=(0.0, 0.0, 0.0),
                 line_width=1.0):
        self.ctm = ctm
        self.fill = fill
        self.stroke = stroke
        self.line_width = line_width

    def copy(self):
        return _GState(self.ctm, self.fill, self.stroke, self.line_width)


def _to_rgb(ops: list, n: int) -> tuple[float, float, float] | None:
    """Color from the last n numeric operands (1=gray, 3=rgb, 4=cmyk)."""
    if len(ops) < n:
        return None
    try:
        vals = [max(0.0, min(1.0, float(v))) for v in ops[-n:]]
    except (TypeError, ValueError):
        return None
    if n == 1:
        return (vals[0],) * 3
    if n == 3:
        return tuple(vals)  # type: ignore[return-value]
    cy, m, y, k = vals
    return ((1 - cy) * (1 - k), (1 - m) * (1 - k), (1 - y) * (1 - k))


class _Raster:
    """One rasterization run over a page's content streams."""

    def __init__(self, doc, cr, base_ctm):
        self.doc = doc
        self.c = _cairo()
        self.cr = cr
        self.base = base_ctm
        self.gs = _GState(base_ctm)
        self.stack: list[_GState] = []
        self.floors = [0]  # per-form gstate-stack floor: inner Q can't
        # pop the caller's states (or underflow cairo's save stack)
        self.ops = 0
        self.painted = 0  # fills/strokes/images actually drawn
        self.pending_clip: int | None = None
        self._keepalive: list[Any] = []  # image buffers cairo reads from
        # text state
        self.tm = None
        self.tlm = None
        self.leading = 0.0
        self.font_size = 12.0
        self.font_face = (b"sans-serif", _FONT_SLANT_NORMAL, _FONT_WEIGHT_NORMAL)
        self.embedded = None        # EmbeddedFont for the current Tf
        self.embedded_glyphs = 0    # glyphs drawn from embedded programs
        self._font_cache: dict[str, Any] = {}  # Tf alias → EmbeddedFont|None

    # --- path + paint ---------------------------------------------------

    def _xy(self, x, y):
        return _mat_apply(self.gs.ctm, float(x), float(y))

    def _paint(self, fill: bool, stroke: bool, evenodd: bool = False) -> None:
        # cairo's clip consumes the path, so with a pending W/W* the
        # paint ops run preserve variants and the clip lands last
        c, cr = self.c, self.cr
        c.cairo_set_fill_rule(
            cr, _FILL_RULE_EVEN_ODD if evenodd else _FILL_RULE_WINDING
        )
        if fill:
            c.cairo_set_source_rgb(cr, *self.gs.fill)
            (c.cairo_fill_preserve if (stroke or self.pending_clip is not None)
             else c.cairo_fill)(cr)
            self.painted += 1
        if stroke:
            c.cairo_set_source_rgb(cr, *self.gs.stroke)
            c.cairo_set_line_width(
                cr, max(0.1, self.gs.line_width * _mat_scale(self.gs.ctm))
            )
            (c.cairo_stroke_preserve if self.pending_clip is not None
             else c.cairo_stroke)(cr)
            self.painted += 1
        if self.pending_clip is not None:
            c.cairo_set_fill_rule(cr, self.pending_clip)
            c.cairo_clip(cr)
            self.pending_clip = None
        c.cairo_new_path(cr)

    # --- text -----------------------------------------------------------

    def _show_text(self, raw: bytes) -> None:
        if self.tm is None:
            return
        if self.embedded is not None and self._show_embedded(raw):
            return
        self._show_toy(raw)

    def _show_embedded(self, raw: bytes) -> bool:
        """Draw a show op with the embedded font program's real glyphs;
        returns False (→ toy fallback) when nothing maps."""
        from .pdf_fonts import CairoGlyph

        font = self.embedded
        codes = font.codes(raw)
        pairs = [(code, font.gid(code)) for code in codes]
        if not any(gid for _c, gid in pairs):
            return False  # font maps nothing here → toy fallback
        c, cr = self.c, self.cr
        m = _mat_mul(self.tm, self.gs.ctm)
        x, y = _mat_apply(m, 0, 0)
        scale = _mat_scale(m)
        size = self.font_size * scale
        if size < 1.0 or size > 2000:
            return True  # suppressed, like the toy path's size guard
        c.cairo_set_font_face(cr, font.cairo_face)
        c.cairo_set_font_size(cr, size)
        c.cairo_set_source_rgb(cr, *self.gs.fill)
        glyphs = (CairoGlyph * len(pairs))()
        n = 0
        adv_text = 0.0  # text-space units for the tm update
        for code, gid in pairs:
            # gid 0 (e.g. subset fonts whose space has no outline)
            # draws nothing but MUST still advance, or words collapse
            probe = CairoGlyph(gid, x, y)
            if gid:
                glyphs[n] = probe
                n += 1
            w = font.width(code)
            if w:
                step = w / 1000.0 * self.font_size  # text space
            elif gid:
                ext = _TextExtents()
                c.cairo_glyph_extents(cr, ctypes.byref(probe), 1,
                                      ctypes.byref(ext))
                step = ext.x_advance / max(scale, 1e-6)
            else:
                step = font.default_width / 1000.0 * self.font_size
            adv_text += step
            x += step * scale  # device-space horizontal advance
        c.cairo_show_glyphs(cr, glyphs, n)
        c.cairo_new_path(cr)
        self.painted += 1
        self.embedded_glyphs += n
        self.tm = _mat_mul((1, 0, 0, 1, adv_text, 0), self.tm)
        return True

    def _show_toy(self, raw: bytes) -> None:
        from .pdf import _printable

        txt = _printable(raw).strip("\x00")
        if not txt:
            return
        c, cr = self.c, self.cr
        m = _mat_mul(self.tm, self.gs.ctm)
        x, y = _mat_apply(m, 0, 0)
        size = self.font_size * _mat_scale(m)
        if size < 1.0 or size > 2000:
            return
        c.cairo_select_font_face(cr, *self.font_face)
        c.cairo_set_font_size(cr, size)
        c.cairo_set_source_rgb(cr, *self.gs.fill)
        data = txt.encode("utf-8")
        c.cairo_move_to(cr, x, y)
        c.cairo_show_text(cr, data)
        c.cairo_new_path(cr)
        self.painted += 1
        ext = _TextExtents()
        c.cairo_text_extents(cr, data, ctypes.byref(ext))
        # advance the text matrix by the device advance mapped back to
        # text space (approximate: divide by the matrix scale)
        adv = ext.x_advance / max(_mat_scale(m), 1e-6)
        self.tm = _mat_mul((1, 0, 0, 1, adv, 0), self.tm)

    def _set_font(self, name: Any, size: Any, resources: dict) -> None:
        try:
            self.font_size = float(size)
        except (TypeError, ValueError):
            return
        # Tf's operand is the resource alias (/F1); the styling lives in
        # the font dict's BaseFont (e.g. Times-BoldItalic)
        base = str(name or "")
        self.embedded = None
        try:
            fonts = self.doc.resolve(resources.get("Font")) or {}
            ref = fonts.get(str(name))  # the UNresolved ref names the
            # object — distinct font dicts sharing a BaseFont (e.g. a
            # form XObject's own /F1) must not collide in the cache
            fdict = self.doc.resolve(ref)
            if isinstance(fdict, dict):
                base = str(self.doc.resolve(fdict.get("BaseFont", base)))
                from .pdf_fonts import load_embedded_font

                key = (f"inline-{id(fdict)}" if isinstance(ref, dict)
                       else repr(ref))
                if key not in self._font_cache:
                    self._font_cache[key] = load_embedded_font(self.doc, fdict)
                self.embedded = self._font_cache[key]
        except Exception:
            pass
        base = base.lower()
        slant = _FONT_SLANT_ITALIC if ("italic" in base or "oblique" in base) \
            else _FONT_SLANT_NORMAL
        weight = _FONT_WEIGHT_BOLD if "bold" in base else _FONT_WEIGHT_NORMAL
        family = b"sans-serif"
        if "times" in base or "serif" in base:
            family = b"serif"
        elif "courier" in base or "mono" in base:
            family = b"monospace"
        self.font_face = (family, slant, weight)

    # --- xobjects -------------------------------------------------------

    def _draw_image(self, arr: np.ndarray) -> None:
        """Place an RGB image through the CTM (PDF maps the image to the
        unit square; rows run top-down)."""
        c, cr = self.c, self.cr
        h, w = arr.shape[:2]
        if h < 1 or w < 1:
            return
        # RGB → premultiplied native-endian ARGB32 (BGRA bytes on LE)
        bgra = np.empty((h, w, 4), np.uint8)
        bgra[..., 0] = arr[..., 2]
        bgra[..., 1] = arr[..., 1]
        bgra[..., 2] = arr[..., 0]
        bgra[..., 3] = 255
        stride = w * 4
        buf = np.ascontiguousarray(bgra).tobytes()
        self._keepalive.append(buf)
        surf = c.cairo_image_surface_create_for_data(
            buf, _FORMAT_ARGB32, w, h, stride
        )
        try:
            # device matrix: unit square → CTM; image pixels → unit
            # square is scale(1/w, -1/h) + translate(0, 1)
            m = _mat_mul((1.0 / w, 0, 0, -1.0 / h, 0, 1), self.gs.ctm)
            cm = _CairoMatrix(m[0], m[1], m[2], m[3], m[4], m[5])
            c.cairo_save(cr)
            c.cairo_set_matrix(cr, ctypes.byref(cm))
            c.cairo_set_source_surface(cr, surf, 0, 0)
            c.cairo_paint(cr)
            c.cairo_restore(cr)
            self.painted += 1
        finally:
            c.cairo_surface_destroy(surf)

    def _do_xobject(self, name: Any, resources: dict, depth: int) -> None:
        from .pdf import Stream, _decode_image_xobject

        xobjects = self.doc.resolve(resources.get("XObject")) or {}
        obj = self.doc.resolve(xobjects.get(str(name)))
        if not isinstance(obj, Stream):
            return
        subtype = str(self.doc.resolve(obj.dict.get("Subtype", "")))
        if subtype == "Image":
            arr = _decode_image_xobject(self.doc, obj)
            if arr is not None:
                self._draw_image(arr)
        elif subtype == "Form" and depth < _MAX_FORM_DEPTH:
            from .pdf import _apply_filters

            try:
                content = _apply_filters(self.doc, obj.dict, obj.raw)
            except Exception:
                return
            sub_res = self.doc.resolve(obj.dict.get("Resources")) or resources
            self.stack.append(self.gs.copy())
            self.c.cairo_save(self.cr)
            floor = len(self.stack)
            self.floors.append(floor)
            mtx = self.doc.resolve(obj.dict.get("Matrix"))
            if isinstance(mtx, list) and len(mtx) == 6:
                try:
                    self.gs.ctm = _mat_mul(
                        tuple(float(v) for v in mtx), self.gs.ctm
                    )
                except (TypeError, ValueError):
                    pass
            try:
                self.run(content, sub_res, depth + 1)
            finally:
                # rebalance any unclosed q's the form content left open
                while len(self.stack) > floor:
                    self.gs = self.stack.pop()
                    self.c.cairo_restore(self.cr)
                self.floors.pop()
                self.c.cairo_restore(self.cr)
                self.gs = self.stack.pop()

    # --- the interpreter ------------------------------------------------

    def run(self, content: bytes, resources: dict, depth: int = 0) -> None:
        from .pdf import PdfError, _Lexer

        c, cr = self.c, self.cr
        lex = _Lexer(content, 0)
        operands: list[Any] = []
        cur = (0.0, 0.0)  # current point in user space (pre-CTM)
        start = cur
        while lex.pos < len(content) and self.ops < _MAX_OPS:
            lex.skip_ws()
            ch = lex.peek()
            if ch == -1:
                break
            try:
                # ASCII digits ONLY: chr(0xB2).isdigit() is True ('²'),
                # and binary residue must not abort the whole render
                if ch in (0x2F, 0x28, 0x3C, 0x5B) or 0x30 <= ch <= 0x39 \
                        or ch in (0x2B, 0x2D, 0x2E):
                    operands.append(lex.parse())
                    self.ops += 1  # operands burn budget too, or a
                    # stream of bare numbers spins outside the cap
                    if len(operands) > 32:
                        del operands[:-32]
                    continue
                op = lex.token()
            except PdfError:
                lex.pos += 1  # skip the bad byte, keep rendering
                operands = []
                self.ops += 1  # binary junk still burns the op budget
                continue
            if not op:
                lex.pos += 1
                continue
            self.ops += 1
            try:
                cur, start = self._exec(
                    op, operands, resources, depth, cur, start
                )
            except Exception:  # noqa: BLE001 - skip busted operators
                pass
            if op == b"ID":  # inline image data: skip to EI
                end = content.find(b"EI", lex.pos)
                lex.pos = len(content) if end < 0 else end + 2
            operands = []

    def _exec(self, op, st, resources, depth, cur, start):
        c, cr = self.c, self.cr
        gs = self.gs
        num = _num
        if op == b"q":
            self.stack.append(gs.copy())
            c.cairo_save(cr)
        elif op == b"Q":
            # never pop past the current form's floor — an excess Q in
            # form content must not consume the caller's states
            if len(self.stack) > self.floors[-1]:
                self.gs = self.stack.pop()
                c.cairo_restore(cr)
        elif op == b"cm" and len(st) >= 6:
            try:
                m = tuple(float(v) for v in st[-6:])
                gs.ctm = _mat_mul(m, gs.ctm)
            except (TypeError, ValueError):
                pass
        elif op == b"w" and st:
            gs.line_width = max(0.0, num(st[-1], 1.0))
        # --- colors
        elif op == b"g":
            gs.fill = _to_rgb(st, 1) or gs.fill
        elif op == b"G":
            gs.stroke = _to_rgb(st, 1) or gs.stroke
        elif op == b"rg":
            gs.fill = _to_rgb(st, 3) or gs.fill
        elif op == b"RG":
            gs.stroke = _to_rgb(st, 3) or gs.stroke
        elif op == b"k":
            gs.fill = _to_rgb(st, 4) or gs.fill
        elif op == b"K":
            gs.stroke = _to_rgb(st, 4) or gs.stroke
        elif op in (b"sc", b"scn", b"SC", b"SCN"):
            nums = [v for v in st if isinstance(v, (int, float))]
            col = _to_rgb(nums, len(nums)) if len(nums) in (1, 3, 4) else None
            if col:
                if op.isupper():
                    gs.stroke = col
                else:
                    gs.fill = col
        # --- path construction
        elif op == b"m" and len(st) >= 2:
            cur = (num(st[-2]), num(st[-1]))
            start = cur
            c.cairo_move_to(cr, *self._xy(*cur))
        elif op == b"l" and len(st) >= 2:
            cur = (num(st[-2]), num(st[-1]))
            c.cairo_line_to(cr, *self._xy(*cur))
        elif op == b"c" and len(st) >= 6:
            p1 = (num(st[-6]), num(st[-5]))
            p2 = (num(st[-4]), num(st[-3]))
            cur = (num(st[-2]), num(st[-1]))
            c.cairo_curve_to(cr, *self._xy(*p1), *self._xy(*p2), *self._xy(*cur))
        elif op == b"v" and len(st) >= 4:
            p2 = (num(st[-4]), num(st[-3]))
            end = (num(st[-2]), num(st[-1]))
            c.cairo_curve_to(cr, *self._xy(*cur), *self._xy(*p2), *self._xy(*end))
            cur = end
        elif op == b"y" and len(st) >= 4:
            p1 = (num(st[-4]), num(st[-3]))
            end = (num(st[-2]), num(st[-1]))
            c.cairo_curve_to(cr, *self._xy(*p1), *self._xy(*end), *self._xy(*end))
            cur = end
        elif op == b"h":
            c.cairo_close_path(cr)
            cur = start
        elif op == b"re" and len(st) >= 4:
            x, y, w_, h_ = (num(v) for v in st[-4:])
            c.cairo_move_to(cr, *self._xy(x, y))
            c.cairo_line_to(cr, *self._xy(x + w_, y))
            c.cairo_line_to(cr, *self._xy(x + w_, y + h_))
            c.cairo_line_to(cr, *self._xy(x, y + h_))
            c.cairo_close_path(cr)
            cur = start = (x, y)
        # --- painting
        elif op == b"f" or op == b"F":
            self._paint(fill=True, stroke=False)
        elif op == b"f*":
            self._paint(fill=True, stroke=False, evenodd=True)
        elif op == b"B":
            self._paint(fill=True, stroke=True)
        elif op == b"B*":
            self._paint(fill=True, stroke=True, evenodd=True)
        elif op in (b"b", b"b*"):
            c.cairo_close_path(cr)
            self._paint(fill=True, stroke=True, evenodd=op == b"b*")
        elif op == b"S":
            self._paint(fill=False, stroke=True)
        elif op == b"s":
            c.cairo_close_path(cr)
            self._paint(fill=False, stroke=True)
        elif op == b"n":
            self._paint(fill=False, stroke=False)
        elif op == b"W":
            self.pending_clip = _FILL_RULE_WINDING
        elif op == b"W*":
            self.pending_clip = _FILL_RULE_EVEN_ODD
        # --- text
        elif op == b"BT":
            self.tm = (1, 0, 0, 1, 0, 0)
            self.tlm = self.tm
        elif op == b"ET":
            self.tm = self.tlm = None
        elif op == b"Tf" and len(st) >= 2:
            self._set_font(st[-2], st[-1], resources)
        elif op == b"TL" and st:
            self.leading = num(st[-1])
        elif op == b"Td" and len(st) >= 2 and self.tlm is not None:
            self.tlm = _mat_mul((1, 0, 0, 1, num(st[-2]), num(st[-1])), self.tlm)
            self.tm = self.tlm
        elif op == b"TD" and len(st) >= 2 and self.tlm is not None:
            self.leading = -num(st[-1])
            self.tlm = _mat_mul((1, 0, 0, 1, num(st[-2]), num(st[-1])), self.tlm)
            self.tm = self.tlm
        elif op == b"Tm" and len(st) >= 6:
            try:
                self.tlm = tuple(float(v) for v in st[-6:])
                self.tm = self.tlm
            except (TypeError, ValueError):
                pass
        elif op == b"T*" and self.tlm is not None:
            self.tlm = _mat_mul((1, 0, 0, 1, 0, -self.leading), self.tlm)
            self.tm = self.tlm
        elif op == b"Tj" and st and isinstance(st[-1], bytes):
            self._show_text(st[-1])
        elif op in (b"'", b'"'):
            if self.tlm is not None:
                self.tlm = _mat_mul((1, 0, 0, 1, 0, -self.leading), self.tlm)
                self.tm = self.tlm
            raw = next((v for v in reversed(st) if isinstance(v, bytes)), None)
            if raw is not None:
                self._show_text(raw)
        elif op == b"TJ" and st and isinstance(st[-1], list):
            for item in st[-1]:
                if isinstance(item, bytes):
                    self._show_text(item)
        # --- xobjects
        elif op == b"Do" and st:
            self._do_xobject(st[-1], resources, depth)
        return cur, start


def _num(v, default: float = 0.0) -> float:
    try:
        return float(v)
    except (TypeError, ValueError):
        return default


def rasterize_page(doc, page: dict, max_dim: int,
                   stats: dict | None = None) -> np.ndarray | None:
    """Render page 1's content stream; None when cairo is missing, the
    page has no content, or nothing got painted. `stats`, when given,
    receives interpreter counters (painted ops, embedded glyphs drawn)."""
    from .pdf import Stream, _apply_filters

    c = _cairo()
    if c is None:
        return None
    contents = doc.resolve(page.get("Contents"))
    if isinstance(contents, Stream):
        contents = [contents]
    elif isinstance(contents, list):
        contents = [doc.resolve(x) for x in contents]
    else:
        return None
    try:
        data = b"\n".join(
            _apply_filters(doc, s.dict, s.raw)
            for s in contents if isinstance(s, Stream)
        )
    except Exception:
        return None
    if not data.strip():
        return None

    box = doc.resolve(page.get("MediaBox")) or [0, 0, 612, 792]
    try:
        x0, y0, x1, y1 = (float(v) for v in box)
    except (TypeError, ValueError):
        x0, y0, x1, y1 = 0.0, 0.0, 612.0, 792.0
    bw, bh = abs(x1 - x0) or 612.0, abs(y1 - y0) or 792.0
    scale = max_dim / max(bw, bh)
    w = max(8, int(round(bw * scale)))
    h = max(8, int(round(bh * scale)))

    surface = c.cairo_image_surface_create(_FORMAT_ARGB32, w, h)
    cr = c.cairo_create(surface)
    if c.cairo_status(cr) != 0:
        c.cairo_destroy(cr)
        c.cairo_surface_destroy(surface)
        return None
    r = None
    try:
        # white page background
        c.cairo_set_source_rgb(cr, 1.0, 1.0, 1.0)
        c.cairo_paint(cr)
        # PDF user space (origin bottom-left) → device pixels
        base = (scale, 0.0, 0.0, -scale, -x0 * scale, y1 * scale)
        r = _Raster(doc, cr, base)
        res = doc.resolve(page.get("Resources")) or {}
        r.run(data, res)
        if stats is not None:
            stats["painted"] = r.painted
            stats["embedded_glyphs"] = r.embedded_glyphs
        if r.painted == 0:
            return None
        c.cairo_surface_flush(surface)
        stride = c.cairo_image_surface_get_stride(surface)
        buf = c.cairo_image_surface_get_data(surface)
        raw = np.ctypeslib.as_array(buf, shape=(h, stride))
        px = raw[:, : w * 4].reshape(h, w, 4).copy()
    finally:
        c.cairo_destroy(cr)
        c.cairo_surface_destroy(surface)
        # native font faces AFTER the context that references them
        if r is not None:
            for font in r._font_cache.values():
                if font is not None:
                    font.release()
    # premultiplied native-endian ARGB → RGB over white
    b, g, rr, a = (px[..., i].astype(np.uint16) for i in range(4))
    inv = (255 - a)
    out = np.stack([
        np.minimum(255, rr + inv), np.minimum(255, g + inv),
        np.minimum(255, b + inv),
    ], axis=-1).astype(np.uint8)
    return out
