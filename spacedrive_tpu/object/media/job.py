"""MediaProcessorJob — thumbnails + EXIF rows + labeler batches.

Parity: ref:core/src/object/media/media_processor/job.rs — init
dispatches ALL thumbnails to the node-wide thumbnailer actor (:148-170),
optionally enqueues an image-labeler batch (:176-196); steps are chunks
of 10 files of EXIF extraction plus WaitThumbnails/WaitLabels
rendezvous steps (:83-88, :199-230).
"""

from __future__ import annotations

import logging
import os
from typing import Any

from ...db.database import escape_like
from ...files.isolated_path import full_path_from_db_row as _full_path
from ...files.isolated_path import materialized_prefix
from ...jobs import StatefulJob
from ...jobs.job import JobContext, JobError, StepResult
from ...jobs.manager import register_job
from .media_data import ImageMetadata

logger = logging.getLogger(__name__)

BATCH_SIZE = 10  # ref:media_processor/job.rs:50

# extensions we can thumbnail / extract exif from (decodable subset of
# the reference's FILTERED_{IMAGE,VIDEO}_EXTENSIONS; videos get a
# keyframe thumb, ref:media_processor/job.rs + thumbnail/process.rs:463)
from .thumbnail.process import (
    DOC_EXTENSIONS,
    IMAGE_EXTENSIONS,
    VIDEO_EXTENSIONS,
)

THUMBNAILABLE_EXTENSIONS = (
    tuple(IMAGE_EXTENSIONS) + tuple(VIDEO_EXTENSIONS) + tuple(DOC_EXTENSIONS)
)
EXIF_EXTENSIONS = ("jpg", "jpeg", "png", "tiff", "webp")
# media_data rows extract for EXIF-bearing images AND videos
# (ref:media_data_extractor.rs images; video facts via the decoder)
MEDIA_DATA_EXTENSIONS = EXIF_EXTENSIONS + tuple(VIDEO_EXTENSIONS)


@register_job
class MediaProcessorJob(StatefulJob):
    """init: {location_id, sub_path?, backend?}"""

    NAME = "media_processor"
    INVALIDATES = ("search.paths", "labels.list")
    IS_BATCHED = True

    async def init_job(self, ctx: JobContext) -> None:
        library = ctx.library
        loc_id = self.init["location_id"]
        location = library.db.find_one("location", id=loc_id)
        if location is None:
            raise JobError(f"location {loc_id} not found")
        self.data.update(location_id=loc_id, location_path=location["path"])

        qmarks = ",".join("?" for _ in THUMBNAILABLE_EXTENSIONS)
        sub_filter = ""
        params: list[Any] = [loc_id, *THUMBNAILABLE_EXTENSIONS]
        if self.init.get("sub_path"):
            sub_filter = " AND materialized_path LIKE ? ESCAPE '\\'"
            params.append(escape_like(materialized_prefix(self.init['sub_path'])) + "%")
        rows = library.db.query(
            f"SELECT id, pub_id, cas_id, object_id, materialized_path, name, extension "
            f"FROM file_path WHERE location_id = ? AND is_dir = 0 "
            f"AND object_id IS NOT NULL AND cas_id IS NOT NULL "
            f"AND extension IN ({qmarks}){sub_filter}",
            tuple(params),
        )

        # dispatch ALL thumbnails up-front to the node thumbnailer actor
        # (ref:job.rs:148-156); the job only awaits counts later.
        thumbnailer = getattr(getattr(library, "node", None), "thumbnailer", None)
        dispatched = 0
        thumb_batch_id = 0
        if thumbnailer is not None and rows:
            loc_path = self.data["location_path"]
            batch = [
                (r["cas_id"], _full_path(loc_path, r)) for r in rows
            ]
            thumb_batch_id = thumbnailer.new_indexed_thumbnails_batch(
                library.id, batch, background=False
            )
            dispatched = len(batch)
        self.data["thumbs_dispatched"] = dispatched

        exif_rows = [
            r for r in rows if (r["extension"] or "").lower() in MEDIA_DATA_EXTENSIONS
        ]
        for i in range(0, len(exif_rows), BATCH_SIZE):
            chunk = exif_rows[i:i + BATCH_SIZE]
            self.steps.append(
                {
                    "kind": "extract_media_data",
                    "ids": [(r["id"], r["object_id"]) for r in chunk],
                }
            )
        if dispatched:
            self.steps.append(
                {
                    "kind": "wait_thumbnails",
                    "count": dispatched,
                    "batch_id": thumb_batch_id,
                }
            )
        labeler = getattr(getattr(library, "node", None), "image_labeler", None)
        label_rows = [
            r for r in rows if (r["extension"] or "").lower() in IMAGE_EXTENSIONS
        ]
        if labeler is not None and label_rows:
            loc_path = self.data["location_path"]
            batch_id = labeler.new_batch(
                library,
                [
                    {"file_path_id": r["id"], "object_id": r["object_id"],
                     "path": _full_path(loc_path, r)}
                    for r in label_rows
                ],
            )
            self.steps.append({"kind": "wait_labels", "batch_id": batch_id})

        self.run_metadata.update(
            media_data_extracted=0, media_data_skipped=0,
            thumbnails_dispatched=dispatched,
        )
        ctx.progress(
            message=f"processing media for {len(rows)} files", phase="media"
        )

    async def execute_step(self, ctx: JobContext, step: dict, step_number: int) -> StepResult:
        kind = step["kind"]
        if kind == "extract_media_data":
            return self._extract_media_data(ctx, step)
        if kind == "wait_thumbnails":
            return await self._wait_thumbnails(ctx, step)
        if kind == "wait_labels":
            return await self._wait_labels(ctx, step)
        return StepResult()

    def _extract_media_data(self, ctx: JobContext, step: dict) -> StepResult:
        library = ctx.library
        loc_path = self.data["location_path"]
        extracted = skipped = 0
        for fp_id, object_id in step["ids"]:
            row = library.db.find_one("file_path", id=fp_id)
            if row is None or object_id is None:
                skipped += 1
                continue
            full = _full_path(loc_path, row)
            ext = (row["extension"] or "").lower()
            if ext in VIDEO_EXTENSIONS:
                from .media_data import VideoMetadata

                meta = VideoMetadata.from_path(full)
            else:
                meta = ImageMetadata.from_path(full)
            if meta is None:
                skipped += 1
                continue
            cols = meta.to_row(object_id)
            library.db.upsert("media_data", {"object_id": object_id}, **{
                k: v for k, v in cols.items() if k != "object_id"
            })
            extracted += 1
        return StepResult(
            metadata={
                "media_data_extracted": self.run_metadata["media_data_extracted"] + extracted,
                "media_data_skipped": self.run_metadata["media_data_skipped"] + skipped,
            }
        )

    async def _wait_thumbnails(self, ctx: JobContext, step: dict) -> StepResult:
        """Rendezvous with the thumbnailer actor (ref:job.rs:83-88
        WaitThumbnails step) — per dispatched batch, so unrelated
        background thumbnail work can't stall this job. After a resume
        the id is from a dead process; `wait_batch` treats unknown ids
        as done (the actor re-queues persisted work on its own)."""
        thumbnailer = getattr(getattr(ctx.library, "node", None), "thumbnailer", None)
        if thumbnailer is not None:
            await thumbnailer.wait_batch(step.get("batch_id", 0))
        return StepResult()

    async def _wait_labels(self, ctx: JobContext, step: dict) -> StepResult:
        labeler = getattr(getattr(ctx.library, "node", None), "image_labeler", None)
        if labeler is not None:
            await labeler.wait_batch(step["batch_id"])
        return StepResult()

    async def finalize(self, ctx: JobContext) -> Any:
        ctx.progress(message="media processing complete", phase="done")
        return dict(self.run_metadata)
